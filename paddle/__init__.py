"""`paddle` import-path shim: maps the reference's import surface
(paddle.trainer_config_helpers, paddle.trainer.PyDataProvider2, paddle.v2,
paddle.utils.*) onto paddle_tpu, so reference config scripts and
dataproviders run UNCHANGED (`from paddle.trainer_config_helpers import *`).

Reference: python/paddle/ package layout.  This is compatibility plumbing
only — every implementation lives in paddle_tpu.
"""

import sys

import paddle_tpu.v2 as v2  # noqa: F401

# alias paddle.v2 (and its submodules) so `import paddle.v2 as paddle`
# scripts work
sys.modules[__name__ + ".v2"] = v2
for _sub in ("activation", "attr", "dataset", "evaluator", "event",
             "inference", "layer", "networks", "optimizer", "parameters",
             "pooling", "reader", "trainer"):
    try:
        _m = __import__(f"paddle_tpu.v2.{_sub}", fromlist=[_sub])
        sys.modules[f"{__name__}.v2.{_sub}"] = _m
    except ImportError:
        pass

# dataset sub-submodules (paddle.v2.dataset.uci_housing etc.)
for _ds in ("mnist", "cifar", "imdb", "imikolov", "movielens", "conll05",
            "uci_housing", "wmt14"):
    try:
        _m = __import__(f"paddle_tpu.data.datasets.{_ds}", fromlist=[_ds])
        sys.modules[f"{__name__}.v2.dataset.{_ds}"] = _m
    except ImportError:
        pass
