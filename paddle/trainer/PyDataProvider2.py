"""Shim for `from paddle.trainer.PyDataProvider2 import *` (reference
python/paddle/trainer/PyDataProvider2.py) -> paddle_tpu.data.provider."""

from paddle_tpu.data.provider import (  # noqa: F401
    provider, CacheType, SeqType, InputType,
    dense_vector, sparse_binary_vector, sparse_float_vector, integer_value,
    dense_vector_sequence, sparse_binary_vector_sequence,
    sparse_float_vector_sequence, integer_value_sequence,
    integer_value_sub_sequence,
)

# reference aliases
dense_slot = dense_vector
sparse_binary_slot = sparse_binary_vector
sparse_float_slot = sparse_float_vector
index_slot = integer_value

__all__ = [
    "provider", "CacheType", "SeqType", "InputType",
    "dense_vector", "sparse_binary_vector", "sparse_float_vector",
    "integer_value", "dense_vector_sequence",
    "sparse_binary_vector_sequence", "sparse_float_vector_sequence",
    "integer_value_sequence", "integer_value_sub_sequence",
    "dense_slot", "sparse_binary_slot", "sparse_float_slot", "index_slot",
]
