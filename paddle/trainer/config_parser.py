"""Shim for `from paddle.trainer.config_parser import parse_config, logger`
(reference python/paddle/trainer/config_parser.py)."""

import logging

from paddle_tpu.compat.config_parser import parse_config  # noqa: F401

logger = logging.getLogger("paddle_tpu.config_parser")

__all__ = ["parse_config", "logger"]
