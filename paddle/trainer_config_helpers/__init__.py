"""Shim for `from paddle.trainer_config_helpers import *` — the surface every
reference v1 config script imports (reference
python/paddle/trainer_config_helpers/__init__.py re-exporting layers,
networks, activations, poolings, attrs, optimizers, data_sources,
evaluators).

Layer ctors come straight from paddle_tpu.layers; a few are wrapped here to
RECORD into the active parse context (paddle_tpu.compat.config_parser):
data_layer notes declaration order and takes sequence-ness from the data
provider's input_types (reference semantics — seq-ness lives in the
provider, not the layer config), and outputs()/evaluators register what the
trainer should optimize/track.
"""

import inspect as _inspect

from paddle_tpu.layers import *              # noqa: F401,F403
import paddle_tpu.layers as _L
from paddle_tpu.layers import layer_math     # noqa: F401
import paddle_tpu.evaluators as _E
from paddle_tpu.compat import config_parser as _cp
from paddle_tpu.compat.v1 import *           # noqa: F401,F403
from paddle_tpu.compat import v1 as _v1
from paddle_tpu.data.provider import SeqType as _SeqType


def _adapt_layer_attr(ctor):
    """v1 configs pass layer_attr=ExtraAttr(...) to nearly every ctor; for
    ctors without that kwarg, merge the attr dict into the node's cfg after
    construction (drop_rate etc. are read from cfg at apply time)."""
    try:
        sig = _inspect.signature(ctor)
    except (TypeError, ValueError):
        return ctor
    if "layer_attr" in sig.parameters or any(
            p.kind == _inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()):
        return ctor

    def wrapped(*a, **kw):
        la = kw.pop("layer_attr", None)
        node = ctor(*a, **kw)
        if la and hasattr(node, "cfg"):
            node.cfg.update(la)
        return node
    wrapped.__name__ = getattr(ctor, "__name__", "layer")
    wrapped.__doc__ = ctor.__doc__
    return wrapped


for _name in dir(_L):
    if _name.startswith("_"):
        continue
    _obj = getattr(_L, _name)
    if callable(_obj) and not isinstance(_obj, type) \
            and getattr(_obj, "__module__", "").startswith("paddle_tpu.layers"):
        globals()[_name] = _adapt_layer_attr(_obj)
del _name, _obj


def data_layer(name, size, is_seq=False, height=None, width=None, **_kw):
    """Wrapped data_layer: sequence-ness comes from the provider's declared
    input_types when parsing a config (reference PyDataProvider2 owns the
    seq/non-seq distinction); declaration order is recorded for positional
    input_types pairing."""
    if _cp.in_parse():
        ctx = _cp.active_context()
        types = _cp.resolve_input_types(ctx)
        itype = None
        if isinstance(types, dict):
            itype = types.get(name)
        elif isinstance(types, (list, tuple)):
            idx = len(ctx.input_order)
            if idx < len(types):
                itype = types[idx]
        if itype is not None and itype.seq_type != _SeqType.NO_SEQUENCE:
            is_seq = True
        ctx.input_order.append(name)
    return _L.data_layer(name, size, is_seq=is_seq, height=height,
                         width=width)


def outputs(layers, *args):
    """Wrapped outputs(): records the output layers on the parse context."""
    out = list(layers if isinstance(layers, (list, tuple)) else [layers])
    out += list(args)
    if _cp.in_parse():
        _cp.active_context().outputs = out
    return out[0] if len(out) == 1 else out


def inputs(layers, *args):
    """Wrapped inputs(): explicit data-layer ordering — wins over the
    outputs-derived DFS order (reference HasInputsSet semantics)."""
    ins = list(layers if isinstance(layers, (list, tuple)) else [layers])
    ins += list(args)
    if _cp.in_parse():
        ctx = _cp.active_context()
        ctx.input_order = [l.name for l in ins]
        ctx.explicit_inputs = True
    return None


def _wrap_evaluator(ctor):
    def wrapped(*a, **kw):
        spec = ctor(*a, **kw)
        if _cp.in_parse():
            _cp.active_context().evaluators.append(spec)
        return spec
    wrapped.__name__ = ctor.__name__
    wrapped.__doc__ = ctor.__doc__
    return wrapped


_eval_names = [n for n in getattr(_E, "__all__", []) if n.endswith("_evaluator")]
for _n in _eval_names:
    globals()[_n] = _wrap_evaluator(getattr(_E, _n))
del _n
