"""Shim: re-export of the trainer_config_helpers surface (see package
__init__)."""

from paddle.trainer_config_helpers import *  # noqa: F401,F403
