"""Scan-invariant hoisting in recurrent_group: the memory-free row-wise
prefix of the step graph runs once over the whole sequence before the scan
(the generalized SequenceToBatch trick).  Must be numerically invisible:
forward and gradients identical with the optimization on and off."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.layers import recurrent as R
from paddle_tpu.layers.graph import Topology, reset_names, value_data

# scan-heavy (hoisted vs unhoisted recurrent_group, fwd+grad);
# nightly lane — README "Running the tests"
pytestmark = pytest.mark.slow


@pytest.fixture
def toggle():
    orig = R.HOIST_SCAN_INVARIANTS
    yield
    R.HOIST_SCAN_INVARIANTS = orig


def _build(np_rng):
    reset_names()
    w = L.data_layer("w", size=30, is_seq=True)      # token ids
    s = L.data_layer("s", size=4, is_seq=True)       # float features
    ctxv = L.data_layer("ctx", size=6)               # static context

    def step(tok, feat, stat):
        mem = L.memory(name="h", size=8)
        emb = L.embedding_layer(tok, size=5)         # hoistable
        proj = L.fc_layer([emb, feat], size=8, act=None,
                          bias_attr=False)           # hoistable (multi-in)
        gate = L.fc_layer([proj, mem, stat], size=8, act="tanh",
                          name="h")                  # memory-dependent
        return gate

    out = L.recurrent_group(step, [w, s, L.StaticInput(ctxv)])
    topo = Topology([L.last_seq(out)])
    seqs_w = pad_sequences([np_rng.randint(0, 30, (t,))
                            for t in [3, 5, 2]], max_len=5)
    seqs_s = pad_sequences([np_rng.randn(t, 4).astype(np.float32)
                            for t in [3, 5, 2]], max_len=5)
    feed = {"w": seqs_w, "s": seqs_s,
            "ctx": np_rng.randn(3, 6).astype(np.float32)}
    return topo, feed


def test_frontier_detection(np_rng, toggle):
    topo, _ = _build(np_rng)
    group = next(n for n in topo.order if n.layer_type == "recurrent_group")
    frontier = R._hoistable_frontier(group.cfg["sub_topo"],
                                     group.cfg["seq_phs"], "test")
    # the multi-input fc (emb + feat) is the maximal hoistable node; the
    # memory-dependent gate is not; the embedding is interior (not frontier)
    assert len(frontier) == 1
    assert frontier[0].layer_type == "fc"


def test_hoist_matches_unhoisted_forward_and_grad(np_rng, toggle):
    topo, feed = _build(np_rng)
    params = topo.init(jax.random.PRNGKey(0))

    def loss(p):
        out = topo.apply(p, feed, mode="test")
        return jnp.sum(value_data(out) ** 2)

    R.HOIST_SCAN_INVARIANTS = True
    l_on, g_on = jax.value_and_grad(loss)(params)
    R.HOIST_SCAN_INVARIANTS = False
    l_off, g_off = jax.value_and_grad(loss)(params)

    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_on),
                    jax.tree_util.tree_leaves(g_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_hoist_respects_dropout_in_train_mode(np_rng, toggle):
    """Nodes with drop_rate must stay in-scan during training (per-step
    masks); the frontier excludes them."""
    reset_names()
    s = L.data_layer("s", size=4, is_seq=True)

    def step(feat):
        mem = L.memory(name="h", size=8)
        proj = L.fc_layer(feat, size=8, act=None, layer_attr={"drop_rate": 0.5})
        return L.fc_layer([proj, mem], size=8, act="tanh", name="h")

    out = L.recurrent_group(step, s)
    group = next(n for n in Topology([out]).order
                 if n.layer_type == "recurrent_group")
    front_train = R._hoistable_frontier(group.cfg["sub_topo"],
                                        group.cfg["seq_phs"], "train")
    front_test = R._hoistable_frontier(group.cfg["sub_topo"],
                                       group.cfg["seq_phs"], "test")
    assert front_train == []          # dropout stays per-step
    assert len(front_test) == 1       # inactive in test mode -> hoistable
