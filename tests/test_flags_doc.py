"""docs/flags.md drift gate: the flag-reference table there is GENERATED
from `paddle_tpu.utils.flags` (the Flags dataclass + FLAG_DOCS).  Adding
a flag without a doc row, leaving a stale row behind, or editing the
dataclass without regenerating the doc fails here — the doc can never
silently drift from the code.

Regenerate with:  python -m paddle_tpu.utils.flags  (paste between the
BEGIN/END markers in docs/flags.md).
"""

import dataclasses
import os

from paddle_tpu.utils import flags

_DOC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "flags.md")


def _field_names():
    return {f.name for f in dataclasses.fields(flags.Flags)}


def test_every_flag_has_a_doc_row():
    missing = sorted(_field_names() - set(flags.FLAG_DOCS))
    assert not missing, (
        f"Flags fields without a FLAG_DOCS row: {missing} — add (help, "
        "reference cmd_parameter equivalent or '—') entries and "
        "regenerate docs/flags.md (python -m paddle_tpu.utils.flags)")


def test_no_stale_doc_rows():
    stale = sorted(set(flags.FLAG_DOCS) - _field_names())
    assert not stale, f"FLAG_DOCS rows for removed flags: {stale}"


def test_doc_rows_name_a_reference_fate():
    # every row either names its reference cmd_parameter or explicitly
    # documents the drop with '—' — no empty cells
    for name, (help_, ref) in flags.FLAG_DOCS.items():
        assert help_.strip(), f"{name}: empty help"
        assert ref.strip(), f"{name}: empty reference column (use '—')"


def test_docs_flags_md_is_regenerated():
    with open(_DOC) as f:
        doc = f.read()
    table = flags.flags_table_md()
    assert flags._TABLE_BEGIN in doc and flags._TABLE_END in doc, (
        "docs/flags.md lost its generated-table markers")
    assert table in doc, (
        "docs/flags.md's generated flags table is stale — regenerate with "
        "`python -m paddle_tpu.utils.flags` and paste between the markers")
