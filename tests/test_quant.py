"""Quantized serving (paddle_tpu/quant/; docs/serving.md "Quantized
serving"): the quantize/dequant math pinned bit-exactly, the committed
quality budget pinned against the fp32 twins on seeded trunks, the
quantized engines' internal bit-identity discipline (slab == paged ==
chunked == the quantized lm_generate oracle, 1 warm-up trace / 0
retraces under admit/CoW churn), the 2x-blocks-at-equal-bytes paged
auto-sizing, and the perf/analytic structural gates in both directions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import transformer
from paddle_tpu.quant import kv as kvq
from paddle_tpu.quant import weights as qw
from paddle_tpu.serving.decode_engine import DecodeEngine, GenerationBatcher
from paddle_tpu.serving.kv_pool import slab_equivalent_blocks
from paddle_tpu.testing import forbid_retrace

V, D, HEADS, LAYERS, MAXLEN = 64, 32, 2, 2, 48


def _trunk(seed=0, **kw):
    return transformer.init(jax.random.PRNGKey(seed), src_vocab=V,
                            trg_vocab=1, d_model=D, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAXLEN, **kw)


def _prompts(seed=0, n=2, lo=3, hi=9):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, V, rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


_prefix = kvq.greedy_prefix_len    # THE budget comparison (one source)


# ------------------------------------------------ quantize/dequant math

def test_kv_identity_scale_roundtrip_bit_exact():
    """scale=1, values in int8 range -> dequant(quantize) BIT-exact:
    the quantize/dequant math itself (round half-to-even, clip,
    convert, multiply) carries no hidden bias."""
    rng = np.random.RandomState(0)
    # per-head amax exactly 127 in every head -> scale exactly 1.0
    x = rng.randint(-126, 127, (4, 6, 2, 16)).astype(np.float32)
    x[..., 0] = 127.0
    x = x.reshape(4, 6, 32)
    q, s = kvq.quantize_heads(jnp.asarray(x), 2)
    np.testing.assert_array_equal(np.asarray(s), np.ones((4, 6, 2)))
    back = np.asarray(kvq.dequantize_heads(q, s))
    np.testing.assert_array_equal(back, x)        # bit-exact


def test_weights_identity_scale_roundtrip_bit_exact():
    rng = np.random.RandomState(1)
    w = rng.randint(-126, 127, (64, 32)).astype(np.float32)
    w[0, :] = 127.0                    # per-column amax -> scale 1.0
    leaf = qw.quantize_leaf(jnp.asarray(w))
    np.testing.assert_array_equal(np.asarray(leaf["s"]),
                                  np.ones((1, 32)))
    np.testing.assert_array_equal(np.asarray(qw.dequantize_leaf(leaf)),
                                  w)


def test_kv_zero_head_roundtrip_and_shapes():
    x = jnp.zeros((3, 5, 32))
    q, s = kvq.quantize_heads(x, 2)
    assert q.dtype == jnp.int8 and q.shape == (3, 5, 32)
    assert s.shape == (3, 5, 2)
    np.testing.assert_array_equal(np.asarray(s), 0.0)   # amax 0 -> 0
    np.testing.assert_array_equal(
        np.asarray(kvq.dequantize_heads(q, s)), 0.0)


def test_quantize_lm_structure():
    params = _trunk()
    qp = qw.quantize_lm(params)
    assert qw.is_quantized_tree(qp) and not qw.is_quantized_tree(params)
    # the positional table is NOT a matmul weight: it stays f32
    assert not qw.is_quantized_leaf(qp["pos"])
    assert qw.weight_shape(qp["src_emb"]) == (V, D)
    shapes = qw.quantized_weight_shapes(qp)
    assert (V, D) in shapes and (D, D) in shapes
    # int8 data + f32 scales shrink the resident bytes close to 4x
    assert qw.param_bytes(qp) < 0.4 * qw.param_bytes(params)
    # maybe_dequant: identity object on a float tree, float on quantized
    assert qw.maybe_dequant(params) is params
    deq = qw.maybe_dequant(qp)
    assert deq["src_emb"].dtype == jnp.float32
    # dequant error bounded by half a quantization step per channel
    err = np.abs(np.asarray(deq["src_emb"])
                 - np.asarray(params["src_emb"]))
    step = np.asarray(qp["src_emb"]["s"])
    assert (err <= 0.5 * step + 1e-7).all()


@pytest.mark.slow
def test_export_leaf_format_interop():
    """``export.quantize_params``' ``{'__int8__','__scale__'}`` leaves
    (the artifact int8 format — same per-out-channel symmetric scheme)
    are recognized by every quant helper, so an exported int8 tree
    feeds the LM paths and the serving engine directly."""
    from paddle_tpu.export import quantize_params
    params = _trunk()
    qp, _dq = quantize_params(params)
    assert qw.is_quantized_tree(qp)
    assert qw.weight_shape(qp["src_emb"]) == (V, D)
    assert qw.param_bytes(qp) < qw.param_bytes(params)
    deq = qw.maybe_dequant(qp)
    assert deq["src_emb"].dtype == jnp.float32
    ids = transformer.lm_generate(qp, np.asarray([[3, 5, 7]], np.int32),
                                  12, HEADS, kv_dtype="int8")
    assert np.asarray(ids).shape == (1, 12)


# -------------------------------------------- prefill/step composition

@pytest.mark.slow
def test_quantized_prefill_equals_sequential_steps():
    """The quantized batched prefill attends over the SAME quantize ->
    dequantize round trip the incremental step applies, so the cached
    int8 values AND sidecar scales are bit-identical between the two
    ingestion orders — the property recovery/CoW/continuation replay
    rides."""
    params = _trunk()
    prompt = _prompts(2, n=1, lo=6, hi=7)[0][None]
    _h, cache = transformer.lm_prefill(params, prompt, MAXLEN, HEADS,
                                       kv_dtype="int8")
    cache2 = transformer.init_lm_cache(params, 1, MAXLEN,
                                       kv_dtype="int8", num_heads=HEADS)
    for t in range(prompt.shape[1]):
        _l, cache2 = transformer.lm_decode_step(params, prompt[:, t], t,
                                                cache2, HEADS)
    tp = prompt.shape[1]
    for key in ("k", "v", "ks", "vs"):
        np.testing.assert_array_equal(
            np.asarray(cache[0][key])[:, :tp],
            np.asarray(cache2[0][key])[:, :tp])


# ------------------------------------------------------ quality budget

@pytest.mark.parametrize("seed", [0, 1])
def test_quality_budget_greedy_prefix_and_logits(seed):
    """The COMMITTED quality budget on the pinned trunks: int8-KV
    greedy streams match the fp32 twin for >= GREEDY_PREFIX_MIN tokens,
    int8-KV + int8-weight streams for >= GREEDY_PREFIX_MIN_FULL, and
    the max |logit error| of a quantized prefill stays under
    LOGIT_ERR_BUDGET."""
    params = _trunk(seed)
    qp = qw.quantize_lm(params)
    n_tok = 2 * kvq.GREEDY_PREFIX_MIN
    for prompt in _prompts(seed, n=1):
        ml = prompt.size + n_tok
        ref = np.asarray(transformer.lm_generate(
            params, prompt[None], ml, HEADS))[0, prompt.size:]
        i8 = np.asarray(transformer.lm_generate(
            params, prompt[None], ml, HEADS,
            kv_dtype="int8"))[0, prompt.size:]
        full = np.asarray(transformer.lm_generate(
            qp, prompt[None], ml, HEADS,
            kv_dtype="int8"))[0, prompt.size:]
        assert _prefix(i8, ref) >= kvq.GREEDY_PREFIX_MIN
        assert _prefix(full, ref) >= kvq.GREEDY_PREFIX_MIN_FULL
        h32, _ = transformer.lm_prefill(params, prompt[None], MAXLEN,
                                        HEADS)
        l32 = transformer._lm_project(params, h32)
        for p, kvd in ((params, "int8"), (qp, "int8")):
            h, _ = transformer.lm_prefill(p, prompt[None], MAXLEN,
                                          HEADS, kv_dtype=kvd)
            lq = transformer._lm_project(p, h)
            err = float(kvq.logit_err(l32, lq).max())
            assert err <= kvq.LOGIT_ERR_BUDGET, err


# --------------------------------------------------- quantized engines

def _drive(engine, prompts, n_tok=10):
    bat = GenerationBatcher(engine, queue_size=64)
    futs = [bat.submit(p, max_tokens=n_tok) for p in prompts]
    outs = [f.result(120)["tokens"] for f in futs]
    bat.close()
    return outs


@pytest.mark.parametrize("layout,chunk", [
    # the ladder (chunk=0) engines compile a prefill-bucket ladder each
    # — slow lane; the chunked default (the serving CLI's mode) stays
    # in the fast lane
    pytest.param("slab", 0, marks=pytest.mark.slow),
    pytest.param("paged", 0, marks=pytest.mark.slow),
    ("paged", 4)])
@pytest.mark.slow
def test_int8_engine_matches_quantized_oracle(layout, chunk):
    """Inside the int8 mode greedy decode stays fully deterministic:
    every engine layout reproduces the quantized ``lm_generate`` oracle
    token for token — the engine/oracle bit-identity discipline carries
    over to quantized serving unchanged (weights quantized too: the
    full-quant stack)."""
    params = qw.quantize_lm(_trunk())
    n_tok = 8
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                       max_len=MAXLEN, prefill_buckets=(8, 16),
                       kv_layout=layout, kv_block_size=8,
                       kv_dtype="int8", prefill_chunk=chunk,
                       name=f"q_{layout}{chunk}")
    prompts = _prompts(3, n=4)
    with forbid_retrace(eng, what="int8 engine churn"):
        outs = _drive(eng, prompts, n_tok)
    for p, got in zip(prompts, outs):
        ids = np.asarray(transformer.lm_generate(
            params, p[None], p.size + n_tok, HEADS, kv_dtype="int8"))
        assert got == [int(t) for t in ids[0, p.size:]]


def test_int8_paged_churn_prefix_cow_no_retrace():
    """Admit/CoW/prefix-hit churn on the int8 paged engine: shared
    system-prompt clients must prefix-hit and copy-on-write fork int8
    blocks, streams identical to the int8 slab twin, and the step/
    write/fork executables trace exactly once at warm-up and never
    again."""
    params = _trunk()
    rng = np.random.RandomState(7)
    sys_prompt = rng.randint(1, V, 12).astype(np.int32)
    prompts = [np.concatenate([sys_prompt,
                               rng.randint(1, V, 3).astype(np.int32)])
               for _ in range(4)]
    prompts[1] = prompts[0].copy()          # exact duplicate: CoW fork
    # chunked engines (the serving default): no ladder to warm, so the
    # churn test exercises prefix-hit seating + span growth + CoW on
    # the ONE unified int8 step
    paged = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                         max_len=MAXLEN, prefill_buckets=(8, 16),
                         kv_layout="paged", kv_block_size=8,
                         kv_dtype="int8", prefill_chunk=4,
                         name="q_churn")
    slab = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                        max_len=MAXLEN, prefill_buckets=(8, 16),
                        kv_dtype="int8", prefill_chunk=4,
                        name="q_churn_slab")
    # leader first (registers the prefix chains), then the churners —
    # step/write/fork executables must all stay warm through the churn
    with forbid_retrace(paged, lambda: paged._write_traces[0],
                        lambda: paged._copy_traces[0],
                        what="int8 paged prefix/CoW churn"):
        outs = _drive(paged, prompts[:1]) + _drive(paged, prompts[1:])
    ref = _drive(slab, prompts)
    assert outs == ref
    snap = paged.metrics.snapshot()
    assert snap["prefix_cache_hits_total"] >= 2
    assert snap["cow_forks_total"] >= 1
    assert snap["kv_dtype"] == "int8"
    paged._paged.check()                    # full ledger audit


def test_int8_paged_auto_doubles_blocks_at_equal_bytes():
    params = _trunk()
    f32 = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                       max_len=MAXLEN, prefill_buckets=(8, 16),
                       kv_layout="paged", kv_block_size=8, warm=False)
    i8 = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                      max_len=MAXLEN, prefill_buckets=(8, 16),
                      kv_layout="paged", kv_block_size=8,
                      kv_dtype="int8", warm=False)
    assert i8._paged.pool.num_allocatable \
        == 2 * f32._paged.pool.num_allocatable
    # the doubled int8 pool + sidecars really fits the f32 byte budget
    def pool_bytes(eng):
        return sum(int(np.prod(l.shape)) * l.dtype.itemsize
                   for c in eng._cache for l in c.values())
    assert pool_bytes(i8) <= pool_bytes(f32)
    assert slab_equivalent_blocks(4, MAXLEN, 8, "int8") \
        == 2 * (slab_equivalent_blocks(4, MAXLEN, 8) - 1) + 1


def test_kv_dtype_validation():
    from paddle_tpu.utils.error import ConfigError
    with pytest.raises(ConfigError):
        DecodeEngine(_trunk(), num_heads=HEADS, kv_dtype="fp8",
                     warm=False)
    with pytest.raises(ValueError):
        transformer.init_lm_cache(_trunk(), 2, 16, kv_dtype="fp8")


@pytest.mark.slow
def test_recovery_replay_bit_identical_int8():
    """PR-6 supervised recovery on the int8 engine: an injected step
    fault rebuilds the slab and re-prefills (through the QUANTIZED
    prefill, whose composition with the step is exact) — recovered
    streams stay identical to the unfaulted int8 twin."""
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.supervisor import Supervisor
    params = _trunk()
    prompts = _prompts(5, n=3)
    clean = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                         max_len=MAXLEN, prefill_buckets=(8, 16),
                         kv_dtype="int8", name="q_clean")
    want = _drive(clean, prompts, n_tok=12)
    chaos = DecodeEngine(params, num_heads=HEADS, num_slots=4,
                         max_len=MAXLEN, prefill_buckets=(8, 16),
                         kv_dtype="int8", name="q_chaos")
    faults.install_spec("serving.decode_step:at=4")
    try:
        with forbid_retrace(chaos, what="int8 supervised recovery",
                            hint="the rebuild retraced the int8 step"):
            bat = GenerationBatcher(chaos, queue_size=64,
                                    supervisor=Supervisor())
            futs = [bat.submit(p, max_tokens=12) for p in prompts]
            got = [f.result(120)["tokens"] for f in futs]
            bat.close()
    finally:
        faults.install_spec("")
    assert got == want
    assert chaos.metrics.snapshot()["slot_reprefills_total"] >= 1


# ------------------------------------------------------ analytic gates

def test_analytic_quant_gates_both_directions():
    """assert_weights_quantized and assert_kv_quantized pass on the
    quantized kernel-forced step, and each FIRES on its twin (fp32
    weights / kernels-off reference) — plus the predicted-bytes model
    clears the 35% acceptance bar."""
    from paddle_tpu.ops.pallas import decode_attention as dk
    from paddle_tpu.perf import analytic as pa
    from paddle_tpu.testing.kernel_smoke import build_private_tables

    params = _trunk()
    qp = qw.quantize_lm(params, min_size=512)
    s, bs, nb_row = 4, 8, MAXLEN // 8
    num_blocks = s * nb_row + 1
    rng = np.random.RandomState(0)
    tokens = rng.randint(1, V, s).astype(np.int32)
    pos = rng.randint(1, MAXLEN - 1, s).astype(np.int32)
    tables = build_private_tables(pos, nb_row, bs, num_blocks)
    dkv = qw.weight_shape(params["enc"][0]["attn"]["wk"])[1]

    def staged(p, kv_dtype, mode):
        cache = transformer.init_lm_cache_paged(
            p, num_blocks, bs, max_len=MAXLEN, kv_dtype=kv_dtype,
            num_heads=HEADS)
        with dk.forced_mode(mode):
            def fn(pp, c, tok, po, tbl):
                logits, c = transformer.lm_decode_step_paged(
                    pp, tok, po, c, tbl, HEADS)
                return jnp.argmax(logits, axis=-1), c
            return jax.jit(fn).lower(p, cache, tokens, pos,
                                     tables).compile().as_text()

    shapes = qw.quantized_weight_shapes(qp)
    floats = qw.float_leaf_shapes(qp)
    assert shapes, "min_size=512 must quantize the test trunk"
    # the test trunk's pos table [MAXLEN, D] = [48, 32] deliberately
    # collides with no weight here, but the allow-list must exist so a
    # colliding trunk (max_len == dff) never false-positives
    t_span = nb_row * bs
    q_on = staged(qp, "int8", "always")
    pa.assert_weights_quantized(q_on, shapes, floats)
    pa.assert_kv_quantized(q_on, s, t_span, dkv)
    with pytest.raises(AssertionError):
        pa.assert_weights_quantized(staged(params, None, "off"), shapes,
                                    floats)
    with pytest.raises(AssertionError):
        pa.assert_kv_quantized(staged(qp, "int8", "off"), s, t_span,
                               dkv)
    b_f32 = pa.predicted_decode_step_bytes(params, s, t_span, HEADS)
    b_i8 = pa.predicted_decode_step_bytes(qp, s, t_span, HEADS, "int8")
    assert 1 - b_i8 / b_f32 >= 0.35


def test_weights_gate_tolerates_shape_collisions():
    """A non-weight f32 leaf whose shape collides with a quantized
    weight's (the positional table [max_len, d] vs FFN w2 [dff, d]
    when max_len == dff) must NOT read as a widened weight copy — the
    count-based gate allows exactly the tree's own float leaves."""
    from paddle_tpu.perf import analytic as pa
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=1, d_model=D, num_heads=HEADS,
                              dff=MAXLEN, enc_layers=1, dec_layers=0,
                              max_len=MAXLEN)
    qp = qw.quantize_lm(params, min_size=512)
    shapes = qw.quantized_weight_shapes(qp)
    assert (MAXLEN, D) in shapes        # w2 collides with pos
    cache = transformer.init_lm_cache(qp, 2, MAXLEN, kv_dtype="int8",
                                      num_heads=HEADS)
    tokens = np.zeros((2,), np.int32)
    pos = np.zeros((2,), np.int32)

    def fn(p, c, tok, po):
        logits, c = transformer.lm_decode_step_slots(p, tok, po, c,
                                                     HEADS)
        return jnp.argmax(logits, axis=-1), c

    hlo = jax.jit(fn).lower(qp, cache, tokens,
                            pos).compile().as_text()
    pa.assert_weights_quantized(hlo, shapes, qw.float_leaf_shapes(qp))
