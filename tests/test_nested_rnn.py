"""Nested (sub-sequence) recurrent groups: the two-level scan engine.

Reference: RecurrentGradientMachine.cpp:642-712 (createInFrameInfo with
subsequence inputs), gserver/tests/test_RecurrentGradientMachine.cpp and its
sequence_nest_rnn.conf vs sequence_rnn.conf equivalence pair — an outer
group iterating subsequences, an inner group iterating words, the inner
memory booted from the outer memory so the state chains across subsequence
boundaries exactly like a flat scan over the concatenated words.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.layers as L
from paddle_tpu.core.sequence import (NestedSequenceBatch, SequenceBatch,
                                      pad_nested_sequences, pad_sequences)
from paddle_tpu.layers.graph import Topology, reset_names, value_data

DIM, HID = 4, 6


def _nested_data(seed=0):
    r = np.random.RandomState(seed)
    subs = [[r.randn(int(t), DIM).astype(np.float32)
             for t in r.randint(1, 5, size=int(s))]
            for s in [2, 3, 1]]
    nested = pad_nested_sequences(subs)
    flat = pad_sequences([np.concatenate(s, axis=0) for s in subs])
    return subs, nested, flat


def _build_nested():
    x = L.data_layer("x", size=DIM, is_seq=True)

    def outer_step(subseq):
        outer_mem = L.memory(name="outer_state", size=HID)

        def inner_step(y):
            inner_mem = L.memory(name="inner_state", size=HID,
                                 boot_layer=outer_mem)
            return L.fc_layer([y, inner_mem], size=HID, act="tanh",
                              name="inner_state",
                              param_attr={"name": "rnnfc"})

        inner_out = L.recurrent_group(inner_step, subseq)
        last = L.last_seq(inner_out, name="outer_state")
        return last

    out = L.recurrent_group(outer_step, L.SubsequenceInput(x))
    return Topology([out]), out


def _build_flat():
    xf = L.data_layer("xf", size=DIM, is_seq=True)

    def step(y):
        mem = L.memory(name="state", size=HID)
        return L.fc_layer([y, mem], size=HID, act="tanh", name="state",
                          param_attr={"name": "rnnfc"})

    out = L.recurrent_group(step, xf)
    return Topology([out]), out


def test_nested_matches_flat_forward():
    subs, nested, flat = _nested_data()
    reset_names()
    topo_n, _ = _build_nested()
    reset_names()
    topo_f, _ = _build_flat()
    params = topo_n.init(jax.random.PRNGKey(0))
    assert "rnnfc" in params

    out_n = topo_n.apply(params, {"x": nested}, mode="test")
    out_f = topo_f.apply(params, {"xf": flat}, mode="test")

    # nested group output: one row per SUBSEQUENCE = the inner state at each
    # subsequence's end; flat output at the matching concatenated positions
    dn = np.asarray(value_data(out_n))          # [B, S, HID]
    df = np.asarray(value_data(out_f))          # [B, sumT, HID]
    for b, sample in enumerate(subs):
        ends = np.cumsum([len(t) for t in sample]) - 1
        for j, e in enumerate(ends):
            np.testing.assert_allclose(dn[b, j], df[b, e], rtol=1e-5,
                                       atol=1e-6)
    # padding slots are zero-masked
    assert isinstance(out_n, SequenceBatch)
    S = dn.shape[1]
    for b, sample in enumerate(subs):
        if len(sample) < S:
            assert np.all(dn[b, len(sample):] == 0.0)


def test_nested_matches_flat_gradients():
    subs, nested, flat = _nested_data(seed=1)
    reset_names()
    topo_n, _ = _build_nested()
    reset_names()
    topo_f, _ = _build_flat()
    params = topo_n.init(jax.random.PRNGKey(1))

    def loss_n(p):
        out = topo_n.apply(p, {"x": nested}, mode="test")
        # final state = last valid subsequence row
        d = value_data(out)
        idx = out.lengths - 1
        fin = jnp.take_along_axis(d, idx[:, None, None], axis=1)[:, 0]
        return jnp.sum(fin ** 2)

    def loss_f(p):
        out = topo_f.apply(p, {"xf": flat}, mode="test")
        d = value_data(out)
        idx = out.lengths - 1
        fin = jnp.take_along_axis(d, idx[:, None, None], axis=1)[:, 0]
        return jnp.sum(fin ** 2)

    ln, gn = jax.value_and_grad(loss_n)(params)
    lf, gf = jax.value_and_grad(loss_f)(params)
    np.testing.assert_allclose(float(ln), float(lf), rtol=1e-5)
    for k in gn:
        leaves_n = jax.tree_util.tree_leaves(gn[k])
        leaves_f = jax.tree_util.tree_leaves(gf[k])
        for a, b in zip(leaves_n, leaves_f):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)


def test_nested_seq_output_is_nested_batch():
    """A step returning the inner group's sequence output stacks into a
    NestedSequenceBatch (reference: nested groups may output sequences)."""
    subs, nested, _ = _nested_data(seed=2)
    reset_names()
    x = L.data_layer("x", size=DIM, is_seq=True)

    def outer_step(subseq):
        def inner_step(y):
            mem = L.memory(name="s", size=HID)
            return L.fc_layer([y, mem], size=HID, act="tanh", name="s")

        return L.recurrent_group(inner_step, subseq)

    out = L.recurrent_group(outer_step, L.SubsequenceInput(x))
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))
    val = topo.apply(params, {"x": nested}, mode="test")
    assert isinstance(val, NestedSequenceBatch)
    B = len(subs)
    assert val.data.shape[0] == B and val.data.shape[-1] == HID
    np.testing.assert_array_equal(np.asarray(val.outer_lengths),
                                  [len(s) for s in subs])
    # inner lengths match per-subsequence lengths; padding fully zeroed
    inner = np.asarray(val.inner_lengths)
    mask = np.asarray(val.inner_mask())
    d = np.asarray(val.data)
    assert np.all(d * (1 - mask[..., None]) == 0.0)
    for b, sample in enumerate(subs):
        for j, t in enumerate(sample):
            assert inner[b, j] == len(t)


def test_nested_jit_compiles():
    subs, nested, _ = _nested_data(seed=3)
    reset_names()
    topo, _ = _build_nested()
    params = topo.init(jax.random.PRNGKey(0))

    @jax.jit
    def f(p, n):
        out = topo.apply(p, {"x": n}, mode="test")
        return jnp.sum(value_data(out))

    v1 = f(params, nested)
    v2 = f(params, nested)
    assert np.isfinite(float(v1)) and float(v1) == float(v2)


def test_nested_padding_invariance():
    """Outputs and parameter grads must be identical when the nested batch
    is padded wider (outer) and longer (inner) with loud garbage — the
    2-level analog of tests/test_padding_invariance.py (the reference
    never pads: subSequenceStartPositions delimit the real data)."""
    subs, nested, _ = _nested_data()
    wide = pad_nested_sequences(
        subs,
        max_outer=int(nested.outer_lengths.max()) + 2,
        max_inner=int(np.asarray(nested.inner_lengths).max()) + 3,
        pad_value=7.5)
    reset_names()
    topo, _ = _build_nested()
    params = topo.init(jax.random.PRNGKey(0))

    def loss(p, feed):
        out = topo.apply(p, feed, mode="test")
        return jnp.sum(jnp.abs(value_data(out).astype(jnp.float32)))

    base = float(loss(params, {"x": nested}))
    padded = float(loss(params, {"x": wide}))
    np.testing.assert_allclose(padded, base, rtol=1e-5)

    ga = jax.grad(loss)(params, {"x": nested})
    gb = jax.grad(loss)(params, {"x": wide})
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(ga)[0],
            jax.tree_util.tree_flatten_with_path(gb)[0]):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-6,
            err_msg=f"nested grad {jax.tree_util.keystr(path)}")
