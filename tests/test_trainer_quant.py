"""Int8 weight-streaming trainer (SGD(quant_weights=True)).

The jitted step is fed the {"master": f32 tree, "q": int8+scale tree}
bundle, runs forward/backward over the dequantized view, updates the
f32 masters and requantizes in-step — so between steps the forward's
weight STREAM is int8 bytes + scale sidecars.  What must hold:

* config fencing: the quant step refuses the combinations whose
  semantics are undefined (grad accumulation window, compute_dtype);
* quality: per-step cost tracks the f32 twin within
  quant/weights.TRAIN_LOSS_BUDGET, with one trace total;
* durability: save/load carries BOTH trees, kill-9-style resume is
  bit-identical to the uninterrupted run (params AND int8 twin), and
  checkpoints cross formats in both directions (plain f32 into a quant
  trainer requantizes; a bundle into a plain trainer adopts the
  masters).
"""

import os

import numpy as np
import pytest
import jax

import paddle_tpu.optim as optim
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.layers import api as L
from paddle_tpu.layers.graph import reset_names
from paddle_tpu.quant import weights as qw
from paddle_tpu.resilience import InjectedFault, faults
from paddle_tpu.trainer.trainer import SGD
from paddle_tpu.utils.error import ConfigError

# fc weights are (4, 16) and (16, 2): min_size=16 quantizes both while
# the 1-D biases stay f32 masters-only
DIM, HID, MIN_SIZE = 4, 16, 16


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


def _trainer(seed=7, quant=True, **kw):
    reset_names()
    x = L.data_layer("tq_x", size=DIM)
    lab = L.data_layer("tq_lab", size=1)
    h = L.fc_layer(input=x, size=HID, act="tanh")
    y = L.fc_layer(input=h, size=2, act="softmax")
    cost = L.classification_cost(y, lab)
    tr = SGD(cost=cost,
             update_equation=optim.Momentum(learning_rate=0.1,
                                            momentum=0.9),
             seed=seed, quant_weights=quant,
             quant_min_size=MIN_SIZE, **kw)
    feeding = {"tq_x": dense_vector(DIM), "tq_lab": integer_value(2)}

    def reader():
        rng = np.random.RandomState(0)      # identical batches every pass
        xs = rng.randn(24, DIM).astype(np.float32)
        ys = (xs[:, 0] > 0).astype(np.int64)
        for i in range(0, 24, 8):
            yield [(xs[j], int(ys[j])) for j in range(i, i + 8)]

    return tr, feeding, reader


def _batches(seed, n, batch=8):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xs = rng.randn(batch, DIM).astype(np.float32)
        ys = (xs[:, 0] > 0).astype(np.int64)
        out.append([(xs[j], int(ys[j])) for j in range(batch)])
    return out


def _equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def test_quant_config_validation():
    """The combinations whose dequant-view semantics are undefined are
    refused at construction, before any step is traced."""
    with pytest.raises(ConfigError, match="grad_accum_steps"):
        _trainer(grad_accum_steps=2)
    with pytest.raises(ConfigError, match="compute_dtype"):
        _trainer(compute_dtype="bfloat16")


def test_quant_loss_parity_and_single_trace():
    """Per-step cost tracks the f32 twin within TRAIN_LOSS_BUDGET; the
    int8 twin exists (both fc weights quantized, biases not), and the
    quant step traces exactly once across steps."""
    tq, feeding, _ = _trainer(quant=True)
    tf, _, _ = _trainer(quant=False)
    assert tq._qtree and len(tq._qtree) == 2, tq._qtree and list(tq._qtree)
    for sub in tq._qtree.values():
        assert qw.is_quantized_leaf(sub)
    feeder = DataFeeder(feeding)
    gap = 0.0
    for b in _batches(3, 6):
        cq = float(tq.train_one_batch(b, feeder))
        cf = float(tf.train_one_batch(b, feeder))
        gap = max(gap, abs(cq - cf) / max(abs(cf), 1.0))
    assert gap <= qw.TRAIN_LOSS_BUDGET, \
        f"quant-trainer loss gap {gap:.4f} > budget {qw.TRAIN_LOSS_BUDGET}"
    assert tq.trace_count == 1, tq.trace_count
    # the step really runs over the int8 view: the twin tracks the
    # masters.  The jitted in-step requantize may reassociate the
    # amax/127 divide by 1 ulp vs this eager one (same fusion note as
    # tests/test_flash_quant.py) — int8 codes must match exactly, the
    # f32 scales to float-epsilon
    fresh = tq._requant(jax.device_get(tq.parameters))
    assert set(tq._qtree) == set(fresh)
    for k, sub in tq._qtree.items():
        np.testing.assert_array_equal(np.asarray(sub["q"]),
                                      np.asarray(fresh[k]["q"]))
        np.testing.assert_allclose(np.asarray(sub["s"]),
                                   np.asarray(fresh[k]["s"]), rtol=1e-6)


def test_quant_ckpt_save_load_continue_bit_identical(tmp_path):
    """save() writes the {"master","q"} bundle; a fresh quant trainer
    load()s it and the continued run is bit-identical — params, int8
    twin, and the next step's cost."""
    sd = str(tmp_path / "ckpt")
    t1, feeding, _ = _trainer()
    feeder = DataFeeder(feeding)
    warm, nxt = _batches(5, 3), _batches(6, 1)[0]
    for b in warm:
        t1.train_one_batch(b, feeder)
    t1.save(sd, pass_id=0)

    t2, _, _ = _trainer(seed=11)            # different init: load wins
    meta = t2.load(sd)
    assert meta["pass_id"] == 0
    assert _equal(jax.device_get(t1.parameters),
                  jax.device_get(t2.parameters))
    assert _equal(jax.device_get(t1._qtree), jax.device_get(t2._qtree))
    # rng streams differ (seed 7 vs 11 — load() only restores trees),
    # so pin them before comparing the continued step
    t2.rng = t1.rng
    c1 = float(t1.train_one_batch(nxt, feeder))
    c2 = float(t2.train_one_batch(nxt, feeder))
    assert c1 == c2
    assert _equal(jax.device_get(t1._qtree), jax.device_get(t2._qtree))


def test_quant_step_fault_then_resume_bit_identical(tmp_path):
    """Kill-9 mid-pass: an injected trainer.step fault, then
    train(resume=True) from the latest complete pass — final params AND
    the int8 twin bit-identical to an uninterrupted quant run."""
    sd = str(tmp_path / "ckpt")
    t1, feeding, reader = _trainer()
    # 3 batches/pass: hit 5 = pass 1, batch 1 — after pass-0 checkpoint
    faults.install_spec("trainer.step:at=5")
    with pytest.raises(InjectedFault):
        t1.train(reader, num_passes=2, feeding=feeding, log_period=0,
                 buffered_batches=0, save_dir=sd)
    faults.clear()
    assert sorted(d for d in os.listdir(sd) if d.startswith("pass-")) \
        == ["pass-00000"]

    t2, feeding, reader = _trainer()
    t2.train(reader, num_passes=2, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=sd, resume=True)

    t3, feeding, reader = _trainer()
    t3.train(reader, num_passes=2, feeding=feeding, log_period=0,
             buffered_batches=0)
    assert _equal(jax.device_get(t2.parameters),
                  jax.device_get(t3.parameters)), \
        "resumed masters diverged from the uninterrupted run"
    assert _equal(jax.device_get(t2._qtree),
                  jax.device_get(t3._qtree)), \
        "resumed int8 twin diverged from the uninterrupted run"


def test_quant_ckpt_crosses_formats_both_directions(tmp_path):
    """A plain f32 checkpoint loads into a quant trainer (masters
    adopted, int8 twin requantized deterministically); a quant bundle
    loads into a plain trainer (masters ARE the params, twin dropped)."""
    feeder_sd = str(tmp_path / "f32")
    quant_sd = str(tmp_path / "quant")
    feeding = {"tq_x": dense_vector(DIM), "tq_lab": integer_value(2)}
    feeder = DataFeeder(feeding)
    batch = _batches(9, 1)[0]

    tf, _, _ = _trainer(quant=False)
    tf.train_one_batch(batch, feeder)
    tf.save(feeder_sd, pass_id=0)
    tq, _, _ = _trainer(quant=True)
    tq.train_one_batch(batch, feeder)
    tq.save(quant_sd, pass_id=0)

    # f32 -> quant: requantize on load, bit-equal to quantizing by hand
    t1, _, _ = _trainer(quant=True, seed=11)
    t1.load(feeder_sd)
    assert _equal(jax.device_get(tf.parameters),
                  jax.device_get(t1.parameters))
    assert _equal(t1._qtree,
                  t1._requant(jax.device_get(tf.parameters)))
    # quant -> plain: the masters are the params; no bundle keys leak
    t2, _, _ = _trainer(quant=False, seed=11)
    t2.load(quant_sd)
    assert set(t2.parameters) == set(jax.device_get(tq.parameters))
    assert _equal(jax.device_get(tq.parameters),
                  jax.device_get(t2.parameters))
    # both loaded trainers still step
    t1.train_one_batch(batch, feeder)
    t2.train_one_batch(batch, feeder)
