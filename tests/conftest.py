"""Test harness: force an 8-device virtual CPU platform so sharding tests run
without TPU hardware (SURVEY.md §4 pattern (4): in-process multi-host tests
replacing the reference's localhost pservers in test_CompareSparse.cpp)."""

import os
import sys

# Force CPU unconditionally: the ambient environment may point JAX at a
# remote single-chip TPU (e.g. JAX_PLATFORMS=axon through a tunnel), which
# would serialize every test through that link — and a sitecustomize hook may
# set the jax_platforms *config* at interpreter startup, which overrides the
# env var. So set both the env var and the config explicitly before any
# backend is initialized. Tests always run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    import jax
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)


def free_port():
    """An OS-assigned free TCP port for multi-process rendezvous tests."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
