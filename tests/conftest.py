"""Test harness: force an 8-device virtual CPU platform so sharding tests run
without TPU hardware (SURVEY.md §4 pattern (4): in-process multi-host tests
replacing the reference's localhost pservers in test_CompareSparse.cpp)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


@pytest.fixture
def rng():
    import jax
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
