"""v1 config-file compatibility acceptance tests (VERDICT r1 item 2 /
SURVEY §7 stage 2): REFERENCE demo config scripts execute UNCHANGED through
the config compiler (paddle_tpu.compat.parse_config, reference
config_parser.py:3558), and the ported seqToseq attention config trains and
generates.

Each test builds tiny fixture data under tmp_path and chdirs there (the
reference configs use cwd-relative data paths, as the reference trainer
did)."""

import itertools
import os

import numpy as np
import pytest

from paddle_tpu.compat import parse_config, config_to_runtime

REFERENCE = os.environ.get("PADDLE_REFERENCE_DIR", "/root/reference")
HAVE_REF = os.path.exists(f"{REFERENCE}/demo/quick_start/trainer_config.lr.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _write(path, content):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(content)


def _train_batches(cfg, n_batches=2, num_passes=1):
    from paddle_tpu.trainer import SGD
    trainer = SGD(cost=cfg["cost"], update_equation=cfg["optimizer"],
                  evaluators=cfg.get("evaluators"))
    costs = []
    trainer.train(
        lambda: itertools.islice(cfg["train_reader"](), n_batches),
        num_passes=num_passes, feeding=cfg.get("feeding"),
        event_handler=lambda e: costs.append(float(e.cost))
        if type(e).__name__ == "EndIteration" else None,
        log_period=0)
    return costs


@pytest.mark.skipif(not HAVE_REF, reason="reference checkout not available")
def test_quick_start_lr_config_unchanged(in_tmp):
    """demo/quick_start/trainer_config.lr.py (logistic regression over BOW)
    runs verbatim: sparse_binary_vector provider, Adam + L2 + grad clipping
    from settings(), classification_cost."""
    _write(in_tmp / "data" / "dict.txt",
           "the 10\nmovie 8\nis 6\ngood 4\nbad 3\n")
    _write(in_tmp / "data" / "train.txt",
           "1\tthe movie is good\n0\tthe movie is bad\n"
           "1\tgood movie\n0\tbad movie\n" * 40)
    _write(in_tmp / "data" / "train.list", "data/train.txt\n")
    _write(in_tmp / "data" / "test.list", "data/train.txt\n")

    parsed = parse_config(
        f"{REFERENCE}/demo/quick_start/trainer_config.lr.py",
        {"dict_file": "data/dict.txt"})
    cfg = config_to_runtime(parsed)
    assert cfg["batch_size"] == 128
    assert parsed.settings["learning_rate"] == 2e-3
    # provider input_types flow into feeding: word is a 5-dim sparse vector
    assert cfg["feeding"]["word"].dim == 5
    costs = _train_batches(cfg, n_batches=2, num_passes=3)
    assert np.isfinite(costs).all()
    assert costs[-1] < costs[0]      # it learns


@pytest.mark.skipif(not HAVE_REF, reason="reference checkout not available")
def test_quick_start_predict_mode(in_tmp):
    """is_predict=True branch: no data sources, outputs = [maxid, prob]."""
    _write(in_tmp / "data" / "dict.txt", "the 1\nmovie 1\n")
    _write(in_tmp / "data" / "pred.list", "")
    parsed = parse_config(
        f"{REFERENCE}/demo/quick_start/trainer_config.lr.py",
        "dict_file=data/dict.txt,is_predict=true")
    assert len(parsed.outputs) == 2
    assert parsed.settings["batch_size"] == 1


@pytest.mark.skipif(not HAVE_REF, reason="reference checkout not available")
def test_sentiment_stacked_lstm_config_unchanged(in_tmp):
    """demo/sentiment/trainer_config.py: stacked 3-LSTM net with dropout
    layer_attrs, per-input ParamAttr lists, init_hook provider, seq-ness
    inferred from provider input_types (list-style, positional)."""
    d = in_tmp / "data" / "pre-imdb"
    _write(d / "dict.txt", "the\t10\nmovie\t8\nis\t6\ngood\t4\nbad\t3\n")
    _write(d / "labels.list", "neg\npos\n")
    _write(d / "train_part_000",
           "1\t\tthe movie is good\n0\t\tthe movie is bad\n"
           "1\t\tgood movie\n0\t\tbad movie\n" * 16)
    _write(d / "train.list", "data/pre-imdb/train_part_000\n")
    _write(d / "test.list", "data/pre-imdb/train_part_000\n")

    parsed = parse_config(f"{REFERENCE}/demo/sentiment/trainer_config.py", "")
    cfg = config_to_runtime(parsed)
    # the word data layer must have picked up sequence-ness from the provider
    word_layer = [n for n in parsed.input_order][0]
    assert word_layer == "word"
    costs = _train_batches(cfg, n_batches=1, num_passes=1)
    assert np.isfinite(costs).all()


@pytest.mark.skipif(not HAVE_REF, reason="reference checkout not available")
def test_mnist_vgg_config_unchanged(in_tmp):
    """demo/mnist/vgg_16_mnist.py: small_vgg conv net via the py2-era
    mnist_provider (xrange shim), dense_vector input, momentum + L2."""
    rng = np.random.RandomState(0)
    n = 10000   # read_from_mnist reads 10k samples for non-'train' files
    raw = in_tmp / "data" / "raw"
    raw.mkdir(parents=True)
    (raw / "mn-images-idx3-ubyte").write_bytes(
        b"\x00" * 16 + rng.randint(0, 256, n * 784).astype(np.uint8).tobytes())
    (raw / "mn-labels-idx1-ubyte").write_bytes(
        b"\x00" * 8 + rng.randint(0, 10, n).astype(np.uint8).tobytes())
    _write(in_tmp / "data" / "train.list", "data/raw/mn\n")
    _write(in_tmp / "data" / "test.list", "data/raw/mn\n")

    parsed = parse_config(f"{REFERENCE}/demo/mnist/vgg_16_mnist.py", "")
    cfg = config_to_runtime(parsed)
    assert cfg["batch_size"] == 128
    costs = _train_batches(cfg, n_batches=1)
    assert np.isfinite(costs).all()


def _write_s2s_data(root):
    d = root / "data" / "pre-wmt14"
    _write(d / "src.dict", "<s>\n<e>\n<unk>\nle\nchat\nnoir\nmange\n")
    _write(d / "trg.dict", "<s>\n<e>\n<unk>\nthe\ncat\nblack\neats\n")
    _write(d / "part-000",
           "le chat noir\tthe black cat\nle chat mange\tthe cat eats\n"
           "le noir chat\tthe cat black\nle chat\tthe cat\n")
    _write(d / "train.list", "data/pre-wmt14/part-000\n")
    _write(d / "test.list", "data/pre-wmt14/part-000\n")
    _write(d / "gen.list", "data/pre-wmt14/part-000\n")


def test_seqtoseq_train_config(in_tmp):
    """demo/seqToseq/v1/train.conf (py3 port of the reference translation
    config): attention GRU encoder-decoder via recurrent_group trains."""
    _write_s2s_data(in_tmp)
    parsed = parse_config(f"{REPO}/demo/seqToseq/v1/train.conf",
                          "dim=16,batch_size=4")
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=1, num_passes=2)
    assert np.isfinite(costs).all()


def test_seqtoseq_generation_config(in_tmp):
    """is_generating=1: same step function becomes beam_search with a
    GeneratedInput; step-layer params share top-level keys with training
    (so trained weights flow into decoding)."""
    import jax
    from paddle_tpu.data import DataFeeder
    from paddle_tpu.layers.graph import Topology
    _write_s2s_data(in_tmp)

    train_parsed = parse_config(f"{REPO}/demo/seqToseq/v1/train.conf",
                                "dim=16,batch_size=4")
    gen_parsed = parse_config(
        f"{REPO}/demo/seqToseq/v1/train.conf",
        "is_generating=1,dim=16,batch_size=2,max_length=6,beam_size=2")

    train_topo = Topology(train_parsed.outputs)
    train_params = train_topo.init(jax.random.PRNGKey(0))
    # training created the decoder step params at top level by name
    assert "gru_decoder" in train_params
    assert "_target_language_embedding" in train_params

    beam = gen_parsed.outputs[0]
    gen_topo = Topology([beam])
    gen_params = gen_topo.init(jax.random.PRNGKey(1))
    # the generation graph shares those same top-level keys -> trained
    # weights drop in directly
    assert "gru_decoder" in gen_params
    gen_params.update({k: v for k, v in train_params.items()
                       if k in gen_params})

    cfg = config_to_runtime(gen_parsed)
    feeder = DataFeeder(cfg["feeding"])
    batch = next(iter(cfg["test_reader"]()))
    feed = feeder(batch)
    res = gen_topo.apply(
        gen_params, {"source_language_word": feed["source_language_word"]},
        mode="test")
    assert res.tokens.shape[:2] == (2, 2)    # [batch, beam]
    assert np.isfinite(np.asarray(res.scores)).all()


def test_benchmark_rnn_config_unchanged(in_tmp):
    """benchmark/paddle/rnn/rnn.py (the BASELINE.md headline LSTM config)
    runs verbatim: imdb.pkl-format provider (py3 map-yielding), list-style
    input_types, config_args for batch/hidden sizes."""
    if not os.path.exists(f"{REFERENCE}/benchmark/paddle/rnn/rnn.py"):
        pytest.skip("reference benchmark configs not available")
    import pickle
    rng = np.random.RandomState(0)
    x = [rng.randint(2, 30, (rng.randint(3, 8),)).tolist()
         for _ in range(32)]
    y = [int(i % 2) for i in range(32)]
    # pre-create imdb.train.pkl + train.list so imdb.create_data skips its
    # download (and its py2 file() call)
    with open(in_tmp / "imdb.train.pkl", "wb") as f:
        pickle.dump((x, y), f)
    _write(in_tmp / "train.list", "imdb.train.pkl\n")
    parsed = parse_config(f"{REFERENCE}/benchmark/paddle/rnn/rnn.py",
                          "batch_size=8,hidden_size=16,pad_seq=true")
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=2)
    assert np.isfinite(costs).all()


# ----------------------------------------------------------------- sweep

_SWEEP_DIR = f"{REFERENCE}/python/paddle/trainer_config_helpers/tests/configs"
_SWEEP_EXCLUDED = {
    # a stdin-driven driver script, not a config file
    "test_config_parser_for_non_file_config.py",
}


def _sweep_configs():
    if not os.path.isdir(_SWEEP_DIR):
        return []
    import glob
    return sorted(os.path.basename(p)
                  for p in glob.glob(f"{_SWEEP_DIR}/*.py")
                  if os.path.basename(p) not in _SWEEP_EXCLUDED)


@pytest.mark.skipif(not os.path.isdir(_SWEEP_DIR),
                    reason="reference checkout not present")
@pytest.mark.parametrize("cfg_name", _sweep_configs())
def test_reference_config_sweep(cfg_name):
    """EVERY reference trainer_config_helpers test config compiles through
    parse_config unchanged (the golden-config discipline of
    tests/configs/generate_protostr.sh, minus the protobuf)."""
    parsed = parse_config(f"{_SWEEP_DIR}/{cfg_name}", "")
    assert parsed.outputs or parsed.costs, cfg_name


_PROTOSTR_DIR = f"{_SWEEP_DIR}/protostr"


def _parse_protostr(path):
    """Minimal text-proto scrape: {layer_name: (type, size)}, input and
    output layer-name lists of the root sub_model."""
    import re
    text = open(path).read()
    layers = {}
    for m in re.finditer(
            r'layers \{\s*name: "([^"]+)"\s*type: "([^"]+)"(?:\s*size: (\d+))?',
            text):
        layers[m.group(1)] = (m.group(2),
                              int(m.group(3)) if m.group(3) else None)
    # each list appears twice: top-level ModelConfig and the root sub_model
    inputs = list(dict.fromkeys(
        re.findall(r'input_layer_names: "([^"]+)"', text)))
    outputs = list(dict.fromkeys(
        re.findall(r'output_layer_names: "([^"]+)"', text)))
    return layers, inputs, outputs


@pytest.mark.skipif(not os.path.isdir(_PROTOSTR_DIR),
                    reason="reference protostr goldens not present")
@pytest.mark.parametrize("cfg_name", [
    # configs whose graph interface we can compare mechanically (excluded:
    # those where our compiler legitimately restructures, e.g. fused
    # softmax+CE aliases or group lowering changes the output node names)
    "test_fc.py", "last_first_seq.py", "test_expand_layer.py",
    "test_sequence_pooling.py", "util_layers.py",
    "img_layers.py", "test_maxout.py", "test_pad.py", "test_spp_layer.py",
    "test_bilinear_interp.py",
    # excluded: test_cost_layers.py — our cost nodes are per-sample
    # scalars (size 1) while the reference's nce/hsigmoid COST layers
    # carry class-count sizes; the compile sweep still covers it
])
def test_protostr_golden_interface(cfg_name):
    """Golden-file parity (reference tests/configs/protostr/*.protostr):
    the DATA interface — every reference data layer exists with the same
    size — and the model emits the same NUMBER of outputs whose sizes
    multiset-match the golden graph's output sizes."""
    golden = os.path.join(_PROTOSTR_DIR, cfg_name.replace(".py", ".protostr"))
    if not os.path.exists(golden):
        pytest.skip(f"no golden for {cfg_name}")
    glayers, _gin, gouts = _parse_protostr(golden)
    parsed = parse_config(f"{_SWEEP_DIR}/{cfg_name}", "")
    from paddle_tpu.layers.graph import Topology
    outs = list(parsed.outputs or parsed.costs)
    topo = Topology(outs)

    ours = {n.name: (n.layer_type, n.size) for n in topo.order}
    # data interface: exact name + size match
    for name, (typ, size) in glayers.items():
        if typ == "data":
            assert name in ours, f"data layer {name} missing"
            assert ours[name][1] == size, (
                f"data layer {name}: size {ours[name][1]} != golden {size}")
    # output arity and size multiset
    golden_sizes = sorted(glayers[n][1] for n in gouts if glayers[n][1])
    our_sizes = sorted(o.size for o in outs)
    assert len(our_sizes) == len(gouts), (
        f"output arity {len(our_sizes)} != golden {len(gouts)}")
    # cost layers: golden size 1 == ours 1; feature outputs match exactly
    assert our_sizes == golden_sizes, (
        f"output sizes {our_sizes} != golden {golden_sizes}")


_GSERVER_DIR = f"{REFERENCE}/paddle/gserver/tests"


def _gserver_configs():
    if not os.path.isdir(_GSERVER_DIR):
        return []
    import glob
    return sorted(os.path.basename(p)
                  for p in glob.glob(f"{_GSERVER_DIR}/*.conf"))


@pytest.mark.skipif(not os.path.isdir(_GSERVER_DIR),
                    reason="reference checkout not present")
@pytest.mark.parametrize("cfg_name", _gserver_configs())
def test_gserver_config_sweep(cfg_name, monkeypatch):
    """The reference's gserver C++-test configs (concat pairs, conv/pool
    pairs, layer groups, hierarchical RNNs) also compile unchanged; their
    provider paths are relative to the reference's paddle/ dir."""
    monkeypatch.chdir(f"{REFERENCE}/paddle")
    parsed = parse_config(f"{_GSERVER_DIR}/{cfg_name}", "")
    assert parsed.outputs, cfg_name


# ------------------------------------------------- network-compare pairs

_COMPARE_PAIRS = [
    ("concat_dotmul_a.conf", "concat_dotmul_b.conf"),
    ("concat_fullmatrix_a.conf", "concat_fullmatrix_b.conf"),
    ("concat_table_a.conf", "concat_table_b.conf"),
    ("img_pool_a.conf", "img_pool_b.conf"),
    # img_conv_a/b excluded: the b-side realizes conv biases as a
    # full-width mixed bias while the a-side conv uses per-channel shared
    # biases — same math family, different parameter layout by design
]


@pytest.mark.skipif(not os.path.isdir(_GSERVER_DIR),
                    reason="reference checkout not present")
@pytest.mark.parametrize("conf_a,conf_b", _COMPARE_PAIRS,
                         ids=[a.replace("_a.conf", "") for a, _ in
                              _COMPARE_PAIRS])
def test_network_compare_pairs(conf_a, conf_b, monkeypatch, np_rng):
    """The reference's test_NetworkCompare discipline: each a/b config pair
    expresses the same computation two ways (layers vs projections, cudnn
    vs plain); with shared parameter values their outputs must match."""
    import jax
    from paddle_tpu.layers.graph import Topology, value_data

    monkeypatch.chdir(f"{REFERENCE}/paddle")

    def build(conf):
        parsed = parse_config(f"{_GSERVER_DIR}/{conf}", "")
        return Topology(list(parsed.outputs))

    topo_a, topo_b = build(conf_a), build(conf_b)
    params_a = topo_a.init(jax.random.PRNGKey(0))
    params_b = topo_b.init(jax.random.PRNGKey(1))
    # the two formulations name layers differently (fc vs one-part mixed):
    # map parameter values POSITIONALLY over same-shaped leaves, the way
    # the reference's compareNetwork copies para_a -> para_b by index
    leaves_a = [l for _, l in sorted(
        jax.tree_util.tree_flatten_with_path(params_a)[0],
        key=lambda kv: jax.tree_util.keystr(kv[0]))]
    flat_b = sorted(jax.tree_util.tree_flatten_with_path(params_b)[0],
                    key=lambda kv: jax.tree_util.keystr(kv[0]))
    assert len(leaves_a) == len(flat_b), (conf_a, conf_b)
    mapped = {}
    for (path, leaf_b), leaf_a in zip(flat_b, leaves_a):
        assert leaf_a.shape == leaf_b.shape, (
            f"{jax.tree_util.keystr(path)}: {leaf_a.shape} vs {leaf_b.shape}")
        mapped[path] = leaf_a
    params_b = jax.tree_util.tree_map_with_path(
        lambda path, leaf: mapped[path], params_b)

    feed = {}
    for name, node in topo_a.data_layers.items():
        if node.is_seq:
            from paddle_tpu.core.sequence import pad_sequences
            feed[name] = pad_sequences(
                [np_rng.randint(0, node.size, (4,)) for _ in range(2)])
        else:
            feed[name] = np_rng.randn(2, node.size).astype(np.float32)

    out_a = topo_a.apply(params_a, feed, mode="test")
    out_b = topo_b.apply(params_b, feed, mode="test")
    fa = [np.asarray(value_data(v)) for v in
          (out_a if isinstance(out_a, tuple) else (out_a,))]
    fb = [np.asarray(value_data(v)) for v in
          (out_b if isinstance(out_b, tuple) else (out_b,))]
    assert len(fa) == len(fb)
    for a, b in zip(fa, fb):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.skipif(
    not os.path.exists(f"{REFERENCE}/demo/seqToseq/translation/train.conf"),
    reason="reference checkout not present")
def test_reference_translation_train_conf_unchanged(in_tmp, monkeypatch):
    """The REFERENCE demo/seqToseq/translation/train.conf — attention GRU
    encoder-decoder at its real dims (512), provider + sibling
    seqToseq_net.py imported through the py2 shim — trains verbatim."""
    d = in_tmp / "data" / "pre-wmt14"
    _write(d / "src.dict", "<s>\n<e>\n<unk>\nle\nchat\nnoir\nmange\n")
    _write(d / "trg.dict", "<s>\n<e>\n<unk>\nthe\ncat\nblack\neats\n")
    _write(d / "part-00000",
           "le chat noir\tthe black cat\nle chat mange\tthe cat eats\n"
           "le noir chat\tthe cat black\nle chat\tthe cat\n")
    _write(d / "train.list", "data/pre-wmt14/part-00000\n")
    _write(d / "test.list", "data/pre-wmt14/part-00000\n")
    # the config does sys.path.append("..") relative to CWD: run from a
    # copy-free vantage — parse against the reference path directly
    parsed = parse_config(
        f"{REFERENCE}/demo/seqToseq/translation/train.conf", "")
    assert parsed.settings["batch_size"] == 50
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=1, num_passes=1)
    assert np.isfinite(costs).all()


@pytest.mark.skipif(
    not os.path.exists(f"{REFERENCE}/demo/seqToseq/translation/gen.conf"),
    reason="reference checkout not present")
def test_reference_translation_gen_conf_parses(in_tmp):
    """gen.conf (is_generating branch): beam_search generation graph builds
    from the same unchanged reference config."""
    d = in_tmp / "data" / "pre-wmt14"
    _write(d / "src.dict", "<s>\n<e>\n<unk>\nle\nchat\n")
    _write(d / "trg.dict", "<s>\n<e>\n<unk>\nthe\ncat\n")
    _write(d / "part-00000", "le chat\nle le\n")
    _write(d / "gen.list", "data/pre-wmt14/part-00000\n")
    parsed = parse_config(
        f"{REFERENCE}/demo/seqToseq/translation/gen.conf", "")
    assert parsed.outputs
    from paddle_tpu.layers.graph import Topology
    import jax
    topo = Topology(list(parsed.outputs))
    params = topo.init(jax.random.PRNGKey(0))
    assert "gru_decoder" in params or any("decoder" in k for k in params)


@pytest.mark.skipif(
    not os.path.exists(f"{REFERENCE}/demo/sequence_tagging/linear_crf.py"),
    reason="reference checkout not present")
def test_sequence_tagging_linear_crf_config(in_tmp, np_rng):
    """demo/sequence_tagging/linear_crf.py parses verbatim: linear-chain
    CRF cost + viterbi decoding + chunk/sum evaluators + ModelAverage and
    lr-decay settings; one fwd+bwd step runs on synthetic features.
    (The demo's gzip/bytes py2 provider is not shimmed — data comes from
    the fixture feed here.)"""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.sequence import pad_sequences
    from paddle_tpu.layers.graph import Topology, value_data

    parsed = parse_config(
        f"{REFERENCE}/demo/sequence_tagging/linear_crf.py", "")
    assert parsed.settings["learning_rate"] == 1e-1
    assert [e.name for e in parsed.evaluators] == ["error", "chunk_f1"]
    topo = Topology(list(parsed.outputs))
    params = topo.init(jax.random.PRNGKey(0))
    assert "crfw" in params            # shared CRF transition params

    # synthetic: 2 sentences of one-hot-ish sparse features (dense here),
    # num_label_types aligned to 24 in the config
    B, T, F, L = 2, 5, 76328, 24
    feats = []
    for _ in range(B):
        t = np_rng.randint(2, T + 1)
        rows = np.zeros((t, F), np.float32)
        rows[np.arange(t), np_rng.randint(0, F, t)] = 1.0
        feats.append(rows)
    feed = {
        "features": pad_sequences(feats),
        "chunk": pad_sequences(
            [np_rng.randint(0, L, (len(f),)) for f in feats]),
    }

    def loss(p):
        out = topo.apply(p, feed, mode="test")
        return jnp.mean(value_data(out))

    l, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(l))
    crf_grad = np.asarray(jax.tree_util.tree_leaves(g["crfw"])[0])
    assert np.isfinite(crf_grad).all() and np.abs(crf_grad).sum() > 0


@pytest.mark.skipif(
    not os.path.exists(f"{REFERENCE}/demo/semantic_role_labeling/db_lstm.py"),
    reason="reference checkout not present")
def test_srl_db_lstm_config_unchanged(in_tmp):
    """demo/semantic_role_labeling/db_lstm.py: 8-layer bidirectional-ish
    deep LSTM over 8 input slots with CRF cost, dict files read at parse
    time, provider passing dicts through args — trains verbatim."""
    d = in_tmp / "data"
    _write(d / "wordDict.txt", "\n".join(f"w{i}" for i in range(20)) + "\n")
    _write(d / "targetDict.txt",
           "\n".join(["O"] + [f"{p}-A{k}" for p in "BI" for k in range(3)])
           + "\n")
    _write(d / "verbDict.txt", "\n".join(f"v{i}" for i in range(5)) + "\n")
    # provider sample: "word1 word2\tverb\t..." — reference conll05-style
    # columns: sentence / predicate / ctx / label sequence
    # 9 tab-separated columns: sentence, predicate, ctx_n2..ctx_p2,
    # mark sequence, label sequence (dataprovider.py process())
    words = "w1 w2 w3 w4"
    mark = "0 1 0 0"
    label = "B-A0 I-A0 O B-A1"
    _write(d / "feature",
           f"{words}\tv1\tw1\tw2\tw3\tw4\tw2\t{mark}\t{label}\n" * 6)
    _write(d / "train.list", "data/feature\n")
    _write(d / "test.list", "data/feature\n")

    parsed = parse_config(
        f"{REFERENCE}/demo/semantic_role_labeling/db_lstm.py", "")
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=1, num_passes=1)
    assert np.isfinite(costs).all()


@pytest.mark.skipif(
    not os.path.exists(
        f"{REFERENCE}/demo/image_classification/vgg_16_cifar.py"),
    reason="reference checkout not present")
def test_cifar_vgg_config_parses_and_steps(in_tmp, np_rng):
    """demo/image_classification/vgg_16_cifar.py builds its graph verbatim
    (small_vgg over 3x32x32) and takes a fwd+bwd step on synthetic images.
    (The demo's jpeg/cPickle provider is py2+PIL legacy; data comes from a
    fixture feed.)"""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.layers.graph import Topology, value_data

    parsed = parse_config(
        f"{REFERENCE}/demo/image_classification/vgg_16_cifar.py", "")
    assert parsed.settings["batch_size"] == 128
    topo = Topology(list(parsed.outputs))
    params = topo.init(jax.random.PRNGKey(0))
    feed = {"image": np_rng.randn(4, 3 * 32 * 32).astype(np.float32),
            "label": np_rng.randint(0, 10, (4, 1)).astype(np.int32)}

    def loss(p):
        return jnp.mean(value_data(topo.apply(p, feed, mode="test")))

    l, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(l))
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(g))

    # predict mode: graph ends in softmax probabilities
    pred = parse_config(
        f"{REFERENCE}/demo/image_classification/vgg_16_cifar.py",
        "is_predict=true")
    assert len(pred.outputs) == 1


@pytest.mark.skipif(
    not os.path.exists(f"{REFERENCE}/demo/introduction/trainer_config.py"),
    reason="reference checkout not present")
def test_introduction_config_learns_line(in_tmp):
    """demo/introduction: y = 2x - 0.3 linear regression — the reference's
    hello-world — trains verbatim with its own dataprovider and converges
    toward the true weights."""
    import shutil
    # the reference keeps dataprovider.py NEXT TO the config (provider
    # imports resolve relative to config_dir), so parse a local copy of
    # both files together
    shutil.copy(f"{REFERENCE}/demo/introduction/dataprovider.py",
                in_tmp / "dataprovider.py")
    shutil.copy(f"{REFERENCE}/demo/introduction/trainer_config.py",
                in_tmp / "trainer_config.py")
    parsed = parse_config(str(in_tmp / "trainer_config.py"), "")
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=60, num_passes=4)
    assert costs[-1] < costs[0]


@pytest.mark.skipif(
    not os.path.exists(
        f"{REFERENCE}/demo/traffic_prediction/trainer_config.py"),
    reason="reference checkout not present")
def test_traffic_prediction_config_unchanged(in_tmp):
    """demo/traffic_prediction/trainer_config.py: 24 shared-weight
    multi-task heads over speed windows — trains verbatim with its own
    provider (f.next() py2-ism shimmed) on fixture CSV."""
    rng = np.random.RandomState(0)
    speeds = ",".join(str(int(v)) for v in rng.randint(1, 5, 120))
    _write(in_tmp / "data" / "speeds.csv",
           "link_id,speeds\n" + f"1,{speeds}\n2,{speeds}\n")
    _write(in_tmp / "data" / "train.list", "data/speeds.csv\n")
    _write(in_tmp / "data" / "test.list", "data/speeds.csv\n")
    parsed = parse_config(
        f"{REFERENCE}/demo/traffic_prediction/trainer_config.py", "")
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=1, num_passes=1)
    assert costs, "provider yielded no batches"
    assert np.isfinite(costs).all()


@pytest.mark.parametrize("conf,cargs", [
    ("smallnet_mnist_cifar.py", "batch_size=4"),
    ("alexnet.py", "batch_size=2"),
    # googlenet compiles for minutes on CPU: covered on demand (it DID
    # expose the DFS input-order and ceil-pool-padding divergences)
    pytest.param("googlenet.py", "batch_size=2", marks=pytest.mark.skipif(
        not os.environ.get("PADDLE_TPU_SLOW_TESTS"),
        reason="minutes-long CPU compile; set PADDLE_TPU_SLOW_TESTS=1")),
], ids=["smallnet", "alexnet", "googlenet"])
def test_benchmark_image_config_unchanged(in_tmp, conf, cargs):
    """benchmark/paddle/image configs (the BASELINE.md conv rows) run
    verbatim: py2 provider (xrange, inclusive-randint labels), img_conv /
    img_cmrnorm / img_pool stacks, conv_projection inceptions, DFS input
    order (label declared first), ceil-mode pooling, config_args batch
    sizing."""
    path = f"{REFERENCE}/benchmark/paddle/image/{conf}"
    if not os.path.exists(path):
        pytest.skip("reference benchmark configs not available")
    _write(in_tmp / "train.list", "dummy\n")
    parsed = parse_config(path, cargs)
    cfg = config_to_runtime(parsed)
    costs = _train_batches(cfg, n_batches=2)
    assert np.isfinite(costs).all()


def test_explicit_inputs_beats_dfs_order(in_tmp):
    """inputs(...) wins over the outputs-derived DFS order (reference
    HasInputsSet early-return, networks.py:1449) — a config listing its
    data layers explicitly must feed in THAT order even when the graph
    reaches them differently."""
    conf = in_tmp / "conf.py"
    _write(conf, """
from paddle.trainer_config_helpers import *
settings(batch_size=4, learning_rate=0.01)
# declared label-first AND reached label-last by the graph; the explicit
# inputs() call pins the order regardless
lab = data_layer(name='lab', size=1)
x = data_layer(name='x', size=6)
fc = fc_layer(input=x, size=4, act=TanhActivation())
cost = classification_cost(
    input=fc_layer(input=fc, size=2, act=SoftmaxActivation()), label=lab)
inputs(lab, x)
outputs(cost)
""")
    parsed = parse_config(str(conf), "")
    assert parsed.input_order == ["lab", "x"]

    conf2 = in_tmp / "conf2.py"
    _write(conf2, """
from paddle.trainer_config_helpers import *
settings(batch_size=4, learning_rate=0.01)
lab = data_layer(name='lab', size=1)
x = data_layer(name='x', size=6)
fc = fc_layer(input=x, size=4, act=TanhActivation())
cost = classification_cost(
    input=fc_layer(input=fc, size=2, act=SoftmaxActivation()), label=lab)
outputs(cost)
""")
    # no inputs(): DFS from the outputs reaches x before lab
    parsed2 = parse_config(str(conf2), "")
    assert parsed2.input_order == ["x", "lab"]
