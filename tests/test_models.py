"""Model-zoo smoke + learning tests (tiny shapes; the reference's
trainer/tests sample-config discipline)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch, pad_sequences
from paddle_tpu.models import (lenet, resnet, text_lstm, seq2seq, transformer,
                               recommendation)
from paddle_tpu import optim


def test_lenet_shapes_and_learning(rng, np_rng):
    params = lenet.init(rng)
    imgs = jnp.asarray(np_rng.randn(8, 784), jnp.float32)
    labels = jnp.asarray(np_rng.randint(0, 10, (8,)))
    logits = lenet.forward(params, imgs)
    assert logits.shape == (8, 10)
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9)
    st = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lenet.loss)(p, imgs, labels)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    losses = []
    for _ in range(15):
        params, st, l = step(params, st)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_resnet_cifar_tiny(rng, np_rng):
    params, state = resnet.init(rng, depth=20, num_classes=10)
    imgs = jnp.asarray(np_rng.randn(4, 32, 32, 3), jnp.float32)
    logits, new_state = resnet.forward(params, state, imgs, depth=20)
    assert logits.shape == (4, 10)
    assert np.all(np.isfinite(np.asarray(logits)))
    # eval mode uses moving stats, state unchanged
    logits2, st2 = resnet.forward(params, state, imgs, depth=20, train=False)
    assert np.all(np.isfinite(np.asarray(logits2)))


def test_text_lstm_loss(rng, np_rng):
    params = text_lstm.init(rng, vocab=100, emb_dim=8, hidden=12,
                            num_layers=2, num_classes=2)
    seqs = [np_rng.randint(0, 100, (l,)) for l in (5, 9, 3)]
    ids = pad_sequences(seqs)
    labels = jnp.asarray([0, 1, 0])
    l = text_lstm.loss(params, ids, labels, 2, 12)
    assert np.isfinite(float(l))
    g = jax.grad(text_lstm.loss)(params, ids, labels, 2, 12)
    assert np.all(np.isfinite(np.asarray(g["emb"])))


def _nmt_batch(np_rng, b=3, v=40):
    src = pad_sequences([np_rng.randint(3, v, (l,)) for l in
                         np_rng.randint(3, 9, b)])
    trg = [np_rng.randint(3, v, (l,)) for l in np_rng.randint(3, 7, b)]
    trg_in = pad_sequences([np.concatenate([[0], t]) for t in trg])
    trg_next = pad_sequences([np.concatenate([t, [1]]) for t in trg])
    return src, trg_in, trg_next


def test_seq2seq_loss_and_generate(rng, np_rng):
    params = seq2seq.init(rng, src_vocab=40, trg_vocab=40, emb_dim=8,
                          hidden=10)
    src, trg_in, trg_next = _nmt_batch(np_rng)
    l = seq2seq.loss(params, src, trg_in, trg_next)
    assert np.isfinite(float(l))
    res = seq2seq.generate(params, src, beam_size=3, max_len=7)
    assert res.tokens.shape == (3, 3, 7)
    assert res.scores.shape == (3, 3)
    # scores sorted best-first
    s = np.asarray(res.scores)
    assert np.all(np.diff(s, axis=1) <= 1e-5)
    toks, lens = seq2seq.greedy_generate(params, src, max_len=7)
    assert toks.shape == (3, 7)


def test_seq2seq_learns_copy_task(rng, np_rng):
    """Tiny copy task: loss should drop markedly in a few steps."""
    params = seq2seq.init(rng, src_vocab=20, trg_vocab=20, emb_dim=8,
                          hidden=12)
    opt = optim.Adam(learning_rate=0.01)
    st = opt.init(params)
    seqs = [np_rng.randint(3, 20, (5,)) for _ in range(8)]
    src = pad_sequences(seqs)
    trg_in = pad_sequences([np.concatenate([[0], s]) for s in seqs])
    trg_next = pad_sequences([np.concatenate([s, [1]]) for s in seqs])

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(seq2seq.loss)(p, src, trg_in, trg_next)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    losses = []
    for _ in range(30):
        params, st, l = step(params, st)
        losses.append(float(l))
    assert losses[-1] < 0.6 * losses[0], losses[::10]


def test_transformer_loss_and_generate(rng, np_rng):
    params = transformer.init(rng, src_vocab=50, trg_vocab=50, d_model=16,
                              num_heads=2, dff=32, enc_layers=2, dec_layers=2,
                              max_len=32)
    src, trg_in, trg_next = _nmt_batch(np_rng, v=50)
    l = transformer.loss(params, src, trg_in, trg_next, num_heads=2)
    assert np.isfinite(float(l))
    res = transformer.generate(params, src, beam_size=2, max_len=6,
                               num_heads=2)
    assert res.tokens.shape == (3, 2, 6)


def test_recommendation_forward(rng, np_rng):
    params = recommendation.init(rng, max_user=50, max_movie=60, emb=16,
                                 hidden=16, title_vocab=30)
    b = 4
    uid = jnp.asarray(np_rng.randint(0, 50, (b,)))
    gender = jnp.asarray(np_rng.randint(0, 2, (b,)))
    age = jnp.asarray(np_rng.randint(0, 7, (b,)))
    job = jnp.asarray(np_rng.randint(0, 21, (b,)))
    mid = jnp.asarray(np_rng.randint(0, 60, (b,)))
    cats = jnp.asarray(np_rng.rand(b, 18) > 0.8, jnp.float32)
    title = pad_sequences([np_rng.randint(0, 30, (l,))
                           for l in np_rng.randint(2, 6, b)])
    score = jnp.asarray(np_rng.randint(1, 6, (b,)), jnp.float32)
    pred = recommendation.forward(params, uid, gender, age, job, mid, cats,
                                  title)
    assert pred.shape == (b,)
    assert np.all(np.abs(np.asarray(pred)) <= 5.0 + 1e-5)
    l = recommendation.loss(params, uid, gender, age, job, mid, cats, title,
                            score)
    assert np.isfinite(float(l))
