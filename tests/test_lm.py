"""Decoder-only causal LM (transformer.lm_loss): packed rows train every
segment as if alone, and the loss composes with sequence parallelism and
the zigzag causal ring — the modern no-padding training plane the
reference's Argument.sequenceStartPositions pointed toward."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch, pack_sequences
from paddle_tpu.models import transformer

V, DM, HEADS, T = 48, 16, 2, 16

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _params(max_len=T):
    return transformer.init(jax.random.PRNGKey(0), src_vocab=V, trg_vocab=1,
                            d_model=DM, dff=32, enc_layers=2, dec_layers=0,
                            max_len=max_len)


def _packed(np_rng, lens=(5, 9, 7, 3, 12, 4), t=T):
    seqs = [np_rng.randint(3, V, n) for n in lens]
    data, seg, pos = pack_sequences(seqs, max_len=t)
    b = data.shape[0]
    return (SequenceBatch(jnp.asarray(data), jnp.full((b,), t, jnp.int32)),
            jnp.asarray(seg), jnp.asarray(pos), seqs)


def test_lm_packed_matches_one_segment_per_row(np_rng):
    """Token-mean loss over PACKED rows == the same sequences laid out one
    per (padded) row: packing changes the layout, not the objective."""
    params = _params()
    tokens, seg, pos, seqs = _packed(np_rng)

    packed = transformer.lm_loss(params, tokens, HEADS, segment_ids=seg,
                                 positions=pos)

    b = len(seqs)
    data1 = np.zeros((b, T), np.int32)
    seg1 = np.zeros((b, T), np.int32)
    pos1 = np.zeros((b, T), np.int32)
    for i, s in enumerate(seqs):
        data1[i, :len(s)] = s
        seg1[i, :len(s)] = 1
        pos1[i, :len(s)] = np.arange(len(s))
    alone = transformer.lm_loss(
        params,
        SequenceBatch(jnp.asarray(data1), jnp.full((b,), T, jnp.int32)),
        HEADS, segment_ids=jnp.asarray(seg1), positions=jnp.asarray(pos1))
    np.testing.assert_allclose(float(packed), float(alone), rtol=2e-5)


def test_lm_unpacked_matches_single_segment_labels(np_rng):
    """The unpacked path (lengths mask) produces the same loss as the
    explicit one-segment-per-row packed encoding of the same batch."""
    params = _params()
    lens = np.asarray([6, 11, 16, 3])
    b = len(lens)
    data = np.zeros((b, T), np.int32)
    seg = np.zeros((b, T), np.int32)
    pos = np.zeros((b, T), np.int32)
    rng = np_rng
    for i, n in enumerate(lens):
        data[i, :n] = rng.randint(3, V, n)
        seg[i, :n] = 1
        pos[i, :n] = np.arange(n)
    sb = SequenceBatch(jnp.asarray(data), jnp.asarray(lens, jnp.int32))
    unpacked = transformer.lm_loss(params, sb, HEADS)
    packed = transformer.lm_loss(
        params,
        SequenceBatch(jnp.asarray(data), jnp.full((b,), T, jnp.int32)),
        HEADS, segment_ids=jnp.asarray(seg), positions=jnp.asarray(pos))
    np.testing.assert_allclose(float(unpacked), float(packed), rtol=2e-5)


def test_lm_loss_trains(np_rng):
    """60 SGD steps on a copy-pattern corpus halve the loss — the LM path
    is trainable end to end, grads flow through the tied embedding."""
    from paddle_tpu import optim
    params = _params()
    tokens, seg, pos, _ = _packed(np_rng, lens=(9, 9, 9, 9, 9))
    opt = optim.Adam(learning_rate=3e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(
            lambda p: transformer.lm_loss(p, tokens, HEADS,
                                          segment_ids=seg,
                                          positions=pos))(p)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    first = None
    for i in range(60):
        params, state, l = step(params, state)
        first = first if first is not None else float(l)
    assert float(l) < 0.6 * first, (first, float(l))


@needs_8
@pytest.mark.parametrize("zigzag", [False, True], ids=["ring", "zigzag"])
def test_lm_packed_seq_parallel_matches_single(np_rng, zigzag):
    """Packed causal LM under a data x seq mesh (plain and zigzag ring)
    reproduces the single-device loss and grads — all three marquee
    features (packing, causal LM, sequence parallelism) in one call."""
    from paddle_tpu.parallel import MeshConfig, make_mesh
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    params = _params()
    tokens, seg, pos, _ = _packed(np_rng)

    def lm(p, mesh_arg, zz):
        return transformer.lm_loss(p, tokens, HEADS, segment_ids=seg,
                                   positions=pos, mesh=mesh_arg, zigzag=zz)

    l1, g1 = jax.jit(jax.value_and_grad(
        lambda p: lm(p, None, False)))(params)
    l2, g2 = jax.jit(jax.value_and_grad(
        lambda p: lm(p, mesh, zigzag)))(params)
    np.testing.assert_allclose(float(l2), float(l1), rtol=2e-4)
    for a, b_ in zip(jax.tree_util.tree_leaves(g2),
                     jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=1e-4)


def test_lm_zigzag_guards():
    params = _params()
    tokens = SequenceBatch(jnp.zeros((2, T), jnp.int32),
                           jnp.full((2,), T, jnp.int32))
    with pytest.raises(ValueError, match="seq > 1"):
        transformer.lm_loss(params, tokens, HEADS, zigzag=True)


def _oracle_greedy(params, prompt, max_len, heads=HEADS):
    """Full-recompute greedy rollout via lm_logits — the numerics oracle
    for the KV-cached lm_generate."""
    b, tp = prompt.shape
    ids = np.zeros((b, max_len), np.int32)
    ids[:, :tp] = prompt
    for t in range(max_len - 1):
        sb = SequenceBatch(jnp.asarray(ids), jnp.full((b,), t + 1,
                                                      jnp.int32))
        logits = transformer.lm_logits(params, sb, heads)
        nxt = np.asarray(jnp.argmax(logits[:, t], axis=-1))
        if t + 1 < tp:
            continue
        ids[:, t + 1] = nxt
    return ids


def test_lm_generate_cached_matches_full_recompute(np_rng):
    """Greedy lm_generate (KV cache, one position per step) reproduces
    the full-sequence argmax rollout exactly."""
    params = _params(max_len=12)
    prompt = np_rng.randint(3, V, (3, 4)).astype(np.int32)
    got = np.asarray(transformer.lm_generate(params, prompt, max_len=12,
                                             num_heads=HEADS))
    want = _oracle_greedy(params, prompt, 12)
    np.testing.assert_array_equal(got, want)
    # prompt preserved
    np.testing.assert_array_equal(got[:, :4], prompt)


def test_lm_generate_sampling_and_eos(np_rng):
    params = _params(max_len=16)
    prompt = np_rng.randint(3, V, (4, 2)).astype(np.int32)
    ids = np.asarray(transformer.lm_generate(
        params, prompt, max_len=16, num_heads=HEADS, temperature=0.8,
        top_k=5, rng=jax.random.PRNGKey(3)))
    assert ids.shape == (4, 16)
    assert ((ids >= 0) & (ids < V)).all()
    # same rng -> same draw; different rng -> (overwhelmingly) different
    ids2 = np.asarray(transformer.lm_generate(
        params, prompt, max_len=16, num_heads=HEADS, temperature=0.8,
        top_k=5, rng=jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(ids, ids2)

    # eos pinning: once a row emits eos, it keeps emitting eos
    eos = 7
    ids3 = np.asarray(transformer.lm_generate(
        params, prompt, max_len=16, num_heads=HEADS, temperature=1.5,
        rng=jax.random.PRNGKey(5), eos_id=eos))
    for row in ids3:
        hit = np.where(row == eos)[0]
        if hit.size and hit[0] >= 2:           # ignore eos inside prompt
            assert (row[hit[0]:] == eos).all()

    # guards
    with pytest.raises(ValueError, match="needs rng"):
        transformer.lm_generate(params, prompt, max_len=16,
                                num_heads=HEADS, temperature=0.5)
    with pytest.raises(ValueError, match="prompt length"):
        transformer.lm_generate(params, np.zeros((1, 20), np.int32),
                                max_len=16, num_heads=HEADS)


def test_lm_generate_eos_in_prompt_does_not_pin(np_rng):
    """An eos-valued token INSIDE the prompt (bos==eos vocabs, separator
    tokens) must not suppress the continuation — only generated eos
    pins a row."""
    params = _params(max_len=12)
    eos = 5
    prompt = np.asarray([[eos, 10, 11, 12]], np.int32)
    ids = np.asarray(transformer.lm_generate(
        params, prompt, max_len=12, num_heads=HEADS, eos_id=eos))
    np.testing.assert_array_equal(ids[0, :4], prompt[0])
    # greedy continuation must equal the no-eos run until it first
    # GENERATES eos (if ever) — i.e. eos handling changed nothing early
    ids_free = np.asarray(transformer.lm_generate(
        params, prompt, max_len=12, num_heads=HEADS))
    gen, free = ids[0, 4:], ids_free[0, 4:]
    cut = np.where(free == eos)[0]
    upto = cut[0] + 1 if cut.size else len(free)
    np.testing.assert_array_equal(gen[:upto], free[:upto])


def test_lm_generate_ragged_prompts_match_per_row(np_rng):
    """One batch with per-row prompt lengths == each row generated alone
    with its exact prompt (greedy): the ragged path changes batching,
    not numerics."""
    params = _params(max_len=14)
    tp = 6
    lens = [2, 6, 4]
    prompt = np_rng.randint(3, V, (3, tp)).astype(np.int32)
    prompt[0, lens[0]:] = 0          # pad values must not matter
    prompt[2, lens[2]:] = V - 1
    got = np.asarray(transformer.lm_generate(
        params, prompt, max_len=14, num_heads=HEADS,
        prompt_lengths=np.asarray(lens)))
    for i, li in enumerate(lens):
        alone = np.asarray(transformer.lm_generate(
            params, prompt[i:i + 1, :li], max_len=14, num_heads=HEADS))
        np.testing.assert_array_equal(got[i], alone[0], err_msg=f"row {i}")
    # bad lengths fail fast
    with pytest.raises(ValueError, match="prompt_lengths"):
        transformer.lm_generate(params, prompt, max_len=14,
                                num_heads=HEADS,
                                prompt_lengths=np.asarray([2, 9, 4]))


@pytest.mark.slow   # multi-second end-to-end; nightly lane
def test_lm_demo_runs():
    """demo/lm end to end at smoke scale: trains, then prints greedy and
    sampled continuations (the 15th demo family stays green)."""
    import os
    import subprocess
    import sys
    demo = os.path.join(os.path.dirname(__file__), "..", "demo", "lm",
                        "train_and_sample.py")
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}    # skip the startup lottery
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, demo, "--epochs", "1"],
                       capture_output=True, text=True, env=env,
                       timeout=480)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "greedy" in r.stdout and "sampled" in r.stdout, r.stdout
