"""Resilience layer (paddle_tpu/resilience): the chaos matrix.

Every registered fault point fires under concurrent load and the stack
must: never deadlock, keep serving, keep recovered greedy streams
BIT-IDENTICAL to the single-request oracle, retrace nothing beyond the
rebuild, and count every recovery event into metrics.  The fault plans
are seeded/counted (resilience/faults.py), so every scenario here
replays bit-for-bit.

Training half: a trainer crash mid-pass (injected ``trainer.step``
fault in-process; a real subprocess SIGKILL mid-checkpoint-write in the
slow lane) must resume via ``train(resume=True)`` from the latest
COMPLETE pass dir to bit-identical final parameters — with a partial
``.tmp-`` checkpoint never picked up.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.resilience import (FaultPlan, InjectedFault, Supervisor,
                                   faults, retry_transient)
from paddle_tpu.resilience.supervisor import BreakerOpenError
from paddle_tpu.serving import (BatchExecutionError, Batcher,
                                GenerationBatcher, InferenceEngine,
                                ServingMetrics, make_server)
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.testing import assert_no_retrace, forbid_retrace
from paddle_tpu.utils.error import ConfigError

VOCAB, HEADS, MAX_LEN, SLOTS, BUCKETS = 64, 2, 48, 4, (8, 16)


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    """A test's fault plan must never leak into the next test (or a
    crashed test leave the process poisoned)."""
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=32, num_heads=HEADS,
                            dff=64, enc_layers=2, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def engine(params):
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=BUCKETS,
                        name="chaos_lm")


def _prompts(seed, n):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, VOCAB, rng.randint(3, BUCKETS[-1] + 1)
                        ).astype(np.int32) for _ in range(n)]


def _reference(engine, cases):
    """Clean single-request runs through the batcher — greedy decode is
    deterministic, so these token lists are the oracle."""
    bat = GenerationBatcher(engine)
    ref = [bat.submit(p, max_tokens=n).result(120)["tokens"]
           for p, n in cases]
    bat.close()
    return ref


def _drive_concurrent(bat, cases, stagger_s=0.004):
    """8+ client threads, staggered submits; returns results (None on a
    failed request) + the per-request exceptions."""
    results, excs = [None] * len(cases), [None] * len(cases)

    def client(i):
        prompt, n = cases[i]
        try:
            time.sleep(stagger_s * i)
            results[i] = bat.submit(prompt, max_tokens=n).result(120)
        except Exception as e:      # noqa: BLE001
            excs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    return results, excs


# ---------------------------------------------------------------- plans


def test_fault_plan_spec_parsing_and_determinism():
    plan = FaultPlan.from_spec(
        "serving.decode_step:at=3; trainer.step:every=2,times=2; "
        "batcher.submit:p=0.5,seed=9,action=error")
    # at=3: one-shot on exactly the 3rd hit
    for i in range(1, 7):
        try:
            plan.hit("serving.decode_step")
            fired = False
        except InjectedFault as e:
            fired = True
            assert e.hit_index == 3
        assert fired == (i == 3)
    # every=2 capped at times=2: hits 2 and 4 fire, 6 does not
    fires = []
    for i in range(1, 7):
        try:
            plan.hit("trainer.step")
        except InjectedFault:
            fires.append(i)
    assert fires == [2, 4]
    # seeded p-mode replays bit-for-bit
    def pattern():
        p = FaultPlan.from_spec("batcher.submit:p=0.5,seed=9")
        out = []
        for _ in range(32):
            try:
                p.hit("batcher.submit")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out
    first = pattern()
    assert first == pattern()
    assert 0 < sum(first) < 32          # really probabilistic
    # unregistered points and bad specs fail loudly
    with pytest.raises(ConfigError):
        FaultPlan.from_spec("serving.decode_stepp:at=1")
    with pytest.raises(ConfigError):
        FaultPlan.from_spec("serving.decode_step:bogus=1")
    with pytest.raises(ConfigError):
        FaultPlan.from_spec("serving.decode_step:at=1,every=2")
    # no plan installed: hit() is a no-op
    faults.clear()
    faults.hit("serving.decode_step")
    assert faults.fired_counts() == {}


# ------------------------------------------------------------ decode step


def test_decode_step_fault_recovery_bit_identical_under_load(engine):
    """The chaos-matrix headline: a poisoned decode step under 12
    concurrent requests (8+ clients, slot churn) rebuilds the slab and
    re-prefills every in-flight stream — every request completes with
    tokens EXACTLY equal to its clean run, zero retraces, recovery
    events counted."""
    cases = [(p, 4 + (i % 5)) for i, p in enumerate(_prompts(1, 12))]
    ref = _reference(engine, cases)
    engine.metrics = ServingMetrics()
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(engine, supervisor=sup)
    faults.install_spec("serving.decode_step:at=6")
    with assert_no_retrace(lambda: engine.step_trace_count,
                           "decode chaos recovery"):
        results, excs = _drive_concurrent(bat, cases)
        bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    assert all(e is None for e in excs), excs
    for i, r in enumerate(results):
        assert r["tokens"] == ref[i], f"stream {i} diverged after recovery"
    snap = engine.metrics.snapshot()
    assert snap["slot_reprefills_total"] >= 1
    assert snap["evictions"]["recovered"] >= 1
    assert engine.free_slots == SLOTS


def test_decode_step_hang_watchdog_rebuild_bit_identical(engine):
    """A HUNG step (injected hang past the watchdog deadline) is
    abandoned, the slab rebuilt, streams recovered bit-identically; the
    late-finishing stale thread is discarded by the epoch guard."""
    cases = [(p, 5) for p in _prompts(2, 6)]
    ref = _reference(engine, cases)
    engine.metrics = ServingMetrics()
    sup = Supervisor(step_deadline_s=0.25, breaker_threshold=10)
    bat = GenerationBatcher(engine, supervisor=sup)
    faults.install_spec("serving.decode_step:at=4,action=hang,hang_s=1.0")
    with forbid_retrace(engine, what="watchdog rebuild recovery",
                        hint="the slab rebuild retraced the step"):
        results, excs = _drive_concurrent(bat, cases)
        bat.close()
    faults.clear()
    assert all(e is None for e in excs), excs
    for i, r in enumerate(results):
        assert r["tokens"] == ref[i]
    assert sup.watchdog_trips == 1
    snap = engine.metrics.snapshot()
    assert snap["watchdog_trips_total"] == 1
    assert snap["slot_reprefills_total"] >= 1
    time.sleep(0.9)     # let the stale thread finish against the epoch
    #                     guard before the next test reuses the engine


def test_supervised_no_faults_is_zero_cost(engine):
    """Acceptance: with NO fault spec installed, a supervised batcher
    serves bit-identically to the oracle with zero extra traces and no
    recovery events — the resilience layer is free when nothing fails."""
    cases = [(p, 5) for p in _prompts(3, 6)]
    ref = _reference(engine, cases)
    engine.metrics = ServingMetrics()
    sup = Supervisor(breaker_threshold=3)
    bat = GenerationBatcher(engine, supervisor=sup)
    with assert_no_retrace(lambda: engine.step_trace_count,
                           "supervised serving without faults"):
        results, excs = _drive_concurrent(bat, cases)
        bat.close()
    assert all(e is None for e in excs)
    assert [r["tokens"] for r in results] == ref
    snap = engine.metrics.snapshot()
    assert snap["slot_reprefills_total"] == 0
    assert snap["watchdog_trips_total"] == 0
    assert snap["retries_total"] == 0
    assert snap["breaker_state"] == 0
    assert snap["faults_fired"] == {}


# ------------------------------------------------------------ prefill


def test_prefill_fault_isolated_under_load(engine):
    """An injected prefill failure fails only its admission group; the
    other concurrent requests complete and the engine keeps serving."""
    engine.metrics = ServingMetrics()
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(engine, supervisor=sup)
    cases = [(p, 4) for p in _prompts(4, 8)]
    faults.install_spec("serving.prefill:at=2")
    results, excs = _drive_concurrent(bat, cases, stagger_s=0.01)
    faults.clear()
    failed = [e for e in excs if e is not None]
    assert all(isinstance(e, BatchExecutionError) for e in failed), excs
    assert len(failed) >= 1
    assert len([r for r in results if r is not None]) \
        == len(cases) - len(failed)
    ok = bat.submit(cases[0][0], max_tokens=3).result(60)
    assert len(ok["tokens"]) == 3       # still serving
    bat.close()
    assert engine.free_slots == SLOTS


# ------------------------------------------------------------ infer plane


def _mlp_engine(warm=True):
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import Topology, reset_names
    reset_names()
    x = L.data_layer("rx", size=8)
    h = L.fc_layer(input=x, size=16, act="tanh")
    out = L.fc_layer(input=h, size=4, act="softmax")
    params = Topology([out]).init(jax.random.PRNGKey(0))
    spec = {"rx": jax.ShapeDtypeStruct((1, 8), np.float32)}
    return InferenceEngine.from_topology(out, params, spec, buckets=(4, 16),
                                         warm=warm)


def test_engine_execute_fault_isolated_and_keeps_serving():
    eng = _mlp_engine()
    bat = Batcher(eng, max_delay_ms=0.0, queue_size=64)
    row = {"rx": np.zeros((8,), np.float32)}
    faults.install_spec("serving.engine.execute:at=1")
    f = bat.submit(row)
    with pytest.raises(BatchExecutionError):
        f.result(30)
    faults.clear()
    assert np.asarray(bat.submit(row).result(30)).shape == (4,)
    assert eng.metrics.snapshot()["errors_total"] == 1
    bat.close()


# ------------------------------------------------------------ submit retry


def test_submit_retry_transient_with_idempotence(engine):
    """Transient submit failures are absorbed by the bounded retry, and
    a failed attempt admitted NOTHING (requests_total counts the one
    real admission only)."""
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine)
    prompt = _prompts(5, 1)[0]
    retried = []
    faults.install_spec("batcher.submit:every=1,times=2")   # hits 1+2 fail
    fut = retry_transient(lambda: bat.submit(prompt, max_tokens=3),
                          budget=3, base_delay_s=0.001, seed=0,
                          on_retry=lambda a, e: retried.append(a))
    assert len(fut.result(60)["tokens"]) == 3
    assert retried == [1, 2]
    snap = engine.metrics.snapshot()
    assert snap["requests_total"] == 1      # idempotent failed attempts
    # budget exhaustion: the transient error surfaces, still nothing
    # admitted by the failed attempts
    faults.install_spec("batcher.submit:every=1")
    with pytest.raises(InjectedFault):
        retry_transient(lambda: bat.submit(prompt, max_tokens=3),
                        budget=2, base_delay_s=0.001, seed=0)
    faults.clear()
    assert engine.metrics.snapshot()["requests_total"] == 1
    bat.close()


# ------------------------------------------------------------ breaker


def test_breaker_opens_sheds_and_recloses(engine):
    """M consecutive step failures open the breaker (fast shed with
    retry_after), the cooldown admits a half-open probe, and a healthy
    step closes it again — serving resumes bit-identically."""
    cases = [(p, 3) for p in _prompts(6, 1)]
    ref = _reference(engine, cases)
    engine.metrics = ServingMetrics()
    sup = Supervisor(breaker_threshold=2, breaker_cooldown_s=0.3,
                     max_request_recoveries=1)
    bat = GenerationBatcher(engine, supervisor=sup)
    prompt, n = cases[0]
    faults.install_spec("serving.decode_step:every=1")   # every step dies
    victim = bat.submit(prompt, max_tokens=n)
    with pytest.raises(BatchExecutionError):
        victim.result(60)       # recovery budget (1) exhausted
    deadline = time.time() + 5
    while sup.breaker.state != "open" and time.time() < deadline:
        time.sleep(0.01)
    assert sup.breaker.state == "open"
    with pytest.raises(BreakerOpenError) as ei:
        bat.submit(prompt, max_tokens=n)
    assert ei.value.retry_after_s > 0
    snap = engine.metrics.snapshot()
    assert snap["rejected"]["breaker"] == 1
    assert snap["breaker_state"] == 2
    assert snap["breaker_open_total"] == 1
    # cause clears; after the cooldown the half-open probe closes it
    faults.clear()
    time.sleep(0.35)
    probe = bat.submit(prompt, max_tokens=n)    # the half-open probe
    assert probe.result(60)["tokens"] == ref[0]
    deadline = time.time() + 5
    while sup.breaker.state != "closed" and time.time() < deadline:
        time.sleep(0.01)
    assert sup.breaker.state == "closed"
    assert bat.submit(prompt, max_tokens=n).result(60)["tokens"] == ref[0]
    bat.close()


def test_breaker_state_machine_units():
    """The documented open -> cooldown -> half-open -> close path, unit
    level: in-flight successes while OPEN do not bypass the cooldown
    (flapping engines keep shedding), probe failures re-open AND count,
    and half-open counts as ready (the probe must be routable)."""
    from paddle_tpu.resilience import CircuitBreaker
    b = CircuitBreaker(threshold=2, cooldown_s=0.25)
    b.record_failure()
    b.record_failure()
    assert b.state == "open" and b.opened_total == 1
    b.record_success()              # a recovered in-flight step
    assert b.state == "open"        # the cooldown stands
    time.sleep(0.3)
    assert b.state == "half_open"
    ok, _ = b.admit()               # the probe
    assert ok
    ok2, ra = b.admit()             # second caller sheds
    assert not ok2 and ra > 0
    b.record_failure()              # probe failed: re-open, counted
    assert b.state == "open" and b.opened_total == 2
    time.sleep(0.3)
    assert b.state == "half_open"
    assert b.seconds_until_probe() > 0
    b.record_success()              # post-cooldown success closes
    assert b.state == "closed"
    assert b.seconds_until_probe() == 0.0


# ------------------------------------------------------------ prefetch


def test_prefetch_h2d_fault_surfaces_in_consumer():
    from paddle_tpu.data.prefetch import ShardedPrefetcher

    def source():
        for i in range(4):
            yield {"x": np.full((2, 2), i, np.float32)}

    faults.install_spec("data.prefetch.h2d:at=2")
    pf = ShardedPrefetcher(source, depth=2)
    first = next(iter(pf))
    assert float(np.asarray(first["x"])[0, 0]) == 0.0
    with pytest.raises(InjectedFault):
        next(iter(pf))
    faults.clear()
    pf.close()          # clean close after the failure: no deadlock


# ------------------------------------------------------------ training


def _tiny_trainer(seed=7):
    import paddle_tpu.optim as optim
    from paddle_tpu.data import dense_vector, integer_value
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import reset_names
    from paddle_tpu.trainer.trainer import SGD
    reset_names()
    x = L.data_layer("res_x", size=4)
    lab = L.data_layer("res_lab", size=1)
    h = L.fc_layer(input=x, size=8, act="tanh")
    y = L.fc_layer(input=h, size=2, act="softmax")
    cost = L.classification_cost(y, lab)
    tr = SGD(cost=cost,
             update_equation=optim.Momentum(learning_rate=0.1,
                                            momentum=0.9), seed=seed)
    feeding = {"res_x": dense_vector(4), "res_lab": integer_value(2)}

    def reader():
        rng = np.random.RandomState(0)      # identical batches every pass
        xs = rng.randn(24, 4).astype(np.float32)
        ys = (xs[:, 0] > 0).astype(np.int64)
        for i in range(0, 24, 8):
            yield [(xs[j], int(ys[j])) for j in range(i, i + 8)]

    return tr, feeding, reader


def _params_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def test_trainer_step_fault_then_resume_bit_identical(tmp_path):
    """A trainer crash mid-pass (injected trainer.step fault) resumes
    via train(resume=True) from the latest complete pass — final params
    bit-identical to an uninterrupted run (rng stream checkpointed)."""
    sd = str(tmp_path / "ckpt")
    t1, feeding, reader = _tiny_trainer()
    # 3 batches/pass: hit 5 = pass 1, batch 1 — mid-pass, after the
    # pass-0 checkpoint landed
    faults.install_spec("trainer.step:at=5")
    with pytest.raises(InjectedFault):
        t1.train(reader, num_passes=2, feeding=feeding, log_period=0,
                 buffered_batches=0, save_dir=sd)
    faults.clear()
    assert sorted(d for d in os.listdir(sd) if d.startswith("pass-")) \
        == ["pass-00000"]

    t2, feeding, reader = _tiny_trainer()
    t2.train(reader, num_passes=2, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=sd, resume=True)

    t3, feeding, reader = _tiny_trainer()
    t3.train(reader, num_passes=2, feeding=feeding, log_period=0,
             buffered_batches=0)
    assert _params_equal(jax.device_get(t2.parameters),
                         jax.device_get(t3.parameters)), \
        "resumed params diverged from the uninterrupted run"
    # resume with nothing to resume is a fresh run, not an error
    t4, feeding, reader = _tiny_trainer()
    t4.train(reader, num_passes=1, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=str(tmp_path / "fresh"),
             resume=True)


def test_preemption_midpass_resume_bit_identical(tmp_path):
    """A SIGTERM-style preemption checkpoint is MID-pass: its meta
    carries batches_done, and train(resume=True) re-enters that pass
    skipping exactly the trained prefix (no step, no rng split) — final
    params bit-identical to an uninterrupted run."""
    from paddle_tpu.trainer import events
    from paddle_tpu.trainer.checkpoint import load_checkpoint
    sd = str(tmp_path / "ckpt")
    t1, feeding, reader = _tiny_trainer()

    def preempt(e):
        # the graceful-stop path without a real signal: mid pass 1
        # (batch 0 of 3), exactly what a TPU maintenance TERM produces
        if isinstance(e, events.EndIteration) and e.pass_id == 1 \
                and e.batch_id == 0:
            t1._stop_signal = 15
    t1.train(reader, num_passes=3, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=sd, event_handler=preempt)
    _, _, _, meta = load_checkpoint(sd)
    assert meta["preempted"] is True and meta["pass_id"] == 1
    assert meta["batches_done"] == 1

    t2, feeding, reader = _tiny_trainer()
    t2.train(reader, num_passes=3, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=sd, resume=True)
    t3, feeding, reader = _tiny_trainer()
    t3.train(reader, num_passes=3, feeding=feeding, log_period=0,
             buffered_batches=0)
    assert _params_equal(jax.device_get(t2.parameters),
                         jax.device_get(t3.parameters)), \
        "mid-pass preemption resume diverged"


def test_checkpoint_write_fault_leaves_no_partial(tmp_path):
    """An injected failure mid-checkpoint-write surfaces to the caller,
    leaves NO partial pass dir or .tmp- droppings, and the next save
    succeeds."""
    from paddle_tpu.trainer.checkpoint import (load_checkpoint,
                                               save_checkpoint)
    params = {"w": np.arange(4, dtype=np.float32)}
    faults.install_spec("trainer.checkpoint.write:at=1")
    with pytest.raises(InjectedFault):
        save_checkpoint(str(tmp_path), 0, params, block=True)
    faults.clear()
    assert [d for d in os.listdir(tmp_path)] == []      # fully cleaned
    save_checkpoint(str(tmp_path), 0, params, block=True)
    p, _, _, meta = load_checkpoint(str(tmp_path))
    assert meta["pass_id"] == 0
    np.testing.assert_array_equal(np.asarray(p["w"]), params["w"])


def test_partial_tmp_checkpoint_never_picked_up(tmp_path):
    """resume/load skip a mid-write partial (the exact artifact a kill
    -9 inside the writer leaves: a hidden .tmp- dir, data but no
    rename) and take the latest COMPLETE pass instead."""
    from paddle_tpu.trainer.checkpoint import (load_checkpoint,
                                               save_checkpoint)
    save_checkpoint(str(tmp_path), 0, {"w": np.zeros(2, np.float32)},
                    block=True)
    partial = tmp_path / ".tmp-pass-00001-killed"
    partial.mkdir()
    np.savez(partial / "params.npz", w=np.ones(2, np.float32))  # no meta,
    #                                                             no rename
    _, _, _, meta = load_checkpoint(str(tmp_path))
    assert meta["pass_id"] == 0         # the partial was never eligible


@pytest.mark.slow
def test_kill9_mid_checkpoint_write_resumes_bit_identical(tmp_path):
    """The honest crash: a subprocess trainer's pass-1 checkpoint write
    HANGS mid-write (injected hang inside the .tmp- staging dir) and the
    process is SIGKILLed in that window.  On disk: complete pass-0, a
    partial .tmp- for pass 1.  train(resume=True) must pick pass-0 and
    finish to params bit-identical to an uninterrupted run."""
    import signal
    import subprocess
    import sys
    sd = str(tmp_path / "ckpt")
    script = tmp_path / "victim.py"
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    script.write_text(
        # the script runs from tmp_path: both the repo root (paddle_tpu)
        # and tests/ (this module) must be put on sys.path explicitly
        "import sys; sys.path[:0] = [%r, %r]\n"
        "from paddle_tpu.resilience import faults\n"
        "from test_resilience import _tiny_trainer\n"
        # pass-1's write hangs AFTER params.npz landed in the .tmp- dir
        "faults.install_spec("
        "'trainer.checkpoint.write:at=2,action=hang,hang_s=600')\n"
        "tr, feeding, reader = _tiny_trainer()\n"
        "tr.train(reader, num_passes=2, feeding=feeding, log_period=0,\n"
        "         buffered_batches=0, save_dir=%r)\n"
        % (os.path.dirname(tests_dir), tests_dir, sd))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 240
        partial = None
        while time.time() < deadline and partial is None:
            if os.path.isdir(sd):
                partial = next((d for d in os.listdir(sd)
                                if d.startswith(".tmp-pass-00001")), None)
            time.sleep(0.1)
        assert partial is not None, "pass-1 mid-write window never opened"
        proc.send_signal(signal.SIGKILL)
        proc.wait(30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL
    # the kill left exactly the crash artifacts the atomic writer promises
    assert sorted(d for d in os.listdir(sd) if d.startswith("pass-")) \
        == ["pass-00000"]
    assert any(d.startswith(".tmp-pass-00001") for d in os.listdir(sd))

    t2, feeding, reader = _tiny_trainer()
    t2.train(reader, num_passes=2, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=sd, resume=True)
    t3, feeding, reader = _tiny_trainer()
    t3.train(reader, num_passes=2, feeding=feeding, log_period=0,
             buffered_batches=0)
    assert _params_equal(jax.device_get(t2.parameters),
                         jax.device_get(t3.parameters))


# ------------------------------------------------------------ HTTP layer


def test_http_readyz_retry_after_and_liveness(engine):
    """The liveness/readiness split + Retry-After satellites, end to
    end: /healthz stays 200 through warming, breaker-open, and drain;
    /readyz flips 503 with the blocking reasons; 429/503 carry
    Retry-After."""
    engine.metrics = ServingMetrics()
    sup = Supervisor(breaker_threshold=1, breaker_cooldown_s=30.0)
    gen = GenerationBatcher(engine, supervisor=sup)
    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    try:
        with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ready"
        # force the breaker open: readiness drops, liveness holds, and
        # a generate request sheds 503 + Retry-After fast
        sup.breaker.record_failure()
        assert sup.breaker.state == "open"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert ei.value.code == 503
        assert "breaker_open" in json.loads(ei.value.read())["reasons"]
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            body = json.loads(r.read())
            assert body["status"] == "ok" and body["draining"] is False
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": [1, 2, 3],
                             "max_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        sup.breaker.record_success()        # close it again
        # drain begun: /readyz 503 draining, /healthz still 200
        gen.close()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert "draining" in json.loads(ei.value.read())["reasons"]
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            body = json.loads(r.read())
            assert body["status"] == "ok" and body["draining"] is True
    finally:
        httpd.shutdown()
        gen.close()


def test_http_readyz_warming_and_overload_retry_after():
    eng = _mlp_engine(warm=False)       # cold ladder: not ready yet
    bat = Batcher(eng, max_delay_ms=0.0, queue_size=2)
    httpd = make_server(bat, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["reasons"] == ["warming"]
        eng.warmup()
        with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ready"
        # overload: stall the engine, fill the bounded queue, expect a
        # 429 with a queue-depth-derived Retry-After
        orig = eng.infer

        def slow(feed):
            time.sleep(0.4)
            return orig(feed)
        eng.infer = slow
        row = {"rx": np.zeros((8,), np.float32)}
        bat.submit(row)                 # occupies the worker
        time.sleep(0.05)
        bat.submit(row)
        bat.submit(row)                 # queue (size 2) now full
        req = urllib.request.Request(
            f"{base}/v1/infer",
            data=json.dumps({"feed": {"rx": [0.0] * 8}}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        eng.infer = orig
    finally:
        httpd.shutdown()
        bat.close()


# ------------------------------------------------------------ drain


def test_drain_deadline_and_second_sigterm_unit():
    """Both forced-shutdown paths of the SIGTERM handler, without
    signals: (a) a drain that never completes force-exits at the hard
    deadline; (b) a second SIGTERM force-exits immediately; (c) a drain
    that completes in time never force-exits."""
    from paddle_tpu.serving.server import _make_drain_handler

    class FakeHttpd:
        def shutdown(self):
            pass

    exits = []
    state = {}
    handler = _make_drain_handler(FakeHttpd(), state, 0.2, exits.append)
    handler(15, None)                   # first SIGTERM: drain + watchdog
    assert exits == []
    handler(15, None)                   # second SIGTERM: immediate
    assert exits == [130]
    time.sleep(0.3)                     # wedged drain: deadline fires
    assert exits == [130, 3]

    exits2, state2 = [], {}
    handler2 = _make_drain_handler(FakeHttpd(), state2, 0.2, exits2.append)
    handler2(15, None)
    state2["drained"] = True            # the drain completed in time
    time.sleep(0.3)
    assert exits2 == []                 # watchdog disarmed


@pytest.mark.slow
def test_second_sigterm_forces_exit_subprocess():
    """Integration: a real server under a real double SIGTERM exits
    immediately with the forced-exit code and logs both paths."""
    import signal
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving", "--demo",
         "--port", "0", "--buckets", "1,4", "--drain-timeout-s", "60"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        # wait for the server to be up (warm-up logged to stderr)
        deadline = time.time() + 240
        for line in proc.stderr:
            if "serving demo on" in line or time.time() > deadline:
                break
        # the startup log prints just BEFORE _serve() installs the
        # handlers; give installation a moment or the first SIGTERM
        # hits the default handler and simply terminates the process
        time.sleep(1.0)
        proc.send_signal(signal.SIGTERM)
        # wait until the FIRST handler observably ran (its drain log
        # line) before the second signal: two quick SIGTERMs can
        # coalesce into one handler invocation, and only after the line
        # is the serve_forever poll window (<=0.5s) reliably still open
        deadline = time.time() + 30
        for line in proc.stderr:
            if "SIGTERM: draining" in line or time.time() > deadline:
                break
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 130, rc                # the forced-exit code


# ------------------------------------------------------------ metrics


def test_resilience_metrics_render():
    m = ServingMetrics(name="r")
    m.observe_retry()
    m.observe_watchdog_trip()
    m.observe_slot_reprefill(2)
    m.set_breaker_state("open", opened_total=1)
    m.reject("breaker")
    m.evict_slot("recovered")
    text = m.render_prometheus()
    assert "r_retries_total 1" in text
    assert "r_watchdog_trips_total 1" in text
    assert "r_slot_reprefills_total 2" in text
    assert "r_breaker_open_total 1" in text
    assert "r_breaker_state 2" in text
    assert 'r_rejected_total{reason="breaker"} 1' in text
    assert 'r_slot_evictions_total{reason="recovered"} 1' in text
    faults.install_spec("serving.decode_step:at=1")
    try:
        faults.hit("serving.decode_step")
    except InjectedFault:
        pass
    assert 'r_fault_injections_total{point="serving.decode_step"} 1' \
        in m.render_prometheus()
    faults.clear()
    snap = m.snapshot()
    assert snap["slot_reprefills_total"] == 2
    assert snap["breaker_state"] == 2
