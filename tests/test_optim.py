"""Optimizer numerics vs reference-style numpy loops (the reference's
math/tests/test_TrainingAlgorithm.cpp pattern: fused update vs
OriginalOptimizerApi.h naive implementation)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu import optim


def run_steps(opt, w0, grads):
    state = opt.init({"w": jnp.asarray(w0)})
    params = {"w": jnp.asarray(w0)}
    for g in grads:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params)
    return np.asarray(params["w"])


def test_momentum_matches_loop(np_rng):
    w0 = np_rng.randn(5).astype(np.float32)
    grads = [np_rng.randn(5).astype(np.float32) for _ in range(4)]
    got = run_steps(optim.Momentum(learning_rate=0.1, momentum=0.9), w0, grads)
    w, mom = w0.copy(), np.zeros(5, np.float32)
    for g in grads:
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_adagrad_matches_loop(np_rng):
    w0 = np_rng.randn(5).astype(np.float32)
    grads = [np_rng.randn(5).astype(np.float32) for _ in range(4)]
    got = run_steps(optim.AdaGrad(learning_rate=0.1, epsilon=1e-6), w0, grads)
    w, acc = w0.copy(), np.zeros(5, np.float32)
    for g in grads:
        acc += g * g
        w -= 0.1 * g / (np.sqrt(acc) + 1e-6)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_adam_matches_loop(np_rng):
    w0 = np_rng.randn(5).astype(np.float32)
    grads = [np_rng.randn(5).astype(np.float32) for _ in range(5)]
    got = run_steps(optim.Adam(learning_rate=0.01), w0, grads)
    w = w0.copy()
    m = np.zeros(5, np.float32)
    v = np.zeros(5, np.float32)
    for t, g in enumerate(grads, start=1):
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mhat = m / (1 - 0.9 ** t)
        vhat = v / (1 - 0.999 ** t)
        w -= 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(got, w, rtol=1e-4)


def test_rmsprop_centered(np_rng):
    w0 = np_rng.randn(4).astype(np.float32)
    grads = [np_rng.randn(4).astype(np.float32) for _ in range(3)]
    got = run_steps(optim.RMSProp(learning_rate=0.05, rho=0.9, epsilon=1e-6), w0, grads)
    w = w0.copy()
    eg2 = np.zeros(4, np.float32)
    eg = np.zeros(4, np.float32)
    for g in grads:
        eg2 = 0.9 * eg2 + 0.1 * g * g
        eg = 0.9 * eg + 0.1 * g
        w -= 0.05 * g / np.sqrt(eg2 - eg * eg + 1e-6)
    np.testing.assert_allclose(got, w, rtol=1e-4)


def test_adadelta_matches_loop(np_rng):
    w0 = np_rng.randn(4).astype(np.float32)
    grads = [np_rng.randn(4).astype(np.float32) for _ in range(3)]
    got = run_steps(optim.AdaDelta(learning_rate=1.0, rho=0.95, epsilon=1e-6), w0, grads)
    w = w0.copy()
    eg2 = np.zeros(4, np.float32)
    edx2 = np.zeros(4, np.float32)
    for g in grads:
        eg2 = 0.95 * eg2 + 0.05 * g * g
        dx = g * np.sqrt((edx2 + 1e-6) / (eg2 + 1e-6))
        edx2 = 0.95 * edx2 + 0.05 * dx * dx
        w -= dx
    np.testing.assert_allclose(got, w, rtol=1e-4)


def test_l2_decay_folds_into_grad(np_rng):
    w0 = np.ones(3, np.float32)
    g = np.zeros(3, np.float32)
    got = run_steps(optim.Momentum(learning_rate=0.1, momentum=0.0, l2=0.5),
                    w0, [g])
    np.testing.assert_allclose(got, w0 - 0.1 * 0.5 * w0, rtol=1e-6)


def test_clip_by_value(np_rng):
    w0 = np.zeros(3, np.float32)
    g = np.array([10.0, -10.0, 0.5], np.float32)
    got = run_steps(optim.Momentum(learning_rate=1.0, momentum=0.0,
                                   clip_threshold=1.0), w0, [g])
    np.testing.assert_allclose(got, [-1.0, 1.0, -0.5], rtol=1e-6)


def test_lr_schedules():
    import jax.numpy as jnp
    from paddle_tpu.optim import schedules
    s = schedules.get("poly", 0.1, decay_a=0.5, decay_b=1.0)
    np.testing.assert_allclose(float(s(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(s(2)), 0.1 / 2.0, rtol=1e-6)
    s = schedules.get("discexp", 0.1, decay_a=0.5, decay_b=10)
    np.testing.assert_allclose(float(s(9)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(s(10)), 0.05, rtol=1e-6)
    s = schedules.get("linear", 0.1, decay_a=0.01, decay_b=0.05)
    np.testing.assert_allclose(float(s(3)), 0.07, rtol=1e-5)
    np.testing.assert_allclose(float(s(100)), 0.05, rtol=1e-6)


def test_averaging_apply():
    from paddle_tpu.optim import averaging
    params = {"w": jnp.asarray([0.0])}
    st = averaging.init(params)
    for v in (1.0, 2.0, 3.0):
        st = averaging.accumulate(st, {"w": jnp.asarray([v])})
    avg = averaging.apply(st, params)
    np.testing.assert_allclose(np.asarray(avg["w"]), [2.0], rtol=1e-6)


def test_manual_and_pass_manual_schedules():
    """Reference LearningRateScheduler.cpp ManualLRS (boundary-inclusive
    piecewise by progress) and PassManualLRS (same table keyed on the pass
    index)."""
    from paddle_tpu.optim import schedules
    m = schedules.manual(1.0, [(10, 1.0), (20, 0.5), (30, 0.1)])
    import numpy.testing as npt
    npt.assert_allclose(float(m(0)), 1.0, rtol=1e-6)
    npt.assert_allclose(float(m(10)), 1.0, rtol=1e-6)  # inclusive boundary
    npt.assert_allclose(float(m(11)), 0.5, rtol=1e-6)
    npt.assert_allclose(float(m(30)), 0.1, rtol=1e-6)
    npt.assert_allclose(float(m(99)), 0.1, rtol=1e-6)  # last rate persists

    pm = schedules.pass_manual(1.0, [(0, 1.0), (1, 0.5), (2, 0.1)],
                               steps_per_pass=5)
    for step, want in [(0, 1.0), (4, 1.0), (5, 0.5), (9, 0.5),
                       (10, 0.1), (42, 0.1)]:
        npt.assert_allclose(float(pm(step)), want, rtol=1e-6)

    import pytest as _pytest
    with _pytest.raises(ValueError, match="steps_per_pass"):
        schedules.get("pass_manual", 1.0, segments=[(0, 1.0)])
