"""Fused Pallas vanilla RNN vs the lax.scan path — same discipline as the
LSTM/GRU twins."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import rnn

B, T, D = 8, 7, 128


def _mk(np_rng, ragged=True):
    x = jnp.asarray(np_rng.randn(B, T, D) * 0.3, jnp.float32)
    lengths = (np_rng.randint(1, T + 1, (B,)) if ragged
               else np.full((B,), T))
    seq = SequenceBatch(data=x, lengths=jnp.asarray(lengths, jnp.int32))
    w = jnp.asarray(np_rng.randn(D, D) * 0.1, jnp.float32)
    bias = jnp.asarray(np_rng.randn(D) * 0.1, jnp.float32)
    return seq, w, bias


def _run(seq, w, bias, fused, reverse=False):
    prior = rnn.FUSED_LSTM
    rnn.FUSED_LSTM = "always" if fused else "0"
    try:
        out, final = rnn.simple_rnn(seq, w, bias=bias, reverse=reverse)
        return jnp.sum(out.data ** 2) + jnp.sum(final ** 2)
    finally:
        rnn.FUSED_LSTM = prior


@pytest.mark.parametrize("reverse", [False, True], ids=["fwd", "rev"])
@pytest.mark.parametrize("ragged", [False, True], ids=["full", "ragged"])
def test_fused_matches_scan_forward(np_rng, reverse, ragged):
    seq, w, bias = _mk(np_rng, ragged)
    a = _run(seq, w, bias, fused=True, reverse=reverse)
    b = _run(seq, w, bias, fused=False, reverse=reverse)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)


@pytest.mark.parametrize("reverse", [False, True], ids=["fwd", "rev"])
def test_fused_matches_scan_grads(np_rng, reverse):
    seq, w, bias = _mk(np_rng, ragged=True)

    def loss(fused, xdata, w, bias):
        s = SequenceBatch(data=xdata, lengths=seq.lengths)
        return _run(s, w, bias, fused, reverse=reverse)

    args = (seq.data, w, bias)
    ga = jax.grad(lambda *a: loss(True, *a), argnums=(0, 1, 2))(*args)
    gb = jax.grad(lambda *a: loss(False, *a), argnums=(0, 1, 2))(*args)
    for la, (a, b) in zip(["dx", "dw", "dbias"], zip(ga, gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=la)


def test_fused_zero_length_sequence(np_rng):
    seq, w, bias = _mk(np_rng, ragged=True)
    seq = SequenceBatch(data=seq.data, lengths=seq.lengths.at[0].set(0))
    a = _run(seq, w, bias, fused=True)
    b = _run(seq, w, bias, fused=False)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)
