"""Gate-blocked Pallas LSTM (ops/pallas/lstm_blocked.py) vs the lax.scan
reference path: the over-VMEM variant must reproduce forward AND every
gradient, including ragged masks, odd T (parity padding), reverse
direction, and the saved-activation BPTT."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import rnn
from paddle_tpu.ops.pallas import lstm_blocked as blk


B, D = 8, 256               # 2 gate blocks of 128


def _mk(np_rng, t, ragged=True):
    x = jnp.asarray(np_rng.randn(B, t, 4 * D) * 0.3, jnp.float32)
    lengths = (np_rng.randint(1, t + 1, (B,)) if ragged
               else np.full((B,), t))
    seq = SequenceBatch(data=x, lengths=jnp.asarray(lengths, jnp.int32))
    w_r = jnp.asarray(np_rng.randn(D, 4 * D) * 0.1, jnp.float32)
    checks = [jnp.asarray(np_rng.randn(D) * 0.1, jnp.float32)
              for _ in range(3)]
    return seq, w_r, checks


def _scan(seq, w_r, checks, reverse=False):
    prior = rnn.FUSED_LSTM
    rnn.FUSED_LSTM = "0"
    try:
        return rnn.lstm(seq, w_r, check_i=checks[0], check_f=checks[1],
                        check_o=checks[2], reverse=reverse)
    finally:
        rnn.FUSED_LSTM = prior


def _blocked(seq, w_r, checks, reverse=False):
    xs = seq.data.transpose(1, 0, 2)
    ms = seq.mask().transpose(1, 0)
    if reverse:
        xs, ms = jnp.flip(xs, 0), jnp.flip(ms, 0)
    hs, (fh, fc) = blk.lstm_fused_blocked(
        xs, ms, w_r, checks[0], checks[1], checks[2], interpret=True)
    if reverse:
        hs = jnp.flip(hs, 0)
    out = hs.transpose(1, 0, 2) * seq.mask(hs.dtype)[..., None]
    return SequenceBatch(data=out, lengths=seq.lengths), (fh, fc)


@pytest.mark.parametrize("t", [6, 7], ids=["evenT", "oddT"])
@pytest.mark.parametrize("ragged", [False, True], ids=["full", "ragged"])
def test_blocked_matches_scan_forward(np_rng, t, ragged):
    seq, w_r, checks = _mk(np_rng, t, ragged)
    got, (gh, gc) = _blocked(seq, w_r, checks)
    want, fin = _scan(seq, w_r, checks)
    np.testing.assert_allclose(np.asarray(got.data),
                               np.asarray(want.data), atol=2e-5)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(fin.c),
                               atol=2e-5)


def test_blocked_matches_scan_reverse(np_rng):
    seq, w_r, checks = _mk(np_rng, 7, ragged=True)
    got, _ = _blocked(seq, w_r, checks, reverse=True)
    want, _ = _scan(seq, w_r, checks, reverse=True)
    np.testing.assert_allclose(np.asarray(got.data),
                               np.asarray(want.data), atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("use_final", [False, True], ids=["hs", "hs+final"])
def test_blocked_matches_scan_grads(np_rng, use_final):
    seq, w_r, checks = _mk(np_rng, 7, ragged=True)

    def loss(impl, xdata, w_r, ci, cf, co):
        s = SequenceBatch(data=xdata, lengths=seq.lengths)
        out, fin = impl(s, w_r, [ci, cf, co])
        val = jnp.sum(out.data ** 2)
        if use_final:
            val = val + jnp.sum(fin[1] ** 2) + jnp.sum(fin[0]) \
                if impl is _blocked else \
                val + jnp.sum(fin.c ** 2) + jnp.sum(fin.h)
        return val

    args = (seq.data, w_r, *checks)
    ga = jax.grad(lambda *a: loss(_blocked, *a), argnums=(0, 1, 2, 3, 4))(
        *args)
    gb = jax.grad(lambda *a: loss(_scan, *a), argnums=(0, 1, 2, 3, 4))(
        *args)
    for x, y, name in zip(ga, gb, ["dx", "dwr", "dci", "dcf", "dco"]):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=5e-4, err_msg=name)


def test_dispatch_uses_blocked_for_over_vmem(monkeypatch, np_rng):
    """ops/rnn.py must route an over-VMEM hidden size to the blocked
    kernel (not the scan) when fusion is on, and count the dispatch."""
    monkeypatch.delenv("PADDLE_TPU_KERNEL_VMEM_MB", raising=False)
    # D=256 fits the resident kernel; shrink the budget so resident says
    # no but blocked (no resident weights) says yes
    from paddle_tpu.ops.pallas import lstm as resident
    need = blk.vmem_bytes(B, D)
    assert need < resident.vmem_bytes(B, D)
    monkeypatch.setenv("PADDLE_TPU_KERNEL_VMEM_MB",
                       str(need / 1024 / 1024 * 1.2))
    assert not resident.supported(B, D, "tanh", "sigmoid", "tanh", None)
    assert blk.supported(B, D, "tanh", "sigmoid", "tanh", None)

    calls = {"blocked": 0}
    orig = blk.lstm_fused_blocked
    monkeypatch.setattr(
        blk, "lstm_fused_blocked",
        lambda *a, **k: calls.__setitem__("blocked",
                                          calls["blocked"] + 1) or
        orig(*a, **k, interpret=True))
    seq, w_r, checks = _mk(np_rng, 6)
    prior = rnn.FUSED_LSTM
    rnn.FUSED_LSTM = "always"
    try:
        n0 = rnn.FUSED_DISPATCH_COUNT
        out, _ = rnn.lstm(seq, w_r)
        assert calls["blocked"] == 1
        assert rnn.FUSED_DISPATCH_COUNT == n0 + 1
        assert np.all(np.isfinite(np.asarray(out.data)))
    finally:
        rnn.FUSED_LSTM = prior
