"""KV-cached incremental decode == full-recompute decode (transformer)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.models import transformer


def _setup(b=3, src_len=9, vocab=60, d=32, heads=4, layers=2, max_len=12):
    params = transformer.init(
        jax.random.PRNGKey(0), src_vocab=vocab, trg_vocab=vocab, d_model=d,
        dff=64, enc_layers=layers, dec_layers=layers, max_len=max_len + src_len)
    rng = np.random.RandomState(1)
    src = SequenceBatch(
        data=jnp.asarray(rng.randint(3, vocab, (b, src_len)), jnp.int32),
        lengths=jnp.asarray(rng.randint(3, src_len + 1, (b,)), jnp.int32))
    return params, src, heads, max_len


@pytest.mark.slow
def test_cached_step_matches_full_decode_column():
    """decode_step_cached at position t == column t of the full decode()
    over the same prefix, for every t."""
    params, src, heads, max_len = _setup()
    b = src.data.shape[0]
    rng = np.random.RandomState(2)
    trg_ids = jnp.asarray(rng.randint(3, 60, (b, max_len)), jnp.int32)

    enc_out = transformer.encode(params, src, heads)
    full_trg = SequenceBatch(data=trg_ids,
                             lengths=jnp.full((b,), max_len, jnp.int32))
    full_logits = np.asarray(transformer.decode(
        params, enc_out, src.mask(), full_trg, heads))    # [B, T, V]

    cache = transformer.init_decode_cache(params, enc_out, max_len)
    cross = transformer.cross_kv(params, enc_out)
    for t in range(max_len):
        logits, cache = transformer.decode_step_cached(
            params, src.mask(), trg_ids[:, t], jnp.int32(t), cache, cross,
            heads)
        np.testing.assert_allclose(np.asarray(logits), full_logits[:, t],
                                   rtol=2e-4, atol=2e-4)


def test_generate_cached_matches_full_recompute():
    params, src, heads, max_len = _setup()
    full = transformer.generate(params, src, beam_size=3, max_len=max_len,
                                num_heads=heads)
    cached = transformer.generate_cached(params, src, beam_size=3,
                                         max_len=max_len, num_heads=heads)
    np.testing.assert_array_equal(np.asarray(full.tokens),
                                  np.asarray(cached.tokens))
    np.testing.assert_array_equal(np.asarray(full.lengths),
                                  np.asarray(cached.lengths))
    np.testing.assert_allclose(np.asarray(full.scores),
                               np.asarray(cached.scores), rtol=1e-4,
                               atol=1e-4)


def test_cached_decode_rejects_overlong_max_len():
    import pytest
    params, src, heads, _ = _setup()
    with pytest.raises(ValueError, match="positional table"):
        transformer.generate_cached(params, src, beam_size=2,
                                    max_len=10_000, num_heads=heads)
