"""Evaluator correctness vs sklearn-free hand computations."""

import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu import evaluators as E


def test_classification_error():
    ev = E.ClassificationError()
    st = ev.init()
    pred = jnp.asarray([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    label = jnp.asarray([0, 1, 1])
    st = ev.update(st, pred=pred, label=label)
    np.testing.assert_allclose(ev.result(st), 1.0 / 3.0, rtol=1e-6)


def test_auc_perfect_and_random():
    ev = E.Auc()
    st = ev.init()
    # perfectly separable
    pred = jnp.asarray([0.9, 0.8, 0.2, 0.1])
    label = jnp.asarray([1, 1, 0, 0])
    st = ev.update(st, pred=pred, label=label)
    assert ev.result(st) > 0.99
    # inverted
    st2 = ev.update(ev.init(), pred=1 - pred, label=label)
    assert ev.result(st2) < 0.01


def test_precision_recall_binary():
    ev = E.PrecisionRecall(num_classes=2, positive_label=1)
    st = ev.init()
    pred = jnp.asarray([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7], [0.6, 0.4]])
    label = jnp.asarray([1, 1, 0, 0])
    st = ev.update(st, pred=pred, label=label)
    r = ev.result(st)
    # predictions: [1, 0, 1, 0]; tp=1 fp=1 fn=1
    np.testing.assert_allclose(r["precision"], 0.5, rtol=1e-6)
    np.testing.assert_allclose(r["recall"], 0.5, rtol=1e-6)


def test_chunk_f1_exact_match():
    ev = E.ChunkEvaluator(num_chunk_types=2)
    st = ev.init()
    # tags: B-0 I-0 B-1 -> spans (0,2,type0),(2,3,type1)
    tags = np.asarray([[0, 1, 2]])
    st = ev.update(st, pred=tags, label=tags, lengths=np.asarray([3]))
    r = ev.result(st)
    np.testing.assert_allclose(r["f1"], 1.0, rtol=1e-6)


def test_chunk_f1_partial():
    ev = E.ChunkEvaluator(num_chunk_types=2)
    st = ev.init()
    pred = np.asarray([[0, 0, 2]])   # spans (0,1),(1,2),(2,3)
    gold = np.asarray([[0, 1, 2]])   # spans (0,2),(2,3)
    st = ev.update(st, pred=pred, label=gold, lengths=np.asarray([3]))
    r = ev.result(st)
    assert 0 < r["f1"] < 1


def test_chunk_schemes_ioe_iobes_plain():
    """Reference tag tables (ChunkEvaluator.cpp:44-48): each scheme decodes
    the same two spans from its own encoding."""
    # two chunks: type0 covering tokens 0-1, type1 at token 2, O at 3
    cases = {
        # IOE: I=0 E=1; O = 2*2=4
        "IOE": [0, 1, 3, 4],          # I-0 E-0 E-1(single via E) O
        # IOBES: B,I,E,S = 0..3; type0 tags 0-3, type1 tags 4-7; O = 8
        "IOBES": [0, 2, 7, 8],        # B-0 E-0 S-1 O
        # plain: one tag per type; O = 2
        "plain": [0, 0, 1, 2],        # 0 0 1 O
    }
    for scheme, tags in cases.items():
        ev = E.ChunkEvaluator(scheme=scheme, num_chunk_types=2)
        st = ev.init()
        arr = np.asarray([tags])
        st = ev.update(st, pred=arr, label=arr,
                       lengths=np.asarray([len(tags)]))
        assert st["gold"] == 2, (scheme, st)
        np.testing.assert_allclose(ev.result(st)["f1"], 1.0, rtol=1e-6,
                                   err_msg=scheme)


def test_chunk_requires_num_types():
    ev = E.ChunkEvaluator()
    with pytest.raises(ValueError, match="num_chunk_types"):
        ev.update(ev.init(), pred=np.asarray([[0]]),
                  label=np.asarray([[0]]), lengths=np.asarray([1]))


def test_chunk_excluded_types():
    ev = E.ChunkEvaluator(num_chunk_types=2, excluded_chunk_types=(1,))
    st = ev.init()
    tags = np.asarray([[0, 1, 2]])    # spans type0 (counted), type1 (excluded)
    st = ev.update(st, pred=tags, label=tags, lengths=np.asarray([3]))
    assert st["gold"] == 1 and st["pred"] == 1 and st["correct"] == 1


def test_ctc_error_edit_distance():
    ev = E.CTCError()
    st = ev.init()
    st = ev.update(st,
                   decoded=np.asarray([[1, 2, 3]]),
                   decoded_lengths=np.asarray([3]),
                   label=np.asarray([[1, 3]]),
                   label_lengths=np.asarray([2]))
    # edit distance(123, 13) = 1; normalized by label len 2
    np.testing.assert_allclose(ev.result(st), 0.5, rtol=1e-6)


def test_sum_and_column_sum():
    ev = E.SumEvaluator()
    st = ev.update(ev.init(), value=jnp.asarray([[1.0], [2.0]]))
    np.testing.assert_allclose(ev.result(st), 3.0)
    ev2 = E.ColumnSum(size=2)
    st2 = ev2.update(ev2.init(), value=jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))
    np.testing.assert_allclose(ev2.result(st2), [4.0, 6.0])
