"""Round-3 feature composition: a pruned fc + MoE network trained through
the HIGH-LEVEL SGD trainer on the 8-device virtual mesh matches
single-device training exactly (SURVEY §4 pattern 3: sharded == unsharded),
with the pruning mask honored throughout."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import optim
from paddle_tpu.compat.v1 import HookAttribute, ParameterAttribute
from paddle_tpu.layers import api as L
from paddle_tpu.layers.api import mse_cost
from paddle_tpu.parallel import MeshConfig, make_mesh
from paddle_tpu.trainer.trainer import SGD

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs the 8-device virtual CPU mesh")


def _net():
    x = L.data_layer("x", size=16)
    y = L.data_layer("y", size=1)
    h = L.fc_layer(input=x, size=32, act="tanh", name="hidden",
                   param_attr=ParameterAttribute(
                       update_hooks=HookAttribute(type="pruning",
                                                  sparsity_ratio=0.5)))
    m = L.moe_layer(h, n_experts=4, top_k=2, expert_dim=32, name="moe")
    out = L.fc_layer(input=m, size=1, act="sigmoid", name="out")
    return mse_cost(input=out, label=y)


def _batches(n=15, bs=32):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        xb = rng.randn(bs, 16).astype(np.float32)
        yb = (xb[:, :4].sum(1, keepdims=True) > 0).astype(np.float32)
        out.append({"x": jnp.asarray(xb), "y": jnp.asarray(yb)})
    return out


def _train(mesh):
    tr = SGD(cost=_net(), mesh=mesh,
             update_equation=optim.Momentum(learning_rate=0.2, momentum=0.9))
    costs = []
    batches = _batches()
    tr.train(lambda: iter(batches), num_passes=1,
             event_handler=lambda e: costs.append(float(e.cost))
             if type(e).__name__ == "EndIteration" else None)
    return tr, costs


@needs_8
def test_pruned_moe_net_mesh_matches_single_device():
    tr1, c1 = _train(mesh=None)
    tr8, c8 = _train(mesh=make_mesh(MeshConfig(data=8, model=1)))

    np.testing.assert_allclose(c1, c8, rtol=2e-5, atol=1e-6)
    # momentum makes per-step cost non-monotone; compare windows
    assert np.mean(c1[-3:]) < np.mean(c1[:3])
    for key in ("hidden", "moe", "out"):
        for leaf, a in tr1.parameters[key].items():
            b = tr8.parameters[key][leaf]
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=f"{key}/{leaf}")

    # the pruning mask held on BOTH paths
    for tr in (tr1, tr8):
        w = np.asarray(tr.parameters["hidden"]["w0"])
        mask = np.asarray(tr._prune_masks["hidden"]["w0"])
        assert (w[mask == 0] == 0).all()
        assert (mask == 0).mean() >= 0.48
