"""End-to-end trainer tests (the reference's test_Trainer/test_TrainerOnePass
role): train tiny nets through SGD.train, checkpoint roundtrip, inference."""

import os

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu
import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.data import reader as reader_mod
from paddle_tpu.layers.graph import reset_names
from paddle_tpu.trainer import SGD, Inferencer, events
from paddle_tpu.trainer.checkpoint import (
    save_checkpoint, load_checkpoint, merge_model, load_merged)


def setup_function(_):
    reset_names()


def _xor_reader(n=256, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 2).astype(np.float32)
    ys = ((xs[:, 0] > 0) ^ (xs[:, 1] > 0)).astype(np.int64)

    def reader():
        for i in range(0, n, batch):
            yield [(xs[j], int(ys[j])) for j in range(i, min(i + batch, n))]
    return reader


def test_sgd_train_xor_loss_drops():
    x = L.data_layer("x", size=2)
    lab = L.data_layer("lab", size=1)
    h = L.fc_layer(x, size=16, act="tanh")
    y = L.fc_layer(h, size=2, act="softmax")
    cost = L.classification_cost(y, lab)

    trainer = SGD(cost=cost, update_equation=optim.Adam(learning_rate=0.05))
    feeding = {"x": dense_vector(2), "lab": integer_value(2)}
    seen = []
    trainer.train(_xor_reader(), num_passes=12,
                  event_handler=lambda e: seen.append(e)
                  if isinstance(e, events.EndIteration) else None,
                  feeding=feeding, log_period=0, buffered_batches=0)
    first = np.mean([float(e.cost) for e in seen[:8]])
    last = np.mean([float(e.cost) for e in seen[-8:]])
    assert last < 0.5 * first, (first, last)
    # inference on the trained params
    inf = Inferencer(y, trainer.parameters)
    probs = inf.infer({"x": jnp.asarray([[1.5, 1.5], [1.5, -1.5]],
                                        jnp.float32)})
    pred = np.argmax(np.asarray(probs), axis=-1)
    np.testing.assert_array_equal(pred, [0, 1])


def test_checkpoint_roundtrip(tmp_path):
    params = {"layer": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                        "b": jnp.ones((3,))}}
    opt_state = {"step": jnp.asarray(5, jnp.int32),
                 "slots": {"mom": {"layer": {"w": jnp.zeros((2, 3)),
                                             "b": jnp.zeros((3,))}}}}
    model_state = {"bn": (jnp.zeros((3,)), jnp.ones((3,)))}
    path = save_checkpoint(str(tmp_path), 3, params, opt_state, model_state)
    assert os.path.basename(path) == "pass-00003"
    p2, o2, m2, meta = load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(np.asarray(p2["layer"]["w"]),
                               np.asarray(params["layer"]["w"]))
    assert int(o2["step"]) == 5
    assert isinstance(m2["bn"], tuple)
    np.testing.assert_allclose(np.asarray(m2["bn"][1]), 1.0)
    assert meta["pass_id"] == 3


def test_save_only_one(tmp_path):
    params = {"w": jnp.ones((2,))}
    save_checkpoint(str(tmp_path), 0, params)
    save_checkpoint(str(tmp_path), 1, params, save_only_one=True)
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("pass-"))
    assert dirs == ["pass-00001"]


def test_merge_model(tmp_path):
    params = {"w": jnp.asarray([1.0, 2.0])}
    save_checkpoint(str(tmp_path), 0, params, model_state={"s": jnp.zeros(1)})
    out = merge_model(str(tmp_path), str(tmp_path / "model.npz"))
    p, ms, meta = load_merged(out)
    np.testing.assert_allclose(np.asarray(p["w"]), [1.0, 2.0])


def test_trainer_resume(tmp_path):
    reset_names()
    x = L.data_layer("x", size=2)
    lab = L.data_layer("lab", size=1)
    y = L.fc_layer(x, size=2, act="softmax")
    cost = L.classification_cost(y, lab)
    t1 = SGD(cost=cost, update_equation=optim.Momentum(learning_rate=0.1))
    feeding = {"x": dense_vector(2), "lab": integer_value(2)}
    t1.train(_xor_reader(n=64), num_passes=1, feeding=feeding, log_period=0,
             buffered_batches=0, save_dir=str(tmp_path))
    reset_names()
    x = L.data_layer("x", size=2)
    lab = L.data_layer("lab", size=1)
    y2 = L.fc_layer(x, size=2, act="softmax")
    cost2 = L.classification_cost(y2, lab)
    t2 = SGD(cost=cost2, update_equation=optim.Momentum(learning_rate=0.1))
    meta = t2.load(str(tmp_path))
    assert meta["pass_id"] == 0
    w1 = np.asarray(t1.parameters[list(t1.parameters)[0]]["w0"])
    w2 = np.asarray(t2.parameters[list(t2.parameters)[0]]["w0"])
    np.testing.assert_allclose(w1, w2)


def test_cli_seq_buckets(tmp_path, monkeypatch):
    """--seq_buckets/--pad_batch plumb into the DataFeeder: every padded
    batch lands on one static shape (XLA compiles once)."""
    conf = tmp_path / "conf.py"
    conf.write_text(
        "import numpy as np\n"
        "import paddle_tpu.layers as L\n"
        "from paddle_tpu import optim\n"
        "from paddle_tpu.data import integer_value_sequence, integer_value\n"
        "from paddle_tpu.data import reader as reader_mod\n"
        "def _samples():\n"
        "    rng = np.random.RandomState(0)\n"
        "    for i in range(40):\n"
        "        n = int(rng.randint(3, 12))\n"
        "        yield [int(x) for x in rng.randint(0, 20, n)], int(i % 2)\n"
        "def get_config():\n"
        "    w = L.data_layer('w', size=20)\n"
        "    lbl = L.data_layer('lbl', size=2)\n"
        "    emb = L.embedding_layer(w, size=6)\n"
        "    p = L.pooling_layer(emb, pooling_type='sum')\n"
        "    out = L.fc_layer(p, size=2, act='softmax')\n"
        "    return {'cost': L.classification_cost(out, lbl),\n"
        "            'optimizer': optim.Momentum(learning_rate=0.1,\n"
        "                                        momentum=0.9),\n"
        "            'train_reader': reader_mod.batch(_samples, 16),\n"
        "            'batch_size': 16,\n"
        "            'feeding': {'w': integer_value_sequence(20),\n"
        "                        'lbl': integer_value(2)}}\n")
    from paddle_tpu.trainer import cli
    seen_shapes = set()
    from paddle_tpu.trainer import trainer as trainer_mod
    orig = trainer_mod._normalize_feed

    def spy(feed):
        out = orig(feed)
        from paddle_tpu.core.sequence import SequenceBatch
        for v in out.values():
            if isinstance(v, SequenceBatch):
                seen_shapes.add(tuple(v.data.shape))
        return out
    monkeypatch.setattr(trainer_mod, "_normalize_feed", spy)
    rc = cli.main(["train", "--config", str(conf), "--num_passes", "1",
                   "--log_period", "0", "--seq_buckets", "16",
                   "--pad_batch"])
    assert not rc
    # one bucket + padded batch = exactly one padded feed shape
    assert seen_shapes == {(16, 16)}, seen_shapes


def test_bf16_compute_dtype_trains_with_f32_master(np_rng):
    """Mixed precision: compute_dtype=bf16 must converge on XOR, keep
    master params + optimizer state f32, and actually run the forward in
    bf16 (checked through the topology with cast params)."""
    import jax.numpy as jnp
    reset_names()
    x = L.data_layer("x", size=2)
    lab = L.data_layer("lab", size=1)
    h = L.fc_layer(x, size=16, act="tanh")
    y = L.fc_layer(h, size=2, act="softmax")
    cost = L.classification_cost(y, lab)
    trainer = SGD(cost=cost, update_equation=optim.Adam(learning_rate=0.05),
                  compute_dtype=jnp.bfloat16)
    feeding = {"x": dense_vector(2), "lab": integer_value(2)}
    seen = []
    trainer.train(_xor_reader(), num_passes=12,
                  event_handler=lambda e: seen.append(e)
                  if isinstance(e, events.EndIteration) else None,
                  feeding=feeding, log_period=0, buffered_batches=0)
    first = np.mean([float(e.cost) for e in seen[:8]])
    last = np.mean([float(e.cost) for e in seen[-8:]])
    assert last < 0.5 * first, (first, last)
    # master params and optimizer slots stayed f32
    for leaf in jax.tree_util.tree_leaves(trainer.parameters):
        assert leaf.dtype == jnp.float32, leaf.dtype
    for leaf in jax.tree_util.tree_leaves(trainer.opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            assert leaf.dtype == jnp.float32, leaf.dtype
    # the step genuinely computes in bf16: the traced program carries
    # bf16 operands into its dots (activations stay f32 at accumulation
    # boundaries BY DESIGN — core/dtypes keeps >=f32 accumulation)
    feed = {"x": jnp.zeros((4, 2), jnp.float32),
            "lab": jnp.zeros((4,), jnp.int32)}
    jaxpr = str(jax.make_jaxpr(
        lambda p, f: trainer._loss_and_extras(p, {}, f,
                                              jax.random.PRNGKey(0))[0])(
        trainer.parameters, feed))
    assert "bf16" in jaxpr, "no bf16 operands in the traced step"
    # bf16 inference wrapper returns f32
    inf = Inferencer(y, trainer.parameters,
                     compute_dtype=jnp.bfloat16)
    probs = inf.infer({"x": jnp.asarray([[1.5, 1.5], [1.5, -1.5]],
                                        jnp.float32)})
    assert np.asarray(probs).dtype == np.float32
    pred = np.argmax(np.asarray(probs), axis=-1)
    np.testing.assert_array_equal(pred, [0, 1])


def test_checkpoint_async_and_atomic(tmp_path, monkeypatch):
    """block=False overlaps the disk write; wait_pending() makes it
    durable and re-raises background failures; a failed write never
    leaves a partial pass dir behind (tmp-dir + rename atomicity)."""
    from paddle_tpu.trainer import checkpoint as ck

    params = {"w": jnp.arange(4, dtype=jnp.float32)}
    p = save_checkpoint(str(tmp_path), 0, params, block=False)
    ck.wait_pending()
    assert os.path.isdir(p)
    got, _, _, meta = load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(np.asarray(got["w"]), np.arange(4.0))
    assert meta["pass_id"] == 0

    # async values are the snapshot at call time, not at write time
    mutable = {"w": jnp.zeros((2,))}
    save_checkpoint(str(tmp_path), 1, mutable, block=False)
    mutable["w"] = jnp.ones((2,))          # mutate AFTER the call
    ck.wait_pending()
    got, _, _, _ = load_checkpoint(str(tmp_path), 1)
    np.testing.assert_allclose(np.asarray(got["w"]), 0.0)

    # failure path: np.savez raising leaves no partial pass dir and the
    # error surfaces at wait_pending
    import pytest

    def boom(*a, **k):
        raise OSError("disk full")
    monkeypatch.setattr(ck.np, "savez", boom)
    save_checkpoint(str(tmp_path), 2, params, block=False)
    with pytest.raises(OSError, match="disk full"):
        ck.wait_pending()
    assert not os.path.exists(tmp_path / "pass-00002")
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp-")]
    monkeypatch.undo()
    # a later blocking save still works (pending state fully cleared)
    save_checkpoint(str(tmp_path), 3, params)
    assert os.path.isdir(tmp_path / "pass-00003")


def test_checkpoint_overwrite_same_pass(tmp_path):
    """Re-saving the same pass id atomically replaces the old dir."""
    save_checkpoint(str(tmp_path), 0, {"w": jnp.zeros((2,))})
    save_checkpoint(str(tmp_path), 0, {"w": jnp.ones((2,))})
    got, _, _, _ = load_checkpoint(str(tmp_path), 0)
    np.testing.assert_allclose(np.asarray(got["w"]), 1.0)


def test_checkpoint_pending_is_per_dir(tmp_path, monkeypatch):
    """Async saves to different dirs are independent: one dir's failure
    never surfaces in (or serializes with) another dir's save."""
    import pytest
    from paddle_tpu.trainer import checkpoint as ck

    a, b = tmp_path / "a", tmp_path / "b"
    params = {"w": jnp.ones((2,))}
    real_savez = ck.np.savez

    def boom_in_a(path, **kw):
        if os.sep + "a" + os.sep in path or "/a/" in path:
            raise OSError("quota on a")
        return real_savez(path, **kw)
    monkeypatch.setattr(ck.np, "savez", boom_in_a)

    save_checkpoint(str(a), 0, params, block=False)
    # b's save must neither raise a's error nor be blocked by it
    save_checkpoint(str(b), 0, params, block=False)
    ck.wait_pending(str(b))                    # b lands cleanly
    got, _, _, _ = load_checkpoint(str(b), 0)
    np.testing.assert_allclose(np.asarray(got["w"]), 1.0)
    with pytest.raises(OSError, match="quota on a"):
        ck.wait_pending(str(a))                # a's failure stays a's
    ck.wait_pending()                          # global drain is clean now


def test_sigterm_graceful_checkpoint(tmp_path):
    """A real SIGTERM mid-pass: the loop finishes the batch, writes a
    preemption checkpoint (meta.preempted=true), and train() returns
    cleanly — the TPU-preemption recovery story."""
    import signal
    import subprocess
    import sys
    import textwrap
    import time as _time

    script = textwrap.dedent("""
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        sys.path.insert(0, %r)
        import paddle_tpu.layers as L
        from paddle_tpu import optim
        from paddle_tpu.trainer import SGD, events
        from paddle_tpu.data import dense_vector, integer_value

        x = L.data_layer("x", size=2)
        lab = L.data_layer("lab", size=1)
        y = L.fc_layer(x, size=2, act="softmax")
        cost = L.classification_cost(y, lab)
        rng = np.random.RandomState(0)

        def reader():
            for i in range(10_000):          # far more than we will run
                time.sleep(0.05)
                yield [(rng.randn(2).astype(np.float32), 1)
                       for _ in range(8)]

        def handler(e):
            if isinstance(e, events.EndIteration) and e.batch_id == 0:
                print("READY", flush=True)

        sgd = SGD(cost, update_equation=optim.Momentum(learning_rate=0.1,
                                                       momentum=0.9))
        sgd.train(reader=reader, num_passes=5, save_dir=%r, log_period=0,
                  event_handler=handler,
                  feeding={"x": dense_vector(2), "lab": integer_value(2)})
        print("STOPPED-CLEANLY", flush=True)
    """) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            str(tmp_path / "ckpt"))
    import queue
    import threading
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True)
    lines = queue.Queue()

    def pump():
        for ln in proc.stdout:
            lines.put(ln)
        lines.put(None)
    threading.Thread(target=pump, daemon=True).start()
    try:
        deadline = _time.time() + 120
        while True:     # a hung child fails at the deadline, never blocks
            try:
                ln = lines.get(timeout=max(0.1, deadline - _time.time()))
            except queue.Empty:
                raise AssertionError("never reached first batch") from None
            assert ln is not None, "child exited before first batch"
            if "READY" in ln:
                break
            assert _time.time() < deadline, "never reached first batch"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
        out = ""
        while True:
            ln = lines.get(timeout=60)
            if ln is None:
                break
            out += ln
    finally:
        proc.kill()
    assert proc.returncode == 0, out
    assert "STOPPED-CLEANLY" in out
    from paddle_tpu.trainer.checkpoint import load_checkpoint
    p, o, m, meta = load_checkpoint(str(tmp_path / "ckpt"))
    assert meta["preempted"] is True
    assert meta["signal"] == int(signal.SIGTERM)


def test_checkpoint_overwrite_crash_window_recoverable(tmp_path, monkeypatch):
    """If a crash lands between the two renames of an overwrite-save, the
    predecessor survives as .old- and load_checkpoint recovers it."""
    from paddle_tpu.trainer import checkpoint as ck
    save_checkpoint(str(tmp_path), 0, {"w": jnp.ones((2,))})

    real_rename = os.rename
    def crash_on_final(src, dst):
        if os.path.basename(dst).startswith("pass-") and ".tmp-" in src:
            raise KeyboardInterrupt("simulated crash mid-overwrite")
        return real_rename(src, dst)
    monkeypatch.setattr(ck.os, "rename", crash_on_final)
    import pytest
    with pytest.raises(KeyboardInterrupt):
        save_checkpoint(str(tmp_path), 0, {"w": jnp.zeros((2,))})
    monkeypatch.undo()
    assert not [d for d in os.listdir(tmp_path) if d.startswith("pass-")]
    got, _, _, meta = load_checkpoint(str(tmp_path))   # .old- fallback
    np.testing.assert_allclose(np.asarray(got["w"]), 1.0)
    assert meta["pass_id"] == 0


def test_grad_accumulation_matches_full_batch(np_rng):
    """accum=2 over half-batches reproduces full-batch training: the mean
    of two half-batch mean-grads equals the full-batch mean-grad, so the
    parameter trajectories match (reference local-accumulate,
    RemoteParameterUpdater.h:37-54)."""
    import pytest
    xs = np_rng.randn(64, 2).astype(np.float32)
    ys = ((xs[:, 0] > 0) ^ (xs[:, 1] > 0)).astype(np.int64)

    def mk_reader(batch):
        def reader():
            for i in range(0, 64, batch):
                yield [(xs[j], int(ys[j])) for j in range(i, i + batch)]
        return reader

    def build(accum):
        reset_names()
        x = L.data_layer("x", size=2)
        lab = L.data_layer("lab", size=1)
        y = L.fc_layer(x, size=2, act="softmax")
        cost = L.classification_cost(y, lab)
        return SGD(cost=cost, grad_accum_steps=accum,
                   update_equation=optim.Momentum(learning_rate=0.2,
                                                  momentum=0.9))
    full = build(1)
    full.train(mk_reader(32), num_passes=2, log_period=0,
               buffered_batches=0,
               feeding={"x": dense_vector(2), "lab": integer_value(2)})
    acc = build(2)
    acc.train(mk_reader(16), num_passes=2, log_period=0,
              buffered_batches=0,
              feeding={"x": dense_vector(2), "lab": integer_value(2)})
    for k in full.parameters:
        for kk in full.parameters[k]:
            np.testing.assert_allclose(
                np.asarray(acc.parameters[k][kk]),
                np.asarray(full.parameters[k][kk]), atol=1e-5,
                err_msg=f"{k}/{kk}")
    assert int(acc.opt_state["tick"]) == 0     # pass ended on a boundary
    with pytest.raises(Exception):
        build(0)


def test_grad_accum_rejects_sparse(np_rng):
    import pytest
    reset_names()
    w = L.data_layer("w", size=50)
    lbl = L.data_layer("lbl", size=2)
    emb = L.embedding_layer(w, size=8, sparse_update=True)
    p = L.pooling_layer(emb, pooling_type="sum")
    out = L.fc_layer(p, size=2, act="softmax")
    cost = L.classification_cost(out, lbl)
    with pytest.raises(Exception, match="sparse"):
        SGD(cost=cost, grad_accum_steps=2,
            update_equation=optim.Momentum(learning_rate=0.1))


def test_grad_accum_mid_checkpoint_resume(np_rng, tmp_path):
    """A checkpoint taken MID-accumulation carries gsum/tick; resuming
    with a matching grad_accum_steps continues the same trajectory, and a
    mismatched setting is rejected up front (not a KeyError mid-jit)."""
    import pytest
    xs = np_rng.randn(48, 2).astype(np.float32)
    ys = (xs[:, 0] > 0).astype(np.int64)

    def mk_reader(n_batches):
        def reader():
            for i in range(n_batches):
                s = (i * 16) % 48
                yield [(xs[j], int(ys[j])) for j in range(s, s + 16)]
        return reader

    def build(accum=2):
        reset_names()
        x = L.data_layer("x", size=2)
        lab = L.data_layer("lab", size=1)
        y = L.fc_layer(x, size=2, act="softmax")
        cost = L.classification_cost(y, lab)
        return SGD(cost=cost, grad_accum_steps=accum,
                   update_equation=optim.Momentum(learning_rate=0.2,
                                                  momentum=0.9))
    feeding = {"x": dense_vector(2), "lab": integer_value(2)}

    # 3 micro-batches with accum=2 -> ends MID-accumulation (tick=1)
    a = build()
    a.train(mk_reader(3), num_passes=1, feeding=feeding, log_period=0,
            buffered_batches=0, save_dir=str(tmp_path))
    assert int(a.opt_state["tick"]) == 1

    b = build()
    b.load(str(tmp_path))
    assert int(b.opt_state["tick"]) == 1
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(b.opt_state["gsum"])[0]),
        np.asarray(jax.tree_util.tree_leaves(a.opt_state["gsum"])[0]))
    # both finish the accumulation window with the same 4th micro-batch
    a.train(mk_reader(1), num_passes=1, feeding=feeding, log_period=0,
            buffered_batches=0)
    b.train(mk_reader(1), num_passes=1, feeding=feeding, log_period=0,
            buffered_batches=0)
    for k in a.parameters:
        for kk in a.parameters[k]:
            np.testing.assert_allclose(np.asarray(b.parameters[k][kk]),
                                       np.asarray(a.parameters[k][kk]),
                                       atol=1e-6)
    # an accum=1 consumer (e.g. the CLI test job) unwraps the state,
    # discarding the partial sums with a warning — never a crash
    c = build(accum=1)
    c.load(str(tmp_path))
    assert "gsum" not in (c.opt_state if isinstance(c.opt_state, dict)
                          else {})
    # a DIFFERENT accum value on a mid-accumulation checkpoint is the one
    # genuinely unsafe case and fails loudly
    d = build(accum=4)
    with pytest.raises(Exception, match="mid-accumulation"):
        d.load(str(tmp_path))



def _write_tiny_conf(path, n_samples=32, with_test_reader=False):
    """Shared tiny CLI config: 2-feature softmax classifier on synthetic
    data (the three CLI-job tests differ only in reader size/test_reader)."""
    test_line = ("    'test_reader': reader_mod.batch(_samples, 8),\n"
                 if with_test_reader else "")
    path.write_text(
        "import numpy as np\n"
        "import paddle_tpu.layers as L\n"
        "from paddle_tpu import optim\n"
        "from paddle_tpu.data import dense_vector, integer_value\n"
        "from paddle_tpu.data import reader as reader_mod\n"
        "def _samples():\n"
        "    rng = np.random.RandomState(0)\n"
        f"    for i in range({n_samples}):\n"
        "        yield rng.randn(2).astype(np.float32), int(i % 2)\n"
        "def get_config():\n"
        "    x = L.data_layer('x', size=2)\n"
        "    lbl = L.data_layer('lbl', size=2)\n"
        "    out = L.fc_layer(x, size=2, act='softmax')\n"
        "    return {'cost': L.classification_cost(out, lbl),\n"
        "            'optimizer': optim.Momentum(learning_rate=0.1),\n"
        "            'train_reader': reader_mod.batch(_samples, 8),\n"
        + test_line +
        "            'batch_size': 8,\n"
        "            'feeding': {'x': dense_vector(2),\n"
        "                        'lbl': integer_value(2)}}\n")

def test_cli_grad_accum_flag(tmp_path):
    conf = tmp_path / "conf.py"
    _write_tiny_conf(conf)
    from paddle_tpu.trainer import cli
    rc = cli.main(["train", "--config", str(conf), "--num_passes", "1",
                   "--log_period", "0", "--grad_accum_steps", "2"])
    assert not rc


def test_cli_test_job_loads_accum_checkpoint(tmp_path):
    """Train with --grad_accum_steps 2, evaluate with the plain test job:
    the accum wrapper unwraps transparently."""
    conf = tmp_path / "conf.py"
    _write_tiny_conf(conf, with_test_reader=True)
    from paddle_tpu.trainer import cli
    d = tmp_path / "out"
    rc = cli.main(["train", "--config", str(conf), "--num_passes", "1",
                   "--log_period", "0", "--grad_accum_steps", "2",
                   "--save_dir", str(d)])
    assert not rc
    rc = cli.main(["test", "--config", str(conf), "--model_dir", str(d)])
    assert not rc


def test_cli_time_job(tmp_path, capsys):
    conf = tmp_path / "conf.py"
    _write_tiny_conf(conf, n_samples=64)
    from paddle_tpu.trainer import cli
    rc = cli.main(["time", "--config", str(conf), "--num_batches", "4",
                   "--warmup", "1"])
    assert not rc
    out = capsys.readouterr().out
    # few samples: percentile labels would overstate fidelity, so the
    # job reports min/mean/max instead
    assert "4 batches" in out and "min=" in out and "max=" in out
    assert "p99=" not in out


def test_train_prefetch_bit_identical():
    """train(prefetch=2) — feeder conversion + H2D on the background
    pipeline thread, donation active (donate defaults True) — produces
    BIT-identical parameters to the synchronous prefetch=0 loop: same
    batches, same order, same rng stream, donation-safe buffers."""
    from paddle_tpu.utils.stats import global_stats

    def build():
        reset_names()
        x = L.data_layer("x", size=2)
        lab = L.data_layer("lab", size=1)
        h = L.fc_layer(x, size=8, act="tanh")
        y = L.fc_layer(h, size=2, act="softmax")
        cost = L.classification_cost(y, lab)
        return SGD(cost=cost, update_equation=optim.Adam(learning_rate=0.05))

    feeding = {"x": dense_vector(2), "lab": integer_value(2)}
    sync = build()
    sync.train(_xor_reader(n=128), num_passes=3, feeding=feeding,
               log_period=0, buffered_batches=0, prefetch=0)
    global_stats.get("h2d_wait").reset()
    over = build()
    over.train(_xor_reader(n=128), num_passes=3, feeding=feeding,
               log_period=0, buffered_batches=0, prefetch=2)
    # the overlap is observable: every batch passed through the counter
    assert global_stats.get("h2d_wait").count == 3 * 4
    for k in sync.parameters:
        for kk in sync.parameters[k]:
            np.testing.assert_array_equal(
                np.asarray(over.parameters[k][kk]),
                np.asarray(sync.parameters[k][kk]),
                err_msg=f"{k}/{kk}: prefetch=2 diverged from prefetch=0")


def test_train_prefetch_propagates_reader_error():
    """A reader blowing up mid-pass surfaces in train() (producer-thread
    failure crosses into the training thread), and the pipeline shuts
    down instead of leaking its thread."""
    import threading
    import pytest

    def bad_reader():
        yield [(np.zeros(2, np.float32), 0) for _ in range(8)]
        raise RuntimeError("reader died")

    reset_names()
    x = L.data_layer("x", size=2)
    lab = L.data_layer("lab", size=1)
    y = L.fc_layer(x, size=2, act="softmax")
    cost = L.classification_cost(y, lab)
    tr = SGD(cost=cost, update_equation=optim.Momentum(learning_rate=0.1))
    with pytest.raises(RuntimeError, match="reader died"):
        tr.train(lambda: bad_reader(), num_passes=1, log_period=0,
                 buffered_batches=0, prefetch=2,
                 feeding={"x": dense_vector(2), "lab": integer_value(2)})
    assert not [t for t in threading.enumerate()
                if t.name == "paddle-tpu-prefetch" and t.is_alive()]


def _bucketed_seq_data(n=48, batch=16, seed=0):
    """Variable-length id sequences: batch 0's lengths stay <= 8 (lands
    on the 8-bucket), later batches reach 15 (the 16-bucket) — both
    precompiled shapes are genuinely exercised."""
    rng = np.random.RandomState(seed)
    lens = [int(rng.randint(3, 9)) if i < batch else int(rng.randint(9, 16))
            for i in range(n)]
    samples = [([int(v) for v in rng.randint(0, 20, ln)], int(i % 2))
               for i, ln in enumerate(lens)]

    def reader():
        for i in range(0, n, batch):
            yield samples[i:i + batch]
    return reader


def test_feed_specs_cross_product_multi_seq():
    """__call__ buckets every sequence slot independently, so feed_specs
    must cover the full bounds cross-product: a seq2seq batch with short
    sources and long targets still hits a precompiled shape."""
    from paddle_tpu.data import integer_value_sequence

    feeder = DataFeeder({"src": integer_value_sequence(10),
                         "tgt": integer_value_sequence(10),
                         "lbl": integer_value(2)},
                        bucket_bounds=[8, 16], pad_batch_to=4)
    specs = feeder.feed_specs(4)
    assert len(specs) == 4                        # 2 bounds ** 2 slots
    shapes = {(s["src"].data.shape[1], s["tgt"].data.shape[1])
              for s in specs}
    assert shapes == {(8, 8), (8, 16), (16, 8), (16, 16)}
    assert all(s["lbl"].shape == (4,) for s in specs)


def test_precompile_buckets_no_retrace():
    """Trainer.precompile compiles ONE executable per bucket feed spec
    (DataFeeder.feed_specs), and a subsequent train() over those buckets
    dispatches to them without a single new trace — the trace-count hook
    (SGD.trace_count only increments inside the step's Python body, i.e.
    under tracing) is the assertable no-retrace guarantee."""
    from paddle_tpu.data import integer_value_sequence
    from paddle_tpu.trainer import Trainer        # = SGD, modern spelling

    reset_names()
    w = L.data_layer("w", size=20)
    lbl = L.data_layer("lbl", size=2)
    emb = L.embedding_layer(w, size=6)
    p = L.pooling_layer(emb, pooling_type="sum")
    out = L.fc_layer(p, size=2, act="softmax")
    cost = L.classification_cost(out, lbl)
    tr = Trainer(cost=cost,
                 update_equation=optim.Momentum(learning_rate=0.1))

    batch, bounds = 16, [8, 16]
    feeder = DataFeeder({"w": integer_value_sequence(20),
                         "lbl": integer_value(2)},
                        bucket_bounds=bounds, pad_batch_to=batch)
    specs = feeder.feed_specs(batch)
    assert len(specs) == 2                        # one per bucket
    assert tr.precompile(specs) == 2
    assert tr.precompile(specs) == 0              # idempotent: all cached
    assert tr.trace_count >= 2

    from paddle_tpu.testing import assert_no_retrace
    reader = _bucketed_seq_data(batch=batch)
    with assert_no_retrace(lambda: tr.trace_count,
                           "train() over precompiled buckets",
                           hint="a bucket shape missed precompile"):
        tr.train(reader, num_passes=2, feeding=feeder, log_period=0,
                 buffered_batches=0)
        # and the precompiled path trains for real with prefetch too
        tr.train(reader, num_passes=1, feeding=feeder, log_period=0,
                 buffered_batches=0, prefetch=2)


def test_cli_time_job_percentiles(tmp_path, capsys):
    conf = tmp_path / "conf.py"
    _write_tiny_conf(conf, n_samples=816)          # 102 batches of 8
    from paddle_tpu.trainer import cli
    rc = cli.main(["time", "--config", str(conf), "--num_batches", "100",
                   "--warmup", "1"])
    assert not rc
    out = capsys.readouterr().out
    assert "100 batches" in out and "p50=" in out and "p99=" in out
