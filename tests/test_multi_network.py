"""MultiNetwork machine (reference gserver/gradientmachines/
MultiNetwork.{h,cpp}, model_type 'multi_nn'): several sub-networks, one
shared parameter store, joint or alternating updates."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.api import MultiNetwork
from paddle_tpu.layers.graph import reset_names


def _two_nets():
    reset_names()
    # sub-net A: classifier over x; sub-net B: regressor sharing the
    # first fc's weights by param name (cross-network tying)
    x = L.data_layer("x", size=6)
    lab = L.data_layer("lab", size=1)
    h_a = L.fc_layer(x, size=8, act="tanh", param_attr={"name": "shared_h"})
    cost_a = L.classification_cost(
        input=L.fc_layer(h_a, size=2, act="softmax"), label=lab)

    y = L.data_layer("y", size=6)
    tgt = L.data_layer("tgt", size=1)
    h_b = L.fc_layer(y, size=8, act="tanh", param_attr={"name": "shared_h"})
    cost_b = L.mse_cost(L.fc_layer(h_b, size=1, act=None), tgt)
    return cost_a, cost_b


def _feed(r):
    return {"x": r.randn(4, 6).astype(np.float32),
            "lab": r.randint(0, 2, (4, 1)).astype(np.int32),
            "y": r.randn(4, 6).astype(np.float32),
            "tgt": r.randn(4, 1).astype(np.float32)}


def test_shared_params_single_store(np_rng):
    mn = MultiNetwork(list(_two_nets()))
    assert "shared_h" in mn.parameters
    # both machines read the SAME dict
    assert mn.machines[0].parameters is mn.parameters
    assert mn.machines[1].parameters is mn.parameters
    outs = mn.forward(_feed(np_rng))
    assert len(outs) == 2


def test_joint_update_sums_gradients(np_rng):
    feed = _feed(np_rng)
    mn = MultiNetwork(list(_two_nets()))
    opt = optim.Momentum(learning_rate=0.1, momentum=0.0)
    st = opt.init(mn.parameters)
    c0 = mn.forwardBackward(feed)
    st = mn.applyOptimizer(opt, st)

    # manual check: one update from the sum of both machines' grads
    mn2 = MultiNetwork(list(_two_nets()))
    mn2.forwardBackward(feed, subnet=0)
    g0 = mn2.machines[0]._grads
    mn2.machines[0]._grads = None
    mn2.forwardBackward(feed, subnet=1)
    g1 = mn2.machines[1]._grads
    summed = jax.tree_util.tree_map(jnp.add, g0, g1)
    expect, _ = opt.update(summed, opt.init(mn2.parameters), mn2.parameters)
    for a, b in zip(jax.tree_util.tree_leaves(mn.parameters),
                    jax.tree_util.tree_leaves(expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_alternating_updates_gan_style(np_rng):
    """Alternating per-subnet updates (the reference gan_trainer drove
    MultiNetwork sub-nets through the API the same way).  momentum=0.9:
    a frozen sub-net's params must not drift via velocity/decay on its
    zero-grad leaves."""
    feed = _feed(np_rng)
    mn = MultiNetwork(list(_two_nets()))
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9, l2=0.01)
    st = opt.init(mn.parameters)
    before_b_head = np.asarray(
        jax.tree_util.tree_leaves(mn.parameters["__fc_3__"])[0]).copy()

    mn.forwardBackward(feed, subnet=0)
    st = mn.applyOptimizer(opt, st, subnet=0)
    # twice: with momentum+decay a naive full-tree update would move
    # subnet 1's params on the second step even with zero grads
    mn.forwardBackward(feed, subnet=0)
    st = mn.applyOptimizer(opt, st, subnet=0)

    # subnet 0's updates must not touch subnet 1's private head...
    after_b_head = np.asarray(
        jax.tree_util.tree_leaves(mn.parameters["__fc_3__"])[0])
    np.testing.assert_array_equal(before_b_head, after_b_head)
    # ...but does move the shared trunk
    mn.forwardBackward(feed, subnet=1)
    st = mn.applyOptimizer(opt, st, subnet=1)
    assert np.any(before_b_head != np.asarray(
        jax.tree_util.tree_leaves(mn.parameters["__fc_3__"])[0]))


def test_gradient_machine_mode_registry(np_rng):
    """GradientMachineMode plugin registry (reference GradientMachineMode.h
    dispatched at Trainer.cpp:150-156): registered modes construct through
    GradientMachine.create, unknown modes fail fast naming the registry,
    re-registration is rejected."""
    from paddle_tpu.api import GradientMachine, GradientMachineMode

    reset_names()
    x = L.data_layer("x", size=4)
    lab = L.data_layer("lab", size=1)
    cost = L.classification_cost(
        input=L.fc_layer(x, size=2, act="softmax"), label=lab)

    # default mode: the standard machine
    gm0 = GradientMachine.create(cost)
    assert isinstance(gm0, GradientMachine)

    made = {}

    @GradientMachineMode.register("logging")
    def make_logging(outputs, seed=1, tag=None, **kw):
        made["tag"] = tag
        return GradientMachine.createFromTopology(outputs, seed=seed)

    try:
        assert GradientMachineMode.is_registered("logging")
        assert "logging" in GradientMachineMode.registered()
        gm = GradientMachine.create(cost, mode="logging", tag="t1")
        assert made["tag"] == "t1"
        feed = {"x": np_rng.randn(4, 4).astype(np.float32),
                "lab": np_rng.randint(0, 2, (4, 1)).astype(np.int32)}
        c, _ = gm.forwardBackward(feed)
        assert np.isfinite(c)
        # collision fails fast
        import pytest
        with pytest.raises(ValueError, match="already registered"):
            GradientMachineMode.register("logging", make_logging)
        # unknown mode names what exists
        with pytest.raises(KeyError, match="logging"):
            GradientMachine.create(cost, mode="nope")
    finally:
        GradientMachineMode.unregister("logging")
