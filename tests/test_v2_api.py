"""paddle.v2-compat namespace tests (reference python/paddle/v2/tests
role): the canonical v2 script shape must run unchanged modulo the import
line, plus parameters tar roundtrip and checkgrad."""

import io

import numpy as np
import jax.numpy as jnp

import paddle_tpu.v2 as paddle
from paddle_tpu.layers.graph import reset_names


def setup_function(_):
    reset_names()


def _reader(np_rng, n=128, batch_ignored=None):
    xs = np_rng.randn(n, 4).astype(np.float32)
    ys = (xs.sum(1) > 0).astype(np.int64)

    def r():
        for i in range(n):
            yield xs[i], int(ys[i])
    return r, xs, ys


def test_v2_script_shape(np_rng):
    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data("x", size=4)
    y = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax)
    lab = paddle.layer.data("lab", size=1)
    cost = paddle.layer.classification_cost(y, lab)

    params = paddle.parameters.create(cost)
    assert params.names()
    trainer = paddle.trainer.SGD(
        cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))

    raw, xs, ys = _reader(np_rng)
    seen = []

    def handler(ev):
        if isinstance(ev, paddle.event.EndIteration):
            seen.append(float(ev.cost))

    trainer.train(paddle.batch(raw, 32), num_passes=6,
                  event_handler=handler,
                  feeding={"x": paddle.data_type.dense_vector(4),
                           "lab": paddle.data_type.integer_value(2)},
                  log_period=0, buffered_batches=0)
    assert np.mean(seen[-4:]) < 0.6 * np.mean(seen[:4])

    probs = paddle.infer(output_layer=y, parameters=params,
                         input={"x": jnp.asarray(xs[:8])})
    assert np.asarray(probs).shape == (8, 2)


def test_parameters_tar_roundtrip(np_rng):
    x = paddle.layer.data("x", size=3)
    y = paddle.layer.fc(x, size=2, act=None)
    params = paddle.parameters.create(y)
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    flat = paddle.parameters.Parameters.from_tar(buf)
    for name in params.names():
        np.testing.assert_array_equal(flat[name], params[name])
    # into a like-tree
    buf.seek(0)
    p2 = paddle.parameters.Parameters.from_tar(buf, like=params)
    for name in params.names():
        np.testing.assert_array_equal(p2[name], params[name])


def test_checkgrad(np_rng):
    import paddle_tpu.layers as L
    from paddle_tpu.layers.graph import Topology
    from paddle_tpu.testing import check_topology_grads
    x = L.data_layer("x", size=5)
    lab = L.data_layer("lab", size=1)
    h = L.fc_layer(x, size=6, act="tanh")
    cost = L.classification_cost(L.fc_layer(h, size=3, act="softmax"), lab)
    feed = {"x": jnp.asarray(np_rng.randn(4, 5), jnp.float32),
            "lab": jnp.asarray(np_rng.randint(0, 3, (4,)))}
    results = check_topology_grads(Topology(cost), feed)
    assert results


def test_v2_module_shims():
    """minibatch/topology/config_base import like the reference v2 pkg."""
    import paddle_tpu.v2 as v2
    assert [len(b) for b in v2.minibatch.batch(lambda: iter(range(5)), 2)()] \
        == [2, 2, 1]
    from paddle_tpu.layers.graph import LayerOutput, Topology
    assert v2.topology.Topology is Topology
    assert v2.config_base.Layer is LayerOutput
