"""Sequence op tests: padded+mask results must equal per-sequence numpy
loops (the reference's padding-free semantics — SURVEY.md §7 hard part (c))."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch, pad_sequences, pad_nested_sequences
from paddle_tpu.ops import sequence as seq_ops


def make_batch(np_rng, lens=(5, 3, 1, 7), dim=4):
    seqs = [np_rng.randn(l, dim).astype(np.float32) for l in lens]
    return seqs, pad_sequences(seqs)


def test_pad_sequences_roundtrip(np_rng):
    seqs, sb = make_batch(np_rng)
    assert sb.data.shape == (4, 7, 4)
    np.testing.assert_array_equal(np.asarray(sb.lengths), [5, 3, 1, 7])
    flat = seq_ops.scatter_rows_to_steps(sb)
    np.testing.assert_allclose(flat, np.concatenate(seqs, axis=0), rtol=1e-6)


def test_seq_pools_match_numpy(np_rng):
    seqs, sb = make_batch(np_rng)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_max_pool(sb)),
        np.stack([s.max(0) for s in seqs]), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_avg_pool(sb)),
        np.stack([s.mean(0) for s in seqs]), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_sum_pool(sb)),
        np.stack([s.sum(0) for s in seqs]), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_last(sb)),
        np.stack([s[-1] for s in seqs]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_first(sb)),
        np.stack([s[0] for s in seqs]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_sqrt_pool(sb)),
        np.stack([s.sum(0) / np.sqrt(len(s)) for s in seqs]), rtol=1e-5)


def test_expand(np_rng):
    seqs, sb = make_batch(np_rng)
    vec = np_rng.randn(4, 6).astype(np.float32)
    out = seq_ops.expand(jnp.asarray(vec), sb)
    for i, s in enumerate(seqs):
        got = np.asarray(out.data[i, :len(s)])
        np.testing.assert_allclose(got, np.tile(vec[i], (len(s), 1)), rtol=1e-6)
    # padding is zero
    assert np.all(np.asarray(out.data[2, 1:]) == 0)


def test_seq_concat(np_rng):
    la, lb = (3, 5, 2), (4, 1, 6)
    sa = [np_rng.randn(l, 3).astype(np.float32) for l in la]
    sb_ = [np_rng.randn(l, 3).astype(np.float32) for l in lb]
    out = seq_ops.seq_concat(pad_sequences(sa), pad_sequences(sb_))
    for i in range(3):
        expect = np.concatenate([sa[i], sb_[i]], axis=0)
        np.testing.assert_allclose(np.asarray(out.data[i, :len(expect)]),
                                   expect, rtol=1e-6)
        assert int(out.lengths[i]) == la[i] + lb[i]


def test_context_projection_matches_reference_semantics(np_rng):
    # context_start=-1, context_len=3: each step concats [prev, cur, next]
    seqs, sb = make_batch(np_rng, lens=(4, 2), dim=3)
    out = seq_ops.context_projection(sb, context_len=3, context_start=-1)
    for i, s in enumerate(seqs):
        T = len(s)
        for t in range(T):
            parts = []
            for off in (-1, 0, 1):
                j = t + off
                parts.append(s[j] if 0 <= j < T else np.zeros(3, np.float32))
            np.testing.assert_allclose(np.asarray(out.data[i, t]),
                                       np.concatenate(parts), rtol=1e-6,
                                       err_msg=f"seq {i} step {t}")


def test_sub_seq_and_slice(np_rng):
    seqs, sb = make_batch(np_rng, lens=(6, 4), dim=2)
    out = seq_ops.sub_seq(sb, jnp.asarray([1, 0]), jnp.asarray([3, 2]), max_out=4)
    np.testing.assert_allclose(np.asarray(out.data[0, :3]), seqs[0][1:4], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out.data[1, :2]), seqs[1][0:2], rtol=1e-6)
    assert np.all(np.asarray(out.data[1, 2:]) == 0)


def test_seq_reshape(np_rng):
    seqs, sb = make_batch(np_rng, lens=(4, 2), dim=4)
    out = seq_ops.seq_reshape(sb, new_dim=2)
    assert out.data.shape == (2, 8, 2)
    np.testing.assert_array_equal(np.asarray(out.lengths), [8, 4])
    np.testing.assert_allclose(np.asarray(out.data[0, :8]).reshape(-1),
                               seqs[0].reshape(-1), rtol=1e-6)


def test_nested_batch(np_rng):
    data = [
        [np_rng.randn(2, 3).astype(np.float32), np_rng.randn(4, 3).astype(np.float32)],
        [np_rng.randn(1, 3).astype(np.float32)],
    ]
    nb = pad_nested_sequences(data)
    assert nb.data.shape == (2, 2, 4, 3)
    np.testing.assert_array_equal(np.asarray(nb.outer_lengths), [2, 1])
    flat = nb.flatten_outer()
    np.testing.assert_array_equal(np.asarray(flat.lengths), [2, 4, 1, 0])


def test_max_id_and_eos():
    x = jnp.asarray([[0.1, 0.9], [0.8, 0.2]])
    np.testing.assert_array_equal(np.asarray(seq_ops.max_id(x)), [1, 0])
    ids = jnp.asarray([1, 2, 1])
    np.testing.assert_array_equal(np.asarray(seq_ops.eos_check(ids, 1)), [1.0, 0.0, 1.0])
