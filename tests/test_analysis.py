"""Static invariant analyzer (paddle_tpu/analysis/; docs/analysis.md).

Every rule is proven IN REVERSE against the seeded-violation fixtures
(analysis/fixtures/) — the analytic-gate discipline: a detector that
never fires is no detector — plus clean controls, the committed-tree
rc-0 acceptance gate, baseline round-trip, JSON schema, and the
FAMILIES/JIT_ROOTS drift test that keeps perf/analytic.py and the
analyzer agreeing on what a "jitted step" is.

The retrace rules also get a RUNTIME confirmation: the statically
flagged fixture shape really retraces per value under jit, its
data-fed twin doesn't (testing/trace.forbid_retrace both ways).

No jax import at module level — the analyzer itself must never need
one; only the runtime-confirmation test pays it.  The real-subprocess
CLI drive rides the slow lane (the in-process calls here cover the
same code at fast-lane cost).
"""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.analysis import baseline as baseline_mod
from paddle_tpu.analysis import callgraph, locks, purity, retrace
from paddle_tpu.analysis import roots as roots_mod
from paddle_tpu.analysis.__main__ import main as analysis_main
from paddle_tpu.analysis.roots import (FAMILIES, FAMILY_ROOTS, JIT_ROOTS,
                                       Root, TRACE_TIME_FLAGS, all_roots)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_JIT_FIXTURE = "paddle_tpu.analysis.fixtures.jit_impure"
_RETRACE_FIXTURE = "paddle_tpu.analysis.fixtures.retrace_hazards"
_LOCK_FIXTURE = "paddle_tpu/analysis/fixtures/lock_disorder.py"


@pytest.fixture(scope="module")
def project():
    """ONE parsed AST index shared by every test here (the parse is the
    expensive part; the passes are milliseconds)."""
    return callgraph.Project(_ROOT)


def _rules(findings):
    return {f.rule for f in findings}


def _keys(findings):
    return {f.key for f in findings}


# ------------------------------------------------------- reverse gates

def test_jit_purity_catches_every_seeded_violation(project):
    found = purity.run(project, [Root("fx", f"{_JIT_FIXTURE}:bad_step")])
    assert "jit-forbidden-call" in _rules(found)
    assert "jit-flags-read" in _rules(found)
    hit_targets = {f.key.rsplit(":", 1)[1] for f in found
                   if f.rule == "jit-forbidden-call"}
    # one per forbidden namespace, incl. the transitive helper reach
    assert {"time.perf_counter", "random.random",
            "threading.get_ident",
            "paddle_tpu.resilience.faults.hit",
            "paddle_tpu.serving.metrics.ServingMetrics",
            "paddle_tpu.obs.trace.enable",
            "paddle_tpu.utils.logging.get_logger",
            "time.sleep"} <= hit_targets
    # the transitive one is attributed to the helper, with the chain
    transitive = [f for f in found if f.key.endswith("time.sleep")]
    assert transitive and len(transitive[0].chain) == 2
    # the non-trace-time FLAGS read names the flag
    assert any(f.key.endswith(":serving_gen_slots") for f in found
               if f.rule == "jit-flags-read")


def test_jit_purity_clean_control(project):
    found = purity.run(project,
                       [Root("fx", f"{_JIT_FIXTURE}:clean_step")])
    assert found == []


def test_jit_purity_visits_every_qualname_sharing_variant(project):
    """Regression (review finding): both fixture `variant_step` defs
    share one qualname and only the SECOND is impure — the walk must
    not dedupe variants away (the DecodeEngine _step_fn situation)."""
    found = purity.run(project,
                       [Root("fx", f"{_JIT_FIXTURE}:variant_step")])
    assert any(f.key.endswith("time.sleep") for f in found), found


def test_retrace_catches_every_seeded_violation(project):
    found = retrace.run(project,
                        [Root("fx", f"{_RETRACE_FIXTURE}:hazard_step")])
    assert {"retrace-data-branch", "retrace-host-sync",
            "retrace-shape-key", "retrace-unordered-iter"} \
        <= _rules(found)
    details = _keys(found)
    assert any("if:positions" in k for k in details)        # if on data
    assert any("while:lengths" in k for k in details)       # while on data
    assert any("int:" in k for k in details)                # int(tracer)
    assert any("item()" in k for k in details)              # .item()
    assert any("fstring:" in k for k in details)            # shape key
    # member-side membership is a VALUE comparison (review finding):
    # `tokens[1] in (0, 1)` must flag (the clean control pins that
    # container-side `"ks" in params` still launders)
    assert any("if:tokens" in k for k in details), details
    # the transitive hazard is found INSIDE the helper via taint
    assert any("_hazard_helper" in k for k in details), details


def test_retrace_clean_control(project):
    found = retrace.run(project,
                        [Root("fx", f"{_RETRACE_FIXTURE}:clean_step")])
    assert found == []


def test_missing_root_is_a_finding_in_every_rooted_pass(project):
    """A drifted root ref must never make a pass vacuously green
    (review finding): purity AND retrace both report it."""
    ghost = [Root("ghost", "no.such.module:nope")]
    assert {f.rule for f in purity.run(project, ghost)} \
        == {"jit-root-missing"}
    assert {f.rule for f in retrace.run(project, ghost)} \
        == {"retrace-root-missing"}


def test_malformed_root_arg_is_a_usage_error(capsys):
    """--root without MOD:QUALNAME shape -> documented rc 2, not a
    traceback (review finding)."""
    assert analysis_main(["--check", "retrace", "--root", "foo",
                          *_FIXTURE_SCAN]) == 2


def test_stale_detection_is_scoped_to_the_selected_check(tmp_path,
                                                         capsys):
    """Regression (review finding): a still-valid LOCKS baseline entry
    must not read as stale under `--check jit --strict` — staleness is
    judged only against the passes that ran."""
    bl = str(tmp_path / "bl.json")
    baseline_mod.dump(bl, {
        "locks:lock-mixed-guard:some.Class.attr": "other pass's entry"})
    rc = analysis_main(["--check", "jit", "--strict", "--baseline", bl,
                        "--root", f"{_JIT_FIXTURE}:clean_step",
                        *_FIXTURE_SCAN])
    assert rc == 0, "locks entry misread as stale by a jit-only run"
    # ...but the SAME entry is honestly stale for a locks run (scanned
    # against a lock-free file, so rc 1 comes from staleness alone)
    rc = analysis_main(["--check", "locks", "--strict", "--baseline",
                        bl, "--lock-paths",
                        "paddle_tpu/analysis/fixtures/__init__.py",
                        *_FIXTURE_SCAN])
    assert rc == 1


def test_locks_catch_cycle_reacquire_and_mixed_guard(project):
    found = locks.run(project, [_LOCK_FIXTURE])
    assert {"lock-order-cycle", "lock-reacquire", "lock-mixed-guard"} \
        <= _rules(found)
    cyc = [f for f in found if f.rule == "lock-order-cycle"]
    keys = {f.key for f in cyc}
    assert any("LockA._lock" in k and "LockB._lock" in k for k in keys)
    # regression (review finding): the acquisition hidden behind the
    # a<->b CALL cycle still produces the _lh -> _la edge even though
    # the driver forces the memo-poisoning computation order first —
    # the CycleHolder ordering cycle must be reported
    assert any("CycleInner._la" in k and "CycleHolderH._lh" in k
               for k in keys), keys
    assert cyc[0].chain            # provenance: the edges
    reacq = {f.key for f in found if f.rule == "lock-reacquire"}
    assert any("Reacquirer._lock" in k for k in reacq)
    mixed = [f for f in found if f.rule == "lock-mixed-guard"]
    assert any("MixedGuard.count" in f.key for f in mixed)
    # the *_locked-suffix helper counted as guarded, racy_inc did not
    assert "racy_inc" in mixed[0].message
    assert "_bump_locked" not in mixed[0].message


def test_locks_real_scan_set_is_not_polluted_by_fixtures(project):
    """The committed gate never sees the seeded lock violations: the
    default scan set excludes analysis/fixtures entirely."""
    found = locks.run(project)
    assert not any("lock_disorder" in f.path for f in found)


# ------------------------------------------- the gate on the real tree

@pytest.mark.slow       # whole-tree parse x all three passes: the
#                         heavy run rides the slow lane (the fast lane
#                         is budget-saturated per PR 14's host note);
#                         healthy_window phase 17 + the subprocess CLI
#                         test below gate the same thing
def test_clean_tree_exits_zero():
    """Acceptance: `python -m paddle_tpu.analysis --check all` exits 0
    on HEAD — every finding fixed or baselined with a reason."""
    assert analysis_main(["--check", "all"]) == 0


_FIXTURE_SCAN = ["--scan-package",
                 os.path.join("paddle_tpu", "analysis", "fixtures")]


def test_each_pass_exits_nonzero_on_its_fixture(capsys):
    """Acceptance: EACH of the three passes exits non-zero through the
    real entry point on its seeded violation fixture.  The scan is
    restricted to the fixtures subtree — same passes, same rc path,
    ~30 ms instead of a whole-tree parse per call."""
    assert analysis_main(["--check", "retrace", "--no-baseline",
                          "--root", f"{_RETRACE_FIXTURE}:hazard_step",
                          *_FIXTURE_SCAN]) == 1
    assert analysis_main(["--check", "locks", "--no-baseline",
                          "--lock-paths", _LOCK_FIXTURE,
                          *_FIXTURE_SCAN]) == 1
    capsys.readouterr()                       # drop the text reports
    # jit last, --json: doubles as the output-schema pin
    rc = analysis_main(["--check", "jit", "--no-baseline", "--json",
                        "--root", f"{_JIT_FIXTURE}:bad_step",
                        *_FIXTURE_SCAN])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == 1 and doc["check"] == "jit"
    assert doc["new"] == len(doc["findings"]) > 0
    assert doc["baselined"] == 0 and doc["stale_baseline_keys"] == []
    f0 = doc["findings"][0]
    assert {"check", "rule", "key", "path", "line", "func", "message",
            "chain", "baselined", "reason"} <= set(f0)
    assert doc["roots"] == [f"{_JIT_FIXTURE}:bad_step"]
    assert isinstance(doc["counts"], dict) and doc["counts"]


# ------------------------------------------------- baseline round-trip

def test_baseline_roundtrip_and_validation(tmp_path):
    p = str(tmp_path / "bl.json")
    entries = {"locks:lock-mixed-guard:a.B.c": "single-threaded by X",
               "jit:jit-forbidden-call:m:f:time.sleep": "trace-time"}
    baseline_mod.dump(p, entries)
    assert baseline_mod.load(p) == entries
    # empty reason rejected
    doc = json.load(open(p))
    doc["entries"][0]["reason"] = "  "
    json.dump(doc, open(p, "w"))
    with pytest.raises(ValueError, match="non-empty reason"):
        baseline_mod.load(p)
    # duplicate keys rejected
    doc["entries"][0]["reason"] = "ok"
    doc["entries"].append(dict(doc["entries"][0]))
    json.dump(doc, open(p, "w"))
    with pytest.raises(ValueError, match="duplicate"):
        baseline_mod.load(p)
    # wrong schema rejected
    json.dump({"schema": 99, "entries": []}, open(p, "w"))
    with pytest.raises(ValueError, match="schema"):
        baseline_mod.load(p)


def test_baseline_apply_marks_and_reports_stale():
    f1 = baseline_mod.Finding("jit", "r", "k1", "p", 1, "f", "m")
    f2 = baseline_mod.Finding("jit", "r", "k2", "p", 2, "f", "m")
    new, stale = baseline_mod.apply([f1, f2],
                                    {"k1": "why", "gone": "old"})
    assert new == [f2]
    assert f1.baselined and f1.reason == "why" and not f2.baselined
    assert stale == ["gone"]


def test_committed_baseline_loads_and_is_justified():
    entries = baseline_mod.load(os.path.join(
        _ROOT, "paddle_tpu", "analysis", "baseline.json"))
    for key, reason in entries.items():
        assert len(reason) > 20, (key, "a real reason, not a stub")


# ------------------------------------------------------- registry drift

def test_every_family_maps_to_known_roots(project):
    """A new bench family cannot add a jitted step the analyzer doesn't
    see: FAMILIES and FAMILY_ROOTS must cover each other exactly, every
    mapped root must exist, and every root ref must resolve in the AST
    index with its static_args naming real parameters."""
    names = {n for n, _m, _b in FAMILIES}
    assert names == set(FAMILY_ROOTS), (
        "FAMILIES vs FAMILY_ROOTS drift — map the new family in "
        "paddle_tpu/analysis/roots.py")
    for fam, rs in FAMILY_ROOTS.items():
        assert rs, f"{fam}: empty root mapping"
        for r in rs:
            assert r in JIT_ROOTS, f"{fam} names unknown root {r}"
    for root in all_roots():
        infos = project.function(root.ref)
        assert infos, f"root {root.name}: {root.ref} not found in AST"
        params = set(infos[0].params())
        missing = set(root.static_args) - params
        assert not missing, (
            f"root {root.name}: static_args {sorted(missing)} are not "
            f"parameters of {root.ref} (has {sorted(params)})")


def test_analytic_families_is_the_shared_registry():
    from paddle_tpu.perf import analytic
    assert analytic.FAMILIES is roots_mod.FAMILIES


def test_trace_time_flags_are_real_flags():
    import dataclasses
    from paddle_tpu.utils.flags import Flags
    fields = {f.name for f in dataclasses.fields(Flags)}
    assert TRACE_TIME_FLAGS <= fields


# ------------------------------------ runtime confirmation (jax lane)

def test_flagged_shape_really_retraces_and_data_twin_does_not():
    """The static retrace-data-branch rule describes a REAL retrace:
    fixtures' branchy_step (flagged) compiles one program per value of
    its branched arg, while masked_step (the data-fed fix) warms in one
    trace and never retraces — forbid_retrace pins both directions."""
    import jax
    import numpy as np
    from paddle_tpu.analysis.fixtures import retrace_hazards as fx
    from paddle_tpu.testing import counting, forbid_retrace

    x = np.ones(4, np.float32)

    bad = counting(fx.branchy_step)
    jbad = jax.jit(bad, static_argnums=(1,))
    jbad(x, 1)                                   # warm-up trace
    with pytest.raises(AssertionError, match="traced"):
        with forbid_retrace(bad, what="branch-on-data step"):
            jbad(x, 2)                           # new value -> new trace
            jbad(x, 3)

    good = counting(fx.masked_step)
    jgood = jax.jit(good)
    jgood(x, np.float32(1.0))                    # warm-up trace
    assert good.trace_count == 1
    with forbid_retrace(good, what="data-masked step"):
        for keep in (0.0, 1.0, 0.0):
            jgood(x, np.float32(keep))           # variation as data
    # and the two agree where the branch says they should
    np.testing.assert_allclose(
        np.asarray(jbad(x, 1)),
        np.asarray(jgood(x, np.float32(1.0))))


def test_forbid_retrace_accepts_engines_and_callables():
    from paddle_tpu.testing import forbid_retrace

    class FakeEngine:
        step_trace_count = 0
    eng = FakeEngine()
    box = [0]
    with forbid_retrace(eng, lambda: box[0], what="fake"):
        pass                                     # nothing moved: fine
    with pytest.raises(AssertionError, match="fake"):
        with forbid_retrace(eng, lambda: box[0], what="fake"):
            box[0] += 1
    with pytest.raises(TypeError):
        with forbid_retrace():
            pass


# ------------------------------------------------ real CLI (slow lane)

@pytest.mark.slow
def test_cli_subprocess_rc_strict_and_write_baseline(tmp_path):
    """The real command line end to end: rc 0 on HEAD, rc 1 on the
    seeded fixture, --write-baseline round-trips into a passing gate,
    and --strict turns a stale entry into rc 1."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", *args],
            cwd=_ROOT, env=env, capture_output=True, text=True,
            timeout=300)

    assert run("--check", "all").returncode == 0
    r = run("--check", "retrace", "--no-baseline",
            "--root", f"{_RETRACE_FIXTURE}:hazard_step")
    assert r.returncode == 1 and "retrace-data-branch" in r.stdout
    # bootstrap a baseline covering the fixture -> gate passes with it
    bl = str(tmp_path / "fixture_bl.json")
    r = run("--check", "retrace", "--root",
            f"{_RETRACE_FIXTURE}:hazard_step", "--write-baseline", bl)
    assert r.returncode == 0
    doc = json.load(open(bl))
    for e in doc["entries"]:
        e["reason"] = "fixture: seeded on purpose"
    json.dump(doc, open(bl, "w"))
    r = run("--check", "retrace", "--baseline", bl,
            "--root", f"{_RETRACE_FIXTURE}:hazard_step")
    assert r.returncode == 0, r.stdout + r.stderr
    # a stale IN-SCOPE entry: warns by default, fails under --strict
    # (an out-of-scope prefix would be ignored — see the scoped-stale
    # test above)
    doc["entries"].append({"key": "retrace:gone:x:y:z",
                           "reason": "stale"})
    json.dump(doc, open(bl, "w"))
    r = run("--check", "retrace", "--baseline", bl,
            "--root", f"{_RETRACE_FIXTURE}:hazard_step")
    assert r.returncode == 0 and "stale" in r.stderr
    r = run("--check", "retrace", "--baseline", bl, "--strict",
            "--root", f"{_RETRACE_FIXTURE}:hazard_step")
    assert r.returncode == 1
