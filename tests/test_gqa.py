"""Grouped-query attention (transformer.init(num_kv_heads=K)): fewer KV
heads carried entirely by the weight shapes — KV cache shrinks by
H/K, every path (full logits, prefill, cached generation, rope, packed)
infers the grouping from the projections."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import attention as att
from paddle_tpu.models import transformer

V, DM, T = 48, 16, 12
HEADS, KV = 4, 2


def _gqa_params(pos_type="learned", seed=0):
    return transformer.init(jax.random.PRNGKey(seed), src_vocab=V,
                            trg_vocab=1, d_model=DM, dff=32,
                            enc_layers=2, dec_layers=0, max_len=T,
                            num_heads=HEADS, num_kv_heads=KV,
                            pos_type=pos_type)


def test_repeat_kv_heads():
    x = jnp.arange(2 * 2 * 3 * 4, dtype=jnp.float32).reshape(2, 2, 3, 4)
    r = att.repeat_kv_heads(x, 4)
    assert r.shape == (2, 4, 3, 4)
    np.testing.assert_array_equal(np.asarray(r[:, 0]), np.asarray(r[:, 1]))
    np.testing.assert_array_equal(np.asarray(r[:, 0]), np.asarray(x[:, 0]))
    assert att.repeat_kv_heads(x, 2) is x
    with pytest.raises(ValueError, match="divisible"):
        att.repeat_kv_heads(x, 3)


def test_gqa_equals_mha_when_kv_weights_tile(np_rng):
    """A GQA trunk whose each KV head equals the corresponding group's
    (identical) MHA heads reproduces full MHA — the grouping is pure
    structure."""
    mha = transformer.init(jax.random.PRNGKey(0), src_vocab=V, trg_vocab=1,
                           d_model=DM, dff=32, enc_layers=2, dec_layers=0,
                           max_len=T)
    import copy
    gqa = copy.deepcopy(mha)
    dh = DM // HEADS
    for i, blk in enumerate(gqa["enc"]):
        for w in ("wk", "wv"):
            full = np.asarray(blk["attn"][w])       # [D, H*dh]
            # take one head per group as the shared KV head...
            grouped = full.reshape(DM, HEADS, dh)[:, ::HEADS // KV, :]
            blk["attn"][w] = jnp.asarray(
                np.ascontiguousarray(grouped).reshape(DM, KV * dh))
            # ...and make the MHA heads within each group identical
            tiled = np.repeat(grouped, HEADS // KV, axis=1)
            mha["enc"][i]["attn"][w] = jnp.asarray(
                np.ascontiguousarray(tiled).reshape(DM, HEADS * dh))
    toks = SequenceBatch(
        jnp.asarray(np_rng.randint(3, V, (3, T)), jnp.int32),
        jnp.full((3,), T, jnp.int32))
    l_mha = transformer.lm_logits(mha, toks, HEADS)
    l_gqa = transformer.lm_logits(gqa, toks, HEADS)
    np.testing.assert_allclose(np.asarray(l_gqa), np.asarray(l_mha),
                               atol=2e-5)


def test_gqa_cache_is_smaller(np_rng):
    params = _gqa_params()
    cache = transformer.init_lm_cache(params, batch=2, max_len=T)
    assert cache[0]["k"].shape == (2, T, DM // HEADS * KV)


@pytest.mark.parametrize("pos_type", ["learned", "rope"])
def test_gqa_generate_matches_oracle(np_rng, pos_type):
    """KV-cached GQA generation (small rotated cache) == full-recompute
    argmax rollout, for both positional schemes."""
    params = _gqa_params(pos_type=pos_type)
    prompt = np_rng.randint(3, V, (3, 4)).astype(np.int32)
    got = np.asarray(transformer.lm_generate(
        params, prompt, max_len=T, num_heads=HEADS, pos_type=pos_type))
    b = prompt.shape[0]
    ids = np.zeros((b, T), np.int32)
    ids[:, :4] = prompt
    for t in range(T - 1):
        sb = SequenceBatch(jnp.asarray(ids),
                           jnp.full((b,), t + 1, jnp.int32))
        logits = transformer.lm_logits(params, sb, HEADS,
                                       pos_type=pos_type)
        nxt = np.asarray(jnp.argmax(logits[:, t], axis=-1))
        if t + 1 >= 4:
            ids[:, t + 1] = nxt
    np.testing.assert_array_equal(got, ids)


def test_gqa_lm_trains(np_rng):
    from paddle_tpu import optim
    params = _gqa_params()
    rng = np.random.RandomState(0)
    data = (np.arange(T)[None] + rng.randint(0, 45, (8, 1))) % 45 + 3
    toks = SequenceBatch(jnp.asarray(data, jnp.int32),
                         jnp.full((8,), T, jnp.int32))
    opt = optim.Adam(learning_rate=3e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(
            lambda p: transformer.lm_loss(p, toks, HEADS))(p)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    first = None
    for _ in range(120):
        params, state, l = step(params, state)
        first = first if first is not None else float(l)
    assert float(l) < 0.5 * first, (first, float(l))


def test_gqa_init_validates():
    with pytest.raises(ValueError, match="divisible"):
        transformer.init(jax.random.PRNGKey(0), src_vocab=V, trg_vocab=1,
                         d_model=DM, dff=32, enc_layers=1, dec_layers=0,
                         max_len=T, num_heads=4, num_kv_heads=3)
