"""bench.py result cache: successful runs persist, wedged runs replay the
cache with provenance, CPU runs don't pollute the committed TPU numbers."""

import importlib.util
import json
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "_CACHE_PATH", str(tmp_path / "bench_cache.json"))
    monkeypatch.delenv("BENCH_NO_CACHE", raising=False)
    monkeypatch.delenv("BENCH_CACHE_CPU", raising=False)
    return mod


def _tpu_result(value=5.14):
    return {"metric": "LSTM-textclass h=512", "value": value,
            "unit": "ms/batch", "vs_baseline": round(184.0 / value, 2),
            "mfu": 0.129, "device": "TPU v5e", "platform": "axon"}


def test_store_and_replay_on_failure(bench, capsys):
    bench._cache_store("lstm", _tpu_result())
    cache = bench._cache_load()
    assert cache["lstm"]["value"] == 5.14
    assert "measured_at" in cache["lstm"]

    stub = {"metric": "lstm (pending)", "value": None,
            "error": "backend_unavailable_timeout", "phase": "init",
            "detail": "watchdog"}
    rc = bench._emit_failure(stub, "lstm")
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["cached"] is True
    assert out["value"] == 5.14
    assert out["live_error"] == "backend_unavailable_timeout"
    assert out["live_phase"] == "init"
    assert "lstm" in out["families"]


def test_failure_without_cache_reports_stub(bench, capsys):
    stub = {"metric": "lstm (pending)", "value": None,
            "error": "backend_unavailable_timeout", "phase": "init"}
    rc = bench._emit_failure(stub, "lstm")
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 3
    assert out["value"] is None
    assert out["error"] == "backend_unavailable_timeout"


def test_failure_for_other_model_not_borrowed(bench, capsys):
    bench._cache_store("resnet50", _tpu_result(31.0))
    stub = {"metric": "lstm (pending)", "value": None,
            "error": "compile_failed", "phase": "compile"}
    rc = bench._emit_failure(stub, "lstm")
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 2
    assert out["value"] is None


def test_cpu_runs_not_cached(bench):
    res = _tpu_result()
    res["platform"] = "cpu"
    bench._cache_store("lstm", res)
    assert bench._cache_load() == {}


def test_families_summary(bench):
    bench._cache_store("lstm", _tpu_result())
    bench._cache_store("resnet50", _tpu_result(31.0))
    fam = bench._families_summary(bench._cache_load())
    assert set(fam) == {"lstm", "resnet50"}
    assert fam["lstm"]["value"] == 5.14
    assert fam["lstm"]["measured_at"]


def test_no_cache_env_disables(bench, monkeypatch):
    monkeypatch.setenv("BENCH_NO_CACHE", "1")
    bench._cache_store("lstm", _tpu_result())
    assert bench._cache_load() == {}


def test_perf_report_renders_tables(tmp_path, capsys):
    import json
    from paddle_tpu.scripts import perf_report
    cache = {
        "lstm": {"metric": "LSTM h=512 bs=64", "value": 5.0,
                 "vs_baseline": 36.8, "mfu": 0.13, "fused_rnn": True,
                 "measured_at": "2026-07-30T05:00:00Z"},
        "lstm@scan": {"metric": "LSTM h=512 bs=64", "value": 15.0,
                      "measured_at": "2026-07-30T05:00:00Z"},
        "lstm1280": {"metric": "LSTM h=1280 bs=64", "value": 18.0,
                     "vs_baseline": 35.6, "fused_rnn": False,
                     "measured_at": "2026-07-30T05:00:00Z"},
        "lstm1280@scan": {"metric": "LSTM h=1280 bs=64", "value": 18.0,
                          "measured_at": "2026-07-30T05:00:00Z"},
        "resnet50@bs512": {"metric": "ResNet-50 bs=512", "value": 99.0,
                           "mfu": 0.4, "remat": True,
                           "measured_at": "2026-07-30T06:00:00Z"},
        "resnet50@bs512@bfloat16": {"metric": "ResNet-50 bs=512",
                                    "value": 55.0, "mfu": 0.6,
                                    "measured_at": "2026-07-30T07:00:00Z"},
    }
    path = tmp_path / "cache.json"
    path.write_text(json.dumps(cache))
    perf_report.main(["--cache", str(path)])
    out = capsys.readouterr().out
    assert "| lstm | 64 | 184.0 | 5.0 | 36.8× | 13.0% |" in out
    assert "| resnet50@bs512 | 99.0 | 40.0% | — | yes |" in out
    # bf16 rows leave the scaling table and pair into their own table;
    # the baseline is honestly labelled auto (the bare TPU row runs the
    # auto bf16-MXU policy) unless an explicit @float32 row exists
    assert "resnet50@bs512@bfloat16" not in out.split("Mixed-precision")[0]
    assert "| resnet50@bs512 | auto | 99.0 | 55.0 | 1.80× | 60.0% |" in out
    assert "| lstm | 5.0 | 15.0 | 3.00× | kernel |" in out
    # a dispatch that actually ran the scan is flagged, not sold as a win
    assert "| lstm1280 | 18.0 | 18.0 | 1.00× | scan (!) |" in out


def test_transformer_serving_bench_buckets(bench):
    """The serving bench builds one fixed batch per (bucket, chunk) from a
    mixed-length request stream and a single run() serves them all; tiny
    dims keep this a CPU-feasible structure check."""
    run, flops, baseline, metric, extra = bench.bench_transformer_serving(
        batch=2, n_requests=6, src_max=16, buckets=(8, 16), max_len=4,
        vocab=64, d_model=16, dff=32, layers=1, heads=2)
    assert baseline is None and flops > 0
    assert "bucketed" in metric
    assert extra["tokens_per_step"] > 0
    import numpy as np
    s = run(0)
    assert np.isfinite(float(s))


def test_cache_key_for(bench, monkeypatch):
    monkeypatch.delenv("BENCH_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FUSED_RNN", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FUSED_LSTM", raising=False)
    assert bench.cache_key_for("lstm", 64) == "lstm"          # default bs
    assert bench.cache_key_for("lstm", 256) == "lstm@bs256"
    assert bench.cache_key_for("smoke_kernels") == "smoke_kernels"
    monkeypatch.setenv("PADDLE_TPU_FUSED_RNN", "0")
    assert bench.cache_key_for("lstm", 64) == "lstm@scan"
    assert bench.cache_key_for("alexnet", 64) == "alexnet"    # not an RNN
    monkeypatch.setenv("BENCH_DTYPE", "bfloat16")
    assert bench.cache_key_for("alexnet", 64) == "alexnet@bfloat16"
    assert bench.cache_key_for("lstm", 256) == "lstm@bs256@scan@bfloat16"


def test_sweep_skip_fresh(tmp_path, monkeypatch):
    """bench_sweep only skips combos whose cache row is live-at-this-exact-
    revision and recent; anything else (old, other revision, dirty tree,
    missing) re-runs."""
    import time as _time
    from paddle_tpu.scripts import bench_sweep as sw
    from paddle_tpu.utils import revision as rev_mod

    monkeypatch.delenv("BENCH_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FUSED_RNN", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FUSED_LSTM", raising=False)
    monkeypatch.delenv("BENCH_PLATFORM", raising=False)
    now = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    old = "2020-01-01T00:00:00Z"
    cache = {
        "lstm": {"value": 5.0, "unit": "ms/batch", "revision": "abc123",
                 "measured_at": now},
        "alexnet": {"value": 9.0, "unit": "ms/batch", "revision": "abc123",
                    "measured_at": old},
        "googlenet": {"value": 7.0, "unit": "ms/batch",
                      "revision": "OTHER", "measured_at": now},
    }
    p = tmp_path / "bench_cache.json"
    p.write_text(json.dumps(cache))

    monkeypatch.setattr(rev_mod, "code_revision", lambda: "abc123")
    assert sw._fresh_live_row("lstm", 64, 3600, str(p))["value"] == 5.0
    assert sw._fresh_live_row("alexnet", 64, 3600, str(p)) is None   # old
    assert sw._fresh_live_row("googlenet", 64, 3600, str(p)) is None # rev
    assert sw._fresh_live_row("resnet50", 32, 3600, str(p)) is None  # none
    assert sw._fresh_live_row("lstm", 64, 0, str(p)) is None         # off
    monkeypatch.setattr(rev_mod, "code_revision", lambda: "abc123+dirty1")
    assert sw._fresh_live_row("lstm", 64, 3600, str(p)) is None      # dirty


def test_sweep_skip_fresh_platform_guards(tmp_path, monkeypatch):
    """CPU rows never satisfy freshness; a cpu-forced sweep never skips."""
    import time as _time
    from paddle_tpu.scripts import bench_sweep as sw
    from paddle_tpu.utils import revision as rev_mod

    monkeypatch.delenv("BENCH_DTYPE", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FUSED_RNN", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FUSED_LSTM", raising=False)
    monkeypatch.delenv("BENCH_PLATFORM", raising=False)
    now = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    p = tmp_path / "bench_cache.json"
    p.write_text(json.dumps({
        "lstm": {"value": 5.0, "revision": "abc123", "measured_at": now,
                 "platform": "cpu"},
        "alexnet": {"value": 9.0, "revision": "abc123", "measured_at": now,
                    "platform": "tpu"}}))
    monkeypatch.setattr(rev_mod, "code_revision", lambda: "abc123")
    assert sw._fresh_live_row("lstm", 64, 3600, str(p)) is None
    assert sw._fresh_live_row("alexnet", 64, 3600, str(p)) is not None
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")
    assert sw._fresh_live_row("alexnet", 64, 3600, str(p)) is None


def test_sweep_stops_on_dead_probe_after_timeout(monkeypatch, capsys):
    """A *_timeout combo triggers the liveness probe; a dead probe stops
    the sweep instead of burning the remaining combos' deadlines."""
    from paddle_tpu.scripts import bench_sweep as sw

    calls = []
    def fake_combo(model, batch, steps, timeout):
        calls.append(model)
        return {"error": "input_build_timeout", "value": None}
    monkeypatch.setattr(sw, "run_combo", fake_combo)
    monkeypatch.setattr(sw, "_chip_alive", lambda timeout_s=90: False)
    monkeypatch.delenv("BENCH_SWEEP_SKIP_FRESH_S", raising=False)
    rc = sw.main(["--combos", "lstm:64,alexnet:64,googlenet:64"])
    assert calls == ["lstm"]          # stopped after the first combo
    assert rc == 2
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["sweep"]["lstm:64"]["wedge_probe"] == "dead"


def test_sweep_continues_on_live_probe_after_timeout(monkeypatch):
    """A slow/oversized combo (timeout but chip alive) must NOT stop the
    sweep — remaining combos still use the healthy window."""
    from paddle_tpu.scripts import bench_sweep as sw

    calls = []
    def fake_combo(model, batch, steps, timeout):
        calls.append(model)
        if model == "lstm":
            return {"error": "compile_timeout", "value": None}
        return {"value": 9.0, "unit": "ms/batch", "error": None}
    monkeypatch.setattr(sw, "run_combo", fake_combo)
    monkeypatch.setattr(sw, "_chip_alive", lambda timeout_s=90: True)
    monkeypatch.delenv("BENCH_SWEEP_SKIP_FRESH_S", raising=False)
    rc = sw.main(["--combos", "lstm:64,alexnet:64"])
    assert calls == ["lstm", "alexnet"]
    assert rc == 0


def test_chip_probe_vacuous_on_cpu_sweep(monkeypatch):
    from paddle_tpu.scripts import bench_sweep as sw
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")
    assert sw._chip_alive() is True        # no subprocess, no 90 s wait


def test_vs_baseline_resolves_per_batch_row():
    """Batch-scaling combos must compare against THEIR published
    BASELINE.md row, not the factory's bs-64 number; unpublished batches
    compare against nothing."""
    import bench
    # published scaling rows
    assert bench._resolve_baseline("alexnet", 512, 195.0) == 1629.0
    assert bench._resolve_baseline("lstm", 256, 184.0) == 414.0
    assert bench._resolve_baseline("smallnet", 512, 10.463) == 63.039
    # default batch keeps the factory's number
    assert bench._resolve_baseline("lstm", 64, 184.0) == 184.0
    assert bench._resolve_baseline("transformer", 32, None) is None
    # non-default, never published -> no comparison
    assert bench._resolve_baseline("resnet50", 1024, None) is None
    assert bench._resolve_baseline("alexnet", 1024, 195.0) is None
    # every _BASELINE_MS key is a real model at a real batch
    for (m, b) in bench._BASELINE_MS:
        assert m in bench._BENCHES and b > 0
