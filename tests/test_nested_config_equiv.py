"""The reference's own hierarchical-RNN equivalence suite, end to end:
gserver/tests/sequence_nest_rnn.conf vs sequence_rnn.conf executed UNCHANGED
through the config compiler + PyDataProvider2 shim + nested scan engine,
with outputs and gradients compared — the test
gserver/tests/test_RecurrentGradientMachine.cpp runs against the C++
machine, reproduced against ours."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.compat import parse_config
from paddle_tpu.core.sequence import (NestedSequenceBatch, SequenceBatch,
                                      pad_nested_sequences, pad_sequences)
from paddle_tpu.layers.graph import Topology, value_data

REFERENCE = os.environ.get("PADDLE_REFERENCE_DIR", "/root/reference")
GSERVER = f"{REFERENCE}/paddle/gserver/tests"

pytestmark = pytest.mark.skipif(
    not os.path.exists(f"{GSERVER}/sequence_nest_rnn.conf"),
    reason="reference checkout not present")

# the provider's fixture data (rnn_data_provider.py): two samples of
# sub-sequences of word ids + a class label
DATA = [
    [[[1, 3, 2], [4, 5, 2]], 0],
    [[[0, 2], [2, 5], [0, 1, 2]], 1],
]


def _nested_feed():
    nested = pad_nested_sequences(
        [[np.asarray(sub, np.int32) for sub in d[0]] for d in DATA])
    labels = np.asarray([[d[1]] for d in DATA], np.int32)
    return {"word": nested, "label": labels}


def _flat_feed():
    flat = pad_sequences(
        [np.concatenate([np.asarray(s, np.int32) for s in d[0]])
         for d in DATA])
    labels = np.asarray([[d[1]] for d in DATA], np.int32)
    return {"word": flat, "label": labels}


def _load(conf):
    # the configs name provider paths relative to the reference's paddle/
    # dir (the reference trainer's cwd)
    cwd = os.getcwd()
    os.chdir(f"{REFERENCE}/paddle")
    try:
        parsed = parse_config(f"{GSERVER}/{conf}", "")
    finally:
        os.chdir(cwd)
    return Topology(list(parsed.outputs))


def _map_params(nested_params, flat_params):
    """Same math, different layer names: inner_rnn_state <-> rnn_state."""
    out = dict(flat_params)
    for fk in flat_params:
        nk = fk.replace("rnn_state", "inner_rnn_state") \
            if "rnn_state" in fk else fk
        assert nk in nested_params, (fk, sorted(nested_params))
        out[fk] = nested_params[nk]
    return out


def test_nest_rnn_conf_matches_flat_conf():
    topo_n = _load("sequence_nest_rnn.conf")
    topo_f = _load("sequence_rnn.conf")
    params_n = topo_n.init(jax.random.PRNGKey(0))
    params_f = _map_params(params_n, topo_f.init(jax.random.PRNGKey(1)))

    def loss_n(p):
        out = topo_n.apply(p, _nested_feed(), mode="test")
        return jnp.mean(value_data(out))

    def loss_f(p):
        out = topo_f.apply(p, _flat_feed(), mode="test")
        return jnp.mean(value_data(out))

    ln, gn = jax.value_and_grad(loss_n)(params_n)
    lf, gf = jax.value_and_grad(loss_f)(params_f)
    np.testing.assert_allclose(float(ln), float(lf), rtol=1e-5)

    for fk in gf:
        nk = fk.replace("rnn_state", "inner_rnn_state") \
            if "rnn_state" in fk else fk
        for a, b in zip(jax.tree_util.tree_leaves(gn[nk]),
                        jax.tree_util.tree_leaves(gf[fk])):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"grad mismatch {nk} vs {fk}")


def test_nest_rnn_conf_trains_through_cli_stack():
    """The nested config trains through the SGD trainer with the provider's
    own data (define_py_data_sources2 -> PyDataProvider2 sub-sequence
    slots)."""
    from paddle_tpu.compat import config_to_runtime
    from paddle_tpu.trainer import SGD
    os.chdir(f"{REFERENCE}/paddle")  # provider paths are cwd-relative
    try:
        parsed = parse_config(f"{GSERVER}/sequence_nest_rnn.conf", "")
        cfg = config_to_runtime(parsed)
        tr = SGD(cost=cfg["cost"], update_equation=cfg["optimizer"],
                 seed=0, donate=False)
        losses = []
        tr.train(cfg["train_reader"], num_passes=8, log_period=0,
                 feeding=cfg.get("feeding"),
                 event_handler=lambda ev: losses.append(float(ev.cost))
                 if type(ev).__name__ == "EndIteration" else None)
    finally:
        os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert losses and np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_nest_rnn_multi_input_matches_flat():
    """sequence_nest_rnn_multi_input.conf vs sequence_rnn_multi_input.conf:
    two SubsequenceInputs (raw ids + pre-embedded), in-step embedding, same
    forward/grads as the flat twin."""
    topo_n = _load("sequence_nest_rnn_multi_input.conf")
    topo_f = _load("sequence_rnn_multi_input.conf")
    params_n = topo_n.init(jax.random.PRNGKey(0))
    params_f = _map_params(params_n, topo_f.init(jax.random.PRNGKey(1)))

    def loss_n(p):
        return jnp.mean(value_data(
            topo_n.apply(p, _nested_feed(), mode="test")))

    def loss_f(p):
        return jnp.mean(value_data(
            topo_f.apply(p, _flat_feed(), mode="test")))

    ln, gn = jax.value_and_grad(loss_n)(params_n)
    lf, gf = jax.value_and_grad(loss_f)(params_f)
    np.testing.assert_allclose(float(ln), float(lf), rtol=1e-5)
    for fk in gf:
        nk = fk.replace("rnn_state", "inner_rnn_state") \
            if "rnn_state" in fk else fk
        for a, b in zip(jax.tree_util.tree_leaves(gn[nk]),
                        jax.tree_util.tree_leaves(gf[fk])):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"grad mismatch {nk} vs {fk}")
