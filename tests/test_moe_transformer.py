"""MoE transformer trunk (init(moe_experts=N)): top-k-gated expert FFNs
in the causal/encoder blocks (ops/moe.py batched-einsum experts), the
load-balance aux threaded through encode -> lm_loss / loss, generation
dispatching the same mixture — and the expert-parallel sharding parity
(SURVEY §4 pattern (3): sharded must match single-device)."""

import copy

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.models import transformer

V, DM, DFF, HEADS, T, E = 48, 16, 32, 2, 12, 4

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _tokens(np_rng, b=3):
    return SequenceBatch(
        jnp.asarray(np_rng.randint(3, V, (b, T)), jnp.int32),
        jnp.full((b,), T, jnp.int32))


def _moe_params(seed=0):
    return transformer.init(jax.random.PRNGKey(seed), src_vocab=V,
                            trg_vocab=1, d_model=DM, dff=DFF,
                            enc_layers=2, dec_layers=0, max_len=T,
                            moe_experts=E)


def test_identical_experts_match_dense(np_rng):
    """A mixture whose experts are all copies of the dense FFN weights
    reproduces the dense trunk exactly (gates renormalize to 1), for the
    full-sequence logits AND the cached generation path."""
    dense = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                             trg_vocab=1, d_model=DM, dff=DFF,
                             enc_layers=2, dec_layers=0, max_len=T)
    moe = copy.deepcopy(dense)
    rng = np.random.RandomState(1)
    for blk in moe["enc"]:
        ffn = blk.pop("ffn")
        blk["moe"] = {
            "wg": jnp.asarray(rng.randn(DM, E) * 0.3, jnp.float32),
            "w1": jnp.tile(ffn["w1"][None], (E, 1, 1)),
            "w2": jnp.tile(ffn["w2"][None], (E, 1, 1)),
        }
    toks = _tokens(np.random.RandomState(2))
    l_dense = transformer.lm_logits(dense, toks, HEADS)
    l_moe = transformer.lm_logits(moe, toks, HEADS)
    np.testing.assert_allclose(np.asarray(l_moe), np.asarray(l_dense),
                               atol=2e-5)
    # aux-free loss equality
    ld = transformer.lm_loss(dense, toks, HEADS)
    lm = transformer.lm_loss(moe, toks, HEADS, moe_aux_weight=0.0)
    np.testing.assert_allclose(float(lm), float(ld), rtol=1e-5)
    # generation (prefill + cached steps) dispatches the mixture too
    prompt = np.asarray(toks.data[:, :4])
    gd = transformer.lm_generate(dense, prompt, max_len=T,
                                 num_heads=HEADS)
    gm = transformer.lm_generate(moe, prompt, max_len=T, num_heads=HEADS)
    np.testing.assert_array_equal(np.asarray(gm), np.asarray(gd))


def test_moe_lm_trains_and_router_learns(np_rng):
    from paddle_tpu import optim
    params = _moe_params()
    wg0 = np.asarray(params["enc"][0]["moe"]["wg"]).copy()
    rng = np.random.RandomState(0)
    data = (np.arange(T)[None] + rng.randint(0, 45, (8, 1))) % 45 + 3
    toks = SequenceBatch(jnp.asarray(data, jnp.int32),
                         jnp.full((8,), T, jnp.int32))
    opt = optim.Adam(learning_rate=3e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(
            lambda p: transformer.lm_loss(p, toks, HEADS))(p)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    first = None
    for _ in range(120):
        params, state, l = step(params, state)
        first = first if first is not None else float(l)
    assert float(l) < 0.5 * first, (first, float(l))
    # the router moved: the aux/CE gradients reach wg
    assert np.abs(np.asarray(params["enc"][0]["moe"]["wg"]) - wg0).max() \
        > 1e-4


def test_moe_aux_increases_loss(np_rng):
    params = _moe_params()
    toks = _tokens(np_rng)
    l0 = float(transformer.lm_loss(params, toks, HEADS,
                                   moe_aux_weight=0.0))
    l1 = float(transformer.lm_loss(params, toks, HEADS,
                                   moe_aux_weight=1.0))
    assert l1 > l0       # load-balance aux is positive


@needs_8
def test_moe_lm_expert_parallel_matches_single(np_rng):
    """lm_loss with expert weights sharded over the 'expert' mesh axis
    == unsharded (loss + grads): the MoE trunk scales over experts the
    way the dryrun's expert leg proves for the raw op."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel import MeshConfig, make_mesh
    mesh = make_mesh(MeshConfig(data=2, expert=4))
    params = _moe_params()
    toks = _tokens(np_rng, b=4)

    def lm(p):
        return transformer.lm_loss(p, toks, HEADS)

    l1, g1 = jax.jit(jax.value_and_grad(lm))(params)

    sh = transformer.moe_lm_shardings(mesh, params)
    placed = jax.device_put(params, sh)
    with mesh:
        l2, g2 = jax.jit(jax.value_and_grad(lm))(placed)
    np.testing.assert_allclose(float(l2), float(l1), rtol=2e-4)
    for a, b_ in zip(jax.tree_util.tree_leaves(g2),
                     jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=1e-4)
