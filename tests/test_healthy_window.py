"""CPU dry-run of the healthy-window playbook (VERDICT r5, Next round #1:
"zero chip-window minutes debugging the harness").

Executes `healthy_window.sh` end-to-end with HW_DRYRUN=1 — every phase
runs its real command on the CPU backend at smoke scale — and asserts
each phase left its artifact behind.  A path typo, env-plumbing break, or
rc-logging bug in the playbook is caught here, not in a five-minute chip
window.

Slow lane only (several minutes of real subprocess work): run with
`pytest -m slow tests/test_healthy_window.py`.
"""

import json
import os
import subprocess

import pytest

pytestmark = pytest.mark.slow

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_ROOT, "paddle_tpu", "scripts", "healthy_window.sh")


def test_dryrun_executes_every_phase(tmp_path):
    art = tmp_path / "window"
    env = dict(os.environ)
    env.update(HW_DRYRUN="1", JAX_PLATFORMS="cpu")
    # a dry run must be hermetic: no JAX persistent cache dir leaking in
    env.pop("BENCH_PROFILE_BASE", None)
    committed = [os.path.join(_ROOT, p)
                 for p in ("bench_cache.json", "BENCH_ANALYTIC_r06.json")]
    mtimes_before = {p: os.path.getmtime(p) for p in committed
                     if os.path.exists(p)}
    proc = subprocess.run(
        ["bash", _SCRIPT, str(art)], env=env, cwd=_ROOT,
        capture_output=True, text=True, timeout=3600)
    log = proc.stdout + proc.stderr
    assert proc.returncode == 0, log[-4000:]

    # every phase's artifact landed
    for name in ("smoke_kernels.json", "bench_sweep.json",
                 "bench_scan_baselines.json", "bench_bf16.json",
                 "bench_int8.json", "diff_cpu.npz", "diff_tpu.npz",
                 "tpu_differential_pytest.log", "nmt_scale.json",
                 "perf_report.md", "analytic.json",
                 "analytic_snapshot.json", "serving_smoke.json",
                 "serving_gen_smoke.json", "chaos_smoke.json",
                 "fleet_smoke.json", "paged_smoke.json",
                 "trace_smoke.json", "trace_chrome.json",
                 "decode_fused_smoke.json", "autoscale_smoke.json",
                 "chunked_smoke.json", "quant_smoke.json",
                 "analysis_gate.json", "spec_smoke.json",
                 "sharded_smoke.json", "spill_smoke.json",
                 "disagg_smoke.json", "quant_prefill_smoke.json",
                 "WINDOW_DONE"):
        assert (art / name).exists(), f"{name} missing; log tail:\n" \
            + log[-4000:]

    # the phases really ran (not just touched files): smoke reports every
    # kernel, the sweep reports its combos, the analytic snapshot holds
    # roofline rows
    smoke = json.loads((art / "smoke_kernels.json").read_text())
    assert smoke["value"] == int(smoke["unit"].split("/")[1]), smoke
    sweep = json.loads((art / "bench_sweep.json").read_text())
    assert set(sweep["sweep"]) == {"smallnet:8", "trainer_prefetch:8"}
    for combo, row in sweep["sweep"].items():
        assert row.get("value") is not None, (combo, row)
    snap = json.loads((art / "analytic_snapshot.json").read_text())
    assert set(snap["families"]) == {"smallnet", "trainer_prefetch",
                                     "serving", "serving_generate"}
    for fam, row in snap["families"].items():
        assert row.get("predicted_ms", 0) > 0, (fam, row)
    # the serving smoke really served: every request answered, the
    # malformed request 400'd, /metrics rendered sanely, and batching
    # happened (occupancy > 1 under the smoke's concurrent clients)
    smoke_srv = json.loads((art / "serving_smoke.json").read_text())
    assert smoke_srv["value"] == int(smoke_srv["unit"].split("/")[1]), \
        smoke_srv
    assert smoke_srv["bad_request_status"] == 400, smoke_srv
    assert smoke_srv["metrics_sane"] is True, smoke_srv
    assert smoke_srv["mean_occupancy"] > 1.0, smoke_srv
    # the generation smoke really generated: every staggered request
    # answered, the stream matched the plain response, the EOS probe
    # finished early, and the TTFT/slot metrics rendered
    smoke_gen = json.loads((art / "serving_gen_smoke.json").read_text())
    assert smoke_gen["value"] == int(smoke_gen["unit"].split("/")[1]), \
        smoke_gen
    assert smoke_gen["stream_ok"] is True, smoke_gen
    assert smoke_gen["eos_early_finish"] is True, smoke_gen
    assert smoke_gen["metrics_sane"] is True, smoke_gen
    assert smoke_gen["gen_tokens_total"] > 0, smoke_gen
    assert smoke_gen["readyz"] == "ready", smoke_gen
    # the chaos smoke really exercised the resilience layer: the injected
    # decode-step fault fired, recovered streams stayed bit-identical,
    # and the kill-9'd trainer resumed to bit-identical params
    chaos = json.loads((art / "chaos_smoke.json").read_text())
    assert chaos["value"] == int(chaos["unit"].split("/")[1]), chaos
    assert chaos["faults_fired"] >= 1, chaos
    assert chaos["bit_identical"] is True, chaos
    assert chaos["victim_killed"] is True, chaos
    assert chaos["resume_bit_identical"] is True, chaos
    # the fleet smoke really failed over: 2 replica subprocesses behind
    # the router, one kill -9'd mid-stream, every stream bit-identical
    # via the cross-replica continuation, and the supervisor restarted
    # the victim to readiness
    fleet = json.loads((art / "fleet_smoke.json").read_text())
    assert fleet["value"] == int(fleet["unit"].split("/")[1]), fleet
    assert fleet["bit_identical"] is True, fleet
    assert fleet["victim_killed"] is True, fleet
    assert fleet["midstream_failovers"] >= 1, fleet
    assert fleet["restarted_ready"] is True, fleet
    assert fleet["victim_restarts"] >= 1, fleet
    # the paged smoke really shared: the exact-duplicate and divergent
    # clients hit the leader's prefix chains, the duplicate's seat
    # copy-on-write forked the shared tail block, and every stream came
    # back bit-identical to the slab-layout twin
    paged = json.loads((art / "paged_smoke.json").read_text())
    assert paged["value"] == int(paged["unit"].split("/")[1]), paged
    assert paged["bit_identical"] is True, paged
    assert paged["prefix_cache_hits"] >= 2, paged
    assert paged["cow_forks"] >= 1, paged
    assert paged["metrics_sane"] is True, paged
    # the trace smoke really stitched: one trace_id crossed the router,
    # the kill -9'd replica, and the failover continuation on the
    # survivor, and the merged Chrome trace-event dump parsed with all
    # three process names
    tsm = json.loads((art / "trace_smoke.json").read_text())
    assert tsm["value"] == int(tsm["unit"].split("/")[1]), tsm
    assert tsm["victim_killed"] is True, tsm
    assert tsm["stitched"] is True, tsm
    assert tsm["chrome_parses"] is True, tsm
    assert tsm["chrome_processes"] >= 3, tsm
    chrome = json.loads((art / "trace_chrome.json").read_text())
    assert chrome["traceEvents"], "empty Chrome trace dump"
    # the decode-fused smoke really fused: both kernels (slab + paged)
    # compiled into the demo engines' steps, every staggered stream
    # bit-identical to the reference-path twin, zero retraces
    fused = json.loads((art / "decode_fused_smoke.json").read_text())
    assert fused["value"] == int(fused["unit"].split("/")[1]), fused
    for layout in ("slab", "paged"):
        assert fused[f"{layout}_kernel_engaged"] is True, fused
        assert fused[f"{layout}_bit_identical"] is True, fused
        assert fused[f"{layout}_retraces"] == 0, fused
    # the autoscale smoke really closed the loop: the seeded spike
    # breached the TTFT target, the control loop scaled 1 -> 2 to
    # readiness, the post-scale drive sat back under target, and the
    # fleet scaled back in — with zero failed requests
    asc = json.loads((art / "autoscale_smoke.json").read_text())
    assert asc["value"] == int(asc["unit"].split("/")[1]), asc
    assert asc["scaled_out"] is True, asc
    assert asc["scaled_in"] is True, asc
    assert asc["recovered_under_target"] is True, asc
    assert asc["failed"] == 0 and asc["completed"] > 0, asc
    assert asc["decisions_out"] >= 1 and asc["decisions_in"] >= 1, asc
    # the chunked-prefill smoke really unified: the long prompt chunked
    # through the shared decode step (>= ceil(15/(K-1)) chunks), the
    # in-flight stream kept emitting while it ingested, and both streams
    # came back bit-identical to the legacy-ladder twin
    chk = json.loads((art / "chunked_smoke.json").read_text())
    assert chk["value"] == int(chk["unit"].split("/")[1]), chk
    assert chk["bit_identical"] is True, chk
    assert chk["interleaved_tokens"] >= 1, chk
    assert chk["prefill_chunks_total"] >= 2, chk
    assert chk["prefill_chunk_lanes_total"] >= 15, chk
    # the quant smoke really quantized: every int8-KV stream inside the
    # committed quality budget vs the fp32 twin, the int8-KV+weights
    # engine token-exact vs the quantized lm_generate oracle, and the
    # int8 pool holding exactly DOUBLE the twin's blocks at equal bytes
    qsm = json.loads((art / "quant_smoke.json").read_text())
    assert qsm["value"] == int(qsm["unit"].split("/")[1]), qsm
    assert qsm["within_budget"] == qsm["value"], qsm
    assert qsm["full_quant_oracle_exact"] == qsm["value"], qsm
    assert qsm["kv_blocks_doubled"] is True, qsm
    assert qsm["kv_blocks_total"] == 2 * qsm["f32_twin_blocks"], qsm
    assert qsm["kv_dtype"] == "int8" and qsm["metrics_sane"] is True, qsm
    # the static invariant gate really gated: all three passes ran
    # against the committed baseline with ZERO new findings (a new
    # finding exits nonzero and withholds WINDOW_DONE — asserted above
    # via rc==0 + the file's existence)
    gate = json.loads((art / "analysis_gate.json").read_text())
    assert gate["check"] == "all", gate
    assert gate["new"] == 0, gate
    assert gate["roots"], "analysis gate ran with no jit roots"
    assert gate["stale_baseline_keys"] == [], gate
    # the speculative smoke really speculated: every staggered stream
    # bit-identical to the non-spec twin, draft lanes actually scored
    # (acceptance evidence on /metrics), every verify step netting
    # >= 1 token, and both engines at 1 warm-up trace / 0 retraces
    spc = json.loads((art / "spec_smoke.json").read_text())
    assert spc["value"] == int(spc["unit"].split("/")[1]), spc
    assert spc["bit_identical"] is True, spc
    assert spc["drafted_tokens_total"] > 0, spc
    assert spc["spec_tokens_per_step"] >= 1.0, spc
    assert spc["no_retrace"] is True, spc
    assert spc["metrics_sane"] is True, spc
    # the sharded smoke really sharded: a 2-device mesh actually backed
    # the step (the probe re-execs itself with the forcing flag on a
    # single-device machine), every staggered stream bit-identical to
    # the single-chip twin, the mesh_shards gauge on /metrics, and
    # exactly one warm-up trace per jitted function
    shd = json.loads((art / "sharded_smoke.json").read_text())
    assert shd["value"] == int(shd["unit"].split("/")[1]), shd
    assert shd["mesh_shards"] == 2, shd
    assert shd["devices"] >= 2, shd
    assert shd["bit_identical"] is True, shd
    assert shd["no_retrace"] is True, shd
    assert shd["metrics_sane"] is True, shd
    # the spill smoke really restored: churn evicted (and spilled) the
    # shared chain, the returning prompt restore-hit from the host tier
    # and seated by reference — ZERO prefill chunk lanes for the return
    # visit — bit-identical to the tier-less twin's recompute, with the
    # spill/restore counters on /metrics and one warm-up trace
    spl = json.loads((art / "spill_smoke.json").read_text())
    assert spl["kv_restore_hits"] >= 1, spl
    assert spl["kv_spill_blocks"] > 0, spl
    assert spl["chunk_lanes_return_visit"] == 0, spl
    assert spl["bit_identical"] is True, spl
    assert spl["step_traces"] == 1, spl
    assert spl["metrics_sane"] is True, spl
    # the disagg smoke really handed off: prompts prefilled on one pool,
    # the KV chain crossed the socket at first token and the decode pool
    # seated it (received counters on both replicas AND the router), a
    # sub-crossover prompt took the analytic recompute fallback, kill -9
    # of the prefill replica fell back to recompute — every stream
    # bit-identical to the single-replica oracle
    dsg = json.loads((art / "disagg_smoke.json").read_text())
    assert dsg["value"] == int(dsg["unit"].split("/")[1]), dsg
    assert dsg["disagg_active"] is True, dsg
    assert dsg["bit_identical"] is True, dsg
    assert dsg["prefill_sent"] >= 3, dsg
    assert dsg["decode_received"] >= 3, dsg
    assert dsg["decode_handoff_bytes"] > 0, dsg
    assert dsg["router_handoffs"]["received"] >= 3, dsg
    assert dsg["router_handoffs"]["fallback"] >= 1, dsg
    assert dsg["kill_fallback_outcome"]["outcome"] == "fallback", dsg
    assert dsg["post_kill_stream_ok"] is True, dsg
    # the quant-prefill smoke really went low-precision end to end:
    # every stream of the int8 flash prefill inside the committed logit
    # budget vs the fp32 twin, the kernel-fed int8 cache matching the
    # sequential-step round trip, and the int8 weight-streaming trainer
    # tracking its f32 twin within the committed training budget with a
    # non-empty int8 tree
    qpf = json.loads((art / "quant_prefill_smoke.json").read_text())
    assert qpf["value"] == int(qpf["unit"].split("/")[1]), qpf
    assert qpf["max_logit_err"] <= qpf["logit_err_budget"], qpf
    assert qpf["cache_matches_sequential"] is True, qpf
    assert qpf["trainer_loss_gap_max"] is not None, qpf
    assert qpf["trainer_loss_gap_max"] <= qpf["train_loss_budget"], qpf
    assert qpf["quant_tree_leaves"] >= 2, qpf
    assert "errors" not in qpf, qpf
    assert "dryrun=1" in (art / "WINDOW_DONE").read_text()

    # a dry run must never rewrite the committed perf artifacts (cpu rows
    # would shadow real measurements) — guarded by BENCH_NO_CACHE and the
    # dryrun-specific --out path above
    for p, before in mtimes_before.items():
        assert os.path.getmtime(p) == before, (
            f"dry run rewrote committed perf artifact {p}")
