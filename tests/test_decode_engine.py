"""Continuous-batching generation serving (serving/decode_engine.py).

The correctness bar mirrors test_serving.py's: a request served through
the full stack — queue, prefill ladder, slot admission, the shared slab
step, eviction — must return EXACTLY the tokens the single-request
oracle (``models/transformer.lm_generate``, greedy) produces for that
prompt.  Every linear layer in the decode path is batched over the
leading slot axis, so a row's numerics do not depend on what the other
slots hold; greedy outputs are therefore bit-identical token for token,
across staggered admissions, mixed prompt lengths, and slot reuse after
eviction.

Trace discipline: the slab step traces exactly ONCE at warm-up and never
again across admission/eviction churn (the shared
``paddle_tpu.testing.trace`` assertion, same as ``InferenceEngine`` and
``SGD.precompile``).

Fault injection covers the GenerationBatcher's admission-control paths
(invalid prompt before the queue, overload, deadline), batch-failure
isolation (a step failure fails only the in-flight requests; the engine
resets and keeps serving), and both drain semantics.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.serving import (BatchExecutionError, DeadlineExceededError,
                                GenerationBatcher, InvalidRequestError,
                                OverloadedError, ServingMetrics,
                                ShutdownError, make_server)
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.testing import assert_no_retrace

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BUCKETS = 48, 4, (8, 16)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def engine(params):
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=BUCKETS,
                        name="test_lm")


def _prompt(rng, n=None):
    return rng.randint(1, VOCAB, n or rng.randint(3, BUCKETS[-1] + 1)
                       ).astype(np.int32)


def _oracle(params, engine, prompt, n_tokens, eos_id=None):
    """Single-request greedy lm_generate, run at the SAME prefill bucket
    and cache width the engine used (pad value is irrelevant — proven by
    lm_generate's own ragged-prompt contract)."""
    bucket = engine.prefill_bucket_for(prompt.size)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :prompt.size] = prompt
    ids = np.asarray(transformer.lm_generate(
        params, padded, max_len=engine.max_len, num_heads=HEADS,
        eos_id=eos_id, prompt_lengths=np.asarray([prompt.size])))
    return ids[0, prompt.size:prompt.size + n_tokens].tolist()


# ------------------------------------------------------------ parity


def test_staggered_admissions_bit_identical_to_lm_generate(params, engine):
    """The acceptance drive: more requests than slots, mixed prompt
    lengths (both ladder buckets), mixed max_tokens, submitted in
    staggered waves so admissions land mid-decode and every slot is
    reused after eviction — each request's greedy tokens must equal the
    single-request oracle exactly."""
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine, default_max_tokens=8)
    rng = np.random.RandomState(1)
    cases = [(_prompt(rng), int(rng.randint(2, 13))) for _ in range(12)]
    futs = []
    for i, (prompt, n) in enumerate(cases):
        futs.append(bat.submit(prompt, max_tokens=n))
        if i % 3 == 2:
            time.sleep(0.01)        # let decode start; later admissions
            #                         churn slots mid-flight
    results = [f.result(120) for f in futs]
    bat.close()
    for (prompt, n), res in zip(cases, results):
        assert res["finish_reason"] == "length"
        assert len(res["tokens"]) == n
        assert res["tokens"] == _oracle(params, engine, prompt, n), \
            f"prompt len {prompt.size}, n {n}"
    # 12 requests over 4 slots: every slot was reused after eviction
    snap = engine.metrics.snapshot()
    assert snap["evictions"]["length"] == 12
    assert engine.free_slots == SLOTS
    assert snap["mean_slot_occupancy"] > 1.0, snap    # real co-residency
    assert snap["ttft_ms"]["p50"] > 0
    assert snap["tpot_ms"]["p50"] > 0


def test_rope_trunk_bit_identical_to_lm_generate():
    """The per-row rope path (positions[:, None] through _rope_flat into
    rope()'s [B, T] branch) is the subtlest slab-step code: pin the same
    bit-identity guarantee on a rope trunk (no learned table at all)."""
    rope_params = transformer.init(jax.random.PRNGKey(1), src_vocab=VOCAB,
                                   trg_vocab=1, d_model=D_MODEL,
                                   num_heads=HEADS, dff=64,
                                   enc_layers=LAYERS, dec_layers=0,
                                   max_len=MAX_LEN, pos_type="rope")
    eng = DecodeEngine(rope_params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       pos_type="rope", name="rope_lm")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(10)
    cases = [(_prompt(rng), int(rng.randint(2, 9))) for _ in range(6)]
    futs = [bat.submit(p, max_tokens=n) for p, n in cases]
    results = [f.result(120) for f in futs]
    bat.close()
    for (prompt, n), res in zip(cases, results):
        bucket = eng.prefill_bucket_for(prompt.size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.size] = prompt
        ids = np.asarray(transformer.lm_generate(
            rope_params, padded, max_len=eng.max_len, num_heads=HEADS,
            prompt_lengths=np.asarray([prompt.size]), pos_type="rope"))
        assert res["tokens"] == \
            ids[0, prompt.size:prompt.size + n].tolist()


def test_eos_early_finish_matches_oracle(params, engine):
    """A generated stop token finishes the request early (reason "eos",
    eos included), exactly where the oracle run with the same eos_id
    stops."""
    bat = GenerationBatcher(engine)
    rng = np.random.RandomState(2)
    prompt = _prompt(rng, 6)
    free = bat.submit(prompt, max_tokens=10).result(60)["tokens"]
    eos = free[4]
    res = bat.submit(prompt, max_tokens=10, eos_id=eos).result(60)
    bat.close()
    assert res["finish_reason"] == "eos"
    assert res["tokens"][-1] == eos
    k = free.index(eos) + 1             # first occurrence stops the run
    assert res["tokens"] == free[:k]
    assert res["tokens"] == _oracle(params, engine, prompt, k, eos_id=eos)


def test_streaming_on_token_callback(params, engine):
    """on_token fires once per emitted token, in order, from the engine
    thread — and a crashing callback is dropped, never fatal."""
    bat = GenerationBatcher(engine)
    rng = np.random.RandomState(3)
    prompt = _prompt(rng, 5)
    seen = []
    res = bat.submit(prompt, max_tokens=7,
                     on_token=seen.append).result(60)
    assert seen == res["tokens"]

    def boom(tok):
        raise RuntimeError("client callback bug")
    res2 = bat.submit(prompt, max_tokens=7, on_token=boom).result(60)
    assert res2["tokens"] == res["tokens"]      # generation unharmed
    bat.close()


# ------------------------------------------------------------ trace


def test_one_warmup_trace_zero_retraces_across_churn(params):
    """The trace-count discipline, end to end: warm-up traces the slab
    step exactly once; an admission/eviction churn run (staggered
    requests, slot reuse, mixed buckets) retraces NOTHING — scheduling is
    host-side by construction."""
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       name="trace_lm")
    assert eng.step_trace_count == 1           # exactly one warm-up trace
    rng = np.random.RandomState(4)
    with assert_no_retrace(lambda: eng.step_trace_count,
                           "decode churn over the warm slab step"):
        bat = GenerationBatcher(eng, default_max_tokens=6)
        futs = [bat.submit(_prompt(rng), max_tokens=int(rng.randint(2, 9)))
                for _ in range(10)]
        for f in futs:
            f.result(120)
        bat.close()
    # prefill ladder discipline: one trace per (length bucket, batch
    # bucket) executable, all paid at warm-up
    for b, peng in eng._prefill_engines.items():
        assert peng.trace_count == len(peng.buckets), (b, peng.trace_count)


# ------------------------------------------------------------ admission


def test_validate_request_rejects_before_queue(engine):
    bat = GenerationBatcher(engine)
    ok = np.arange(1, 5, dtype=np.int32)
    for bad, kw in [
        (np.zeros((2, 3), np.int32), {}),            # 2-D
        (np.zeros((0,), np.int32), {}),              # empty
        (np.zeros((BUCKETS[-1] + 1,), np.int32), {}),  # past the ladder
        (np.zeros((3,), np.float32), {}),            # not ids
        (np.full((3,), VOCAB, np.int32), {}),        # out of vocab
        (ok, {"max_tokens": 0}),                     # no emission budget
        (ok, {"max_tokens": MAX_LEN}),               # overflows the slab
    ]:
        with pytest.raises(InvalidRequestError):
            bat.submit(bad, **kw)
    res = bat.submit(ok, max_tokens=3).result(60)    # still healthy
    assert len(res["tokens"]) == 3
    bat.close()


def _stall_engine(engine, stall_s):
    """Make each slab step slow — deterministic queue buildup."""
    orig = engine.step

    def slow():
        time.sleep(stall_s)
        return orig()
    engine.step = slow
    return orig


def test_overload_deadline_and_metrics(engine):
    engine.metrics = ServingMetrics()
    orig = _stall_engine(engine, 0.1)
    try:
        bat = GenerationBatcher(engine, queue_size=2,
                                default_max_tokens=6)
        rng = np.random.RandomState(5)
        first = bat.submit(_prompt(rng, 4))     # admitted immediately
        time.sleep(0.05)                        # loop now inside a
        #                                         stalled step: the next
        #                                         submits queue up
        q1 = bat.submit(_prompt(rng, 4), max_tokens=2)
        dead = bat.submit(_prompt(rng, 4), deadline_ms=5)
        with pytest.raises(OverloadedError):
            bat.submit(_prompt(rng, 4))         # queue_size=2 exceeded
        with pytest.raises(DeadlineExceededError):
            dead.result(60)
        assert len(q1.result(120)["tokens"]) == 2
        assert len(first.result(120)["tokens"]) == 6
        snap = engine.metrics.snapshot()
        assert snap["rejected"]["overload"] == 1
        assert snap["rejected"]["deadline"] == 1
        bat.close()
    finally:
        engine.step = orig


# ------------------------------------------------------------ faults


def test_step_failure_isolated_and_engine_recovers(params, engine):
    """A decode-step failure fails exactly the in-flight requests with
    BatchExecutionError, the engine resets, and the next request serves
    with unchanged numerics."""
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine, default_max_tokens=30)
    rng = np.random.RandomState(6)
    prompt = _prompt(rng, 5)
    orig = _stall_engine(engine, 0.05)  # keep the victim in flight long
    #                                     enough to inject deterministically

    def boom():
        raise RuntimeError("injected step failure")
    victim = bat.submit(prompt)
    time.sleep(0.1)                     # it reaches a slot, mid-decode
    engine.step = boom
    with pytest.raises(BatchExecutionError):
        victim.result(60)
    engine.step = orig
    res = bat.submit(prompt, max_tokens=6).result(60)
    assert res["tokens"] == _oracle(params, engine, prompt, 6)
    snap = engine.metrics.snapshot()
    assert snap["evictions"]["error"] >= 1
    assert snap["errors_total"] >= 1
    assert engine.free_slots == SLOTS
    bat.close()


def test_prefill_failure_isolated(engine):
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine)
    orig = engine.prefill

    def boom(prompts, lengths):
        raise RuntimeError("injected prefill failure")
    engine.prefill = boom
    try:
        f = bat.submit(np.arange(1, 5, dtype=np.int32), max_tokens=3)
        with pytest.raises(BatchExecutionError):
            f.result(60)
    finally:
        engine.prefill = orig
    ok = bat.submit(np.arange(1, 5, dtype=np.int32), max_tokens=3)
    assert len(ok.result(60)["tokens"]) == 3
    bat.close()


def test_abandon_reclaims_slot_midflight(engine):
    """A disconnected caller's request stops burning decode steps: the
    slot is evicted at the next token boundary instead of running to
    max_tokens, and co-resident requests are untouched."""
    engine.metrics = ServingMetrics()
    orig = _stall_engine(engine, 0.03)
    try:
        bat = GenerationBatcher(engine, default_max_tokens=40)
        rng = np.random.RandomState(12)
        victim = bat.submit(_prompt(rng, 4))
        survivor = bat.submit(_prompt(rng, 4), max_tokens=8)
        time.sleep(0.1)             # both slotted, mid-decode
        bat.abandon(victim)
        assert len(survivor.result(120)["tokens"]) == 8
        deadline = time.time() + 10
        while engine.free_slots < SLOTS and time.time() < deadline:
            time.sleep(0.01)
        assert engine.free_slots == SLOTS   # reclaimed well before 40 toks
        assert engine.metrics.snapshot()["evictions"]["abandoned"] == 1
        bat.close()
    finally:
        engine.step = orig


# ------------------------------------------------------------ drain


def test_drain_finishes_queued_and_inflight(engine):
    orig = _stall_engine(engine, 0.02)
    try:
        bat = GenerationBatcher(engine, default_max_tokens=6)
        rng = np.random.RandomState(7)
        futs = [bat.submit(_prompt(rng, 4)) for _ in range(8)]
        t = threading.Thread(target=bat.close, kwargs={"drain": True})
        t.start()
        time.sleep(0.01)
        with pytest.raises(ShutdownError):
            bat.submit(_prompt(rng, 4))     # draining: no new admissions
        t.join(120)
        for f in futs:
            assert len(f.result(0)["tokens"]) == 6  # all completed
        assert engine.free_slots == SLOTS
    finally:
        engine.step = orig


@pytest.mark.parametrize("drain", [True, False])
def test_close_during_inflight_prefill_resolves_submitter(engine, drain):
    """The batcher-close-during-in-flight-prefill race: close() while a
    prefill future is outstanding must RESOLVE the submitter (result on
    drain=True, ShutdownError on drain=False) — never strand it.  The
    worker is provably inside the prefill when close() lands."""
    orig = engine.prefill
    inside = threading.Event()

    def slow(prompts, lengths):
        inside.set()
        time.sleep(0.3)
        return orig(prompts, lengths)
    engine.prefill = slow
    try:
        bat = GenerationBatcher(engine, default_max_tokens=4)
        rng = np.random.RandomState(13)
        fut = bat.submit(rng.randint(1, VOCAB, 4).astype(np.int32))
        assert inside.wait(10)          # worker is mid-prefill NOW
        closer = threading.Thread(target=bat.close,
                                  kwargs={"drain": drain})
        closer.start()
        if drain:
            assert len(fut.result(30)["tokens"]) == 4
        else:
            with pytest.raises((ShutdownError, BatchExecutionError)):
                fut.result(30)          # resolved, not stranded
        closer.join(30)
        assert not closer.is_alive(), "close() wedged on the prefill"
        assert engine.free_slots == SLOTS
    finally:
        engine.prefill = orig


def test_close_without_drain_fails_inflight_and_queued(engine):
    orig = _stall_engine(engine, 0.1)
    try:
        bat = GenerationBatcher(engine, default_max_tokens=40)
        rng = np.random.RandomState(8)
        futs = [bat.submit(_prompt(rng, 4)) for _ in range(6)]
        time.sleep(0.05)                # some in slots, some queued
        bat.close(drain=False)
        failed = 0
        for f in futs:
            try:
                f.result(30)
            except ShutdownError:
                failed += 1
        assert failed == 6
        assert engine.free_slots == SLOTS       # slots reclaimed
    finally:
        engine.step = orig


# ------------------------------------------------------------ HTTP


def test_http_generate_plain_stream_and_faults(params, engine):
    """/v1/generate end to end on a generation-only server: plain JSON,
    chunked NDJSON streaming (identical ids — greedy is deterministic),
    and the error mapping."""
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine, default_max_tokens=6)
    httpd = make_server(None, port=0, gen_batcher=bat)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.port}"
    try:
        prompt = np.random.RandomState(9).randint(1, VOCAB, 5).tolist()

        def post(body, path="/v1/generate"):
            req = urllib.request.Request(
                f"{base}{path}", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, r.read()

        status, raw = post({"prompt": prompt, "max_tokens": 6})
        plain = json.loads(raw)
        assert status == 200 and plain["finish_reason"] == "length"
        assert plain["tokens"] == _oracle(params, engine,
                                          np.asarray(prompt, np.int32), 6)
        assert plain["ttft_ms"] >= 0

        _, raw = post({"prompt": prompt, "max_tokens": 6, "stream": True})
        lines = [json.loads(ln) for ln in raw.decode().splitlines() if ln]
        assert [ln["token"] for ln in lines if "token" in ln] \
            == plain["tokens"]
        assert lines[-1]["done"] and lines[-1]["tokens"] == plain["tokens"]

        def expect(code, body, path="/v1/generate"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                post(body, path=path)
            assert ei.value.code == code
            return json.loads(ei.value.read())

        assert "error" in expect(400, {"noprompt": 1})
        assert "error" in expect(400, {"prompt": []})
        assert "error" in expect(400, {"prompt": ["a", "b"]})
        assert "error" in expect(400, {"prompt": [2 ** 80]})  # > int64
        assert "error" in expect(400, {"prompt": prompt,
                                       "max_tokens": MAX_LEN + 9})
        assert "error" in expect(400, {"prompt": prompt,
                                       "deadline_ms": -1})
        # generation-only server: /v1/infer names the absent model
        assert "error" in expect(404, {"feed": {}}, path="/v1/infer")
        # the engine survived every fault
        status, raw = post({"prompt": prompt, "max_tokens": 3})
        assert status == 200 and len(json.loads(raw)["tokens"]) == 3

        # /metrics surfaces the generation section
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert "gen_tokens_total" in text
        assert 'ttft_seconds{quantile="0.50"}' in text
        assert 'slot_evictions_total{reason="length"}' in text
    finally:
        httpd.shutdown()
        bat.close()


# ------------------------------------------------------------ load


@pytest.mark.slow
def test_generation_load_sweep_continuous_beats_whole_batch():
    """The bench acceptance property, asserted: under the serving-shaped
    short/long mix at 8 closed-loop clients, continuous batching
    out-throughputs the sequential whole-batch policy (same compiled
    step, same prefill ladder) with a lower p99 TTFT, and really packs
    the slab (occupancy > 1)."""
    import importlib
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    bench = importlib.import_module("bench")
    built = bench.bench_serving_generate(slots=8, n_requests=48)
    extras = built[4]
    assert extras["mean_slot_occupancy"] > 1.0, extras
    # the committed bench shows ~2.6x; assert with slack for loaded CI
    assert extras["continuous_tokens_per_s"] \
        > 1.5 * extras["gang_tokens_per_s"], extras
    assert extras["continuous_ttft_p99_ms"] \
        < extras["gang_ttft_p99_ms"], extras
    # the analytic hook lowers without executing
    assert extras["lower"]() is not None


@pytest.mark.slow
def test_generation_smoke_subprocess():
    """`python -m paddle_tpu.serving --smoke-generate` — the
    healthy_window.sh phase-8 command — passes end to end in a fresh
    process."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.serving", "--smoke-generate"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["value"] == int(out["unit"].split("/")[1])
    assert out["eos_early_finish"] is True
    assert out["stream_ok"] is True
    assert out["metrics_sane"] is True
