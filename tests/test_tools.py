"""User tooling (reference python/paddle/utils/): log curve plotting, model
diagram emission, torch parameter import."""

import os

import numpy as np
import pytest
import jax

import paddle_tpu.layers as L
from paddle_tpu.layers.graph import Topology, reset_names


def test_plotcurve_parses_and_writes(tmp_path):
    from paddle_tpu.utils.tools import plotcurve
    log = [
        "I 0729 paddle_tpu] Pass 0 done, mean cost 0.83612 Eval: err=0.5\n",
        "I 0729 paddle_tpu] Pass 1 done, mean cost 0.51 Eval: err=0.25\n",
        "I 0729 paddle_tpu] Pass 2 done, mean cost 0.20 Eval: err=0.125\n",
    ]
    out = tmp_path / "curve.png"
    data = plotcurve.plot_curves(log, str(out), keys=("cost", "err"))
    assert out.exists() and out.stat().st_size > 0
    assert data["cost"] == [(0, 0.83612), (1, 0.51), (2, 0.20)]
    assert data["err"] == [(0, 0.5), (1, 0.25), (2, 0.125)]


def test_make_diagram_dot(tmp_path):
    from paddle_tpu.utils.tools import make_diagram, topology_dot
    reset_names()
    x = L.data_layer("x", size=4)
    out = L.fc_layer(x, size=2, act="softmax", name="out")
    dot = topology_dot(out)
    assert '"x" -> "out"' in dot and "digraph" in dot
    p = make_diagram(out, str(tmp_path / "m.dot"))
    assert open(p).read().startswith("digraph")


def test_torch_import_positional_and_mapped():
    torch = pytest.importorskip("torch")
    from paddle_tpu.utils.tools import from_torch_state_dict
    reset_names()
    x = L.data_layer("x", size=4)
    out = L.fc_layer(x, size=3, act=None, name="fc")
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))

    lin = torch.nn.Linear(4, 3)
    sd = lin.state_dict()            # weight [3,4], bias [3]
    # positional: [w, b] order matches our {'fc': {'w', 'b'}} leaves
    got = from_torch_state_dict(params, sd)
    np.testing.assert_allclose(np.asarray(got["fc"]["w0"]),
                               sd["weight"].numpy().T, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got["fc"]["b"]),
                               sd["bias"].numpy(), rtol=1e-6)

    got2 = from_torch_state_dict(params, sd,
                                 mapping={"fc/w0": "weight", "fc/b": "bias"})
    np.testing.assert_allclose(np.asarray(got2["fc"]["w0"]),
                               sd["weight"].numpy().T, rtol=1e-6)

    # model still runs with imported weights
    val = topo.apply(got, {"x": np.ones((2, 4), np.float32)}, mode="test")
    ref = lin(torch.ones(2, 4)).detach().numpy()
    np.testing.assert_allclose(np.asarray(val), ref, rtol=1e-5, atol=1e-6)


def test_preprocess_img_roundtrip(tmp_path):
    PIL = pytest.importorskip("PIL")
    from PIL import Image
    from paddle_tpu.utils.tools import preprocess_img
    from paddle_tpu import native
    if not native.is_available():
        pytest.skip("native runtime not built")
    src = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (src / cls).mkdir(parents=True)
        for i in range(4):
            arr = (np.random.RandomState(i).rand(20, 30, 3) * 255
                   ).astype(np.uint8)
            Image.fromarray(arr).save(src / cls / f"{i}.png")
    out = tmp_path / "rec"
    counts, mean = preprocess_img.preprocess(str(src), str(out), size=16,
                                             test_ratio=0.25, seed=0)
    assert counts["train"] + counts["test"] == 8
    rows = list(preprocess_img.record_reader(
        str(out / "train.rec"), str(out / "meta.npz"))())
    assert len(rows) == counts["train"]
    x, y = rows[0]
    assert x.shape == (16 * 16 * 3,) and y in (0, 1)
    assert np.isfinite(x).all()


def test_v2_ploter(tmp_path, monkeypatch):
    """paddle.v2.plot.Ploter (reference v2/plot/plot.py): append named
    curves, plot to a file headless, DISABLE_PLOT short-circuits."""
    from paddle_tpu.v2.plot import Ploter
    p = Ploter("train_cost", "test_cost")
    for i in range(5):
        p.append("train_cost", i, 1.0 / (i + 1))
        p.append("test_cost", i, 1.2 / (i + 1))
    out = tmp_path / "curves.png"
    p.plot(path=str(out))
    assert out.exists() and out.stat().st_size > 0
    monkeypatch.setenv("DISABLE_PLOT", "True")
    p.plot()          # prints instead of plotting; no error
    p.reset()
    assert not p.__plot_data__["train_cost"].step


def test_xprof_report_attributes_categories(tmp_path, monkeypatch):
    """End-to-end: capture a real jax.profiler trace of a jitted matmul
    loop, then the report must attribute the bulk to matmul_conv and
    expose busy/idle per track (the pre-staged MFU analysis loop)."""
    import json as _json
    import jax
    import jax.numpy as jnp
    from paddle_tpu.scripts import xprof_report

    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((256, 256))
    f(x).block_until_ready()
    prof = str(tmp_path / "prof")
    jax.profiler.start_trace(prof)
    for _ in range(4):
        f(x).block_until_ready()
    jax.profiler.stop_trace()

    runs = xprof_report.find_runs(prof)
    assert len(runs) == 1
    rep = xprof_report.report_run(runs[0])
    assert rep["tracks"], "no device/host tracks found"
    track = next(iter(rep["tracks"].values()))
    assert track["wall_us"] > 0 and 0 <= track["idle_pct"] <= 100
    cats = track["by_category_us"]
    assert cats.get("matmul_conv", 0) > 0
    assert cats["matmul_conv"] >= max(cats.values()) * 0.5
    # text + json renderers both work
    assert "matmul_conv" in xprof_report.render(rep)
    rc = xprof_report.main([prof, "--json"])
    assert rc == 0
    # --write: both artifacts in one parse
    rc = xprof_report.main([prof, "--write", str(tmp_path / "rep")])
    assert rc == 0
    assert (tmp_path / "rep.json").exists()
    assert "matmul_conv" in (tmp_path / "rep.txt").read_text()
    # categorization traps fixed by review: convert is NOT MXU time,
    # custom-call (Pallas kernels) gets its own bucket
    assert xprof_report.categorize("convert.5") == "fusion_elementwise"
    assert xprof_report.categorize("custom-call.7") == "custom_kernel"
    assert xprof_report.categorize("convolution.3") == "matmul_conv"
    assert xprof_report.categorize("while.2") == "scan_control"

    # BENCH_PROFILE_BASE plumbing: per-combo dir derived from model/batch
    monkeypatch.setenv("BENCH_PROFILE_BASE", str(tmp_path / "base"))
    from paddle_tpu.scripts import bench_sweep
    captured = {}

    class FakeProc:
        returncode = 0
        stdout = '{"value": 1.0}'
        stderr = ""

    monkeypatch.setattr(bench_sweep.subprocess, "run",
                        lambda cmd, env=None, **kw: (
                            captured.__setitem__("env", env) or FakeProc()))
    bench_sweep.run_combo("lstm", 64, None, 60)
    assert captured["env"]["BENCH_PROFILE_DIR"].endswith("lstm_bs64")


def test_ref_params_roundtrip(tmp_path):
    """Reference binary Parameter format (paraconvert.py:33-55 spec):
    write -> read identity, binary<->text round trip, 16-byte header."""
    import struct
    from paddle_tpu.utils.tools import ref_params
    rng = np.random.RandomState(0)
    table = rng.randn(7, 5).astype(np.float32)
    b = tmp_path / "emb.bin"
    ref_params.write_param(str(b), table)
    # header layout is the documented 16 bytes: version, float_size, count
    raw = b.read_bytes()
    version, fsize, count = struct.unpack("<iiq", raw[:16])
    assert (version, fsize, count) == (0, 4, 35)
    np.testing.assert_array_equal(
        ref_params.read_param(str(b)).reshape(7, 5), table)
    # binary -> text -> binary survives (text carries 7 decimals)
    t = tmp_path / "emb.txt"
    b2 = tmp_path / "emb2.bin"
    assert ref_params.binary2text(str(b), str(t), dim=5) == 7
    assert t.read_text().splitlines()[0] == "0,4,35"
    ref_params.text2binary(str(t), str(b2))
    np.testing.assert_allclose(ref_params.read_param(str(b2)),
                               table.reshape(-1), atol=1e-6)


def test_ref_params_f64_and_errors(tmp_path):
    import struct
    from paddle_tpu.utils.tools import ref_params
    # f64 body (float_size=8) reads too
    vals = np.arange(6, dtype=np.float64)
    p = tmp_path / "d.bin"
    with open(p, "wb") as f:
        f.write(struct.pack("<iiq", 0, 8, 6))
        vals.tofile(f)
    got = ref_params.read_param(str(p))
    assert got.dtype == np.float64
    np.testing.assert_array_equal(got, vals)
    # truncated body fails loudly
    q = tmp_path / "t.bin"
    q.write_bytes(struct.pack("<iiq", 0, 4, 100) + b"\x00" * 8)
    with pytest.raises(ValueError, match="promises 100"):
        ref_params.read_param(str(q))
    # junk float_size fails loudly
    r = tmp_path / "j.bin"
    r.write_bytes(struct.pack("<iiq", 0, 3, 1) + b"\x00" * 4)
    with pytest.raises(ValueError, match="float_size"):
        ref_params.read_param(str(r))


def test_ref_params_extract_and_pass_dir(tmp_path):
    """extract_para.py role (sub-dict rows) + reference pass-dir bulk
    load feeding an actual embedding_layer lookup."""
    from paddle_tpu.utils.tools import ref_params
    rng = np.random.RandomState(1)
    table = rng.randn(20, 4).astype(np.float32)
    emb = tmp_path / "baidu_emb.bin"
    ref_params.write_param(str(emb), table)
    rows = ref_params.extract_rows(str(emb), [3, 0, 19], 4)
    np.testing.assert_array_equal(rows, table[[3, 0, 19]])
    with pytest.raises(ValueError, match="rows"):
        ref_params.extract_rows(str(emb), [20], 4)

    # reference checkpoint dir: one binary file per param + a done marker
    d = tmp_path / "pass-00003"
    d.mkdir()
    ref_params.write_param(str(d / "emb.w0"), table)
    ref_params.write_param(str(d / "fc.w0"), table[:4, :2])
    (d / "done").write_text("")
    loaded = ref_params.load_pass_dir(str(d))
    assert sorted(loaded) == ["emb.w0", "fc.w0"]
    np.testing.assert_array_equal(loaded["emb.w0"].reshape(20, 4), table)

    # the imported table drives a real embedding lookup
    import jax.numpy as jnp
    from paddle_tpu.ops.embedding import embedding_lookup
    out = embedding_lookup(jnp.asarray(loaded["emb.w0"].reshape(20, 4)),
                           jnp.asarray([[3, 0]]))
    np.testing.assert_allclose(np.asarray(out)[0], table[[3, 0]],
                               atol=1e-6)


def test_ref_embedding_demo_cli(tmp_path):
    """demo CLI: ref_embedding subcommand extracts a sub-dict from a
    pretrained-format table (the pre_DictAndModel.sh -> extract_para.py
    workflow, zero-egress)."""
    import subprocess
    import sys as _sys
    from paddle_tpu.utils.tools import ref_params
    rng = np.random.RandomState(2)
    table = rng.randn(11, 3).astype(np.float32)
    emb = tmp_path / "model.bin"
    ref_params.write_param(str(emb), table)
    idx = tmp_path / "ids.txt"
    idx.write_text("5\n1\n9\n")
    demo = os.path.join(os.path.dirname(__file__), "..", "demo",
                        "model_zoo", "extract_features.py")
    r = subprocess.run(
        [_sys.executable, demo, "ref_embedding", "--emb_file", str(emb),
         "--dim", "3", "--indices", str(idx),
         "--out", str(tmp_path / "sub.npz"),
         "--text", str(tmp_path / "sub.txt")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    got = np.load(tmp_path / "sub.npz")["embedding"]
    np.testing.assert_array_equal(got, table[[5, 1, 9]])
    assert (tmp_path / "sub.txt").read_text().splitlines()[0] == "3 3"
