"""User tooling (reference python/paddle/utils/): log curve plotting, model
diagram emission, torch parameter import."""

import numpy as np
import pytest
import jax

import paddle_tpu.layers as L
from paddle_tpu.layers.graph import Topology, reset_names


def test_plotcurve_parses_and_writes(tmp_path):
    from paddle_tpu.utils.tools import plotcurve
    log = [
        "I 0729 paddle_tpu] Pass 0 done, mean cost 0.83612 Eval: err=0.5\n",
        "I 0729 paddle_tpu] Pass 1 done, mean cost 0.51 Eval: err=0.25\n",
        "I 0729 paddle_tpu] Pass 2 done, mean cost 0.20 Eval: err=0.125\n",
    ]
    out = tmp_path / "curve.png"
    data = plotcurve.plot_curves(log, str(out), keys=("cost", "err"))
    assert out.exists() and out.stat().st_size > 0
    assert data["cost"] == [(0, 0.83612), (1, 0.51), (2, 0.20)]
    assert data["err"] == [(0, 0.5), (1, 0.25), (2, 0.125)]


def test_make_diagram_dot(tmp_path):
    from paddle_tpu.utils.tools import make_diagram, topology_dot
    reset_names()
    x = L.data_layer("x", size=4)
    out = L.fc_layer(x, size=2, act="softmax", name="out")
    dot = topology_dot(out)
    assert '"x" -> "out"' in dot and "digraph" in dot
    p = make_diagram(out, str(tmp_path / "m.dot"))
    assert open(p).read().startswith("digraph")


def test_torch_import_positional_and_mapped():
    torch = pytest.importorskip("torch")
    from paddle_tpu.utils.tools import from_torch_state_dict
    reset_names()
    x = L.data_layer("x", size=4)
    out = L.fc_layer(x, size=3, act=None, name="fc")
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))

    lin = torch.nn.Linear(4, 3)
    sd = lin.state_dict()            # weight [3,4], bias [3]
    # positional: [w, b] order matches our {'fc': {'w', 'b'}} leaves
    got = from_torch_state_dict(params, sd)
    np.testing.assert_allclose(np.asarray(got["fc"]["w0"]),
                               sd["weight"].numpy().T, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got["fc"]["b"]),
                               sd["bias"].numpy(), rtol=1e-6)

    got2 = from_torch_state_dict(params, sd,
                                 mapping={"fc/w0": "weight", "fc/b": "bias"})
    np.testing.assert_allclose(np.asarray(got2["fc"]["w0"]),
                               sd["weight"].numpy().T, rtol=1e-6)

    # model still runs with imported weights
    val = topo.apply(got, {"x": np.ones((2, 4), np.float32)}, mode="test")
    ref = lin(torch.ones(2, 4)).detach().numpy()
    np.testing.assert_allclose(np.asarray(val), ref, rtol=1e-5, atol=1e-6)


def test_preprocess_img_roundtrip(tmp_path):
    PIL = pytest.importorskip("PIL")
    from PIL import Image
    from paddle_tpu.utils.tools import preprocess_img
    from paddle_tpu import native
    if not native.is_available():
        pytest.skip("native runtime not built")
    src = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (src / cls).mkdir(parents=True)
        for i in range(4):
            arr = (np.random.RandomState(i).rand(20, 30, 3) * 255
                   ).astype(np.uint8)
            Image.fromarray(arr).save(src / cls / f"{i}.png")
    out = tmp_path / "rec"
    counts, mean = preprocess_img.preprocess(str(src), str(out), size=16,
                                             test_ratio=0.25, seed=0)
    assert counts["train"] + counts["test"] == 8
    rows = list(preprocess_img.record_reader(
        str(out / "train.rec"), str(out / "meta.npz"))())
    assert len(rows) == counts["train"]
    x, y = rows[0]
    assert x.shape == (16 * 16 * 3,) and y in (0, 1)
    assert np.isfinite(x).all()


def test_v2_ploter(tmp_path, monkeypatch):
    """paddle.v2.plot.Ploter (reference v2/plot/plot.py): append named
    curves, plot to a file headless, DISABLE_PLOT short-circuits."""
    from paddle_tpu.v2.plot import Ploter
    p = Ploter("train_cost", "test_cost")
    for i in range(5):
        p.append("train_cost", i, 1.0 / (i + 1))
        p.append("test_cost", i, 1.2 / (i + 1))
    out = tmp_path / "curves.png"
    p.plot(path=str(out))
    assert out.exists() and out.stat().st_size > 0
    monkeypatch.setenv("DISABLE_PLOT", "True")
    p.plot()          # prints instead of plotting; no error
    p.reset()
    assert not p.__plot_data__["train_cost"].step


def test_xprof_report_attributes_categories(tmp_path, monkeypatch):
    """End-to-end: capture a real jax.profiler trace of a jitted matmul
    loop, then the report must attribute the bulk to matmul_conv and
    expose busy/idle per track (the pre-staged MFU analysis loop)."""
    import json as _json
    import jax
    import jax.numpy as jnp
    from paddle_tpu.scripts import xprof_report

    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((256, 256))
    f(x).block_until_ready()
    prof = str(tmp_path / "prof")
    jax.profiler.start_trace(prof)
    for _ in range(4):
        f(x).block_until_ready()
    jax.profiler.stop_trace()

    runs = xprof_report.find_runs(prof)
    assert len(runs) == 1
    rep = xprof_report.report_run(runs[0])
    assert rep["tracks"], "no device/host tracks found"
    track = next(iter(rep["tracks"].values()))
    assert track["wall_us"] > 0 and 0 <= track["idle_pct"] <= 100
    cats = track["by_category_us"]
    assert cats.get("matmul_conv", 0) > 0
    assert cats["matmul_conv"] >= max(cats.values()) * 0.5
    # text + json renderers both work
    assert "matmul_conv" in xprof_report.render(rep)
    rc = xprof_report.main([prof, "--json"])
    assert rc == 0
    # --write: both artifacts in one parse
    rc = xprof_report.main([prof, "--write", str(tmp_path / "rep")])
    assert rc == 0
    assert (tmp_path / "rep.json").exists()
    assert "matmul_conv" in (tmp_path / "rep.txt").read_text()
    # categorization traps fixed by review: convert is NOT MXU time,
    # custom-call (Pallas kernels) gets its own bucket
    assert xprof_report.categorize("convert.5") == "fusion_elementwise"
    assert xprof_report.categorize("custom-call.7") == "custom_kernel"
    assert xprof_report.categorize("convolution.3") == "matmul_conv"
    assert xprof_report.categorize("while.2") == "scan_control"

    # BENCH_PROFILE_BASE plumbing: per-combo dir derived from model/batch
    monkeypatch.setenv("BENCH_PROFILE_BASE", str(tmp_path / "base"))
    from paddle_tpu.scripts import bench_sweep
    captured = {}

    class FakeProc:
        returncode = 0
        stdout = '{"value": 1.0}'
        stderr = ""

    monkeypatch.setattr(bench_sweep.subprocess, "run",
                        lambda cmd, env=None, **kw: (
                            captured.__setitem__("env", env) or FakeProc()))
    bench_sweep.run_combo("lstm", 64, None, 60)
    assert captured["env"]["BENCH_PROFILE_DIR"].endswith("lstm_bs64")
