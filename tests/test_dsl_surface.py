"""Tests for the DSL-surface additions: generation (beam_search DSL),
network composites (gru_group vs grumemory equivalence — the reference's
test_RecurrentGradientMachine discipline), conv projection/operator, and
evaluator DSL wired through SGD.train."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.data import DataFeeder, dense_vector, integer_value
from paddle_tpu.evaluators import classification_error_evaluator
from paddle_tpu.layers import networks as N
from paddle_tpu.layers.graph import Topology, reset_names
from paddle_tpu.trainer import SGD, events


def setup_function(_):
    reset_names()


_REFERENCE = os.environ.get("PADDLE_REFERENCE_DIR", "/root/reference")


@pytest.mark.skipif(
    not os.path.exists(f"{_REFERENCE}/python/paddle/trainer_config_helpers"),
    reason="reference checkout not available")
def test_layer_surface_covers_reference_all():
    """Every name in the reference trainer_config_helpers __all__ lists
    (layers + networks) resolves on paddle_tpu.layers."""
    import re
    missing = []
    for rel in ("layers.py", "networks.py"):
        src = open(f"{_REFERENCE}/python/paddle/"
                   f"trainer_config_helpers/{rel}").read()
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
        for name in re.findall(r"['\"]([^'\"]+)['\"]", m.group(1)):
            if not hasattr(L, name):
                missing.append(name)
    assert not missing, missing


def test_gru_group_matches_grumemory(rng, np_rng):
    w = L.data_layer("w", size=30, is_seq=True)
    emb = L.embedding_layer(w, size=6, param_attr={"initial_std": 0.1})
    mix = L.fc_layer(emb, size=12, act=None, bias_attr=False,
                     param_attr={"initial_std": 0.1})
    whole = L.grumemory(mix, size=4, name="gru_whole")
    grouped = N.gru_group(mix, size=4, name="gru_grp")
    topo = Topology([whole, grouped])
    params = topo.init(rng)
    gp = params["gru_grp_out"]
    wp = params["gru_whole"]
    gp["w_gate"], gp["w_state"], gp["b"] = (wp["w_gate"], wp["w_state"],
                                            wp["b"])
    seqs = [np_rng.randint(0, 30, (l,)) for l in (6, 3)]
    ow, og = topo.apply(params, {"w": pad_sequences(seqs)})
    np.testing.assert_allclose(np.asarray(ow.data), np.asarray(og.data),
                               rtol=1e-4, atol=1e-5)


def test_lstmemory_group_runs(rng, np_rng):
    w = L.data_layer("w", size=30, is_seq=True)
    emb = L.embedding_layer(w, size=6)
    mix = L.fc_layer(emb, size=16, act=None, bias_attr=False)
    grp = N.lstmemory_group(mix, size=4)
    topo = Topology(grp)
    params = topo.init(rng)
    seqs = [np_rng.randint(0, 30, (l,)) for l in (5, 2)]
    out = topo.apply(params, {"w": pad_sequences(seqs)})
    assert out.data.shape == (2, 5, 4)
    assert np.all(np.isfinite(np.asarray(out.data)))


def test_conv_projection_and_operator(rng, np_rng):
    img = L.data_layer("img", size=1 * 8 * 8, height=8, width=8)
    proj = L.mixed_layer(
        input=[L.conv_projection(img, filter_size=3, num_filters=2,
                                 num_channels=1)],
        size=2 * 6 * 6, act="relu")
    filt = L.data_layer("filt", size=2 * 1 * 3 * 3)
    op = L.mixed_layer(
        input=[L.conv_operator(img, filt, filter_size=3, num_filters=2,
                               num_channels=1)],
        size=2 * 6 * 6, act=None)
    topo = Topology([proj, op])
    params = topo.init(rng)
    feed = {"img": jnp.asarray(np_rng.randn(3, 64), jnp.float32),
            "filt": jnp.asarray(np_rng.randn(3, 18), jnp.float32)}
    out_p, out_o = topo.apply(params, feed)
    assert out_p.shape == (3, 72) and out_o.shape == (3, 72)
    # per-sample semantics: row i only depends on filter row i
    feed2 = dict(feed)
    f2 = np.array(feed["filt"])
    f2[1] = 0.0
    feed2["filt"] = jnp.asarray(f2)
    _, out_o2 = topo.apply(params, feed2)
    np.testing.assert_allclose(np.asarray(out_o2[0]), np.asarray(out_o[0]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_o2[1]), 0.0, atol=1e-6)


def test_beam_search_dsl_generates(rng, np_rng):
    """Tiny decoder: state = fc(emb(prev)); probs = softmax(fc(state)).
    Checks shapes, score ordering, eos termination."""
    V, E, H = 11, 6, 8
    enc = L.data_layer("enc", size=H)

    def step(word_emb, enc_static):
        mem = L.memory(name="dec_state", size=H)
        s = L.fc_layer(L.concat_layer([word_emb, mem, enc_static]),
                       size=H, act="tanh", name="dec_state")
        return L.fc_layer(s, size=V, act="softmax", name="dec_prob")

    gen = L.beam_search(
        step,
        input=[L.GeneratedInput(size=V, embedding_name="trg_emb",
                                embedding_size=E),
               L.StaticInput(enc)],
        bos_id=0, eos_id=1, beam_size=3, max_length=7)
    topo = Topology(gen)
    params = topo.init(rng)
    res = topo.apply(params, {"enc": jnp.asarray(np_rng.randn(4, H),
                                                 jnp.float32)}, mode="test")
    assert res.tokens.shape == (4, 3, 7)
    assert res.scores.shape == (4, 3)
    # scores sorted best-first
    s = np.asarray(res.scores)
    assert np.all(np.diff(s, axis=1) <= 1e-6)
    # all tokens in range
    assert np.asarray(res.tokens).min() >= 0
    assert np.asarray(res.tokens).max() < V


def test_greedy_generation_dsl(rng, np_rng):
    V, E, H = 9, 4, 6
    enc = L.data_layer("enc", size=H)

    def step(word_emb, enc_static):
        mem = L.memory(name="g_state", size=H)
        s = L.fc_layer(L.concat_layer([word_emb, mem, enc_static]),
                       size=H, act="tanh", name="g_state")
        return L.fc_layer(s, size=V, act="softmax")

    gen = L.greedy_generation(
        step,
        input=[L.GeneratedInput(size=V, embedding_name="e", embedding_size=E),
               L.StaticInput(enc)],
        bos_id=0, eos_id=1, max_length=5)
    topo = Topology(gen)
    params = topo.init(rng)
    out = topo.apply(params, {"enc": jnp.asarray(np_rng.randn(3, H),
                                                 jnp.float32)}, mode="test")
    assert out.data.shape == (3, 5)
    assert np.all(np.asarray(out.lengths) <= 5)


def test_evaluator_dsl_in_train_loop(np_rng):
    x = L.data_layer("x", size=4)
    lab = L.data_layer("lab", size=1)
    y = L.fc_layer(x, size=3, act="softmax")
    cost = L.classification_cost(y, lab)
    ev = classification_error_evaluator(y, lab, name="clserr")
    trainer = SGD(cost=cost, update_equation=optim.Adam(learning_rate=0.05),
                  evaluators=[ev])
    xs = np_rng.randn(96, 4).astype(np.float32)
    ys = np_rng.randint(0, 3, (96,))

    def reader():
        for i in range(0, 96, 16):
            yield [(xs[j], int(ys[j])) for j in range(i, i + 16)]

    trainer.train(reader, num_passes=2,
                  feeding={"x": dense_vector(4), "lab": integer_value(3)},
                  log_period=0, buffered_batches=0)
    r = ev.result()
    assert 0.0 <= r <= 1.0


def test_ctc_and_chunk_evaluator_adapters(np_rng):
    from paddle_tpu.evaluators import (ctc_error_evaluator, chunk_evaluator,
                                       pnpair_evaluator)
    from paddle_tpu.core.sequence import SequenceBatch
    out = L.data_layer("o", size=5, is_seq=True)
    lab = L.data_layer("l", size=1, is_seq=True)
    ev = ctc_error_evaluator(out, lab, blank=0)
    # frames decode to [2, 3] (collapse repeats, drop blank); label [2, 3]
    probs = np.zeros((1, 4, 5), np.float32)
    for t, c in enumerate([2, 2, 0, 3]):
        probs[0, t, c] = 1.0
    ev.update(SequenceBatch(data=jnp.asarray(probs),
                            lengths=jnp.asarray([4])),
              SequenceBatch(data=jnp.asarray([[2, 3]]),
                            lengths=jnp.asarray([2])))
    assert ev.result() == 0.0  # perfect decode
    ev2 = chunk_evaluator(out, lab, num_chunk_types=2)
    tags = np.array([[0, 1, 2, 3]])  # B-0 I-0 B-1 I-1 -> two spans
    ev2.update(SequenceBatch(data=jnp.asarray(tags), lengths=jnp.asarray([4])),
               SequenceBatch(data=jnp.asarray(tags), lengths=jnp.asarray([4])))
    r = ev2.result()
    assert r["f1"] == 1.0
    # pnpair: extra_inputs carries the query layer for the trainer
    q = L.data_layer("q", size=1)
    ev3 = pnpair_evaluator(out, lab, q)
    assert "query_id" in ev3.extra_inputs


def test_generated_input_ids_not_clobbered():
    enc = L.data_layer("enc2", size=4)

    def step(we, cs):
        mem = L.memory(name="st2", size=4)
        s = L.fc_layer([we, mem, cs], size=4, act="tanh", name="st2")
        return L.fc_layer(s, size=7, act="softmax")

    gi = L.GeneratedInput(size=7, embedding_name="e2", embedding_size=3,
                          bos_id=5, eos_id=6)
    node = L.beam_search(step, input=[gi, L.StaticInput(enc)], beam_size=2,
                         max_length=3)
    assert gi.bos_id == 5 and gi.eos_id == 6
    # explicit override still wins
    gi2 = L.GeneratedInput(size=7, embedding_name="e3", embedding_size=3,
                           bos_id=5, eos_id=6)
    L.beam_search(step, input=[gi2, L.StaticInput(enc)], bos_id=0, eos_id=1,
                  beam_size=2, max_length=3)
    assert gi2.bos_id == 0 and gi2.eos_id == 1
