"""Int8 flash prefill (ops/pallas/flash_attention.flash_attention_quant;
docs/serving.md "Quantized serving"): the kernel pinned bit-exactly
against flash over the dequantized widened twin (same blocks = identical
summation order), against the XLA reference within float tolerance, the
dispatch/coverage/validation surface, the lm_prefill routing's cache
bit-exactness to the sequential-step round trip, and the perf/analytic
widened-prefill structural gate in both directions."""

import importlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import transformer
from paddle_tpu.ops.attention import dot_product_attention, repeat_kv_heads
from paddle_tpu.quant import kv as kvq
from paddle_tpu.quant import weights as qw

fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

V, D, HEADS, LAYERS, MAXLEN = 64, 32, 2, 2, 48


def _trunk(seed=0):
    return transformer.init(jax.random.PRNGKey(seed), src_vocab=V,
                            trg_vocab=1, d_model=D, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAXLEN)


def _case(seed, b, heads, hkv, tq, dh):
    rng = np.random.RandomState(seed)
    d, dkv = heads * dh, hkv * dh
    q = jnp.asarray(rng.randn(b, tq, d).astype(np.float32))
    qk, sk = kvq.quantize_heads(
        jnp.asarray(rng.randn(b, tq, dkv).astype(np.float32)), hkv)
    qv, sv = kvq.quantize_heads(
        jnp.asarray(rng.randn(b, tq, dkv).astype(np.float32)), hkv)
    return q, qk, qv, sk, sv


def _widened_bhtd(q, qk, qv, sk, sv, heads):
    """The dequantized [B, H, T, dh] twin of the kernel's int8 inputs."""
    b, tq, d = q.shape
    hkv = sk.shape[-1]
    split = lambda a, hh: a.reshape(b, tq, hh, -1).transpose(0, 2, 1, 3)
    kw = kvq.dequantize_heads(qk, sk)
    vw = kvq.dequantize_heads(qv, sv)
    return (split(q, heads),
            repeat_kv_heads(split(kw, hkv), heads),
            repeat_kv_heads(split(vw, hkv), heads))


# ------------------------------------------------------- kernel oracle

def test_quant_kernel_bit_exact_vs_dequant_flash_oracle():
    """The acceptance oracle: flash_attention_quant vs flash_attention
    over the dequantized widened K/V with the SAME block sizes — the
    in-register widen is the exact dequantize_heads product and the
    blocks impose identical summation order, so the outputs agree to
    1e-7 (bit-exact in practice)."""
    q, qk, qv, sk, sv = _case(0, b=2, heads=2, hkv=2, tq=32, dh=16)
    out = fa.flash_attention_quant(q, qk, qv, sk, sv, 2, causal=True,
                                   interpret=True)
    qh, kh, vh = _widened_bhtd(q, qk, qv, sk, sv, 2)
    want = fa.flash_attention(qh, kh, vh, causal=True, interpret=True)
    err = float(jnp.abs(out - want).max())
    assert err <= 1e-7, err


def test_quant_kernel_matches_xla_reference():
    q, qk, qv, sk, sv = _case(1, b=2, heads=2, hkv=2, tq=32, dh=16)
    out = fa.flash_attention_quant(q, qk, qv, sk, sv, 2, causal=True,
                                   interpret=True)
    qh, kh, vh = _widened_bhtd(q, qk, qv, sk, sv, 2)
    want = dot_product_attention(qh, kh, vh, causal=True,
                                 use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_quant_kernel_gqa_group_reads_kv_head_stripe():
    """heads=4 over hkv=2: each query head's BlockSpec index map selects
    its KV head's dh-column stripe from the FLAT [B, Tk, Dkv] cache —
    no repeat_kv_heads materialization feeds the kernel."""
    q, qk, qv, sk, sv = _case(2, b=1, heads=4, hkv=2, tq=32, dh=16)
    out = fa.flash_attention_quant(q, qk, qv, sk, sv, 4, causal=True,
                                   interpret=True)
    qh, kh, vh = _widened_bhtd(q, qk, qv, sk, sv, 4)
    want = fa.flash_attention(qh, kh, vh, causal=True, interpret=True)
    err = float(jnp.abs(out - want).max())
    assert err <= 1e-7, err


@pytest.mark.slow
@pytest.mark.parametrize("b,heads,hkv,tq,dh,blk", [
    (1, 2, 2, 64, 16, 512),      # single kv head group, one block
    (2, 4, 1, 64, 32, 32),       # MQA: every head reads the one stripe
    (2, 8, 2, 128, 64, 64),      # multi-block q AND k loops
    (1, 2, 2, 96, 128, 48),      # lane-width dh, non-power-of-2 blocks
])
def test_quant_kernel_grid(b, heads, hkv, tq, dh, blk):
    """The blocking/GQA grid — every (multi-block, group, dh) corner
    stays on the 1e-7 oracle."""
    q, qk, qv, sk, sv = _case(b + heads + tq, b, heads, hkv, tq, dh)
    out = fa.flash_attention_quant(q, qk, qv, sk, sv, heads,
                                   causal=True, block_q=blk,
                                   block_k=blk, interpret=True)
    qh, kh, vh = _widened_bhtd(q, qk, qv, sk, sv, heads)
    want = fa.flash_attention(qh, kh, vh, causal=True, block_q=blk,
                              block_k=blk, interpret=True)
    err = float(jnp.abs(out - want).max())
    assert err <= 1e-7, err


# -------------------------------------------- validation + dispatch

def test_quant_kernel_validation():
    q, qk, qv, sk, sv = _case(3, b=1, heads=2, hkv=2, tq=16, dh=16)
    with pytest.raises(ValueError):        # f32 K/V is the caller's bug
        fa.flash_attention_quant(q, kvq.dequantize_heads(qk, sk), qv,
                                 sk, sv, 2, interpret=True)
    with pytest.raises(ValueError):        # missing sidecars
        fa.flash_attention_quant(q, qk, qv, None, None, 2,
                                 interpret=True)
    with pytest.raises(ValueError):        # d/dkv not a head layout
        fa.flash_attention_quant(q, qk[..., :24], qv[..., :24],
                                 sk, sv, 2, interpret=True)
    with pytest.raises(ValueError):        # causal needs tq == tk
        fa.flash_attention_quant(q[:, :8], qk, qv, sk, sv, 2,
                                 causal=True, interpret=True)


def test_prefill_quant_covers():
    assert fa.prefill_quant_covers(1, 32, 32, 32, 32, 2, True)
    assert not fa.prefill_quant_covers(1, 32, 16, 32, 32, 2, True)
    assert not fa.prefill_quant_covers(1, 12, 12, 32, 32, 2, True)
    assert not fa.prefill_quant_covers(1, 32, 32, 32, 24, 2, True)


def test_maybe_prefill_quant_dispatch():
    q, qk, qv, sk, sv = _case(4, b=1, heads=2, hkv=2, tq=16, dh=16)
    with fa.forced_prefill_quant_mode("off"):
        assert fa.maybe_prefill_quant(q, qk, qv, sk, sv, 2) is None
    with fa.forced_prefill_quant_mode("always"):
        # a float cache (no sidecars) never routes here
        assert fa.maybe_prefill_quant(q, qk, qv, None, None, 2) is None
        out = fa.maybe_prefill_quant(q, qk, qv, sk, sv, 2)
    assert out is not None and out.shape == q.shape
    qh, kh, vh = _widened_bhtd(q, qk, qv, sk, sv, 2)
    want = fa.flash_attention(qh, kh, vh, causal=True, interpret=True)
    b, tq, d = q.shape
    want = want.transpose(0, 2, 1, 3).reshape(b, tq, d)
    assert float(jnp.abs(out - want).max()) <= 1e-7
    # uncoverable shape: fall back (Tp=12 has no sublane block)
    with fa.forced_prefill_quant_mode("always"):
        assert fa.maybe_prefill_quant(q[:, :12], qk[:, :12], qv[:, :12],
                                      sk[:, :12], sv[:, :12], 2) is None


def test_prefill_quant_mode_parsing():
    with fa.forced_prefill_quant_mode("off"):
        assert not fa.prefill_quant_enabled()
    with fa.forced_prefill_quant_mode("always"):
        assert fa.prefill_quant_enabled()
    with fa.forced_prefill_quant_mode("bogus"):
        with pytest.raises(ValueError):
            fa.prefill_quant_enabled()
    # the tier-1 default: auto follows use_pallas() — off on CPU, so
    # the reference path keeps the batched-vs-sequential bit-exactness
    with fa.forced_prefill_quant_mode("auto"):
        from paddle_tpu.ops import pallas as pk
        assert fa.prefill_quant_enabled() == pk.use_pallas()


# ------------------------------------------------ lm_prefill routing

def test_lm_prefill_quant_cache_bit_exact_to_sequential_steps():
    """The ingestion-order invariant EXTENDED to the kernel path: with
    the quant kernel forced ON, lm_prefill's int8 cache (values AND
    sidecar scales) stays bit-identical to the sequential-step round
    trip — the quantize math feeding the cache is untouched by how
    attention reads it back.  (Eager like the reference-path twin in
    test_quant.py: whole-program jit may reassociate the scale divide
    by 1 ulp on ANY attention path — that is jit fusion, not the
    kernel, and the int8 values stay bit-exact either way.)"""
    params = _trunk()
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, V, (1, 8)).astype(np.int32)
    with fa.forced_prefill_quant_mode("always"):
        _h, cache = transformer.lm_prefill(params, prompt, MAXLEN,
                                           HEADS, kv_dtype="int8")
    cache2 = transformer.init_lm_cache(params, 1, MAXLEN,
                                       kv_dtype="int8", num_heads=HEADS)
    for t in range(prompt.shape[1]):
        _l, cache2 = transformer.lm_decode_step(params, prompt[:, t], t,
                                                cache2, HEADS)
    tp = prompt.shape[1]
    for key in ("k", "v", "ks", "vs"):
        np.testing.assert_array_equal(
            np.asarray(cache[0][key])[:, :tp],
            np.asarray(cache2[0][key])[:, :tp])


def test_lm_prefill_quant_kernel_matches_reference_path():
    """Kernel ON vs kernel OFF over the SAME int8 cache: the hidden
    states agree to float tolerance and the caches bit-exactly."""
    params = _trunk(1)
    rng = np.random.RandomState(6)
    prompt = rng.randint(1, V, (2, 16)).astype(np.int32)

    def prefill(mode):
        with fa.forced_prefill_quant_mode(mode):
            return jax.jit(lambda p, t: transformer.lm_prefill(
                p, t, MAXLEN, HEADS, kv_dtype="int8"))(params, prompt)

    h_on, c_on = prefill("always")
    h_off, c_off = prefill("off")
    assert float(jnp.abs(h_on - h_off).max()) <= 1e-4
    for key in ("k", "v", "ks", "vs"):
        np.testing.assert_array_equal(np.asarray(c_on[0][key]),
                                      np.asarray(c_off[0][key]))


# ------------------------------------------------------ analytic gates

def test_analytic_prefill_gates_both_directions():
    """assert_prefill_kv_quantized passes on the kernel-forced int8
    prefill and FIRES on the dequant twin (>= 2 widen converts per
    layer: K and V) — plus the predicted-prefill-bytes model clears the
    35% acceptance bar."""
    from paddle_tpu.perf import analytic as pa
    params = _trunk()
    qp = qw.quantize_lm(params, min_size=512)
    b, tp = 2, 16
    prompt = np.random.RandomState(0).randint(
        1, V, (b, tp)).astype(np.int32)
    dkv = qw.weight_shape(params["enc"][0]["attn"]["wk"])[1]

    def staged(mode):
        with fa.forced_prefill_quant_mode(mode):
            def fn(p, toks):
                return transformer.lm_prefill(p, toks, MAXLEN, HEADS,
                                              kv_dtype="int8")
            return jax.jit(fn).lower(qp, prompt).compile().as_text()

    pa.assert_prefill_kv_quantized(staged("always"), b, tp, dkv)
    twin = staged("off")
    with pytest.raises(AssertionError):
        pa.assert_prefill_kv_quantized(twin, b, tp, dkv)
    assert len(pa.widened_prefill_kv_instrs(twin, b, tp, dkv)) \
        >= 2 * LAYERS
    b_f32 = pa.predicted_prefill_bytes(params, b, tp, HEADS)
    b_i8 = pa.predicted_prefill_bytes(qp, b, tp, HEADS, "int8")
    assert 1 - b_i8 / b_f32 >= 0.35
