"""Sharding tests on the 8-device virtual CPU mesh (SURVEY.md §4 pattern (4):
replaces the reference's in-process localhost pserver tests,
test_CompareSparse.cpp) — including single-device vs data-parallel
equivalence (pattern (3))."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import (
    MeshConfig, make_mesh, megatron_rules, param_shardings, shard_params,
    batch_shardings, valid_spec, AXIS_MODEL)


needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


@needs_8
def test_mesh_shapes():
    mesh = make_mesh(MeshConfig(data=4, model=2))
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 4, "stage": 1, "seq": 1, "expert": 1, "model": 2}


def test_valid_spec_fallback():
    mesh = make_mesh(MeshConfig(data=4, model=2))
    # dim 5 not divisible by model=2 -> replicated
    assert valid_spec(P(None, AXIS_MODEL), (3, 5), mesh) == P()
    assert valid_spec(P(None, AXIS_MODEL), (3, 6), mesh) == P(None, AXIS_MODEL)


@needs_8
def test_megatron_rules_shard_embeddings():
    mesh = make_mesh(MeshConfig(data=4, model=2))
    params = {"emb": jnp.zeros((64, 16)), "fc": {"w": jnp.zeros((16, 32))},
              "bias": jnp.zeros((7,))}
    sh = param_shardings(params, mesh, megatron_rules())
    assert sh["emb"].spec == P(AXIS_MODEL)
    assert sh["fc"]["w"].spec == P(None, AXIS_MODEL)
    assert sh["bias"].spec == P()  # odd size -> replicated
    placed = shard_params(params, mesh, megatron_rules())
    assert placed["emb"].sharding.spec == P(AXIS_MODEL)


@needs_8
def test_data_parallel_matches_single_device(np_rng):
    """Sharded train step == single-device step (the framework's strongest
    regression tool per SURVEY.md §4: config-pair equivalence)."""
    from paddle_tpu.models import lenet
    from paddle_tpu import optim

    params = lenet.init(jax.random.PRNGKey(0))
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9)
    images = jnp.asarray(np_rng.randn(16, 784), jnp.float32)
    labels = jnp.asarray(np_rng.randint(0, 10, (16,)))

    def step(p, s, im, lab):
        l, g = jax.value_and_grad(lenet.loss)(p, im, lab)
        p2, s2 = opt.update(g, s, p)
        return p2, l

    # single device
    p1, l1 = jax.jit(step)(params, opt.init(params), images, labels)

    # 8-way data parallel
    mesh = make_mesh(MeshConfig(data=8, model=1))
    ps = param_shardings(params, mesh)
    fs = batch_shardings({"im": images, "lab": labels}, mesh)
    st = opt.init(params)
    os_ = {"step": jax.sharding.NamedSharding(mesh, P()),
           "slots": {"mom": ps}}
    stepj = jax.jit(step, in_shardings=(ps, os_, fs["im"], fs["lab"]),
                    out_shardings=(ps, jax.sharding.NamedSharding(mesh, P())))
    p8, l8 = stepj(jax.device_put(params, ps), jax.device_put(st, os_),
                   jax.device_put(images, fs["im"]),
                   jax.device_put(labels, fs["lab"]))
    np.testing.assert_allclose(float(l1), float(l8), rtol=1e-5)
    w1 = np.asarray(p1["f2"]["w"])
    w8 = np.asarray(p8["f2"]["w"])
    np.testing.assert_allclose(w1, w8, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
@needs_8
def test_graft_dryrun_multichip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
