"""Pipeline parallelism (parallel/pipeline.py): the GPipe schedule over the
'stage' mesh axis must reproduce the sequential stack — forward AND grads
(the backward schedule is autodiff's transpose of the forward rotation) —
including combined pipeline x data parallelism and remat.

The reference's analog is ParallelNeuralNetwork's device= placement
(ParallelNeuralNetwork.cpp:15-60); the equivalence oracle is the same
config-pair discipline as test_NetworkCompare.cpp."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import (MeshConfig, make_mesh)
from paddle_tpu.parallel.pipeline import (
    gpipe, stack_stages, unstack_stages, stage_spec, microbatch,
    unmicrobatch)

S, D = 4, 16


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _mk_params(rng):
    return [{"w": jnp.asarray(rng.randn(D, D) * 0.4, jnp.float32),
             "b": jnp.asarray(rng.randn(D) * 0.1, jnp.float32)}
            for _ in range(S)]


def _sequential(params_list, x):
    for p in params_list:
        x = _stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig(data=2, stage=S))


def test_forward_matches_sequential(np_rng, mesh):
    params = _mk_params(np_rng)
    stacked = stack_stages(params)
    x = jnp.asarray(np_rng.randn(24, D), jnp.float32)
    x_mb = microbatch(x, 6)
    got = unmicrobatch(gpipe(_stage_fn, stacked, x_mb, mesh=mesh))
    want = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_single_microbatch_and_unstack(np_rng, mesh):
    params = _mk_params(np_rng)
    stacked = stack_stages(params)
    x = jnp.asarray(np_rng.randn(1, 8, D), jnp.float32)   # M=1 degenerate
    got = gpipe(_stage_fn, stacked, x, mesh=mesh)
    want = _sequential(params, x[0])
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want),
                               atol=1e-5)
    back = unstack_stages(stacked)
    for a, b in zip(back, params):
        np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


@pytest.mark.slow
@pytest.mark.parametrize("remat", [False, True], ids=["plain", "remat"])
def test_grads_match_sequential(np_rng, mesh, remat):
    params = _mk_params(np_rng)
    stacked = stack_stages(params)
    x = jnp.asarray(np_rng.randn(16, D), jnp.float32)
    tgt = jnp.asarray(np_rng.randn(16, D), jnp.float32)

    def loss_pipe(sp):
        y = unmicrobatch(gpipe(_stage_fn, sp, microbatch(x, 4), mesh=mesh,
                               remat=remat))
        return jnp.mean((y - tgt) ** 2)

    def loss_seq(plist):
        return jnp.mean((_sequential(plist, x) - tgt) ** 2)

    gp = jax.grad(loss_pipe)(stacked)
    gs = stack_stages(jax.grad(loss_seq)(params))
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   atol=1e-5)


def test_pp_times_dp(np_rng, mesh):
    """Microbatch dim sharded over 'data' while stages pipeline."""
    params = _mk_params(np_rng)
    stacked = stack_stages(params)
    x = jnp.asarray(np_rng.randn(32, D), jnp.float32)
    x_mb = microbatch(x, 4)                       # [4, 8, D], 8 % data=2 == 0
    got = unmicrobatch(gpipe(_stage_fn, stacked, x_mb, mesh=mesh,
                             data_axis="data"))
    want = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_trains(np_rng, mesh):
    """A few pipelined SGD steps reduce the loss (end-to-end schedule +
    backward under jit)."""
    params = _mk_params(np_rng)
    stacked = stack_stages(params)
    x = jnp.asarray(np_rng.randn(16, D), jnp.float32)
    tgt = jnp.tanh(jnp.asarray(np_rng.randn(16, D), jnp.float32))

    @jax.jit
    def step(sp):
        def loss(sp):
            y = unmicrobatch(gpipe(_stage_fn, sp, microbatch(x, 4),
                                   mesh=mesh))
            return jnp.mean((y - tgt) ** 2)
        l, g = jax.value_and_grad(loss)(sp)
        return jax.tree_util.tree_map(lambda p, gg: p - 0.2 * gg, sp, g), l

    first = None
    for _ in range(30):
        stacked, l = step(stacked)
        first = first if first is not None else float(l)
    assert float(l) < 0.6 * first, (first, float(l))


def test_bad_microbatch_raises():
    with pytest.raises(ValueError, match="not divisible"):
        microbatch(jnp.zeros((10, D)), 3)


def test_stage_count_mismatch_raises(np_rng, mesh):
    params = _mk_params(np_rng)[:2]               # 2 stages, mesh has 4
    with pytest.raises(ValueError, match="stacked stages"):
        gpipe(_stage_fn, stack_stages(params),
              microbatch(jnp.zeros((8, D)), 2), mesh=mesh)


@pytest.mark.slow
def test_pp_times_tp_times_dp(np_rng):
    """3D: megatron-sharded MLP blocks (tp over 'model') inside pipeline
    stages (pp over 'stage') on data-sharded microbatches (dp)."""
    from jax.sharding import PartitionSpec as P
    mesh3 = make_mesh(MeshConfig(data=2, stage=2, model=2))
    F = 32
    params = [{"w1": jnp.asarray(np_rng.randn(D, F) * 0.3, jnp.float32),
               "w2": jnp.asarray(np_rng.randn(F, D) * 0.3, jnp.float32),
               "b": jnp.asarray(np_rng.randn(D) * 0.1, jnp.float32)}
              for _ in range(2)]
    stacked = stack_stages(params)
    specs = {"w1": P("stage", None, "model"),   # column-parallel
             "w2": P("stage", "model", None),   # row-parallel
             "b": P("stage")}

    def block(p, x):
        h = jax.nn.relu(x @ p["w1"])            # local [mb, F/tp]
        part = h @ p["w2"]                      # partial sum
        return x + jax.lax.psum(part, "model") + p["b"]

    def block_seq(p, x):
        return x + jax.nn.relu(x @ p["w1"]) @ p["w2"] + p["b"]

    x = jnp.asarray(np_rng.randn(16, D), jnp.float32)

    def loss_pipe(sp):
        y = unmicrobatch(gpipe(block, sp, microbatch(x, 4), mesh=mesh3,
                               data_axis="data", param_specs=specs))
        return jnp.mean(y ** 2)

    def loss_seq(plist):
        h = x
        for p in plist:
            h = block_seq(p, h)
        return jnp.mean(h ** 2)

    got = loss_pipe(stacked)
    want = loss_seq(params)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    gp = jax.grad(loss_pipe)(stacked)
    gs = stack_stages(jax.grad(loss_seq)(params))
    for k in ("w1", "w2", "b"):
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   atol=2e-5)


def test_param_specs_wrong_leading_dim_raises(np_rng, mesh):
    from jax.sharding import PartitionSpec as P
    params = _mk_params(np_rng)
    with pytest.raises(ValueError, match="leading dim"):
        gpipe(_stage_fn, stack_stages(params),
              microbatch(jnp.zeros((8, D)), 2), mesh=mesh,
              param_specs={"w": P("model"), "b": P("model")})
