"""Fused Pallas GRU vs the lax.scan reference path — same dual-path
discipline as tests/test_pallas_lstm.py, including the time-flip trick for
the reverse (encoder-backward) direction."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import rnn

B, T, D = 8, 7, 128


def _mk(np_rng, ragged=True):
    x = jnp.asarray(np_rng.randn(B, T, 3 * D) * 0.3, jnp.float32)
    lengths = (np_rng.randint(1, T + 1, (B,)) if ragged
               else np.full((B,), T))
    seq = SequenceBatch(data=x, lengths=jnp.asarray(lengths, jnp.int32))
    w_gate = jnp.asarray(np_rng.randn(D, 2 * D) * 0.1, jnp.float32)
    w_state = jnp.asarray(np_rng.randn(D, D) * 0.1, jnp.float32)
    bias = jnp.asarray(np_rng.randn(3 * D) * 0.1, jnp.float32)
    return seq, w_gate, w_state, bias


def _run(seq, w_gate, w_state, bias, fused, reverse=False, use_final=False):
    prior = rnn.FUSED_LSTM
    rnn.FUSED_LSTM = "always" if fused else "0"
    try:
        out, final = rnn.gru(seq, w_gate, w_state, bias=bias,
                             reverse=reverse)
        tot = jnp.sum(out.data ** 2)
        if use_final:
            tot = tot + jnp.sum(final ** 2)
        return tot
    finally:
        rnn.FUSED_LSTM = prior


@pytest.mark.parametrize("reverse", [False, True], ids=["fwd", "rev"])
@pytest.mark.parametrize("ragged", [False, True], ids=["full", "ragged"])
def test_fused_matches_scan_forward(np_rng, reverse, ragged):
    seq, wg, ws, bias = _mk(np_rng, ragged)
    a = _run(seq, wg, ws, bias, fused=True, reverse=reverse)
    b = _run(seq, wg, ws, bias, fused=False, reverse=reverse)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)


@pytest.mark.parametrize("reverse", [False, True], ids=["fwd", "rev"])
def test_fused_matches_scan_grads(np_rng, reverse):
    seq, wg, ws, bias = _mk(np_rng, ragged=True)

    def loss(fused, xdata, wg, ws, bias):
        s = SequenceBatch(data=xdata, lengths=seq.lengths)
        return _run(s, wg, ws, bias, fused, reverse=reverse,
                    use_final=True)

    args = (seq.data, wg, ws, bias)
    ga = jax.grad(lambda *a: loss(True, *a), argnums=(0, 1, 2, 3))(*args)
    gb = jax.grad(lambda *a: loss(False, *a), argnums=(0, 1, 2, 3))(*args)
    for la, (a, b) in zip(["dx", "dw_gate", "dw_state", "dbias"],
                          zip(ga, gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=la)


def test_fused_zero_length_sequence(np_rng):
    seq, wg, ws, bias = _mk(np_rng, ragged=True)
    seq = SequenceBatch(data=seq.data, lengths=seq.lengths.at[0].set(0))
    a = _run(seq, wg, ws, bias, fused=True)
    b = _run(seq, wg, ws, bias, fused=False)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)
