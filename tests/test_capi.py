"""C inference API tests (reference capi/tests + capi/examples role):
train tiny model -> merge_model -> drive libpaddle_tpu_capi.so from an
actual C program (subprocess), and in-process via ctypes."""

import ctypes
import os
import subprocess

import numpy as np
import pytest
import jax

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.layers.graph import Topology, reset_names
from paddle_tpu.trainer.checkpoint import save_checkpoint, merge_model

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_ROOT, "paddle_tpu", "native")
_LIB = os.path.join(_NATIVE, "libpaddle_tpu_capi.so")

# the .so is not committed; build it on demand from a clean checkout
from paddle_tpu.native import build as _native_build   # noqa: E402
_native_build.ensure("capi")

pytestmark = pytest.mark.skipif(
    not os.path.exists(_LIB),
    reason="capi lib not built (python -m paddle_tpu.native.build)")


_CONFIG = """
import paddle_tpu.layers as L
from paddle_tpu.layers.graph import reset_names
reset_names()
x = L.data_layer("x", size=4)
h = L.fc_layer(x, size=8, act="tanh", name="h0")
predict = L.fc_layer(h, size=2, act="softmax", name="out")
"""


@pytest.fixture(scope="module")
def merged_model(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("capi")
    reset_names()
    x = L.data_layer("x", size=4)
    h = L.fc_layer(x, size=8, act="tanh", name="h0")
    y = L.fc_layer(h, size=2, act="softmax", name="out")
    topo = Topology(y)
    params = topo.init(jax.random.PRNGKey(0))
    save_dir = str(tmp / "ckpt")
    save_checkpoint(save_dir, 0, params, None, {})
    model_path = str(tmp / "model.npz")
    merge_model(save_dir, model_path)
    config_path = str(tmp / "config.py")
    with open(config_path, "w") as f:
        f.write(_CONFIG)
    # reference outputs for the C program's fixed input
    import jax.numpy as jnp
    inp = np.array([[1, 0, 0, 0], [0, 0, 0, 1]], np.float32)
    ref = np.asarray(topo.apply(params, {"x": jnp.asarray(inp)},
                                mode="test"))
    return config_path, model_path, inp, ref


def test_capi_ctypes_roundtrip(merged_model):
    config_path, model_path, inp, ref = merged_model
    lib = ctypes.CDLL(_LIB)
    lib.pt_capi_create.restype = ctypes.c_int64
    lib.pt_capi_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.pt_capi_last_error.restype = ctypes.c_char_p
    assert lib.pt_capi_init(_ROOT.encode()) == 0
    h = lib.pt_capi_create(config_path.encode(), model_path.encode())
    assert h > 0, lib.pt_capi_last_error().decode()
    flat = np.ascontiguousarray(inp)
    rc = lib.pt_capi_set_input_dense(
        ctypes.c_int64(h), b"x",
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(2), ctypes.c_int64(4))
    assert rc == 0, lib.pt_capi_last_error().decode()
    n = lib.pt_capi_run(ctypes.c_int64(h))
    assert n == 1, lib.pt_capi_last_error().decode()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    assert lib.pt_capi_output_shape(ctypes.c_int64(h), 0,
                                    ctypes.byref(rows),
                                    ctypes.byref(cols)) == 0
    assert (rows.value, cols.value) == ref.shape
    buf = np.zeros(ref.shape, np.float32)
    wrote = lib.pt_capi_get_output(
        ctypes.c_int64(h), 0,
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(buf.size))
    assert wrote == buf.size
    np.testing.assert_allclose(buf, ref, rtol=1e-5, atol=1e-6)
    lib.pt_capi_destroy(ctypes.c_int64(h))


def test_capi_from_c_program(merged_model, tmp_path):
    """Compile and run the shipped C example against the trained model —
    the reference's capi/examples/model_inference flow."""
    config_path, model_path, inp, ref = merged_model
    exe = str(tmp_path / "infer_dense")
    src = os.path.join(_NATIVE, "examples", "infer_dense.c")
    subprocess.check_call(
        ["gcc", src, "-I" + os.path.join(_NATIVE, "include"),
         "-L" + _NATIVE, "-lpaddle_tpu_capi",
         "-Wl,-rpath," + _NATIVE, "-o", exe])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe, _ROOT, config_path, model_path],
                         capture_output=True, text=True, env=env,
                         timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("row")]
    assert len(lines) == 2
    got = np.array([[float(v) for v in l.split(":")[1].split()]
                    for l in lines])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
