"""C inference API tests (reference capi/tests + capi/examples role):
train tiny model -> merge_model -> drive libpaddle_tpu_capi.so from an
actual C program (subprocess), and in-process via ctypes."""

import ctypes
import os
import subprocess

import numpy as np
import pytest
import jax

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.layers.graph import Topology, reset_names
from paddle_tpu.trainer.checkpoint import save_checkpoint, merge_model

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_ROOT, "paddle_tpu", "native")
_LIB = os.path.join(_NATIVE, "libpaddle_tpu_capi.so")

# the .so is not committed; build it on demand from a clean checkout
from paddle_tpu.native import build as _native_build   # noqa: E402
_native_build.ensure("capi")

pytestmark = pytest.mark.skipif(
    not os.path.exists(_LIB),
    reason="capi lib not built (python -m paddle_tpu.native.build)")


_CONFIG = """
import paddle_tpu.layers as L
from paddle_tpu.layers.graph import reset_names
reset_names()
x = L.data_layer("x", size=4)
h = L.fc_layer(x, size=8, act="tanh", name="h0")
predict = L.fc_layer(h, size=2, act="softmax", name="out")
"""


@pytest.fixture(scope="module")
def merged_model(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("capi")
    reset_names()
    x = L.data_layer("x", size=4)
    h = L.fc_layer(x, size=8, act="tanh", name="h0")
    y = L.fc_layer(h, size=2, act="softmax", name="out")
    topo = Topology(y)
    params = topo.init(jax.random.PRNGKey(0))
    save_dir = str(tmp / "ckpt")
    save_checkpoint(save_dir, 0, params, None, {})
    model_path = str(tmp / "model.npz")
    merge_model(save_dir, model_path)
    config_path = str(tmp / "config.py")
    with open(config_path, "w") as f:
        f.write(_CONFIG)
    # reference outputs for the C program's fixed input
    import jax.numpy as jnp
    inp = np.array([[1, 0, 0, 0], [0, 0, 0, 1]], np.float32)
    ref = np.asarray(topo.apply(params, {"x": jnp.asarray(inp)},
                                mode="test"))
    return config_path, model_path, inp, ref


def test_capi_ctypes_roundtrip(merged_model):
    config_path, model_path, inp, ref = merged_model
    lib = ctypes.CDLL(_LIB)
    lib.pt_capi_create.restype = ctypes.c_int64
    lib.pt_capi_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.pt_capi_last_error.restype = ctypes.c_char_p
    assert lib.pt_capi_init(_ROOT.encode()) == 0
    h = lib.pt_capi_create(config_path.encode(), model_path.encode())
    assert h > 0, lib.pt_capi_last_error().decode()
    flat = np.ascontiguousarray(inp)
    rc = lib.pt_capi_set_input_dense(
        ctypes.c_int64(h), b"x",
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(2), ctypes.c_int64(4))
    assert rc == 0, lib.pt_capi_last_error().decode()
    n = lib.pt_capi_run(ctypes.c_int64(h))
    assert n == 1, lib.pt_capi_last_error().decode()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    assert lib.pt_capi_output_shape(ctypes.c_int64(h), 0,
                                    ctypes.byref(rows),
                                    ctypes.byref(cols)) == 0
    assert (rows.value, cols.value) == ref.shape
    buf = np.zeros(ref.shape, np.float32)
    wrote = lib.pt_capi_get_output(
        ctypes.c_int64(h), 0,
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(buf.size))
    assert wrote == buf.size
    np.testing.assert_allclose(buf, ref, rtol=1e-5, atol=1e-6)
    lib.pt_capi_destroy(ctypes.c_int64(h))


def test_capi_from_c_program(merged_model, tmp_path):
    """Compile and run the shipped C example against the trained model —
    the reference's capi/examples/model_inference flow."""
    config_path, model_path, inp, ref = merged_model
    exe = str(tmp_path / "infer_dense")
    src = os.path.join(_NATIVE, "examples", "infer_dense.c")
    subprocess.check_call(
        ["gcc", src, "-I" + os.path.join(_NATIVE, "include"),
         "-L" + _NATIVE, "-lpaddle_tpu_capi",
         "-Wl,-rpath," + _NATIVE, "-o", exe])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe, _ROOT, config_path, model_path],
                         capture_output=True, text=True, env=env,
                         timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("row")]
    assert len(lines) == 2
    got = np.array([[float(v) for v in l.split(":")[1].split()]
                    for l in lines])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# sequence / sparse_binary / multi_thread example parity
# (reference capi/examples/model_inference/{sequence,sparse_binary,
#  multi_thread}/main.c)
# ---------------------------------------------------------------------------

_SEQ_CONFIG = """
import paddle_tpu.layers as L
from paddle_tpu.layers.graph import reset_names
reset_names()
ids = L.data_layer("ids", size=16, is_seq=True)
emb = L.embedding_layer(ids, size=8, name="emb")
pooled = L.pooling_layer(emb, pooling_type=L.pooling.Max)
predict = L.fc_layer(pooled, size=2, act="softmax", name="out")
"""

_SPARSE_CONFIG = """
import paddle_tpu.layers as L
from paddle_tpu.layers.graph import reset_names
reset_names()
x = L.data_layer("x", size=64)
predict = L.fc_layer(x, size=2, act="softmax", name="out")
"""


def _build_model(tmp, config_src, out_layer_fn):
    reset_names()
    topo = Topology(out_layer_fn())
    params = topo.init(jax.random.PRNGKey(7))
    save_dir = str(tmp / "ckpt")
    save_checkpoint(save_dir, 0, params, None, {})
    model_path = str(tmp / "model.npz")
    merge_model(save_dir, model_path)
    config_path = str(tmp / "config.py")
    with open(config_path, "w") as f:
        f.write(config_src)
    return config_path, model_path, topo, params


def _compile_example(name, tmp_path, extra=()):
    exe = str(tmp_path / name)
    src = os.path.join(_NATIVE, "examples", name + ".c")
    subprocess.check_call(
        ["gcc", src, "-I" + os.path.join(_NATIVE, "include"),
         "-L" + _NATIVE, "-lpaddle_tpu_capi",
         "-Wl,-rpath," + _NATIVE] + list(extra) + ["-o", exe])
    return exe


def _parse_rows(stdout):
    lines = [l for l in stdout.splitlines() if l.startswith("row")]
    return np.array([[float(v) for v in l.split(":")[1].split()]
                     for l in lines])


@pytest.fixture(scope="module")
def seq_model(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("capi_seq")

    def build():
        import paddle_tpu.layers as LL
        ids = LL.data_layer("ids", size=16, is_seq=True)
        emb = LL.embedding_layer(ids, size=8, name="emb")
        pooled = LL.pooling_layer(emb, pooling_type=LL.pooling.Max)
        return LL.fc_layer(pooled, size=2, act="softmax", name="out")

    config_path, model_path, topo, params = _build_model(
        tmp, _SEQ_CONFIG, build)
    # reference output for the C program's fixed two-sentence batch
    from paddle_tpu.core.sequence import SequenceBatch
    import jax.numpy as jnp
    ids = np.array([[7, 3, 1, 4, 2, 5], [9, 8, 6, 0, 0, 0]], np.int32)
    lens = np.array([6, 3], np.int32)
    batch = SequenceBatch(data=jnp.asarray(ids), lengths=jnp.asarray(lens))
    ref = np.asarray(topo.apply(params, {"ids": batch}, mode="test"))
    return config_path, model_path, ref


def test_capi_sequence_example(seq_model, tmp_path):
    """Per-row lengths through the C API: padding slots must not leak into
    the pooled result (the reference sequence example's seq_pos role)."""
    config_path, model_path, ref = seq_model
    exe = _compile_example("infer_sequence", tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe, _ROOT, config_path, model_path],
                         capture_output=True, text=True, env=env,
                         timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    got = _parse_rows(out.stdout)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_capi_sequence_lengths_matter(seq_model):
    """ctypes twin of the C example, checking lengths actually gate the
    pool: growing a row's length over its padding changes the output."""
    config_path, model_path, ref = seq_model
    lib = ctypes.CDLL(_LIB)
    lib.pt_capi_create.restype = ctypes.c_int64
    lib.pt_capi_last_error.restype = ctypes.c_char_p
    assert lib.pt_capi_init(_ROOT.encode()) == 0
    h = lib.pt_capi_create(config_path.encode(), model_path.encode())
    assert h > 0, lib.pt_capi_last_error().decode()
    ids = np.array([[7, 3, 1, 4, 2, 5], [9, 8, 6, 0, 0, 0]], np.int32)

    def run_with(lens):
        lens = np.asarray(lens, np.int32)
        rc = lib.pt_capi_set_input_ids(
            ctypes.c_int64(h), b"ids",
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(2), ctypes.c_int64(6),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        assert rc == 0, lib.pt_capi_last_error().decode()
        assert lib.pt_capi_run(ctypes.c_int64(h)) == 1
        buf = np.zeros((2, 2), np.float32)
        assert lib.pt_capi_get_output(
            ctypes.c_int64(h), 0,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(buf.size)) == buf.size
        return buf

    got = run_with([6, 3])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # treating row-1 padding as real tokens must change row 1 only
    got_full = run_with([6, 6])
    np.testing.assert_allclose(got_full[0], ref[0], rtol=1e-5, atol=1e-6)
    assert not np.allclose(got_full[1], ref[1], atol=1e-6)
    lib.pt_capi_destroy(ctypes.c_int64(h))


@pytest.fixture(scope="module")
def sparse_model(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("capi_sparse")

    def build():
        import paddle_tpu.layers as LL
        x = LL.data_layer("x", size=64)
        return LL.fc_layer(x, size=2, act="softmax", name="out")

    config_path, model_path, topo, params = _build_model(
        tmp, _SPARSE_CONFIG, build)
    import jax.numpy as jnp
    dense = np.zeros((2, 64), np.float32)
    dense[0, [9, 13, 47]] = 1.0
    dense[1, [2, 60]] = 1.0
    ref = np.asarray(topo.apply(params, {"x": jnp.asarray(dense)},
                                mode="test"))
    return config_path, model_path, ref


def test_capi_sparse_binary_example(sparse_model, tmp_path):
    """CSR sparse-binary input through the C API matches the densified
    Python forward (reference sparse_binary example's copy_from path)."""
    config_path, model_path, ref = sparse_model
    exe = _compile_example("infer_sparse_binary", tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe, _ROOT, config_path, model_path],
                         capture_output=True, text=True, env=env,
                         timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    got = _parse_rows(out.stdout)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_capi_sparse_binary_bad_csr(sparse_model):
    """Malformed CSR (offsets not ending at n_cols, col id out of range)
    must fail cleanly with an error message, not corrupt the feed."""
    config_path, model_path, _ref = sparse_model
    lib = ctypes.CDLL(_LIB)
    lib.pt_capi_create.restype = ctypes.c_int64
    lib.pt_capi_last_error.restype = ctypes.c_char_p
    assert lib.pt_capi_init(_ROOT.encode()) == 0
    h = lib.pt_capi_create(config_path.encode(), model_path.encode())
    assert h > 0

    def set_csr(cols, offs):
        cols = np.asarray(cols, np.int32)
        offs = np.asarray(offs, np.int32)
        return lib.pt_capi_set_input_sparse_binary(
            ctypes.c_int64(h), b"x", ctypes.c_int64(64),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(len(cols)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(len(offs)))

    assert set_csr([1, 2, 3], [0, 2]) != 0          # offsets end != n_cols
    assert b"CSR" in lib.pt_capi_last_error()
    assert set_csr([1, 99], [0, 2]) != 0            # col id >= dim
    assert set_csr([1, 2], [0, 2]) == 0             # well-formed recovers
    lib.pt_capi_destroy(ctypes.c_int64(h))


def test_capi_multi_thread_example(merged_model, tmp_path):
    """Concurrent inference from 4 native threads over pt_capi_clone
    handles sharing one parameter set; the C program itself verifies the
    concurrent outputs against serial replays (reference multi_thread
    example's create_shared_param role)."""
    config_path, model_path, _inp, _ref = merged_model
    exe = _compile_example("infer_multi_thread", tmp_path,
                           extra=("-lpthread",))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe, _ROOT, config_path, model_path],
                         capture_output=True, text=True, env=env,
                         timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    ok_lines = [l for l in out.stdout.splitlines() if " OK:" in l]
    assert len(ok_lines) == 4, out.stdout


def test_capi_exported_stablehlo(merged_model, tmp_path):
    """merge_model -> StableHLO export -> C service: a C program executes
    the self-contained artifact through pt_capi_create_exported and
    reproduces the Python forward (docs/serving.md §1 + §2 end-to-end)."""
    config_path, model_path, inp, ref = merged_model
    # re-materialize the topology the config defines and export it
    ns = {}
    exec(compile(open(config_path).read(), config_path, "exec"), ns)
    from paddle_tpu import export as pexport
    from paddle_tpu.trainer.checkpoint import load_merged
    params, model_state, _meta = load_merged(model_path)
    art = str(tmp_path / "model.shlo")
    # the C client subprocess is pinned to cpu; export for that platform
    # explicitly so the test also passes when pytest itself runs on TPU
    pexport.export_inference(ns["predict"], params,
                             feed_spec={"x": np.zeros((2, 4), np.float32)},
                             model_state=model_state, path=art,
                             platforms=("cpu",))

    exe = _compile_example("infer_exported", tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe, _ROOT, art], capture_output=True, text=True,
                         env=env, timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    got = _parse_rows(out.stdout)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    # ctypes twin: clone of an exported machine serves too (thread
    # pattern).  This half runs IN the pytest process, so it needs the
    # process backend to match the artifact's platform.
    if jax.default_backend() != "cpu":
        pytest.skip("ctypes twin needs a cpu-backend pytest process "
                    "(artifact exported for cpu)")
    lib = ctypes.CDLL(_LIB)
    lib.pt_capi_create_exported.restype = ctypes.c_int64
    lib.pt_capi_clone.restype = ctypes.c_int64
    lib.pt_capi_last_error.restype = ctypes.c_char_p
    assert lib.pt_capi_init(_ROOT.encode()) == 0
    h = lib.pt_capi_create_exported(art.encode())
    assert h > 0, lib.pt_capi_last_error().decode()
    h2 = lib.pt_capi_clone(ctypes.c_int64(h))
    assert h2 > 0, lib.pt_capi_last_error().decode()
    flat = np.ascontiguousarray(inp)
    for hh in (h, h2):
        assert lib.pt_capi_set_input_dense(
            ctypes.c_int64(hh), b"x",
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(2), ctypes.c_int64(4)) == 0
        assert lib.pt_capi_run(ctypes.c_int64(hh)) == 1, \
            lib.pt_capi_last_error().decode()
        buf = np.zeros((2, 2), np.float32)
        assert lib.pt_capi_get_output(
            ctypes.c_int64(hh), 0,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(buf.size)) == buf.size
        np.testing.assert_allclose(buf, ref, rtol=1e-5, atol=1e-6)
    lib.pt_capi_destroy(ctypes.c_int64(h2))
    lib.pt_capi_destroy(ctypes.c_int64(h))
