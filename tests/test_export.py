"""StableHLO inference export (paddle_tpu.export, SURVEY §7 stage 11):
the serialized artifact reproduces live inference bit-for-bit, carries the
trained parameters as constants, and round-trips through bytes on disk."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import export as pexport
from paddle_tpu import optim
from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers import api as L
from paddle_tpu.trainer.trainer import SGD


def _trained_mlp():
    x = L.data_layer("x", size=8)
    y = L.data_layer("y", size=1)
    h = L.fc_layer(input=x, size=16, act="tanh", name="h")
    out = L.fc_layer(input=h, size=1, act="sigmoid", name="out")
    from paddle_tpu.layers.api import mse_cost
    tr = SGD(cost=mse_cost(input=out, label=y),
             update_equation=optim.Momentum(learning_rate=0.2, momentum=0.9))
    rng = np.random.RandomState(0)

    def batches():
        for _ in range(10):
            xb = rng.randn(32, 8).astype(np.float32)
            yield {"x": jnp.asarray(xb),
                   "y": jnp.asarray((xb[:, :2].sum(1, keepdims=True) > 0)
                                    .astype(np.float32))}
    tr.train(lambda: batches(), num_passes=1)
    return out, tr


def test_export_matches_live_inference(tmp_path):
    out, tr = _trained_mlp()
    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    from paddle_tpu.layers.graph import Topology
    live = np.asarray(Topology([out]).apply(tr.parameters, {"x": x},
                                            mode="test"))

    path = str(tmp_path / "model.shlo")
    exp = pexport.export_inference(out, tr.parameters,
                                   feed_spec={"x": np.zeros((4, 8),
                                                            np.float32)},
                                   path=path)
    assert exp.serialize()          # non-empty artifact

    run = pexport.load_inference(path)
    got = np.asarray(run({"x": jnp.asarray(x)}))
    np.testing.assert_allclose(got, live, rtol=1e-6, atol=1e-7)


def test_export_sequence_batch_input(tmp_path):
    x = L.data_layer("ids", size=50)
    emb = L.embedding_layer(input=x, size=8)
    pooled = L.pooling_layer(input=emb, pooling_type=None)
    out = L.fc_layer(input=pooled, size=2, act="softmax")
    from paddle_tpu.layers.graph import Topology
    import jax
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))

    ids = SequenceBatch(
        data=jnp.asarray(np.random.RandomState(2).randint(0, 50, (3, 7)),
                         jnp.int32),
        lengths=jnp.asarray([7, 4, 2], jnp.int32))
    live = np.asarray(topo.apply(params, {"ids": ids}, mode="test"))

    art = pexport.export_inference(out, params, feed_spec={"ids": ids})
    run = pexport.load_inference(art.serialize())
    got = np.asarray(run({"ids": ids}))
    np.testing.assert_allclose(got, live, rtol=1e-6, atol=1e-7)


def test_export_shape_mismatch_rejected(tmp_path):
    out, tr = _trained_mlp()
    run = pexport.load_inference(pexport.export_inference(
        out, tr.parameters,
        feed_spec={"x": np.zeros((4, 8), np.float32)}).serialize())
    with pytest.raises(Exception):
        run({"x": jnp.zeros((5, 8), jnp.float32)})   # wrong batch size


def test_export_bn_model_uses_trained_state(tmp_path):
    """Trained BN statistics travel into the artifact via model_state;
    omitting it warns instead of silently baking init stats."""
    import jax
    from paddle_tpu.layers.graph import Topology
    x = L.data_layer("x", size=8)
    y = L.data_layer("y", size=1)
    h = L.fc_layer(input=x, size=16, act="linear", name="pre")
    from paddle_tpu.layers.vision import batch_norm_layer
    bn = batch_norm_layer(input=h, act="relu", name="bn")
    out = L.fc_layer(input=bn, size=1, act="sigmoid")
    from paddle_tpu.layers.api import mse_cost
    tr = SGD(cost=mse_cost(input=out, label=y),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    rng = np.random.RandomState(3)
    tr.train(lambda: iter([{
        "x": jnp.asarray(rng.randn(64, 8).astype(np.float32) * 3 + 1),
        "y": jnp.asarray(rng.rand(64, 1).astype(np.float32))}
        for _ in range(5)]), num_passes=1)

    xq = rng.randn(4, 8).astype(np.float32)
    live = np.asarray(Topology([out]).apply(
        tr.parameters, {"x": xq}, mode="test", state=tr.model_state))
    run = pexport.load_inference(pexport.export_inference(
        out, tr.parameters, feed_spec={"x": xq},
        model_state=tr.model_state).serialize())
    np.testing.assert_allclose(np.asarray(run({"x": xq})), live,
                               rtol=1e-5, atol=1e-6)

    # omitting model_state on a stateful model warns (the framework logger
    # doesn't propagate to root, so capture the call directly)
    from unittest import mock
    from paddle_tpu.utils import logging as ptlog
    with mock.patch.object(ptlog.logger, "warning") as warn:
        pexport.export_inference(out, tr.parameters, feed_spec={"x": xq})
    assert warn.called
    assert "INITIAL statistics" in warn.call_args[0][0]


def test_int8_quantized_export_smaller_and_accurate(tmp_path, rng, np_rng):
    """quantize='int8' bakes weight-only int8 + per-channel scales into
    the artifact: >=2.5x smaller, predictions track f32 closely (argmax
    identical on a well-separated trained-ish model), biases stay f32."""
    import jax.numpy as jnp
    from paddle_tpu import export as pexport
    import paddle_tpu.layers as L
    from paddle_tpu.layers.graph import Topology, reset_names

    reset_names()
    x = L.data_layer("x", size=64)
    h = L.fc_layer(x, size=256, act="tanh")
    y = L.fc_layer(h, size=4, act="softmax")
    topo = Topology(y)
    params = topo.init(rng)
    # sharpen the logits (untrained softmax is near-uniform; quant noise
    # could flip a near-tie argmax and flake the exact-equality check)
    params = jax.tree_util.tree_map(lambda w: w * 3.0, params)

    feed_spec = {"x": np.zeros((8, 64), np.float32)}
    f32_path = str(tmp_path / "f32.shlo")
    q_path = str(tmp_path / "int8.shlo")
    pexport.export_inference(y, params, feed_spec, path=f32_path)
    pexport.export_inference(y, params, feed_spec, path=q_path,
                             quantize="int8")
    size_f32 = os.path.getsize(f32_path)
    size_q = os.path.getsize(q_path)
    assert size_q < size_f32 / 2.5, (size_f32, size_q)

    batch = {"x": np_rng.randn(8, 64).astype(np.float32)}
    ref = np.asarray(pexport.load_inference(f32_path)(batch))
    got = np.asarray(pexport.load_inference(q_path)(batch))
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=0.02)
    np.testing.assert_array_equal(got.argmax(-1), ref.argmax(-1))


def test_quantize_params_structure(rng):
    """Per-output-channel scales on big matrices; small leaves (biases)
    untouched; dequant rebuilds within int8 step size."""
    import jax.numpy as jnp
    from paddle_tpu.export import quantize_params
    params = {"fc": {"w0": jax.random.normal(rng, (64, 128)) * 0.3,
                     "b": jnp.ones((128,)) * 0.5}}
    qt, dequant = quantize_params(params)
    assert qt["fc"]["w0"]["__int8__"].dtype == jnp.int8
    assert qt["fc"]["w0"]["__scale__"].shape == (1, 128)
    assert qt["fc"]["b"].dtype == jnp.float32      # too small to quantize
    back = dequant(qt)
    w = np.asarray(params["fc"]["w0"])
    scale_per_col = np.abs(w).max(0) / 127.0
    np.testing.assert_allclose(np.asarray(back["fc"]["w0"]), w,
                               atol=float(scale_per_col.max()) * 0.51)
    np.testing.assert_array_equal(np.asarray(back["fc"]["b"]),
                                  np.asarray(params["fc"]["b"]))


def test_inferencer_int8(rng, np_rng):
    """Inferencer(quantize='int8') serves close to the f32 Inferencer."""
    import jax.numpy as jnp
    import paddle_tpu.layers as L
    from paddle_tpu.layers.graph import Topology, reset_names
    from paddle_tpu.trainer.trainer import Inferencer

    reset_names()
    x = L.data_layer("x", size=32)
    y = L.fc_layer(x, size=8, act="softmax")
    topo = Topology(y)
    params = topo.init(rng)
    batch = {"x": np_rng.randn(4, 32).astype(np.float32)}
    ref = np.asarray(Inferencer(y, params).infer(batch))
    q = Inferencer(y, params, quantize="int8")
    got = np.asarray(q.infer(batch))
    np.testing.assert_allclose(got, ref, atol=0.02)
    # the public attribute still holds the caller's float tree (int8 is an
    # execution detail) — feeding it onward must not leak sentinel dicts
    for leaf in jax.tree_util.tree_leaves(q.parameters):
        assert hasattr(leaf, "dtype") and leaf.dtype == jnp.float32
