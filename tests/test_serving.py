"""Serving runtime (paddle_tpu/serving): bucketed AOT engine, dynamic
batcher, HTTP front-end, metrics.

The correctness bar: a request served through the full stack — queue,
dynamic batch formation, bucket padding, slicing — must return EXACTLY
what the direct forward returns for that row.  On the CPU test backend,
XLA gemm row results are bit-stable across batch sizes >= 2 (row dots
accumulate in the same order), so the tests pin bucket ladders with a
minimum bucket of 4 and assert BIT-IDENTICAL outputs, not allclose.

Fault injection covers each admission-control path: invalid feed
(rejected before the queue), queue overflow, per-request deadline, batch
execution failure (isolated to its batch) — and after every fault the
engine keeps serving.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import jax

from paddle_tpu.layers import api as L
from paddle_tpu.layers.graph import Topology, reset_names
from paddle_tpu.serving import (BatchExecutionError, Batcher,
                                DeadlineExceededError, InferenceEngine,
                                InvalidRequestError, OverloadedError,
                                ServingMetrics, ShutdownError, make_server)


def setup_function(_):
    reset_names()


def _mlp(dim=8, hidden=16, classes=4, seed=0):
    x = L.data_layer("x", size=dim)
    h = L.fc_layer(input=x, size=hidden, act="tanh")
    out = L.fc_layer(input=h, size=classes, act="softmax")
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(seed))
    return out, topo, params


def _engine(buckets=(4, 16), warm=True, dim=8):
    out, topo, params = _mlp(dim=dim)
    spec = {"x": jax.ShapeDtypeStruct((1, dim), np.float32)}
    eng = InferenceEngine.from_topology(out, params, spec, buckets=buckets,
                                        warm=warm)
    return eng, topo, params


# ---------------------------------------------------------------- engine


def test_engine_pads_to_bucket_and_slices_back():
    eng, topo, params = _engine(buckets=(4, 16))
    rng = np.random.RandomState(0)
    for b in (1, 3, 4, 5, 16):
        xb = rng.randn(b, 8).astype(np.float32)
        direct = np.asarray(topo.apply(params, {"x": xb.copy()},
                                       mode="test"))
        got = np.asarray(eng.infer({"x": xb}))
        assert got.shape == (b, 4)
        # bucket >= 4 executes every batch at M >= 4: bit-stable rows
        np.testing.assert_array_equal(got, direct)


def test_engine_chunks_batches_beyond_ladder_top():
    eng, topo, params = _engine(buckets=(4, 16))
    xb = np.random.RandomState(1).randn(37, 8).astype(np.float32)
    direct = np.asarray(topo.apply(params, {"x": xb.copy()}, mode="test"))
    got = np.asarray(eng.infer({"x": xb}))
    assert got.shape == (37, 4)
    np.testing.assert_array_equal(got, direct)


def test_engine_trace_count_stable_after_warmup():
    eng, _, _ = _engine(buckets=(4, 16), warm=True)
    assert eng.trace_count == 2          # one trace per bucket, exactly
    rng = np.random.RandomState(2)
    for b in (1, 2, 4, 9, 16, 33):
        eng.infer({"x": rng.randn(b, 8).astype(np.float32)})
    assert eng.trace_count == 2          # steady-state serving: no retrace


def test_engine_lazy_compile_on_first_use():
    eng, _, _ = _engine(buckets=(4, 16), warm=False)
    assert eng.trace_count == 0
    eng.infer({"x": np.zeros((3, 8), np.float32)})   # -> bucket 4 only
    assert eng.trace_count == 1
    eng.infer({"x": np.zeros((2, 8), np.float32)})   # same bucket: cached
    assert eng.trace_count == 1


def test_engine_validates_feeds():
    eng, _, _ = _engine()
    with pytest.raises(InvalidRequestError):
        eng.validate({"x": np.zeros((2, 5), np.float32)})   # wrong width
    with pytest.raises(InvalidRequestError):
        eng.validate({"x": np.zeros((2, 8), np.int32)})     # wrong dtype
    with pytest.raises(InvalidRequestError):
        eng.validate({"y": np.zeros((2, 8), np.float32)})   # wrong slot
    with pytest.raises(InvalidRequestError):
        eng.validate({"x": np.zeros((8,), np.float32)})     # no batch axis
    with pytest.raises(InvalidRequestError):
        eng.validate({"x": np.zeros((3, 8), np.float32)},
                     batch=False)                           # row API misuse
    assert eng.validate({"x": np.zeros((8,), np.float32)}, batch=False) == 1


def test_engine_lower_hook_exposes_bucket_cost():
    # the extras["lower"] analytic idiom: lower (never execute) a bucket's
    # program and read XLA's cost model from it (perf/analytic.py)
    from paddle_tpu.perf import cost
    eng, _, _ = _engine(buckets=(4, 16), warm=False)
    row = cost.extract(eng.lower(16).compile())
    assert row["flops"] > 0 and row["bytes_accessed"] > 0


# ---------------------------------------------------------------- batcher


def test_concurrent_clients_bit_identical_and_batched():
    """The acceptance drive: 16 threads hammer the batcher; every response
    is bit-identical to the direct forward of that request, and mean batch
    occupancy shows real cross-request batching."""
    eng, topo, params = _engine(buckets=(4, 16))
    xb = np.random.RandomState(3).randn(16, 8).astype(np.float32)
    direct = np.asarray(topo.apply(params, {"x": xb.copy()}, mode="test"))

    bat = Batcher(eng, max_delay_ms=100.0, queue_size=64)
    results = [None] * 16

    def client(i):
        results[i] = np.asarray(bat.submit({"x": xb[i]}).result(30))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    bat.close()

    for i in range(16):
        np.testing.assert_array_equal(results[i], direct[i])
    snap = eng.metrics.snapshot()
    assert snap["responses_total"] == 16
    assert snap["mean_occupancy"] > 1.0, snap    # batching actually happened
    assert snap["errors_total"] == 0


def _stalled_engine(stall_s=0.15, buckets=(4, 16)):
    """Engine whose infer sleeps first — deterministic queue buildup."""
    eng, _, _ = _engine(buckets=buckets)
    orig = eng.infer

    def slow(feed):
        time.sleep(stall_s)
        return orig(feed)
    eng.infer = slow
    return eng


def test_fault_injection_all_paths_and_engine_stays_healthy():
    eng = _stalled_engine(stall_s=0.2)
    row = {"x": np.zeros((8,), np.float32)}
    bat = Batcher(eng, max_delay_ms=0.0, queue_size=3)

    # invalid feed: rejected synchronously, never queued
    with pytest.raises(InvalidRequestError):
        bat.submit({"x": np.zeros((5,), np.float32)})
    with pytest.raises(InvalidRequestError):
        bat.submit({"x": np.zeros((8,), np.int64)})

    # occupy the worker, then fill the bounded queue — the deadline'd
    # request sits behind the stall and must expire, the others succeed
    first = bat.submit(row)
    time.sleep(0.05)            # worker now inside the stalled infer
    q1, q2 = bat.submit(row), bat.submit(row)
    dead = bat.submit(row, deadline_ms=10)
    with pytest.raises(OverloadedError):
        bat.submit(row)         # queue_size=3 exceeded -> explicit 429 path
    with pytest.raises(DeadlineExceededError):
        dead.result(30)
    # the co-queued requests without deadlines still succeed
    assert np.asarray(first.result(30)).shape == (4,)
    assert np.asarray(q1.result(30)).shape == (4,)
    assert np.asarray(q2.result(30)).shape == (4,)

    snap = bat.metrics.snapshot()
    assert snap["rejected"]["invalid"] == 2
    assert snap["rejected"]["overload"] == 1
    assert snap["rejected"]["deadline"] == 1

    # batch execution failure: fails ONLY that batch's futures...
    def boom(feed):
        raise RuntimeError("injected batch failure")
    healthy_infer, eng.infer = eng.infer, boom
    f = bat.submit(row)
    with pytest.raises(BatchExecutionError):
        f.result(30)
    # ...and the engine keeps serving afterwards
    eng.infer = healthy_infer
    ok = bat.submit(row).result(30)
    assert np.asarray(ok).shape == (4,)
    assert bat.metrics.snapshot()["errors_total"] == 1
    bat.close()


def test_drain_on_shutdown():
    eng = _stalled_engine(stall_s=0.1)
    row = {"x": np.zeros((8,), np.float32)}
    bat = Batcher(eng, max_delay_ms=0.0, queue_size=64)
    futs = [bat.submit(row) for _ in range(6)]
    t = threading.Thread(target=bat.close, kwargs={"drain": True})
    t.start()
    time.sleep(0.02)
    # late submit while draining: rejected, not silently queued
    with pytest.raises(ShutdownError):
        bat.submit(row)
    t.join(30)
    # every in-flight future completed with a real result
    for f in futs:
        assert np.asarray(f.result(0)).shape == (4,)
    assert bat.metrics.snapshot()["rejected"]["shutdown"] == 1


def test_client_cancel_does_not_kill_the_worker():
    """A client-side fut.cancel() racing the batch must not raise
    InvalidStateError inside the worker thread (which would wedge the
    whole batcher): cancelled requests are dropped, later ones serve."""
    eng = _stalled_engine(stall_s=0.1)
    row = {"x": np.zeros((8,), np.float32)}
    bat = Batcher(eng, max_delay_ms=0.0, queue_size=64)
    bat.submit(row)             # occupies the worker
    time.sleep(0.02)
    victim = bat.submit(row)    # still PENDING in the queue
    assert victim.cancel()
    # worker processes the queue (dropping the cancelled future) and
    # must still be alive to serve this:
    ok = bat.submit(row).result(30)
    assert np.asarray(ok).shape == (4,)
    assert victim.cancelled()
    bat.close()


def test_zero_queue_size_rejected():
    # queue.Queue(0) would mean UNBOUNDED — refuse the footgun outright
    eng, _, _ = _engine()
    with pytest.raises(ValueError):
        Batcher(eng, queue_size=0)


def test_close_without_drain_fails_queued_requests():
    eng = _stalled_engine(stall_s=0.2)
    row = {"x": np.zeros((8,), np.float32)}
    bat = Batcher(eng, max_delay_ms=0.0, queue_size=64)
    bat.submit(row)             # occupies the worker
    time.sleep(0.05)
    queued = [bat.submit(row) for _ in range(3)]
    bat.close(drain=False)
    failed = 0
    for f in queued:
        try:
            f.result(30)
        except ShutdownError:
            failed += 1
    assert failed == 3


# ---------------------------------------------------------------- export


def test_export_bucketed_and_from_artifacts_roundtrip(tmp_path):
    out, topo, params = _mlp()
    from paddle_tpu import export as pexport
    spec = {"x": np.zeros((1, 8), np.float32)}
    paths = pexport.export_bucketed(out, params, spec, buckets=(2, 8),
                                    path_prefix=str(tmp_path / "mlp"))
    assert sorted(paths) == [2, 8]
    for n, p in paths.items():
        assert p.endswith(f".b{n}.shlo")    # the documented convention

    eng = InferenceEngine.from_artifacts(str(tmp_path / "mlp.b*.shlo"))
    assert eng.buckets == (2, 8)
    xb = np.random.RandomState(4).randn(5, 8).astype(np.float32)
    direct = np.asarray(topo.apply(params, {"x": xb.copy()}, mode="test"))
    got = np.asarray(eng.infer({"x": xb}))      # 5 -> bucket 8
    np.testing.assert_array_equal(got, direct)
    # artifacts hold serialized StableHLO: the analytic lower() hook is an
    # in-process-engine feature and must say so rather than mislead
    from paddle_tpu.utils.error import ConfigError
    with pytest.raises(ConfigError):
        eng.lower()


def test_from_artifact_single_bucket(tmp_path):
    out, topo, params = _mlp()
    from paddle_tpu import export as pexport
    path = str(tmp_path / "one.shlo")
    pexport.export_inference(out, params,
                             feed_spec={"x": np.zeros((4, 8), np.float32)},
                             path=path)
    eng = InferenceEngine.from_artifact(path)
    assert eng.buckets == (4,)
    xb = np.random.RandomState(5).randn(3, 8).astype(np.float32)
    direct = np.asarray(topo.apply(params, {"x": xb.copy()}, mode="test"))
    np.testing.assert_array_equal(np.asarray(eng.infer({"x": xb})), direct)


# ---------------------------------------------------------------- v2 API


def test_v2_infer_parity_with_direct_forward():
    """Satellite: v2.infer routes through the bucketed engine and must
    match the old direct-Inferencer path bit-for-bit."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer.trainer import Inferencer
    out, topo, params = _mlp()
    xb = np.random.RandomState(6).randn(8, 8).astype(np.float32)
    direct = np.asarray(Inferencer(out, params).infer({"x": xb.copy()}))
    via_engine = np.asarray(paddle.infer(output_layer=out, parameters=params,
                                         input={"x": xb}))
    assert via_engine.shape == (8, 4)
    np.testing.assert_array_equal(via_engine, direct)

    # the class form reuses ONE engine across ragged batch sizes
    inf = paddle.inference.Inference(out, params)
    for b in (1, 3, 8, 70):     # 70 > ladder top: chunking path
        xi = np.random.RandomState(b).randn(b, 8).astype(np.float32)
        d = np.asarray(topo.apply(params, {"x": xi.copy()}, mode="test"))
        got = np.asarray(inf.infer({"x": xi}))
        assert got.shape == (b, 4)
        if b > 1:       # M=1 gemv accumulates differently on CPU XLA;
            np.testing.assert_array_equal(got, d)   # all M>=2 bit-match
        else:
            np.testing.assert_allclose(got, d, rtol=1e-6, atol=1e-7)


def test_v2_infer_sequence_feeds_across_padded_lengths():
    """Sequence slots pad per batch: a reused v2 Inference must serve
    DIFFERENT padded lengths (one engine per row signature), and the
    engine must pad/slice SequenceBatch pytrees correctly."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.core.sequence import SequenceBatch
    import jax.numpy as jnp
    ids = L.data_layer("ids", size=50)
    emb = L.embedding_layer(input=ids, size=8)
    pooled = L.pooling_layer(input=emb, pooling_type=None)
    out = L.fc_layer(input=pooled, size=2, act="softmax")
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))
    inf = paddle.inference.Inference(out, params)
    rng = np.random.RandomState(8)
    for b, t in ((3, 7), (5, 12), (2, 7)):
        sb = SequenceBatch(
            data=jnp.asarray(rng.randint(0, 50, (b, t)), jnp.int32),
            lengths=jnp.asarray(rng.randint(1, t + 1, (b,)), jnp.int32))
        direct = np.asarray(topo.apply(params, {"ids": sb}, mode="test"))
        got = np.asarray(inf.infer({"ids": sb}))
        assert got.shape == (b, 2)
        np.testing.assert_allclose(got, direct, rtol=1e-6, atol=1e-7)


def test_v2_engine_cache_lru_bounded_with_eviction_counter():
    """Satellite: the per-row-signature engine table is a bounded LRU —
    under many distinct padded lengths it stops growing, counts its
    evictions (surfaced at /metrics as engine_cache_evictions_total),
    and an evicted signature that returns simply recompiles and still
    serves the right numbers."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.core.sequence import SequenceBatch
    import jax.numpy as jnp
    ids = L.data_layer("ids", size=50)
    emb = L.embedding_layer(input=ids, size=8)
    pooled = L.pooling_layer(input=emb, pooling_type=None)
    out = L.fc_layer(input=pooled, size=2, act="softmax")
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))
    inf = paddle.inference.Inference(out, params, max_engines=3)
    rng = np.random.RandomState(11)

    def feed(t):
        return {"ids": SequenceBatch(
            data=jnp.asarray(rng.randint(0, 50, (2, t)), jnp.int32),
            lengths=jnp.asarray([t, max(1, t - 1)], jnp.int32))}

    for t in range(4, 11):          # 7 distinct signatures through cap 3
        inf.infer(feed(t))
    assert len(inf._engines) == 3
    assert inf.metrics.engine_cache_evictions == 4
    assert "engine_cache_evictions_total 4" \
        in inf.metrics.render_prometheus()
    # the evicted t=4 signature returns: recompiles, same numerics
    fd = feed(4)
    direct = np.asarray(topo.apply(params, dict(fd), mode="test"))
    np.testing.assert_allclose(np.asarray(inf.infer(fd)), direct,
                               rtol=1e-6, atol=1e-7)
    # most-recently-used signatures survived the round trip
    assert len(inf._engines) == 3

    # default bound: the ragged-length loop that used to grow without
    # limit now stays capped
    inf8 = paddle.inference.Inference(out, params)
    for t in range(3, 13):
        inf8.infer(feed(t))
    assert len(inf8._engines) <= 8


# ---------------------------------------------------------------- HTTP


def _start_server(buckets=(4, 16), **batcher_kw):
    eng, topo, params = _engine(buckets=buckets)
    bat = Batcher(eng, **{"max_delay_ms": 50.0, "queue_size": 64,
                          **batcher_kw})
    httpd = make_server(bat, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, bat, topo, params


def _post(port, payload, path="/v1/infer", raw=None):
    body = raw if raw is not None else json.dumps(payload).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_http_concurrent_clients_match_direct_forward():
    httpd, bat, topo, params = _start_server()
    try:
        xb = np.random.RandomState(7).randn(8, 8).astype(np.float32)
        direct = np.asarray(topo.apply(params, {"x": xb.copy()},
                                       mode="test"))
        results = [None] * 8

        def client(i):
            status, resp = _post(httpd.port,
                                 {"feed": {"x": xb[i].tolist()}})
            assert status == 200
            results[i] = np.asarray(resp["outputs"], np.float32)

        ts = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i in range(8):
            # JSON round-trips float32 exactly (float -> shortest repr
            # double -> float32), so even HTTP responses are bit-identical
            np.testing.assert_array_equal(results[i], direct[i])

        # live metrics reflect the traffic
        with urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.port}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert "paddle_tpu_serving_requests_total 8" in text
        assert 'latency_seconds{quantile="0.99"}' in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.port}/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"
    finally:
        httpd.shutdown()
        bat.close()


def test_http_fault_paths():
    httpd, bat, topo, params = _start_server()
    try:
        port = httpd.port

        def expect(code, payload=None, raw=None, path="/v1/infer"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(port, payload, path=path, raw=raw)
            assert ei.value.code == code
            return json.loads(ei.value.read())

        assert "error" in expect(400, raw=b"{not json")
        assert "error" in expect(400, {"nofeed": 1})
        assert "error" in expect(400, {"feed": {"x": [1.0] * 5}})
        assert "error" in expect(400, {"feed": {"x": [1.0] * 8,
                                                "bogus": [1]}})
        assert "error" in expect(400, {"feed": {"x": [1.0] * 8},
                                       "deadline_ms": -5})
        assert "error" in expect(404, {"feed": {}}, path="/v1/nope")

        # the engine survived every fault: a good request still serves
        status, resp = _post(port, {"feed": {"x": [0.5] * 8}})
        assert status == 200 and len(resp["outputs"]) == 4
    finally:
        httpd.shutdown()
        bat.close()


# ---------------------------------------------------------------- metrics


def test_metrics_prometheus_render_and_waste():
    m = ServingMetrics(name="t")
    m.accepted()
    m.observe_batch(n_real=3, bucket=4, seconds=0.002)
    m.observe_response(0.010)
    m.reject("overload")
    assert m.mean_occupancy == 3.0
    assert m.padding_waste == pytest.approx(0.25)
    text = m.render_prometheus()
    assert "t_requests_total 1" in text
    assert 't_rejected_total{reason="overload"} 1' in text
    assert 't_latency_seconds{quantile="0.50"} 0.010000' in text
    assert "t_batch_occupancy_mean 3.000000" in text
    snap = m.snapshot()
    assert snap["latency_ms"]["p99"] == pytest.approx(10.0)


def test_histogram_keep_last_is_a_ring():
    from paddle_tpu.utils.stats import Histogram
    h = Histogram("x", max_samples=4, keep="last")
    for v in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
        h.add(v)
    assert h.count == 6
    assert sorted(h.samples) == [3.0, 4.0, 5.0, 6.0]   # oldest evicted


# ---------------------------------------------------------------- load


@pytest.mark.slow
def test_load_sweep_batched_beats_batch_size_1():
    """The bench acceptance property, asserted: at saturating closed-loop
    offered load the dynamic batcher out-throughputs the same engine at
    max_batch_size=1 and really batches (occupancy > 1)."""
    import importlib
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    bench = importlib.import_module("bench")
    built = bench.bench_serving_engine(batch=16, n_requests=192)
    extras = built[4]
    assert extras["mean_batch_occupancy"] > 1.0, extras
    assert extras["batched_throughput_rps"] > extras["bs1_throughput_rps"], \
        extras
    # the analytic hook lowers without executing
    assert extras["lower"]() is not None


@pytest.mark.slow
def test_serving_smoke_subprocess():
    """`python -m paddle_tpu.serving --smoke` — the healthy_window.sh
    phase-7 command — passes end to end in a fresh process."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.serving", "--smoke"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["value"] == int(out["unit"].split("/")[1])
    assert out["metrics_sane"] is True
    assert out["mean_occupancy"] > 1.0
