"""Cross-replica KV handoff (serving/transfer.py + the hardened wire
format in serving/kv_pool.py; docs/serving.md "Disaggregated serving").

Fast lane: the length-prefixed socket framing (declared-length bound,
truncation), envelope validation (``peek_chain_header`` — version byte,
size bound, trunk signature), the receive path's every-failure-is-a-
fallback contract against stub engines and real in-process HTTP export
servers, and ``deliver_chain_blob``'s pool-poisoning rejection.

Slow lane: the full cross-process round trip — a prefill-role replica
SUBPROCESS serializes its resident chain over ``POST /v1/kv/export``, a
decode-role replica subprocess receives and seats it, the stream is
bit-identical to the cold ``lm_generate`` recompute, and the
``kv_handoff_*`` counters on BOTH replicas' /metrics are exact.
"""

import http.client
import http.server
import io
import json
import re
import threading
import urllib.request

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.serving import ServingMetrics
from paddle_tpu.serving import transfer
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.serving.kv_pool import (HostTier, MAX_CHAIN_BLOB_BYTES,
                                        WIRE_VERSION, WireFormatError,
                                        WireVersionError,
                                        peek_chain_header, restore_chain,
                                        serialize_chain)
from paddle_tpu.utils.error import ConfigError

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, BS = 48, 8
SIG = f"L{LAYERS}.d{D_MODEL}.dkv{D_MODEL // HEADS}.h{HEADS}.float32.b{BS}"


def _blob(rng, n_blocks=2, sig=SIG):
    tokens = [int(t) for t in rng.integers(1, VOCAB, n_blocks * BS)]
    arrays = [("k0", rng.standard_normal((n_blocks, BS, 16))
               .astype(np.float32)),
              ("v0", rng.standard_normal((n_blocks, BS, 16))
               .astype(np.float32))]
    return tokens, serialize_chain(tokens, n_blocks * BS, arrays, sig)


# ----------------------------------------------------- socket framing


def test_write_read_blob_round_trip():
    rng = np.random.default_rng(0)
    _, blob = _blob(rng)
    buf = io.BytesIO()
    transfer.write_blob(buf, blob)
    assert buf.getvalue()[:8] == len(blob).to_bytes(8, "little")
    buf.seek(0)
    assert transfer.read_blob(buf) == blob


def test_read_blob_bounds_declared_length_before_allocating():
    # a peer declaring a huge payload is rejected at the 8-byte prefix,
    # before the receive buffer grows toward it
    evil = (1 << 40).to_bytes(8, "little")
    with pytest.raises(transfer.HandoffError, match="receive bound"):
        transfer.read_blob(io.BytesIO(evil), max_bytes=1 << 20)
    # ... and the default bound is the wire format's own blob ceiling
    with pytest.raises(transfer.HandoffError, match="receive bound"):
        transfer.read_blob(io.BytesIO(
            (MAX_CHAIN_BLOB_BYTES + 1).to_bytes(8, "little")))


def test_read_blob_rejects_truncation():
    with pytest.raises(transfer.HandoffError, match="length prefix"):
        transfer.read_blob(io.BytesIO(b"\x05\x00\x00"))
    body = (100).to_bytes(8, "little") + b"x" * 40
    with pytest.raises(transfer.HandoffError, match="truncated at 40/100"):
        transfer.read_blob(io.BytesIO(body))


# ------------------------------------------------ envelope validation


def test_peek_chain_header_bounds_and_signature():
    rng = np.random.default_rng(1)
    tokens, blob = _blob(rng)
    header = peek_chain_header(blob, SIG)
    assert header["covered"] == len(tokens)
    assert [int(t) for t in header["tokens"]] == tokens
    # size bound checked FIRST, before any parsing
    with pytest.raises(WireFormatError, match="receive bound"):
        peek_chain_header(blob, SIG, max_bytes=16)
    with pytest.raises(WireFormatError, match="trunk signature"):
        peek_chain_header(blob, SIG.replace(f"L{LAYERS}",
                                            f"L{LAYERS + 1}"))
    with pytest.raises(WireVersionError):
        peek_chain_header(bytes([WIRE_VERSION + 1]) + blob[1:], SIG)
    with pytest.raises(WireFormatError, match="not valid JSON"):
        peek_chain_header(blob[:9] + b"\xff" * (len(blob) - 9), SIG)


def test_restore_chain_honors_max_bytes():
    rng = np.random.default_rng(2)
    _, blob = _blob(rng)
    with pytest.raises(WireFormatError, match="receive bound"):
        restore_chain(blob, SIG, max_bytes=32)
    # errors stay ValueError for every pre-hardening call site
    assert issubclass(WireVersionError, WireFormatError)
    assert issubclass(WireFormatError, ValueError)


# --------------------------------------------- in-process export peer


class _ExportPeer:
    """A minimal real-socket /v1/kv/export peer: serves one canned blob
    (optionally lying about its length or truncating mid-stream), so the
    fetch path is tested over genuine HTTP without an engine."""

    def __init__(self, blob, mode="ok"):
        peer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                if peer.mode == "http_error":
                    self.send_error(404, "no resident KV coverage")
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.end_headers()
                if peer.mode == "overdeclare":
                    self.wfile.write((1 << 40).to_bytes(8, "little"))
                elif peer.mode == "truncate":
                    self.wfile.write(len(peer.blob).to_bytes(8, "little"))
                    self.wfile.write(peer.blob[:len(peer.blob) // 2])
                else:
                    transfer.write_blob(self.wfile, peer.blob)

            def log_message(self, *a):
                pass

        self.blob, self.mode = blob, mode
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_fetch_chain_round_trip_over_real_socket():
    rng = np.random.default_rng(3)
    tokens, blob = _blob(rng)
    peer = _ExportPeer(blob)
    try:
        covered, got = transfer.fetch_chain(peer.url, tokens, SIG)
        assert covered == len(tokens) and got == blob
    finally:
        peer.close()


def test_fetch_chain_failure_modes_raise_handoff_error():
    rng = np.random.default_rng(4)
    tokens, blob = _blob(rng)
    # dead peer (the kill -9 case): connection refused, not a hang
    dead = _ExportPeer(blob)
    dead.close()
    with pytest.raises(transfer.HandoffError, match="failed"):
        transfer.fetch_chain(dead.url, tokens, SIG, timeout=2.0)
    for mode, pat in (("http_error", "HTTP 404"),
                      ("overdeclare", "receive bound"),
                      ("truncate", "truncated")):
        peer = _ExportPeer(blob, mode=mode)
        try:
            with pytest.raises(transfer.HandoffError, match=pat):
                transfer.fetch_chain(peer.url, tokens, SIG, timeout=5.0)
        finally:
            peer.close()
    # foreign blob: fetched fine, rejected at the envelope
    peer = _ExportPeer(blob)
    try:
        with pytest.raises(WireFormatError, match="trunk signature"):
            transfer.fetch_chain(
                peer.url, tokens,
                SIG.replace(f"d{D_MODEL}", f"d{D_MODEL * 2}"))
    finally:
        peer.close()


# ------------------------------------------- receive path (fallbacks)


class _StubEngine:
    """Duck-typed receiver: exactly the surface ``receive_chain`` uses."""

    def __init__(self, tier, faster=True, sig=SIG):
        self.host_tier = tier
        self.block_size = BS
        self._trunk_sig = sig
        self._faster = faster
        self.delivered = []

    def _handoff_predicted_faster(self, est):
        return self._faster, 0.1, 0.2

    def deliver_chain_blob(self, blob, max_bytes=None):
        header = peek_chain_header(blob, self._trunk_sig, max_bytes)
        self.host_tier.put(tuple(int(t) for t in header["tokens"]),
                           int(header["covered"]), blob)
        self.delivered.append(blob)
        return tuple(header["tokens"]), int(header["covered"])


def test_receive_chain_success_counts_and_parks():
    rng = np.random.default_rng(5)
    tokens, blob = _blob(rng)
    peer = _ExportPeer(blob)
    eng = _StubEngine(HostTier(64 << 20))
    m = ServingMetrics()
    try:
        out = transfer.receive_chain(eng, peer.url, tokens, metrics=m)
        assert out["outcome"] == "received" and out["reason"] is None
        assert out["bytes"] == len(blob)
        assert out["covered"] == len(tokens)
        assert eng.delivered == [blob]
        snap = m.snapshot()
        assert snap["kv_handoffs_total"] == {"sent": 0, "received": 1,
                                             "fallback": 0}
        assert snap["kv_handoff_bytes_total"] == len(blob)
        # an immediate retry finds the chain resident: no second fetch
        again = transfer.receive_chain(eng, peer.url, tokens, metrics=m)
        assert again["outcome"] == "received"
        assert again["reason"] == "resident" and again["bytes"] == 0
        assert m.snapshot()["kv_handoff_bytes_total"] == len(blob)
    finally:
        peer.close()


def test_receive_chain_every_failure_is_a_counted_fallback():
    rng = np.random.default_rng(6)
    tokens, blob = _blob(rng)
    m = ServingMetrics()

    def recv(eng, source, toks):
        return transfer.receive_chain(eng, source, toks, metrics=m)

    class _NoTier:
        host_tier = None

    cases = [
        (recv(_NoTier(), "http://127.0.0.1:9", tokens), "no_host_tier"),
        (recv(_StubEngine(HostTier(1 << 20)), "http://127.0.0.1:9",
              tokens[:BS - 1]), "below_block"),
        (recv(_StubEngine(HostTier(1 << 20), faster=False),
              "http://127.0.0.1:9", tokens), "analytic"),
        # dead peer: the socket error becomes a fallback, never a raise
        (recv(_StubEngine(HostTier(1 << 20)), "http://127.0.0.1:9",
              tokens), "HandoffError"),
    ]
    peer = _ExportPeer(blob)       # serves SIG blobs to a foreign engine
    try:
        cases.append((recv(_StubEngine(HostTier(1 << 20),
                                       sig=SIG + ".x"), peer.url,
                           tokens), "WireFormatError"))
    finally:
        peer.close()
    for out, reason in cases:
        assert out["outcome"] == "fallback", (reason, out)
        assert out["reason"] == reason, out
        assert out["bytes"] == 0 and out["covered"] == 0, out
    assert m.snapshot()["kv_handoffs_total"]["fallback"] == len(cases)


# ------------------------------------- delivery hardening (real engine)


@pytest.fixture(scope="module")
def cold_engine():
    """Uncompiled tiny-trunk engine (warm=False): delivery validation
    needs the trunk signature and tier, never a compiled step."""
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                              trg_vocab=1, d_model=D_MODEL,
                              num_heads=HEADS, dff=64, enc_layers=LAYERS,
                              dec_layers=0, max_len=MAX_LEN)
    return DecodeEngine(params, num_heads=HEADS, num_slots=2,
                        max_len=MAX_LEN, prefill_buckets=(8,),
                        name="transfer_cold", warm=False,
                        kv_layout="paged", kv_block_size=BS,
                        kv_num_blocks=2 * (MAX_LEN // BS) + 1,
                        prefill_chunk=BS, kv_host_bytes=64 << 20)


def _poisoned(tokens, covered, arrays, sig):
    """serialize_chain with the coverage invariant bypassed — the blob a
    hostile peer would craft."""
    blob = serialize_chain(tokens, (len(tokens) // BS) * BS, arrays, sig)
    hlen = int.from_bytes(blob[1:9], "little")
    header = json.loads(blob[9:9 + hlen])
    header["covered"] = covered
    h = json.dumps(header).encode()
    return blob[:1] + len(h).to_bytes(8, "little") + h + blob[9 + hlen:]


def test_deliver_chain_blob_rejects_pool_poisoning(cold_engine):
    rng = np.random.default_rng(7)
    tokens = [int(t) for t in rng.integers(1, VOCAB, 2 * BS)]
    arrays = [("k0", rng.standard_normal((2, BS, 16)).astype(np.float32))]
    # coverage lying PAST the key would seat garbage beyond the tokens
    with pytest.raises(WireFormatError, match="refusing to pool"):
        cold_engine.deliver_chain_blob(
            _poisoned(tokens, 3 * BS, arrays, cold_engine._trunk_sig))
    # coverage over max_len would wedge receivers in eternal claim-defer
    long_toks = [int(t) for t in rng.integers(1, VOCAB, MAX_LEN + BS)]
    long_arr = [("k0", rng.standard_normal(
        ((MAX_LEN + BS) // BS, BS, 16)).astype(np.float32))]
    with pytest.raises(WireFormatError, match="refusing to pool"):
        cold_engine.deliver_chain_blob(
            serialize_chain(long_toks, MAX_LEN + BS, long_arr,
                            cold_engine._trunk_sig))
    # foreign trunk: rejected before it touches the tier
    with pytest.raises(WireFormatError, match="trunk signature"):
        cold_engine.deliver_chain_blob(
            serialize_chain(tokens, 2 * BS, arrays, SIG + ".other"))
    assert cold_engine.host_tier.bytes == 0
    # the honest blob pools fine
    key, covered = cold_engine.deliver_chain_blob(
        serialize_chain(tokens, 2 * BS, arrays, cold_engine._trunk_sig))
    assert key == tuple(tokens) and covered == 2 * BS
    assert cold_engine.host_tier.bytes > 0


def test_deliver_chain_blob_needs_host_tier():
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                              trg_vocab=1, d_model=D_MODEL,
                              num_heads=HEADS, dff=64, enc_layers=LAYERS,
                              dec_layers=0, max_len=MAX_LEN)
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=2,
                       max_len=MAX_LEN, prefill_buckets=(8,),
                       name="transfer_tierless", warm=False,
                       kv_layout="paged", kv_block_size=BS,
                       kv_num_blocks=13, prefill_chunk=BS)
    with pytest.raises(ConfigError, match="kv_host_bytes"):
        eng.deliver_chain_blob(b"\x01")


# ------------------------------------ cross-process round trip (slow)


def _outcome_counts(text):
    return {m.group(1): int(m.group(2)) for m in re.finditer(
        r'^\S*_kv_handoffs_total\{outcome="(\w+)"\} (\d+)\s*$',
        text, re.MULTILINE)}


@pytest.mark.slow
def test_cross_process_handoff_bit_identical_exact_counters():
    """One prefill-role replica subprocess serializes its chain over the
    socket; one decode-role subprocess receives and seats it.  The
    decode stream must be bit-identical to the cold in-process
    ``lm_generate`` recompute, and the ``kv_handoff_*`` counters on both
    /metrics must be EXACT: one sent, one received, zero fallbacks, the
    same blob bytes on both sides."""
    from paddle_tpu.serving.fleet import ReplicaSupervisor

    n_tokens, max_len, bs, plen = 12, 64, 8, 32
    extra = ["--gen-slots", "4", "--gen-max-len", str(max_len),
             "--gen-prefill-buckets", "8,16",
             "--gen-max-tokens", str(n_tokens),
             "--prefill-chunk", str(bs),
             "--kv-layout", "paged", "--kv-block-size", str(bs),
             "--kv-num-blocks", "49", "--kv-prefix-cache", "1",
             "--kv-host-bytes", str(64 << 20)]
    sup = ReplicaSupervisor(n_replicas=2, roles=("prefill", "decode"),
                            extra_args=extra, backoff_base_s=0.3, seed=0,
                            name="transfer_xproc")

    def post(url, body):
        req = urllib.request.Request(
            f"{url}/v1/generate", json.dumps(body).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    sup.start()
    try:
        assert sup.wait_ready(timeout=240), "replicas never became ready"
        eps = dict(sup.endpoints())
        prefill_url, decode_url = eps["r0"], eps["r1"]
        prompt = [int(t)
                  for t in np.random.RandomState(11).randint(1, 256, plen)]

        # serialize side: prefill to the first token on r0
        lead = post(prefill_url, {"prompt": prompt, "max_tokens": 1})
        assert len(lead["tokens"]) == 1, lead

        # receive side: r1 pulls the chain over the socket, seats it,
        # and decodes the continuation
        out = post(decode_url, {
            "prompt": prompt, "replay": lead["tokens"],
            "max_tokens": n_tokens - 1,
            "kv_handoff": {"source": prefill_url,
                           "tokens": prompt + lead["tokens"]}})
        hand = out["kv_handoff"]
        assert hand["outcome"] == "received", hand
        assert hand["bytes"] > 0 and hand["covered"] >= plen, hand

        # bit-identity vs the cold recompute oracle
        params = transformer.init(
            jax.random.PRNGKey(0), src_vocab=256, trg_vocab=1,
            d_model=32, num_heads=2, dff=64, enc_layers=2, dec_layers=0,
            max_len=max_len)
        p = np.asarray(prompt, np.int32)
        ids = np.asarray(transformer.lm_generate(
            params, p[None], max_len=max_len, num_heads=2,
            prompt_lengths=np.asarray([p.size])))
        oracle = ids[0, p.size:p.size + n_tokens].tolist()
        assert lead["tokens"] + out["tokens"] == oracle

        # exact counters on both /metrics
        def metrics(url):
            with urllib.request.urlopen(f"{url}/metrics",
                                        timeout=30) as r:
                return r.read().decode()

        pre, dec = metrics(prefill_url), metrics(decode_url)
        assert _outcome_counts(pre) == {"sent": 1, "received": 0,
                                        "fallback": 0}, pre[-500:]
        assert _outcome_counts(dec) == {"sent": 0, "received": 1,
                                        "fallback": 0}, dec[-500:]
        sent_b = re.search(r"^\S*_kv_handoff_bytes_total (\d+)", pre,
                           re.MULTILINE)
        recv_b = re.search(r"^\S*_kv_handoff_bytes_total (\d+)", dec,
                           re.MULTILINE)
        assert sent_b and recv_b, (pre[-500:], dec[-500:])
        assert int(sent_b.group(1)) == int(recv_b.group(1)) \
            == hand["bytes"]
    finally:
        sup.stop()
