"""Tensor-parallel sharded decode (DecodeEngine mesh=decode_mesh(n)).

The ONE unified chunked step runs under parallel.sharding.shard_map
over a 1-axis "model" mesh: head-sharded attention + KV pool, vocab-
sharded tied embeddings, everything else replicated — only column-
slice-exact tensors shard, so the greedy streams are BIT-IDENTICAL to
the single-chip twin (the lm_generate oracle) on both KV layouts, with
speculation composed.  Trace discipline is unchanged by the mesh: one
warm-up trace for the engine step, one for the draft rollout, zero
retraces across admission / acceptance churn (placement is data for
the tracer, not shape).

tests/conftest.py forces 8 virtual host devices, so a real >= 2-chip
mesh backs every run here — in-process, no subprocess re-exec.

Fast lane: ONE module-shared warm sharded engine (paged + speculating,
the deepest composition) plus the config seams and pool-sizing math.
Layout x k grids, int8 composition, chaos recovery, continuation
replay, and the 4-way mesh ride the slow lane (the tier-1 wrapper is
saturated on this host).
"""

import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.parallel.sharding import decode_mesh
from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.serving import GenerationBatcher, ServingMetrics
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.serving.kv_pool import slab_equivalent_blocks
from paddle_tpu.serving.speculative import DraftTrunk, make_draft
from paddle_tpu.testing import forbid_retrace
from paddle_tpu.utils.error import ConfigError

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BS, SHARDS, SPEC_K = 48, 4, 8, 2, 3


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


def _engine(params, shards=SHARDS, **kw):
    kw.setdefault("prefill_chunk", 4)
    if shards:
        kw.setdefault("mesh", decode_mesh(shards))
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, **kw)


@pytest.fixture(scope="module")
def sharded_engine(params):
    # ONE warm sharded engine shared across the fast lane — warm-up is
    # the expensive part, and sharing pins the trace counters across
    # every drive below (they must END at 1/1, not per-test 1/1).
    # Paged + speculating: the deepest composition (head-sharded pool
    # blocks, chain rollback, sharded draft rollout); the slow-lane
    # grid sweeps slab and the non-speculating corner.
    return _engine(params, name="sharded_shared", kv_layout="paged",
                   kv_block_size=BS, speculate_k=SPEC_K,
                   draft=make_draft(params, layers=1))


def _prompt(rng, n=None):
    return rng.randint(1, VOCAB, n or rng.randint(1, 30)).astype(np.int32)


def _oracle(params, prompt, n_tokens):
    """The single-chip twin: plain replicated greedy decode."""
    ids = np.asarray(transformer.lm_generate(
        params, prompt[None], max_len=MAX_LEN, num_heads=HEADS,
        prompt_lengths=np.asarray([prompt.size])))
    return ids[0, prompt.size:prompt.size + n_tokens].tolist()


def _drive(bat, cases, stagger_s=0.002):
    """Concurrent client threads (admissions land mid-step)."""
    results, excs = [None] * len(cases), [None] * len(cases)

    def client(i):
        prompt, n = cases[i]
        try:
            time.sleep(stagger_s * i)
            results[i] = bat.submit(prompt, max_tokens=n).result(180)
        except Exception as e:      # noqa: BLE001
            excs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    assert all(e is None for e in excs), excs
    return results


# ------------------------------------------------- bit-identity core


@pytest.mark.slow
def test_sharded_streams_bit_identical_paged(params, sharded_engine):
    """Staggered concurrent streams off the 2-way sharded speculating
    paged engine reproduce the single-chip oracle token for token —
    every collective is a concatenation or an add-zero psum, so the
    mesh changes placement, never a bit — with the mesh gauge live on
    /metrics and the block ledger balanced across the head stripes."""
    eng = sharded_engine
    eng.metrics = ServingMetrics()
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(0)
    cases = [(_prompt(rng), 4 + (i % 7)) for i in range(6)]
    with forbid_retrace(eng, eng.draft, what="sharded paged serving"):
        results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]
    snap = eng.metrics.snapshot()
    assert snap["mesh_shards"] == SHARDS, snap
    assert snap["drafted_tokens_total"] > 0, snap
    assert f"{eng.metrics.name}_mesh_shards {SHARDS}" \
        in eng.metrics.render_prometheus()
    eng._paged.check()


def test_sharded_slab_bit_identical(params):
    """The slab layout shards the same way (each chip's rows carry its
    Dkv stripe): streams oracle-identical at 1 warm-up trace."""
    eng = _engine(params, name="sharded_slab", kv_layout="slab")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(1)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(4)]
    with forbid_retrace(eng, what="sharded slab serving"):
        results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]
    assert eng.step_trace_count == 1
    assert eng.metrics.snapshot()["mesh_shards"] == SHARDS
    # the unsharded twin reports the degenerate gauge
    assert _engine(params, shards=0, name="twin_gauge") \
        .metrics.snapshot()["mesh_shards"] == 1


# --------------------------------------------- capacity + trace + config


def test_sharded_pool_capacity_multiplies(params, sharded_engine):
    """A chip holds only its Hkv/n stripe of each block, so the slab-
    equivalent PER-CHIP byte budget holds n× the blocks — the capacity
    win tensor parallelism exists for — and int8 composes on top."""
    base = slab_equivalent_blocks(SLOTS, MAX_LEN, BS)
    both = slab_equivalent_blocks(SLOTS, MAX_LEN, BS, kv_dtype="int8",
                                  mesh_shards=SHARDS)
    assert base == SLOTS * (MAX_LEN // BS) + 1
    assert slab_equivalent_blocks(SLOTS, MAX_LEN, BS,
                                  mesh_shards=SHARDS) == \
        SHARDS * (base - 1) + 1
    assert both == 2 * SHARDS * (base - 1) + 1
    # the shared engine's auto-sized pool really got the n× count
    assert sharded_engine._paged.pool.num_blocks == \
        slab_equivalent_blocks(SLOTS, MAX_LEN, BS, mesh_shards=SHARDS)


def test_sharded_trace_discipline(sharded_engine):
    """After every fast-lane drive above: the sharded engine step
    traced ONCE and the sharded draft rollout traced ONCE — the mesh
    never bought a second trace."""
    assert sharded_engine.step_trace_count == 1
    assert sharded_engine.draft.trace_count == 1


def test_sharded_config_validation(params):
    """The config seams fail fast at construction: a mesh without the
    'model' axis, the legacy prefill ladder, an indivisible trunk, and
    a draft on a different mesh."""
    from jax.sharding import Mesh
    with pytest.raises(ConfigError, match="axis"):
        _engine(params, shards=0,
                mesh=Mesh(np.asarray(jax.devices()[:2]), ("data",)))
    with pytest.raises(ConfigError, match="chunked"):
        _engine(params, prefill_chunk=0, prefill_buckets=(8, 16))
    with pytest.raises(ConfigError, match="cannot shard"):
        _engine(params, shards=3)       # 2 heads / 64 vocab don't split 3
    with pytest.raises(ConfigError, match="mesh"):
        single = DraftTrunk(make_draft(params, layers=1), k=SPEC_K,
                            num_slots=SLOTS, max_len=MAX_LEN,
                            chunk=SPEC_K + 2, num_heads=HEADS)
        _engine(params, speculate_k=SPEC_K, draft=single)


# ------------------------------------------------------- slow lane


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["slab", "paged"])
@pytest.mark.parametrize("k", [0, 2])
def test_sharded_layout_k_grid_bit_identical(params, layout, k):
    """layout x speculate_k sweep on the 2-way mesh: every pairing
    reproduces the oracle under staggered concurrency, zero retraces."""
    kw = {"kv_layout": layout, "speculate_k": k}
    if layout == "paged":
        kw["kv_block_size"] = BS
    if k:
        kw["draft"] = make_draft(params, layers=1)
    eng = _engine(params, name=f"sharded_{layout}_{k}", **kw)
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(10 + k)
    cases = [(_prompt(rng), 4 + (i % 6)) for i in range(6)]
    jits = (eng, eng.draft) if k else (eng,)
    with forbid_retrace(*jits, what=f"sharded {layout} k={k}"):
        results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]


@pytest.mark.slow
def test_sharded_int8_kv_matches_unsharded_twin(params):
    """Quant composition: an int8-KV sharded paged engine (per-chip
    stripes of the int8 blocks AND their scale sidecars) emits the
    SAME streams as its int8-KV single-chip twin — bit-identity holds
    within the quantization mode."""
    kw = dict(kv_layout="paged", kv_block_size=BS, kv_dtype="int8")
    shd = _engine(params, name="sharded_q", **kw)
    twin = _engine(params, shards=0, name="sharded_q_twin", **kw)
    rng = np.random.RandomState(20)
    cases = [(_prompt(rng), 4 + (i % 6)) for i in range(6)]
    bat = GenerationBatcher(shd)
    got = [r["tokens"] for r in _drive(bat, cases)]
    bat.close()
    bat = GenerationBatcher(twin)
    ref = [r["tokens"] for r in _drive(bat, cases)]
    bat.close()
    assert got == ref
    shd._paged.check()


@pytest.mark.slow
def test_sharded_chaos_recovery_bit_identical(params):
    """An injected decode-step fault on the sharded engine rebuilds the
    SHARDED caches (reset() re-places every stripe on the mesh) and
    re-seats every stream: all streams oracle-identical, zero extra
    traces — recovery never falls back to replicated buffers."""
    eng = _engine(params, name="sharded_chaos", kv_layout="paged",
                  kv_block_size=BS)
    rng = np.random.RandomState(30)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(8)]
    ref = [_oracle(params, p, n) for p, n in cases]
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(eng, supervisor=sup)
    faults.install_spec("serving.decode_step:at=6")
    with forbid_retrace(eng, what="sharded chaos recovery"):
        results = _drive(bat, cases)
        bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    assert [r["tokens"] for r in results] == ref
    assert eng.metrics.snapshot()["evictions"]["recovered"] >= 1
    eng._paged.check()


@pytest.mark.slow
def test_sharded_continuation_replay_bit_identical(params):
    """Continuations ride the mesh: a stream interrupted after j
    delivered tokens finishes emitting ONLY the remainder through the
    sharded step."""
    eng = _engine(params, name="sharded_cont")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(40)
    for plen, n, j in ((5, 10, 3), (16, 12, 7)):
        prompt = _prompt(rng, plen)
        full = _oracle(params, prompt, n)
        res = bat.submit(prompt, replay=np.asarray(full[:j], np.int32),
                         max_tokens=n - j).result(60)
        assert res["tokens"] == full[j:], (plen, n, j)
    bat.close()


@pytest.mark.slow
def test_sharded_4way_mesh_bit_identical():
    """A 4-way mesh on a 4-head trunk (1 head stripe per chip, vocab
    16/chip): the policy holds at deeper splits, streams oracle-
    identical."""
    params4 = transformer.init(jax.random.PRNGKey(2), src_vocab=VOCAB,
                               trg_vocab=1, d_model=D_MODEL, num_heads=4,
                               dff=64, enc_layers=LAYERS, dec_layers=0,
                               max_len=MAX_LEN)
    eng = DecodeEngine(params4, num_heads=4, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_chunk=4,
                       mesh=decode_mesh(4), name="sharded_4way")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(50)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(4)]
    with forbid_retrace(eng, what="4-way sharded serving"):
        results = _drive(bat, cases)
    bat.close()
    got = [r["tokens"] for r in results]
    ref = []
    for p, n in cases:
        ids = np.asarray(transformer.lm_generate(
            params4, p[None], max_len=MAX_LEN, num_heads=4,
            prompt_lengths=np.asarray([p.size])))
        ref.append(ids[0, p.size:p.size + n].tolist())
    assert got == ref
    assert eng.metrics.snapshot()["mesh_shards"] == 4
