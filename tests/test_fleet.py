"""Replicated serving tier (serving/fleet.py + serving/router.py): the
fleet-scope chaos matrix (docs/serving.md §7).

In-process half: the router's policies against scripted stub replicas
(readiness gating, least-loaded dispatch, outlier ejection + half-open
readmission, the ``router.dispatch`` fault point, hedging) and against a
REAL in-process replica (mid-stream failover bit-identity, client-
disconnect propagation to ``abandon()``, the continuation ``replay``
submit contract).

Subprocess half: a real 2-replica fleet behind the router — kill -9 one
replica under 8 concurrent streaming clients and every stream must
finish BIT-IDENTICAL to ``lm_generate``; the supervisor restarts the
victim with the exact seeded backoff; a rolling-drain sweep completes
with zero failed requests.
"""

import http.client
import json
import random
import signal
import socket
import struct
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.resilience import FaultPlan, faults
from paddle_tpu.serving import (DecodeEngine, GenerationBatcher,
                                ReplicaSupervisor, Router, ServingMetrics,
                                make_server)

VOCAB, HEADS, MAX_LEN, SLOTS, BUCKETS = 64, 2, 48, 4, (8, 16)

# the fleet replicas' demo-LM scale (server.py _demo_gen_batcher with the
# flags below); the decode-step hang paces tokens so kills land MID-stream
FLEET_VOCAB, FLEET_MAX_LEN, FLEET_TOKENS = 256, 64, 20
FLEET_ARGS = ["--gen-slots", "4", "--gen-max-len", str(FLEET_MAX_LEN),
              "--gen-prefill-buckets", "8,16",
              "--gen-max-tokens", str(FLEET_TOKENS),
              "--fault-spec",
              "serving.decode_step:every=1,action=hang,hang_s=0.02"]
FLEET_SEED = 0


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=32, num_heads=HEADS,
                            dff=64, enc_layers=2, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def engine(params):
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=BUCKETS,
                        name="fleet_lm")


@pytest.fixture(scope="module")
def replica(engine):
    """One REAL in-process generation replica (engine + batcher + HTTP)."""
    gen = GenerationBatcher(engine)
    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield httpd, gen
    httpd.shutdown()
    gen.close()


def _oracle(params, prompt, n_tokens, max_len=MAX_LEN, heads=HEADS):
    ids = np.asarray(transformer.lm_generate(
        params, np.asarray(prompt, np.int32)[None], max_len=max_len,
        num_heads=heads, prompt_lengths=np.asarray([len(prompt)])))
    return ids[0, len(prompt):len(prompt) + n_tokens].tolist()


def _wait(pred, timeout=30.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def _stream(port, body, close_after=None, timeout=120):
    """Drive one streaming /v1/generate; returns (tokens, done_record).
    close_after=k drops the connection after k tokens (the disconnect
    test)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/generate", json.dumps(body).encode(),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    toks, done = [], None
    while True:
        line = resp.readline()
        if not line:
            break
        rec = json.loads(line)
        if "token" in rec:
            toks.append(rec["token"])
            if close_after is not None and len(toks) >= close_after:
                # hard close (RST) — the router must notice and close the
                # upstream replica connection
                conn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                     struct.pack("ii", 1, 0))
                conn.close()
                return toks, None
        if rec.get("done"):
            done = rec
            break
    conn.close()
    return toks, done


# ------------------------------------------------------- continuation API


def test_replay_submit_bit_identical(engine):
    """The contract the router's failover rides on: submitting prompt +
    already-delivered replay tokens continues the greedy stream
    bit-identically, emitting only NEW tokens — including when the
    combined context outgrows the prefill ladder top (re-prefill the
    clamped prefix + teacher-forced replay)."""
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine)
    rng = np.random.RandomState(3)
    try:
        for size, cut, total in ((5, 3, 12), (14, 9, 12), (16, 1, 20)):
            prompt = rng.randint(1, VOCAB, size).astype(np.int32)
            full = bat.submit(prompt, max_tokens=total).result(120)["tokens"]
            cont = bat.submit(prompt, replay=np.asarray(full[:cut],
                                                        np.int32),
                              max_tokens=total - cut).result(120)
            assert cont["tokens"] == full[cut:], (size, cut)
            # the (16, 1) case: context 17 > ladder top 16 — clamped
        with pytest.raises(Exception, match="replay"):
            bat.submit(np.asarray([1, 2], np.int32), replay=np.asarray(
                [], np.int32), max_tokens=2).result(5)
        with pytest.raises(Exception, match="max_len"):
            bat.submit(np.asarray([1] * 10, np.int32),
                       replay=np.asarray([2] * 30, np.int32),
                       max_tokens=20)
    finally:
        bat.close()


# ------------------------------------------------------------ stub router


class _StubReplica:
    """A scripted replica: /readyz, /metrics queue depth, /v1/infer with
    a settable mode, /v1/generate streaming a scripted token list with an
    optional abrupt death."""

    def __init__(self, ready=True, depth=0, infer_mode="ok",
                 infer_delay_s=0.0, gen_tokens=(), die_after=None):
        self.ready = ready
        self.depth = depth
        self.infer_mode = infer_mode
        self.infer_delay_s = infer_delay_s
        self.gen_tokens = list(gen_tokens)
        self.die_after = die_after
        self.infer_hits = 0
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def handle(self):
                try:
                    super().handle()
                except (ConnectionError, BrokenPipeError):
                    pass        # the death script RSTs its own socket

            def _send(self, code, body, headers=()):
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    if stub.ready:
                        self._send(200, b'{"status": "ready"}')
                    else:
                        self._send(503, b'{"status": "unready"}',
                                   [("Retry-After", "1")])
                elif self.path == "/metrics":
                    self._send(200, f"stub_queue_depth {stub.depth}\n"
                               .encode())
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length")
                                    or 0))
                if self.path == "/v1/infer":
                    stub.infer_hits += 1
                    time.sleep(stub.infer_delay_s)
                    if stub.infer_mode == "fail":
                        self._send(500, b'{"error": "boom"}')
                    else:
                        self._send(200, b'{"outputs": {"y": [1]}}')
                    return
                # streaming generate: scripted tokens, optional death
                self.send_response(200)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for i, t in enumerate(stub.gen_tokens):
                    if stub.die_after is not None \
                            and i >= stub.die_after:
                        self.connection.setsockopt(
                            socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
                        self.connection.close()
                        self.close_connection = True
                        return
                    data = (json.dumps({"token": int(t)}) + "\n").encode()
                    self.wfile.write(f"{len(data):X}\r\n".encode() + data
                                     + b"\r\n")
                    time.sleep(0.01)
                data = (json.dumps({"done": True,
                                    "tokens": stub.gen_tokens,
                                    "finish_reason": "length"})
                        + "\n").encode()
                self.wfile.write(f"{len(data):X}\r\n".encode() + data
                                 + b"\r\n0\r\n\r\n")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


def _post(port, path, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_router_readiness_gating_and_least_loaded():
    """An unready replica is never dispatched to; among ready ones the
    smaller polled queue depth wins."""
    a = _StubReplica(ready=False)
    b = _StubReplica(depth=5)
    c = _StubReplica(depth=0)
    router = Router(replicas=[a.url, b.url, c.url], poll_interval_s=0.05,
                    hedge_ms=0)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        for _ in range(4):
            st, out = _post(httpd.port, "/v1/infer", {"feed": {}})
            assert st == 200 and "outputs" in out
        assert a.infer_hits == 0            # gated out by /readyz
        assert c.infer_hits == 4            # least-loaded (depth 0 vs 5)
        assert b.infer_hits == 0
        # the unready replica keeps /readyz-flagged; flipping it ready
        # admits it within a poll interval
        a.ready = True
        assert _wait(lambda: router.replica_states()["r0"]["ready"], 10)
    finally:
        router.close()
        for s in (a, b, c):
            s.close()


def test_router_ejection_and_halfopen_readmission():
    """Consecutive dispatch failures eject the replica (requests keep
    succeeding via retry on the healthy one); after the cooldown ONE
    half-open probe readmits it on success — counters count both
    transitions.  The cooldown elapses on the router's INJECTABLE clock
    (advanced by hand) instead of a wall-clock sleep."""

    class _Clock:
        def __init__(self):
            self.t = time.monotonic()

        def __call__(self):
            return self.t

        def advance(self, dt):
            self.t += dt

    clock = _Clock()
    a = _StubReplica(infer_mode="fail")     # r0 wins the load tie
    b = _StubReplica()
    router = Router(replicas=[a.url, b.url], poll_interval_s=0.05,
                    eject_threshold=2, eject_cooldown_s=0.4,
                    retry_budget=2, hedge_ms=0, clock=clock)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        for _ in range(3):
            st, _out = _post(httpd.port, "/v1/infer", {"feed": {}})
            assert st == 200                # retry absorbed the failure
        snap = router.metrics.snapshot()
        assert snap["ejections_total"].get("r0") == 1
        assert snap["retries_total"] >= 2
        assert router.replica_states()["r0"]["breaker"] != "closed"
        hits_after_eject = a.infer_hits
        _post(httpd.port, "/v1/infer", {"feed": {}})
        assert a.infer_hits == hits_after_eject    # ejected: not dialed
        # heal the replica; ADVANCE the injected clock past the cooldown
        # (no wall-clock sleep) — the half-open probe lands on it (load
        # tie -> r0 first) and recloses the breaker
        a.infer_mode = "ok"
        clock.advance(0.5)
        st, _out = _post(httpd.port, "/v1/infer", {"feed": {}})
        assert st == 200
        assert _wait(lambda: router.metrics.snapshot()
                     ["readmissions_total"].get("r0") == 1, 10)
        assert router.replica_states()["r0"]["breaker"] == "closed"
    finally:
        router.close()
        a.close()
        b.close()


def test_router_dispatch_fault_point():
    """The router-layer fault point: a seeded plan injects a dispatch
    error at the router->replica boundary; the bounded retry absorbs it
    and the fire count is exact.  Seeded p= schedules replay bit-for-bit
    at this point like the in-process seven."""
    plan_a = FaultPlan.from_spec("router.dispatch:p=0.5,seed=9")
    plan_b = FaultPlan.from_spec("router.dispatch:p=0.5,seed=9")
    fires_a, fires_b = [], []
    for plan, fires in ((plan_a, fires_a), (plan_b, fires_b)):
        for _ in range(64):
            try:
                plan.hit("router.dispatch")
                fires.append(0)
            except Exception:
                fires.append(1)
    assert fires_a == fires_b and sum(fires_a) > 0

    a = _StubReplica()
    router = Router(replicas=[a.url], poll_interval_s=0.05,
                    retry_budget=2, hedge_ms=0)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        faults.install_spec("router.dispatch:at=1")
        st, out = _post(httpd.port, "/v1/infer", {"feed": {}})
        assert st == 200 and "outputs" in out
        assert faults.fired_counts()["router.dispatch"] == 1
        snap = router.metrics.snapshot()
        assert snap["retries_total"] == 1
        assert snap["dispatch_errors_total"].get("r0") == 1
    finally:
        faults.clear()
        router.close()
        a.close()


def test_router_hedged_infer():
    """With hedging on, a slow primary is raced by a hedge on the other
    replica and the fast answer wins."""
    a = _StubReplica(infer_delay_s=0.6)     # r0: the slow primary
    b = _StubReplica()
    router = Router(replicas=[a.url, b.url], poll_interval_s=0.05,
                    hedge_ms=40, retry_budget=1)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        t0 = time.perf_counter()
        st, out = _post(httpd.port, "/v1/infer", {"feed": {}})
        dt = time.perf_counter() - t0
        assert st == 200 and "outputs" in out
        assert dt < 0.55, f"hedge did not cut the tail: {dt:.3f}s"
        snap = router.metrics.snapshot()
        assert snap["hedges_total"] == 1
        assert snap["hedge_wins_total"] == 1
    finally:
        router.close()
        a.close()
        b.close()


# ------------------------------------------- in-process failover + abandon


def test_midstream_failover_bit_identical(params, replica):
    """A replica that dies mid-stream (4 tokens out, then RST, no done
    record): the router resubmits prompt + delivered tokens as a
    continuation on the healthy replica and the client's stream finishes
    bit-identical to lm_generate."""
    httpd_real, gen = replica
    rng = np.random.RandomState(7)
    prompt = rng.randint(1, VOCAB, 6).astype(np.int32)
    oracle = _oracle(params, prompt, 10)
    # r0 = the dying stub (wins the idle load tie), r1 = the real engine
    stub = _StubReplica(gen_tokens=oracle, die_after=4)
    router = Router(replicas=[stub.url, f"http://127.0.0.1:"
                                        f"{httpd_real.port}"],
                    poll_interval_s=0.05, retry_budget=2, hedge_ms=0)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        toks, done = _stream(httpd.port, {"prompt": prompt.tolist(),
                                          "max_tokens": 10,
                                          "stream": True})
        assert toks == oracle, (toks, oracle)
        assert done is not None and done["tokens"] == oracle
        snap = router.metrics.snapshot()
        assert snap["midstream_failovers_total"] == 1
        assert snap["tokens_proxied_total"] == 10
    finally:
        router.close()
        stub.close()


def test_client_disconnect_propagates_abandon(engine, replica):
    """Satellite: a dropped downstream /v1/generate stream must close the
    upstream replica connection so the replica's abandon() slot
    reclamation fires (the slot frees at the next token boundary instead
    of decoding to max_tokens for nobody)."""
    httpd_real, gen = replica
    engine.metrics = gen.metrics = ServingMetrics()
    router = Router(replicas=[f"http://127.0.0.1:{httpd_real.port}"],
                    poll_interval_s=0.05, hedge_ms=0)
    httpd = router.start(port=0)
    # pace the in-process engine so the stream is still live when the
    # client drops (cleared by the autouse fixture)
    faults.install_spec("serving.decode_step:every=1,action=hang,"
                        "hang_s=0.02")
    try:
        assert _wait(router.ready, 10)
        prompt = np.random.RandomState(8).randint(1, VOCAB, 5)
        toks, done = _stream(httpd.port,
                             {"prompt": prompt.tolist(), "max_tokens": 30,
                              "stream": True}, close_after=2)
        assert done is None and len(toks) >= 2
        # the replica reclaims the slot instead of decoding to 30
        assert _wait(lambda: gen.metrics.snapshot()["evictions"]
                     ["abandoned"] >= 1, 30), \
            gen.metrics.snapshot()["evictions"]
        assert _wait(lambda: engine.free_slots == engine.num_slots, 30)
        assert _wait(lambda: router.metrics.snapshot()
                     ["client_disconnects_total"] >= 1, 10)
    finally:
        faults.clear()
        router.close()


# --------------------------------------------------- supervisor (no jax)


def test_supervisor_backoff_and_storm_breaker_exact():
    """A replica that dies instantly is restarted with the EXACT seeded
    exponential-backoff schedule until the restart-storm breaker trips;
    counters are exact."""
    sup = ReplicaSupervisor(
        n_replicas=1, cmd=["-c", "import sys; sys.exit(3)"],
        backoff_base_s=0.05, backoff_max_s=0.4, storm_threshold=4,
        storm_window_s=30.0, seed=11)
    sup.start()
    try:
        assert _wait(lambda: sup.snapshot()["r0"]["storm_tripped"], 30)
        snap = sup.snapshot()["r0"]
        assert snap["state"] == "failed"
        # threshold crashes -> threshold-1 restarts (the storm check
        # fires on the Nth crash, before scheduling another restart)
        assert snap["restarts_total"] == 3
        assert snap["consecutive_failures"] == 4
        # the jittered delays replay exactly from the seeded stream
        rng = random.Random(11 * 7919 + 0)
        expect = [round(min(0.05 * 2 ** k, 0.4)
                        * (0.5 + 0.5 * rng.random()), 4)
                  for k in range(3)]
        assert snap["backoff_delays_s"] == expect
        # tripped: no further restarts ever get scheduled
        time.sleep(0.3)
        assert sup.snapshot()["r0"]["restarts_total"] == 3
    finally:
        sup.stop()


# ------------------------------------------------- subprocess fleet chaos


@pytest.fixture(scope="module")
def fleet_params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=FLEET_VOCAB,
                            trg_vocab=1, d_model=32, num_heads=2, dff=64,
                            enc_layers=2, dec_layers=0,
                            max_len=FLEET_MAX_LEN)


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """One real 2-replica subprocess fleet + router, shared by the
    ordered chaos tests below (spawning replicas is the expensive part;
    a module-local persistent XLA cache makes the restarted replicas'
    warm-up a disk read instead of a recompile)."""
    import os
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   str(tmp_path_factory.mktemp("xla_cache")))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    sup = ReplicaSupervisor(n_replicas=2, extra_args=FLEET_ARGS,
                            backoff_base_s=0.3, seed=FLEET_SEED,
                            env=env, name="test_fleet")
    sup.start()
    if not sup.wait_ready(timeout=300):
        sup.stop()
        pytest.fail("fleet replicas never became ready")
    router = Router(supervisor=sup, poll_interval_s=0.1,
                    eject_threshold=2, eject_cooldown_s=1.0,
                    retry_budget=3, hedge_ms=0)
    httpd = router.start(port=0)
    assert _wait(router.ready, 30)
    yield sup, router, httpd.port
    router.close()
    sup.stop()


@pytest.mark.slow
def test_fleet_kill9_midstream_under_concurrent_load(fleet, fleet_params):
    """THE acceptance drive: kill -9 one replica while 8 concurrent
    clients stream — every stream must finish bit-identical to
    lm_generate (cross-replica continuation failover), with the router's
    failover counters as evidence."""
    sup, router, port = fleet
    n_clients = 8
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, FLEET_VOCAB, int(rng.randint(3, 17)))
               for _ in range(n_clients)]
    oracle = [_oracle(fleet_params, p, FLEET_TOKENS,
                      max_len=FLEET_MAX_LEN, heads=2) for p in prompts]
    results = [None] * n_clients
    errs = []
    seen2 = threading.Barrier(n_clients + 1, timeout=120)

    def hit(i):
        armed = True
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            conn.request("POST", "/v1/generate",
                         json.dumps({"prompt": prompts[i].tolist(),
                                     "max_tokens": FLEET_TOKENS,
                                     "stream": True}).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            toks, done = [], None
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if "token" in rec:
                    toks.append(rec["token"])
                    if armed and len(toks) >= 2:
                        armed = False
                        seen2.wait()
                if rec.get("done"):
                    done = rec
                    break
            conn.close()
            if armed:
                seen2.wait()
            results[i] = (toks, done)
        except Exception as e:      # noqa: BLE001
            errs.append(f"client {i}: {type(e).__name__}: {e}")
            if armed:
                try:
                    seen2.wait()
                except threading.BrokenBarrierError:
                    pass

    threads = [threading.Thread(target=hit, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    seen2.wait()                    # every stream is visibly mid-decode
    sup.kill("r0", signal.SIGKILL)
    for t in threads:
        t.join(180)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    assert not errs, errs
    for i, (toks, done) in enumerate(results):
        assert toks == oracle[i], f"stream {i} diverged after the kill"
        assert done is not None and done["tokens"] == oracle[i]
    snap = router.metrics.snapshot()
    # half the streams lived on the victim: all of them failed over
    assert snap["midstream_failovers_total"] >= 1
    assert snap["failovers_total"] >= snap["midstream_failovers_total"]


@pytest.mark.slow   # reads the supervision evidence kill9 leaves behind
def test_fleet_victim_restarted_with_seeded_backoff(fleet):
    """Supervision evidence after the kill: exactly one crash-restart of
    r0, with the first backoff delay replaying the seeded schedule, and
    the replica back in rotation (router sees it ready again)."""
    sup, router, _port = fleet
    assert sup.wait_ready(timeout=300, rids=("r0",)), sup.snapshot()
    snap = sup.snapshot()["r0"]
    assert snap["restarts_total"] == 1
    assert snap["storm_tripped"] is False
    rng = random.Random(FLEET_SEED * 7919 + 0)
    expect = round(min(0.3, 10.0) * (0.5 + 0.5 * rng.random()), 4)
    assert snap["backoff_delays_s"] == [expect]
    assert _wait(lambda: router.replica_states().get("r0", {})
                 .get("ready", False), 30)


@pytest.mark.slow
def test_fleet_rolling_drain_zero_failed_requests(fleet, fleet_params):
    """Satellite: SIGTERM one replica at a time (rolling restart) while
    clients keep generating through the router — zero failed requests,
    every response still bit-identical (the router routes around the
    draining replica via /readyz)."""
    sup, router, port = fleet
    restarts_before = {rid: r["restarts_total"]
                       for rid, r in sup.snapshot().items()}
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, FLEET_VOCAB, int(rng.randint(3, 17)))
               for _ in range(4)]
    oracle = [_oracle(fleet_params, p, 6, max_len=FLEET_MAX_LEN, heads=2)
              for p in prompts]
    stop = threading.Event()
    failures, completed = [], [0]

    def client(i):
        while not stop.is_set():
            try:
                st, out = _post(port, "/v1/generate",
                                {"prompt": prompts[i].tolist(),
                                 "max_tokens": 6}, timeout=120)
                if st != 200 or out["tokens"] != oracle[i]:
                    failures.append((i, st, out))
                completed[0] += 1
            except Exception as e:      # noqa: BLE001
                failures.append((i, f"{type(e).__name__}: {e}"))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    try:
        sup.rolling_restart(ready_timeout=300)
    finally:
        stop.set()
        for t in threads:
            t.join(120)
    assert not failures, failures[:5]
    assert completed[0] > 0
    fsnap = sup.snapshot()
    assert all(r["drains_total"] == 1 for r in fsnap.values()), fsnap
    # drains are deliberate: no crash-restart accounting moved
    for rid, r in fsnap.items():
        assert r["restarts_total"] == restarts_before[rid], fsnap


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
