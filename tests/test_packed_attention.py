"""Packed-sequence (segment-ids) attention: several ragged sequences share
one row; attention must behave exactly as if each sequence ran alone —
the ragged-attention half of the reference's no-padding story
(Argument.sequenceStartPositions, parameter/Argument.h:84-93)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import pack_sequences
from paddle_tpu.ops import attention as att

H, D = 2, 8


def test_pack_sequences_layout():
    seqs = [np.arange(1, 6), np.arange(10, 13), np.arange(20, 27),
            np.arange(30, 32)]
    data, seg, pos = pack_sequences(seqs, max_len=8)
    # first-fit: [5,3] share row 0; [7] row 1 then [2] fits row 1's tail?
    # row1 free=1 < 2, so row 2
    assert data.shape == seg.shape == pos.shape
    assert (seg > 0).sum() == sum(len(s) for s in seqs)
    # every segment's tokens are contiguous, positions restart at 0
    for i in range(seg.shape[0]):
        for s_id in np.unique(seg[i]):
            if s_id == 0:
                continue
            idx = np.where(seg[i] == s_id)[0]
            assert (np.diff(idx) == 1).all()
            np.testing.assert_array_equal(pos[i, idx],
                                          np.arange(len(idx)))
    # truncation
    d2, s2, _ = pack_sequences([np.arange(100)], max_len=8)
    assert (s2[0] == 1).sum() == 8


def _per_segment_reference(x, seg, causal):
    """Run each segment alone through dense attention, scatter back."""
    out = np.zeros_like(np.asarray(x))
    b = x.shape[0]
    for i in range(b):
        for s_id in np.unique(np.asarray(seg[i])):
            if s_id == 0:
                continue
            idx = np.where(np.asarray(seg[i]) == s_id)[0]
            xi = x[i : i + 1, :, idx, :]
            oi = att.dot_product_attention(xi, xi, xi, causal=causal,
                                           use_flash=False)
            out[i, :, idx, :] = np.asarray(oi)[0].transpose(1, 0, 2)
    return out


@pytest.mark.parametrize("causal", [False, True], ids=["plain", "causal"])
def test_chunked_segment_attention_isolates(np_rng, causal):
    seqs = [np_rng.randint(0, 9, n) for n in (5, 3, 7, 2, 8, 6)]
    _, seg, _ = pack_sequences(seqs, max_len=16)
    b = seg.shape[0]
    x = jnp.asarray(np_rng.randn(b, H, 16, D) * 0.5, jnp.float32)
    segj = jnp.asarray(seg)
    got = att.chunked_attention(x, x, x, causal=causal,
                                q_segment_ids=segj, q_chunk=8, k_chunk=8,
                                key_mask=(segj > 0).astype(jnp.float32))
    want = _per_segment_reference(x, seg, causal)
    mask = (seg > 0)[:, None, :, None]
    np.testing.assert_allclose(np.asarray(got) * mask, want * mask,
                               atol=2e-5)


def test_segment_mask_matches_chunked(np_rng):
    """Dense path with segment_mask == chunked with segment ids."""
    seqs = [np_rng.randint(0, 9, n) for n in (4, 4, 6, 2)]
    _, seg, _ = pack_sequences(seqs, max_len=8)
    b = seg.shape[0]
    x = jnp.asarray(np_rng.randn(b, H, 8, D) * 0.5, jnp.float32)
    segj = jnp.asarray(seg)
    dense = att.dot_product_attention(
        x, x, x, mask=att.segment_mask(segj), use_flash=False)
    chunked = att.chunked_attention(x, x, x, q_segment_ids=segj,
                                    q_chunk=4, k_chunk=4,
                                    key_mask=(segj > 0).astype(jnp.float32))
    mask = (seg > 0)[:, None, :, None]
    np.testing.assert_allclose(np.asarray(chunked) * mask,
                               np.asarray(dense) * mask, atol=2e-5)


def test_segment_grads_flow(np_rng):
    seqs = [np_rng.randint(0, 9, n) for n in (5, 3)]
    _, seg, _ = pack_sequences(seqs, max_len=8)
    segj = jnp.asarray(seg)
    x = jnp.asarray(np_rng.randn(1, H, 8, D) * 0.5, jnp.float32)

    def loss(x):
        o = att.chunked_attention(x, x, x, causal=True,
                                  q_segment_ids=segj, q_chunk=4,
                                  k_chunk=4,
                                  key_mask=(segj > 0).astype(jnp.float32))
        return jnp.sum((o * (segj > 0)[:, None, :, None]) ** 2)

    g = jax.grad(loss)(x)
    assert bool(jnp.all(jnp.isfinite(g)))
    # grads at padded positions are zero (nothing attends them)
    pad = np.where(np.asarray(seg[0]) == 0)[0]
    np.testing.assert_allclose(np.asarray(g)[0, :, pad, :], 0.0, atol=1e-7)


def test_kv_segments_without_q_segments_raises(np_rng):
    x = jnp.asarray(np_rng.randn(1, H, 8, D), jnp.float32)
    with pytest.raises(ValueError, match="label the query side"):
        att.chunked_attention(x, x, x,
                              kv_segment_ids=jnp.ones((1, 8), jnp.int32))


def test_mha_level_segment_attention(np_rng):
    """Packed batches work through the standard MHA entry point: outputs
    at each segment's positions equal running that segment alone."""
    D_MODEL = H * D
    seqs = [np_rng.randint(0, 9, n) for n in (5, 3, 6)]
    _, seg, _ = pack_sequences(seqs, max_len=8)
    b, t = seg.shape
    x = jnp.asarray(np_rng.randn(b, t, D_MODEL) * 0.5, jnp.float32)
    w = {k: jnp.asarray(np_rng.randn(D_MODEL, D_MODEL) * 0.2, jnp.float32)
         for k in "qkvo"}
    segj = jnp.asarray(seg)
    packed = att.multi_head_attention(
        x, x, w["q"], w["k"], w["v"], w["o"], H, causal=True,
        q_segment_ids=segj)
    for i in range(b):
        for s_id in np.unique(seg[i]):
            if s_id == 0:
                continue
            idx = np.where(seg[i] == s_id)[0]
            alone = att.multi_head_attention(
                x[i : i + 1, idx], x[i : i + 1, idx], w["q"], w["k"],
                w["v"], w["o"], H, causal=True)
            np.testing.assert_allclose(np.asarray(packed)[i, idx],
                                       np.asarray(alone)[0], atol=2e-5)


@pytest.mark.parametrize("causal", [False, True], ids=["plain", "causal"])
def test_mha_segment_ring_matches_unsharded(np_rng, causal):
    """Packed segments COMPOSE with sequence parallelism: the same MHA
    call with a seq>1 mesh (KV labels rotating around the ring) equals
    the single-device packed path, values and grads."""
    from paddle_tpu.parallel import MeshConfig, make_mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    D_MODEL = H * D
    T = 16
    seqs = [np_rng.randint(0, 9, n) for n in (5, 3, 6, 7, 2, 4)]
    _, seg, _ = pack_sequences(seqs, max_len=T)
    b = seg.shape[0]
    x = jnp.asarray(np_rng.randn(b, T, D_MODEL) * 0.5, jnp.float32)
    w = {k: jnp.asarray(np_rng.randn(D_MODEL, D_MODEL) * 0.2, jnp.float32)
         for k in "qkvo"}
    segj = jnp.asarray(seg)
    vmask = (seg > 0)[:, :, None]

    def run(ws, mesh_arg):
        out = att.multi_head_attention(
            x, x, ws["q"], ws["k"], ws["v"], ws["o"], H, causal=causal,
            q_segment_ids=segj, mesh=mesh_arg)
        # padded rows differ by convention (ring zeroes the attention
        # output before wo; dense lets them attend fellow padding) —
        # compare/locate the loss on real tokens only
        return jnp.sum((out * vmask) ** 2)

    v1, g1 = jax.jit(jax.value_and_grad(lambda ws: run(ws, None)))(w)
    v2, g2 = jax.jit(jax.value_and_grad(lambda ws: run(ws, mesh)))(w)
    np.testing.assert_allclose(float(v2), float(v1), rtol=2e-4)
    for ka in sorted(w):
        np.testing.assert_allclose(np.asarray(g2[ka]), np.asarray(g1[ka]),
                                   rtol=5e-3, atol=5e-5)


@pytest.mark.slow   # multi-second end-to-end; nightly lane
def test_transformer_encode_packed_matches_alone(np_rng):
    """transformer.encode on a packed row equals encoding each sequence
    alone: segment-isolated attention + within-segment positions."""
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer

    V, DM, HEADS, MAXLEN = 32, 16, 2, 12
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=V, d_model=DM, dff=32,
                              enc_layers=2, dec_layers=1, max_len=MAXLEN)
    seqs = [np_rng.randint(3, V, n) for n in (5, 4, 7, 3)]
    data, seg, pos = pack_sequences(seqs, max_len=MAXLEN)
    b = data.shape[0]
    packed = transformer.encode(
        params,
        SequenceBatch(jnp.asarray(data), jnp.full((b,), MAXLEN, jnp.int32)),
        num_heads=HEADS, segment_ids=jnp.asarray(seg),
        positions=jnp.asarray(pos))
    # oracle: each sequence alone (full-length row of its own size)
    for i in range(b):
        for s_id in np.unique(seg[i]):
            if s_id == 0:
                continue
            idx = np.where(seg[i] == s_id)[0]
            ids = data[i, idx][None]
            alone = transformer.encode(
                params,
                SequenceBatch(jnp.asarray(ids),
                              jnp.asarray([len(idx)], jnp.int32)),
                num_heads=HEADS)
            np.testing.assert_allclose(np.asarray(packed)[i, idx],
                                       np.asarray(alone)[0], atol=3e-5)
    # both-or-neither guard
    with pytest.raises(ValueError, match="BOTH segment_ids"):
        transformer.encode(
            params,
            SequenceBatch(jnp.asarray(data),
                          jnp.full((b,), MAXLEN, jnp.int32)),
            num_heads=HEADS, segment_ids=jnp.asarray(seg))


def test_packed_positions_overflow_raises(np_rng):
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=32,
                              trg_vocab=32, d_model=16, dff=32,
                              enc_layers=1, dec_layers=1, max_len=4)
    data, seg, pos = pack_sequences([np.arange(3, 9)], max_len=8)
    with pytest.raises(ValueError, match="positional table"):
        transformer.encode(
            params,
            SequenceBatch(jnp.asarray(data), jnp.asarray([8], jnp.int32)),
            num_heads=2, segment_ids=jnp.asarray(seg),
            positions=jnp.asarray(pos))


def test_packed_reader_decorator(np_rng):
    from paddle_tpu.data import reader as reader_mod
    seqs = [np_rng.randint(0, 9, n) for n in np_rng.randint(2, 9, 30)]

    def base():
        yield from seqs
    rows = list(reader_mod.packed(base, max_len=16, buffer_size=10)())
    # every token survives, segments isolated per row, rows are packed
    total = sum(int((seg > 0).sum()) for _, seg, _ in rows)
    assert total == sum(len(s) for s in seqs)
    for data, seg, pos in rows:
        assert data.shape == seg.shape == pos.shape == (16,)
        for s_id in np.unique(seg):
            if s_id == 0:
                continue
            idx = np.where(seg == s_id)[0]
            np.testing.assert_array_equal(pos[idx], np.arange(len(idx)))
    assert len(rows) < len(seqs)          # actually packed, not 1:1
