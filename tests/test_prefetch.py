"""ShardedPrefetcher unit tests: ordering, bounded depth, exception
propagation, clean shutdown, donation safety (the DoubleBuffer contract
completed to the device side — data/prefetch.py)."""

import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.data.prefetch import ShardedPrefetcher, device_placer


def _arange_source(n, shape=(4,)):
    def source():
        for i in range(n):
            yield np.full(shape, i, np.float32)
    return source


def test_ordering_and_values():
    """Batches come out device-resident, in source order, value-intact."""
    out = list(ShardedPrefetcher(_arange_source(50), depth=3))
    assert len(out) == 50
    for i, a in enumerate(out):
        assert isinstance(a, jax.Array)
        np.testing.assert_array_equal(np.asarray(a), np.full((4,), i))


def test_convert_runs_on_producer_thread():
    """convert (the feeder role) runs off the consumer thread and its
    output — not the raw batch — is what gets placed and delivered."""
    main = threading.get_ident()
    seen = []

    def convert(b):
        seen.append(threading.get_ident())
        return {"x": b * 2}

    out = list(ShardedPrefetcher(_arange_source(5), depth=2,
                                 convert=convert))
    assert all(t != main for t in seen)
    np.testing.assert_array_equal(np.asarray(out[3]["x"]),
                                  np.full((4,), 6.0))


def test_bounded_depth():
    """The producer never runs more than depth+1 batches ahead of the
    consumer (depth in the queue + one in flight), so HBM cost is
    bounded no matter how slow the consumer is."""
    produced = []
    consumed = 0
    max_ahead = 0
    depth = 2

    def place(b):
        produced.append(1)
        return b

    pf = ShardedPrefetcher(_arange_source(20), depth=depth, place=place)
    for _ in pf:
        time.sleep(0.01)         # slow consumer: the queue stays full
        consumed += 1
        max_ahead = max(max_ahead, len(produced) - consumed)
    assert consumed == 20
    assert max_ahead <= depth + 1, max_ahead


@pytest.mark.parametrize("where", ["source", "convert", "place"])
def test_exception_propagates_to_consumer(where):
    """A failure in the reader, the feeder conversion, or device
    placement surfaces in the CONSUMER thread, after the batches that
    were already good, and ends the stream."""
    def source():
        for i in range(10):
            if where == "source" and i == 3:
                raise RuntimeError("boom in source")
            yield np.full((2,), i, np.float32)

    def fail_at_3(tag):
        def fn(b):
            if int(b[0]) == 3:
                raise RuntimeError(f"boom in {tag}")
            return b
        return fn

    pf = ShardedPrefetcher(
        source, depth=2,
        convert=fail_at_3("convert") if where == "convert" else None,
        place=fail_at_3("place") if where == "place" else jax.device_put)
    got = []
    with pytest.raises(RuntimeError, match=f"boom in {where}"):
        for b in pf:
            got.append(int(np.asarray(b).flat[0]))
    assert got == [0, 1, 2]
    with pytest.raises(StopIteration):      # the stream is over, not wedged
        next(pf)
    assert not pf._thread.is_alive()


def test_close_mid_stream_joins_producer():
    """close() mid-stream (even against a full queue) stops and joins the
    producer; it is idempotent and the context manager calls it."""
    def slow_source():
        for i in range(1000):
            yield np.full((2,), i, np.float32)

    pf = ShardedPrefetcher(slow_source, depth=2)
    next(pf)
    pf.close()
    assert not pf._thread.is_alive()
    pf.close()                              # idempotent
    with ShardedPrefetcher(slow_source, depth=2) as pf2:
        next(pf2)
    assert not pf2._thread.is_alive()


def test_start_false_autostarts_on_iteration():
    """start=False defers the producer, but iterating must not deadlock
    on a forever-empty queue: __next__ starts the thread lazily."""
    pf = ShardedPrefetcher(_arange_source(3), depth=2, start=False)
    assert not pf._thread.is_alive()
    assert len(list(pf)) == 3


def test_abandoned_prefetcher_reclaimed_by_gc():
    """A consumer that drops the prefetcher without close() (break,
    exception) must not leak a producer thread pinning ~depth+1 batches
    of HBM: the GC finalizer stops and drains it.  Only possible because
    the producer thread targets a module-level fn — a bound-method target
    would keep the prefetcher alive for as long as the thread runs."""
    import gc

    def endless():
        i = 0
        while True:
            yield np.full((2,), i, np.float32)
            i += 1

    pf = ShardedPrefetcher(endless, depth=2)
    next(pf)
    thread = pf._thread
    del pf
    gc.collect()
    thread.join(timeout=5.0)
    assert not thread.is_alive()


def test_donation_safety():
    """A jitted step that DONATES its input can consume prefetched
    batches: every batch is a fresh device_put and the producer drops its
    reference on enqueue, so no buffer the step invalidates is ever held
    (or re-delivered) by the pipeline.

    Scope caveat: CPU XLA declines input donation ('donated buffers were
    not usable'), so on the CI backend this exercises the structural
    discipline (fresh buffer per batch, no pooling/re-delivery) rather
    than actual buffer invalidation — the aliasing-failure mode itself
    only arms on TPU/GPU."""
    step = jax.jit(lambda acc, x: acc + jnp.sum(x), donate_argnums=(0, 1))
    acc = jnp.zeros(())
    for x in ShardedPrefetcher(_arange_source(10), depth=3):
        acc = step(acc, x)
    assert float(acc) == sum(4 * i for i in range(10))


def test_wait_accounting():
    """wait_s accumulates consumer-side blocked time — the trainer's
    h2d_wait counter.  A slow source must show up as wait; batches counts
    deliveries."""
    def slow_source():
        for i in range(3):
            time.sleep(0.05)
            yield np.zeros((2,), np.float32)

    pf = ShardedPrefetcher(slow_source, depth=2)
    list(pf)
    assert pf.batches == 3
    assert pf.wait_s > 0.01


def test_device_placer_default_and_mesh():
    """mesh=None -> plain device_put; with a mesh, leaves land sharded
    under batch_shardings (leading dim over 'data', scalars replicated)."""
    place = device_placer(None)
    a = place(np.ones((4, 2), np.float32))
    assert isinstance(a, jax.Array)

    from paddle_tpu.parallel import make_mesh
    mesh = make_mesh()
    b = mesh.shape["data"] * 2      # batch divisible by the data axis
    place = device_placer(mesh)
    feed = place({"x": np.ones((b, 2), np.float32)})
    x = feed["x"]
    assert isinstance(x, jax.Array)
    sharding = x.sharding
    assert sharding.mesh.shape == mesh.shape
    # leading (batch) dim is the sharded one
    assert sharding.spec[0] is not None
