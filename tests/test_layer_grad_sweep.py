"""Registry-driven per-layer gradient sweep — the reference's strongest
correctness tool reproduced (gserver/tests/test_LayerGrad.cpp:34-80: 71 TESTs
perturbing every layer family across batch/config variants;
LayerGradUtil.cpp testLayerGrad:266 central differences).

Design: every registered layer type must either appear in a CASES builder
below or be listed in EXCLUDED with a reason — `test_registry_fully_covered`
fails when someone registers a new layer type without adding a sweep case.
Each case runs at two (batch, seq_len) variants; gradients are checked for
ALL parameter leaves AND all float inputs (the reference checks both
parameter and input gradients)."""

import zlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu.core.sequence import SequenceBatch, pad_sequences
from paddle_tpu.layers.graph import Topology, reset_names, value_data
from paddle_tpu.layers import networks as N
from paddle_tpu.testing import check_grads

# scan-heavy sweep (finite-difference grads through every recurrent/
# attention case); nightly lane — README "Running the tests"
pytestmark = pytest.mark.slow

# layer types with no gradient path to sweep, with reasons
EXCLUDED = {
    "data": "input placeholder",
    "__memory__": "group placeholder",
    "__static__": "group placeholder",
    "__step_input__": "group placeholder",
    "shared_table": "parameter-only node (covered via generation tests)",
    "print": "side-effecting printer",
    "maxid": "integer argmax output",
    "eos": "integer mask output",
    "sampling_id": "stochastic integer output",
    "beam_search_gen": "decoding (integer tokens; no grads)",
    "greedy_gen": "decoding (integer tokens; no grads)",
    "crf_decoding": "viterbi argmax output",
    "priorbox": "constant box generator",
}

B0, T0 = 3, 4

# float inputs that are LABELS: the reference computes no input gradient for
# these (e.g. LambdaCost backward only writes to the score input), and our
# impls stop_gradient them on purpose — exclude from finite differencing
NONDIFF_INPUTS = {
    "regress_costs": {"srel"},
}


def _r(np_rng, *shape):
    return np_rng.randn(*shape).astype(np.float32)


def _seq(np_rng, b, t, d):
    return pad_sequences([_r(np_rng, np_rng.randint(1, t + 1), d)
                          for _ in range(b)], max_len=t)


def _ids(np_rng, b, t, v):
    return pad_sequences([np_rng.randint(0, v, (np_rng.randint(1, t + 1),))
                          for _ in range(b)], max_len=t)


# ---------------------------------------------------------------- cases
# each: name -> builder(np_rng, B, T) -> (outputs, feed); `covers` maps the
# case to the registry types it exercises.

CASES = {}


def case(name, covers):
    def deco(fn):
        CASES[name] = (fn, covers)
        return fn
    return deco


@case("fc_tanh_bias", ["fc"])
def _(r, B, T):
    x = L.data_layer("x", size=5)
    return L.fc_layer(x, size=4, act="tanh"), {"x": _r(r, B, 5)}


@case("fc_multi_input_nobias", ["fc"])
def _(r, B, T):
    x = L.data_layer("x", size=5)
    y = L.data_layer("y", size=3)
    return (L.fc_layer([x, y], size=4, act="sigmoid", bias_attr=False),
            {"x": _r(r, B, 5), "y": _r(r, B, 3)})


@case("moe_softmax_gate", ["moe"])
def _(r, B, T):
    x = L.data_layer("x", size=6)
    # top_k == n_experts keeps the gate smooth (the top-k cut is piecewise;
    # finite differences need differentiability) while still exercising the
    # router grad and both expert einsums; top_k<E forward is covered by
    # tests/test_moe.py
    return (L.moe_layer(x, n_experts=3, top_k=3, expert_dim=8),
            {"x": _r(r, B, 6)})


@case("embedding", ["embedding"])
def _(r, B, T):
    w = L.data_layer("w", size=11, is_seq=True)
    emb = L.embedding_layer(w, size=4)
    return L.pooling_layer(emb, pooling_type="sum"), {"w": _ids(r, B, T, 11)}


@case("mixed_projections", ["mixed"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    y = L.data_layer("y", size=4)
    m = L.mixed_layer(size=4, input=[
        L.full_matrix_projection(x), L.identity_projection(y),
        L.dotmul_projection(y), L.scaling_projection(x),
        L.dotmul_operator(x, y)], act="tanh", bias_attr=True)
    return m, {"x": _r(r, B, 4), "y": _r(r, B, 4)}


@case("mixed_trans_table_context", ["mixed"])
def _(r, B, T):
    w = L.data_layer("w", size=9, is_seq=True)
    s = L.data_layer("s", size=4, is_seq=True)
    # projections of one mixed layer must share its width (the reference
    # MixedLayer asserts this) — context (4*3=12) gets its own mixed
    m1 = L.mixed_layer(size=4, input=[
        L.table_projection(w, 4), L.trans_full_matrix_projection(s)],
        act=None)
    m2 = L.mixed_layer(size=12, input=[L.context_projection(s, context_len=3)],
                       act=None)
    return ([L.pooling_layer(m1, pooling_type="sum"),
             L.pooling_layer(m2, pooling_type="sum")],
            {"w": _ids(r, B, T, 9), "s": _seq(r, B, T, 4)})


@case("addto_concat", ["addto", "concat"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    y = L.data_layer("y", size=4)
    return (L.concat_layer([L.addto_layer([x, y], act="tanh"), x]),
            {"x": _r(r, B, 4), "y": _r(r, B, 4)})


@case("elementwise_weighted",
      ["interpolation", "power", "scaling", "slope_intercept"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    y = L.data_layer("y", size=4)
    wt = L.data_layer("wt", size=1)
    outs = [L.interpolation_layer([x, y], weight=wt),
            L.power_layer(x, weight=wt),
            L.scaling_layer(x, weight=wt),
            L.slope_intercept_layer(x, slope=0.7, intercept=0.2)]
    return outs, {"x": np.abs(_r(r, B, 4)) + 0.5, "y": _r(r, B, 4),
                  "wt": np.abs(_r(r, B, 1)) * 0.5 + 0.5}


@case("comb_and_norms",
      ["linear_comb", "sum_to_one_norm", "cos_sim", "cos_sim_vec_mat"])
def _(r, B, T):
    w = L.data_layer("w", size=6)
    v = L.data_layer("v", size=12)
    a = L.data_layer("a", size=4)
    b = L.data_layer("b", size=4)
    m = L.data_layer("m", size=12)
    outs = [L.linear_comb_layer(weights=w, vectors=v, size=2),
            L.sum_to_one_norm_layer(L.fc_layer(a, size=3, act="sigmoid")),
            L.cos_sim(a, b), L.cos_sim(a, m, size=3)]
    return outs, {"w": _r(r, B, 6), "v": _r(r, B, 12), "a": _r(r, B, 4),
                  "b": _r(r, B, 4), "m": _r(r, B, 12)}


@case("shape_ops", ["out_prod", "trans", "rotate", "resize", "repeat"])
def _(r, B, T):
    a = L.data_layer("a", size=3)
    b = L.data_layer("b", size=4)
    sq = L.data_layer("sq", size=9, height=3, width=3)
    outs = [L.out_prod_layer(a, b), L.trans_layer(sq),
            L.rotate_layer(sq, height=3, width=3),
            L.resize_layer(b, size=2), L.repeat_layer(a, 2)]
    return outs, {"a": _r(r, B, 3), "b": _r(r, B, 4), "sq": _r(r, B, 9)}


@case("tensor_multiplex_convshift", ["tensor", "multiplex", "conv_shift"])
def _(r, B, T):
    a = L.data_layer("a", size=3)
    b = L.data_layer("b", size=4)
    idx = L.data_layer("idx", size=1)
    c = L.data_layer("c", size=3)   # odd-sized kernel for conv_shift
    outs = [L.tensor_layer(a, b, size=2),
            L.multiplex_layer([idx, a, c]),
            L.conv_shift_layer(b, c)]
    return outs, {"a": _r(r, B, 3), "b": _r(r, B, 4), "c": _r(r, B, 3),
                  "idx": r.randint(0, 2, (B, 1)).astype(np.int32)}


@case("featmap_prelu_selective", ["featmap_expand", "prelu", "selective_fc"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    outs = [L.featmap_expand_layer(x, num_filters=2),
            L.prelu_layer(x),
            L.selective_fc_layer(x, size=5)]
    return outs, {"x": _r(r, B, 4)}


@case("seq_pooling", ["seq_pool"])
def _(r, B, T):
    s = L.data_layer("s", size=4, is_seq=True)
    outs = [L.pooling_layer(s, pooling_type="avg"),
            L.pooling_layer(s, pooling_type="sum"),
            L.pooling_layer(s, pooling_type=L.pooling.SqrtN()),
            L.last_seq(s), L.first_seq(s)]
    return outs, {"s": _seq(r, B, T, 4)}


@case("seq_manip", ["expand", "seq_concat", "seq_reshape", "sub_seq",
                    "seq_slice"])
def _(r, B, T):
    s = L.data_layer("s", size=4, is_seq=True)
    s2 = L.data_layer("s2", size=4, is_seq=True)
    v = L.data_layer("v", size=4)
    off = L.data_layer("off", size=1)
    sz = L.data_layer("sz", size=1)
    outs = [L.pooling_layer(L.expand_layer(v, expand_as=s),
                            pooling_type="sum"),
            L.pooling_layer(L.seq_concat_layer(s, s2), pooling_type="sum"),
            L.pooling_layer(L.seq_reshape_layer(s, reshape_size=8),
                            pooling_type="sum"),
            L.pooling_layer(L.sub_seq_layer(s, off, sz), pooling_type="sum"),
            L.pooling_layer(L.seq_slice_layer(s, starts=off),
                            pooling_type="sum")]
    feed = {"s": _seq(r, B, T, 4), "s2": _seq(r, B, T, 4), "v": _r(r, B, 4),
            "off": np.zeros((B, 1), np.int32),
            "sz": np.ones((B, 1), np.int32)}
    return outs, feed


@case("dropout_test_mode", ["dropout"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    return (L.dropout_layer(L.fc_layer(x, size=4, act="tanh"), 0.5),
            {"x": _r(r, B, 4)})


@case("conv_pool_bn", ["conv", "pool", "batch_norm"])
def _(r, B, T):
    img = L.data_layer("img", size=2 * 6 * 6, height=6, width=6)
    conv = L.img_conv_layer(img, filter_size=3, num_filters=3,
                            num_channels=2, act="tanh", padding=1)
    bn = L.batch_norm_layer(conv, act="tanh")
    pool = L.img_pool_layer(bn, pool_size=2, stride=2)
    return pool, {"img": _r(r, B, 72)}


@case("vision_norms", ["cmrnorm", "cross_channel_norm", "data_norm"])
def _(r, B, T):
    img = L.data_layer("img", size=4 * 3 * 3, height=3, width=3)
    outs = [L.img_cmrnorm_layer(img, size=3),
            L.cross_channel_norm_layer(img, num_channels=4),
            L.data_norm_layer(L.data_layer("x", size=4))]
    return outs, {"img": np.abs(_r(r, B, 36)) + 0.1, "x": _r(r, B, 4)}


@case("vision_shapes", ["maxout", "bilinear_interp", "block_expand", "spp",
                        "pad"])
def _(r, B, T):
    img = L.data_layer("img", size=4 * 4 * 4, height=4, width=4)
    outs = [L.maxout_layer(img, groups=2, num_channels=4),
            L.bilinear_interp_layer(img, out_size_x=6, out_size_y=6),
            L.pooling_layer(L.block_expand_layer(
                img, block_x=2, block_y=2, stride_x=2, stride_y=2,
                num_channels=4), pooling_type="sum"),
            L.spp_layer(img, pyramid_height=2),
            L.pad_layer(img, pad_c=[1, 1], pad_h=[0, 1], pad_w=[1, 0])]
    return outs, {"img": _r(r, B, 64)}


@case("conv_projection_operator", ["mixed"])
def _(r, B, T):
    img = L.data_layer("img", size=2 * 5 * 5, height=5, width=5)
    # conv_operator's second input is a per-sample filter bank
    # [num_filters * num_channels * k * k]
    filt = L.data_layer("filt", size=2 * 2 * 3 * 3)
    m = L.mixed_layer(input=[
        L.conv_projection(img, filter_size=3, num_filters=2, num_channels=2,
                          padding=1),
        L.conv_operator(img, filt, filter_size=3, num_filters=2,
                        num_channels=2, padding=1)])
    return m, {"img": _r(r, B, 50), "filt": _r(r, B, 36)}


@case("recurrent_whole_seq", ["recurrent", "lstmemory", "grumemory"])
def _(r, B, T):
    s = L.data_layer("s", size=3, is_seq=True)
    fc4 = L.fc_layer(s, size=8, act=None, bias_attr=False)
    fc3 = L.fc_layer(s, size=6, act=None, bias_attr=False)
    fc1 = L.fc_layer(s, size=2, act=None, bias_attr=False)
    outs = [L.pooling_layer(L.lstmemory(fc4, size=2), pooling_type="sum"),
            L.pooling_layer(L.grumemory(fc3, size=2), pooling_type="sum"),
            L.pooling_layer(L.recurrent_layer(fc1), pooling_type="sum")]
    return outs, {"s": _seq(r, B, T, 3)}


@case("recurrent_group_steps", ["recurrent_group", "gru_step", "lstm_step",
                                "get_output"])
def _(r, B, T):
    s = L.data_layer("s", size=3, is_seq=True)
    gates3 = L.fc_layer(s, size=6, act=None, bias_attr=False)
    gates4 = L.fc_layer(s, size=8, act=None, bias_attr=False)

    def step(x3, x4):
        gmem = L.memory(name="g", size=2)
        lmem = L.memory(name="l", size=4)
        g = L.gru_step_layer(x3, gmem, size=2, name="g")
        lt = L.lstm_step_layer(x4, lmem, size=2, name="l")
        return g, lt

    grp = L.recurrent_group(step, input=[gates3, gates4])
    out2 = L.get_output_layer(grp, index=1)
    return ([L.pooling_layer(grp, pooling_type="sum"),
             L.pooling_layer(out2, pooling_type="sum")],
            {"s": _seq(r, B, T, 3)})


@case("attention_group", ["attention_context"])
def _(r, B, T):
    s = L.data_layer("s", size=3, is_seq=True)
    enc = L.fc_layer(s, size=4, act="tanh")
    proj = L.fc_layer(enc, size=4, act=None, bias_attr=False)

    def step(x):
        mem = L.memory(name="dec", size=4)
        ctx = N.simple_attention(encoded_sequence=enc_s, encoded_proj=proj_s,
                                 decoder_state=mem)
        return L.fc_layer([ctx, x], size=4, act="tanh", name="dec")

    enc_s = L.StaticInput(enc, is_seq=True)
    proj_s = L.StaticInput(proj, is_seq=True)

    def step2(x, e, p):
        mem = L.memory(name="dec", size=4)
        ctx = N.simple_attention(encoded_sequence=e, encoded_proj=p,
                                 decoder_state=mem)
        return L.fc_layer([ctx, x], size=4, act="tanh", name="dec")

    grp = L.recurrent_group(step2, input=[enc, enc_s, proj_s])
    return L.pooling_layer(grp, pooling_type="sum"), {"s": _seq(r, B, T, 3)}


@case("mdlstm", ["mdlstmemory"])
def _(r, B, T):
    x = L.data_layer("x", size=8)
    gates = L.fc_layer(x, size=5 * 2 * 2 * 2, act=None, bias_attr=False)
    return L.mdlstmemory(gates, size=2, height=2, width=2), {"x": _r(r, B, 8)}


@case("class_costs", ["classification_cost", "ce_selfnorm", "soft_bce",
                      "multi_bce"])
def _(r, B, T):
    x = L.data_layer("x", size=5)
    lab = L.data_layer("lab", size=1)
    soft = L.data_layer("soft", size=3)
    multi = L.data_layer("multi", size=3)
    p1 = L.fc_layer(x, size=3, act="softmax")
    p2 = L.fc_layer(x, size=3, act="softmax", name="p2")
    p3 = L.fc_layer(x, size=3, act="sigmoid")
    outs = [L.classification_cost(input=p1, label=lab),
            L.cross_entropy_with_selfnorm(p2, lab),
            L.soft_binary_class_cross_entropy(p3, soft),
            L.multi_binary_label_cross_entropy(p3, multi)]
    feed = {"x": _r(r, B, 5),
            "lab": r.randint(0, 3, (B, 1)).astype(np.int32),
            "soft": r.uniform(0.1, 0.9, (B, 3)).astype(np.float32),
            "multi": r.randint(0, 2, (B, 3)).astype(np.float32)}
    return outs, feed


@case("regress_costs", ["mse", "huber", "smooth_l1", "sum_cost", "rank",
                        "lambda"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    y = L.data_layer("y", size=3)
    blab = L.data_layer("blab", size=1)
    rlab = L.data_layer("rlab", size=1)
    # lambda rank runs list-wise over sequences of per-doc scores
    ss = L.data_layer("ss", size=1, is_seq=True)
    srel = L.data_layer("srel", size=1, is_seq=True)
    pred = L.fc_layer(x, size=3, act=None)
    lpred = L.fc_layer(x, size=1, act=None, name="lp")
    rpred = L.fc_layer(y, size=1, act=None, name="rp")
    hpred = L.fc_layer(x, size=1, act=None, name="hp")
    outs = [L.mse_cost(pred, y),
            L.huber_cost(hpred, blab),
            L.smooth_l1_cost(pred, y),
            L.sum_cost(L.fc_layer(x, size=1, act="sigmoid")),
            L.rank_cost(left=lpred, right=rpred, label=rlab),
            L.lambda_cost(input=ss, score=srel, NDCG_num=2)]
    feed = {"x": _r(r, B, 4), "y": _r(r, B, 3),
            "blab": r.randint(0, 2, (B, 1)).astype(np.int32),
            "rlab": r.uniform(0, 1, (B, 1)).astype(np.float32),
            "ss": pad_sequences(
                [r.randn(t, 1).astype(np.float32)
                 for t in ([2, 3, 2][:B] + [2] * max(0, B - 3))], max_len=T),
            "srel": pad_sequences(
                [r.uniform(0, 1, (t, 1)).astype(np.float32)
                 for t in ([2, 3, 2][:B] + [2] * max(0, B - 3))], max_len=T)}
    return outs, feed


@case("structured_costs", ["crf", "ctc"])
def _(r, B, T):
    s = L.data_layer("s", size=3, is_seq=True)
    lab = L.data_layer("lab", size=3, is_seq=True)
    em = L.fc_layer(s, size=3, act=None)
    em5 = L.fc_layer(s, size=5, act=None, name="em5")
    outs = [L.crf_layer(em, lab, size=3),
            L.ctc_layer(em5, lab, size=5)]
    # CTC needs input long enough for the label (+ blanks); keep inputs at
    # full length T and labels short, or the loss hits its impossible-path
    # sentinel and gradients vanish
    lab_lens = ([1, 2, 1][:B] + [1] * max(0, B - 3))
    labs = pad_sequences([r.randint(0, 3, (l,)) for l in lab_lens],
                         max_len=T)
    full = pad_sequences([_r(r, T, 3) for _ in range(B)], max_len=T)
    return outs, {"s": full, "lab": labs}


@case("sampling_costs", ["nce", "hsigmoid"])
def _(r, B, T):
    x = L.data_layer("x", size=4)
    lab = L.data_layer("lab", size=1)
    outs = [L.nce_layer(x, lab, num_classes=7, num_neg_samples=3),
            L.hsigmoid(x, lab, num_classes=7)]
    return outs, {"x": _r(r, B, 4),
                  "lab": r.randint(0, 7, (B, 1)).astype(np.int32)}


# ---------------------------------------------------------------- engine

def _loss_over(topo, outs, feed_rebuild):
    def loss_fn(bundle):
        feed = feed_rebuild(bundle["inp"])
        out = topo.apply(bundle["p"], feed, mode="test",
                         rng=jax.random.PRNGKey(7))
        vals = out if isinstance(out, tuple) else (out,)
        total = 0.0
        for v in vals:
            d = value_data(v)
            # promote (never downcast): f64 sweeps must stay f64 or the
            # central differences drown in f32 rounding noise
            total = total + jnp.mean(d.astype(jnp.result_type(d.dtype,
                                                              jnp.float32)))
        return total
    return loss_fn


def run_sweep_case(name, B, T):
    build, _ = CASES[name]
    reset_names()
    # deterministic digest: str hash() is salted per interpreter, which made
    # failures non-reproducible across pytest runs
    r = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
    outs, feed = build(r, B, T)
    outs = outs if isinstance(outs, list) else [outs]
    topo = Topology(outs)
    params = topo.init(jax.random.PRNGKey(0))
    # float64 everywhere: central differences on f32 are noise-limited for
    # small gradients (the reference's checker runs in double for the same
    # reason, WITH_DOUBLE)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float64)
        if np.issubdtype(np.asarray(x).dtype, np.floating) else x, params)

    # split feed: float arrays (and SequenceBatch float data) are
    # differentiable inputs; ints and lengths stay static
    diff_inp, static = {}, {}
    nondiff = NONDIFF_INPUTS.get(name, set())
    for k, v in feed.items():
        if k in nondiff:
            static[k] = ("const", v)
        elif isinstance(v, SequenceBatch):
            if np.issubdtype(np.asarray(v.data).dtype, np.floating):
                diff_inp[k] = jnp.asarray(v.data, jnp.float64)
                static[k] = ("seq", v.lengths)
            else:
                static[k] = ("const", v)
        elif np.issubdtype(np.asarray(v).dtype, np.floating):
            diff_inp[k] = jnp.asarray(v, jnp.float64)
            static[k] = ("arr", None)
        else:
            static[k] = ("const", jnp.asarray(v))

    def rebuild(inp):
        out = {}
        for k, (kind, aux) in static.items():
            if kind == "seq":
                out[k] = SequenceBatch(data=inp[k], lengths=aux)
            elif kind == "arr":
                out[k] = inp[k]
            else:
                out[k] = aux
        return out

    loss_fn = _loss_over(topo, outs, rebuild)
    check_grads(loss_fn, {"p": params, "inp": diff_inp},
                eps=1e-5, rtol=1e-2, atol=1e-6, max_elems_per_leaf=2,
                rng=np.random.RandomState(0))


@pytest.mark.parametrize("variant", [(B0, T0), (1, 6)],
                         ids=["b3t4", "b1t6"])
@pytest.mark.parametrize("name", sorted(CASES))
def test_layer_grad(name, variant):
    from paddle_tpu.core import dtypes
    jax.config.update("jax_enable_x64", True)
    dtypes.set_policy("float64", "float64")
    try:
        run_sweep_case(name, *variant)
    finally:
        dtypes.set_policy("float32", None)
        jax.config.update("jax_enable_x64", False)


def test_registry_fully_covered():
    """Every registered layer type is either swept or explicitly excluded —
    the registry-driven guarantee that new layers get gradient coverage."""
    from paddle_tpu.layers.graph import _LAYER_IMPLS
    covered = set()
    for _, (_, covers) in CASES.items():
        covered.update(covers)
    missing = sorted(set(_LAYER_IMPLS) - covered - set(EXCLUDED))
    assert not missing, f"layer types without a gradcheck case: {missing}"
    stale = sorted(set(EXCLUDED) & covered)
    assert not stale, f"excluded types that now have cases: {stale}"
