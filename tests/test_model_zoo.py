"""Model-zoo pretrained-import parity (reference demo/model_zoo/resnet:
get_model.sh + classify.py ran a DOWNLOADED pretrained ResNet; this
zero-egress twin proves the import path itself — a torch checkpoint in
torchvision's ResNet key convention converts into our pytree and
reproduces torch's own forward bit-for-bit-close, BN running stats
included — so a user pointing `extract_features.py import_torch` at a
real torchvision .pth gets the reference workflow end to end)."""

import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn.functional as F

# end-to-end demo/torch-import runs (multi-minute subprocesses);
# nightly lane — README "Running the tests"
pytestmark = pytest.mark.slow

BLOCKS = (3, 4, 6, 3)
WIDTHS = (256, 512, 1024, 2048)
NUM_CLASSES = 10


def _torch_resnet50_state_dict(seed=0):
    """Deterministic state_dict with torchvision ResNet-50 key names and
    shapes (fc sized NUM_CLASSES) — stands in for a downloaded
    resnet50.pth; running stats are non-trivial so eval-mode BN is
    genuinely exercised."""
    g = torch.Generator().manual_seed(seed)

    def t(*shape, scale=None):
        if scale is None:
            # He-ish conv init: keeps activations O(1) through 16 blocks
            # so the torch-vs-jax comparison is numerically meaningful
            fan = int(np.prod(shape[1:])) if len(shape) == 4 else shape[-1]
            scale = (2.0 / fan) ** 0.5
        return torch.randn(*shape, generator=g) * scale

    def bn_entries(prefix, c):
        # running_var > gamma^2 so each eval-mode BN damps slightly:
        # with arbitrary (non-fitted) running stats the network would
        # otherwise amplify ~1.3x per BN and reach 1e6 activations,
        # drowning the parity check in f32 rounding noise
        return {f"{prefix}.weight": 1.0 + t(c, scale=0.05),
                f"{prefix}.bias": t(c, scale=0.05),
                f"{prefix}.running_mean": t(c, scale=0.1),
                f"{prefix}.running_var": 2.5 + t(c, scale=0.1).abs()}

    sd = {"conv1.weight": t(64, 3, 7, 7)}
    sd.update(bn_entries("bn1", 64))
    cin = 64
    for si, (n, w) in enumerate(zip(BLOCKS, WIDTHS)):
        mid = w // 4
        for bi in range(n):
            p = f"layer{si + 1}.{bi}"
            sd[f"{p}.conv1.weight"] = t(mid, cin, 1, 1)
            sd.update(bn_entries(f"{p}.bn1", mid))
            sd[f"{p}.conv2.weight"] = t(mid, mid, 3, 3)
            sd.update(bn_entries(f"{p}.bn2", mid))
            sd[f"{p}.conv3.weight"] = t(w, mid, 1, 1)
            sd.update(bn_entries(f"{p}.bn3", w))
            if bi == 0:
                sd[f"{p}.downsample.0.weight"] = t(w, cin, 1, 1)
                sd.update(bn_entries(f"{p}.downsample.1", w))
            cin = w
    sd["fc.weight"] = t(NUM_CLASSES, cin, scale=0.02)
    sd["fc.bias"] = t(NUM_CLASSES, scale=0.02)
    return sd


def _torch_forward(sd, x_nchw):
    """Functional eval-mode ResNet-50 v1.5 forward straight off the
    state_dict — the oracle the imported JAX model must match."""

    def bn(x, p):
        return F.batch_norm(x, sd[f"{p}.running_mean"],
                            sd[f"{p}.running_var"], sd[f"{p}.weight"],
                            sd[f"{p}.bias"], training=False)

    def block(x, p, stride):
        y = F.relu(bn(F.conv2d(x, sd[f"{p}.conv1.weight"]), f"{p}.bn1"))
        y = F.relu(bn(F.conv2d(y, sd[f"{p}.conv2.weight"], stride=stride,
                               padding=1), f"{p}.bn2"))
        y = bn(F.conv2d(y, sd[f"{p}.conv3.weight"]), f"{p}.bn3")
        if f"{p}.downsample.0.weight" in sd:
            x = bn(F.conv2d(x, sd[f"{p}.downsample.0.weight"],
                            stride=stride), f"{p}.downsample.1")
        return F.relu(x + y)

    with torch.no_grad():
        x = F.conv2d(x_nchw, sd["conv1.weight"], stride=2, padding=3)
        x = F.relu(bn(x, "bn1"))
        x = F.max_pool2d(x, 3, 2, 1)
        for si, n in enumerate(BLOCKS):
            for bi in range(n):
                x = block(x, f"layer{si + 1}.{bi}",
                          2 if (bi == 0 and si > 0) else 1)
        pooled = x.mean(dim=(2, 3))
        logits = F.linear(pooled, sd["fc.weight"], sd["fc.bias"])
    return pooled.numpy(), logits.numpy()


def _images(b=2, hw=32, seed=1):
    rng = np.random.RandomState(seed)
    return rng.rand(b, hw, hw, 3).astype(np.float32)


def test_torchvision_resnet_import_matches_torch_forward():
    """The golden proof for the model-zoo row: importing a torch
    checkpoint and running OUR ResNet reproduces TORCH's forward on the
    same weights (features and logits)."""
    from paddle_tpu.models import resnet
    from paddle_tpu.utils.tools.torch_import import import_torchvision_resnet

    sd = _torch_resnet50_state_dict()
    params, state = import_torchvision_resnet(sd, depth=50)
    imgs = _images()
    want_pool, want_logits = _torch_forward(
        sd, torch.from_numpy(imgs.transpose(0, 3, 1, 2)))

    got_pool = np.asarray(resnet.features(params, state, jnp.asarray(imgs)))
    got_logits, _ = resnet.forward(params, state, jnp.asarray(imgs),
                                   train=False)
    np.testing.assert_allclose(got_pool, want_pool, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_logits), want_logits,
                               rtol=1e-4, atol=1e-4)


def test_model_zoo_demo_end_to_end(tmp_path):
    """The reference workflow: get_model (here: import_torch) ->
    classify.py --job=extract (here: resnet --layer pool) — run through
    the actual demo CLI, output equals the torch oracle and the
    committed golden."""
    sd = _torch_resnet50_state_dict()
    pt = tmp_path / "resnet50_det.pt"
    torch.save(sd, str(pt))
    imgs = _images()
    np.save(tmp_path / "imgs.npy", imgs)

    demo = os.path.join(os.path.dirname(__file__), "..", "demo",
                        "model_zoo", "extract_features.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ck = tmp_path / "ckpt"
    r = subprocess.run(
        [sys.executable, demo, "import_torch", "--torch_file", str(pt),
         "--depth", "50", "--out_dir", str(ck)],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, demo, "resnet", "--model_dir", str(ck),
         "--layer", "pool", "--images", str(tmp_path / "imgs.npy"),
         "--out", str(tmp_path / "feats.npz")],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr

    feats = np.load(tmp_path / "feats.npz")["features"]
    want_pool, _ = _torch_forward(
        sd, torch.from_numpy(imgs.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(feats, want_pool, rtol=1e-4, atol=1e-4)

    golden_path = os.path.join(os.path.dirname(__file__), "..", "demo",
                               "model_zoo", "golden_features.npz")
    if os.path.exists(golden_path):
        golden = np.load(golden_path)["features"]
        np.testing.assert_allclose(feats, golden, rtol=1e-5, atol=1e-5)


def test_resnet_mapping_is_exhaustive():
    """Every tensor in a torchvision-convention checkpoint is consumed,
    and every leaf of our pytree is written — nothing silently keeps its
    random init (the classic weight-import failure mode)."""
    from paddle_tpu.utils.tools.torch_import import resnet_mapping
    sd = _torch_resnet50_state_dict()
    pm, sm = resnet_mapping(50)
    used = set(pm.values()) | set(sm.values())
    # num_batches_tracked has no analog; everything else must be used
    assert used == set(sd.keys())

    from paddle_tpu.models import resnet
    params, state = resnet.init(jax.random.PRNGKey(0), depth=50,
                                num_classes=NUM_CLASSES)

    def paths(tree, prefix=""):
        if isinstance(tree, dict):
            for k, v in tree.items():
                yield from paths(v, f"{prefix}{k}/")
        else:
            yield prefix.rstrip("/")

    assert set(pm) == set(paths(params))
    assert set(sm) == set(paths(state))
