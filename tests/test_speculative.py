"""Speculative decoding on the slot engine (DecodeEngine speculate_k > 0).

A truncated-trunk draft rolls k tokens ahead per slot; the target's ONE
chunked step scores the committed token + k draft lanes (all_lanes) and
the host accepts the longest greedy-matching prefix — so every verify
step nets >= 1 token and the emitted stream is BIT-IDENTICAL to
non-speculative greedy decode for ANY draft, on every layout.  Trace
discipline: one warm-up trace for the engine step, one for the draft
rollout, zero retraces across acceptance churn (k_eff, feeds, and
budgets are data, not shape).

Fast lane: the degenerate/boundary/adversarial facts at tiny shapes.
Heavy k x layout x quant grids, chaos recovery, and continuation replay
ride the slow lane (the tier-1 wrapper is saturated on this host).
"""

import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.serving import GenerationBatcher, ServingMetrics
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.serving.speculative import DraftTrunk, make_draft
from paddle_tpu.testing import forbid_retrace
from paddle_tpu.utils.error import ConfigError

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BS, SPEC_K = 48, 4, 8, 3


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def adversarial_params():
    # independently initialized: near-zero agreement with `params`'
    # greedy argmaxes, the draft-quality worst case
    return transformer.init(jax.random.PRNGKey(7), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


def _engine(params, **kw):
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("speculate_k", SPEC_K)
    if kw["speculate_k"] and "draft" not in kw:
        kw["draft"] = make_draft(params, layers=1)
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, **kw)


@pytest.fixture(scope="module")
def spec_engine(params):
    # ONE paged spec engine shared across the fast lane — warm-up is the
    # expensive part, and sharing also pins the trace counters across
    # every drive below (they must END at 1/1, not per-test 1/1).  Paged
    # because that's the layout with real rollback code (chain
    # truncation); the adversarial engine below covers slab, and the
    # slow-lane grid sweeps both layouts at every k.
    return _engine(params, name="spec_shared", kv_layout="paged",
                   kv_block_size=BS)


def _prompt(rng, n=None):
    return rng.randint(1, VOCAB, n or rng.randint(1, 30)).astype(np.int32)


def _oracle(params, prompt, n_tokens, eos_id=None):
    ids = np.asarray(transformer.lm_generate(
        params, prompt[None], max_len=MAX_LEN, num_heads=HEADS,
        eos_id=eos_id, prompt_lengths=np.asarray([prompt.size])))
    return ids[0, prompt.size:prompt.size + n_tokens].tolist()


def _drive(bat, cases, stagger_s=0.002):
    """Concurrent client threads (admissions land mid-verify)."""
    results, excs = [None] * len(cases), [None] * len(cases)

    def client(i):
        prompt, n = cases[i]
        try:
            time.sleep(stagger_s * i)
            results[i] = bat.submit(prompt, max_tokens=n).result(180)
        except Exception as e:      # noqa: BLE001
            excs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    assert all(e is None for e in excs), excs
    return results


# ------------------------------------------------- bit-identity core


def test_spec_streams_bit_identical_paged(params, spec_engine):
    """Staggered concurrent streams off the speculating paged engine
    reproduce the single-request oracle token for token — draft-lane
    K/V past the accepted prefix rolls back by chain truncation
    (PagedKVState.truncate), ledger balanced — with real acceptance
    evidence (lanes drafted AND accepted) and >= 1 token per verify
    step."""
    eng = spec_engine
    eng.metrics = ServingMetrics()
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(0)
    cases = [(_prompt(rng), 4 + (i % 7)) for i in range(6)]
    with forbid_retrace(eng, eng.draft, what="paged spec serving"):
        results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]
    snap = eng.metrics.snapshot()
    assert snap["drafted_tokens_total"] > 0, snap
    assert snap["accepted_tokens_total"] > 0, snap
    assert snap["spec_tokens_per_step"] >= 1.0, snap
    assert snap["speculate_k"] == SPEC_K, snap
    eng._paged.check()


@pytest.mark.slow
def test_adversarial_draft_bit_identical_nets_one(params,
                                                  adversarial_params):
    """A draft that (almost) never agrees with the target costs
    throughput, never correctness: streams stay oracle-identical and
    every verify step still nets >= 1 token."""
    eng = _engine(params, name="spec_adv",
                  draft=make_draft(adversarial_params, layers=1))
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(2)
    cases = [(_prompt(rng), 5 + (i % 5)) for i in range(5)]
    results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]
    snap = eng.metrics.snapshot()
    assert snap["drafted_tokens_total"] > 0, snap
    assert snap["spec_tokens_per_step"] >= 1.0, snap
    assert snap["spec_acceptance_rate"] < 0.5, snap


# ------------------------------------------------- boundary behavior


@pytest.mark.slow
def test_k1_degenerate_matches_nonspec(params):
    """speculate_k=1 is the smallest speculating engine: one draft lane
    per verify span, streams byte-for-byte the oracle's, tokens per
    step within [1, 2].  Slow lane: the k x layout grid already drives
    k=1 on both layouts; this adds only the tokens-per-step bound."""
    eng = _engine(params, name="spec_k1", speculate_k=1)
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(3)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(4)]
    results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]
    snap = eng.metrics.snapshot()
    assert snap["drafted_tokens_total"] > 0, snap
    assert 1.0 <= snap["spec_tokens_per_step"] <= 2.0, snap


def test_eos_inside_accepted_run(params, spec_engine):
    """EOS landing INSIDE an accepted draft run must clip the emission
    exactly where non-speculative decode would stop — accepted lanes
    past the EOS are discarded, finish_reason is eos."""
    bat = GenerationBatcher(spec_engine)
    rng = np.random.RandomState(4)
    for _ in range(5):              # the 6th seeded prompt's stream
        _prompt(rng, 9)             # first emits its EOS id at index 2
    prompt = _prompt(rng, 9)
    full = _oracle(params, prompt, 12)
    eos = full[2]
    assert eos not in full[:2], full    # seeded: EOS lands MID-run
    res = bat.submit(prompt, max_tokens=12, eos_id=eos).result(60)
    assert res["finish_reason"] == "eos", res
    assert res["tokens"] == full[:3], (res["tokens"], full)
    # immediate first-token EOS: the degenerate clip
    res = bat.submit(prompt, max_tokens=12, eos_id=full[0]).result(60)
    assert res["finish_reason"] == "eos" and res["tokens"] == [full[0]]
    bat.close()


def test_max_tokens_boundary_mid_run(params, spec_engine):
    """max_tokens landing inside an accepted run truncates the emission
    at the budget, exactly like the non-speculating engine."""
    bat = GenerationBatcher(spec_engine)
    rng = np.random.RandomState(5)
    prompt = _prompt(rng, 7)
    full = _oracle(params, prompt, SPEC_K + 2)
    for n in (1, 2, SPEC_K + 2):
        res = bat.submit(prompt, max_tokens=n).result(60)
        assert res["finish_reason"] == "length", (n, res)
        assert res["tokens"] == full[:n], (n, res["tokens"], full[:n])
    bat.close()


# ------------------------------------------- metrics + trace + config


def test_metrics_swap_reapplies_speculate_k(params, spec_engine):
    """The bench's per-drive metrics reset: a swapped-in ServingMetrics
    inherits the speculate_k gauge immediately (config, like the chunk
    gauge) and the spec counters grow on the NEW object only."""
    eng = spec_engine
    old = eng.metrics
    eng.metrics = fresh = ServingMetrics()
    assert fresh.snapshot()["speculate_k"] == SPEC_K
    before_old = old.snapshot()["drafted_tokens_total"]
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(6)
    res = bat.submit(_prompt(rng, 5), max_tokens=6).result(60)
    bat.close()
    assert res["tokens"] == _oracle(params, _prompt(
        np.random.RandomState(6), 5), 6)
    snap = fresh.snapshot()
    assert snap["drafted_tokens_total"] > 0, snap
    assert snap["spec_steps_total"] > 0, snap
    assert old.snapshot()["drafted_tokens_total"] == before_old
    # prometheus surface: acceptance evidence renders off the new object
    text = fresh.render_prometheus()
    assert f"{fresh.name}_speculate_k {SPEC_K}" in text
    assert "_spec_acceptance_rate " in text


def test_spec_trace_discipline(spec_engine):
    """After every fast-lane drive above: the engine step traced ONCE
    (warm-up) and the draft rollout traced ONCE — acceptance churn,
    EOS clips, and budget truncation never retraced either."""
    assert spec_engine.step_trace_count == 1
    assert spec_engine.draft.trace_count == 1


def test_spec_config_validation(params):
    """The config seams: a draft without speculate_k, speculate_k
    without the unified chunked step, and a mismatched DraftTrunk all
    fail fast at construction."""
    with pytest.raises(ConfigError, match="draft"):
        _engine(params, speculate_k=0, draft=make_draft(params, layers=1))
    with pytest.raises(ConfigError, match="chunked"):
        _engine(params, prefill_chunk=0)
    with pytest.raises(ConfigError, match="does not match"):
        mismatched = DraftTrunk(make_draft(params, layers=1),
                                k=SPEC_K + 1, num_slots=SLOTS,
                                max_len=MAX_LEN, chunk=SPEC_K + 3,
                                num_heads=HEADS)
        _engine(params, draft=mismatched)
    with pytest.raises(ConfigError, match="layers"):
        make_draft(params, layers=LAYERS + 1)


# ------------------------------------------------------- slow lane


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["slab", "paged"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_spec_k_layout_grid_bit_identical(params, layout, k):
    """k x layout sweep: every (k, layout) pairing reproduces the
    oracle under staggered concurrency."""
    kw = {"kv_layout": layout}
    if layout == "paged":
        kw["kv_block_size"] = BS
    eng = _engine(params, name=f"spec_{layout}_{k}", speculate_k=k, **kw)
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(10 + k)
    cases = [(_prompt(rng), 4 + (i % 6)) for i in range(6)]
    with forbid_retrace(eng, eng.draft, what=f"{layout} spec k={k}"):
        results = _drive(bat, cases)
    bat.close()
    assert [r["tokens"] for r in results] == \
        [_oracle(params, p, n) for p, n in cases]


@pytest.mark.slow
def test_spec_int8_kv_quant_draft_matches_nonspec_twin(params):
    """Quant composition: an int8-KV paged spec engine with an int8
    draft emits the SAME streams as its non-speculating int8-KV twin —
    bit-identity holds within the quantization mode."""
    kw = dict(kv_layout="paged", kv_block_size=BS, kv_dtype="int8")
    spec = _engine(params, name="spec_q",
                   draft=make_draft(params, layers=1, quantize=True),
                   **kw)
    twin = _engine(params, name="spec_q_twin", speculate_k=0, **kw)
    rng = np.random.RandomState(20)
    cases = [(_prompt(rng), 4 + (i % 6)) for i in range(6)]
    bat = GenerationBatcher(spec)
    got = [r["tokens"] for r in _drive(bat, cases)]
    bat.close()
    bat = GenerationBatcher(twin)
    ref = [r["tokens"] for r in _drive(bat, cases)]
    bat.close()
    assert got == ref
    assert spec.metrics.snapshot()["drafted_tokens_total"] > 0
    spec._paged.check()


@pytest.mark.slow
def test_spec_supervisor_recovery_bit_identical(params):
    """PR-6 chaos on the speculating engine: an injected decode-step
    fault rebuilds BOTH caches (target + draft) and re-seats every
    stream; contexts re-feed the draft through _draft_seed — all
    streams oracle-identical, zero extra traces."""
    eng = _engine(params, name="spec_chaos", kv_layout="paged",
                  kv_block_size=BS)
    rng = np.random.RandomState(30)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(8)]
    ref = [_oracle(params, p, n) for p, n in cases]
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(eng, supervisor=sup)
    faults.install_spec("serving.decode_step:at=6")
    with forbid_retrace(eng, eng.draft, what="spec chaos recovery"):
        results = _drive(bat, cases)
        bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    assert [r["tokens"] for r in results] == ref
    snap = eng.metrics.snapshot()
    assert snap["evictions"]["recovered"] >= 1
    eng._paged.check()


@pytest.mark.slow
def test_spec_continuation_replay_bit_identical(params):
    """PR-7 continuations ride speculation: a stream interrupted after
    j delivered tokens finishes emitting ONLY the remainder, and the
    replayed context re-feeds the draft like any committed prefix."""
    eng = _engine(params, name="spec_cont")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(40)
    for plen, n, j in ((5, 10, 3), (16, 12, 7)):
        prompt = _prompt(rng, plen)
        full = _oracle(params, prompt, n)
        res = bat.submit(prompt, replay=np.asarray(full[:j], np.int32),
                         max_tokens=n - j).result(60)
        assert res["tokens"] == full[j:], (plen, n, j)
    bat.close()
