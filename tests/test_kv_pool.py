"""Paged KV cache (serving/kv_pool.py + DecodeEngine kv_layout="paged").

The correctness bar is the slab's own: every greedy stream served
through the paged layout — block-pool admission, prefix-cache seating,
copy-on-write forks, pool-pressure preemption and re-seat, supervisor
recovery, continuation replay — must be BIT-IDENTICAL to the
single-request oracle (``models/transformer.lm_generate``) and hence to
the slab layout.  Trace discipline: ONE warm-up trace for the paged
step (plus one block-write and one block-fork executable), ZERO traces
across any block-table churn — the table is data, not shape.

The allocator's refcount ledger (``PagedKVState.check``: every block's
refcount equals its slot-chain + prefix-index references; the free list
and refcounts partition the pool exactly) is audited after every
scenario here, including a chaos run through the PR-6 fault points —
no leak, no double-free.
"""

import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.serving import (GenerationBatcher, InvalidRequestError,
                                ServingMetrics)
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.serving.kv_pool import (SCRATCH_BLOCK, BlockPool,
                                        InsufficientBlocksError,
                                        PagedKVState, PrefixIndex)
from paddle_tpu.testing import assert_no_retrace
from paddle_tpu.utils.error import ConfigError

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BUCKETS, BS = 48, 4, (8, 16), 8


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def engine(params):
    """Auto-sized pool (the slab-equivalent byte budget), prefix cache
    on — the default paged configuration."""
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=BUCKETS,
                        name="paged_lm", kv_layout="paged",
                        kv_block_size=BS)


def _prompt(rng, n=None):
    return rng.randint(1, VOCAB, n or rng.randint(3, BUCKETS[-1] + 1)
                       ).astype(np.int32)


def _oracle(params, engine, prompt, n_tokens, eos_id=None):
    """Single-request greedy lm_generate at the engine's prefill bucket
    (same composition the slab parity tests pin)."""
    bucket = engine.prefill_bucket_for(prompt.size)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :prompt.size] = prompt
    ids = np.asarray(transformer.lm_generate(
        params, padded, max_len=engine.max_len, num_heads=HEADS,
        eos_id=eos_id, prompt_lengths=np.asarray([prompt.size])))
    return ids[0, prompt.size:prompt.size + n_tokens].tolist()


def _drive(bat, cases, stagger_s=0.004):
    """Concurrent client threads; returns results (None on failure) and
    per-request exceptions."""
    results, excs = [None] * len(cases), [None] * len(cases)

    def client(i):
        prompt, n = cases[i]
        try:
            time.sleep(stagger_s * i)
            results[i] = bat.submit(prompt, max_tokens=n).result(120)
        except Exception as e:      # noqa: BLE001
            excs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    return results, excs


def _audit(engine):
    """The no-leak/no-double-free ledger invariant, plus: every slot is
    free again, so only prefix-index references may keep blocks held."""
    engine._paged.check()
    assert engine.free_slots == engine.num_slots
    held = engine._paged.pool.num_used
    idx = engine._paged.index
    assert held == (len({b for _c, ch in idx._entries.values()
                         for b in ch}) if idx is not None else 0)


# ------------------------------------------------------- allocator units


def test_block_pool_alloc_share_release_and_errors():
    pool = BlockPool(num_blocks=5, block_size=4)
    assert pool.num_allocatable == 4 and pool.num_free == 4
    a, b = pool.alloc(), pool.alloc()
    assert {a, b}.isdisjoint({SCRATCH_BLOCK})
    assert pool.refcount(a) == 1
    pool.share(a)
    assert pool.refcount(a) == 2
    pool.release(a)
    pool.release(a)                     # refcount 0 -> back on free list
    assert pool.num_free == 3
    with pytest.raises(RuntimeError, match="double free"):
        pool.release(a)
    with pytest.raises(RuntimeError, match="unowned"):
        pool.share(a)
    c, d = pool.alloc(), pool.alloc()
    assert pool.alloc() is not None     # 4th allocatable
    assert pool.alloc() is None         # dry, not an exception
    pool.check()
    pool.release(b), pool.release(c), pool.release(d)
    # a manufactured leak trips check()
    pool._ref[2] += 1
    with pytest.raises(AssertionError):
        pool.check()
    with pytest.raises(ConfigError):
        BlockPool(num_blocks=1, block_size=4)
    with pytest.raises(ConfigError):
        BlockPool(num_blocks=4, block_size=0)


def test_prefix_index_longest_match_and_lru():
    pool = BlockPool(num_blocks=12, block_size=4)
    chain = [pool.alloc() for _ in range(3)]
    idx = PrefixIndex(pool)
    toks = list(range(1, 11))               # 10 tokens = 2.5 blocks
    idx.register(toks, chain)
    # entries: [0:4], [0:8] aligned + the exact 10-token partial tail
    assert len(idx) == 3
    assert idx.lookup(toks) == (10, chain)              # exact, tail too
    cov, got = idx.lookup(toks[:8] + [99, 98, 97])      # divergent tail
    assert cov == 8 and got == chain[:2]
    cov, got = idx.lookup(toks[:4] + [99] * 6)
    assert cov == 4 and got == chain[:1]
    assert idx.lookup([99, 98]) == (0, [])
    # one pool reference per (entry, block): 1 + 2 + 3
    assert idx.block_refs == 6
    assert pool.refcount(chain[0]) == 4     # owner + three entries
    # LRU: evicting all entries releases exactly the index references
    idx.clear()
    assert len(idx) == 0 and idx.block_refs == 0
    for b in chain:
        assert pool.refcount(b) == 1
        pool.release(b)
    assert pool.num_free == pool.num_allocatable
    pool.check()


def test_paged_state_seating_cow_victim_and_atomic_exhaustion():
    st = PagedKVState(num_slots=2, num_blocks=6, block_size=4, max_len=16)
    chain = st.seat_fresh(0, 6)             # 2 blocks
    st.register_prefix(list(range(1, 7)), 0)
    # a sharer seats on the registered chain: refcounts go shared
    st.seat_shared(1, chain, 6)
    assert st.pool.refcount(chain[0]) > 1
    # slot 1's next write into the shared tail block must CoW-fork it
    plan = st.write_plan(1, 5)
    assert plan[0] == "cow" and plan[2] == chain[1]
    assert st.tables[1, 1] == plan[3] != chain[1]
    # growth past the chain allocates ("alloc"), then the pool runs dry
    # mid-claim: seat_fresh is all-or-nothing and the ledger stays clean
    assert st.write_plan(1, 8)[0] == "alloc"
    with pytest.raises(InsufficientBlocksError):
        st.seat_fresh(None, 99)             # would need 25 blocks
    st.check()
    # victim order: youngest (most recently seated) goes first
    assert st.victim(exclude=set()) == 1
    assert st.victim(exclude={1}) == 0
    st.evict(1)
    st.evict(0)
    st.check()
    assert (st.tables == SCRATCH_BLOCK).all()


# ------------------------------------------------------------- parity


def test_paged_staggered_admissions_bit_identical_to_lm_generate(
        params, engine):
    """The acceptance drive on the paged layout: more requests than
    slots, mixed prompt lengths and max_tokens, staggered so admissions
    and evictions churn the block tables mid-decode — every stream must
    equal the single-request oracle exactly, and the refcount ledger
    must balance afterwards."""
    engine.metrics = ServingMetrics()
    bat = GenerationBatcher(engine, default_max_tokens=8)
    rng = np.random.RandomState(1)
    cases = [(_prompt(rng), int(rng.randint(2, 13))) for _ in range(12)]
    results, excs = _drive(bat, cases)
    bat.close()
    assert all(e is None for e in excs), excs
    for (prompt, n), res in zip(cases, results):
        assert res["finish_reason"] == "length"
        assert res["tokens"] == _oracle(params, engine, prompt, n), \
            f"prompt len {prompt.size}, n {n}"
    snap = engine.metrics.snapshot()
    assert snap["evictions"]["length"] == 12
    assert snap["kv_blocks_total"] == engine._paged.pool.num_allocatable
    _audit(engine)


def test_prefix_cache_hit_and_cow_fork_bit_identical(params, engine):
    """Prefix sharing end to end: a leader registers a 1.5-block system
    prompt; an EXACT duplicate then seats inside the shared tail block
    (copy-on-write fork on its first write) and a divergent prompt
    seats on the shared aligned block — both by reference, neither
    re-prefilled, all three streams bit-identical to the oracle."""
    engine.metrics = ServingMetrics()
    rng = np.random.RandomState(2)
    sys_prompt = _prompt(rng, BS + BS // 2)
    divergent = np.concatenate([sys_prompt[:BS], _prompt(rng, 4)])
    bat = GenerationBatcher(engine)
    pre0 = engine.prefill_positions_total
    lead = bat.submit(sys_prompt, max_tokens=6).result(60)
    prefilled_lead = engine.prefill_positions_total - pre0
    dup = bat.submit(sys_prompt, max_tokens=6).result(60)
    div = bat.submit(divergent, max_tokens=6).result(60)
    bat.close()
    assert lead["tokens"] == dup["tokens"] \
        == _oracle(params, engine, sys_prompt, 6)
    assert div["tokens"] == _oracle(params, engine, divergent, 6)
    snap = engine.metrics.snapshot()
    assert snap["prefix_cache_hits_total"] == 2
    assert snap["cow_forks_total"] >= 1
    # the hits never touched the prefill ladder
    assert engine.prefill_positions_total - pre0 == prefilled_lead
    _audit(engine)


def test_paged_equals_slab_layout_token_for_token(params, engine):
    """The two memory layouts are one compiled trunk: the same prompts
    through a slab engine produce byte-identical streams."""
    slab = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=BUCKETS,
                        name="slab_twin")
    rng = np.random.RandomState(3)
    cases = [(_prompt(rng), 7) for _ in range(6)]
    engine.metrics = ServingMetrics()
    for eng in (engine, slab):
        bat = GenerationBatcher(eng)
        outs = [bat.submit(p, max_tokens=n).result(60)["tokens"]
                for p, n in cases]
        bat.close()
        if eng is engine:
            paged_outs = outs
    assert paged_outs == outs
    _audit(engine)


def test_prefix_cache_off_still_bit_identical(params):
    """kv_layout="paged" with prefix_cache=False: pure block packing,
    no sharing — parity and the ledger still hold, and duplicates
    re-prefill (zero hits by construction)."""
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       name="paged_nocache", kv_layout="paged",
                       kv_block_size=BS, prefix_cache=False)
    eng.metrics = ServingMetrics()
    rng = np.random.RandomState(4)
    p = _prompt(rng, 10)
    bat = GenerationBatcher(eng)
    a = bat.submit(p, max_tokens=5).result(60)
    b = bat.submit(p, max_tokens=5).result(60)
    bat.close()
    assert a["tokens"] == b["tokens"] == _oracle(params, eng, p, 5)
    snap = eng.metrics.snapshot()
    assert snap["prefix_cache_hits_total"] == 0
    assert eng._paged.pool.num_used == 0
    eng._paged.check()


# ------------------------------------------------------- pool pressure


@pytest.mark.slow
def test_pool_pressure_preemption_recovers_bit_identical(params):
    """A pool deliberately too small for the offered load: admissions
    defer and mid-decode growth preempts victim slots (evictions
    reason="pool_exhausted"); preempted requests re-seat through the
    shared seat-prefix helper and every stream still completes
    bit-identical to the oracle — space pressure is never a failure.

    The pressure schedule is DETERMINISTIC: every request is submitted
    from this thread in one tight loop (submit() is non-blocking), so
    the full backlog is queued orders of magnitude faster than one
    decode step and the admission gate sees the same queue on every
    host.  The old staggered-client-thread drive let a slow 1-core box
    serialize the clients — requests finished before pressure ever
    built, and the preemption asserts below flaked."""
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       name="paged_tight", kv_layout="paged",
                       kv_block_size=BS, kv_num_blocks=10)
    eng.metrics = ServingMetrics()
    bat = GenerationBatcher(eng, default_max_tokens=8)
    rng = np.random.RandomState(5)
    # each request spans 16-token prompt + 16 tokens = 4 blocks; the
    # admission gate books 3 (prompt + first emission), so 3 of the 9
    # allocatable-block budget's requests seat concurrently and their
    # growth to 12 wanted blocks guarantees mid-decode preemption —
    # regardless of how fast the worker runs relative to this thread
    cases = [(_prompt(rng, BUCKETS[-1]), 16) for _ in range(6)]
    futs = [bat.submit(p, max_tokens=n) for p, n in cases]
    results = [f.result(300) for f in futs]
    bat.close()
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(params, eng, prompt, n)
    snap = eng.metrics.snapshot()
    assert snap["evictions"]["pool_exhausted"] >= 1, snap
    assert snap["slot_reprefills_total"] >= 1, snap
    eng._paged.check()
    assert eng.free_slots == SLOTS


def test_request_that_cannot_fit_pool_rejected_up_front(params):
    """One request larger than the whole pool is a client error at
    submit (the preemption path could never make room), while the same
    request fits the auto-sized pool."""
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       name="paged_small", kv_layout="paged",
                       kv_block_size=BS, kv_num_blocks=3)
    bat = GenerationBatcher(eng)
    with pytest.raises(InvalidRequestError, match="KV blocks"):
        bat.submit(np.arange(1, 13, dtype=np.int32), max_tokens=8)
    bat.close()


# ------------------------------------------------------- trace counts


def test_one_warmup_trace_zero_retraces_under_block_churn(params):
    """Warm-up traces the paged step exactly once (plus ONE block-write
    and ONE block-fork executable — no per-bucket admission ladder);
    then a churn run covering admission, prefix-cache seating, CoW
    forks, pool-pressure preemption and re-seat retraces NOTHING: the
    block table is data, not shape."""
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       name="paged_trace", kv_layout="paged",
                       kv_block_size=BS, kv_num_blocks=12)
    assert eng.step_trace_count == 1
    assert eng._write_traces[0] == 1 and eng._copy_traces[0] == 1
    rng = np.random.RandomState(6)
    shared = _prompt(rng, BS + 2)
    with assert_no_retrace(lambda: eng.step_trace_count
                           + eng._write_traces[0] + eng._copy_traces[0],
                           "paged block churn (admit/CoW/preempt)"):
        bat = GenerationBatcher(eng, default_max_tokens=10)
        cases = [(shared, 10), (shared, 10)]    # prefix hit + CoW fork
        cases += [(_prompt(rng, BUCKETS[-1]), 12) for _ in range(4)]
        results, excs = _drive(bat, cases)
        bat.close()
    assert all(e is None for e in excs), excs
    snap = eng.metrics.snapshot()
    assert snap["cow_forks_total"] >= 1         # the churn really forked
    eng._paged.check()


# ------------------------------------------------- recovery + replay


def test_supervisor_recovery_on_paged_engine_bit_identical(params, engine):
    """PR-6 chaos on the paged layout: an injected decode-step fault
    rebuilds the pool (fresh allocator, empty prefix index) and the
    supervisor re-seats every in-flight stream through the shared
    seat-prefix helper — all streams bit-identical, zero extra traces,
    and the refcount ledger balances after the storm."""
    engine.metrics = ServingMetrics()
    rng = np.random.RandomState(7)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(8)]
    ref = [_oracle(params, engine, p, n) for p, n in cases]
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(engine, supervisor=sup)
    faults.install_spec("serving.decode_step:at=6")
    with assert_no_retrace(lambda: engine.step_trace_count,
                           "paged chaos recovery"):
        results, excs = _drive(bat, cases)
        bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    assert all(e is None for e in excs), excs
    assert [r["tokens"] for r in results] == ref
    snap = engine.metrics.snapshot()
    assert snap["evictions"]["recovered"] >= 1
    assert snap["slot_reprefills_total"] >= 1
    _audit(engine)


def test_continuation_replay_on_paged_engine_bit_identical(params, engine):
    """The PR-7 cross-replica continuation (`submit(replay=)`) on the
    paged layout: a stream interrupted after k delivered tokens finishes
    through a paged engine emitting ONLY the remaining tokens, and the
    concatenation equals the uninterrupted oracle — including when the
    replay context is longer than the prefill ladder top."""
    engine.metrics = ServingMetrics()
    rng = np.random.RandomState(8)
    bat = GenerationBatcher(engine)
    for plen, n, k in ((6, 10, 3), (BUCKETS[-1], 12, 7),
                       (BUCKETS[-1], 24, 14)):   # 16+14 > ladder top
        prompt = _prompt(rng, plen)
        full = _oracle(params, engine, prompt, n)
        res = bat.submit(prompt, replay=np.asarray(full[:k], np.int32),
                         max_tokens=n - k).result(60)
        assert res["tokens"] == full[k:], (plen, n, k)
    bat.close()
    _audit(engine)


# ------------------------------------------------------- construction


def test_paged_config_validation_and_auto_sizing(params):
    blocks_per_row = -(-MAX_LEN // BS)
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=BUCKETS,
                       name="paged_auto", kv_layout="paged",
                       kv_block_size=BS, kv_num_blocks=0, warm=False)
    # auto-size = the slab-equivalent KV bytes + the scratch block
    assert eng._paged.pool.num_blocks == SLOTS * blocks_per_row + 1
    assert eng._cache[0]["k"].shape == \
        (SLOTS * blocks_per_row + 1, BS,
         params["enc"][0]["attn"]["wk"].shape[1])
    with pytest.raises(ConfigError):
        DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                     max_len=MAX_LEN, kv_layout="bogus", warm=False)
    with pytest.raises(ConfigError):
        DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                     max_len=MAX_LEN, kv_layout="paged",
                     kv_block_size=0, warm=False)
