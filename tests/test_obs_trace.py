"""End-to-end request tracing (obs/trace.py; docs/observability.md).

Units: span nesting / ring bound / deterministic sampling / the
disabled-path strict no-op / traceparent round-trip / Chrome export.
Integration: one trace_id propagated across a REAL router + replica
subprocess pair, and the no-retrace discipline — tracing enabled adds
ZERO jit traces to the decode engine (testing/trace.py
``assert_no_retrace``, the same counter every AOT surface pins).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest
import jax

from paddle_tpu.obs import trace
from paddle_tpu.testing.trace import assert_no_retrace


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    yield
    trace.disable()


# ------------------------------------------------------- correlated logs


def _fmt(formatter, msg="hello"):
    import logging
    rec = logging.LogRecord("paddle_tpu", logging.INFO, __file__, 1,
                            msg, (), None)
    return formatter.format(rec)


def test_json_log_format_carries_context():
    from paddle_tpu.utils import logging as ptlog
    with ptlog.log_context(trace_id="abc123", request_id="r-9"):
        line = _fmt(ptlog._JsonFormatter())
    obj = json.loads(line)
    assert obj["trace_id"] == "abc123" and obj["request_id"] == "r-9"
    assert obj["level"] == "INFO" and obj["logger"] == "paddle_tpu"
    # the greppable k=v tail rides in msg too, so ONE
    # `grep trace_id=<id>` crosses text- and json-format process logs
    assert "trace_id=abc123" in obj["msg"]
    # outside the context: clean line, no stale fields
    obj = json.loads(_fmt(ptlog._JsonFormatter()))
    assert "trace_id" not in obj and obj["msg"] == "hello"


def test_text_log_format_appends_context_tail():
    from paddle_tpu.utils import logging as ptlog
    fmt = ptlog._TextFormatter(ptlog._FMT, datefmt="%m%d %H:%M:%S")
    assert _fmt(fmt).endswith("hello")
    with ptlog.log_context(trace_id="abc123"):
        assert _fmt(fmt).endswith("hello trace_id=abc123")
    # nesting merges; falsy values are dropped
    with ptlog.log_context(trace_id="abc123"):
        with ptlog.log_context(request_id="r-1", empty=None):
            assert ptlog.context_fields() == {"trace_id": "abc123",
                                              "request_id": "r-1"}
        assert ptlog.context_fields() == {"trace_id": "abc123"}


def test_set_format_switches_installed_handlers():
    from paddle_tpu.utils import logging as ptlog
    log = ptlog.get_logger()
    try:
        ptlog.set_format("json")
        assert all(isinstance(h.formatter, ptlog._JsonFormatter)
                   for h in log.handlers)
    finally:
        ptlog.set_format("text")
    assert all(isinstance(h.formatter, ptlog._TextFormatter)
               for h in log.handlers)


# ------------------------------------------------------------------ units


def test_disabled_path_is_a_strict_noop():
    # no tracer installed: every entry point returns the ONE null
    # singleton — no allocation, no context mutation, empty ids
    assert trace.span("x", a=1) is trace.NULL
    assert trace.start_span("y") is trace.NULL
    assert trace.instant("z") is trace.NULL
    assert trace.NULL.trace_id == "" and not trace.NULL.recording
    with trace.span("x"):
        assert trace.current() is None      # NULL never touches the ctx
    # every mutator is inert and chainable
    assert trace.NULL.set(a=1).event("e").end() is trace.NULL
    assert trace.snapshot() == []
    assert trace.slowest() == {"wall": [], "ttft": []}
    assert trace.debug_payload()["enabled"] is False
    # inject with no context propagates nothing
    assert trace.inject({}) == {}


def test_span_nesting_parents_and_context():
    trace.enable(sample=1.0, capacity=64, process="unit")
    with trace.span("root", route="/x") as r:
        assert trace.current() == (r.trace_id, r.span_id)
        with trace.span("mid") as m:
            with trace.span("leaf") as leaf:
                assert leaf.trace_id == r.trace_id
                assert leaf.parent_id == m.span_id
            assert m.parent_id == r.span_id
        # context restored after each exit
        assert trace.current() == (r.trace_id, r.span_id)
    assert trace.current() is None
    spans = {s["name"]: s for s in trace.snapshot()}
    assert set(spans) == {"root", "mid", "leaf"}
    assert spans["root"]["parent_id"] is None
    assert spans["root"]["attrs"]["root"] is True
    # completed spans carry both timestamps
    for s in spans.values():
        assert s["t_end"] >= s["t_start"]


def test_start_span_is_context_free_and_async_endable():
    trace.enable(sample=1.0, capacity=64, process="unit")
    with trace.span("req") as r:
        seam = trace.start_span("queue_wait")
        assert seam.parent_id == r.span_id       # parented to current...
        assert trace.current() == (r.trace_id, r.span_id)  # ...but not
        #                                           made current itself
    done = []

    def other_thread():
        seam.event("picked")
        seam.end(batch_size=3)
        done.append(True)

    t = threading.Thread(target=other_thread)
    t.start()
    t.join(5)
    assert done
    s = next(s for s in trace.snapshot() if s["name"] == "queue_wait")
    assert s["attrs"]["batch_size"] == 3
    assert [e["name"] for e in s["events"]] == ["picked"]
    # double-end is idempotent
    first_end = s["t_end"]
    seam.end()
    s2 = next(s for s in trace.snapshot() if s["name"] == "queue_wait")
    assert s2["t_end"] == first_end


def test_ring_bound_drops_oldest():
    trace.enable(sample=1.0, capacity=5, process="unit")
    for i in range(12):
        trace.start_span(f"s{i}").end()
    spans = trace.snapshot()
    assert len(spans) == 5
    assert [s["name"] for s in spans] == [f"s{i}" for i in range(7, 12)]
    assert trace.get_tracer().dropped_total == 7
    assert trace.get_tracer().started_total == 12


def test_sampling_is_deterministic_on_trace_id_hash():
    ids = [trace.new_trace_id() for _ in range(400)]
    a = trace.Tracer(sample=0.5)
    b = trace.Tracer(sample=0.5)
    verdicts = [a.sampled(i) for i in ids]
    # the SAME ids get the SAME verdict in a different tracer/process
    assert verdicts == [b.sampled(i) for i in ids]
    assert 100 < sum(verdicts) < 300        # roughly the asked-for half
    assert all(trace.Tracer(sample=1.0).sampled(i) for i in ids)
    assert not any(trace.Tracer(sample=0.0).sampled(i) for i in ids)


def test_unsampled_spans_keep_ids_but_never_record():
    trace.enable(sample=0.0, capacity=64, process="unit")
    with trace.span("root") as r:
        assert len(r.trace_id) == 32        # ids exist: responses/logs
        assert not r.recording              # still correlate
        with trace.span("child") as c:
            assert c.trace_id == r.trace_id
        hdrs = trace.inject({})             # propagation stays coherent
        assert r.trace_id in hdrs["traceparent"]
    assert trace.snapshot() == []


def test_traceparent_round_trip_and_malformed():
    trace.enable(sample=1.0, capacity=8, process="unit")
    with trace.span("root") as r:
        hdr = trace.inject({})["traceparent"]
    assert trace.extract(hdr) == (r.trace_id, r.span_id)
    for bad in (None, "", "junk", "00-short-id-01",
                "00-" + "x" * 32 + "-" + "cd" * 8 + "-01"):
        assert trace.extract(bad) is None


def test_chrome_trace_export_shape():
    trace.enable(sample=1.0, capacity=64, process="replica:1")
    with trace.span("server.request", route="/v1/generate") as r:
        sl = trace.start_span("slot", slot=2, mode="prefill")
        sl.event("first_token")
        sl.end(reason="length")
    obj = trace.chrome_trace()
    json.loads(json.dumps(obj))             # valid JSON
    evs = obj["traceEvents"]
    procs = [e for e in evs if e["ph"] == "M"
             and e["name"] == "process_name"]
    assert [p["args"]["name"] for p in procs] == ["replica:1"]
    tracks = {e["args"]["name"] for e in evs if e["ph"] == "M"
              and e["name"] == "thread_name"}
    assert tracks == {"host", "slot 2"}
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"server.request", "slot"}
    assert xs["slot"]["tid"] == 102
    assert xs["slot"]["args"]["trace_id"] == r.trace_id
    instants = [e for e in evs if e["ph"] == "i"]
    assert [e["name"] for e in instants] == ["first_token"]


def test_slowest_surfaces_worst_roots():
    trace.enable(sample=1.0, capacity=64, process="unit")
    import time
    for i, dt in enumerate((0.0, 0.03, 0.01)):
        with trace.span(f"r{i}", route="/x") as s:
            s.set(ttft_ms=dt * 500)
            time.sleep(dt)
        # non-root spans never show up
        trace.start_span("noise").end()
    sl = trace.slowest(2)
    assert [r["name"] for r in sl["wall"]] == ["r1", "r2"]
    assert sl["wall"][0]["wall_ms"] >= sl["wall"][1]["wall_ms"]
    assert sl["ttft"][0]["ttft_ms"] == 15.0
    assert all(len(r["trace_id"]) == 32 for r in sl["wall"])


# ------------------------------------------------------ engine no-retrace


def test_tracing_enabled_adds_zero_jit_traces():
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.decode_engine import (DecodeEngine,
                                                  GenerationBatcher)
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=64,
                              trg_vocab=1, d_model=16, num_heads=2,
                              dff=32, enc_layers=1, dec_layers=0,
                              max_len=32)
    # warm up with tracing DISABLED, then serve with it ENABLED: the
    # compiled step/admit/prefill surfaces must not trace again
    engine = DecodeEngine(params, num_heads=2, num_slots=2, max_len=32,
                          prefill_buckets=(4, 8), name="obs_nr")
    trace.enable(sample=1.0, capacity=256, process="unit")
    gen = GenerationBatcher(engine, default_max_tokens=4)
    try:
        with assert_no_retrace(
                lambda: engine.step_trace_count,
                "decode under enabled tracing"):
            futs = [gen.submit(np.arange(1, 4 + 2 * i) % 60,
                               max_tokens=4) for i in range(3)]
            outs = [f.result(60) for f in futs]
        assert all(len(o["tokens"]) == 4 for o in outs)
    finally:
        gen.close()
    # a post-close submit is rejected — and must not leak a span
    from paddle_tpu.serving.batcher import ShutdownError
    with pytest.raises(ShutdownError):
        gen.submit(np.arange(1, 4), max_tokens=2)
    # the spans really recorded: every request has a slot lifetime span
    slots = [s for s in trace.snapshot() if s["name"] == "slot"]
    assert len(slots) == 3
    assert all(s["attrs"]["reason"] == "length" for s in slots)
    assert all(s["attrs"]["tokens"] == 4 for s in slots)
    # no span leaked into the live registry: every started span ended
    # (rejected submits, finished requests, prefill batches alike)
    assert trace.get_tracer()._active == {}


# ------------------------------------------- cross-process propagation


@pytest.mark.slow
def test_propagation_across_router_and_replica_subprocess(tmp_path):
    """One trace_id stitches the in-process router and a REAL replica
    subprocess: the replica's server.request span (fetched over its
    /debug/traces) must parent to the router's dispatch span."""
    from paddle_tpu.serving.fleet import ReplicaSupervisor
    from paddle_tpu.serving.router import Router

    import logging as pylogging
    from paddle_tpu.utils import logging as ptlog

    trace.enable(sample=1.0, capacity=1024, process="router")
    extra = ["--gen-slots", "2", "--gen-max-len", "48",
             "--gen-prefill-buckets", "8,16", "--gen-max-tokens", "6",
             "--obs-trace", "1"]
    sup = ReplicaSupervisor(n_replicas=1, extra_args=extra, seed=0,
                            name="obs_prop")
    router = Router(supervisor=sup, poll_interval_s=0.1,
                    name="obs_prop_router")
    httpd = None
    # capture the router's own log lines: the handler wraps each request
    # in log_context, so even debug access logs carry trace_id=<id>
    captured = []

    class _Cap(pylogging.Handler):
        def emit(self, rec):
            captured.append(self.format(rec))

    cap = _Cap(level=pylogging.DEBUG)
    cap.setFormatter(ptlog._TextFormatter(ptlog._FMT))
    shared = ptlog.get_logger()
    old_level = shared.level
    shared.addHandler(cap)
    shared.setLevel(pylogging.DEBUG)
    try:
        sup.start()
        assert sup.wait_ready(timeout=240), "replica never became ready"
        httpd = router.start(port=0)
        base = f"http://127.0.0.1:{httpd.port}"
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": [3, 5, 7],
                             "max_tokens": 6}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
            hdr_tid = r.headers.get("X-Trace-Id")
        tid = out["trace_id"]
        assert len(tid) == 32 and hdr_tid == tid
        # `grep trace_id=<id>` works on the router's process log
        assert any(f"trace_id={tid}" in line for line in captured), \
            captured[-5:]

        # router half: request root + a dispatch span on the same trace
        router_spans = {s["span_id"]: s for s in trace.snapshot()
                        if s["trace_id"] == tid}
        roots = [s for s in router_spans.values()
                 if s["name"] == "router.request"]
        dispatches = [s for s in router_spans.values()
                      if s["name"] == "router.dispatch"]
        assert len(roots) == 1 and dispatches
        assert all(d["parent_id"] == roots[0]["span_id"]
                   for d in dispatches)

        # replica half, over the wire: same trace_id, parented to the
        # router's dispatch span via the traceparent header
        (rid, url), = sup.endpoints()
        with urllib.request.urlopen(f"{url}/debug/traces",
                                    timeout=30) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] is True
        assert payload["process"].startswith("replica:")
        rep = [s for s in payload["spans"] if s["trace_id"] == tid]
        byname = {s["name"]: s for s in rep}
        assert {"server.request", "gen.queue_wait", "slot"} <= set(byname)
        assert byname["server.request"]["parent_id"] in router_spans
        assert router_spans[byname["server.request"]["parent_id"]][
            "name"] == "router.dispatch"
        assert byname["slot"]["attrs"]["reason"] == "length"

        # a merged fleet dump parses and names both processes
        merged = list(router_spans.values()) + rep
        path = tmp_path / "chrome.json"
        trace.dump_chrome_trace(str(path), merged)
        with open(path) as f:
            chrome = json.load(f)
        procs = {e["args"]["name"] for e in chrome["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert "router" in procs
        assert any(p.startswith("replica:") for p in procs)
    finally:
        shared.removeHandler(cap)
        shared.setLevel(old_level)
        router.close()
        sup.stop()
