"""Cross-rank straggler telemetry unit semantics (the 2-process behavior
is asserted in test_distributed); single-process here: report shape,
empty-window collective safety, array inputs."""

import numpy as np

from paddle_tpu.parallel.distributed import step_skew_report


def test_report_shape_and_content():
    rep = step_skew_report([0.010, 0.012, 0.020, 0.011])
    assert rep.startswith("train_step skew (4 steps/rank):")
    assert "r0[p50=" in rep and "p99=" in rep
    assert "slowest=r0" in rep and "p50-spread=0%" in rep


def test_array_input_and_name():
    rep = step_skew_report(np.asarray([0.5, 0.25]), name="io_wait")
    assert rep.startswith("io_wait skew (2 steps/rank)")


def test_empty_window_returns_none_after_gather():
    # the gather still runs (collective safety) but the report is None
    assert step_skew_report([]) is None
    assert step_skew_report(np.asarray([])) is None
