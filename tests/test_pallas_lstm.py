"""Fused Pallas LSTM vs the lax.scan reference path: forward and every
gradient must agree (the dual-implementation discipline the reference
applies to its fused CUDA LSTM in test_LayerGrad + test_RecurrentLayer).

Runs the kernel in interpret mode on the CPU mesh; the same code lowers to
Mosaic on a real chip (exercised by bench.py and the TPU differential
sweep)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.ops import rnn

B, T, D = 8, 7, 128          # kernel needs B%8==0, D%128==0


def _mk(np_rng, ragged=True):
    x = jnp.asarray(np_rng.randn(B, T, 4 * D) * 0.3, jnp.float32)
    lengths = (np_rng.randint(1, T + 1, (B,)) if ragged
               else np.full((B,), T))
    seq = SequenceBatch(data=x, lengths=jnp.asarray(lengths, jnp.int32))
    w_r = jnp.asarray(np_rng.randn(D, 4 * D) * 0.1, jnp.float32)
    checks = [jnp.asarray(np_rng.randn(D) * 0.1, jnp.float32)
              for _ in range(3)]
    bias = jnp.asarray(np_rng.randn(4 * D) * 0.1, jnp.float32)
    return seq, w_r, checks, bias


def _run(seq, w_r, checks, bias, fused, use_final=False, peephole=True):
    prior = rnn.FUSED_LSTM
    rnn.FUSED_LSTM = "always" if fused else "0"
    try:
        ci, cf, co = checks if peephole else (None, None, None)
        out, final = rnn.lstm(seq, w_r, bias=bias,
                              check_i=ci, check_f=cf, check_o=co)
        if use_final:
            return jnp.sum(out.data ** 2) + jnp.sum(final.c ** 2) \
                + jnp.sum(final.h)
        return jnp.sum(out.data ** 2)
    finally:
        rnn.FUSED_LSTM = prior


@pytest.mark.parametrize("ragged", [False, True], ids=["full", "ragged"])
@pytest.mark.parametrize("peephole", [True, False], ids=["peep", "nopeep"])
def test_fused_matches_scan_forward(np_rng, ragged, peephole):
    seq, w_r, checks, bias = _mk(np_rng, ragged)
    a = _run(seq, w_r, checks, bias, fused=True, peephole=peephole)
    b = _run(seq, w_r, checks, bias, fused=False, peephole=peephole)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)


@pytest.mark.parametrize("use_final", [False, True], ids=["hs", "hs+final"])
def test_fused_matches_scan_grads(np_rng, use_final):
    seq, w_r, checks, bias = _mk(np_rng, ragged=True)

    def loss(fused, xdata, w_r, checks, bias):
        s = SequenceBatch(data=xdata, lengths=seq.lengths)
        return _run(s, w_r, checks, bias, fused, use_final=use_final)

    args = (seq.data, w_r, checks, bias)
    ga = jax.grad(lambda *a: loss(True, *a), argnums=(0, 1, 2, 3))(*args)
    gb = jax.grad(lambda *a: loss(False, *a), argnums=(0, 1, 2, 3))(*args)
    labels = ["dx", "dw_r", "dchecks", "dbias"]
    for la, (a, b) in zip(labels, zip(jax.tree_util.tree_leaves(ga),
                                      jax.tree_util.tree_leaves(gb))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=la)


def test_fused_zero_length_sequence(np_rng):
    seq, w_r, checks, bias = _mk(np_rng, ragged=True)
    seq = SequenceBatch(data=seq.data,
                        lengths=seq.lengths.at[0].set(0))
    a = _run(seq, w_r, checks, bias, fused=True)
    b = _run(seq, w_r, checks, bias, fused=False)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)


def test_fused_reverse_matches_scan(np_rng):
    seq, w_r, checks, bias = _mk(np_rng, ragged=True)

    def loss(fused, xdata):
        s = SequenceBatch(data=xdata, lengths=seq.lengths)
        prior = rnn.FUSED_LSTM
        rnn.FUSED_LSTM = "always" if fused else "0"
        try:
            out, final = rnn.lstm(s, w_r, bias=bias, check_i=checks[0],
                                  check_f=checks[1], check_o=checks[2],
                                  reverse=True)
            return (jnp.sum(out.data ** 2) + jnp.sum(final.c ** 2)
                    + jnp.sum(final.h))
        finally:
            rnn.FUSED_LSTM = prior

    a, ga = jax.value_and_grad(lambda x: loss(True, x))(seq.data)
    b, gb = jax.value_and_grad(lambda x: loss(False, x))(seq.data)
    np.testing.assert_allclose(float(a), float(b), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=2e-4, atol=2e-5)


def test_vmem_guard_routes_oversized_to_scan(monkeypatch):
    """d=1280's w_r (26 MB f32) cannot be VMEM-resident on a ~16 MB core:
    supported() must say no BEFORE Mosaic discovers it the hard way, and
    the budget must be overridable for bigger chips."""
    from paddle_tpu.ops.pallas import lstm as pl
    monkeypatch.delenv("PADDLE_TPU_KERNEL_VMEM_MB", raising=False)
    assert pl.supported(64, 512, "tanh", "sigmoid", "tanh", None)
    assert not pl.supported(64, 1280, "tanh", "sigmoid", "tanh", None)
    monkeypatch.setenv("PADDLE_TPU_KERNEL_VMEM_MB", "128")
    assert pl.supported(64, 1280, "tanh", "sigmoid", "tanh", None)
    monkeypatch.setenv("PADDLE_TPU_KERNEL_VMEM_MB", "1")
    assert not pl.supported(64, 512, "tanh", "sigmoid", "tanh", None)
