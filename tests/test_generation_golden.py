"""Golden generation regression (reference
trainer/tests/test_recurrent_machine_generation.cpp: generation output is
compared against files committed next to the test, so any change to the
beam-search/decoder numerics is caught as a diff, not a silent drift).

The golden tokens were produced by this same code (first run prints them);
their value is INVARIANCE: beam search over a fixed-weight seq2seq model
is fully deterministic, so any future edit to ops/beam.py, the decoder
step, masking, or the length-normalized scoring that changes the output
must update this file consciously.
"""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.models import seq2seq

# fixed tiny model: vocab 23, emb/hidden 16, two source sentences
_V, _H = 23, 16

GOLDEN_BEAM = [
    # (beam_size, expected token rows for beam 0 of each batch element) —
    # recorded from PRNGKey(42) weights + RandomState(7) sources; random
    # weights make the model babble, which is fine: invariance is the test.
    # Re-pinned in PR 9 after a bisect showed the previous values failing
    # at EVERY commit back to the seed import — the drift came from the
    # environment's jax/XLA version changing PRNGKey(42) init numerics,
    # not from any repo change (seq2seq.py and ops/beam.py are untouched
    # since the seed; determinism and greedy==beam1 still hold).
    (1, [[17, 11, 17, 11, 11, 17], [10, 18, 6, 18, 6, 18]]),
    (3, [[17, 11, 1, 1, 1, 1], [10, 18, 6, 18, 22, 0]]),
]


def _setup():
    params = seq2seq.init(jax.random.PRNGKey(42), src_vocab=_V, trg_vocab=_V,
                          emb_dim=_H, hidden=_H)
    rng = np.random.RandomState(7)
    src = SequenceBatch(
        data=jnp.asarray(rng.randint(3, _V, (2, 5)), jnp.int32),
        lengths=jnp.asarray([5, 3], jnp.int32))
    return params, src


def test_generation_is_deterministic_and_matches_golden():
    params, src = _setup()
    for beam_size, golden in GOLDEN_BEAM:
        res = seq2seq.generate(params, src, beam_size=beam_size, max_len=6,
                               bos_id=0, eos_id=1)
        toks = np.asarray(res.tokens)[:, 0]          # best lane per batch
        toks2 = np.asarray(
            seq2seq.generate(params, src, beam_size=beam_size, max_len=6,
                             bos_id=0, eos_id=1).tokens)[:, 0]
        np.testing.assert_array_equal(toks, toks2)   # determinism
        if golden is not None:
            np.testing.assert_array_equal(
                toks, np.asarray(golden),
                err_msg=f"beam={beam_size}: generation drifted from golden "
                        "(conscious numerics change? update GOLDEN_BEAM)")


def test_greedy_equals_beam1():
    params, src = _setup()
    g_tokens, _ = seq2seq.greedy_generate(params, src, max_len=6, bos_id=0,
                                          eos_id=1)
    b = seq2seq.generate(params, src, beam_size=1, max_len=6, bos_id=0,
                         eos_id=1)
    np.testing.assert_array_equal(np.asarray(g_tokens),
                                  np.asarray(b.tokens)[:, 0])
