"""Beam search: on a fixed-transition toy LM the beam must find the
highest-probability sequence (enumerable exactly)."""

import itertools

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops import beam as beam_ops


def make_step(trans):
    """trans: [V, V] log-prob of next token given previous (static)."""
    t = jnp.asarray(trans)

    def step_fn(state, prev_ids):
        return t[prev_ids], state
    return step_fn


def brute_best(trans, bos, eos, max_len):
    v = trans.shape[0]
    best, best_seq = -np.inf, None
    for seq in itertools.product(range(v), repeat=max_len):
        score, prev, done = 0.0, bos, False
        ok = True
        length = 0
        for s in seq:
            score += trans[prev, s]
            prev = s
            length += 1
            if s == eos:
                done = True
                break
        # compare only full-length or eos-terminated sequences as the beam does
        if score > best:
            best, best_seq = score, seq[:length]
    return best, best_seq


def test_beam_finds_optimal(np_rng):
    v, max_len, eos = 5, 4, 1
    logits = np_rng.randn(v, v).astype(np.float32)
    trans = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    res = beam_ops.beam_search(make_step(trans), jnp.zeros((1 * 8, 1)),
                               batch_size=1, beam_size=8, max_len=max_len,
                               bos_id=0, eos_id=eos)
    got = float(res.scores[0, 0])
    best, best_seq = brute_best(trans, 0, eos, max_len)
    np.testing.assert_allclose(got, best, rtol=1e-4)
    got_tokens = list(np.asarray(res.tokens[0, 0]))[:len(best_seq)]
    assert got_tokens == list(best_seq)


def test_greedy_matches_manual_chain(np_rng):
    v, max_len, eos = 6, 5, 1
    logits = np_rng.randn(v, v).astype(np.float32)
    trans = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    toks, lens = beam_ops.greedy_search(make_step(trans), jnp.zeros((2, 1)),
                                        batch_size=2, max_len=max_len,
                                        bos_id=0, eos_id=eos)
    # manual argmax chain
    prev, out = 0, []
    for _ in range(max_len):
        nxt = int(np.argmax(trans[prev]))
        out.append(nxt)
        prev = nxt
        if nxt == eos:
            break
    got = list(np.asarray(toks[0]))[:len(out)]
    assert got == out


def test_beam_eos_freezes_score(np_rng):
    """Once a lane emits eos, later steps must not change its score."""
    v, eos = 4, 1
    # token 1 (eos) hugely preferred from bos: everything finishes at t=0
    trans = np.full((v, v), -10.0, np.float32)
    trans[:, eos] = -0.1
    res = beam_ops.beam_search(make_step(trans), jnp.zeros((3, 1)),
                               batch_size=1, beam_size=3, max_len=6,
                               bos_id=0, eos_id=eos)
    np.testing.assert_allclose(float(res.scores[0, 0]), -0.1, rtol=1e-5)
    assert int(res.lengths[0, 0]) == 0  # eos-terminated immediately


def test_drop_callback_bans_token(np_rng):
    """The per-node drop hook (reference NormOrDropNodeCallback,
    RecurrentGradientMachine.h:87-177): dropping every expansion to token 3
    must keep 3 out of all decoded lanes, and the result must equal the
    brute-force optimum over the 3-free vocabulary."""
    v, max_len, eos = 5, 4, 1
    banned = 3
    logits = np_rng.randn(v, v).astype(np.float32)
    trans = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))

    def drop(tokens, t, cand):
        return cand.at[..., banned].set(-1e30)

    res = beam_ops.beam_search(make_step(trans), jnp.zeros((1 * 8, 1)),
                               batch_size=1, beam_size=8, max_len=max_len,
                               bos_id=0, eos_id=eos, drop_callback=drop)
    toks = np.asarray(res.tokens[0])
    lens = np.asarray(res.lengths[0])
    for k in range(toks.shape[0]):
        assert banned not in toks[k, :lens[k]]

    # brute force with the banned token removed from transitions
    trans_banned = trans.copy()
    trans_banned[:, banned] = -1e30
    best, _ = brute_best(trans_banned, 0, eos, max_len)
    np.testing.assert_allclose(float(res.scores[0, 0]), best, rtol=1e-5)


def test_drop_callback_sees_prefix(np_rng):
    """The hook receives each lane's decoded prefix: ban immediate token
    repetition (cand[prev] = -inf) and check no lane repeats."""
    v, max_len, eos = 6, 5, 0
    logits = np_rng.randn(v, v).astype(np.float32)
    # make repetition attractive so the test bites
    logits[np.arange(v), np.arange(v)] += 3.0
    trans = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))

    def drop(tokens, t, cand):
        prev = jnp.where(t > 0, tokens[:, :, jnp.maximum(t - 1, 0)], -1)
        mask = jax.nn.one_hot(prev, v, dtype=bool)
        return jnp.where(mask, -1e30, cand)

    res = beam_ops.beam_search(make_step(trans), jnp.zeros((1 * 4, 1)),
                               batch_size=1, beam_size=4, max_len=max_len,
                               bos_id=1, eos_id=eos, drop_callback=drop)
    toks = np.asarray(res.tokens[0])
    lens = np.asarray(res.lengths[0])
    for k in range(toks.shape[0]):
        seq = toks[k, :lens[k]]
        assert all(seq[i] != seq[i + 1] for i in range(len(seq) - 1)), seq
