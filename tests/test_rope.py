"""Rotary positions (ops.attention.rope + transformer pos_type='rope'):
relative-position invariance, cached-generation parity, packed rows,
ring composition, and the headline capability — running BEYOND the
training max_len (no learned table to outgrow)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch, pack_sequences
from paddle_tpu.ops import attention as att
from paddle_tpu.models import transformer

V, DM, HEADS, T = 48, 16, 2, 12

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _rope_params(max_len=T, seed=0):
    return transformer.init(jax.random.PRNGKey(seed), src_vocab=V,
                            trg_vocab=1, d_model=DM, dff=32,
                            enc_layers=2, dec_layers=0, max_len=max_len,
                            pos_type="rope")


def test_rope_scores_are_relative(np_rng):
    """q.k after rope depends only on the position DIFFERENCE — the
    property that makes length extrapolation possible."""
    q = jnp.asarray(np_rng.randn(1, 2, 4, 8), jnp.float32)
    k = jnp.asarray(np_rng.randn(1, 2, 4, 8), jnp.float32)
    p = jnp.asarray([0, 3, 7, 11])
    s1 = jnp.einsum("bhqd,bhkd->bhqk", att.rope(q, p), att.rope(k, p))
    s2 = jnp.einsum("bhqd,bhkd->bhqk", att.rope(q, p + 100),
                    att.rope(k, p + 100))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-4)
    with pytest.raises(ValueError, match="even head dim"):
        att.rope(jnp.zeros((1, 1, 2, 7)), jnp.arange(2))


def test_rope_params_have_no_table():
    params = _rope_params()
    assert "pos" not in params
    # and a learned init of the same seed matches everywhere else
    learned = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                               trg_vocab=1, d_model=DM, dff=32,
                               enc_layers=2, dec_layers=0, max_len=T)
    np.testing.assert_array_equal(np.asarray(params["src_emb"]),
                                  np.asarray(learned["src_emb"]))
    np.testing.assert_array_equal(
        np.asarray(params["enc"][0]["attn"]["wq"]),
        np.asarray(learned["enc"][0]["attn"]["wq"]))


def test_rope_lm_generate_matches_oracle(np_rng):
    """KV-cached rope generation (rotated keys in the cache) == the
    full-recompute argmax rollout."""
    params = _rope_params()
    prompt = np_rng.randint(3, V, (3, 4)).astype(np.int32)
    got = np.asarray(transformer.lm_generate(
        params, prompt, max_len=T, num_heads=HEADS, pos_type="rope"))
    b = prompt.shape[0]
    ids = np.zeros((b, T), np.int32)
    ids[:, :4] = prompt
    for t in range(T - 1):
        sb = SequenceBatch(jnp.asarray(ids),
                           jnp.full((b,), t + 1, jnp.int32))
        logits = transformer.lm_logits(params, sb, HEADS, pos_type="rope")
        nxt = np.asarray(jnp.argmax(logits[:, t], axis=-1))
        if t + 1 >= 4:
            ids[:, t + 1] = nxt
    np.testing.assert_array_equal(got, ids)


@pytest.mark.slow
def test_rope_packed_matches_per_row(np_rng):
    """Packed rope rows use within-segment positions: the loss equals the
    one-sequence-per-row layout, exactly like the learned path."""
    params = _rope_params()
    seqs = [np_rng.randint(3, V, n) for n in (5, 9, 7, 3)]
    data, seg, pos = pack_sequences(seqs, max_len=T)
    b = data.shape[0]
    packed = transformer.lm_loss(
        params,
        SequenceBatch(jnp.asarray(data), jnp.full((b,), T, jnp.int32)),
        HEADS, segment_ids=jnp.asarray(seg), positions=jnp.asarray(pos),
        pos_type="rope")
    n = len(seqs)
    d1 = np.zeros((n, T), np.int32)
    s1 = np.zeros((n, T), np.int32)
    p1 = np.zeros((n, T), np.int32)
    for i, sq in enumerate(seqs):
        d1[i, :len(sq)] = sq
        s1[i, :len(sq)] = 1
        p1[i, :len(sq)] = np.arange(len(sq))
    alone = transformer.lm_loss(
        params,
        SequenceBatch(jnp.asarray(d1), jnp.full((n,), T, jnp.int32)),
        HEADS, segment_ids=jnp.asarray(s1), positions=jnp.asarray(p1),
        pos_type="rope")
    np.testing.assert_allclose(float(packed), float(alone), rtol=2e-5)


@pytest.mark.slow
def test_rope_runs_beyond_trained_max_len(np_rng):
    """THE rope payoff: a trunk initialized with max_len=8 runs T=24
    sequences (logits AND generation) — the learned path hard-fails at
    its table size."""
    params = _rope_params(max_len=8)
    long_toks = SequenceBatch(
        jnp.asarray(np_rng.randint(3, V, (2, 24)), jnp.int32),
        jnp.full((2,), 24, jnp.int32))
    logits = transformer.lm_logits(params, long_toks, HEADS,
                                   pos_type="rope")
    assert logits.shape == (2, 24, V)
    assert np.isfinite(np.asarray(logits)).all()
    ids = transformer.lm_generate(params,
                                  np.asarray(long_toks.data[:, :6]),
                                  max_len=24, num_heads=HEADS,
                                  pos_type="rope")
    assert np.asarray(ids).shape == (2, 24)
    # the learned twin refuses the same request, loudly
    learned = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                               trg_vocab=1, d_model=DM, dff=32,
                               enc_layers=2, dec_layers=0, max_len=8)
    with pytest.raises(ValueError, match="positional table"):
        transformer.lm_generate(learned,
                                np.asarray(long_toks.data[:, :6]),
                                max_len=24, num_heads=HEADS)


def test_rope_lm_trains(np_rng):
    from paddle_tpu import optim
    params = _rope_params()
    rng = np.random.RandomState(0)
    data = (np.arange(T)[None] + rng.randint(0, 45, (8, 1))) % 45 + 3
    toks = SequenceBatch(jnp.asarray(data, jnp.int32),
                         jnp.full((8,), T, jnp.int32))
    opt = optim.Adam(learning_rate=3e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: transformer.lm_loss(
            p, toks, HEADS, pos_type="rope"))(p)
        p2, s2 = opt.update(g, s, p)
        return p2, s2, l

    first = None
    for _ in range(120):
        params, state, l = step(params, state)
        first = first if first is not None else float(l)
    assert float(l) < 0.5 * first, (first, float(l))


@needs_8
def test_rope_ring_matches_single(np_rng):
    """rope composes with the seq-parallel ring unchanged (rotation is
    positionwise, applied before sharding): sharded loss+grads ==
    single-device."""
    from paddle_tpu.parallel import MeshConfig, make_mesh
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    params = _rope_params(max_len=16)
    toks = SequenceBatch(
        jnp.asarray(np_rng.randint(3, V, (4, 16)), jnp.int32),
        jnp.full((4,), 16, jnp.int32))

    def lm(p, m):
        return transformer.lm_loss(p, toks, HEADS, mesh=m,
                                   pos_type="rope")

    l1, g1 = jax.jit(jax.value_and_grad(lambda p: lm(p, None)))(params)
    l2, g2 = jax.jit(jax.value_and_grad(lambda p: lm(p, mesh)))(params)
    np.testing.assert_allclose(float(l2), float(l1), rtol=2e-4)
    for a, b_ in zip(jax.tree_util.tree_leaves(g2),
                     jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=1e-4)
