"""Unified chunked-prefill serving (DecodeEngine prefill_chunk > 0).

Prompt ingestion folded into the ONE jitted decode step: each step
advances a mix of decode rows (1 token) and admitting rows (up to K
prompt tokens, re-derived emissions swallowed until the last chunk).
The correctness bar is the slab engine's own: every greedy stream —
staggered admission, chunk boundaries, EOS, paged CoW churn, pool
pressure, supervisor recovery, continuation replay — must be
BIT-IDENTICAL to the single-request oracle
(``models/transformer.lm_generate``).  Trace discipline: ONE warm-up
trace for the chunked step (plus one block-fork executable on paged),
ZERO traces across any churn — tokens, positions, AND lane counts are
data, not shape, so the per-step chunk budget tunes without retracing.
"""

import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.ops.pallas import decode_attention as decode_kernels
from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.serving import (GenerationBatcher, InvalidRequestError,
                                ServingMetrics)
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.testing import assert_no_retrace
from paddle_tpu.utils.error import ConfigError

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BUCKETS, BS, K = 48, 4, (8, 16), 8, 4


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def rope_params():
    return transformer.init(jax.random.PRNGKey(1), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN, pos_type="rope")


def _engine(params, **kw):
    kw.setdefault("prefill_chunk", K)
    kw.setdefault("prefill_buckets", BUCKETS)
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, **kw)


def _prompt(rng, n=None):
    return rng.randint(1, VOCAB, n or rng.randint(1, 30)).astype(np.int32)


def _oracle(params, prompt, n_tokens, eos_id=None, pos_type="learned"):
    ids = np.asarray(transformer.lm_generate(
        params, prompt[None], max_len=MAX_LEN, num_heads=HEADS,
        eos_id=eos_id, prompt_lengths=np.asarray([prompt.size]),
        pos_type=pos_type))
    return ids[0, prompt.size:prompt.size + n_tokens].tolist()


def _drive(bat, cases, stagger_s=0.002):
    """Concurrent client threads (admissions land mid-decode)."""
    results, excs = [None] * len(cases), [None] * len(cases)

    def client(i):
        prompt, n = cases[i]
        try:
            time.sleep(stagger_s * i)
            results[i] = bat.submit(prompt, max_tokens=n).result(180)
        except Exception as e:      # noqa: BLE001
            excs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    return results, excs


# ----------------------------------------------------- step-level units


def test_chunk_step_matches_prefill_bit_identical(params):
    """Feeding a prompt through lm_decode_chunk_slots in K-token chunks
    produces BIT-IDENTICAL K/V and last-position logits to the batched
    lm_prefill pass — the numerics fact the whole unified engine rests
    on."""
    rng = np.random.RandomState(0)
    prompt = _prompt(rng, 10)
    hidden, pc = transformer.lm_prefill(params, prompt[None], MAX_LEN,
                                        HEADS)
    h_last = np.asarray(hidden)[:, prompt.size - 1][:, None]
    ref_logits = np.asarray(transformer._lm_project(
        params, jax.numpy.asarray(h_last)))[:, 0]
    cache = transformer.init_lm_cache(params, SLOTS, MAX_LEN)
    p, out = 0, None
    while p < prompt.size:
        n = min(K, prompt.size - p)
        toks = np.zeros((SLOTS, K), np.int32)
        toks[0, :n] = prompt[p:p + n]
        lens = np.ones((SLOTS,), np.int32)
        lens[0] = n
        poss = np.zeros((SLOTS,), np.int32)
        poss[0] = p
        out, cache = transformer.lm_decode_chunk_slots(
            params, toks, poss, lens, cache, HEADS)
        p += n
    assert np.array_equal(np.asarray(out)[0], ref_logits[0])
    for layer, (c, ref) in enumerate(zip(cache, pc)):
        assert np.array_equal(np.asarray(c["k"])[0, :prompt.size],
                              np.asarray(ref["k"])[0, :prompt.size]), layer
        assert np.array_equal(np.asarray(c["v"])[0, :prompt.size],
                              np.asarray(ref["v"])[0, :prompt.size]), layer


def test_chunk_step_len1_matches_tq1_step(params):
    """Every row at lengths=1 computes what the Tq=1 slot step computes
    — same greedy tokens, logits equal to float rounding (XLA may tile
    the [S, K, D] matmuls differently from [S, 1, D], so the last ULP
    can move; the ENGINE is self-consistent because it always runs the
    one chunk-shaped step, and the drive tests below pin stream-level
    bit-identity against lm_generate)."""
    rng = np.random.RandomState(1)
    cache = transformer.init_lm_cache(params, SLOTS, MAX_LEN)
    toks = rng.randint(1, VOCAB, SLOTS).astype(np.int32)
    pos = rng.randint(0, 8, SLOTS).astype(np.int32)
    l1, c1 = transformer.lm_decode_step_slots(params, toks, pos, cache,
                                              HEADS)
    tk = np.zeros((SLOTS, K), np.int32)
    tk[:, 0] = toks
    l2, c2 = transformer.lm_decode_chunk_slots(
        params, tk, pos, np.ones((SLOTS,), np.int32), cache, HEADS)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                               rtol=1e-6, atol=1e-7)
    assert np.array_equal(np.argmax(np.asarray(l1), -1),
                          np.argmax(np.asarray(l2), -1))
    rows = np.arange(SLOTS)
    for a, b in zip(c1, c2):
        np.testing.assert_allclose(np.asarray(a["k"])[rows, pos],
                                   np.asarray(b["k"])[rows, pos],
                                   rtol=2e-5, atol=1e-6)


# --------------------------------------------------------- engine parity


@pytest.mark.slow
def test_chunked_staggered_admissions_bit_identical(params):
    """The acceptance drive: more requests than slots, mixed prompt
    lengths (including chunk-boundary sizes 1 / K-1 / K / K+1 / 2K and
    prompts BEYOND the legacy ladder top) and mixed max_tokens,
    staggered so admissions land mid-decode — every stream equals the
    single-request oracle exactly."""
    eng = _engine(params, name="cp_slab")
    eng.metrics = ServingMetrics()
    bat = GenerationBatcher(eng, default_max_tokens=8)
    rng = np.random.RandomState(2)
    sizes = [1, K - 1, K, K + 1, 2 * K, 25, 30]     # 25/30 > ladder 16
    cases = [(_prompt(rng, s), int(rng.randint(2, 10))) for s in sizes]
    cases += [(_prompt(rng), int(rng.randint(2, 10))) for _ in range(5)]
    results, excs = _drive(bat, cases)
    bat.close()
    assert all(e is None for e in excs), excs
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(params, prompt, n), \
            f"prompt len {prompt.size}, n {n}"
        assert res["finish_reason"] == "length"
    snap = eng.metrics.snapshot()
    assert snap["prefill_chunks_total"] >= 1
    assert snap["prefill_chunk_lanes_total"] > 0
    assert snap["prefill_chunk_size"] == K
    assert eng.free_slots == SLOTS
    # the legacy ladder was never touched: no prefill engines exist
    assert not eng._prefill_engines


def test_chunked_eos_and_single_token(params):
    """EOS pinning (including an immediate first-token EOS) and
    max_tokens=1 — the finishes that land exactly at the feed-drain
    boundary."""
    eng = _engine(params, name="cp_eos")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(3)
    prompt = _prompt(rng, 9)
    first = _oracle(params, prompt, 1)[0]
    res = bat.submit(prompt, max_tokens=20, eos_id=first).result(60)
    assert res["finish_reason"] == "eos" and res["tokens"] == [first]
    res = bat.submit(prompt, max_tokens=1).result(60)
    assert res["finish_reason"] == "length" and res["tokens"] == [first]
    want = _oracle(params, prompt, 12, eos_id=first + 1)
    res = bat.submit(prompt, max_tokens=12,
                     eos_id=first + 1).result(60)
    stop = want.index(first + 1) + 1 if first + 1 in want else 12
    assert res["tokens"] == want[:stop]
    bat.close()


def test_chunked_rope_trunk_bit_identical(rope_params):
    """The rope trunk chunks with per-lane rotary positions — streams
    stay bit-identical to the rope oracle."""
    eng = _engine(rope_params, name="cp_rope", pos_type="rope")
    bat = GenerationBatcher(eng, default_max_tokens=6)
    rng = np.random.RandomState(4)
    cases = [(_prompt(rng, s), 6) for s in (3, K, 13)]
    results, excs = _drive(bat, cases)
    bat.close()
    assert all(e is None for e in excs), excs
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(rope_params, prompt, n,
                                        pos_type="rope")


def test_chunked_continuation_replay_bit_identical(params):
    """PR-7 continuations ride chunks: a stream interrupted after k
    delivered tokens finishes emitting ONLY the remainder, bit-identical
    — including contexts longer than the legacy ladder top."""
    eng = _engine(params, name="cp_cont")
    bat = GenerationBatcher(eng)
    rng = np.random.RandomState(5)
    for plen, n, k in ((5, 10, 3), (16, 12, 7), (16, 24, 14)):
        prompt = _prompt(rng, plen)
        full = _oracle(params, prompt, n)
        res = bat.submit(prompt, replay=np.asarray(full[:k], np.int32),
                         max_tokens=n - k).result(60)
        assert res["tokens"] == full[k:], (plen, n, k)
    bat.close()


# ------------------------------------------------------------ paged


@pytest.mark.slow
def test_chunked_paged_prefix_cow_pressure_bit_identical(params):
    """The paged composition: chunked admission grows chains block by
    block, prompts register in the prefix index at first emission,
    duplicates seat by reference and CoW-fork on their first write,
    and a deliberately tight pool preempts + re-seats — every stream
    bit-identical, ledger balanced."""
    eng = _engine(params, name="cp_paged", kv_layout="paged",
                  kv_block_size=BS)
    eng.metrics = ServingMetrics()
    bat = GenerationBatcher(eng, default_max_tokens=6)
    rng = np.random.RandomState(6)
    sysp = _prompt(rng, BS + BS // 2)
    div = np.concatenate([sysp[:BS], _prompt(rng, 4)])
    lead = bat.submit(sysp, max_tokens=6).result(60)
    dup = bat.submit(sysp, max_tokens=6).result(60)
    dv = bat.submit(div, max_tokens=6).result(60)
    bat.close()
    assert lead["tokens"] == dup["tokens"] == _oracle(params, sysp, 6)
    assert dv["tokens"] == _oracle(params, div, 6)
    snap = eng.metrics.snapshot()
    assert snap["prefix_cache_hits_total"] == 2
    assert snap["cow_forks_total"] >= 1
    eng._paged.check()
    assert eng.free_slots == SLOTS

    # deterministic pool pressure (tight pool, tight-loop submits)
    eng2 = _engine(params, name="cp_tight", kv_layout="paged",
                   kv_block_size=BS, kv_num_blocks=10)
    bat2 = GenerationBatcher(eng2, default_max_tokens=16)
    cases = [(_prompt(rng, 16), 16) for _ in range(6)]
    futs = [bat2.submit(p, max_tokens=n) for p, n in cases]
    results = [f.result(300) for f in futs]
    bat2.close()
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(params, prompt, n)
    s2 = eng2.metrics.snapshot()
    assert s2["evictions"]["pool_exhausted"] >= 1, s2
    assert s2["slot_reprefills_total"] >= 1, s2
    eng2._paged.check()


# ----------------------------------------------------- trace discipline


def test_one_warmup_trace_zero_retraces_under_chunk_churn(params):
    """ONE step trace at warm-up (the chunked engine compiles no
    admission write and no prefill ladder at all; paged adds only the
    block-fork executable), then ZERO traces across admission churn,
    varying chunk lane counts, budget throttling, prefix hits, CoW
    forks and pool preemption — lane counts are data, not shape."""
    for layout, extra in (("slab", {}),
                          ("paged", {"kv_block_size": BS,
                                     "kv_num_blocks": 12})):
        eng = _engine(params, name=f"cp_trace_{layout}",
                      kv_layout=layout, prefill_chunk_budget=5, **extra)
        assert eng.step_trace_count == 1
        rng = np.random.RandomState(7)
        shared = _prompt(rng, BS + 2)
        counters = [lambda: eng.step_trace_count]
        if layout == "paged":
            assert eng._copy_traces[0] == 1
            assert eng._write_traces[0] == 0    # never compiled
            counters.append(lambda: eng._copy_traces[0])
        with assert_no_retrace(
                lambda: sum(c() for c in counters),
                f"chunked churn ({layout}: admit/chunk/budget/CoW)"):
            bat = GenerationBatcher(eng, default_max_tokens=8)
            cases = [(shared, 8), (shared, 8)]
            cases += [(_prompt(rng), int(rng.randint(2, 13)))
                      for _ in range(6)]
            results, excs = _drive(bat, cases)
            bat.close()
        assert all(e is None for e in excs), excs


def test_chunk_budget_bounds_per_step_lanes(params):
    """prefill_chunk_budget=B: no step ever feeds more than B
    teacher-forced lanes across all slots (the per-step prefill bound
    that keeps TPOT flat), and streams stay bit-identical."""
    budget = 3

    class Spy(ServingMetrics):
        max_lanes = 0

        def observe_decode_step(self, n_active, n_slots, seconds,
                                prefill_lanes=0):
            Spy.max_lanes = max(Spy.max_lanes, prefill_lanes)
            super().observe_decode_step(n_active, n_slots, seconds,
                                        prefill_lanes)

    eng = _engine(params, name="cp_budget", prefill_chunk_budget=budget)
    eng.metrics = Spy()
    bat = GenerationBatcher(eng, default_max_tokens=5)
    rng = np.random.RandomState(8)
    cases = [(_prompt(rng, 20), 5) for _ in range(6)]
    results, excs = _drive(bat, cases, stagger_s=0.0)
    bat.close()
    assert all(e is None for e in excs), excs
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(params, prompt, n)
    assert 0 < Spy.max_lanes <= budget


# --------------------------------------------------- fused chunk kernels


@pytest.mark.slow
def test_chunked_with_fused_kernels_token_identical(params):
    """pallas_decode=always compiles the Tq=chunk kernels INTO the
    unified step (interpret mode on CPU): greedy streams must be
    TOKEN-identical to the oracle on both layouts, still 1 trace."""
    rng = np.random.RandomState(9)
    cases = [(_prompt(rng), int(rng.randint(2, 9))) for _ in range(6)]
    for layout in ("slab", "paged"):
        with decode_kernels.forced_mode("always"):
            eng = _engine(params, name=f"cp_k_{layout}",
                          kv_layout=layout, kv_block_size=BS)
            assert eng.decode_kernels
            bat = GenerationBatcher(eng, default_max_tokens=8)
            results, excs = _drive(bat, cases)
            bat.close()
        assert all(e is None for e in excs), excs
        for (prompt, n), res in zip(cases, results):
            assert res["tokens"] == _oracle(params, prompt, n), layout
        assert eng.step_trace_count == 1


# ------------------------------------------------- supervisor recovery


@pytest.mark.slow
def test_supervisor_recovery_rides_chunks_bit_identical(params):
    """PR-6 chaos on the chunked engine: an injected decode-step fault
    rebuilds the pool and re-seats every in-flight stream through
    CHUNKED seating (whole contexts as K-lane feeds — no ladder, no
    per-token-only replay) — all streams bit-identical, zero extra
    traces, ledger balanced."""
    eng = _engine(params, name="cp_chaos", kv_layout="paged",
                  kv_block_size=BS)
    eng.metrics = ServingMetrics()
    rng = np.random.RandomState(10)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(8)]
    ref = [_oracle(params, p, n) for p, n in cases]
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(eng, supervisor=sup)
    faults.install_spec("serving.decode_step:at=6")
    with assert_no_retrace(lambda: eng.step_trace_count,
                           "chunked chaos recovery"):
        results, excs = _drive(bat, cases)
        bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    assert all(e is None for e in excs), excs
    assert [r["tokens"] for r in results] == ref
    snap = eng.metrics.snapshot()
    assert snap["evictions"]["recovered"] >= 1
    assert snap["slot_reprefills_total"] >= 1
    assert not eng._prefill_engines       # recovery never built a ladder
    eng._paged.check()


# --------------------------------------------------------- validation


def test_chunked_validation_and_config(params):
    eng = _engine(params, name="cp_val", warm=False)
    # no ladder cap: a prompt beyond the bucket top is FINE now...
    eng.validate_request(np.arange(1, 31, dtype=np.int32), 8)
    # ...but max_len still bounds prompt + emission
    with pytest.raises(InvalidRequestError, match="max_len"):
        eng.validate_request(np.arange(1, 41, dtype=np.int32), 10)
    with pytest.raises(ConfigError, match="prefill_chunk"):
        _engine(params, name="cp_bad", prefill_chunk=-1, warm=False)
    with pytest.raises(ConfigError, match="prefill_chunk"):
        _engine(params, name="cp_bad2", prefill_chunk=MAX_LEN + 1,
                warm=False)
    # chunked mode ignores the ladder-top-vs-max_len constraint the
    # legacy mode enforces (it never builds the ladder)
    DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS, max_len=24,
                 prefill_buckets=(8, 32), prefill_chunk=K, warm=False,
                 name="cp_nobucket")


# ----------------------------------------------------------- metrics


def test_chunked_metrics_surface(params):
    """The new /metrics surface: chunk counters, occupancy, TPOT jitter
    — in both the snapshot and the Prometheus rendering."""
    eng = _engine(params, name="cp_metrics")
    eng.metrics = ServingMetrics()
    bat = GenerationBatcher(eng, default_max_tokens=6)
    rng = np.random.RandomState(11)
    futs = [bat.submit(_prompt(rng, 20), max_tokens=6) for _ in range(4)]
    for f in futs:
        f.result(60)
    bat.close()
    snap = eng.metrics.snapshot()
    # each 20-token prompt feeds 19 tokens; at K-1 = 3 loaded lanes per
    # chunk that is >= 5 chunks and >= 10 loaded lanes per request
    assert snap["prefill_chunks_total"] >= 4 * 5
    assert snap["prefill_chunk_lanes_total"] >= 4 * 10
    assert snap["prefill_chunk_size"] == K
    assert snap["mean_prefill_chunk_occupancy"] > 0
    assert snap["tpot_jitter_p99_p50"] >= 1.0
    text = eng.metrics.render_prometheus()
    n = eng.metrics.name
    assert f"{n}_prefill_chunks_total " in text
    assert f"{n}_prefill_chunk_lanes_total " in text
    assert f"{n}_prefill_chunk_size {K}" in text
    assert f"{n}_prefill_chunk_occupancy_mean " in text
    assert f"{n}_tpot_jitter_p99_p50 " in text


# ------------------------------------------------- prefill flash gate


def test_prefill_flash_no_score_matrix_and_reverse():
    """The analytic acceptance gate's core: lm_prefill routed through
    flash holds NO [Tp, Tp] float buffer in its compiled HLO, and the
    masked XLA reference TRIPS the same detector (the gate works in
    both directions).  Tp is large enough that flash really blocks —
    a single-block run would legitimately hold a [Tp, Tp] tile."""
    import importlib

    import jax.numpy as jnp

    from paddle_tpu.perf import analytic

    flash_mod = importlib.import_module(
        "paddle_tpu.ops.pallas.flash_attention")
    tp = 640
    p = transformer.init(jax.random.PRNGKey(2), src_vocab=VOCAB,
                         trg_vocab=1, d_model=64, dff=64, enc_layers=1,
                         dec_layers=0, max_len=tp, num_heads=1)
    spec = jax.ShapeDtypeStruct((1, tp), jnp.int32)

    def lower():
        # fresh closure per mode: the routing is read at trace time and
        # jax caches traces on the function object
        def fn(prompt):
            return transformer.lm_prefill(p, prompt, tp, 1)
        return jax.jit(fn).lower(spec).compile().as_text()

    with flash_mod.forced_prefill_mode("always"):
        analytic.assert_prefill_flash(lower(), tp)
    with flash_mod.forced_prefill_mode("off"):
        hits = analytic.score_matrix_instrs(lower(), tp, tp)
    assert hits, "detector failed to flag the masked XLA prefill"
    with pytest.raises(AssertionError, match="score matrix"):
        with flash_mod.forced_prefill_mode("off"):
            analytic.assert_prefill_flash(lower(), tp)


def test_prefill_flash_numerics_close(params):
    """Flash-routed prefill is numerically equivalent to the masked
    reference (not bit-identical — the online softmax accumulates
    differently, which is why the CPU tier-1 default keeps the
    reference path and the flag is trace-time opt-in)."""
    import importlib
    flash_mod = importlib.import_module(
        "paddle_tpu.ops.pallas.flash_attention")
    rng = np.random.RandomState(12)
    prompt = _prompt(rng, 16)[None]
    with flash_mod.forced_prefill_mode("off"):
        h_ref, c_ref = transformer.lm_prefill(params, prompt, MAX_LEN,
                                              HEADS)
    with flash_mod.forced_prefill_mode("always"):
        h_fl, c_fl = transformer.lm_prefill(params, prompt, MAX_LEN,
                                            HEADS)
    np.testing.assert_allclose(np.asarray(h_ref), np.asarray(h_fl),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_ref[0]["k"]),
                               np.asarray(c_fl[0]["k"]),
                               rtol=2e-5, atol=2e-5)
