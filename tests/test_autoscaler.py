"""SLO-holding control plane (serving/autoscaler.py + serving/overload.py;
docs/serving.md §8).

Fast lane: the control LAW against scripted stub fleets on a SIMULATED
clock — scale-out on sustained TTFT-p99 breach, scale-in on sustained
slack with the idle-victim rule, flap-free hysteresis under oscillating
load, min/max bounds, `fleet.spawn`/`autoscaler.scale` chaos with
seeded-backoff retries, the brownout ladder's exact rung entry/exit
counter sequences, AIMD limiter + priority shed order + honest
Retry-After, router-level shedding/brownout effects over stub replicas,
and the headline determinism property: the full decision journal
replays BIT-FOR-BIT given the same seed and simulated clock.  No test
here sleeps for control-loop time — the injectable clock is the point.

Slow lane: the real-subprocess drive — `python -m
paddle_tpu.serving.autoscaler --smoke` (1 replica + seeded spike →
scale-out to 2 → recover → scale-in, zero failed requests).
"""

import json
import os
import random
import socket
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddle_tpu.resilience import faults
from paddle_tpu.serving.autoscaler import Autoscaler
from paddle_tpu.serving.overload import (AIMDLimiter, BrownoutLadder,
                                         DrainRate, OverloadController,
                                         ShedError)
from paddle_tpu.serving.router import Router, RouterMetrics
from paddle_tpu.utils.stats import Histogram

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


# --------------------------------------------------------------- harness


class SimClock:
    """A monotonic clock the test advances by hand."""

    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)
        return self.t


class StubSupervisor:
    """A scripted ReplicaSupervisor: add/remove bookkeeping without
    subprocesses.  ``add_replica`` fires the same ``fleet.spawn`` fault
    point the real one does, so seeded chaos plans hit identically."""

    def __init__(self, n=1, ready=True):
        self.replicas = {f"r{i}": object() for i in range(n)}
        self._next = n
        self.added, self.removed = [], []
        self.ready = ready              # wait_ready verdict (scriptable)

    def add_replica(self):
        faults.hit("fleet.spawn")
        rid = f"r{self._next}"
        self._next += 1
        self.replicas[rid] = object()
        self.added.append(rid)
        return rid

    def remove_replica(self, rid, drain_timeout=60.0):
        self.replicas.pop(rid)
        self.removed.append(rid)

    def wait_ready(self, timeout=0.0, rids=None):
        return self.ready


class StubRouterView:
    """The router surface the autoscaler consumes: a real RouterMetrics
    (sim-clocked recent windows) + a scriptable replica_states()."""

    def __init__(self, clock, states=None):
        self.metrics = RouterMetrics(clock=clock)
        self.extra_render_fns = []
        self.states = states if states is not None else {
            "r0": {"ready": True, "queue_depth": 0, "inflight": 0,
                   "breaker": "closed"}}

    def replica_states(self):
        return {rid: dict(st) for rid, st in self.states.items()}

    def set_replica(self, rid, ready=True, queue_depth=0, inflight=0,
                    breaker="closed"):
        self.states[rid] = {"ready": ready, "queue_depth": queue_depth,
                            "inflight": inflight, "breaker": breaker}


def make_scaler(sup, router, clk, **kw):
    base = dict(poll_interval_s=1.0, target_ttft_ms=500.0, hysteresis=0.2,
                breach_polls=3, slack_polls=4, cooldown_out_s=5.0,
                cooldown_in_s=20.0, min_replicas=1, max_replicas=3,
                window_s=10.0, seed=7, ready_timeout_s=1.0,
                clock=clk)
    base.update(kw)
    return Autoscaler(sup, router, **base)


def feed_ttft(router, ms, n=5):
    for _ in range(n):
        router.metrics.observe_ttft(ms / 1e3)


# ------------------------------------------------- injectable clock plumbing


def test_histogram_windowed_percentiles_sim_clock():
    """The satellite clock threading: a sim-clocked Histogram's windowed
    p99 expires samples deterministically — no wall-clock sleeps — and
    a clockless Histogram rejects window_s while behaving exactly as
    before otherwise."""
    clk = SimClock(0.0)
    h = Histogram("t", keep="last", clock=clk)
    h.add(1.0)
    clk.advance(5)
    h.add(0.1)
    assert h.percentiles((99,))[99] > 0.9          # un-windowed: all
    assert h.percentiles((99,), window_s=3)[99] == pytest.approx(0.1)
    clk.advance(10)
    assert h.percentiles((99,), window_s=3)[99] == 0.0   # expired
    plain = Histogram("p")
    plain.add(2.0)
    assert plain.percentiles((50,))[50] == 2.0
    with pytest.raises(ValueError, match="clock"):
        plain.percentiles((50,), window_s=1)


def test_router_metrics_slo_signal_prefers_ttft():
    clk = SimClock()
    m = RouterMetrics(clock=clk)
    # EMPTY window = no signal, not "healthy 0ms"
    assert m.slo_p99_recent_s(10) is None
    m.observe_response(0.4)
    assert m.slo_p99_recent_s(10) == pytest.approx(0.4)   # latency fallback
    m.observe_ttft(0.05)
    assert m.slo_p99_recent_s(10) == pytest.approx(0.05)  # ttft wins
    assert "ttft_ms" in m.snapshot()
    # samples expiring out of the window bring the None back
    clk.advance(100)
    assert m.slo_p99_recent_s(10) is None


# ------------------------------------------------------------- control law


def test_scale_out_on_sustained_breach_only():
    """A breach must HOLD for breach_polls before anything moves; the
    scale-out lands exactly on the Nth breach poll and capacity follows
    spawn-to-readiness."""
    clk = SimClock()
    sup = StubSupervisor(1)
    router = StubRouterView(clk)
    a = make_scaler(sup, router, clk, breach_polls=3)
    feed_ttft(router, 2000)
    decisions = []
    for _ in range(4):
        decisions.append(a.tick()["decision"])
        clk.advance(1.0)
    assert decisions[:2] == ["hold", "hold"]    # streak building
    assert decisions[2] == "out"                # 3rd consecutive breach
    assert sup.added == ["r1"]
    assert len(sup.replicas) == 2
    assert a.scales_total["out"] == 1
    # one transient blip never scales: streak resets on a healthy poll
    sup2 = StubSupervisor(1)
    router2 = StubRouterView(clk)
    b = make_scaler(sup2, router2, clk, breach_polls=3, window_s=0.5)
    for i in range(6):
        # alternate: one breached poll, one healthy poll
        router2.metrics.observe_ttft(2.0 if i % 2 == 0 else 0.05)
        b.tick()
        clk.advance(1.0)
    assert sup2.added == []


def test_max_and_min_bounds_are_hard():
    clk = SimClock()
    sup = StubSupervisor(2)
    router = StubRouterView(clk)
    router.set_replica("r1")
    a = make_scaler(sup, router, clk, breach_polls=1, max_replicas=2,
                    cooldown_out_s=0.0)
    feed_ttft(router, 2000)
    e = a.tick()
    assert e["decision"] == "hold" and "max_replicas" in e["reason"]
    assert sup.added == []
    # and the floor: slack at min_replicas never scales in
    clk.advance(100)
    sup2 = StubSupervisor(1)
    router2 = StubRouterView(clk)
    b = make_scaler(sup2, router2, clk, slack_polls=1, min_replicas=1,
                    cooldown_in_s=0.0)
    feed_ttft(router2, 10)
    for _ in range(5):
        assert b.tick()["decision"] == "hold"
        clk.advance(1.0)
    assert sup2.removed == []


def test_scale_in_never_drains_active_when_idle_exists():
    """The small-fix satellite: the scale-in victim is the IDLE replica,
    even when the busy one sorts first by id."""
    clk = SimClock()
    sup = StubSupervisor(2)
    router = StubRouterView(clk)
    router.set_replica("r0", inflight=3)        # busy, lower id
    router.set_replica("r1", inflight=0)        # idle
    a = make_scaler(sup, router, clk, slack_polls=2, cooldown_in_s=0.0)
    feed_ttft(router, 10)
    a.tick()
    clk.advance(1.0)
    e = a.tick()
    assert e["decision"] == "in"
    assert sup.removed == ["r1"], "drained the busy replica instead " \
        "of the idle one"
    # with NO idle replica, the least-loaded one drains (graceful drain
    # finishes its streams; drain-then-death is pinned separately below)
    clk.advance(100)
    sup2 = StubSupervisor(2)
    router2 = StubRouterView(clk)
    router2.set_replica("r0", inflight=5)
    router2.set_replica("r1", inflight=1)
    b = make_scaler(sup2, router2, clk, slack_polls=1, cooldown_in_s=0.0)
    feed_ttft(router2, 10)
    b.tick()
    assert sup2.removed == ["r1"]


def test_scale_in_removes_dead_replica_before_draining_healthy():
    """Review hardening: the scale-in victim is a NOT-serving replica
    (dead/backoff) when one exists — draining the only healthy replica
    while a corpse stays counted would be a self-inflicted outage."""
    clk = SimClock()
    sup = StubSupervisor(2)
    router = StubRouterView(clk)
    router.set_replica("r0", ready=True, inflight=0)    # healthy + idle
    router.set_replica("r1", ready=False)               # dead/backoff
    a = make_scaler(sup, router, clk, slack_polls=1, cooldown_in_s=0.0)
    feed_ttft(router, 10)
    e = a.tick()
    assert e["decision"] == "in"
    assert sup.removed == ["r1"], "drained the healthy replica while " \
        "a dead one stayed counted"


def test_total_stall_no_signal_never_reads_as_slack():
    """Review hardening: an EMPTY SLO window (nothing completed) with
    work still in flight is a stall, not health — the loop holds; only
    a provably idle fleet (no queue, no inflight) shrinks on
    no-signal."""
    clk = SimClock()
    sup = StubSupervisor(2)
    router = StubRouterView(clk)
    router.set_replica("r0", inflight=3)        # stuck in-flight work
    router.set_replica("r1", inflight=2)
    a = make_scaler(sup, router, clk, slack_polls=1, cooldown_in_s=0.0)
    # no ttft/latency samples at all -> p99 is None
    for _ in range(5):
        e = a.tick()
        assert e["decision"] == "hold", e
        assert e["signals"]["ttft_p99_ms"] is None
        clk.advance(1.0)
    assert sup.removed == []
    # the same no-signal fleet, provably idle -> slack applies
    router.set_replica("r0", inflight=0)
    router.set_replica("r1", inflight=0)
    e = a.tick()
    assert e["decision"] == "in" and "no-signal" in e["reason"]


def test_flap_free_under_oscillating_load():
    """The acceptance bar: under oscillating load the replica count
    changes at most once per cooldown window — consecutive scale events
    are separated by at least the acting direction's cooldown."""
    clk = SimClock()
    sup = StubSupervisor(1)
    router = StubRouterView(clk)
    a = make_scaler(sup, router, clk, breach_polls=2, slack_polls=2,
                    cooldown_out_s=4.0, cooldown_in_s=10.0,
                    window_s=0.5, max_replicas=2)
    events = []
    for i in range(120):
        # square-wave load: 6 polls loud, 6 polls quiet — each phase is
        # long enough to fill either streak, so only the cooldowns damp
        router.metrics.observe_ttft(2.0 if (i // 6) % 2 == 0 else 0.01)
        # keep the router view in lockstep with the fleet (the real
        # poller's job)
        router.states = {rid: {"ready": True, "queue_depth": 0,
                               "inflight": 0, "breaker": "closed"}
                         for rid in sup.replicas}
        e = a.tick()
        if e["decision"] in ("out", "in"):
            events.append((e["t"], e["decision"]))
        clk.advance(1.0)
    assert events, "the oscillation never moved the fleet at all"
    for (t1, _d1), (t2, d2) in zip(events, events[1:]):
        need = 4.0 if d2 == "out" else 10.0
        assert t2 - t1 >= need, (events, "flapped faster than cooldown")


# ------------------------------------------------------------ chaos legs


def test_spawn_fault_retries_with_seeded_backoff():
    """fleet.spawn chaos: the injected spawn failure is retried with the
    EXACT seeded backoff delay, the failed attempt registers nothing,
    and the retry succeeds once the fault is spent."""
    clk = SimClock()
    sup = StubSupervisor(1)
    router = StubRouterView(clk)
    router.set_replica("r0", queue_depth=4, inflight=2)
    a = make_scaler(sup, router, clk, breach_polls=1, cooldown_out_s=0.0,
                    seed=13, retry_base_s=0.5, retry_max_s=4.0)
    feed_ttft(router, 2000)
    faults.install_spec("fleet.spawn:at=1")
    e = a.tick()
    assert e["decision"] == "out"
    assert e["actuation"]["ok"] is False
    assert "InjectedFault" in e["actuation"]["error"]
    assert sup.added == [] and len(sup.replicas) == 1
    assert a.scale_failures_total == 1
    # the retry delay replays the seeded stream exactly
    expect = round(0.5 * (0.5 + 0.5 * random.Random(13).random()), 4)
    assert e["actuation"]["retry_in_s"] == expect
    # before the backoff elapses: hold, no second attempt
    clk.advance(expect / 2)
    assert a.tick()["decision"] == "hold"
    assert sup.added == []
    # past the backoff: the retry fires and lands (fault was one-shot)
    clk.advance(expect)
    e = a.tick()
    assert e["decision"] == "out" and e["actuation"]["ok"] is True
    assert sup.added == ["r1"]
    assert faults.fired_counts()["fleet.spawn"] == 1


def test_unready_replica_never_counts_as_capacity():
    """A spawned replica that never reaches readiness is REMOVED and the
    attempt retried — the fleet never carries phantom capacity."""
    clk = SimClock()
    sup = StubSupervisor(1, ready=False)        # wait_ready times out
    router = StubRouterView(clk)
    router.set_replica("r0", queue_depth=4)
    a = make_scaler(sup, router, clk, breach_polls=1, cooldown_out_s=0.0)
    feed_ttft(router, 2000)
    e = a.tick()
    assert e["actuation"]["ok"] is False and "not ready" in \
        e["actuation"]["error"]
    assert sup.added == ["r1"] and sup.removed == ["r1"]
    assert len(sup.replicas) == 1
    assert a.scale_failures_total == 1


def test_autoscaler_scale_fault_point():
    """autoscaler.scale chaos: actuation fails BEFORE the supervisor is
    touched; the retry resolves it."""
    clk = SimClock()
    sup = StubSupervisor(1)
    router = StubRouterView(clk)
    a = make_scaler(sup, router, clk, breach_polls=1, cooldown_out_s=0.0,
                    retry_base_s=0.1, retry_max_s=0.1)
    feed_ttft(router, 2000)
    faults.install_spec("autoscaler.scale:at=1")
    e = a.tick()
    assert e["actuation"]["ok"] is False
    assert sup.added == [], "a failed decision must not touch the fleet"
    clk.advance(1.0)
    e = a.tick()
    assert e["actuation"]["ok"] is True and sup.added == ["r1"]
    assert faults.fired_counts()["autoscaler.scale"] == 1


def test_real_supervisor_spawn_fault_becomes_backoff_restart():
    """The REAL ReplicaSupervisor placement of fleet.spawn: an injected
    spawn failure on start() is accounted exactly like an instant crash
    — seeded backoff schedule, then the monitor retries and the replica
    comes up (no supervisor thread death, no unhandled exception)."""
    from paddle_tpu.serving.fleet import ReplicaSupervisor
    faults.install_spec("fleet.spawn:at=1")
    sup = ReplicaSupervisor(n_replicas=1,
                            cmd=["-c", "import time; time.sleep(60)"],
                            backoff_base_s=0.05, backoff_max_s=0.4,
                            seed=11, name="spawn_fault_t")
    sup.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap = sup.snapshot()["r0"]
            if snap["pid"] is not None:
                break
            time.sleep(0.02)
        snap = sup.snapshot()["r0"]
        assert snap["pid"] is not None, snap
        assert snap["consecutive_failures"] == 1
        rng = random.Random(11 * 7919 + 0)
        expect = round(min(0.05, 0.4) * (0.5 + 0.5 * rng.random()), 4)
        assert snap["backoff_delays_s"] == [expect]
        assert faults.fired_counts()["fleet.spawn"] == 1
    finally:
        sup.stop()


def test_retry_abandoned_when_conditions_invert():
    """Review hardening: a pending scale-out retry is ABANDONED when the
    spike ends while the spawn was failing — the stale direction must
    not fire into a healthy fleet; the law re-decides from fresh
    streaks."""
    clk = SimClock()
    sup = StubSupervisor(1)
    router = StubRouterView(clk)
    a = make_scaler(sup, router, clk, breach_polls=1, cooldown_out_s=0.0,
                    window_s=2.0, retry_base_s=5.0, retry_max_s=5.0)
    feed_ttft(router, 2000)
    faults.install_spec("fleet.spawn:every=1")      # every spawn fails
    e = a.tick()
    assert e["decision"] == "out" and e["actuation"]["ok"] is False
    faults.clear()
    # the spike ends during the backoff: breach samples expire, healthy
    # ones land
    clk.advance(3.0)
    router.metrics.observe_ttft(0.01)
    clk.advance(3.0)                    # past the retry-at time
    router.metrics.observe_ttft(0.01)
    e = a.tick()
    assert e["decision"] == "hold", e    # retry dropped, law re-decides
    for _ in range(5):
        clk.advance(1.0)
        router.metrics.observe_ttft(0.01)
        e = a.tick()
        assert e["decision"] != "out", e
    assert sup.added == [], "stale retry scaled a healthy fleet"


# ----------------------------------------------------- bit-for-bit replay


def _scripted_run(seed):
    """One full scripted scenario (breach -> chaos -> recovery -> slack)
    on a fresh sim-clocked stub fleet; returns the journal lines."""
    faults.clear()
    faults.install_spec("fleet.spawn:at=2")
    clk = SimClock(50.0)
    sup = StubSupervisor(1)
    router = StubRouterView(clk)
    a = make_scaler(sup, router, clk, breach_polls=2, slack_polls=3,
                    cooldown_out_s=2.0, cooldown_in_s=6.0, seed=seed,
                    retry_base_s=0.5, window_s=4.0, max_replicas=3)
    script = [2000] * 8 + [100] * 4 + [2000] * 6 + [10] * 14
    for i, ms in enumerate(script):
        router.metrics.observe_ttft(ms / 1e3)
        for rid in list(sup.replicas):
            router.set_replica(rid, inflight=1 if ms > 500 and
                               rid == "r0" else 0)
        a.tick()
        clk.advance(1.0)
    lines = a.journal_lines()
    faults.clear()
    return lines


def test_decision_journal_replays_bit_for_bit():
    """THE determinism acceptance bar: same seed + same simulated clock
    + same scripted signals -> the SAME decision log, byte for byte —
    including the chaos retry timing; a different seed diverges."""
    run1 = _scripted_run(seed=21)
    run2 = _scripted_run(seed=21)
    assert run1 == run2
    assert any('"decision": "out"' in ln for ln in run1)
    assert any('"decision": "in"' in ln for ln in run1)
    assert any('"ok": false' in ln for ln in run1)    # the chaos leg
    run3 = _scripted_run(seed=22)
    assert run3 != run1                 # the seed is load-bearing


# ------------------------------------------------------- brownout ladder


def test_brownout_ladder_exact_rung_sequences():
    """Rung entry/exit counters, exactly: sustained breach climbs one
    rung per hold period (hedge_off -> token_cap -> shed_background),
    sustained health walks back down one rung per exit period, and a
    short blip moves nothing."""
    clk = SimClock(0.0)
    lad = BrownoutLadder(slo_ttft_s=0.5, enter_hold_s=2.0, exit_hold_s=3.0,
                         clock=clk)
    rungs = []
    for _ in range(9):                      # 9s of breach
        rungs.append(lad.observe(1.0))
        clk.advance(1.0)
    # t=0 arm, t=2 rung1, t=4 rung2, t=6 rung3, capped thereafter
    assert rungs == [0, 0, 1, 1, 2, 2, 3, 3, 3]
    assert lad.entries == {"hedge_off": 1, "token_cap": 1,
                           "shed_background": 1}
    assert lad.exits == {"hedge_off": 0, "token_cap": 0,
                         "shed_background": 0}
    assert not lad.hedging_allowed() and lad.shed_background()
    rungs = []
    for _ in range(11):                     # 11s of health
        rungs.append(lad.observe(0.1))
        clk.advance(1.0)
    assert rungs == [3, 3, 3, 2, 2, 2, 1, 1, 1, 0, 0]
    assert lad.exits == {"hedge_off": 1, "token_cap": 1,
                         "shed_background": 1}
    assert lad.hedging_allowed() and not lad.shed_background()
    # a 1s blip (under enter_hold) never enters a rung
    lad.observe(1.0)
    clk.advance(1.0)
    assert lad.observe(0.1) == 0
    assert lad.entries["hedge_off"] == 1
    # disabled ladder is inert
    off = BrownoutLadder(slo_ttft_s=0.0, clock=clk)
    for _ in range(10):
        assert off.observe(99.0) == 0
        clk.advance(5.0)


# -------------------------------------------- AIMD limiter + shed policy


def test_aimd_limiter_increase_decrease_and_class_order():
    clk = SimClock()
    # class slices of a limit of 3: background 1.8, standard 2.55,
    # interactive 3.0 — background saturates (sheds) first
    lim2 = AIMDLimiter(initial=3, min_limit=1, max_limit=8,
                       decrease_cooldown_s=1.0, clock=clk)
    for _ in range(2):
        assert lim2.try_acquire("standard")       # 0,1 < 2.55
    assert not lim2.try_acquire("background")     # 2 >= 1.8: shed first
    assert lim2.try_acquire("interactive")        # 2 < 3: still admitted
    assert not lim2.try_acquire("interactive")    # 3 >= 3: full
    # multiplicative decrease, once per cooldown window
    lim2.release(overloaded=True)
    assert lim2.limit == 1.5 and lim2.decreases_total == 1
    lim2.release(overloaded=True)                 # same congestion event
    assert lim2.limit == 1.5 and lim2.decreases_total == 1
    clk.advance(2.0)
    lim2.release(overloaded=True)
    assert lim2.limit == 1.0                      # floored at min_limit
    # additive increase on clean completions: +increase/limit each
    lim3 = AIMDLimiter(initial=2, increase=1.0, clock=clk)
    lim3.try_acquire()
    lim3.release()
    assert lim3.limit == pytest.approx(2.5)


def test_retry_after_is_honest_drain_rate():
    """Retry-After = excess in-flight over observed completions/s —
    derived, not a constant."""
    clk = SimClock(0.0)
    ctl = OverloadController(limiter=AIMDLimiter(initial=2, clock=clk),
                             drain_window_s=10.0, clock=clk)
    # 2 completions/second observed for 4s
    for _ in range(8):
        ctl.drain.observe()
        clk.advance(0.5)
    assert ctl.drain.rate() == pytest.approx(2.0, rel=0.3)
    ctl.limiter.inflight = 6        # 6 in flight over a limit of 2
    ra = ctl.retry_after_s()
    # excess = 6 - 2 + 1 = 5; 5 / ~2 per s -> ~3s
    assert 2 <= ra <= 4, ra
    # shed carries it
    ctl.limiter.inflight = int(ctl.limiter.limit) + 5
    with pytest.raises(ShedError) as ei:
        ctl.admit("standard")
    assert ei.value.retry_after_s == ra or ei.value.retry_after_s >= 1
    assert ctl.shed_reasons["limit"] == 1


def test_deadline_aware_shed():
    """A request whose deadline cannot survive the estimated QUEUE wait
    (the excess beyond the parallel-service limit over the drain rate)
    is shed immediately instead of timing out inside the fleet — and at
    healthy concurrency (no excess) a deadline is never shed."""
    clk = SimClock(0.0)
    ctl = OverloadController(limiter=AIMDLimiter(initial=4, clock=clk),
                             drain_window_s=10.0, clock=clk)
    for _ in range(10):                  # ~1 completion/s
        ctl.drain.observe()
        clk.advance(1.0)
    ctl.limiter.inflight = 8             # 4 beyond the limit: ~4s queue
    with pytest.raises(ShedError) as ei:
        ctl.admit("interactive", deadline_ms=2000)
    assert ei.value.reason == "deadline"
    # healthy concurrency: inflight under the limit, zero queue wait —
    # even a tight deadline is admitted (review hardening: the fleet
    # serves in parallel, inflight/rate is NOT the wait)
    ctl.limiter.inflight = 2
    ctl.admit("interactive", deadline_ms=100)


# ------------------------------------------------ router-level integration


class _Stub:
    """Minimal scripted replica for router-level tests: /readyz 200,
    /metrics depth, /v1/infer (settable delay), /v1/generate streaming a
    scripted token list (optional death mid-stream); captures the last
    generate request body."""

    def __init__(self, infer_delay_s=0.0, gen_tokens=(), die_after=None):
        self.infer_delay_s = infer_delay_s
        self.gen_tokens = list(gen_tokens)
        self.die_after = die_after
        self.ready = True
        self.gen_bodies = []
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def handle(self):
                try:
                    super().handle()
                except (ConnectionError, BrokenPipeError):
                    pass

            def _send(self, code, body):
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    self._send(200 if stub.ready else 503, b"{}")
                elif self.path == "/metrics":
                    self._send(200, b"stub_queue_depth 0\n")
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length") or 0))
                if self.path == "/v1/infer":
                    time.sleep(stub.infer_delay_s)
                    self._send(200, b'{"outputs": {"y": [1]}}')
                    return
                stub.gen_bodies.append(json.loads(body))
                if not self.path == "/v1/generate":
                    self._send(404, b"{}")
                    return
                req = stub.gen_bodies[-1]
                n = min(len(stub.gen_tokens),
                        int(req.get("max_tokens") or 64))
                if req.get("stream"):
                    self.send_response(200)
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    for i, t in enumerate(stub.gen_tokens[:n]):
                        if stub.die_after is not None \
                                and i >= stub.die_after:
                            self.connection.setsockopt(
                                socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
                            self.connection.close()
                            self.close_connection = True
                            return
                        data = (json.dumps({"token": int(t)})
                                + "\n").encode()
                        self.wfile.write(f"{len(data):X}\r\n".encode()
                                         + data + b"\r\n")
                    data = (json.dumps(
                        {"done": True, "tokens": stub.gen_tokens[:n],
                         "finish_reason": "length", "ttft_ms": 12.0})
                        + "\n").encode()
                    self.wfile.write(f"{len(data):X}\r\n".encode() + data
                                     + b"\r\n0\r\n\r\n")
                else:
                    self._send(200, json.dumps(
                        {"tokens": stub.gen_tokens[:n],
                         "finish_reason": "length",
                         "ttft_ms": 12.0}).encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


def _wait(pred, timeout=15.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def _post_raw(port, path, body, headers=None, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.getheaders()), r.read()
    except urllib.error.HTTPError as e:
        data = e.read()
        hd = dict(e.headers.items())
        e.close()
        return e.code, hd, data


def test_router_sheds_lowest_class_first_with_retry_after():
    """Admission through the AIMD limit: with the limit pinned low and
    held by in-flight standard traffic, a background request sheds 429
    + Retry-After while an interactive one still lands — and the shed
    is visible in rejected{shed} + overload_shed_total{priority}."""
    stub = _Stub(infer_delay_s=0.6)
    ctl = OverloadController(limiter=AIMDLimiter(initial=3, min_limit=1))
    router = Router(replicas=[stub.url], poll_interval_s=0.05, hedge_ms=0,
                    overload=ctl)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        results = {}

        def infer(tag, headers):
            results[tag] = _post_raw(httpd.port, "/v1/infer", {"feed": {}},
                                     headers)

        slow = [threading.Thread(target=infer,
                                 args=(f"s{i}", {"X-Priority": "standard"}))
                for i in range(2)]
        for t in slow:
            t.start()
        # both standard permits taken (limit 3 -> standard slice 2.55)
        assert _wait(lambda: ctl.limiter.inflight >= 2, 5)
        st, hd, data = _post_raw(httpd.port, "/v1/infer", {"feed": {}},
                                 {"X-Priority": "background"})
        assert st == 429
        assert "Retry-After" in hd and int(hd["Retry-After"]) >= 1
        assert json.loads(data)["priority"] == "background"
        st2, _hd2, _ = _post_raw(httpd.port, "/v1/infer", {"feed": {}},
                                 {"X-Priority": "interactive"})
        assert st2 == 200, "interactive must outlive background"
        for t in slow:
            t.join(30)
        assert all(r[0] == 200 for r in results.values())
        snap = router.metrics.snapshot()
        assert snap["rejected"]["shed"] == 1
        osnap = ctl.snapshot()
        assert osnap["shed_total"]["background"] == 1
        assert osnap["admitted_total"]["interactive"] == 1
        mtext = router.render_prometheus()
        assert 'overload_shed_total{priority="background"} 1' in mtext
        assert "overload_limit" in mtext and "brownout_rung" in mtext
    finally:
        router.close()
        stub.close()


def test_brownout_effects_in_router():
    """The three rungs, through the real router: rung 1 suppresses
    hedging, rung 2 caps a generate's max_tokens before it reaches the
    replica, rung 3 sheds background generates outright — and the
    priority field in the body is honored."""
    clk = SimClock()
    stub = _Stub(gen_tokens=list(range(40)))
    lad = BrownoutLadder(slo_ttft_s=0.1, enter_hold_s=1.0, exit_hold_s=1.0,
                         clock=clk)
    ctl = OverloadController(ladder=lad, brownout_max_tokens=5, clock=clk)
    router = Router(replicas=[stub.url], poll_interval_s=0.05,
                    hedge_ms=40, overload=ctl)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        # drive the ladder to rung 3 by hand (deterministic sim clock)
        for _ in range(8):
            lad.observe(1.0)
            clk.advance(1.0)
        assert lad.rung == 3
        # rung 2 effect: max_tokens capped at 5 on the wire
        st, _hd, data = _post_raw(httpd.port, "/v1/generate",
                                  {"prompt": [1, 2, 3],
                                   "max_tokens": 30})
        assert st == 200
        assert stub.gen_bodies[-1]["max_tokens"] == 5
        assert len(json.loads(data)["tokens"]) == 5
        assert ctl.token_caps_applied_total >= 1
        # rung 3 effect: background generate shed 429 despite free limit
        st, hd, data = _post_raw(httpd.port, "/v1/generate",
                                 {"prompt": [1], "max_tokens": 3,
                                  "priority": "background"})
        assert st == 429 and "Retry-After" in hd
        assert ctl.shed_reasons["brownout"] == 1
        # rung 1 effect: hedged infer suppressed (hedges_total stays 0)
        stub.infer_delay_s = 0.3
        st, _hd, _ = _post_raw(httpd.port, "/v1/infer", {"feed": {}})
        assert st == 200
        assert router.metrics.snapshot()["hedges_total"] == 0
        assert ctl.hedges_suppressed_total >= 1
        # walk the ladder back down: full service returns
        for _ in range(5):
            lad.observe(0.01)
            clk.advance(1.0)
        assert lad.rung == 0
        st, _hd, data = _post_raw(httpd.port, "/v1/generate",
                                  {"prompt": [1, 2], "max_tokens": 8,
                                   "priority": "background"})
        assert st == 200 and len(json.loads(data)["tokens"]) == 8
        assert lad.exits["shed_background"] == 1
    finally:
        router.close()
        stub.close()


def test_autoscaler_metrics_on_router_page():
    """The autoscaler's autoscaler_* lines land on the ROUTER's /metrics
    page through extra_render_fns."""
    clk = SimClock()
    stub = _Stub()
    router = Router(replicas=[stub.url], poll_interval_s=0.05, hedge_ms=0,
                    clock=clk)
    sup = StubSupervisor(1)
    a = make_scaler(sup, router, clk)
    try:
        feed_ttft_ms = router.metrics.observe_ttft
        feed_ttft_ms(0.01)
        a.tick()
        text = router.render_prometheus()
        assert "autoscaler_replicas 1" in text
        assert 'autoscaler_decisions_total{direction="hold"} 1' in text
        assert "autoscaler_ttft_p99_ms" in text
    finally:
        router.close()
        stub.close()


# ----------------------------- drain-then-death mid-stream (small fix #2)


@pytest.fixture(scope="module")
def lm_replica():
    """One real in-process generation replica (the failover target)."""
    import jax
    import numpy as np       # noqa: F401 — used by the test below
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (DecodeEngine, GenerationBatcher,
                                    make_server)
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=64,
                              trg_vocab=1, d_model=32, num_heads=2,
                              dff=64, enc_layers=2, dec_layers=0,
                              max_len=48)
    engine = DecodeEngine(params, num_heads=2, num_slots=4, max_len=48,
                          prefill_buckets=(8, 16), name="autoscale_lm")
    gen = GenerationBatcher(engine)
    httpd = make_server(None, port=0, gen_batcher=gen)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield params, httpd
    httpd.shutdown()
    gen.close()


def test_drained_replica_dies_midstream_failover_bit_identical(lm_replica):
    """Small-fix satellite, part 2: a replica being DRAINED for scale-in
    (unready, mid-stream still attached) that dies before its drain
    completes must not break the stream — the router's continuation
    failover finishes it bit-identical to lm_generate."""
    import numpy as np
    from paddle_tpu.models import transformer
    params, httpd_real = lm_replica
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, 64, 6).astype(np.int32)
    ids = np.asarray(transformer.lm_generate(
        params, prompt[None], max_len=48, num_heads=2,
        prompt_lengths=np.asarray([prompt.size])))
    oracle = ids[0, prompt.size:prompt.size + 10].tolist()
    # the victim: starts ready (the stream lands on it), flips UNREADY
    # at drain start, then dies after 4 tokens — drain-then-death
    victim = _Stub(gen_tokens=oracle, die_after=4)
    router = Router(replicas=[victim.url,
                              f"http://127.0.0.1:{httpd_real.port}"],
                    poll_interval_s=0.05, retry_budget=2, hedge_ms=0)
    httpd = router.start(port=0)
    try:
        assert _wait(router.ready, 10)
        got = {}

        def stream():
            import http.client
            conn = http.client.HTTPConnection("127.0.0.1", httpd.port,
                                              timeout=60)
            conn.request("POST", "/v1/generate",
                         json.dumps({"prompt": prompt.tolist(),
                                     "max_tokens": 10,
                                     "stream": True}).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            toks, done = [], None
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if "token" in rec:
                    toks.append(rec["token"])
                    if len(toks) == 1:
                        # the drain begins while the stream is live:
                        # the victim drops out of readiness (exactly
                        # what a SIGTERM'd replica's /readyz does)
                        victim.ready = False
                if rec.get("done"):
                    done = rec
                    break
            conn.close()
            got["toks"], got["done"] = toks, done

        t = threading.Thread(target=stream)
        t.start()
        t.join(60)
        assert not t.is_alive(), "stream wedged"
        # ... and then it died before the drain finished (die_after=4):
        # the stream must still have completed bit-identically
        assert got["toks"] == oracle, (got["toks"], oracle)
        assert got["done"] is not None and got["done"]["tokens"] == oracle
        snap = router.metrics.snapshot()
        assert snap["midstream_failovers_total"] == 1
        # and the router recorded a fleet-level TTFT sample for the SLO
        assert router.metrics.ttft.count >= 1
    finally:
        router.close()
        victim.close()


# ------------------------------------------------------------- slow lane


@pytest.mark.slow
def test_autoscale_smoke_real_subprocess_drive(tmp_path):
    """The real-2-subprocess scale-out drive: `--smoke` spawns 1 demo
    replica + router + autoscaler, breaches the TTFT target with a
    seeded spike, scales out to 2 to readiness, recovers under target,
    scales back in — zero failed requests, every completed stream
    bit-identical to lm_generate."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", str(tmp_path / "xla"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.serving.autoscaler", "--smoke"],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["value"] == int(out["unit"].split("/")[1]), out
    assert out["scaled_out"] is True and out["scaled_in"] is True
    assert out["failed"] == 0 and out["completed"] > 0
    assert out["recovered_under_target"] is True
    assert out["decisions_out"] >= 1 and out["decisions_in"] >= 1


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
