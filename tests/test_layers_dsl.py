"""Layer-DSL graph tests: build small topologies, check size inference,
init/apply shapes, autodiff flow, train/test mode behavior (the reference's
config-parser + LayerGrad test roles, SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.layers.graph import Topology, reset_names


def setup_function(_):
    reset_names()


def test_fc_net_shapes_and_grad(rng, np_rng):
    x = L.data_layer("x", size=8)
    h = L.fc_layer(x, size=16, act="relu")
    y = L.fc_layer(h, size=4, act="softmax")
    lab = L.data_layer("lab", size=1)
    cost = L.classification_cost(y, lab)
    topo = Topology(cost)
    params = topo.init(rng)
    assert params[h.name]["w0"].shape == (8, 16)
    assert params[y.name]["w0"].shape == (16, 4)

    feed = {"x": jnp.asarray(np_rng.randn(5, 8), jnp.float32),
            "lab": jnp.asarray(np_rng.randint(0, 4, (5,)))}

    def loss(p):
        return jnp.mean(topo.apply(p, feed, mode="test"))

    val, grads = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(val))
    gnorm = sum(float(jnp.sum(jnp.abs(g)))
                for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


def test_mixed_layer_projections(rng, np_rng):
    a = L.data_layer("a", size=6)
    b = L.data_layer("b", size=6)
    m = L.mixed_layer(size=6, input=[
        L.identity_projection(a),
        L.dotmul_projection(b),
    ], act=None)
    topo = Topology(m)
    params = topo.init(rng)
    fa = np_rng.randn(3, 6).astype(np.float32)
    fb = np_rng.randn(3, 6).astype(np.float32)
    out = topo.apply(params, {"a": jnp.asarray(fa), "b": jnp.asarray(fb)})
    # dotmul weight initializes to ones -> out = a + b
    np.testing.assert_allclose(np.asarray(out), fa + fb, rtol=1e-5)


def test_embedding_and_seq_pool(rng, np_rng):
    w = L.data_layer("w", size=50, is_seq=True)
    emb = L.embedding_layer(w, size=12)
    pooled = L.pooling_layer(emb, pooling_type=L.pooling.Max)
    topo = Topology(pooled)
    params = topo.init(rng)
    seqs = [np_rng.randint(0, 50, (l,)) for l in (3, 7)]
    out = topo.apply(params, {"w": pad_sequences(seqs)})
    assert out.shape == (2, 12)


def test_conv_pool_shapes(rng, np_rng):
    img = L.data_layer("img", size=1 * 28 * 28, height=28, width=28)
    conv = L.img_conv_layer(img, filter_size=5, num_filters=4, num_channels=1,
                            act="relu")
    assert conv.img_shape == (24, 24)
    pool = L.img_pool_layer(conv, pool_size=2, stride=2)
    assert pool.img_shape == (12, 12)  # (24-2+2-1)//2+1, MathUtils.cpp:75
    topo = Topology(pool)
    params = topo.init(rng)
    out = topo.apply(params, {"img": jnp.asarray(
        np_rng.randn(2, 784), jnp.float32)})
    assert out.shape == (2, 4 * 12 * 12)


def test_batch_norm_train_updates_state(rng, np_rng):
    x = L.data_layer("x", size=6)
    bn = L.batch_norm_layer(L.fc_layer(x, size=6, act=None), act="relu")
    topo = Topology(bn)
    params = topo.init(rng)
    state = topo.init_state()
    feed = {"x": jnp.asarray(np_rng.randn(8, 6), jnp.float32)}
    out, new_state = topo.apply(params, feed, mode="train", state=state,
                                return_state=True)
    assert bn.name in new_state
    # moving mean must have moved
    assert float(jnp.sum(jnp.abs(new_state[bn.name][0]))) > 0
    # test mode uses provided stats, returns no update
    out2, st2 = topo.apply(params, feed, mode="test", state=state,
                           return_state=True)
    assert bn.name not in st2


def test_dropout_train_vs_test(rng, np_rng):
    x = L.data_layer("x", size=100)
    d = L.dropout_layer(x, dropout_rate=0.5)
    topo = Topology(d)
    params = topo.init(rng)
    feed = {"x": jnp.ones((4, 100))}
    out_test = topo.apply(params, feed, mode="test")
    np.testing.assert_allclose(np.asarray(out_test), 1.0)
    out_train = topo.apply(params, feed, mode="train", rng=rng)
    frac_zero = float(jnp.mean(out_train == 0))
    assert 0.3 < frac_zero < 0.7


def test_lstmemory_via_dsl(rng, np_rng):
    w = L.data_layer("w", size=20, is_seq=True)
    emb = L.embedding_layer(w, size=8)
    mix = L.fc_layer(emb, size=16, act=None, bias_attr=False)
    lstm = L.lstmemory(mix, size=4)
    last = L.last_seq(lstm)
    topo = Topology(last)
    params = topo.init(rng)
    seqs = [np_rng.randint(0, 20, (l,)) for l in (5, 2)]
    out = topo.apply(params, {"w": pad_sequences(seqs)})
    assert out.shape == (2, 4)
    assert np.all(np.isfinite(np.asarray(out)))


def test_recurrent_group_matches_grumemory(rng, np_rng):
    """DSL recurrent_group with gru_step must equal grumemory (the
    reference's test_RecurrentGradientMachine equivalence discipline)."""
    w = L.data_layer("w", size=30, is_seq=True)
    emb = L.embedding_layer(w, size=6, param_attr={"initial_std": 0.1})
    mix = L.fc_layer(emb, size=12, act=None, bias_attr=False,
                     param_attr={"initial_std": 0.1}, name="mix")
    whole = L.grumemory(mix, size=4, name="gru_whole")

    def step(x3):
        mem = L.memory(name="gru_out", size=4)
        return L.gru_step_layer(x3, mem, size=4, name="gru_out")

    grouped = L.recurrent_group(step, input=mix)
    topo = Topology([whole, grouped])
    params = topo.init(rng)
    # share weights: copy whole-seq params into the group's step params
    # (step-layer params live at top level under their own keys)
    gp = params["gru_out"]
    wp = params["gru_whole"]
    gp["w_gate"] = wp["w_gate"]
    gp["w_state"] = wp["w_state"]
    gp["b"] = wp["b"]

    seqs = [np_rng.randint(0, 30, (l,)) for l in (6, 3)]
    out_whole, out_group = topo.apply(params, {"w": pad_sequences(seqs)})
    np.testing.assert_allclose(np.asarray(out_whole.data),
                               np.asarray(out_group.data), rtol=1e-4,
                               atol=1e-5)


def test_cost_layers_all_finite(rng, np_rng):
    x = L.data_layer("x", size=5)
    lab_id = L.data_layer("lab", size=1)
    lab_vec = L.data_layer("labv", size=5)
    pred = L.fc_layer(x, size=5, act="softmax")
    costs = [
        L.classification_cost(pred, lab_id),
        L.regression_cost(pred, lab_vec),
        L.multi_binary_label_cross_entropy(L.fc_layer(x, size=5, act=None),
                                           lab_vec),
        L.smooth_l1_cost(pred, lab_vec),
        L.sum_cost(pred),
    ]
    topo = Topology(costs)
    params = topo.init(rng)
    feed = {"x": jnp.asarray(np_rng.randn(4, 5), jnp.float32),
            "lab": jnp.asarray(np_rng.randint(0, 5, (4,))),
            "labv": jnp.asarray(np.abs(np_rng.randn(4, 5)).astype(np.float32))}
    outs = topo.apply(params, feed)
    for o in outs:
        assert np.all(np.isfinite(np.asarray(o)))


def test_param_sharing_via_param_name(rng, np_rng):
    """crf_layer + crf_decoding_layer share weights by param_name."""
    em = L.data_layer("em", size=3, is_seq=True)
    lab = L.data_layer("lab", size=1, is_seq=True)
    cost = L.crf_layer(em, lab, size=3, name="mycrf")
    decode = L.crf_decoding_layer(em, size=3,
                                  param_name=cost.cfg["param_name"])
    topo = Topology([cost, decode])
    params = topo.init(rng)
    assert cost.cfg["param_name"] in params
    seqs = [np_rng.randn(4, 3).astype(np.float32)]
    labs = [np_rng.randint(0, 3, (4, 1))]
    out_cost, out_dec = topo.apply(
        params, {"em": pad_sequences(seqs), "lab": pad_sequences(labs)})
    assert np.all(np.isfinite(np.asarray(out_cost)))
    assert out_dec.data.shape == (1, 4, 1)
