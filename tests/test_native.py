"""Native data-path runtime tests (C++ dataio: packing, record IO, prefetch
pool).  Pure host-side — no JAX needed.

The .so binaries are NOT committed (gitignored); `native.build.ensure`
rebuilds them on demand the first time the module is touched, which the
cold-build test below proves from a binary-less state."""

import os
import shutil
import struct
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from paddle_tpu import native


_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_ROOT, "paddle_tpu", "native")

# applied per-test (NOT module-wide): the two gate tests below must run
# even where the lib can't build — a host without g++ is exactly where a
# committed stale .so would otherwise slip through
needs_lib = pytest.mark.skipif(
    not native.is_available(),
    reason="native lib not built (python -m paddle_tpu.native.build)")


def test_no_binaries_committed():
    """The shared libraries are build artifacts: gitignored, rebuilt on
    demand — a committed .so would go stale against its source silently."""
    r = subprocess.run(["git", "ls-files", "--", "*.so"], cwd=_ROOT,
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("not a git checkout")
    assert r.stdout.strip() == "", (
        f"committed binaries found: {r.stdout} — git rm them; "
        "native/build.py builds on demand")


def test_analysis_baseline_committed_and_parseable():
    """The static-analyzer allow-list rides the same git gate: the
    committed baseline must exist IN git (not just on disk — an
    untracked baseline silently vanishes for the next clone, turning
    every documented exception into a red gate) and must parse under
    the strict loader (every entry keyed + justified)."""
    rel = os.path.join("paddle_tpu", "analysis", "baseline.json")
    r = subprocess.run(["git", "ls-files", "--", rel], cwd=_ROOT,
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("not a git checkout")
    assert r.stdout.strip() == rel, (
        f"{rel} is not committed — the analyzer gate needs its "
        "allow-list in git")
    from paddle_tpu.analysis import baseline
    entries = baseline.load(os.path.join(_ROOT, rel))
    for key, reason in entries.items():
        assert reason.strip(), f"baseline entry {key} has no reason"


@pytest.mark.slow   # full g++ rebuild in a subprocess; nightly lane
def test_cold_build_from_binaryless_checkout(tmp_path):
    """A clean checkout has no .so: the first native touch must build it
    (build.ensure).  Proven cold — the binary is moved aside and a fresh
    interpreter has to rebuild it before packing works.  (The fast lane
    still exercises the on-demand build implicitly: importing
    paddle_tpu.native on a fresh checkout runs build.ensure.)"""
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    so = os.path.join(_NATIVE_DIR, "libpaddle_tpu_dataio.so")
    backup = None
    if os.path.exists(so):
        backup = str(tmp_path / "dataio.so.bak")
        shutil.move(so, backup)
    code = ("import numpy as np\n"
            "from paddle_tpu import native\n"
            "assert native.is_available()\n"
            "out, lens = native.pack_i32([np.arange(3, dtype=np.int32)])\n"
            "assert out.shape == (1, 3) and lens[0] == 3\n"
            "print('COLD_BUILD_OK')\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], cwd=_ROOT,
                           capture_output=True, text=True, timeout=300)
        assert "COLD_BUILD_OK" in r.stdout, r.stdout + r.stderr
        assert os.path.exists(so), "ensure() did not rebuild the .so"
    finally:
        if backup and not os.path.exists(so):
            shutil.move(backup, so)


@needs_lib
def test_pack_i32_matches_numpy(np_rng):
    seqs = [np_rng.randint(0, 100, (l,)).astype(np.int32) for l in (4, 1, 7)]
    out, lens = native.pack_i32(seqs, pad=-7)
    assert out.shape == (3, 7)
    for i, s in enumerate(seqs):
        np.testing.assert_array_equal(out[i, :len(s)], s)
        assert np.all(out[i, len(s):] == -7)
    np.testing.assert_array_equal(lens, [4, 1, 7])


@needs_lib
def test_pack_i32_truncates():
    out, lens = native.pack_i32([np.arange(10, dtype=np.int32)], max_len=4)
    np.testing.assert_array_equal(out[0], [0, 1, 2, 3])
    assert lens[0] == 4


@needs_lib
def test_pack_f32(np_rng):
    seqs = [np_rng.randn(l, 3).astype(np.float32) for l in (2, 5)]
    out, lens = native.pack_f32(seqs)
    assert out.shape == (2, 5, 3)
    np.testing.assert_allclose(out[0, :2], seqs[0])
    assert np.all(out[0, 2:] == 0)


@needs_lib
def test_densify_sparse():
    d = native.densify_sparse([0, 0, 2], [1, 3, 0], None, 3, 4)
    assert d[0, 1] == 1.0 and d[0, 3] == 1.0 and d[2, 0] == 1.0
    assert d.sum() == 3.0
    with pytest.raises(RuntimeError):
        native.densify_sparse([5], [0], None, 3, 4)  # row out of range


@needs_lib
def test_record_roundtrip():
    p = os.path.join(tempfile.mkdtemp(), "x.ptrc")
    payloads = [struct.pack("<3i", i, i * 2, i * 3) for i in range(20)]
    with native.RecordWriter(p) as w:
        for pl in payloads:
            w.put(pl)
    with native.RecordReader(p) as r:
        got = list(r)
    assert got == payloads


@needs_lib
def test_record_reader_rejects_garbage():
    p = os.path.join(tempfile.mkdtemp(), "bad.ptrc")
    with open(p, "wb") as f:
        f.write(b"NOTAMAGIC")
    with pytest.raises(IOError):
        native.RecordReader(p)


@needs_lib
def test_prefetch_queue_streams_all():
    d = tempfile.mkdtemp()
    paths = []
    for fi in range(3):
        p = os.path.join(d, f"f{fi}.ptrc")
        with native.RecordWriter(p) as w:
            for i in range(10):
                w.put(bytes([fi, i]))
        paths.append(p)
    q = native.PrefetchQueue(4)
    for p in paths:
        q.add_file(p)
    got = []
    while True:
        item = q.pop(500)
        if item is None:
            break
        got.append(item)
    q.close()
    assert len(got) == 30
    assert sorted(got) == sorted(bytes([fi, i])
                                 for fi in range(3) for i in range(10))


@needs_lib
def test_prefetch_queue_timeout_empty():
    q = native.PrefetchQueue(4)
    assert q.pop(50) is None
    q.close()
