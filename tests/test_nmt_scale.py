"""scripts/nmt_scale.py: the reference-scale NMT harness (verbatim
train.conf + gen.conf) runs end-to-end at toy scale on CPU."""

import json
import os
import subprocess
import sys

import pytest

# subprocess end-to-end NMT harness run; nightly lane
pytestmark = pytest.mark.slow

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REF = os.environ.get("PADDLE_TPU_REFERENCE", "/root/reference")


@pytest.mark.skipif(
    not os.path.exists(f"{_REF}/demo/seqToseq/translation/train.conf"),
    reason="reference checkout not present")
def test_nmt_scale_end_to_end(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.scripts.nmt_scale",
         "--out-dir", str(tmp_path), "--vocab", "120", "--steps", "4",
         "--gen-sents", "2", "--beam", "5", "--max-gen-len", "12"],
        cwd=_ROOT, env=env, timeout=420, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["vocab"] == 120
    assert out["batch_size"] == 50          # train.conf's own setting
    assert out["beam_size"] == 5
    assert out["train_ms_per_batch"] > 0
    assert out["first_cost"] > 0 and out["last_cost"] > 0
    golden = out["golden_file"]
    assert os.path.exists(golden)
    text = open(golden).read()
    assert text.count("src:") == 2
    assert "beam4" in text
