"""CPU-vs-TPU differential comparison (SURVEY §4 pattern 1; reference
math/tests/test_matrixCompare.cpp runs every op on CpuMatrix+GpuMatrix and
compares within epsilon).

Two-process protocol (the suite pins jax to the virtual CPU mesh, and a
platform cannot be re-pinned after backend init):

    python -m paddle_tpu.testing.tpu_diff cpu     /tmp/diff_cpu.npz
    python -m paddle_tpu.testing.tpu_diff default /tmp/diff_tpu.npz  # on TPU
    PADDLE_TPU_DIFF="/tmp/diff_cpu.npz:/tmp/diff_tpu.npz" pytest \
        tests/test_tpu_differential.py

Skipped unless PADDLE_TPU_DIFF points at the two dumps — the dumps need a
real chip, which CI boxes don't have.
"""

import functools
import os

import numpy as np
import pytest

_SPEC = os.environ.get("PADDLE_TPU_DIFF", "")
_PATHS = _SPEC.split(":")
_READY = len(_PATHS) == 2 and all(os.path.exists(p) for p in _PATHS)


@functools.lru_cache(maxsize=1)
def _load():
    cpu_path, tpu_path = _PATHS
    return np.load(cpu_path), np.load(tpu_path)


pytestmark = pytest.mark.skipif(
    not _READY,
    reason="PADDLE_TPU_DIFF=cpu.npz:tpu.npz not set (needs a TPU dump)")


def _cases():
    if not _READY:
        return []
    cpu, _ = _load()
    return sorted({k.split("::")[0] for k in cpu.files
                   if not k.startswith("__")})


def test_same_code_revision():
    """Both dumps must come from the same code state — a resumed cache
    from an older revision would diff two different programs."""
    cpu, tpu = _load()
    revs = []
    for z in (cpu, tpu):
        revs.append(bytes(z["__revision__"]).decode()
                    if "__revision__" in z.files else "<unstamped>")
    for r in revs:
        assert r not in ("unknown", "<unstamped>"), (
            f"dump revision unverifiable ({revs}) — regenerate with git "
            "available so provenance can be checked")
    assert revs[0] == revs[1], (
        f"dump revision mismatch: cpu={revs[0]} tpu={revs[1]} — "
        "regenerate both dumps at the current revision")


@pytest.mark.parametrize("case", _cases())
def test_case_matches(case):
    cpu, tpu = _load()
    cpu_keys = {k for k in cpu.files if k.startswith(case + "::")}
    tpu_keys = {k for k in tpu.files if k.startswith(case + "::")}
    assert cpu_keys == tpu_keys, (cpu_keys ^ tpu_keys)
    for k in sorted(cpu_keys):
        if k.endswith("__error__"):
            msg_c = bytes(cpu[k]).decode()
            msg_t = bytes(tpu[k]).decode()
            # a timeout means the case was never numerically compared —
            # that must FAIL, not hide behind the same-error exemption
            assert not msg_c.startswith("TimeoutExpired"), (k, msg_c)
            assert not msg_t.startswith("TimeoutExpired"), (k, msg_t)
            # an identical in-case failure on both platforms is a sweep
            # harness limitation, not a numerics divergence — surface it
            print(f"{k}: {msg_c[:120]}")
            assert msg_c[:80] == msg_t[:80]
            continue
        a, b = cpu[k], tpu[k]
        assert a.shape == b.shape, k
        scale = max(np.abs(a).max(), 1.0)
        # HIGHEST matmul precision on the MXU: f32-comparable; transcendental
        # op tables differ slightly between backends
        np.testing.assert_allclose(
            b, a, rtol=5e-3, atol=5e-4 * scale,
            err_msg=f"{k}: CPU and TPU disagree")
