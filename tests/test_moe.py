"""MoE FFN + expert parallelism (ops/moe.py, the 'expert' mesh axis)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.ops import moe


def _params(d=8, f=16, e=4, seed=0):
    return moe.init_moe(jax.random.PRNGKey(seed), d, f, e)


def test_gates_topk_renormalized():
    p = _params()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 8), jnp.float32)
    probs = moe.router_probs(x, p["wg"])
    g = np.asarray(moe.moe_gates(probs, top_k=2))
    assert ((g > 0).sum(-1) == 2).all()
    np.testing.assert_allclose(g.sum(-1), 1.0, rtol=1e-5)
    # top_k >= E degrades to plain softmax
    g_all = np.asarray(moe.moe_gates(probs, top_k=4))
    assert (g_all > 0).all()


def test_gates_exactly_topk_on_ties():
    # uniform router: every prob tied — the index mask must STILL keep
    # exactly top_k experts
    probs = jnp.full((3, 7, 4), 0.25, jnp.float32)
    g = np.asarray(moe.moe_gates(probs, top_k=2))
    assert ((g > 0).sum(-1) == 2).all()
    np.testing.assert_allclose(g.sum(-1), 1.0, rtol=1e-5)


def test_moe_ffn_matches_per_expert_loop():
    """The batched-einsum formulation == explicit per-expert computation."""
    p = _params()
    x = jnp.asarray(np.random.RandomState(1).randn(2, 6, 8), jnp.float32)
    out = np.asarray(moe.moe_ffn(x, p, top_k=2))

    gates = np.asarray(moe.moe_gates(moe.router_probs(x, p["wg"]), top_k=2))
    ref = np.zeros_like(out)
    for e in range(4):
        h = jax.nn.gelu(x @ p["w1"][e])
        ye = np.asarray(h @ p["w2"][e])
        ref += ye * gates[..., e:e + 1]
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_aux_loss_uniform_is_one():
    """Perfectly uniform router -> aux loss == 1 (its minimum), at any
    top_k now that tied probs keep exactly top_k experts."""
    d, e = 8, 4
    wg = jnp.zeros((d, e), jnp.float32)    # uniform probs everywhere
    x = jnp.asarray(np.random.RandomState(2).randn(2, 10, d), jnp.float32)
    probs = moe.router_probs(x, wg)
    for k in (1, 2, e):
        gates = moe.moe_gates(probs, k)
        val = float(moe.aux_load_balance_loss(probs, gates, k))
        assert val == pytest.approx(1.0, rel=1e-5), k


def test_expert_parallel_matches_single_device():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs the 8-device virtual CPU mesh")
    p = _params(e=4)
    x = jnp.asarray(np.random.RandomState(3).randn(4, 6, 8), jnp.float32)
    single = np.asarray(moe.moe_ffn(x, p, top_k=2))

    mesh = Mesh(np.asarray(devs[:4]).reshape(2, 2), ("data", "expert"))
    psh = moe.expert_shardings(mesh)
    xsh = NamedSharding(mesh, P("data", None, None))
    f = jax.jit(lambda p, x: moe.moe_ffn(x, p, top_k=2),
                in_shardings=(psh, xsh), out_shardings=xsh)
    with mesh:
        sharded = np.asarray(f(jax.device_put(p, psh),
                               jax.device_put(x, xsh)))
    np.testing.assert_allclose(single, sharded, rtol=2e-5, atol=2e-5)


def test_moe_trains():
    p = _params()
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(8, 6, 8), jnp.float32)
    y = jnp.asarray(rng.randn(8, 6, 8) * 0.1, jnp.float32)

    @jax.jit
    def step(p):
        def loss_fn(p):
            out, aux = moe.moe_ffn(x, p, top_k=2, return_aux=True)
            return jnp.mean((out - y) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda w, gw: w - 0.2 * gw, p, g), l

    losses = []
    for _ in range(40):
        p, l = step(p)
        losses.append(float(l))
    assert losses[-1] < 0.6 * losses[0]


def test_moe_layer_dsl():
    """moe_layer in the graph DSL: dense and sequence inputs, output size
    preserved, trains through the SGD trainer."""
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import Topology
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu import optim
    from paddle_tpu.trainer.trainer import SGD

    x = L.data_layer("x", size=8)
    y = L.data_layer("y", size=1)
    m = L.moe_layer(x, n_experts=4, top_k=2, expert_dim=16, name="moe1")
    out = L.fc_layer(input=m, size=1, act="sigmoid")
    from paddle_tpu.layers.api import mse_cost
    tr = SGD(cost=mse_cost(input=out, label=y),
             update_equation=optim.Adam(learning_rate=0.01))
    assert set(tr.parameters["moe1"]) == {"wg", "w1", "w2"}
    rng = np.random.RandomState(0)

    def batch():
        xb = rng.randn(32, 8).astype(np.float32)
        yb = (xb[:, :3].sum(1, keepdims=True) > 0).astype(np.float32)
        return {"x": jnp.asarray(xb), "y": jnp.asarray(yb)}

    costs = []
    tr.train(lambda: iter([batch() for _ in range(25)]), num_passes=1,
             event_handler=lambda e: costs.append(float(e.cost))
             if hasattr(e, "cost") else None)
    assert costs[-1] < 0.6 * costs[0]

    # sequence input keeps lengths
    s = L.data_layer("s", size=8, is_seq=True)
    mseq = L.moe_layer(s, n_experts=2, top_k=1, expert_dim=8)
    topo = Topology([mseq])
    params = topo.init(jax.random.PRNGKey(0))
    sb = SequenceBatch(
        data=jnp.asarray(np.random.RandomState(1).randn(2, 5, 8),
                         jnp.float32),
        lengths=jnp.asarray([5, 3], jnp.int32))
    o = topo.apply(params, {"s": sb}, mode="test")
    assert o.data.shape == (2, 5, 8)
    assert (np.asarray(o.lengths) == [5, 3]).all()


def test_moe_layer_nested_and_multi_input():
    from paddle_tpu.layers import api as L
    from paddle_tpu.layers.graph import Topology
    from paddle_tpu.core.sequence import NestedSequenceBatch
    from paddle_tpu.utils.error import ConfigError

    # nested sequences flow through (4-d data flattened internally)
    s = L.data_layer("ns", size=8, is_seq=True)
    m = L.moe_layer(s, n_experts=2, top_k=1, expert_dim=8)
    topo = Topology([m])
    params = topo.init(jax.random.PRNGKey(0))
    nb = NestedSequenceBatch(
        data=jnp.asarray(np.random.RandomState(0).randn(2, 3, 4, 8),
                         jnp.float32),
        outer_lengths=jnp.asarray([3, 2], jnp.int32),
        inner_lengths=jnp.asarray([[4, 2, 1], [3, 4, 0]], jnp.int32))
    o = topo.apply(params, {"ns": nb}, mode="test")
    assert o.data.shape == (2, 3, 4, 8)

    # multi-input is a config error at construction time
    a = L.data_layer("a", size=8)
    b = L.data_layer("b", size=8)
    with pytest.raises(ConfigError, match="single input"):
        L.moe_layer([a, b], n_experts=2)
