"""Sparse/large-vocab embedding path (VERDICT r1 item 4; reference
SparseRowMatrix.h:204 + RemoteParameterUpdater.h:265 sparse push/pull).

- unique/gather/scatter primitives honor the static row budget
- sparse_update=True training matches the dense path exactly (plain SGD)
  and under momentum when every row is touched every batch
- step time scales with touched rows, not vocab (the capability the dense
  path can't provide)
- the sparse step compiles and runs on a device mesh"""

import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu import optim
from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.layers.graph import reset_names
from paddle_tpu.ops import sparse as sparse_ops
from paddle_tpu.trainer import SGD


def test_unique_touched_budget_and_inverse():
    ids = jnp.asarray([[3, 7, 3], [9, 7, 0]], jnp.int32)
    uids, inv = sparse_ops.unique_touched(ids, budget=8, vocab=100)
    assert uids.shape == (8,)
    # fill slots carry the out-of-range sentinel
    assert int((uids == 100).sum()) == 4
    table = jnp.arange(100 * 2, dtype=jnp.float32).reshape(100, 2)
    rows = sparse_ops.gather_rows(table, uids)
    np.testing.assert_array_equal(np.asarray(rows[inv]),
                                  np.asarray(table[ids]))


def test_scatter_rows_drops_fill_slots():
    table = jnp.zeros((10, 3))
    uids = jnp.asarray([2, 5, 10, 10], jnp.int32)   # two fill slots (== V)
    new_rows = jnp.ones((4, 3))
    out = sparse_ops.scatter_rows(table, uids, new_rows)
    touched = np.zeros((10,), bool)
    touched[[2, 5]] = True
    np.testing.assert_array_equal(np.asarray(out[touched]), 1.0)
    np.testing.assert_array_equal(np.asarray(out[~touched]), 0.0)


def _build_model(vocab, sparse, budget=None, emb_dim=8):
    reset_names()
    w = L.data_layer("w", size=vocab, is_seq=True)
    emb = L.embedding_layer(w, size=emb_dim, sparse_update=sparse,
                            sparse_budget=budget,
                            param_attr={"initial_std": 0.1, "name": "emb"})
    pooled = L.pooling_layer(emb, pooling_type="sum")
    out = L.fc_layer(pooled, size=2, act="softmax",
                     param_attr={"initial_std": 0.1})
    lab = L.data_layer("lab", size=1)
    return L.classification_cost(input=out, label=lab)


def _batches(np_rng, vocab, n=3, b=6, t=5):
    out = []
    for _ in range(n):
        seqs = [np_rng.randint(0, vocab, (np_rng.randint(2, t + 1),))
                for _ in range(b)]
        out.append({"w": pad_sequences(seqs, max_len=t),
                    "lab": np_rng.randint(0, 2, (b, 1)).astype(np.int32)})
    return out


def _train(cost, opt, batches):
    tr = SGD(cost=cost, update_equation=opt, seed=3, donate=False)
    tr.train(lambda: iter(batches), num_passes=2, log_period=0)
    return tr


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad"])
def test_sparse_matches_dense(np_rng, opt_name):
    """Touched-rows-only updates == dense updates for history-free rules
    (plain SGD) and row-local accumulators (adagrad): untouched rows have
    zero grad, so the dense path leaves them unchanged too."""
    vocab = 50
    batches = _batches(np_rng, vocab)

    def make_opt():
        return (optim.Momentum(learning_rate=0.1, momentum=0.0)
                if opt_name == "sgd"
                else optim.AdaGrad(learning_rate=0.1))

    dense = _train(_build_model(vocab, sparse=False), make_opt(), batches)
    sparse = _train(_build_model(vocab, sparse=True), make_opt(), batches)
    for key in ("emb", "__fc_0__"):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            dense.parameters[key], sparse.parameters[key])


def test_sparse_momentum_matches_dense_when_all_rows_touched(np_rng):
    """With momentum, sparse == dense only when every row is touched every
    batch (otherwise dense momentum keeps decaying untouched rows — the
    reference's catch-up problem); construct batches covering the vocab."""
    vocab = 8
    batches = []
    for _ in range(3):
        perm = np_rng.permutation(vocab)
        seqs = [perm[:4], perm[4:]]
        batches.append({"w": pad_sequences(seqs),
                        "lab": np.asarray([[0], [1]], np.int32)})
    dense = _train(_build_model(vocab, sparse=False),
                   optim.Momentum(learning_rate=0.1, momentum=0.9), batches)
    sparse = _train(_build_model(vocab, sparse=True),
                    optim.Momentum(learning_rate=0.1, momentum=0.9), batches)
    np.testing.assert_allclose(np.asarray(dense.parameters["emb"]["w"]),
                               np.asarray(sparse.parameters["emb"]["w"]),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_sparse_step_scales_with_touched_rows_not_vocab(np_rng):
    """The capability test: at vocab 1M the sparse step beats the dense
    step by a wide margin because it never materializes a [V, D] gradient
    or updates [V, D] momentum (reference sparse-update raison d'etre).
    The margin asserted is intentionally far below the observed ~10x so a
    noisy CI host can't flip it."""
    vocab = 1_000_000
    batches = _batches(np_rng, vocab, n=1, b=8, t=8)

    def steps_per_sec(sparse):
        # donate=True (the default) so the touched-row scatter runs in
        # place; without donation XLA must copy the [V, D] table each step
        tr = SGD(cost=_build_model(vocab, sparse=sparse, emb_dim=32),
                 update_equation=optim.Momentum(learning_rate=0.1,
                                                momentum=0.9),
                 seed=3)
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)  # compile
        t0 = time.perf_counter()
        tr.train(lambda: iter(batches * 20), num_passes=1, log_period=0)
        return 20 / (time.perf_counter() - t0)

    sparse_rate = steps_per_sec(True)
    dense_rate = steps_per_sec(False)
    assert sparse_rate > 1.3 * dense_rate, (
        f"sparse {sparse_rate:.1f} steps/s vs dense {dense_rate:.1f}")


def test_sparse_step_on_mesh(np_rng):
    """Sparse gather/update/scatter compiles and runs under a data-parallel
    mesh (per-shard state: slots inherit the table's sharding)."""
    from paddle_tpu.parallel import MeshConfig, make_mesh
    vocab = 64
    mesh = make_mesh(MeshConfig(data=len(jax.devices())))
    batches = _batches(np_rng, vocab, n=2, b=8, t=4)
    tr = SGD(cost=_build_model(vocab, sparse=True),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9),
             seed=3, mesh=mesh, donate=False)
    tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert np.isfinite(np.asarray(tr.parameters["emb"]["w"])).all()


def test_sparse_clip_norm_matches_dense(np_rng):
    """Global-norm clipping must compute ONE norm across the split grad
    tree (dense params + gathered rows) — with per-partition norms the
    sparse path would train differently whenever clipping engages."""
    vocab = 8
    batches = []
    for _ in range(3):
        perm = np_rng.permutation(vocab)
        batches.append({"w": pad_sequences([perm[:4], perm[4:]]),
                        "lab": np.asarray([[0], [1]], np.int32)})

    def make_opt():
        # clip_norm small enough that it engages on every step
        return optim.Momentum(learning_rate=0.5, momentum=0.0,
                              clip_norm=0.01)

    dense = _train(_build_model(vocab, sparse=False), make_opt(), batches)
    sparse = _train(_build_model(vocab, sparse=True), make_opt(), batches)
    np.testing.assert_allclose(np.asarray(dense.parameters["emb"]["w"]),
                               np.asarray(sparse.parameters["emb"]["w"]),
                               rtol=1e-5, atol=1e-7)


def test_sparse_budget_grows_with_batch_shape(np_rng):
    """A later, larger batch must get a larger auto budget (jit retrace),
    not a silent jnp.unique truncation at the first batch's budget."""
    vocab = 64
    small = _batches(np_rng, vocab, n=1, b=2, t=2)
    # large batch touching > default_row_budget(2*2) distinct ids
    seqs = [np.arange(16) + 16 * i for i in range(3)]
    big = [{"w": pad_sequences(seqs),
            "lab": np.zeros((3, 1), np.int32)}]

    tr = SGD(cost=_build_model(vocab, sparse=True),
             update_equation=optim.Momentum(learning_rate=1.0, momentum=0.0),
             seed=3, donate=False)
    before = np.asarray(tr.parameters["emb"]["w"]).copy()
    tr.train(lambda: iter(small + big), num_passes=1, log_period=0)
    after = np.asarray(tr.parameters["emb"]["w"])
    # every one of the 48 distinct ids in the big batch must have updated
    changed = np.any(before[:48] != after[:48], axis=-1)
    assert changed.all(), f"only {changed.sum()}/48 touched rows updated"


def test_sparse_table_shared_with_dense_layer_rejected():
    """params[key] becomes the gathered row block inside sparse_step; any
    non-sparse layer sharing that key must be rejected at config time."""
    from paddle_tpu.utils.error import ConfigError
    reset_names()
    vocab = 16
    w = L.data_layer("w", size=vocab, is_seq=True)
    w2 = L.data_layer("w2", size=vocab, is_seq=True)
    emb = L.embedding_layer(w, size=4, sparse_update=True,
                            param_attr={"name": "shared_emb"})
    emb2 = L.embedding_layer(w2, size=4, sparse_update=False,
                             param_attr={"name": "shared_emb"})
    pooled = L.addto_layer([L.pooling_layer(emb, pooling_type="sum"),
                            L.pooling_layer(emb2, pooling_type="sum")])
    lab = L.data_layer("lab", size=1)
    cost = L.classification_cost(
        input=L.fc_layer(pooled, size=2, act="softmax"), label=lab)
    with pytest.raises(ConfigError, match="shared"):
        SGD(cost=cost, update_equation=optim.Momentum(learning_rate=0.1),
            seed=0)
