"""RNN differential tests: scanned whole-sequence LSTM/GRU vs per-step numpy
reference loops implementing the reference formulas (hl_lstm_ops.cuh:60-66,
hl_gru_ops.cuh:42-80), including padding-invariance (reference semantics are
padding-free, so results must not depend on pad length)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch, pad_sequences
from paddle_tpu.ops import rnn


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_lstm_ref(x4, w_r, ci, cf, co):
    """x4: [T, 4D] -> outputs [T, D] per the reference gate equations."""
    t, d4 = x4.shape
    d = d4 // 4
    h = np.zeros(d, np.float32)
    c = np.zeros(d, np.float32)
    outs = []
    for step in range(t):
        g = x4[step] + h @ w_r
        a, ig, fg, og = g[:d], g[d:2*d], g[2*d:3*d], g[3*d:]
        a = np.tanh(a)
        i = sigmoid(ig + c * ci)
        f = sigmoid(fg + c * cf)
        c = a * i + c * f
        o = sigmoid(og + c * co)
        h = o * np.tanh(c)
        outs.append(h.copy())
    return np.stack(outs), h, c


def np_gru_ref(x3, wg, ws):
    t, d3 = x3.shape
    d = d3 // 3
    h = np.zeros(d, np.float32)
    outs = []
    for step in range(t):
        xu, xr, xc = x3[step][:d], x3[step][d:2*d], x3[step][2*d:]
        ru = h @ wg
        u = sigmoid(xu + ru[:d])
        r = sigmoid(xr + ru[d:])
        c = np.tanh(xc + (r * h) @ ws)
        h = h - u * h + u * c
        outs.append(h.copy())
    return np.stack(outs), h


def test_lstm_matches_reference_loop(np_rng):
    d = 5
    lens = (4, 7, 1)
    seqs = [np_rng.randn(l, 4 * d).astype(np.float32) * 0.5 for l in lens]
    w_r = (np_rng.randn(d, 4 * d) * 0.3).astype(np.float32)
    ci, cf, co = [(np_rng.randn(d) * 0.2).astype(np.float32) for _ in range(3)]

    sb = pad_sequences(seqs)
    out, final = rnn.lstm(sb, jnp.asarray(w_r), check_i=jnp.asarray(ci),
                          check_f=jnp.asarray(cf), check_o=jnp.asarray(co))
    for i, s in enumerate(seqs):
        ref, href, cref = np_lstm_ref(s, w_r, ci, cf, co)
        np.testing.assert_allclose(np.asarray(out.data[i, :len(s)]), ref,
                                   rtol=2e-2, atol=2e-3)
        # final state must be the state at the last VALID step
        np.testing.assert_allclose(np.asarray(final.h[i]), href, rtol=2e-2, atol=2e-3)
        np.testing.assert_allclose(np.asarray(final.c[i]), cref, rtol=2e-2, atol=2e-3)


def test_lstm_padding_invariance(np_rng):
    d = 4
    seqs = [np_rng.randn(3, 4 * d).astype(np.float32)]
    w_r = (np_rng.randn(d, 4 * d) * 0.3).astype(np.float32)
    out_a, fin_a = rnn.lstm(pad_sequences(seqs, max_len=3), jnp.asarray(w_r))
    out_b, fin_b = rnn.lstm(pad_sequences(seqs, max_len=10), jnp.asarray(w_r))
    np.testing.assert_allclose(np.asarray(out_a.data[0, :3]),
                               np.asarray(out_b.data[0, :3]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fin_a.h), np.asarray(fin_b.h), rtol=1e-6)


def test_gru_matches_reference_loop(np_rng):
    d = 6
    lens = (5, 2)
    seqs = [np_rng.randn(l, 3 * d).astype(np.float32) * 0.5 for l in lens]
    wg = (np_rng.randn(d, 2 * d) * 0.3).astype(np.float32)
    ws = (np_rng.randn(d, d) * 0.3).astype(np.float32)
    out, final = rnn.gru(pad_sequences(seqs), jnp.asarray(wg), jnp.asarray(ws))
    for i, s in enumerate(seqs):
        ref, href = np_gru_ref(s, wg, ws)
        np.testing.assert_allclose(np.asarray(out.data[i, :len(s)]), ref,
                                   rtol=2e-2, atol=2e-3)
        np.testing.assert_allclose(np.asarray(final[i]), href, rtol=2e-2, atol=2e-3)


def test_reverse_lstm(np_rng):
    d = 3
    seqs = [np_rng.randn(4, 4 * d).astype(np.float32)]
    w_r = (np_rng.randn(d, 4 * d) * 0.3).astype(np.float32)
    # reverse pass on seq == forward pass on reversed seq, output re-reversed
    out_r, _ = rnn.lstm(pad_sequences(seqs), jnp.asarray(w_r), reverse=True)
    out_f, _ = rnn.lstm(pad_sequences([seqs[0][::-1]]), jnp.asarray(w_r))
    np.testing.assert_allclose(np.asarray(out_r.data[0]),
                               np.asarray(out_f.data[0])[::-1], rtol=1e-5, atol=1e-6)


def test_recurrent_group_generic(np_rng):
    """recurrent_group with a custom step must equal simple_rnn."""
    d = 4
    lens = (3, 6)
    seqs = [np_rng.randn(l, d).astype(np.float32) for l in lens]
    w_r = (np_rng.randn(d, d) * 0.3).astype(np.float32)
    sb = pad_sequences(seqs)

    out_ref, fin_ref = rnn.simple_rnn(sb, jnp.asarray(w_r))

    def step(mem, x):
        h = rnn.simple_rnn_cell(x, mem, jnp.asarray(w_r))
        return h, h

    out_g, fin_g = rnn.recurrent_group(step, sb, jnp.zeros((2, d)))
    np.testing.assert_allclose(np.asarray(out_g.data), np.asarray(out_ref.data),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(fin_g), np.asarray(fin_ref), rtol=1e-5,
                               atol=1e-6)


def test_lstm_grad_flows(np_rng):
    d = 3
    seqs = [np_rng.randn(4, 4 * d).astype(np.float32)]
    sb = pad_sequences(seqs)

    def loss(w_r):
        out, _ = rnn.lstm(sb, w_r)
        return jnp.sum(out.data ** 2)

    g = jax.grad(loss)(jnp.asarray((np_rng.randn(d, 4 * d) * 0.3).astype(np.float32)))
    assert np.all(np.isfinite(np.asarray(g)))
    assert float(jnp.sum(jnp.abs(g))) > 0


def test_prev_batch_state_carries_across_batches(np_rng):
    """Reference --prev_batch_state (Flags.cpp:73): the RNN's final state
    boots the next batch.  Split one long sequence into two halves; running
    them as consecutive 'batches' with the carry must equal one unbroken
    run."""
    import paddle_tpu.layers as L
    from paddle_tpu.layers.graph import Topology, reset_names, value_data
    from paddle_tpu.core.sequence import SequenceBatch

    reset_names()
    x = L.data_layer("x", size=12, is_seq=True)
    out = L.lstmemory(x, size=3, prev_batch_state=True)
    topo = Topology([out])
    params = topo.init(jax.random.PRNGKey(0))

    full = jnp.asarray(np_rng.randn(2, 8, 12), jnp.float32)
    seq_full = SequenceBatch(full, jnp.full((2,), 8, jnp.int32))
    half = lambda lo, hi: SequenceBatch(   # noqa: E731
        full[:, lo:hi], jnp.full((2,), hi - lo, jnp.int32))

    ref = value_data(topo.apply(params, {"x": seq_full}, mode="test"))

    o1, st = topo.apply(params, {"x": half(0, 4)}, mode="test",
                        return_state=True)
    o2, _ = topo.apply(params, {"x": half(4, 8)}, mode="test", state=st,
                       return_state=True)
    got = jnp.concatenate([value_data(o1), value_data(o2)], axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)

    # without the carry the halves diverge from the unbroken run
    reset_names()
    x2 = L.data_layer("x", size=12, is_seq=True)
    topo2 = Topology([L.lstmemory(x2, size=3)])
    o1n = value_data(topo2.apply(params_rename(params), {"x": half(0, 4)},
                                 mode="test"))
    o2n = value_data(topo2.apply(params_rename(params), {"x": half(4, 8)},
                                 mode="test"))
    got_n = np.concatenate([np.asarray(o1n), np.asarray(o2n)], axis=1)
    assert not np.allclose(got_n, np.asarray(ref), rtol=1e-4, atol=1e-5)


def params_rename(params):
    """Both topologies auto-name their lstm '__lstmemory_0__' after
    reset_names, so params transfer as-is."""
    return params
