"""Round-2 verdict compat tail: sentiment dataset, dump_config, image_util,
and the reference binary proto data format."""

import io
import os

import numpy as np
import pytest

from paddle_tpu.utils.error import ConfigError


# ------------------------------------------------------------- sentiment

def test_sentiment_synthetic_reader():
    from paddle_tpu.data.datasets import sentiment
    it = sentiment.train()
    first = next(it)
    words, label = first
    assert isinstance(words, list) and words
    assert label in (0, 1)
    train = list(sentiment.train())
    test = list(sentiment.test())
    assert len(train) + 1 == sentiment.NUM_TRAINING_INSTANCES \
        or len(train) == sentiment.NUM_TRAINING_INSTANCES
    assert len(train) + len(test) == sentiment.NUM_TOTAL_INSTANCES
    # interleaved neg/pos for balanced batches
    assert {train[0][1], train[1][1]} == {0, 1}


def test_sentiment_word_dict_freq_sorted():
    from paddle_tpu.data.datasets import sentiment
    wd = sentiment.get_word_dict()
    assert wd[0][1] == 0 and wd[1][1] == 1
    ids = dict(wd)
    assert len(ids) == len(wd)


def test_sentiment_real_corpus_layout(tmp_path, monkeypatch):
    d = tmp_path / "corpora" / "movie_reviews"
    for cat, texts in [("neg", ["terrible awful film", "bad bad plot"]),
                       ("pos", ["great wonderful film", "good fine plot"])]:
        (d / cat).mkdir(parents=True)
        for i, t in enumerate(texts):
            (d / cat / f"cv{i}.txt").write_text(t)
    monkeypatch.setenv("PADDLE_TPU_DATA_DIR", str(tmp_path))
    from paddle_tpu.data.datasets import sentiment
    data = sentiment.load_sentiment_data()
    assert len(data) == 4
    labels = [l for _, l in data]
    assert labels == [0, 1, 0, 1]           # interleaved
    ids = dict(sentiment.get_word_dict())
    assert "film" in ids and "bad" in ids


# ----------------------------------------------------------- dump_config

def test_dump_config_prints_layers(tmp_path, capsys):
    conf = tmp_path / "conf.py"
    conf.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=32, learning_rate=0.1)\n"
        "d = data_layer(name='x', size=8)\n"
        "h = fc_layer(input=d, size=16, act=TanhActivation())\n"
        "outputs(fc_layer(input=h, size=4, act=SoftmaxActivation()))\n")
    from paddle_tpu.utils.tools import dump_config
    dump_config.main([str(conf)])
    out = capsys.readouterr().out
    assert 'name: "x"' in out and 'type: "data"' in out
    assert "size: 8" in out
    assert 'input_layer_names: "x"' in out
    assert out.count("layers {") == 3
    dump_config.main([str(conf), "", "--whole"])
    whole = capsys.readouterr().out
    assert "batch_size" in whole and "layers {" in whole


# ------------------------------------------------------------ image_util

def test_image_util_crop_and_flip():
    from paddle_tpu.utils.tools import image_util as iu
    im = np.arange(3 * 8 * 8, dtype=np.float32).reshape(3, 8, 8)
    center = iu.crop_img(im, 4, color=True, test=True)
    assert center.shape == (3, 4, 4)
    np.testing.assert_array_equal(center, im[:, 2:6, 2:6])
    gray = iu.crop_img(im[0], 4, color=False, test=True)
    assert gray.shape == (4, 4)
    # undersized image gets zero-padded
    small = iu.crop_img(im[:, :2, :2], 4, color=True, test=True)
    assert small.shape == (3, 4, 4)
    np.testing.assert_array_equal(iu.flip(im), im[:, :, ::-1])


def test_image_util_preprocess_and_transformer():
    from paddle_tpu.utils.tools import image_util as iu
    im = np.random.RandomState(0).rand(3, 10, 10).astype(np.float32)
    mean = np.zeros((3, 6, 6), np.float32)
    flat = iu.preprocess_img(im, mean, 6, is_train=False)
    assert flat.shape == (3 * 6 * 6,)
    tr = iu.ImageTransformer(transpose=(2, 0, 1), channel_swap=(2, 1, 0),
                             mean=np.asarray([1.0, 2.0, 3.0]))
    hwc = np.random.RandomState(1).rand(6, 6, 3).astype(np.float32)
    out = tr.transformer(hwc)
    assert out.shape == (3, 6, 6)
    np.testing.assert_allclose(
        out[0], hwc[:, :, 2] - 1.0, rtol=1e-6)


def test_image_util_oversample_and_jpeg():
    from paddle_tpu.utils.tools import image_util as iu
    from PIL import Image
    imgs = [np.random.RandomState(2).rand(8, 8, 3).astype(np.float32)]
    crops = iu.oversample(imgs, (4, 4))
    assert crops.shape == (10, 4, 4, 3)
    # mirrors: second five are flips of first five
    np.testing.assert_array_equal(crops[5], crops[0][:, ::-1, :])
    buf = io.BytesIO()
    Image.fromarray((imgs[0] * 255).astype(np.uint8)).save(buf, "JPEG")
    arr = iu.decode_jpeg(buf.getvalue())
    assert arr.shape == (3, 8, 8)


def test_image_util_load_meta(tmp_path):
    from paddle_tpu.utils.tools import image_util as iu
    mean = np.arange(3 * 6 * 6, dtype=np.float32)
    path = str(tmp_path / "meta.npz")
    np.savez(path, data_mean=mean)
    m = iu.load_meta(path, 6, 4, color=True)
    assert m.shape == (3, 4, 4)


# ----------------------------------------------------- proto data format

def _sample_slot_defs():
    from paddle_tpu.data import proto_format as pf
    return [(pf.VECTOR_DENSE, 4), (pf.VECTOR_SPARSE_NON_VALUE, 100),
            (pf.VECTOR_SPARSE_VALUE, 50), (pf.STRING, 0), (pf.INDEX, 10)]


def _sample_rows():
    return [
        ((np.asarray([1.0, 2.0, 3.5, -1.0], np.float32), [3, 7, 99],
          ([1, 4], [0.5, 2.5]), "hello", 7), True),
        ((np.asarray([0.0, 0.5, 0.25, 8.0], np.float32), [], ([], []),
          "world", 2), False),
    ]


@pytest.mark.parametrize("suffix", ["bin", "gz"])
def test_proto_format_round_trip(tmp_path, suffix):
    from paddle_tpu.data import proto_format as pf
    path = str(tmp_path / f"data.{suffix}")
    pf.write_proto_data(path, _sample_slot_defs(), _sample_rows())
    f = pf.ProtoDataFile(path)
    assert f.slot_defs == _sample_slot_defs()
    rows = list(f)
    assert len(rows) == 2
    (dense, sp, spv, s, idx), beg = rows[0]
    np.testing.assert_allclose(dense, [1.0, 2.0, 3.5, -1.0])
    assert sp == [3, 7, 99]
    assert spv[0] == [1, 4]
    np.testing.assert_allclose(spv[1], [0.5, 2.5])
    assert s == "hello" and idx == 7 and beg is True
    (_, sp2, _, s2, idx2), beg2 = rows[1]
    assert sp2 == [] and s2 == "world" and idx2 == 2 and beg2 is False


def test_proto_format_reader_creator(tmp_path):
    from paddle_tpu.data import proto_format as pf
    path = str(tmp_path / "data.bin")
    pf.write_proto_data(path, _sample_slot_defs(), _sample_rows())
    rows = list(pf.reader_creator(path)())
    assert len(rows) == 2 and rows[0][4] == 7


def test_proto_format_var_mdim(tmp_path):
    from paddle_tpu.data import proto_format as pf
    defs = [(pf.VAR_MDIM_DENSE, 0), (pf.VAR_MDIM_INDEX, 1000)]
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    path = str(tmp_path / "md.bin")
    pf.write_proto_data(path, defs, [((arr, [5, 500, 999]), True)])
    (got, ids), _ = next(iter(pf.ProtoDataFile(path)))
    np.testing.assert_array_equal(got, arr)
    assert ids == [5, 500, 999]


def test_proto_format_truncated_errors(tmp_path):
    from paddle_tpu.data import proto_format as pf
    path = str(tmp_path / "data.bin")
    pf.write_proto_data(path, _sample_slot_defs(), _sample_rows())
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-5])
    with pytest.raises(ConfigError, match="truncated"):
        list(pf.ProtoDataFile(path))
    with open(path, "wb") as f:
        f.write(b"")
    with pytest.raises(ConfigError, match="empty"):
        pf.ProtoDataFile(path)


# -------------------------------------------------------------- show_pb

def test_show_pb_dumps_wire_format(tmp_path, capsys):
    from paddle_tpu.data import proto_format as pf
    from paddle_tpu.utils.tools import show_pb
    path = str(tmp_path / "data.bin")
    pf.write_proto_data(path, [(pf.VECTOR_DENSE, 2), (pf.INDEX, 5)],
                        [((np.asarray([1.5, -2.0], np.float32), 3), True)])
    # strip the varint framing: dump the header message itself
    with open(path, "rb") as f:
        raw = f.read()
    size, pos = pf._read_varint(raw, 0)
    lines = show_pb.format_pb(raw[pos:pos + size])
    text = "\n".join(lines)
    assert "1 {" in text            # slot_defs submessage
    assert "2: 2" in text           # dim field
    show_pb.main([path])
    assert capsys.readouterr().out   # full-file dump prints something
