"""Pallas kernel tests (interpret mode on CPU — the dual-backend
differential discipline of SURVEY.md §4: kernel vs XLA reference)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import dot_product_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(np_rng, b=1, h=2, t=128, d=32, dtype=jnp.float32):
    return (jnp.asarray(np_rng.randn(b, h, t, d), dtype),
            jnp.asarray(np_rng.randn(b, h, t, d), dtype),
            jnp.asarray(np_rng.randn(b, h, t, d), dtype))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference_fwd(np_rng, causal):
    q, k, v = _qkv(np_rng)
    ref = dot_product_attention(q, k, v, causal=causal, use_flash=False)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference_grads(np_rng, causal):
    q, k, v = _qkv(np_rng)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(
            q, k, v, causal=causal, use_flash=False) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=causal, interpret=True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        scale = max(1e-9, float(jnp.max(jnp.abs(a))))
        np.testing.assert_allclose(np.asarray(b) / scale,
                                   np.asarray(a) / scale,
                                   rtol=5e-4, atol=5e-5)


def test_flash_multiblock_kv_loop(np_rng):
    """T > block forces the in-kernel kv loop (multiple blocks each way)."""
    q, k, v = _qkv(np_rng, t=256, d=16)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_fallback_on_ragged_shapes(np_rng):
    """Non-block-multiple T falls back to the XLA path (still correct)."""
    q, k, v = _qkv(np_rng, t=100)
    ref = dot_product_attention(q, k, v, use_flash=False)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bf16_inputs(np_rng):
    q, k, v = _qkv(np_rng, dtype=jnp.bfloat16)
    ref = dot_product_attention(q.astype(jnp.float32),
                                k.astype(jnp.float32),
                                v.astype(jnp.float32), use_flash=False)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)
