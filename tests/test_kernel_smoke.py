"""The bench.py --smoke-kernels cases run (interpret mode) on CPU.

The same CASES dict is what runs through a real Mosaic compile on TPU; this
test keeps the harness itself honest (oracle wiring, fresh-trace dispatch,
tolerances) so an on-chip failure can only mean a lowering/numerics problem.
"""

import pytest

from paddle_tpu.testing import kernel_smoke


@pytest.mark.parametrize("name", sorted(kernel_smoke.CASES))
def test_kernel_smoke_case(name):
    err = kernel_smoke.CASES[name]()
    assert err == err  # not NaN
    assert err < 0.05
