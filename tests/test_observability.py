"""Observability floor (VERDICT r1 item 8): jax.profiler wiring, debug_nans
flag, valid_spec replication warnings, per-pass step-time percentiles.

Reference: utils/Stat.h:70-241 (REGISTER_TIMER/globalStat dumps),
utils/BarrierStat.h:196 (worker-skew profiling), TrainerMain.cpp:49
(feenableexcept: NaN -> crash)."""

import logging
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp


@pytest.mark.slow   # multi-second end-to-end; nightly lane
def test_profiler_trace_writes_files(tmp_path):
    from paddle_tpu.utils import profiler
    d = str(tmp_path / "xprof")
    with profiler.trace(d):
        with profiler.annotate("matmul_region"):
            x = jnp.ones((64, 64))
            (x @ x).block_until_ready()
    assert not profiler.is_tracing()
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "no trace files written"


@pytest.mark.slow
def test_profiler_start_idempotent(tmp_path):
    from paddle_tpu.utils import profiler
    d = str(tmp_path / "xprof2")
    profiler.start(d)
    profiler.start(d)   # warns, doesn't raise
    profiler.stop()
    profiler.stop()     # no-op


def test_flags_apply_debug_nans():
    from paddle_tpu.utils.flags import Flags
    f = Flags(debug_nans=True, dtype="float32", compute_dtype="auto")
    try:
        f.apply()
        with pytest.raises((FloatingPointError, Exception)) as ei:
            jax.jit(lambda x: jnp.log(x))(jnp.zeros(())).block_until_ready()
            # log(0) = -inf is fine; 0/0 produces the NaN
            jax.jit(lambda x: x / x)(jnp.zeros(())).block_until_ready()
        assert "nan" in str(ei.value).lower()
    finally:
        jax.config.update("jax_debug_nans", False)


def test_flags_surface_covers_reference_names():
    """Every reference gflag name resolves: either a field, a renamed field,
    or an entry in the SUBSUMED lookup table."""
    from paddle_tpu.utils import flags as F
    import dataclasses
    fields = {f.name for f in dataclasses.fields(F.Flags)}
    renames = {"use_gpu": "use_tpu", "trainer_id": "process_id",
               "num_gradient_servers": "num_processes",
               "trainer_count": "data_parallel"}
    reference_flags = [
        "use_gpu", "trainer_count", "port", "ports_num", "nics", "rdma_tcp",
        "trainer_id", "num_gradient_servers", "comment", "log_period",
        "checkgrad_eps", "beam_size", "predict_file", "init_model_path",
        "job", "config", "config_args", "save_dir", "saving_period",
        "saving_period_by_batches", "num_passes", "start_pass", "test_pass",
        "test_period", "average_test_period", "save_only_one", "seed",
        "load_missing_parameter_strategy", "show_parameter_stats_period",
        "show_layer_stat", "prev_batch_state", "with_cost", "dot_period",
        "predict_output_dir", "parallel_nn", "start_pserver", "local",
        "distribute_test", "test_wait", "enable_parallel_vector",
        "loadsave_parameters_in_pserver", "log_period_server",
        "ports_num_for_sparse", "test_all_data_in_one_period",
    ]
    missing = []
    for name in reference_flags:
        if name in fields or renames.get(name) in fields:
            continue
        if any(name in k for k in F.SUBSUMED):
            continue
        missing.append(name)
    assert not missing, f"unaccounted reference flags: {missing}"


@pytest.fixture
def propagating_logger():
    """paddle_tpu's logger sets propagate=False (own stderr handler);
    caplog needs propagation to see records."""
    from paddle_tpu.utils.logging import logger as plogger
    plogger.propagate = True
    yield
    plogger.propagate = False


def test_valid_spec_warns_on_big_replication_fallback(caplog,
                                                      propagating_logger):
    from paddle_tpu.parallel import MeshConfig, make_mesh, valid_spec
    from jax.sharding import PartitionSpec as P
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(MeshConfig(data=4, model=2))
    with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
        # big embedding with an odd vocab: fallback must warn
        spec = valid_spec(P("model", None), (100001, 512), mesh,
                          path="emb/w")
        assert spec == P()
        assert any("REPLICATED" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
        # tiny param: silent fallback (no warning spam)
        spec = valid_spec(P("model"), (7,), mesh)
        assert spec == P()
        assert not caplog.records


def test_pass_end_step_histogram(caplog, propagating_logger):
    """trainer.train logs p50/p90/p99 step times at each pass end and
    resets the histogram."""
    import paddle_tpu.layers as L
    from paddle_tpu import optim
    from paddle_tpu.layers.graph import reset_names
    from paddle_tpu.trainer import SGD
    from paddle_tpu.utils.stats import step_histogram

    reset_names()
    x = L.data_layer("x", size=4)
    lab = L.data_layer("lab", size=1)
    cost = L.classification_cost(
        input=L.fc_layer(x, size=2, act="softmax"), label=lab)
    r = np.random.RandomState(0)
    batches = [{"x": r.randn(4, 4).astype(np.float32),
                "lab": r.randint(0, 2, (4, 1)).astype(np.int32)}
               for _ in range(3)]
    tr = SGD(cost=cost, update_equation=optim.Momentum(learning_rate=0.1),
             seed=0)
    with caplog.at_level(logging.INFO, logger="paddle_tpu"):
        tr.train(lambda: iter(batches), num_passes=1, log_period=0)
    assert any("p99" in rec.message or "p99" in rec.getMessage()
               for rec in caplog.records)
    assert not step_histogram.samples  # reset after the pass
