"""Ring attention vs dense attention equivalence on the 8-device CPU mesh
(SURVEY.md §4 pattern (3): sharded must match single-device)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import dot_product_attention
from paddle_tpu.parallel import MeshConfig, make_mesh
from paddle_tpu.parallel.ring_attention import ring_attention, ulysses_attention

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _qkv(np_rng, b=2, h=4, t=16, d=8):
    return (jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32),
            jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32),
            jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32))


@needs_8
def test_ring_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    dense = dot_product_attention(q, k, v)
    ring = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_causal_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    dense = dot_product_attention(q, k, v, causal=True)
    ring = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_with_padding_mask(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    kv_mask = jnp.asarray(
        (np.arange(16)[None, :] < np.asarray([12, 9])[:, None]), jnp.float32)
    mask4 = (kv_mask[:, None, None, :] > 0)
    dense = dot_product_attention(q, k, v, mask=mask4)
    ring = ring_attention(q, k, v, mesh, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ulysses_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng, h=8)
    dense = dot_product_attention(q, k, v, causal=True)
    uly = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_transformer_seq_parallel_training_matches_single(np_rng):
    """The full transformer train step with mesh seq=4: every attention
    (enc self, dec causal self, cross) rides the ppermute ring, loss AND
    grads match the single-device model (SURVEY.md §4 pattern (3))."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer

    mesh = make_mesh(MeshConfig(data=2, seq=4, model=1))
    V, D, H, T, B = 64, 16, 2, 16, 4
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=V, d_model=D, dff=32,
                              enc_layers=2, dec_layers=2, max_len=T)
    ids = np_rng.randint(3, V, (3, B, T)).astype(np.int32)
    lens = np_rng.randint(T // 2, T + 1, (3, B)).astype(np.int32)
    mk = lambda i: SequenceBatch(jnp.asarray(ids[i]), jnp.asarray(lens[i]))
    src, trg_in, trg_next = mk(0), mk(1), mk(2)

    def loss_single(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=H)

    def loss_sp(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=H,
                                mesh=mesh)

    l1, g1 = jax.value_and_grad(loss_single)(params)

    # shard the feeds: batch over data, T over seq; params replicated
    bsh = NamedSharding(mesh, P("data", "seq"))
    shard_seq = lambda s: SequenceBatch(
        jax.device_put(s.data, bsh),
        jax.device_put(s.lengths, NamedSharding(mesh, P("data"))))
    src, trg_in, trg_next = (shard_seq(src), shard_seq(trg_in),
                             shard_seq(trg_next))
    l2, g2 = jax.jit(jax.value_and_grad(loss_sp))(params)

    np.testing.assert_allclose(float(l2), float(l1), rtol=2e-4)
    flat1 = jax.tree_util.tree_leaves(g1)
    flat2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(flat2, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-5)
