"""Ring attention vs dense attention equivalence on the 8-device CPU mesh
(SURVEY.md §4 pattern (3): sharded must match single-device)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import dot_product_attention
from paddle_tpu.parallel import MeshConfig, make_mesh
from paddle_tpu.parallel.ring_attention import ring_attention, ulysses_attention

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _qkv(np_rng, b=2, h=4, t=16, d=8):
    return (jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32),
            jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32),
            jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32))


@needs_8
def test_ring_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    dense = dot_product_attention(q, k, v)
    ring = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_causal_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    dense = dot_product_attention(q, k, v, causal=True)
    ring = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_with_padding_mask(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    kv_mask = jnp.asarray(
        (np.arange(16)[None, :] < np.asarray([12, 9])[:, None]), jnp.float32)
    mask4 = (kv_mask[:, None, None, :] > 0)
    dense = dot_product_attention(q, k, v, mask=mask4)
    ring = ring_attention(q, k, v, mesh, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ulysses_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng, h=8)
    dense = dot_product_attention(q, k, v, causal=True)
    uly = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
@needs_8
def test_transformer_seq_parallel_training_matches_single(np_rng):
    """The full transformer train step with mesh seq=4: every attention
    (enc self, dec causal self, cross) rides the ppermute ring, loss AND
    grads match the single-device model (SURVEY.md §4 pattern (3))."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer

    mesh = make_mesh(MeshConfig(data=2, seq=4, model=1))
    V, D, H, T, B = 64, 16, 2, 16, 4
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=V, d_model=D, dff=32,
                              enc_layers=2, dec_layers=2, max_len=T)
    ids = np_rng.randint(3, V, (3, B, T)).astype(np.int32)
    lens = np_rng.randint(T // 2, T + 1, (3, B)).astype(np.int32)
    mk = lambda i: SequenceBatch(jnp.asarray(ids[i]), jnp.asarray(lens[i]))
    src, trg_in, trg_next = mk(0), mk(1), mk(2)

    def loss_single(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=H)

    def loss_sp(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=H,
                                mesh=mesh)

    l1, g1 = jax.value_and_grad(loss_single)(params)

    # shard the feeds: batch over data, T over seq; params replicated
    bsh = NamedSharding(mesh, P("data", "seq"))
    shard_seq = lambda s: SequenceBatch(
        jax.device_put(s.data, bsh),
        jax.device_put(s.lengths, NamedSharding(mesh, P("data"))))
    src, trg_in, trg_next = (shard_seq(src), shard_seq(trg_in),
                             shard_seq(trg_next))
    l2, g2 = jax.jit(jax.value_and_grad(loss_sp))(params)

    np.testing.assert_allclose(float(l2), float(l1), rtol=2e-4)
    flat1 = jax.tree_util.tree_leaves(g1)
    flat2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(flat2, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-5)


@pytest.mark.slow
@needs_8
@pytest.mark.parametrize("ragged", [False, True], ids=["full", "ragged"])
def test_zigzag_causal_matches_dense(np_rng, ragged):
    """Balanced causal ring: zigzag-permuted inputs through
    ring_attention_zigzag reproduce dense causal attention after
    unpermuting."""
    from paddle_tpu.parallel.ring_attention import (
        ring_attention_zigzag, zigzag_permute, zigzag_unpermute)
    n = 4
    mesh = make_mesh(MeshConfig(data=2, seq=n, model=1))
    b, h, t, d = 2, 3, 32, 8
    q, k, v = _qkv(np_rng, b=b, h=h, t=t, d=d)
    km = None
    mask2d = None
    if ragged:
        lens = np_rng.randint(t // 2, t + 1, (b,))
        km = jnp.asarray(np.arange(t)[None, :] < lens[:, None], jnp.float32)
        mask2d = km[:, None, None, :] > 0
    dense = dot_product_attention(q, k, v, causal=True, mask=mask2d,
                                  use_flash=False)

    zp = lambda x: zigzag_permute(x, n)
    kmz = zigzag_permute(km, n, axis=1) if km is not None else None
    out_z = ring_attention_zigzag(zp(q), zp(k), zp(v), mesh, kv_mask=kmz,
                                  q_mask=kmz)
    got = zigzag_unpermute(out_z, n)
    if km is not None:
        # padded query rows are zeroed, matching ring_attention; align
        # the dense reference before comparing
        dense = dense * (km[:, None, :, None] > 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
@needs_8
def test_zigzag_grads_match_dense(np_rng):
    from paddle_tpu.parallel.ring_attention import (
        ring_attention_zigzag, zigzag_permute, zigzag_unpermute)
    n = 4
    mesh = make_mesh(MeshConfig(data=2, seq=n, model=1))
    q, k, v = _qkv(np_rng, b=1, h=2, t=32, d=8)

    def loss_z(q, k, v):
        zp = lambda x: zigzag_permute(x, n)
        out = ring_attention_zigzag(zp(q), zp(k), zp(v), mesh)
        return jnp.sum(zigzag_unpermute(out, n) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True,
                                             use_flash=False) ** 2)

    gz = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b_, nme in zip(gz, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"grad d{nme}")


def test_zigzag_order_roundtrip():
    from paddle_tpu.parallel.ring_attention import (
        zigzag_order, zigzag_permute, zigzag_unpermute)
    import numpy as np
    order = zigzag_order(16, 2)
    assert sorted(order.tolist()) == list(range(16))
    # device 0 holds chunks 0 and 3; device 1 holds 1 and 2
    assert order.tolist()[:8] == [0, 1, 2, 3, 12, 13, 14, 15]
    x = jnp.arange(16.0)[None, None, :, None]
    np.testing.assert_array_equal(
        np.asarray(zigzag_unpermute(zigzag_permute(x, 2), 2)),
        np.asarray(x))
    with pytest.raises(ValueError, match="zigzag needs"):
        zigzag_order(10, 2)


@pytest.mark.slow
@needs_8
def test_transformer_zigzag_matches_plain_ring(np_rng):
    """zigzag=True (balanced causal self-attention + permuted labels)
    reproduces the plain seq-parallel mesh path: same loss, same grads."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer

    mesh = make_mesh(MeshConfig(data=2, seq=4, model=1))
    V, D, H, T, B = 64, 16, 2, 16, 4    # T % (2*seq) == 0
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=V, d_model=D, dff=32,
                              enc_layers=1, dec_layers=2, max_len=T)
    ids = np_rng.randint(3, V, (3, B, T)).astype(np.int32)
    lens = np_rng.randint(T // 2, T + 1, (3, B)).astype(np.int32)
    bsh = NamedSharding(mesh, P("data", "seq"))
    lsh = NamedSharding(mesh, P("data"))
    mk = lambda i: SequenceBatch(jax.device_put(jnp.asarray(ids[i]), bsh),
                                 jax.device_put(jnp.asarray(lens[i]), lsh))
    src, trg_in, trg_next = mk(0), mk(1), mk(2)

    def loss_plain(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=H,
                                mesh=mesh)

    def loss_zig(p):
        return transformer.loss(p, src, trg_in, trg_next, num_heads=H,
                                mesh=mesh, zigzag=True)

    l1, g1 = jax.jit(jax.value_and_grad(loss_plain))(params)
    l2, g2 = jax.jit(jax.value_and_grad(loss_zig))(params)
    np.testing.assert_allclose(float(l2), float(l1), rtol=2e-4)
    for a, b in zip(jax.tree_util.tree_leaves(g2),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-5)
    # guard: zigzag without a seq mesh is refused
    with pytest.raises(ValueError, match="seq > 1"):
        transformer.loss(params, src, trg_in, trg_next, num_heads=H,
                         zigzag=True)


# ---------------- packed segments x sequence parallelism ----------------

def _packed_qkv(np_rng, t=16, h=4, d=8, lens=(5, 3, 6, 7, 2, 4)):
    from paddle_tpu.core.sequence import pack_sequences
    seqs = [np_rng.randint(0, 9, n) for n in lens]
    _, seg, _ = pack_sequences(seqs, max_len=t)
    b = seg.shape[0]
    q, k, v = (jnp.asarray(np_rng.randn(b, h, t, d) * 0.5, jnp.float32)
               for _ in range(3))
    return q, k, v, jnp.asarray(seg)


@needs_8
@pytest.mark.parametrize("causal", [False, True], ids=["plain", "causal"])
def test_ring_segment_matches_dense(np_rng, causal):
    """ring_attention with rotating KV segment labels == dense attention
    with the materialized segment mask, at every real-token position."""
    from paddle_tpu.ops.attention import segment_mask
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v, seg = _packed_qkv(np_rng)
    got = ring_attention(q, k, v, mesh, causal=causal,
                         q_segment_ids=seg, q_mask=(seg > 0))
    want = dot_product_attention(q, k, v, mask=segment_mask(seg),
                                 causal=causal, use_flash=False)
    m = np.asarray(seg > 0)[:, None, :, None]
    np.testing.assert_allclose(np.asarray(got) * m, np.asarray(want) * m,
                               atol=2e-5)


@pytest.mark.slow
@needs_8
def test_zigzag_segment_matches_dense(np_rng):
    """Balanced causal ring with PACKED rows: zigzag-permuted tokens AND
    labels reproduce dense causal segment attention after unpermute."""
    from paddle_tpu.ops.attention import segment_mask
    from paddle_tpu.parallel.ring_attention import (
        ring_attention_zigzag, zigzag_permute, zigzag_unpermute)
    n = 8
    mesh = make_mesh(MeshConfig(data=1, seq=n, model=1))
    q, k, v, seg = _packed_qkv(np_rng, t=32, lens=(9, 3, 14, 7, 2, 11, 4))
    qp, kp, vp = (zigzag_permute(x, n) for x in (q, k, v))
    segp = zigzag_permute(seg, n, axis=1)
    got = ring_attention_zigzag(qp, kp, vp, mesh, q_segment_ids=segp,
                                q_mask=(segp > 0))
    got = zigzag_unpermute(got, n)
    want = dot_product_attention(q, k, v, mask=segment_mask(seg),
                                 causal=True, use_flash=False)
    m = np.asarray(seg > 0)[:, None, :, None]
    np.testing.assert_allclose(np.asarray(got) * m, np.asarray(want) * m,
                               atol=2e-5)


@needs_8
def test_transformer_encode_packed_seq_parallel(np_rng):
    """The marquee composition: transformer.encode on PACKED rows under a
    seq>1 mesh == the unsharded packed path (loss and grads)."""
    from paddle_tpu.core.sequence import SequenceBatch, pack_sequences
    from paddle_tpu.models import transformer

    mesh = make_mesh(MeshConfig(data=2, seq=4, model=1))
    V, DM, HEADS, T = 32, 16, 2, 16
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=V, d_model=DM, dff=32,
                              enc_layers=2, dec_layers=1, max_len=T)
    seqs = [np_rng.randint(3, V, n) for n in (5, 9, 7, 3, 12, 4, 6)]
    data, seg, pos = pack_sequences(seqs, max_len=T)
    b = data.shape[0]
    src = SequenceBatch(jnp.asarray(data), jnp.full((b,), T, jnp.int32))
    segj, posj = jnp.asarray(seg), jnp.asarray(pos)
    vmask = (seg > 0)[:, :, None]

    def enc_loss(p, mesh_arg):
        out = transformer.encode(p, src, num_heads=HEADS, mesh=mesh_arg,
                                 segment_ids=segj, positions=posj)
        return jnp.sum((out * vmask) ** 2)

    v1, g1 = jax.jit(jax.value_and_grad(
        lambda p: enc_loss(p, None)))(params)
    v2, g2 = jax.jit(jax.value_and_grad(
        lambda p: enc_loss(p, mesh)))(params)
    np.testing.assert_allclose(float(v2), float(v1), rtol=2e-4)
    for a, b_ in zip(jax.tree_util.tree_leaves(g2),
                     jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=1e-4)


# ------------------------------------------------- grouped KV (GQA ring)


@pytest.mark.slow
@needs_8
def test_ring_grouped_kv_matches_dense(np_rng):
    """Grouped K/V stripes ([B, Hkv, T/n, D]) travel the ppermute ring
    and expand per hop in registers — same numbers as repeating to full
    head width before dispatch, at H/Hkv less ring traffic."""
    from paddle_tpu.ops.attention import repeat_kv_heads
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, _, _ = _qkv(np_rng, h=4)
    kv_rng = np.random.RandomState(5)
    k = jnp.asarray(kv_rng.randn(2, 2, 16, 8), jnp.float32)
    v = jnp.asarray(kv_rng.randn(2, 2, 16, 8), jnp.float32)
    dense = dot_product_attention(q, repeat_kv_heads(k, 4),
                                  repeat_kv_heads(v, 4))
    ring = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)
    causal_dense = dot_product_attention(q, repeat_kv_heads(k, 4),
                                         repeat_kv_heads(v, 4),
                                         causal=True)
    causal_ring = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(causal_ring),
                               np.asarray(causal_dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_zigzag_grouped_kv_matches_dense(np_rng):
    """The balanced causal ring composes with grouped K/V: zigzag halves
    expand per hop too."""
    from paddle_tpu.ops.attention import repeat_kv_heads
    from paddle_tpu.parallel.ring_attention import (
        ring_attention_zigzag, zigzag_permute, zigzag_unpermute)
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, _, _ = _qkv(np_rng, h=4)
    kv_rng = np.random.RandomState(6)
    k = jnp.asarray(kv_rng.randn(2, 2, 16, 8), jnp.float32)
    v = jnp.asarray(kv_rng.randn(2, 2, 16, 8), jnp.float32)
    dense = dot_product_attention(q, repeat_kv_heads(k, 4),
                                  repeat_kv_heads(v, 4), causal=True)
    qz, kz, vz = (zigzag_permute(x, 8) for x in (q, k, v))
    got = zigzag_unpermute(ring_attention_zigzag(qz, kz, vz, mesh), 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_rejects_non_divisor_kv_heads(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, _, _ = _qkv(np_rng, h=4)
    bad = jnp.zeros((2, 3, 16, 8), jnp.float32)
    with pytest.raises(ValueError, match="not a multiple"):
        ring_attention(q, bad, bad, mesh)


@needs_8
def test_gqa_trunk_seq_parallel_matches_unsharded(np_rng):
    """multi_head_attention end to end: a GQA trunk under a seq>1 mesh
    (grouped stripes through the ring) == the unsharded GQA path."""
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer

    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    V, DM, T = 32, 16, 16
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=V,
                              trg_vocab=1, d_model=DM, dff=32,
                              enc_layers=2, dec_layers=0, max_len=T,
                              num_heads=4, num_kv_heads=2)
    toks = SequenceBatch(
        jnp.asarray(np_rng.randint(3, V, (2, T)), jnp.int32),
        jnp.full((2,), T, jnp.int32))

    def loss(p, mesh_arg):
        return jnp.sum(transformer.lm_logits(p, toks, 4,
                                             mesh=mesh_arg) ** 2)

    v1, g1 = jax.jit(jax.value_and_grad(
        lambda p: loss(p, None)))(params)
    v2, g2 = jax.jit(jax.value_and_grad(
        lambda p: loss(p, mesh)))(params)
    np.testing.assert_allclose(float(v2), float(v1), rtol=2e-4)
    for a, b_ in zip(jax.tree_util.tree_leaves(g2),
                     jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=1e-4)
