"""Ring attention vs dense attention equivalence on the 8-device CPU mesh
(SURVEY.md §4 pattern (3): sharded must match single-device)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import dot_product_attention
from paddle_tpu.parallel import MeshConfig, make_mesh
from paddle_tpu.parallel.ring_attention import ring_attention, ulysses_attention

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _qkv(np_rng, b=2, h=4, t=16, d=8):
    return (jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32),
            jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32),
            jnp.asarray(np_rng.randn(b, h, t, d), jnp.float32))


@needs_8
def test_ring_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    dense = dot_product_attention(q, k, v)
    ring = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_causal_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    dense = dot_product_attention(q, k, v, causal=True)
    ring = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ring_with_padding_mask(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng)
    kv_mask = jnp.asarray(
        (np.arange(16)[None, :] < np.asarray([12, 9])[:, None]), jnp.float32)
    mask4 = (kv_mask[:, None, None, :] > 0)
    dense = dot_product_attention(q, k, v, mask=mask4)
    ring = ring_attention(q, k, v, mesh, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


@needs_8
def test_ulysses_matches_dense(np_rng):
    mesh = make_mesh(MeshConfig(data=1, seq=8, model=1))
    q, k, v = _qkv(np_rng, h=8)
    dense = dot_product_attention(q, k, v, causal=True)
    uly = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)
