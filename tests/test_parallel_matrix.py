"""Multi-chip numerics matrix (SURVEY.md §4 patterns 3-4): sharded train
steps must be numerically equivalent to single-device, leaf-wise, across
model-parallel and data×model meshes, for both a seq model (seq2seq with
attention) and a conv model (resnet) — the reference proves the analogous
claims with test_CompareTwoNets / test_CompareSparse over in-process
pservers; here XLA collectives replace the pserver plane so equivalence of
the jitted step under shardings IS the test.

Plus a real 2-process multi-controller run (jax.distributed over local TCP,
gloo CPU collectives) exercising parallel/distributed.py, which the
reference covers with its localhost --pservers tests.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.sequence import SequenceBatch, pad_sequences
from paddle_tpu.parallel import (MeshConfig, make_mesh, megatron_rules,
                                 param_shardings, batch_shardings,
                                 replicated_shardings)
from paddle_tpu import optim

# mesh-matrix sweep over model/data/seq shardings (multi-minute);
# nightly lane — README "Running the tests"
pytestmark = pytest.mark.slow

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _assert_tree_close(a, b, rtol=1e-4, atol=1e-5, what="leaf"):
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=rtol, atol=atol,
            err_msg=f"{what} {jax.tree_util.keystr(path)}")


def _seq_feed(rng, b, t, vocab):
    return pad_sequences([rng.randint(1, vocab, (rng.randint(2, t + 1),))
                          for _ in range(b)], max_len=t)


def _seq2seq_case(np_rng, b=8):
    from paddle_tpu.models import seq2seq
    params = seq2seq.init(jax.random.PRNGKey(0), src_vocab=64, trg_vocab=64,
                          emb_dim=16, hidden=16)
    src = _seq_feed(np_rng, b, 6, 64)
    trg_in = _seq_feed(np_rng, b, 5, 64)
    trg_next = SequenceBatch(np.roll(np.asarray(trg_in.data), -1, axis=1),
                             trg_in.lengths)

    def loss_fn(p, feed):
        return seq2seq.loss(p, feed["src"], feed["trg_in"], feed["trg_next"])

    return params, {"src": src, "trg_in": trg_in, "trg_next": trg_next}, loss_fn


def _transformer_case(np_rng, b=8):
    from paddle_tpu.models import transformer
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=64,
                              trg_vocab=64, d_model=16, num_heads=2, dff=32,
                              enc_layers=2, dec_layers=2, max_len=8)
    src = _seq_feed(np_rng, b, 6, 64)
    trg_in = _seq_feed(np_rng, b, 5, 64)
    trg_next = SequenceBatch(np.roll(np.asarray(trg_in.data), -1, axis=1),
                             trg_in.lengths)

    def loss_fn(p, feed):
        return transformer.loss(p, feed["src"], feed["trg_in"],
                                feed["trg_next"], num_heads=2)

    return params, {"src": src, "trg_in": trg_in, "trg_next": trg_next}, \
        loss_fn


def _resnet_case(np_rng, b=8):
    # f64: conv reduction order differs between sharded and unsharded
    # layouts, so f32 accumulation noise (up to ~1e-2 relative on
    # cancelling sums) would swamp a tight equivalence check
    from paddle_tpu.models import resnet
    f64 = lambda t: jax.tree_util.tree_map(          # noqa: E731
        lambda x: x.astype(jnp.float64)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, t)
    params, state = resnet.init(jax.random.PRNGKey(0), depth=50,
                                num_classes=16)
    params, state = f64(params), f64(state)
    images = jnp.asarray(np_rng.randn(b, 32, 32, 3), jnp.float64)
    labels = jnp.asarray(np_rng.randint(0, 16, (b,)))

    def loss_fn(p, feed):
        l, _ = resnet.loss(p, state, feed["im"], feed["lab"], depth=50,
                           train=False)
        return l

    return params, {"im": images, "lab": labels}, loss_fn


def _grad_step(loss_fn):
    def step(p, feed):
        return jax.value_and_grad(loss_fn)(p, feed)
    return step


def _run_sharded_vs_single(case, mesh_cfg, rules=None, rtol=1e-4, atol=1e-5):
    np_rng = np.random.RandomState(0)
    params, feed, loss_fn = case(np_rng)
    step = _grad_step(loss_fn)

    l1, g1 = jax.jit(step)(params, feed)

    mesh = make_mesh(mesh_cfg)
    ps = param_shardings(params, mesh, rules)
    fs = batch_shardings(feed, mesh)
    scalar = NamedSharding(mesh, P())
    stepj = jax.jit(step, in_shardings=(ps, fs), out_shardings=(scalar, ps))
    lN, gN = stepj(jax.device_put(params, ps), jax.device_put(feed, fs))

    np.testing.assert_allclose(float(l1), float(lN), rtol=rtol)
    _assert_tree_close(g1, gN, rtol=rtol, atol=atol, what="grad")


@needs_8
def test_seq2seq_model_parallel():
    """Megatron tensor parallelism over 'model' (8-way) == single device."""
    _run_sharded_vs_single(_seq2seq_case, MeshConfig(data=1, model=8),
                           megatron_rules())


@needs_8
def test_transformer_model_parallel():
    """Attention-stack tensor parallelism (qkv column / out row shards via
    the megatron rules) == single device — covers the MHA path under GSPMD
    partitioning (XLA attention on the CPU mesh)."""
    rules = megatron_rules()
    # the rules must actually shard the attention projections (a prior
    # version replicated them, silently weakening this test)
    from paddle_tpu.parallel.sharding import AXIS_MODEL
    assert tuple(rules.spec_for("enc/0/attn/wq")) == (None, AXIS_MODEL)
    assert tuple(rules.spec_for("enc/0/attn/wo")) == (AXIS_MODEL, None)
    _run_sharded_vs_single(_transformer_case, MeshConfig(data=1, model=8),
                           rules)


@needs_8
def test_transformer_data_model_mesh():
    _run_sharded_vs_single(_transformer_case, MeshConfig(data=2, model=4),
                           megatron_rules())


@needs_8
def test_seq2seq_data_model_mesh():
    """Hybrid 2-way data x 4-way model mesh == single device."""
    _run_sharded_vs_single(_seq2seq_case, MeshConfig(data=2, model=4),
                           megatron_rules())


def _in_f64(fn):
    from paddle_tpu.core import dtypes
    jax.config.update("jax_enable_x64", True)
    dtypes.set_policy("float64", "float64")
    try:
        fn()
    finally:
        dtypes.set_policy("float32", None)
        jax.config.update("jax_enable_x64", False)


@needs_8
def test_resnet_data_parallel():
    _in_f64(lambda: _run_sharded_vs_single(
        _resnet_case, MeshConfig(data=8, model=1), rtol=1e-8, atol=1e-10))


@needs_8
def test_resnet_data_model_mesh():
    """Conv kernels replicate (megatron rules only hit [in,out] mats); the
    fc head shards over model — still must match exactly."""
    _in_f64(lambda: _run_sharded_vs_single(
        _resnet_case, MeshConfig(data=4, model=2), megatron_rules(),
        rtol=1e-8, atol=1e-10))


@needs_8
def test_optimizer_update_sharded_seq2seq():
    """Full train step (fwd+bwd+Adam update) under data x model sharding
    matches single device leaf-wise — optimizer slots inherit param specs."""
    np_rng = np.random.RandomState(1)
    params, feed, loss_fn = _seq2seq_case(np_rng)
    opt = optim.Adam(learning_rate=1e-2)

    def train_step(p, s, feed):
        l, g = jax.value_and_grad(loss_fn)(p, feed)
        p2, s2 = opt.update(g, s, p)
        return l, p2, s2

    s0 = opt.init(params)
    l1, p1, _ = jax.jit(train_step)(params, s0, feed)

    mesh = make_mesh(MeshConfig(data=2, model=4))
    rules = megatron_rules()
    ps = param_shardings(params, mesh, rules)
    fs = batch_shardings(feed, mesh)
    # optimizer state: replicate the step counter, shard slots like params
    ss = _opt_state_shardings(s0, ps, mesh)
    scalar = NamedSharding(mesh, P())
    stepj = jax.jit(train_step, in_shardings=(ps, ss, fs),
                    out_shardings=(scalar, ps, ss))
    lN, pN, _ = stepj(jax.device_put(params, ps), jax.device_put(s0, ss),
                      jax.device_put(feed, fs))
    np.testing.assert_allclose(float(l1), float(lN), rtol=1e-4)
    _assert_tree_close(p1, pN, rtol=1e-4, atol=1e-5, what="param")


def _opt_state_shardings(state, param_sh, mesh):
    """Optimizer state sharding: replicate the step counter, give each slot
    tree (params-shaped) the parameters' own shardings."""
    scalar = NamedSharding(mesh, P())
    return {"step": scalar, "slots": {k: param_sh for k in state["slots"]}}


@needs_8
def test_two_process_distributed_cpu():
    """Real multi-controller run: 2 processes x 4 CPU devices, gloo
    collectives, one data-parallel Momentum step; both ranks must see the
    same loss/params, equal to the single-process result."""
    from conftest import free_port
    port = free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(rank), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
        for rank in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    # both ranks agree
    np.testing.assert_allclose(outs[0]["loss"], outs[1]["loss"], rtol=1e-6)
    np.testing.assert_allclose(outs[0]["wsum"], outs[1]["wsum"], rtol=1e-6)

    # equals the single-process reference computed here
    ref = _single_process_reference()
    np.testing.assert_allclose(outs[0]["loss"], ref[0], rtol=1e-5)
    np.testing.assert_allclose(outs[0]["wsum"], ref[1], rtol=1e-5)


def _toy_data():
    r = np.random.RandomState(7)
    x = r.randn(16, 8).astype(np.float32)
    y = r.randint(0, 4, (16,))
    return x, y


def _toy_model():
    from paddle_tpu.ops import losses

    def loss_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"])
        logits = h @ p["w2"]
        return jnp.mean(losses.classification_cost(logits, y))

    r = np.random.RandomState(3)
    params = {"w1": jnp.asarray(r.randn(8, 16) * 0.1, jnp.float32),
              "w2": jnp.asarray(r.randn(16, 4) * 0.1, jnp.float32)}
    return params, loss_fn


def _single_process_reference():
    params, loss_fn = _toy_model()
    x, y = _toy_data()
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9)

    def step(p, s, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        p2, s2 = opt.update(g, s, p)
        return l, p2

    l, p2 = jax.jit(step)(params, opt.init(params), x, y)
    return float(l), float(sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(p2)))


_WORKER = textwrap.dedent("""
    import json, sys
    import numpy as np
    rank, port = int(sys.argv[1]), int(sys.argv[2])
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.parallel import distributed, MeshConfig
    from paddle_tpu.parallel import batch_shardings, param_shardings
    from paddle_tpu import optim
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    distributed.init_distributed(coordinator=f"127.0.0.1:{port}",
                                 num_processes=2, process_id=rank)
    assert jax.device_count() == 8 and jax.local_device_count() == 4
    mesh = distributed.global_mesh(MeshConfig(data=8))
    distributed.barrier("start")

    sys.path.insert(0, ".")
    from tests.test_parallel_matrix import _toy_model, _toy_data
    params, loss_fn = _toy_model()
    x, y = _toy_data()
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9)

    def step(p, s, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        p2, s2 = opt.update(g, s, p)
        return l, p2

    xsh = NamedSharding(mesh, P("data"))
    ssh = NamedSharding(mesh, P())
    # each process owns half the batch: global [16] over 8 devices
    lo = 8 * rank
    gx = jax.make_array_from_process_local_data(xsh, x[lo:lo + 8], (16, 8))
    gy = jax.make_array_from_process_local_data(xsh, y[lo:lo + 8], (16,))
    psh = param_shardings(params, mesh)
    st = opt.init(params)
    osh = {"step": ssh, "slots": {"mom": psh}}
    stepj = jax.jit(step, in_shardings=(psh, osh, xsh, xsh),
                    out_shardings=(ssh, psh))
    l, p2 = stepj(jax.device_put(params, psh),
                  jax.device_put(st, osh), gx, gy)
    wsum = float(sum(jnp.sum(v) for v in jax.tree_util.tree_leaves(p2)))
    distributed.barrier("end")
    print(json.dumps({"loss": float(l), "wsum": wsum}))
""")
