"""img_pool_layer vs a brute-force oracle across ceil/floor modes and
paddings (reference outputSize semantics, config_parser cnn_output_size:
ceil_mode pools pad the HIGH side just enough to reach the ceil output —
the inception 3x3 s1 p1 case regressed once by double-counting base
padding)."""

import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu.layers.graph import Topology, reset_names


def _ref_pool(img, k, s, p, ceil, kind):
    c, h, w = img.shape

    def osz(n):
        if ceil:
            return int(math.ceil((n + 2 * p - k) / s)) + 1
        return (n + 2 * p - k) // s + 1

    oh, ow = osz(h), osz(w)
    out = np.zeros((c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            vals = []
            for di in range(k):
                for dj in range(k):
                    y, x = i * s - p + di, j * s - p + dj
                    if 0 <= y < h and 0 <= x < w:
                        vals.append(img[:, y, x])
            v = np.stack(vals, 0)
            out[:, i, j] = v.max(0) if kind == "max" else v.mean(0)
    return out


@pytest.mark.parametrize("h,k,s,p,ceil", [
    (28, 3, 1, 1, True),      # inception maxpool (the regression case)
    (56, 3, 2, 0, True),      # stem pool, fractional ceil
    (28, 3, 2, 1, True),
    (14, 5, 3, 2, True),
    (28, 3, 2, 1, False),
    (29, 2, 2, 0, True),      # odd input
])
@pytest.mark.parametrize("kind", ["max", "avg"])
def test_pool_matches_bruteforce(h, k, s, p, ceil, kind):
    reset_names()
    c = 2
    rng = np.random.RandomState(h * 100 + k * 10 + s + p)
    x = L.data_layer("x", size=c * h * h)
    pool = L.img_pool_layer(x, pool_size=k, stride=s, padding=p,
                            num_channels=c, ceil_mode=ceil, pool_type=kind)
    topo = Topology([pool])
    params = topo.init(jax.random.PRNGKey(0))
    img = rng.randn(c, h, h).astype(np.float32)
    got = np.asarray(topo.apply(
        params, {"x": jnp.asarray(img.reshape(1, -1))}, mode="test"))
    want = _ref_pool(img, k, s, p, ceil, kind)
    assert pool.img_shape == want.shape[1:]
    np.testing.assert_allclose(got.reshape(want.shape), want, atol=1e-5)
