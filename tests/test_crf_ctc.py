"""CRF / CTC correctness vs brute-force enumeration (tiny shapes)."""

import itertools

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops import crf, ctc


def brute_force_crf(em, length, w):
    start, end, trans = np.asarray(w[0]), np.asarray(w[1]), np.asarray(w[2:])
    n = em.shape[-1]
    best, best_path, logz = -np.inf, None, -np.inf
    scores = []
    for path in itertools.product(range(n), repeat=length):
        s = start[path[0]] + end[path[-1]] + em[0, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + em[t, path[t]]
        scores.append(s)
        if s > best:
            best, best_path = s, path
    logz = np.logaddexp.reduce(scores)
    return best, best_path, logz


def test_crf_decode_matches_bruteforce(np_rng):
    n, t = 3, 4
    em = np_rng.randn(2, t, n).astype(np.float32)
    w = (np_rng.randn(n + 2, n) * 0.5).astype(np.float32)
    lengths = np.array([4, 2], np.int32)
    tags, score = crf.crf_decode(jnp.asarray(em), jnp.asarray(lengths), jnp.asarray(w))
    for i in range(2):
        b_score, b_path, _ = brute_force_crf(em[i], int(lengths[i]), w)
        np.testing.assert_allclose(float(score[i]), b_score, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(tags[i, :lengths[i]]), b_path)


def test_crf_loss_matches_bruteforce_logz(np_rng):
    n, t = 3, 3
    em = np_rng.randn(1, t, n).astype(np.float32)
    w = (np_rng.randn(n + 2, n) * 0.5).astype(np.float32)
    tags = np.array([[1, 0, 2]], np.int32)
    nll = crf.crf_log_likelihood(jnp.asarray(em), jnp.asarray(tags),
                                 jnp.asarray([t]), jnp.asarray(w))
    _, _, logz = brute_force_crf(em[0], t, w)
    start, end, trans = w[0], w[1], w[2:]
    gold = (start[1] + em[0, 0, 1] + trans[1, 0] + em[0, 1, 0]
            + trans[0, 2] + em[0, 2, 2] + end[2])
    np.testing.assert_allclose(float(nll[0]), logz - gold, rtol=1e-5)


def brute_force_ctc(logp, T, labels, blank=0):
    """Sum over all alignments of length T that collapse to `labels`."""
    c = logp.shape[-1]
    total = -np.inf
    for align in itertools.product(range(c), repeat=T):
        collapsed = []
        prev = None
        for a in align:
            if a != blank and a != prev:
                collapsed.append(a)
            prev = a
        if collapsed == list(labels):
            total = np.logaddexp(total, sum(logp[t, align[t]] for t in range(T)))
    return -total


def test_ctc_matches_bruteforce(np_rng):
    t, c = 4, 3
    logits = np_rng.randn(1, t, c).astype(np.float32)
    logp = np.asarray(jnp.log(jnp.exp(logits) / jnp.exp(logits).sum(-1, keepdims=True)))
    labels = [1, 2]
    loss = ctc.ctc_loss(jnp.asarray(logp), jnp.asarray([t]),
                        jnp.asarray([labels]), jnp.asarray([2]))
    expect = brute_force_ctc(logp[0], t, labels)
    np.testing.assert_allclose(float(loss[0]), expect, rtol=1e-4)


def test_ctc_respects_logit_lengths(np_rng):
    t, c = 5, 3
    logits = np_rng.randn(1, t, c).astype(np.float32)
    logp = np.asarray(jnp.log(jnp.exp(logits) / jnp.exp(logits).sum(-1, keepdims=True)))
    loss_a = ctc.ctc_loss(jnp.asarray(logp), jnp.asarray([3]),
                          jnp.asarray([[1]]), jnp.asarray([1]))
    expect = brute_force_ctc(logp[0, :3], 3, [1])
    np.testing.assert_allclose(float(loss_a[0]), expect, rtol=1e-4)


def test_ctc_greedy_decode():
    # argmax path: [1, 1, 0, 2, 2] -> collapse -> [1, 2]
    lp = np.full((1, 5, 3), -5.0, np.float32)
    for t, k in enumerate([1, 1, 0, 2, 2]):
        lp[0, t, k] = -0.1
    ids, lens = ctc.ctc_greedy_decode(jnp.asarray(lp), jnp.asarray([5]))
    assert int(lens[0]) == 2
    np.testing.assert_array_equal(np.asarray(ids[0, :2]), [1, 2])


def test_spp_fixed_width_regardless_of_input():
    from paddle_tpu.ops.conv import spatial_pyramid_pool
    for hw in (3, 7, 8):
        x = jnp.ones((2, hw, hw, 5))
        out = spatial_pyramid_pool(x, pyramid_height=3)
        assert out.shape == (2, 5 * (1 + 4 + 16)), out.shape
