"""chunked_attention (ops/attention.py): the pure-XLA flash-style path
must reproduce dense attention — forward and grads — for plain, causal
(square and offset), ragged-key, and non-dividing-chunk shapes, and the
dense dispatcher must route oversized shapes to it."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops import attention as att

B, H, D = 2, 3, 16


def _qkv(np_rng, tq, tk):
    mk = lambda t: jnp.asarray(np_rng.randn(B, H, t, D) * 0.5, jnp.float32)
    return mk(tq), mk(tk), mk(tk)


def _dense(q, k, v, causal=False, key_mask=None):
    mask = None
    if key_mask is not None:
        mask = (key_mask[:, None, None, :] > 0)
    return att.dot_product_attention(q, k, v, mask=mask, causal=causal,
                                     use_flash=False)


@pytest.mark.parametrize("tq,tk", [(64, 64), (64, 96), (50, 70)],
                         ids=["square", "offset", "nondividing"])
@pytest.mark.parametrize("causal", [False, True], ids=["plain", "causal"])
def test_chunked_matches_dense(np_rng, tq, tk, causal):
    q, k, v = _qkv(np_rng, tq, tk)
    got = att.chunked_attention(q, k, v, causal=causal,
                                q_chunk=32, k_chunk=32)
    want = _dense(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_chunked_ragged_keys(np_rng):
    q, k, v = _qkv(np_rng, 48, 64)
    lengths = np.asarray([37, 64])
    km = jnp.asarray((np.arange(64)[None, :] < lengths[:, None]),
                     jnp.float32)
    got = att.chunked_attention(q, k, v, key_mask=km, q_chunk=16,
                                k_chunk=16)
    want = _dense(q, k, v, key_mask=km)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_chunked_grads_match_dense(np_rng):
    q, k, v = _qkv(np_rng, 64, 64)

    def loss_c(q, k, v):
        return jnp.sum(att.chunked_attention(q, k, v, causal=True,
                                             q_chunk=32, k_chunk=32) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(_dense(q, k, v, causal=True) ** 2)

    gc = jax.grad(loss_c, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gc, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   err_msg=f"grad d{n}")


def test_dense_dispatch_routes_big_shapes(np_rng, monkeypatch):
    """Above the logit-element threshold the dense path silently switches
    to the chunked implementation (same numbers)."""
    q, k, v = _qkv(np_rng, 64, 64)
    seen = {}
    real = att.chunked_attention

    def spy(*a, **kw):
        seen["hit"] = True
        return real(*a, **kw)
    monkeypatch.setattr(att, "chunked_attention", spy)
    monkeypatch.setattr(att, "_CHUNKED_MIN", 64 * 64)
    got = att.dot_product_attention(q, k, v, use_flash=False)
    assert seen.get("hit")
    monkeypatch.setattr(att, "_CHUNKED_MIN", 10 ** 9)
    want = att.dot_product_attention(q, k, v, use_flash=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_long_context_cpu_feasible(np_rng):
    """The point of the path: a sequence whose dense logits would be
    [2,3,4096,4096] f32 (~400 MB) runs chunked in O(T) memory on CPU."""
    t = 4096
    q = jnp.asarray(np_rng.randn(1, 2, t, D) * 0.3, jnp.float32)
    out = att.chunked_attention(q, q, q, causal=True)
    assert out.shape == (1, 2, t, D)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_flash_forced_with_key_mask_raises(np_rng):
    q, k, v = _qkv(np_rng, 64, 64)
    km = jnp.ones((B, 64))
    with pytest.raises(ValueError, match="no mask support"):
        att.dot_product_attention(q, k, v, key_mask=km, use_flash=True)


def test_transformer_full_seq_promise_checked(np_rng):
    """full_seq=True on a genuinely padded (concrete) batch raises instead
    of silently attending padded keys."""
    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer
    params = transformer.init(jax.random.PRNGKey(0), src_vocab=32,
                              trg_vocab=32, d_model=16, dff=32,
                              enc_layers=1, dec_layers=1, max_len=8)
    ids = jnp.asarray(np_rng.randint(3, 32, (2, 8)), jnp.int32)
    padded = SequenceBatch(ids, jnp.asarray([8, 5], jnp.int32))
    full = SequenceBatch(ids, jnp.full((2,), 8, jnp.int32))
    with pytest.raises(ValueError, match="full_seq=True but"):
        transformer.forward(params, padded, full, num_heads=2,
                            full_seq=True)
    out = transformer.forward(params, full, full, num_heads=2,
                              full_seq=True)
    assert out.shape == (2, 8, 32)
