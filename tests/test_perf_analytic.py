"""Analytic perf layer (paddle_tpu/perf): roofline math, cost/HLO
extraction, the structural regression gate (injected de-fusion MUST trip
it; identical snapshots MUST pass), and the committed golden snapshot for
two small bench families.

This is the chip-independent half of the perf evidence (ISSUE 3): every
assertion here runs on the CPU backend, so the gate works every round
regardless of the TPU's health.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.perf import analytic, cost, roofline
from paddle_tpu.scripts import perf_report

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GOLDEN = os.path.join(_ROOT, "tests", "golden", "analytic_smoke.json")

_S = jax.ShapeDtypeStruct


# ------------------------------------------------------------- roofline

def test_roofline_compute_bound():
    # exactly one second of v5e MXU work, negligible bytes
    r = roofline.predict(197e12, 1.0, "v5e")
    assert r["bottleneck"] == "compute"
    assert r["predicted_ms"] == pytest.approx(1000.0)
    assert r["predicted_mfu"] == pytest.approx(1.0)


def test_roofline_memory_bound():
    # exactly one second of v5e HBM traffic, negligible FLOPs
    r = roofline.predict(1.0, 819e9, "v5e")
    assert r["bottleneck"] == "memory"
    assert r["predicted_ms"] == pytest.approx(1000.0)
    assert r["predicted_mfu"] == pytest.approx(0.0, abs=1e-9)


def test_roofline_mixed_known_numbers():
    # 1 ms of compute vs 2 ms of memory -> memory-bound at 50% MFU
    flops = 197e12 * 1e-3
    nbytes = 819e9 * 2e-3
    r = roofline.predict(flops, nbytes, "v5e")
    assert r["predicted_ms"] == pytest.approx(2.0)
    assert r["predicted_mfu"] == pytest.approx(0.5)
    assert r["compute_ms"] == pytest.approx(1.0)
    assert r["memory_ms"] == pytest.approx(2.0)
    assert r["arithmetic_intensity"] == pytest.approx(flops / nbytes)


def test_roofline_ridge_point():
    spec = roofline.SPECS["v5e"]
    assert spec.ridge_intensity == pytest.approx(197e12 / 819e9)
    # at exactly the ridge intensity both ceilings agree
    r = roofline.predict(spec.peak_flops, spec.hbm_bytes_per_s, spec)
    assert r["compute_ms"] == pytest.approx(r["memory_ms"])
    assert r["predicted_mfu"] == pytest.approx(1.0)


def test_roofline_rejects_negative():
    with pytest.raises(ValueError):
        roofline.predict(-1.0, 10.0, "v5e")


# ------------------------------------------------------ cost extraction

def test_op_histogram_parses_tuple_types_and_skips_bookkeeping():
    hlo = "\n".join([
        "ENTRY %main (p0: f32[2,2]) -> f32[] {",
        "  %p0 = f32[2,2]{1,0} parameter(0)",
        "  %c = f32[] constant(0)",
        "  %t = (f32[2]{0}, s32[]) while(%p0), condition=%cond, body=%b",
        "  ROOT %d = f32[2,2]{1,0} dot(%p0, %p0), lhs_contracting_dims={1}",
        "}",
    ])
    hist = cost.op_histogram(hlo)
    assert hist == {"dot": 1, "while": 1}   # parameter/constant skipped


def test_extract_on_compiled_step():
    def f(x, w):
        return jnp.tanh(x @ w).sum()

    c = jax.jit(f).lower(_S((64, 128), jnp.float32),
                         _S((128, 256), jnp.float32)).compile()
    row = cost.extract(c)
    # 2*M*K*N matmul MACs dominate XLA's flop count
    assert row["flops"] >= 2 * 64 * 128 * 256
    assert row["bytes_accessed"] > 0
    assert row["dot_count"] == 1
    assert row["arithmetic_intensity"] == pytest.approx(
        row["flops"] / row["bytes_accessed"])
    assert row["hlo_op_total"] == sum(row["hlo_op_histogram"].values())


# ------------------------------------------------- the regression gate

def _tiny_snapshot(step_fn):
    c = jax.jit(step_fn).lower(
        _S((128, 256), jnp.float32), _S((256, 512), jnp.float32),
        _S((512, 128), jnp.float32)).compile()
    return {"schema": 1, "families": {"tiny": cost.extract(c)}}


def _fused_step(x, w1, w2):
    return (jnp.tanh(x @ w1) @ w2).sum()


def _defused_step(x, w1, w2):
    # same math, deliberately de-fused: the first matmul split into
    # column blocks (re-reads x per block, 8 extra dots + a concatenate)
    blocks = [jnp.tanh(x @ w1[:, i * 64:(i + 1) * 64]) for i in range(8)]
    return (jnp.concatenate(blocks, axis=1) @ w2).sum()


def test_identical_snapshots_pass():
    snap = _tiny_snapshot(_fused_step)
    assert perf_report.analytic_diff(snap, snap) == []


def test_injected_defusion_is_flagged():
    fused = _tiny_snapshot(_fused_step)
    defused = _tiny_snapshot(_defused_step)
    # the injected split really changed the structure (guards the guard)
    assert defused["families"]["tiny"]["dot_count"] \
        > fused["families"]["tiny"]["dot_count"]
    regs = perf_report.analytic_diff(fused, defused)
    assert regs, "de-fused step must trip the structural gate"
    assert any("dot" in r or "bytes" in r for r in regs)
    # and the gate is one-directional: the FIX (defused -> fused) passes
    assert perf_report.analytic_diff(defused, fused) == []


def test_fusion_collapse_with_flat_total_is_flagged():
    """The third de-fusion face: ops migrate out of fusion bodies (total
    flat, fusions collapse, bytes possibly under bytes_tol) must flag;
    a genuine simplification (total shrinks too) must not."""
    base_hist = {"fusion": 10, "dot": 6, "add": 24, "multiply": 20}
    row = {"flops": 1e9, "bytes_accessed": 1e8,
           "hlo_op_histogram": base_hist}
    old = {"families": {"fam": row}}
    collapsed = dict(row, hlo_op_histogram={
        "fusion": 3, "dot": 6, "add": 29, "multiply": 22})   # total flat
    regs = perf_report.analytic_diff(old, {"families": {"fam": collapsed}})
    assert any("fusion count collapsed" in r for r in regs), regs
    simplified = dict(row, hlo_op_histogram={
        "fusion": 3, "dot": 2, "add": 6, "multiply": 5})     # total -73%
    assert perf_report.analytic_diff(
        old, {"families": {"fam": simplified}}) == []


def test_missing_and_errored_families_flagged():
    snap = _tiny_snapshot(_fused_step)
    assert perf_report.analytic_diff(snap, {"families": {}}) \
        == ["tiny: family missing from new snapshot"]
    broken = {"families": {"tiny": {"error": "XlaRuntimeError: boom"}}}
    regs = perf_report.analytic_diff(snap, broken)
    assert regs and "fails to build" in regs[0]


def test_analytic_diff_cli_exit_codes(tmp_path):
    """Acceptance: perf_report --analytic-diff exits non-zero on the
    injected de-fusion and zero on identical snapshots — via a real
    subprocess so the exit code itself is what's proven."""
    fused = _tiny_snapshot(_fused_step)
    defused = _tiny_snapshot(_defused_step)
    a, b = tmp_path / "a.json", tmp_path / "c.json"
    a.write_text(json.dumps(fused))
    b.write_text(json.dumps(defused))
    base = [sys.executable, "-m", "paddle_tpu.scripts.perf_report",
            "--analytic-diff"]
    ok = subprocess.run(base + [str(a), str(a)], cwd=_ROOT,
                        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(base + [str(a), str(b)], cwd=_ROOT,
                         capture_output=True, text=True)
    assert bad.returncode != 0
    assert "ANALYTIC REGRESSION" in bad.stdout


# ------------------------------------------------- golden snapshot gate

def _fresh_smoke_snapshot():
    rows = {}
    for name, model, batch in analytic.FAMILIES:
        if name in ("smallnet", "trainer_prefetch"):
            rows[name] = analytic.capture(name, model, batch)
    return {"schema": 1, "families": rows}


def test_golden_snapshot_still_matches():
    """The committed golden (two small families) vs a fresh capture: the
    structural gate must stay quiet — i.e. today's code has not de-fused
    or bytes-inflated the smallnet / trainer_prefetch steps since the
    golden was cut.  Regenerate the golden when an INTENDED change trips
    this:  python bench.py --analytic --families smallnet,trainer_prefetch
    --out tests/golden/analytic_smoke.json
    (--out matters: the default path is the committed full snapshot)."""
    with open(_GOLDEN) as f:
        golden = json.load(f)
    fresh = _fresh_smoke_snapshot()
    for name, row in fresh["families"].items():
        assert "error" not in row, row.get("error")
        for key in ("flops", "bytes_accessed", "arithmetic_intensity",
                    "hlo_op_histogram", "predicted_ms", "predicted_mfu",
                    "bottleneck"):
            assert key in row
    regs = perf_report.analytic_diff(golden, fresh)
    assert regs == [], f"analytic regressions vs committed golden: {regs}"


def test_snapshot_families_cover_bench():
    """Every analytic family name must resolve to a real bench.py model
    (the registry can't silently drift from the bench)."""
    sys.path.insert(0, _ROOT)
    import bench
    for _name, model, batch in analytic.FAMILIES:
        assert model in bench._BENCHES, model
        if batch is not None:
            assert batch > 0
