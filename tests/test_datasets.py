"""Real dataset parsers against fixture files in PADDLE_TPU_DATA_DIR
(VERDICT r1 item 9; reference python/paddle/v2/dataset/* + its
tests/common_test.py fixture pattern).  Each dataset keeps a deterministic
synthetic fallback for air-gapped runs — tested too."""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest


@pytest.fixture
def data_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_DATA_DIR", str(tmp_path))
    return tmp_path


# ------------------------------------------------------------------ mnist

def test_mnist_real_idx(data_dir):
    from paddle_tpu.data.datasets import mnist
    d = data_dir / "mnist"
    d.mkdir()
    imgs = (np.arange(3 * 784) % 256).astype(np.uint8).reshape(3, 784)
    labs = np.asarray([5, 0, 9], np.uint8)
    with gzip.open(d / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">IIII", 2051, 3, 28, 28) + imgs.tobytes())
    with gzip.open(d / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">II", 2049, 3) + labs.tobytes())
    rows = list(mnist.train()())
    assert len(rows) == 3
    assert [y for _, y in rows] == [5, 0, 9]
    x0 = rows[0][0]
    assert x0.shape == (784,) and -1.0 <= x0.min() and x0.max() <= 1.0


# ------------------------------------------------------------------ cifar

def test_cifar_real_pickle(data_dir):
    from paddle_tpu.data.datasets import cifar
    d = data_dir / "cifar" / "cifar-10-batches-py"
    d.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for name, n in [("data_batch_1", 4), ("test_batch", 2)]:
        batch = {b"data": rng.randint(0, 256, (n, 3072)).astype(np.uint8),
                 b"labels": list(rng.randint(0, 10, n))}
        with open(d / name, "wb") as f:
            pickle.dump(batch, f)
    for i in range(2, 6):
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": np.zeros((1, 3072), np.uint8),
                         b"labels": [0]}, f)
    rows = list(cifar.train10()())
    assert len(rows) == 4 + 4      # 4 real + 4 one-row filler batches
    x, y = rows[0]
    assert x.shape == (3072,) and 0.0 <= x.min() and x.max() <= 1.0
    assert 0 <= y < 10
    assert len(list(cifar.test10()())) == 2


# ------------------------------------------------------------------- imdb

def test_imdb_real_acl_layout(data_dir):
    from paddle_tpu.data.datasets import imdb
    for split in ("train", "test"):
        for pol, texts in [("pos", ["a great movie", "great fun film"]),
                           ("neg", ["terrible boring movie"])]:
            d = data_dir / "aclImdb" / split / pol
            d.mkdir(parents=True)
            for i, t in enumerate(texts):
                (d / f"{i}_7.txt").write_text(t)
    wd = imdb.word_dict()
    # frequency-ordered: 'great' (3) and 'movie' (2) before singletons
    assert wd["great"] == 0 and wd["movie"] == 1
    assert "<unk>" in wd
    rows = list(imdb.train(wd)())
    assert len(rows) == 3
    labels = [y for _, y in rows]
    assert labels == [0, 1, 0]     # interleaved pos/neg
    ids, _ = rows[0]
    assert all(isinstance(i, int) and 0 <= i < len(wd) for i in ids)


# ----------------------------------------------------------------- conll05

def test_conll05_real_props(data_dir):
    from paddle_tpu.data.datasets import conll05
    d = data_dir / "conll05"
    d.mkdir()
    words = "The\ncat\nsat\ndown\n\nDogs\nbark\n\n"
    # sentence 1: one predicate 'sat' (row 2): A0 spans rows 0-1, V row 2,
    # A2 row 3; sentence 2: predicate 'bark' row 1
    props = ("-\t(A0*\n-\t*)\nsit\t(V*)\n-\t(A2*)\n\n"
             "-\t(A0*)\nbark\t(V*)\n\n")
    with gzip.open(d / "test.wsj.words.gz", "wt") as f:
        f.write(words)
    with gzip.open(d / "test.wsj.props.gz", "wt") as f:
        f.write(props)
    wd, vd, td = conll05.get_dict()
    assert "cat" in wd and "sit" in vd and "bark" in vd
    assert "B-A0" in td and "I-A0" in td and "O" in td
    rows = list(conll05.train()())
    assert len(rows) == 2          # one per (sentence, predicate)
    w1, p1, l1 = rows[0]
    assert len(w1) == len(p1) == len(l1) == 4
    assert p1 == [vd["sit"]] * 4
    assert l1 == [td["B-A0"], td["I-A0"], td["B-V"], td["B-A2"]]
    w2, p2, l2 = rows[1]
    assert l2 == [td["B-A0"], td["B-V"]]


# --------------------------------------------------------------- movielens

def test_movielens_real_ml1m(data_dir):
    from paddle_tpu.data.datasets import movielens
    d = data_dir / "ml-1m"
    d.mkdir()
    (d / "users.dat").write_text(
        "1::F::1::10::48067\n2::M::25::16::70072\n")
    (d / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's\n")
    (d / "ratings.dat").write_text(
        "1::1::5::978300760\n2::2::3::978298151\n1::2::4::978302109\n")
    rows = list(movielens.train()())
    assert len(rows) == 3          # 9:1 split keeps first 3 of 3 lines
    uid, gender, age, job, mid, cats, title, score = rows[0]
    assert (uid, gender, job, mid, score) == (1, 0, 10, 1, 5.0)
    assert age == 0                # age bucket '1' -> index 0
    assert len(cats) == 3 and len(title) == 3
    # shared genre vocabulary across movies
    _, _, _, _, _, cats2, _, _ = rows[1]
    assert set(cats) & set(cats2)  # Children's shared


# ------------------------------------------------------- synthetic fallback

@pytest.mark.parametrize("mod,reader_args", [
    ("mnist", ()), ("cifar", ()), ("imdb", ()), ("conll05", ()),
    ("movielens", ()), ("uci_housing", ()), ("imikolov", ()), ("wmt14", ()),
])
def test_synthetic_fallback_deterministic(data_dir, mod, reader_args):
    import importlib
    m = importlib.import_module(f"paddle_tpu.data.datasets.{mod}")
    train = getattr(m, "train10", None) or m.train
    r1 = list(__import__("itertools").islice(train(*reader_args)(), 5))
    r2 = list(__import__("itertools").islice(train(*reader_args)(), 5))
    assert len(r1) == 5

    def flat(rows):
        out = []
        for row in rows:
            row = row if isinstance(row, tuple) else (row,)
            for item in row:
                out.append(np.asarray(item, dtype=object)
                           if isinstance(item, list) else item)
        return out

    for a, b in zip(flat(r1), flat(r2)):
        np.testing.assert_array_equal(np.asarray(a, dtype=float)
                                      if not isinstance(a, np.ndarray)
                                      else a, np.asarray(b, dtype=float)
                                      if not isinstance(b, np.ndarray) else b)


def test_common_download_cache_and_airgap(data_dir, tmp_path):
    """common.download: cached hit returns without network; cache-miss in an
    air-gapped env raises DownloadError naming the manual path (reference
    v2/dataset/common.py contract)."""
    from paddle_tpu.data.datasets import common
    # seed the cache manually, then 'download' must return it (md5-checked)
    d = data_dir / "mymod"
    d.mkdir()
    f = d / "blob.bin"
    f.write_bytes(b"hello world")
    md5 = common.md5file(str(f))
    got = common.download("http://localhost:1/no/such/blob.bin", "mymod", md5)
    assert got == str(f)
    # miss + no network -> DownloadError with manual instructions
    with pytest.raises(common.DownloadError, match="place the file"):
        common.download("http://localhost:1/absent.bin", "mymod",
                        "0" * 32, timeout=2)
