"""Fused Pallas decode-attention kernels (ops/pallas/decode_attention.py).

Interpret-mode (CPU) coverage of the two serving decode kernels — the
slab stripe kernel and the block-table-walking paged kernel — against
the reference XLA paths in ``models/transformer``:

* kernel numerics vs ``_attend`` / the chain-gather path (allclose,
  incl. grouped-KV head layouts);
* masked-width semantics at block boundaries (a position on the last
  slot of a block must not read the next block);
* the reserved scratch block 0 is NEVER attended by an active row
  (poisoned with NaN, outputs unchanged);
* engine-level greedy streams token-identical to ``lm_generate`` with
  the kernels compiled into the step — across staggered admissions,
  prefix-cache hits, CoW forks, and PR-6 supervisor recovery — at
  exactly 1 warm-up trace and 0 retraces under churn;
* the fusion-proof analytic gate (perf/analytic.assert_decode_fused)
  passes on the fused step's HLO and FAILS on the reference step's.

The kernels are forced via ``decode_attention.forced_mode("always")``
(interpret mode off-TPU); the default CPU path stays the reference XLA
implementation, so every other test file keeps pinning bit-identity
against it.  The chaos-recovery, rope-trunk, and fusion-gate cases ride
the slow lane (each builds/lowers an extra engine or step); the kernel
numerics and both engine bit-identity drives stay in the fast lane.
"""

import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import transformer
from paddle_tpu.ops.pallas import decode_attention as dk
from paddle_tpu.perf import analytic as perf_analytic
from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.serving import GenerationBatcher, ServingMetrics
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.testing import assert_no_retrace

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BUCKETS, BS = 48, 4, (8, 16), 8


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def slab_engine(params):
    """Slab engine whose step COMPILED the fused kernel in (the mode is
    read at trace time = warm-up; later drives run the baked step)."""
    with dk.forced_mode("always"):
        eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                           max_len=MAX_LEN, prefill_buckets=BUCKETS,
                           name="kern_slab")
    assert eng.decode_kernels
    return eng


@pytest.fixture(scope="module")
def paged_engine(params):
    with dk.forced_mode("always"):
        eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                           max_len=MAX_LEN, prefill_buckets=BUCKETS,
                           name="kern_paged", kv_layout="paged",
                           kv_block_size=BS)
    assert eng.decode_kernels
    return eng


def _prompt(rng, n=None):
    return rng.randint(1, VOCAB, n or rng.randint(3, BUCKETS[-1] + 1)
                       ).astype(np.int32)


def _oracle(params, engine, prompt, n_tokens):
    """Single-request greedy lm_generate — runs the REFERENCE XLA path
    (kernels are off outside forced_mode on CPU), so engine-vs-oracle
    equality crosses the kernel/reference boundary."""
    bucket = engine.prefill_bucket_for(prompt.size)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :prompt.size] = prompt
    ids = np.asarray(transformer.lm_generate(
        params, padded, max_len=engine.max_len, num_heads=HEADS,
        prompt_lengths=np.asarray([prompt.size])))
    return ids[0, prompt.size:prompt.size + n_tokens].tolist()


def _drive(bat, cases, stagger_s=0.004):
    results, excs = [None] * len(cases), [None] * len(cases)

    def client(i):
        prompt, n = cases[i]
        try:
            time.sleep(stagger_s * i)
            results[i] = bat.submit(prompt, max_tokens=n).result(120)
        except Exception as e:      # noqa: BLE001
            excs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(cases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
        assert not t.is_alive(), "client thread wedged: DEADLOCK"
    return results, excs


# --------------------------------------------------------- kernel numerics


def _ref_slab(q, k, v, positions, num_heads):
    t = k.shape[1]
    pm = jnp.arange(t)[None, :] <= jnp.asarray(positions)[:, None]
    return np.asarray(transformer._attend(
        jnp.asarray(q)[:, None], jnp.asarray(k), jnp.asarray(v),
        num_heads, jnp.broadcast_to(pm, (q.shape[0], t))))[:, 0]


@pytest.mark.parametrize("h,hkv,dh,t", [(2, 2, 16, 24), (4, 2, 8, 48),
                                        (4, 1, 32, 16), (2, 2, 64, 130)])
def test_slab_kernel_matches_attend(h, hkv, dh, t):
    rng = np.random.RandomState(h * 100 + t)
    s, d, dkv = 5, h * dh, hkv * dh
    q = rng.randn(s, d).astype(np.float32)
    k = rng.randn(s, t, dkv).astype(np.float32)
    v = rng.randn(s, t, dkv).astype(np.float32)
    pos = rng.randint(0, t, s).astype(np.int32)
    with dk.forced_mode("always"):
        out = dk.maybe_slab(jnp.asarray(q), jnp.asarray(k),
                            jnp.asarray(v), jnp.asarray(pos), h)
    assert out is not None
    np.testing.assert_allclose(np.asarray(out),
                               _ref_slab(q, k, v, pos, h),
                               rtol=1e-5, atol=1e-5)


def _paged_setup(rng, s, nb, bs, nb_row, dkv, d):
    """Random pool + per-row private chains (block 0 stays scratch) —
    the shared builder from testing/kernel_smoke."""
    from paddle_tpu.testing.kernel_smoke import build_private_tables
    t = nb_row * bs
    q = rng.randn(s, d).astype(np.float32)
    kp = rng.randn(nb, bs, dkv).astype(np.float32)
    vp = rng.randn(nb, bs, dkv).astype(np.float32)
    pos = rng.randint(0, t, s).astype(np.int32)
    tables = build_private_tables(pos, nb_row, bs, nb)
    return q, kp, vp, pos, tables, t


def _ref_paged(q, kp, vp, pos, tables, num_heads):
    s = q.shape[0]
    dkv = kp.shape[-1]
    t = tables.shape[1] * kp.shape[1]
    k_rows = kp[tables].reshape(s, -1, dkv)
    v_rows = vp[tables].reshape(s, -1, dkv)
    pm = np.arange(t)[None, :] <= pos[:, None]
    return np.asarray(transformer._attend(
        jnp.asarray(q)[:, None], jnp.asarray(k_rows),
        jnp.asarray(v_rows), num_heads, jnp.asarray(pm)))[:, 0]


@pytest.mark.parametrize("h,hkv,dh,bs", [(2, 2, 16, 8), (4, 2, 8, 4)])
def test_paged_kernel_matches_chain_gather(h, hkv, dh, bs):
    rng = np.random.RandomState(h * 10 + bs)
    s, nb_row = 4, 3
    d, dkv = h * dh, hkv * dh
    q, kp, vp, pos, tables, _t = _paged_setup(rng, s, 13, bs, nb_row,
                                              dkv, d)
    with dk.forced_mode("always"):
        out = dk.maybe_paged(jnp.asarray(q), jnp.asarray(kp),
                             jnp.asarray(vp), jnp.asarray(pos),
                             jnp.asarray(tables), h)
    assert out is not None
    np.testing.assert_allclose(np.asarray(out),
                               _ref_paged(q, kp, vp, pos, tables, h),
                               rtol=1e-5, atol=1e-5)


def test_block_boundary_positions():
    """Masked-width semantics at the block seams: a row whose position
    sits on the LAST slot of a block (p % bs == bs-1) must attend that
    whole block and nothing of the next; the first slot of a block
    (p % bs == 0) must attend exactly one position of it."""
    rng = np.random.RandomState(3)
    h, dh, bs, nb_row = 2, 16, 8, 3
    d = dkv = h * dh
    s = 4
    q, kp, vp, _pos, _tables, t = _paged_setup(rng, s, 13, bs, nb_row,
                                               dkv, d)
    from paddle_tpu.testing.kernel_smoke import build_private_tables
    pos = np.asarray([bs - 1, bs, 2 * bs - 1, 0], np.int32)
    tables = build_private_tables(pos, nb_row, bs, 13)
    with dk.forced_mode("always"):
        out = dk.maybe_paged(jnp.asarray(q), jnp.asarray(kp),
                             jnp.asarray(vp), jnp.asarray(pos),
                             jnp.asarray(tables), h)
    np.testing.assert_allclose(np.asarray(out),
                               _ref_paged(q, kp, vp, pos, tables, h),
                               rtol=1e-5, atol=1e-5)
    # slab twin at the same boundary positions
    ks = rng.randn(s, t, dkv).astype(np.float32)
    vs = rng.randn(s, t, dkv).astype(np.float32)
    with dk.forced_mode("always"):
        out_s = dk.maybe_slab(jnp.asarray(q), jnp.asarray(ks),
                              jnp.asarray(vs), jnp.asarray(pos), h)
    np.testing.assert_allclose(np.asarray(out_s),
                               _ref_slab(q, ks, vs, pos, h),
                               rtol=1e-5, atol=1e-5)


def test_scratch_block_rows_never_attended():
    """Poison the reserved scratch block 0 with NaN: every ACTIVE row's
    output must be bit-identical to the clean-pool kernel run — the
    clamped table walk never even addresses block 0 for a row that owns
    its chain."""
    rng = np.random.RandomState(4)
    h, dh, bs, nb_row = 2, 16, 8, 3
    d = dkv = h * dh
    q, kp, vp, pos, tables, _t = _paged_setup(rng, 6, 19, bs, nb_row,
                                              dkv, d)
    with dk.forced_mode("always"):
        clean = dk.maybe_paged(jnp.asarray(q), jnp.asarray(kp),
                               jnp.asarray(vp), jnp.asarray(pos),
                               jnp.asarray(tables), h)
        kp2, vp2 = kp.copy(), vp.copy()
        kp2[0] = np.nan
        vp2[0] = np.nan
        poisoned = dk.maybe_paged(jnp.asarray(q), jnp.asarray(kp2),
                                  jnp.asarray(vp2), jnp.asarray(pos),
                                  jnp.asarray(tables), h)
    np.testing.assert_array_equal(np.asarray(poisoned),
                                  np.asarray(clean))
    assert np.all(np.isfinite(np.asarray(poisoned)))


def test_dispatch_gating():
    """auto on CPU -> reference path (None); off -> None even when
    forced upstream; always -> kernel output; bad mode -> error."""
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 32), jnp.float32)
    k = jnp.asarray(rng.randn(2, 16, 32), jnp.float32)
    v = jnp.asarray(rng.randn(2, 16, 32), jnp.float32)
    pos = jnp.asarray([3, 7], jnp.int32)
    with dk.forced_mode("auto"):
        assert dk.maybe_slab(q, k, v, pos, 2) is None   # CPU backend
    with dk.forced_mode("off"):
        assert dk.maybe_slab(q, k, v, pos, 2) is None
    with dk.forced_mode("always"):
        assert dk.maybe_slab(q, k, v, pos, 2) is not None
    with dk.forced_mode("bogus"), pytest.raises(ValueError,
                                                match="pallas_decode"):
        dk.decode_kernels_enabled()
    # the FLAGS path (MODE=None reads utils.flags.FLAGS.pallas_decode)
    from paddle_tpu.utils.flags import FLAGS
    old = FLAGS.pallas_decode
    try:
        FLAGS.pallas_decode = "always"
        assert dk.decode_kernels_enabled()
        FLAGS.pallas_decode = "off"
        assert not dk.decode_kernels_enabled()
    finally:
        FLAGS.pallas_decode = old


def test_untileable_shapes_fall_back_not_crash():
    """Shapes the lane-replicated stat layout cannot express must
    DECLINE (None -> reference path), never fail mid-trace: a paged
    block_size of 136 (> LANES, not a LANES multiple — `_lanes` can
    neither slice nor tile it) and an interpret-mode head dim of 136
    both go through `covers` -> False."""
    rng = np.random.RandomState(6)
    with dk.forced_mode("always"):
        assert not dk.covers(2, 32, 32, 136, paged=True)
        q = jnp.asarray(rng.randn(2, 32), jnp.float32)
        kp = jnp.asarray(rng.randn(5, 136, 32), jnp.float32)
        tbl = jnp.zeros((2, 2), jnp.int32)
        pos = jnp.asarray([3, 7], jnp.int32)
        assert dk.maybe_paged(q, kp, kp, pos, tbl, 2) is None
        # dh = 136: _lanes on the [H, dh] accumulator can't tile either
        assert not dk.covers(2, 272, 272, 16, paged=True)
        q2 = jnp.asarray(rng.randn(2, 272), jnp.float32)
        k2 = jnp.asarray(rng.randn(2, 16, 272), jnp.float32)
        assert dk.maybe_slab(q2, k2, k2, pos, 2) is None


def test_covers_judges_the_per_chip_stripe():
    """Tensor-parallel coverage (docs/serving.md "Sharded decode") is
    judged on the PER-CHIP widths — num_heads/n query heads over a
    d/n-wide q and dkv/n-wide K/V stripe — never the full trunk's:
    inside the engine's shard_map the maybe_* dispatch sees the local
    arrays, so warmup's resolved-path prediction (covers(shards=n))
    must localize the same way or the logged path lies."""
    with dk.forced_mode("always"):
        # full trunk covered; the 2-way stripe still splits its heads
        # (hkv = 2 -> one KV head per chip)
        assert dk.covers(4, 128, 64, 16)
        assert dk.covers(4, 128, 64, 16, shards=2)
        # 4-way: the local stripe is one query head over a 16-wide Dkv
        # — dkv/n stops dividing dh, the grouped-head layout is gone
        assert not dk.covers(4, 128, 64, 16, shards=4)
        # uneven stripes never reach the kernels at all
        assert not dk.covers(4, 128, 64, 16, shards=8)
        assert not dk.covers(4, 128, 64, 16, shards=3)


def test_covers_compiled_stripe_loses_lane_tiling(monkeypatch):
    """Compiled-mode pin for the same localization: a Dkv that Mosaic's
    lanes tile at full width (384 = 3 * 128) stops tiling at the 2-way
    stripe (192 is neither <= 128 nor a 128-multiple), so the sharded
    engine must reject to the reference path even though the identical
    single-chip trunk compiles the fused kernel."""
    monkeypatch.setattr(dk, "_interpret", lambda i: False)
    with dk.forced_mode("always"):
        assert dk.covers(16, 384, 384, 16, paged=True)
        assert not dk.covers(16, 384, 384, 16, paged=True, shards=2)


# ------------------------------------------------------- engine parity


def test_slab_engine_greedy_bit_identical_no_retrace(params, slab_engine):
    """Staggered admissions through the KERNEL-compiled slab step: every
    greedy stream token-identical to the reference-path lm_generate
    oracle; 1 warm-up trace, 0 retraces across churn."""
    eng = slab_engine
    eng.metrics = ServingMetrics()
    assert eng.step_trace_count == 1
    rng = np.random.RandomState(11)
    cases = [(_prompt(rng), int(rng.randint(2, 13))) for _ in range(6)]
    with assert_no_retrace(lambda: eng.step_trace_count,
                           "fused slab churn"):
        bat = GenerationBatcher(eng, default_max_tokens=8)
        results, excs = _drive(bat, cases)
        bat.close()
    assert all(e is None for e in excs), excs
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(params, eng, prompt, n), \
            f"prompt len {prompt.size}, n {n}"
    assert eng.free_slots == SLOTS


def test_paged_engine_greedy_bit_identical_under_churn(params,
                                                      paged_engine):
    """The paged kernel under real allocator churn: shared prompts
    (prefix hit + CoW fork), mixed lengths, slot reuse — streams
    token-identical to the oracle, zero retraces of step/write/fork."""
    eng = paged_engine
    eng.metrics = ServingMetrics()
    rng = np.random.RandomState(12)
    shared = _prompt(rng, BS + 3)
    cases = [(shared, 8), (shared, 8)]
    cases += [(_prompt(rng), int(rng.randint(2, 11))) for _ in range(5)]
    with assert_no_retrace(lambda: eng.step_trace_count
                           + eng._write_traces[0] + eng._copy_traces[0],
                           "fused paged churn (admit/CoW/evict)"):
        bat = GenerationBatcher(eng, default_max_tokens=8)
        results, excs = _drive(bat, cases)
        bat.close()
    assert all(e is None for e in excs), excs
    for (prompt, n), res in zip(cases, results):
        assert res["tokens"] == _oracle(params, eng, prompt, n), \
            f"prompt len {prompt.size}, n {n}"
    snap = eng.metrics.snapshot()
    assert snap["prefix_cache_hits_total"] >= 1
    assert snap["cow_forks_total"] >= 1
    eng._paged.check()


@pytest.mark.slow
def test_supervisor_recovery_with_kernels_bit_identical(params,
                                                        paged_engine):
    """The PR-6 chaos case with the kernels compiled in: an injected
    decode-step fault rebuilds the pool and the supervisor re-seats
    every in-flight stream — all streams bit-identical to the
    reference-path oracle, ZERO extra traces (recovery re-runs the same
    compiled kernel step), exact fault counts, ledger balanced."""
    eng = paged_engine
    eng.metrics = ServingMetrics()
    rng = np.random.RandomState(13)
    cases = [(_prompt(rng), 4 + (i % 5)) for i in range(8)]
    ref = [_oracle(params, eng, p, n) for p, n in cases]
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(eng, supervisor=sup)
    faults.install_spec("serving.decode_step:at=6")
    with assert_no_retrace(lambda: eng.step_trace_count,
                           "fused paged chaos recovery"):
        results, excs = _drive(bat, cases)
        bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    assert all(e is None for e in excs), excs
    assert [r["tokens"] for r in results] == ref
    snap = eng.metrics.snapshot()
    assert snap["evictions"]["recovered"] >= 1
    assert snap["slot_reprefills_total"] >= 1
    eng._paged.check()
    assert eng.free_slots == eng.num_slots


# --------------------------------------------------- fusion-proof gate


@pytest.mark.slow
def test_fusion_proof_gate_both_directions(paged_engine):
    """perf/analytic.assert_decode_fused: clean on the fused step's
    post-optimization HLO, and the SAME detector flags the reference
    chain-gather step — the PR-3 de-fusion detector run in reverse."""
    eng = paged_engine
    t_span = eng._paged.tables.shape[1] * eng.block_size
    dkv = int(eng.params["enc"][0]["attn"]["wk"].shape[1])
    with dk.forced_mode("always"):
        fused_text = eng.lower().compile().as_text()
    perf_analytic.assert_decode_fused(fused_text, eng.num_slots, t_span,
                                      dkv)

    def staged(mode):
        # a FRESH jit wrapper per mode: the dispatch is read at trace
        # time and pjit caches the engine step's jaxpr by avals, so
        # flipping the mode around eng.lower() would silently reuse the
        # warm-up trace
        with dk.forced_mode(mode):
            def fn(p, c, tok, po, tbl):
                return transformer.lm_decode_step_paged(p, tok, po, c,
                                                        tbl, HEADS)
            return jax.jit(fn).lower(
                eng.params, eng._cache, eng._tokens, eng._pos,
                eng._paged.tables).compile().as_text()

    ref_text = staged("off")
    hits = perf_analytic.chain_buffer_instrs(ref_text, eng.num_slots,
                                             t_span, dkv)
    assert hits, "detector missed the reference chain gather"
    with pytest.raises(AssertionError, match="full-chain"):
        perf_analytic.assert_decode_fused(ref_text, eng.num_slots,
                                          t_span, dkv)


def test_chain_buffer_detector_shapes():
    """The detector keys on leading-dim == S and exact element count, so
    the pool itself (leading dim num_blocks) and small row buffers never
    false-positive."""
    hlo = """ENTRY main {
  %p = f32[257,8,128]{2,1,0} parameter(0)
  %g = f32[4,6,8,32]{3,2,1,0} gather(f32[49,8,32]{2,1,0} %p2, s32[4,6,1]{2,1,0} %i)
  %r = f32[4,48,32]{2,1,0} reshape(f32[4,6,8,32]{3,2,1,0} %g)
  %small = f32[4,32]{1,0} add(f32[4,32]{1,0} %a, f32[4,32]{1,0} %b)
}"""
    hits = perf_analytic.chain_buffer_instrs(hlo, 4, 48, 32)
    assert len(hits) == 2           # the gather and its reshape
    assert not perf_analytic.chain_buffer_instrs(hlo, 8, 48, 32)


# ------------------------------------------------------------- rope


@pytest.mark.slow
def test_rope_trunk_slab_kernel_bit_identical():
    """Rope rotation happens BEFORE the kernel (q/k_new pre-rotated, the
    cache stores rotated keys) — the kernel path must keep the rope
    trunk's engine streams token-identical to lm_generate too."""
    rope_params = transformer.init(jax.random.PRNGKey(2), src_vocab=VOCAB,
                                   trg_vocab=1, d_model=D_MODEL,
                                   num_heads=HEADS, dff=64,
                                   enc_layers=LAYERS, dec_layers=0,
                                   max_len=MAX_LEN, pos_type="rope")
    with dk.forced_mode("always"):
        eng = DecodeEngine(rope_params, num_heads=HEADS, num_slots=2,
                           max_len=MAX_LEN, prefill_buckets=(8,),
                           name="kern_rope", pos_type="rope")
    assert eng.decode_kernels
    bat = GenerationBatcher(eng, default_max_tokens=6)
    rng = np.random.RandomState(14)
    prompt = _prompt(rng, 6)
    res = bat.submit(prompt, max_tokens=6).result(60)
    bat.close()
    padded = np.zeros((1, 8), np.int32)
    padded[0, :prompt.size] = prompt
    ids = np.asarray(transformer.lm_generate(
        rope_params, padded, max_len=MAX_LEN, num_heads=HEADS,
        prompt_lengths=np.asarray([prompt.size]), pos_type="rope"))
    assert res["tokens"] == ids[0, prompt.size:prompt.size + 6].tolist()
