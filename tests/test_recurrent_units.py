"""recurrent_units: pre-built LSTM/GRU step units and layer groups
(reference python/paddle/trainer/recurrent_units.py).  The reference states
the *LayerGroup forms are equivalent to LstmLayer/GatedRecurrentLayer —
prove it numerically with mapped parameters."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu.layers as L
from paddle_tpu.core.sequence import pad_sequences
from paddle_tpu.layers.graph import Topology, reset_names, value_data

D_IN, D = 5, 6
B, T = 3, 5


def _data(seed=0):
    r = np.random.RandomState(seed)
    return pad_sequences([r.randn(int(t), D_IN).astype(np.float32)
                          for t in r.randint(2, T + 1, B)], max_len=T)


def test_lstm_layer_group_matches_lstmemory():
    seq = _data()
    reset_names()
    x = L.data_layer("x", size=D_IN, is_seq=True)
    group_out = L.lstm_recurrent_layer_group(name="g", size=D, input=[x])
    topo_g = Topology([L.last_seq(group_out)])
    params_g = topo_g.init(jax.random.PRNGKey(0))

    reset_names()
    x2 = L.data_layer("x", size=D_IN, is_seq=True)
    proj = L.mixed_layer(size=4 * D,
                         input=[L.full_matrix_projection(x2)], act=None,
                         bias_attr=False, name="proj")
    mem_out = L.lstmemory(proj, size=D)
    topo_m = Topology([L.last_seq(mem_out)])
    params_m = topo_m.init(jax.random.PRNGKey(1))

    # map group params onto the monolithic layer:
    #   input transform w -> proj's w; recurrent w -> lstmemory w;
    #   step bias [4D gates | 3D peepholes] -> lstmemory b (same layout)
    params_m["proj"]["w0"] = params_g["g_transform_input"]["w0"]
    params_m[[k for k in params_m if "lstmemory" in k][0]] = {
        "w": params_g["g_input_recurrent"]["w1"],
        "b": params_g["g_hc"]["b"],
    }
    out_g = topo_g.apply(params_g, {"x": seq}, mode="test")
    out_m = topo_m.apply(params_m, {"x": seq}, mode="test")
    np.testing.assert_allclose(np.asarray(value_data(out_g)),
                               np.asarray(value_data(out_m)),
                               rtol=1e-5, atol=1e-6)


def test_gru_layer_group_matches_grumemory():
    seq = _data(seed=1)
    reset_names()
    x = L.data_layer("x", size=D_IN, is_seq=True)
    group_out = L.gated_recurrent_layer_group(name="g", size=D, input=[x])
    topo_g = Topology([L.last_seq(group_out)])
    params_g = topo_g.init(jax.random.PRNGKey(0))

    reset_names()
    x2 = L.data_layer("x", size=D_IN, is_seq=True)
    proj = L.mixed_layer(size=3 * D,
                         input=[L.full_matrix_projection(x2)], act=None,
                         bias_attr=False, name="proj")
    mem_out = L.grumemory(proj, size=D)
    topo_m = Topology([L.last_seq(mem_out)])
    params_m = topo_m.init(jax.random.PRNGKey(1))

    params_m["proj"]["w0"] = params_g["g_transform_input"]["w0"]
    gkey = [k for k in params_m if "grumemory" in k][0]
    params_m[gkey] = {"w_gate": params_g["g_gate.w"]["w_gate"],
                      "w_state": params_g["g_gate.w"]["w_state"],
                      "b": params_g["g_gate.w"]["b"]}
    out_g = topo_g.apply(params_g, {"x": seq}, mode="test")
    out_m = topo_m.apply(params_m, {"x": seq}, mode="test")
    np.testing.assert_allclose(np.asarray(value_data(out_g)),
                               np.asarray(value_data(out_m)),
                               rtol=1e-5, atol=1e-6)


def test_lstm_unit_trains_in_custom_group():
    """A custom step mixing an lstm unit with extra layers compiles, runs
    and takes gradients."""
    seq = _data(seed=2)
    reset_names()
    x = L.data_layer("x", size=D_IN, is_seq=True)

    def step(xt):
        h = L.lstm_recurrent_unit(name="u", size=D,
                                  input=[xt])
        return L.fc_layer(h, size=D, act="tanh", name="post")

    out = L.recurrent_group(step, x)
    topo = Topology([L.last_seq(out)])
    params = topo.init(jax.random.PRNGKey(0))

    def loss(p):
        return jnp.sum(value_data(topo.apply(p, {"x": seq}, mode="test")) ** 2)

    g = jax.grad(loss)(params)
    norms = [float(jnp.sum(jnp.abs(l))) for l in jax.tree_util.tree_leaves(g)]
    assert all(np.isfinite(n) for n in norms)
    assert any(n > 0 for n in norms)
