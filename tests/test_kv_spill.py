"""Hierarchical KV cache (serving/kv_pool.py HostTier + DecodeEngine
kv_host_bytes; docs/serving.md "Hierarchical KV").

When the paged pool evicts a prefix chain under pressure, the chain's
payload spills to a byte-capped LRU host-RAM tier as a RELOCATABLE blob
(``serialize_chain`` — the ROADMAP item 2(b) wire format); the next
prompt covered by that prefix restores it asynchronously (claim fresh
blocks -> transfer-thread staging -> between-steps commit) and seats by
reference exactly like a resident hit.  The correctness bar is the
paged layout's own: greedy streams BIT-IDENTICAL to the tier-less
twin's cold recompute, ZERO prefill chunk lanes for a fully covered
return visit, ONE warm-up trace and zero retraces through the whole
spill/restore churn, and a balanced refcount ledger (including the
pending-restore claims) after every scenario.  A PR-6 ``reset()``
racing an in-flight restore must drop the stale landing (epoch guard)
while the blob survives for the next probe.
"""

import threading
import time

import numpy as np
import pytest
import jax

from paddle_tpu.models import transformer
from paddle_tpu.resilience import Supervisor, faults
from paddle_tpu.serving import GenerationBatcher, ServingMetrics
from paddle_tpu.serving.decode_engine import DecodeEngine
from paddle_tpu.serving.kv_pool import (HostTier, RestorePendingError,
                                        WIRE_VERSION, restore_chain,
                                        serialize_chain)
from paddle_tpu.testing import assert_no_retrace
from paddle_tpu.utils.error import ConfigError

VOCAB, D_MODEL, LAYERS, HEADS = 64, 32, 2, 2
MAX_LEN, SLOTS, BS, CHUNK = 48, 4, 8, 8
# two slots' worth of blocks + scratch: churn traffic evicts the shared
# chain deterministically
POOL_BLOCKS = 2 * (MAX_LEN // BS) + 1
SIG = f"L{LAYERS}.d{D_MODEL}.dkv{D_MODEL // HEADS}.h{HEADS}.float32.b{BS}"


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                            trg_vocab=1, d_model=D_MODEL, num_heads=HEADS,
                            dff=64, enc_layers=LAYERS, dec_layers=0,
                            max_len=MAX_LEN)


@pytest.fixture(scope="module")
def spill_eng(params):
    """Tiny-pool chunked paged engine with the host tier attached."""
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=(8, 16),
                        name="spill_lm", kv_layout="paged",
                        kv_block_size=BS, kv_num_blocks=POOL_BLOCKS,
                        prefill_chunk=CHUNK, kv_host_bytes=64 << 20)


@pytest.fixture(scope="module")
def twin_eng(params):
    """The cold-recompute twin: same trunk, same tiny pool, no tier."""
    return DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                        max_len=MAX_LEN, prefill_buckets=(8, 16),
                        name="spill_twin", kv_layout="paged",
                        kv_block_size=BS, kv_num_blocks=POOL_BLOCKS,
                        prefill_chunk=CHUNK)


def _fresh(eng):
    """Reset one of the module engines to a clean scenario baseline."""
    eng.reset()
    if eng.host_tier is not None:
        eng.host_tier.clear()
    eng.metrics = ServingMetrics()
    return eng


def _prompt(rng, n):
    return rng.randint(1, VOCAB, n).astype(np.int32)


def _churn_out(eng, bat, rng, shared, rounds=4):
    """Admit fresh traffic until the shared chain is no longer resident
    (evicted => spilled on a tier engine)."""
    for _ in range(rounds):
        bat.submit(_prompt(rng, 28), max_tokens=4).result(60)
    assert eng._paged.lookup_prefix(shared)[0] == 0, \
        "churn failed to evict the shared chain"


def _arrays(rng, blocks=3):
    return [("k0", rng.standard_normal((blocks, BS, 16))
             .astype(np.float32)),
            ("v0", rng.standard_normal((blocks, BS, 16))
             .astype(np.float32)),
            ("scale", rng.standard_normal((blocks, BS, HEADS))
             .astype(np.float32))]


# ------------------------------------------------------- wire format


def test_wire_format_round_trip_property():
    """serialize -> restore is the identity on (tokens, covered,
    arrays) across random shapes/dtypes — the relocatable-blob property
    the cross-replica handoff (ROADMAP item 2(b)) relies on."""
    rng = np.random.default_rng(0)
    for trial in range(8):
        n_blocks = int(rng.integers(1, 5))
        tokens = [int(t) for t in rng.integers(1, VOCAB, n_blocks * BS)]
        arrays = [(f"leaf{i}",
                   (rng.standard_normal(
                       (n_blocks, BS, int(rng.integers(1, 9))))
                    * 8).astype(dt))
                  for i, dt in enumerate(
                      [np.float32, np.int8, np.float32][:int(
                          rng.integers(1, 4))])]
        blob = serialize_chain(tokens, n_blocks * BS, arrays, SIG)
        assert blob[0] == WIRE_VERSION
        toks, covered, out = restore_chain(blob, SIG)
        assert toks == tuple(tokens) and covered == n_blocks * BS
        assert [n for n, _ in out] == [n for n, _ in arrays], trial
        for (_, a), (_, b) in zip(arrays, out):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)


def test_wire_format_rejects_foreign_and_corrupt_blobs():
    rng = np.random.default_rng(1)
    tokens = [int(t) for t in rng.integers(1, VOCAB, BS)]
    blob = serialize_chain(tokens, BS, _arrays(rng, 1), SIG)
    # trunk-signature mismatch: K/V bytes only relocate between twins
    with pytest.raises(ValueError, match="trunk signature"):
        restore_chain(blob, SIG.replace(f"L{LAYERS}", f"L{LAYERS + 1}"))
    # version-byte mismatch
    with pytest.raises(ValueError, match="version"):
        restore_chain(bytes([WIRE_VERSION + 1]) + blob[1:], SIG)
    # truncation (inside the payload) and trailing garbage
    with pytest.raises(ValueError, match="truncated"):
        restore_chain(blob[:-3], SIG)
    with pytest.raises(ValueError, match="trailing"):
        restore_chain(blob + b"xx", SIG)
    with pytest.raises(ValueError, match="truncated"):
        restore_chain(b"\x01\x00", SIG)


# --------------------------------------------------------- host tier


def test_host_tier_lru_cap_lookup_and_covers():
    rng = np.random.default_rng(2)
    blob = serialize_chain([1] * BS, BS, _arrays(rng, 1), SIG)
    tier = HostTier(cap_bytes=int(len(blob) * 3.5))
    t1, t2 = tuple(range(1, BS + 1)), tuple(range(101, 101 + BS))
    assert tier.put(t1, BS, blob) == 0
    assert tier.put(t2, BS, blob) == 0
    assert len(tier) == 2 and tier.bytes == 2 * len(blob)
    # block-aligned descending lookup: a longer query finds the prefix
    key, covered, got = tier.lookup(list(t1) + [7, 8, 9], BS)
    assert key == t1 and covered == BS and got == blob
    assert tier.lookup([9] * BS, BS) == (None, 0, None)
    # covers(): equal-or-longer stored key supersets the probe
    long_key = t1 + tuple(range(51, 51 + BS))
    tier.put(long_key, 2 * BS, blob + blob[9:])
    assert tier.covers(t1) and tier.covers(long_key)
    assert not tier.covers(t2 + (1,))
    # the strict-prefix entry was dropped as superseded by long_key
    assert tier.lookup(list(t1), BS) == (None, 0, None)
    # LRU byte cap: t2 (stalest) falls off when the next put overflows
    dropped = tier.put(tuple(range(201, 201 + BS)), BS, blob)
    assert dropped >= 1 and tier.bytes <= tier.cap_bytes
    assert tier.lookup(list(t2), BS) == (None, 0, None)
    assert tier.pop(long_key) is not None
    tier.clear()
    assert len(tier) == 0 and tier.bytes == 0


def test_engine_config_validation(params):
    for kw, match in (
            (dict(kv_layout="slab", kv_host_bytes=1), "paged"),
            (dict(kv_layout="paged", prefix_cache=False,
                  kv_host_bytes=1), "prefix"),
            (dict(kv_layout="paged", kv_host_bytes=-1), ">= 0"),
    ):
        with pytest.raises(ConfigError, match=match):
            DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                         max_len=MAX_LEN, prefill_buckets=(8, 16),
                         name="bad_spill", kv_block_size=BS,
                         prefill_chunk=CHUNK, warm=False, **kw)


def test_restore_vs_recompute_routing_directions(params):
    """The analytic router (perf/analytic.predicted_restore_ms vs
    predicted_recompute_ms, consulted at seat time) must favor RESTORE
    for a multi-block prefix and RECOMPUTE for a sub-chunk one — the
    same both-directions gate the serving_kv_spill bench enforces."""
    eng = DecodeEngine(params, num_heads=HEADS, num_slots=SLOTS,
                       max_len=MAX_LEN, prefill_buckets=(8, 16),
                       name="route_lm", kv_layout="paged",
                       kv_block_size=BS, prefill_chunk=CHUNK,
                       kv_host_bytes=1 << 20, warm=False)
    long_v, long_r, long_c = eng._restore_predicted_faster(4 * BS)
    short_v, short_r, short_c = eng._restore_predicted_faster(CHUNK // 2)
    assert long_v and long_r < long_c, (long_r, long_c)
    assert not short_v and short_c < short_r, (short_r, short_c)


# ------------------------------------------------- spill -> restore


def _audit(eng):
    eng._paged.check()
    assert eng.free_slots == eng.num_slots
    assert not eng._paged._pending, "leaked pending restore claims"


def test_spill_restore_bit_identical_zero_lanes_one_trace(
        params, spill_eng, twin_eng):
    """The tentpole scenario end-to-end: a block-aligned shared prefix
    is registered, churn evicts (and spills) it, and its return visit
    restore-hits — seating by reference with ZERO prefill chunk lanes,
    the stream bit-identical both to its own first serving and to the
    tier-less twin's cold recompute, with no trace past warm-up and a
    balanced ledger."""
    eng, twin = _fresh(spill_eng), _fresh(twin_eng)
    rng = np.random.RandomState(3)
    shared = _prompt(rng, 4 * BS)
    with assert_no_retrace(
            lambda: eng.step_trace_count + eng._write_traces[0]
            + eng._copy_traces[0], "spill/restore churn"):
        bat = GenerationBatcher(eng)
        r1 = bat.submit(shared, max_tokens=6).result(60)
        _churn_out(eng, bat, rng, shared)
        snap = eng.metrics.snapshot()
        assert snap["kv_spill_blocks_total"] > 0, "eviction never spilled"
        assert eng.host_tier.covers(tuple(int(t) for t in shared))
        lanes0 = snap["prefill_chunk_lanes_total"]
        r2 = bat.submit(shared, max_tokens=6).result(60)
        bat.close()
    snap = eng.metrics.snapshot()
    assert snap["kv_restore_hits_total"] == 1, snap
    assert snap["kv_restore_bytes_total"] > 0
    assert snap["kv_restore_ms"]["p50"] > 0
    assert snap["host_tier_bytes"] == eng.host_tier.bytes
    # the covered return visit consumed NO chunk lanes: the restored
    # chain seated by reference, not through prefill
    assert snap["prefill_chunk_lanes_total"] == lanes0, snap
    tbat = GenerationBatcher(twin)
    t1 = tbat.submit(shared, max_tokens=6).result(60)
    tbat.close()
    assert r2["tokens"] == r1["tokens"] == t1["tokens"]
    assert eng.step_trace_count == 1
    _audit(eng)


def test_reset_races_inflight_restore_epoch_guard(params, spill_eng):
    """PR-6 supervisor recovery racing an in-flight restore: the reset
    bumps the epoch and replaces the paged state, so the staged landing
    must be DROPPED (never seated into the fresh pool) — while the blob
    stays resident in the tier, and the next visit restore-hits and
    streams bit-identically."""
    eng = _fresh(spill_eng)
    rng = np.random.RandomState(4)
    shared = _prompt(rng, 4 * BS)
    bat = GenerationBatcher(eng)
    r1 = bat.submit(shared, max_tokens=6).result(60)
    _churn_out(eng, bat, rng, shared)
    bat.close()
    # begin a restore by hand (no batcher: the worker thread must not
    # race the claim), then reset while the transfer is in flight
    pending = eng._maybe_begin_restore(shared)
    assert isinstance(pending, RestorePendingError)
    assert eng._paged._pending, "restore claimed no blocks"
    eng.reset()
    assert not eng._pending_restores   # reset cleared the marker
    assert not eng._paged._pending     # claim died with the old state
    # give the worker time to stage the orphaned job; its completion
    # must land NOTHING in the fresh pool (no marker -> early-out)
    time.sleep(0.3)
    assert eng.poll_restores(timeout=0.05) == 0
    assert len(eng._paged.index) == 0
    assert eng.metrics.snapshot()["kv_restore_hits_total"] == 0
    eng._paged.check()
    # the blob survived the reset: the next visit restores (the stale
    # completion drains benignly — identical payload, same key) and
    # the stream still matches the pre-reset serving
    bat = GenerationBatcher(eng)
    r2 = bat.submit(shared, max_tokens=6).result(60)
    bat.close()
    assert r2["tokens"] == r1["tokens"]
    assert eng.metrics.snapshot()["kv_restore_hits_total"] == 1
    _audit(eng)


# ------------------------------------------------------- slow lane


@pytest.mark.slow
def test_cow_fork_on_restored_chain_bit_identical(params, spill_eng,
                                                  twin_eng):
    """A restored chain is a first-class prefix-cache entry: an exact
    duplicate (CoW fork in the shared tail) and a divergent follower
    both seat on it by reference, every stream bit-identical to the
    tier-less twin."""
    eng, twin = _fresh(spill_eng), _fresh(twin_eng)
    rng = np.random.RandomState(5)
    shared = _prompt(rng, 4 * BS)
    q = _prompt(rng, 4)
    cases = [(shared, 6), (shared, 6),
             (np.concatenate([shared, q]), 6)]
    bat = GenerationBatcher(eng)
    bat.submit(shared, max_tokens=6).result(60)      # register
    _churn_out(eng, bat, rng, shared)
    outs = [bat.submit(p, max_tokens=n).result(60)["tokens"]
            for p, n in cases]
    bat.close()
    snap = eng.metrics.snapshot()
    assert snap["kv_restore_hits_total"] >= 1, snap
    assert snap["cow_forks_total"] >= 1, snap
    tbat = GenerationBatcher(twin)
    ref = [tbat.submit(p, max_tokens=n).result(60)["tokens"]
           for p, n in cases]
    tbat.close()
    assert outs == ref
    _audit(eng)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_spill_storm_staggered_admissions_bit_identical(params, seed):
    """Pool-exhaustion spill storm: staggered concurrent clients with a
    recurring shared prefix over a pool too small to hold everyone —
    evictions spill, returns restore, preemptions ride the existing
    defer seams — and EVERY stream must match the tier-less twin token
    for token with a balanced ledger at the end."""
    def build(name, host_bytes):
        return DecodeEngine(
            transformer.init(jax.random.PRNGKey(0), src_vocab=VOCAB,
                             trg_vocab=1, d_model=D_MODEL,
                             num_heads=HEADS, dff=64, enc_layers=LAYERS,
                             dec_layers=0, max_len=MAX_LEN),
            num_heads=HEADS, num_slots=SLOTS, max_len=MAX_LEN,
            prefill_buckets=(8, 16), name=name, kv_layout="paged",
            kv_block_size=BS, kv_num_blocks=POOL_BLOCKS,
            prefill_chunk=CHUNK, kv_host_bytes=host_bytes)

    eng, twin = build(f"storm_{seed}", 64 << 20), build(
        f"storm_twin_{seed}", 0)
    rng = np.random.RandomState(seed)
    shared = _prompt(rng, 4 * BS)
    cases = []
    for i in range(14):
        if i % 3 == 0:
            cases.append((shared, 5))
        else:
            cases.append((_prompt(rng, int(rng.randint(20, 33))),
                          4 + i % 4))

    def drive(engine):
        bat = GenerationBatcher(engine, queue_size=256)
        results = [None] * len(cases)
        excs = []

        def client(i):
            try:
                time.sleep(0.004 * i)
                results[i] = bat.submit(
                    cases[i][0], max_tokens=cases[i][1]).result(120)
            except Exception as e:      # noqa: BLE001
                excs.append((i, e))

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(cases))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(180)
            assert not t.is_alive(), "client wedged: DEADLOCK"
        bat.close()
        assert not excs, excs
        return [r["tokens"] for r in results]

    got, ref = drive(eng), drive(twin)
    assert got == ref
    assert eng.metrics.snapshot()["kv_spill_blocks_total"] > 0
    assert eng.step_trace_count == 1
    _audit(eng)


@pytest.mark.slow
def test_supervisor_chaos_with_tier_bit_identical(params, spill_eng,
                                                  twin_eng):
    """The PR-6 fault matrix on a tier engine: an injected decode-step
    fault mid-storm rebuilds the pool; the tier (and any spilled
    payloads) survives the reset, recovery re-seats every stream, and
    all outputs still match the twin."""
    eng, twin = _fresh(spill_eng), _fresh(twin_eng)
    rng = np.random.RandomState(9)
    shared = _prompt(rng, 4 * BS)
    cases = [(shared, 6)] + [(_prompt(rng, 28), 5) for _ in range(4)] \
        + [(shared, 6)]
    faults.install_spec("serving.decode_step:at=7")
    sup = Supervisor(breaker_threshold=10)
    bat = GenerationBatcher(eng, supervisor=sup)
    outs = [bat.submit(p, max_tokens=n).result(120)["tokens"]
            for p, n in cases]
    bat.close()
    assert faults.fired_counts() == {"serving.decode_step": 1}
    faults.clear()
    tbat = GenerationBatcher(twin)
    ref = [tbat.submit(p, max_tokens=n).result(120)["tokens"]
           for p, n in cases]
    tbat.close()
    assert outs == ref
    assert eng.metrics.snapshot()["evictions"]["recovered"] >= 1
    _audit(eng)
