"""Registry-driven padding-invariance sweep (SURVEY hard part c).

The reference NEVER pads: every sequence op walks
`Argument::sequenceStartPositions` (parameter/Argument.h:84-93), so its
results cannot depend on anything past a sequence's end.  The TPU rebuild
pads to static shapes and masks — meaning every sequence op must produce
IDENTICAL results when the same sequences are padded longer.  This module
enforces that property for EVERY sweep case with a sequence input, driven
off the same CASES registry as the gradient sweep (new layers get the check
for free).

Method: build the case feed at T, extend every SequenceBatch's data with
EXTRA garbage timesteps (nonzero, so any op that reads past lengths is
caught — zeros would hide mean/sum leaks), keep lengths unchanged, and
compare the scalar loss over all outputs.
"""

import zlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.core.sequence import SequenceBatch
from paddle_tpu.layers.graph import Topology, reset_names, value_data

from tests.test_layer_grad_sweep import CASES, B0, T0

# scan-heavy sweep (every sequence case re-built at two padded lengths);
# nightly lane — README "Running the tests"
pytestmark = pytest.mark.slow

EXTRA = 3          # appended timesteps
GARBAGE = 7.5      # pad payload: loud, not zero

# cases whose outputs legitimately depend on the padded length
EXCLUDED = {
    # none known — an entry here needs a comment citing the reference
    # semantics that make the op max_len-dependent
}


def _seq_cases():
    return sorted(n for n in CASES if n not in EXCLUDED)


def _extend(v):
    """SequenceBatch [B, T, ...] -> [B, T+EXTRA, ...] with garbage pad and
    unchanged lengths."""
    data = np.asarray(v.data)
    pad_shape = (data.shape[0], EXTRA) + data.shape[2:]
    if np.issubdtype(data.dtype, np.floating):
        pad = np.full(pad_shape, GARBAGE, data.dtype)
    else:
        pad = np.ones(pad_shape, data.dtype)   # in-vocab garbage ids
    return SequenceBatch(data=jnp.asarray(np.concatenate([data, pad], 1)),
                         lengths=v.lengths)


def _loss(topo, params, feed):
    out = topo.apply(params, feed, mode="test", rng=jax.random.PRNGKey(7))
    vals = out if isinstance(out, tuple) else (out,)
    total = 0.0
    for v in vals:
        d = value_data(v)
        total = total + jnp.sum(jnp.abs(d.astype(jnp.float32)))
    return total


@pytest.mark.parametrize("name", _seq_cases())
def test_padding_invariant(name):
    build, _ = CASES[name]
    reset_names()
    r = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
    outs, feed = build(r, B0, T0)
    outs = outs if isinstance(outs, list) else [outs]
    if not any(isinstance(v, SequenceBatch) for v in feed.values()):
        pytest.skip("no sequence inputs")
    topo = Topology(outs)
    params = topo.init(jax.random.PRNGKey(0))

    base = float(_loss(topo, params, feed))
    wide = {k: _extend(v) if isinstance(v, SequenceBatch) else v
            for k, v in feed.items()}
    padded = float(_loss(topo, params, wide))
    np.testing.assert_allclose(
        padded, base, rtol=1e-5,
        err_msg=f"{name}: output depends on padding beyond lengths")

    # gradient side: d(loss)/d(param) must not see the padding either
    g_base = jax.grad(lambda p: _loss(topo, p, feed))(params)
    g_wide = jax.grad(lambda p: _loss(topo, p, wide))(params)
    for (path, ga), (_, gw) in zip(
            jax.tree_util.tree_flatten_with_path(g_base)[0],
            jax.tree_util.tree_flatten_with_path(g_wide)[0]):
        if np.issubdtype(np.asarray(ga).dtype, np.floating):
            np.testing.assert_allclose(
                np.asarray(gw), np.asarray(ga), rtol=1e-4, atol=1e-6,
                err_msg=f"{name}: param grad {jax.tree_util.keystr(path)} "
                        "depends on padding")
