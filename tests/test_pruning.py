"""Static pruning hooks (reference ParameterUpdaterHook.cpp StaticPruningHook):
value masked at init, gradient masked every update, so pruned weights stay
exactly zero through real training."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu import optim
from paddle_tpu.compat.v1 import HookAttribute, ParameterAttribute
from paddle_tpu.core.sequence import SequenceBatch  # noqa: F401 (feed types)
from paddle_tpu.layers import api as L
from paddle_tpu.trainer import hooks
from paddle_tpu.trainer.trainer import SGD
from paddle_tpu.utils.error import ConfigError


def _toy_net(ratio=0.5, hook=None):
    x = L.data_layer("x", size=16)
    y = L.data_layer("y", size=1)
    hook = hook or HookAttribute(type="pruning", sparsity_ratio=ratio)
    h = L.fc_layer(input=x, size=32, act="tanh", name="hidden",
                   param_attr=ParameterAttribute(update_hooks=hook))
    out = L.fc_layer(input=h, size=1, act="sigmoid", name="out")
    from paddle_tpu.layers.api import mse_cost
    cost = mse_cost(input=out, label=y)
    return cost


def _feed(rng, n=64):
    x = rng.randn(n, 16).astype(np.float32)
    y = (x[:, :4].sum(1, keepdims=True) > 0).astype(np.float32)
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}


def _sparsity(arr):
    a = np.asarray(arr)
    return float((a == 0).mean())


def test_ratio_mask_applied_at_init_and_through_training():
    ratio = 0.5
    tr = SGD(cost=_toy_net(ratio),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    w0 = np.asarray(tr.parameters["hidden"]["w0"])
    assert _sparsity(w0) >= ratio - 0.02
    # bias is a separate parameter: its mask is all-ones (never pruned)
    assert (np.asarray(tr._prune_masks["hidden"]["b"]) == 1).all()

    rng = np.random.RandomState(0)
    reader = lambda: iter([_feed(rng) for _ in range(20)])
    losses = []
    tr.train(reader, num_passes=1,
             event_handler=lambda e: losses.append(e.cost)
             if hasattr(e, "cost") else None)
    w1 = np.asarray(tr.parameters["hidden"]["w0"])
    # pruned positions stayed exactly zero; kept positions trained
    assert _sparsity(w1) >= ratio - 0.02
    assert (w1[w0 == 0] == 0).all()
    assert np.abs(w1 - w0).max() > 0
    assert losses[-1] < losses[0]


def test_gradients_masked_in_step():
    tr = SGD(cost=_toy_net(0.7),
             update_equation=optim.Adam(learning_rate=0.01))
    mask = np.asarray(tr._prune_masks["hidden"]["w0"])
    rng = np.random.RandomState(1)
    tr.train(lambda: iter([_feed(rng)]), num_passes=1)
    w = np.asarray(tr.parameters["hidden"]["w0"])
    assert (w[mask == 0] == 0).all()
    # adam moves every unmasked weight off its init on step one
    assert np.abs(w[mask == 1]).min() > 0


def test_mask_file_round_trip(tmp_path):
    rng = np.random.RandomState(2)
    bits = rng.randint(0, 2, (16 * 32,)).astype(np.float32)
    path = str(tmp_path / "mask.bin")
    hooks.write_mask_file(path, bits)
    back = hooks.load_mask_file(path, expect_size=bits.size)
    np.testing.assert_array_equal(back, bits)
    # odd (non-multiple-of-8) size exercises the padded tail byte
    hooks.write_mask_file(path, bits[:13])
    np.testing.assert_array_equal(hooks.load_mask_file(path), bits[:13])


def test_mask_file_drives_training(tmp_path):
    rng = np.random.RandomState(3)
    bits = rng.randint(0, 2, (16 * 32,)).astype(np.float32)
    path = str(tmp_path / "mask.bin")
    hooks.write_mask_file(path, bits)

    x = L.data_layer("x", size=16)
    y = L.data_layer("y", size=1)
    h = L.fc_layer(input=x, size=32, act="tanh", name="hidden",
                   bias_attr=False,
                   param_attr=ParameterAttribute(
                       update_hooks=HookAttribute(mask_filename=path)))
    out = L.fc_layer(input=h, size=1, act="sigmoid", name="out")
    from paddle_tpu.layers.api import mse_cost
    tr = SGD(cost=mse_cost(input=out, label=y),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    w = np.asarray(tr.parameters["hidden"]["w0"])
    assert (w.reshape(-1)[bits == 0] == 0).all()
    tr.train(lambda: iter([_feed(np.random.RandomState(4))]), num_passes=1)
    w1 = np.asarray(tr.parameters["hidden"]["w0"])
    assert (w1.reshape(-1)[bits == 0] == 0).all()


def test_param_attr_list_hooks():
    """fc_layer accepts one ParamAttr per input; a hook on one input's attr
    masks only that input's weight."""
    a = L.data_layer("a", size=8)
    b = L.data_layer("b", size=8)
    y = L.data_layer("y", size=1)
    h = L.fc_layer(input=[a, b], size=32, act="tanh", name="h2",
                   param_attr=[
                       ParameterAttribute(update_hooks=HookAttribute(
                           type="pruning", sparsity_ratio=0.5)),
                       ParameterAttribute()])
    out = L.fc_layer(input=h, size=1, act="sigmoid")
    from paddle_tpu.layers.api import mse_cost
    tr = SGD(cost=mse_cost(input=out, label=y),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    assert _sparsity(tr.parameters["h2"]["w0"]) >= 0.48
    assert _sparsity(tr.parameters["h2"]["w1"]) < 0.1
    assert (np.asarray(tr._prune_masks["h2"]["w1"]) == 1).all()


def test_projection_hooks_in_mixed_layer():
    from paddle_tpu.layers.api import full_matrix_projection, mixed_layer
    x = L.data_layer("x", size=16)
    y = L.data_layer("y", size=1)
    m = mixed_layer(
        input=[full_matrix_projection(
            x, param_attr=ParameterAttribute(update_hooks=HookAttribute(
                type="pruning", sparsity_ratio=0.6)))],
        size=32, act="tanh", name="mx")
    out = L.fc_layer(input=m, size=1, act="sigmoid")
    from paddle_tpu.layers.api import mse_cost
    tr = SGD(cost=mse_cost(input=out, label=y),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    assert _sparsity(tr.parameters["mx"]["w0"]) >= 0.58


def test_masks_rebuilt_on_checkpoint_load(tmp_path):
    """Resume keeps the checkpointed zeros pinned: masks re-derive from the
    LOADED weights, not the fresh random init."""
    ratio = 0.5
    tr = SGD(cost=_toy_net(ratio),
             update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    rng = np.random.RandomState(5)
    tr.train(lambda: iter([_feed(rng) for _ in range(5)]), num_passes=1)
    w_saved = np.asarray(tr.parameters["hidden"]["w0"])
    tr.save(str(tmp_path), pass_id=0)

    tr2 = SGD(cost=_toy_net(ratio), seed=99,
              update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
    tr2.load(str(tmp_path))
    np.testing.assert_array_equal(
        np.asarray(tr2.parameters["hidden"]["w0"]), w_saved)
    # masks now match the checkpoint's zeros, and training keeps them zero
    mask = np.asarray(tr2._prune_masks["hidden"]["w0"])
    assert ((w_saved == 0) == (mask == 0)).all()
    tr2.train(lambda: iter([_feed(rng) for _ in range(5)]), num_passes=1)
    w_after = np.asarray(tr2.parameters["hidden"]["w0"])
    assert (w_after[w_saved == 0] == 0).all()
    assert np.abs(w_after - w_saved).max() > 0


def test_mask_file_truncated_payload(tmp_path):
    path = str(tmp_path / "mask.bin")
    hooks.write_mask_file(path, np.ones(64))
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-3])
    with pytest.raises(ConfigError, match="truncated"):
        hooks.load_mask_file(path)


def test_mask_file_size_mismatch(tmp_path):
    path = str(tmp_path / "mask.bin")
    hooks.write_mask_file(path, np.ones(10))
    with pytest.raises(ConfigError, match="size"):
        hooks.load_mask_file(path, expect_size=11)


def test_unknown_hook_type_errors():
    with pytest.raises(ConfigError, match="hook type"):
        SGD(cost=_toy_net(hook={"type": "quantize"}),
            update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))


def test_hook_without_spec_errors():
    with pytest.raises(ConfigError, match="sparsity_ratio"):
        SGD(cost=_toy_net(hook={"type": "pruning"}),
            update_equation=optim.Momentum(learning_rate=0.1, momentum=0.9))
