"""The documented quickstarts must run as written — docs that rot are
worse than no docs.  Mirrors docs/getstarted.md's ten-liner and the
README's config-compiler invocation."""

import numpy as np
import jax


def test_getstarted_ten_liner():
    import paddle_tpu.layers as L
    from paddle_tpu import optim
    from paddle_tpu.data import dense_vector, integer_value
    from paddle_tpu.trainer import SGD
    from paddle_tpu.layers.graph import reset_names

    reset_names()

    def my_reader():
        r = np.random.RandomState(0)
        for _ in range(6):
            yield [(r.randn(784).astype(np.float32),
                    int(r.randint(0, 10))) for _ in range(8)]

    x = L.data_layer("x", size=784)
    h = L.fc_layer(x, size=32, act="relu")
    y = L.fc_layer(h, size=10, act="softmax")
    lab = L.data_layer("lab", size=1)
    cost = L.classification_cost(y, lab)

    trainer = SGD(cost=cost, update_equation=optim.Adam(learning_rate=1e-3))
    trainer.train(my_reader, num_passes=2,
                  feeding={"x": dense_vector(784),
                           "lab": integer_value(10)})


def test_readme_train_cli(tmp_path):
    """`python -m paddle_tpu train --config ...` — the README's headline
    invocation — through the CLI main in-process."""
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "import numpy as np\n"
        "import paddle_tpu.layers as L\n"
        "from paddle_tpu.data import dense_vector, integer_value\n"
        "def get_config():\n"
        "    x = L.data_layer('x', size=4)\n"
        "    y = L.fc_layer(x, size=2, act='softmax')\n"
        "    lab = L.data_layer('lab', size=1)\n"
        "    cost = L.classification_cost(y, lab)\n"
        "    def reader():\n"
        "        r = np.random.RandomState(0)\n"
        "        for _ in range(4):\n"
        "            yield [(r.randn(4).astype(np.float32),\n"
        "                    int(r.randint(0, 2))) for _ in range(8)]\n"
        "    return dict(cost=cost, train_reader=reader,\n"
        "                feeding={'x': dense_vector(4),\n"
        "                         'lab': integer_value(2)})\n")
    from paddle_tpu.trainer.cli import main
    from paddle_tpu.layers.graph import reset_names
    reset_names()
    rc = main(["train", "--config", str(cfg), "--num_passes", "1",
               "--save_dir", str(tmp_path / "out")])
    assert rc in (0, None)
