"""Multi-process distributed bring-up: 2 localhost processes connect through
jax.distributed.initialize (env contract parallel/distributed.py:12-18),
train a tiny model data-parallel with per-process batch shards, and match
single-process numerics — the reference's test_ParameterServer2 /
test_CompareSparse.cpp:66-87 pattern, multi-controller style.

Driven through scripts/launch_cluster.py --local, so the launcher's rank
fan-out and rendezvous env wiring are exercised end-to-end too.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from conftest import free_port

# multi-process rendezvous tests (subprocess workers + timeouts);
# nightly lane — README "Running the tests"
pytestmark = pytest.mark.slow

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch_cmd(nproc, cmd_tail, timeout=240, expect_rc=0):
    """Fan out any command over nproc local ranks via the cluster
    launcher, in its OWN process group so a timeout reaps the rank
    workers too (orphans would hold the coordinator port + CPU)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # each rank gets exactly ONE cpu device: drop the test harness's
    # 8-device virtual mesh flag
    env["XLA_FLAGS"] = ""
    cmd = [sys.executable, "-m", "paddle_tpu.scripts.launch_cluster",
           "--local", str(nproc), "--port", str(free_port()),
           "--workdir", _ROOT, "--"] + list(cmd_tail)
    proc = subprocess.Popen(cmd, env=env, cwd=_ROOT, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.wait()
        raise
    assert proc.returncode == expect_rc, (
        f"launcher rc={proc.returncode} (wanted {expect_rc})\n"
        f"stdout:\n{stdout[-2000:]}\nstderr:\n{stderr[-2000:]}")


def _launch(nproc, out_dir, worker_args=(), timeout=240, expect_rc=0,
            load_ranks=None):
    """Fan out nproc dist_worker ranks via the cluster launcher."""
    os.makedirs(out_dir, exist_ok=True)
    _launch_cmd(nproc,
                [sys.executable, "-m", "paddle_tpu.testing.dist_worker",
                 out_dir] + list(worker_args),
                timeout=timeout, expect_rc=expect_rc)
    results = []
    for r in (range(nproc) if load_ranks is None else load_ranks):
        with open(os.path.join(out_dir, f"rank{r}.json")) as f:
            results.append(json.load(f))
    return results


def test_two_process_data_parallel_matches_single(tmp_path):
    two = _launch(2, str(tmp_path / "p2"))
    assert [r["nproc"] for r in two] == [2, 2]
    assert {r["rank"] for r in two} == {0, 1}
    # both ranks saw the GLOBAL mesh (2 devices across 2 processes)
    assert [r["global_devices"] for r in two] == [2, 2]
    assert [r["coordinator"] for r in two] == [True, False]
    # SPMD: every rank holds identical replicated params
    assert two[0]["checksum"] == pytest.approx(two[1]["checksum"], abs=1e-6)
    assert two[0]["loss"] == pytest.approx(two[1]["loss"], abs=1e-6)

    one = _launch(1, str(tmp_path / "p1"))
    # 2-process sharded-batch training == single-process full-batch training
    assert two[0]["loss"] == pytest.approx(one[0]["loss"], rel=1e-5)
    assert two[0]["checksum"] == pytest.approx(one[0]["checksum"], rel=1e-5)
    # and it actually trained
    assert two[0]["loss"] < 0.8 * two[0]["first_loss"]


def test_2x2_mesh_matches_single(tmp_path):
    """4 processes on a 2x2 data×model mesh — both axes >1, parameters
    tensor-sharded over `model` — must reproduce single-process numerics
    (the reference's wider matrix: multi-trainer × parallel_nn model
    split, test_CompareSparse.cpp:66-87 pattern)."""
    four = _launch(4, str(tmp_path / "p4"), worker_args=["--mesh",
                                                         "data,model"],
                   timeout=360)
    assert [r["global_devices"] for r in four] == [4] * 4
    assert {r["rank"] for r in four} == {0, 1, 2, 3}
    # SPMD: all ranks agree bit-for-bit on the state they computed
    assert len({r["checksum"] for r in four}) == 1
    assert len({r["loss"] for r in four}) == 1

    one = _launch(1, str(tmp_path / "p1"))
    assert four[0]["loss"] == pytest.approx(one[0]["loss"], rel=1e-5)
    assert four[0]["checksum"] == pytest.approx(one[0]["checksum"],
                                                rel=1e-5)
    assert four[0]["loss"] < 0.8 * four[0]["first_loss"]


def test_crash_midpass_then_resume(tmp_path):
    """Kill rank 1 mid-pass (after the coordinator checkpointed at step
    10): the launcher must fail fast with the worker's rc instead of
    hanging the surviving rank, and a relaunch must resume from the
    checkpoint and land on uninterrupted-run numerics — the whole-job
    restart story of a real TPU pod."""
    ck = str(tmp_path / "ck")
    # run A: rank 1 dies at step 14 of 20
    _launch(2, str(tmp_path / "runA"),
            worker_args=["--ckpt-dir", ck, "--crash-rank", "1",
                         "--crash-step", "14"],
            expect_rc=3, load_ranks=[])
    assert any(n.startswith("pass-") for n in os.listdir(ck)), \
        "checkpoint missing after crash"
    # run B: fresh launch resumes from the checkpoint
    resumed = _launch(2, str(tmp_path / "runB"),
                      worker_args=["--ckpt-dir", ck])
    assert [r["start_step"] for r in resumed] == [10, 10]
    # uninterrupted reference run
    clean = _launch(2, str(tmp_path / "clean"))
    assert resumed[0]["loss"] == pytest.approx(clean[0]["loss"], rel=1e-6)
    assert resumed[0]["checksum"] == pytest.approx(clean[0]["checksum"],
                                                   rel=1e-6)


def test_wait_fail_fast_reaps_survivors():
    """A rank exiting nonzero must terminate the remaining ranks promptly
    (they would otherwise block forever in a collective)."""
    import time
    from paddle_tpu.scripts.launch_cluster import wait_fail_fast
    sleeper = subprocess.Popen([sys.executable, "-c",
                                "import time; time.sleep(600)"])
    failer = subprocess.Popen([sys.executable, "-c",
                               "import sys; sys.exit(7)"])
    t0 = time.time()
    rc = wait_fail_fast([sleeper, failer])
    assert rc == 7
    assert time.time() - t0 < 30, "fail-fast took too long"
    assert sleeper.poll() is not None, "surviving rank was not reaped"


def test_ssh_transport_plumbing(monkeypatch, tmp_path):
    """--hosts mode wires rank/rendezvous env into ssh commands (mocked
    transport — no real ssh): coordinator is the first host, each rank
    gets its id, the command runs in --workdir."""
    from paddle_tpu.scripts import launch_cluster

    launched = []

    class FakeProc:
        def __init__(self, cmd, **kw):
            launched.append(cmd)

        def poll(self):
            return 0

        def wait(self, timeout=None):
            return 0

        def send_signal(self, sig):
            pass

    monkeypatch.setattr(launch_cluster.subprocess, "Popen", FakeProc)
    rc = launch_cluster.main(["--hosts", "tpu-a,tpu-b,tpu-c",
                              "--port", "9123", "--workdir", "/srv/repo",
                              "--", "python", "-m",
                              "paddle_tpu.trainer.cli", "train"])
    assert rc == 0
    assert len(launched) == 3
    for rank, (cmd, host) in enumerate(zip(launched,
                                           ["tpu-a", "tpu-b", "tpu-c"])):
        assert cmd[0] == "ssh" and host in cmd
        remote = cmd[-1]
        assert "cd /srv/repo" in remote
        assert "PADDLE_TPU_COORDINATOR=tpu-a:9123" in remote
        assert f"PADDLE_TPU_PROCESS_ID={rank}" in remote
        assert "PADDLE_TPU_NUM_PROCESSES=3" in remote
        assert "python -m paddle_tpu.trainer.cli train" in remote


def test_launcher_arg_validation():
    from paddle_tpu.scripts import launch_cluster
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "2", "--hosts", "a,b", "--", "true"])
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "2"])
    # zero/negative rank counts must error, not silently launch nothing
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "0", "--", "true"])
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "-2", "--", "true"])


def test_rendezvous_env_contract():
    from paddle_tpu.scripts.launch_cluster import rendezvous_env
    env = rendezvous_env("h0", 8476, 4, 3)
    assert env == {"PADDLE_TPU_COORDINATOR": "h0:8476",
                   "PADDLE_TPU_NUM_PROCESSES": "4",
                   "PADDLE_TPU_PROCESS_ID": "3"}


def test_trainer_sparse_multiprocess_matches_single(tmp_path):
    """The user-facing trainer path at multi-process scale: a layers-DSL
    model with a sparse_update embedding trained through SGD(mesh=global
    mesh) across 2 processes must reproduce single-process numerics AND
    make progress — the reference's test_CompareSparse scenario
    (multi-trainer sparse vs local) on the SPMD runtime."""
    two = _launch(2, str(tmp_path / "p2"),
                  worker_args=["--trainer-sparse"], timeout=300)
    one = _launch(1, str(tmp_path / "p1"),
                  worker_args=["--trainer-sparse"])
    assert [r["mode"] for r in two] == ["trainer-sparse"] * 2
    # SPMD: both ranks computed identical state
    assert two[0]["loss"] == two[1]["loss"]
    assert two[0]["emb_checksum"] == pytest.approx(two[1]["emb_checksum"],
                                                   abs=1e-6)
    # distributed == local
    assert two[0]["loss"] == pytest.approx(one[0]["loss"], abs=1e-5)
    assert two[0]["emb_checksum"] == pytest.approx(one[0]["emb_checksum"],
                                                   rel=1e-5)
    assert two[0]["fc_checksum"] == pytest.approx(one[0]["fc_checksum"],
                                                  rel=1e-5)
    # and it learned
    assert two[0]["loss"] < 0.95 * two[0]["first_loss"]
    # cross-rank straggler telemetry (the BarrierStat successor) fired:
    # every rank carries the same report naming each rank's p50/p99
    for r in two:
        rep = r["skew_report"]
        assert rep and "r0[p50=" in rep and "r1[p50=" in rep \
            and "slowest=" in rep and "p50-spread=" in rep
    assert two[0]["skew_report"] is not None
    # single-process runs are not multiprocess: no collective, no report
    assert one[0]["skew_report"] is None


def test_cli_train_under_launcher(tmp_path):
    """The full user story: launch_cluster fans out `paddle_tpu train`
    ranks; the CLI detects the rendezvous env, connects jax.distributed,
    defaults to data-parallel over the job's devices, and the coordinator
    writes the checkpoint.  Final params must match a single-process run
    of the same config."""
    conf = tmp_path / "conf.py"
    conf.write_text(
        "import numpy as np\n"
        "import paddle_tpu.layers as L\n"
        "from paddle_tpu import optim\n"
        "from paddle_tpu.data import dense_vector, integer_value\n"
        "from paddle_tpu.data import reader as reader_mod\n"
        "def _samples():\n"
        "    rng = np.random.RandomState(0)\n"
        "    for _ in range(128):\n"
        "        v = rng.randn(8).astype(np.float32)\n"
        "        yield v, int(v[:3].sum() > 0)\n"
        "def get_config():\n"
        "    x = L.data_layer('x', size=8)\n"
        "    lbl = L.data_layer('lbl', size=2)\n"
        "    h = L.fc_layer(x, size=16, act='tanh')\n"
        "    out = L.fc_layer(h, size=2, act='softmax')\n"
        "    return {'cost': L.classification_cost(out, lbl),\n"
        "            'optimizer': optim.Momentum(learning_rate=0.1,\n"
        "                                        momentum=0.0),\n"
        "            'train_reader': reader_mod.batch(_samples, 32),\n"
        "            'batch_size': 32,\n"
        "            'feeding': {'x': dense_vector(8),\n"
        "                        'lbl': integer_value(2)}}\n")

    def run(nproc, save):
        _launch_cmd(nproc,
                    [sys.executable, "-m", "paddle_tpu.trainer.cli",
                     "train", "--config", str(conf), "--num_passes", "2",
                     "--log_period", "0", "--save_dir", save],
                    timeout=300)

    run(2, str(tmp_path / "ck2"))
    run(1, str(tmp_path / "ck1"))
    import jax
    from paddle_tpu.trainer.checkpoint import load_checkpoint
    p2, _, _, _ = load_checkpoint(str(tmp_path / "ck2"))
    p1, _, _, _ = load_checkpoint(str(tmp_path / "ck1"))
    flat1 = {jax.tree_util.keystr(k): v
             for k, v in jax.tree_util.tree_leaves_with_path(p1)}
    n = 0
    for k, v in jax.tree_util.tree_leaves_with_path(p2):
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(flat1[jax.tree_util.keystr(k)]),
            rtol=1e-5, atol=1e-6, err_msg=jax.tree_util.keystr(k))
        n += 1
    assert n >= 2


def test_pipeline_across_processes(tmp_path):
    """2 processes, each owning ONE GPipe stage: the stage-to-stage
    ppermute rides the inter-process transport, grads flow back through
    it, and the trajectory matches an in-process sequential run of the
    same blocks."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    two = _launch(2, str(tmp_path / "p2"), worker_args=["--mesh", "stage"],
                  timeout=360)
    assert [r["global_devices"] for r in two] == [2, 2]
    assert len({r["checksum"] for r in two}) == 1
    assert two[0]["loss"] < 0.8 * two[0]["first_loss"]

    # sequential oracle: identical seeds, identical update rule
    rng = np.random.RandomState(0)
    s = 2
    w = [jnp.asarray(rng.randn(8, 8) * 0.4, jnp.float32) for _ in range(s)]
    b = [jnp.zeros((8,), jnp.float32) for _ in range(s)]
    STEPS, B = 20, 16
    xs = rng.randn(STEPS, B, 8).astype(np.float32)
    ys = np.tanh(rng.randn(STEPS, B, 8)).astype(np.float32)

    @jax.jit
    def step(w, b, x, y):
        def loss_fn(wb):
            w_, b_ = wb
            h = x
            for i in range(s):
                h = jnp.tanh(h @ w_[i] + b_[i])
            return jnp.mean((h - y) ** 2)
        loss, (gw, gb) = jax.value_and_grad(loss_fn)((w, b))
        return ([wi - 0.3 * g for wi, g in zip(w, gw)],
                [bi - 0.3 * g for bi, g in zip(b, gb)], loss)

    loss = None
    for t in range(STEPS):
        w, b, loss = step(w, b, jnp.asarray(xs[t]), jnp.asarray(ys[t]))
    assert two[0]["loss"] == pytest.approx(float(loss), rel=1e-4)
    checksum = float(sum(jnp.sum(jnp.abs(v)) for v in w + b))
    assert two[0]["checksum"] == pytest.approx(checksum, rel=1e-4)


def test_check_equal_progress_kv_path(monkeypatch):
    """The pass-end equal-progress guard gathers counts over the
    coordination service's HOST-side KV store (no device collective — a
    skewed rank's wedged device queue cannot block it): equal counts pass
    and clean up their keys, unequal counts raise ConfigError naming
    every rank."""
    import jax
    from jax._src import distributed as _dist
    from paddle_tpu.parallel import distributed as D
    from paddle_tpu.utils.error import ConfigError

    class FakeClient:
        def __init__(self):
            self.store = {}
            self.barriers = []

        def key_value_set(self, k, v):
            assert k not in self.store, f"stale key reused: {k}"
            self.store[k] = v

        def blocking_key_value_get(self, k, timeout_ms):
            return self.store[k]

        def wait_at_barrier(self, b, timeout_ms):
            self.barriers.append(b)

        def key_value_delete(self, k):
            self.store.pop(k, None)

    fake = FakeClient()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setattr(_dist.global_state, "client", fake, raising=False)

    # equal counts: pre-populate rank 1's key as its process would have
    seq = D._progress_seq[0]
    fake.store[f"paddle_tpu/eqprog/{seq}/r1"] = "5"
    assert D.check_equal_progress(5, name="pass 0") == (5, False)
    # arrival + cleanup barriers ran, own key deleted
    assert fake.barriers == [f"paddle_tpu/eqprog/{seq}/barrier",
                             f"paddle_tpu/eqprog/{seq}/done"]
    assert f"paddle_tpu/eqprog/{seq}/r0" not in fake.store

    # unequal counts: hard ConfigError naming each rank's count
    seq = D._progress_seq[0]
    fake.store[f"paddle_tpu/eqprog/{seq}/r1"] = "7"
    with pytest.raises(ConfigError, match=r"r0=5 r1=7"):
        D.check_equal_progress(5, name="pass 1")

    # preempted rank (skip=True) still participates, marking its count
    # -(n+1): unequal decoded counts do NOT raise — every rank gets
    # (None, True) and consistently skips follow-up device syncs
    seq = D._progress_seq[0]
    fake.store[f"paddle_tpu/eqprog/{seq}/r1"] = "9"
    assert D.check_equal_progress(5, name="pass 2",
                                  skip=True) == (None, True)
    # mirror: this rank finished, the OTHER rank was preempted at 3
    seq = D._progress_seq[0]
    fake.store[f"paddle_tpu/eqprog/{seq}/r1"] = "-4"
    assert D.check_equal_progress(5, name="pass 3") == (None, True)
    # preempted but EQUAL counts (cluster-wide SIGTERM between batches):
    # device queues are sound — common count comes back, syncs are safe
    seq = D._progress_seq[0]
    fake.store[f"paddle_tpu/eqprog/{seq}/r1"] = "5"
    assert D.check_equal_progress(5, name="pass 4",
                                  skip=True) == (5, True)

    # single process: no client interaction at all
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    n_keys = len(fake.store)
    assert D.check_equal_progress(3) == (3, False)
    assert D.check_equal_progress(3, skip=True) == (3, True)
    assert len(fake.store) == n_keys
