"""Multi-process distributed bring-up: 2 localhost processes connect through
jax.distributed.initialize (env contract parallel/distributed.py:12-18),
train a tiny model data-parallel with per-process batch shards, and match
single-process numerics — the reference's test_ParameterServer2 /
test_CompareSparse.cpp:66-87 pattern, multi-controller style.

Driven through scripts/launch_cluster.py --local, so the launcher's rank
fan-out and rendezvous env wiring are exercised end-to-end too.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from conftest import free_port

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(nproc, out_dir, timeout=240):
    """Fan out nproc dist_worker ranks via the cluster launcher."""
    os.makedirs(out_dir, exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # each rank gets exactly ONE cpu device: drop the test harness's
    # 8-device virtual mesh flag
    env["XLA_FLAGS"] = ""
    cmd = [sys.executable, "-m", "paddle_tpu.scripts.launch_cluster",
           "--local", str(nproc), "--port", str(free_port()),
           "--workdir", _ROOT,
           "--", sys.executable, "-m", "paddle_tpu.testing.dist_worker",
           out_dir]
    # own process group: a timeout must reap the rank workers too, not just
    # the launcher (orphans would hold the coordinator port + CPU)
    proc = subprocess.Popen(cmd, env=env, cwd=_ROOT, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.wait()
        raise
    assert proc.returncode == 0, (
        f"launcher rc={proc.returncode}\nstdout:\n{stdout[-2000:]}\n"
        f"stderr:\n{stderr[-2000:]}")
    results = []
    for r in range(nproc):
        with open(os.path.join(out_dir, f"rank{r}.json")) as f:
            results.append(json.load(f))
    return results


def test_two_process_data_parallel_matches_single(tmp_path):
    two = _launch(2, str(tmp_path / "p2"))
    assert [r["nproc"] for r in two] == [2, 2]
    assert {r["rank"] for r in two} == {0, 1}
    # both ranks saw the GLOBAL mesh (2 devices across 2 processes)
    assert [r["global_devices"] for r in two] == [2, 2]
    assert [r["coordinator"] for r in two] == [True, False]
    # SPMD: every rank holds identical replicated params
    assert two[0]["checksum"] == pytest.approx(two[1]["checksum"], abs=1e-6)
    assert two[0]["loss"] == pytest.approx(two[1]["loss"], abs=1e-6)

    one = _launch(1, str(tmp_path / "p1"))
    # 2-process sharded-batch training == single-process full-batch training
    assert two[0]["loss"] == pytest.approx(one[0]["loss"], rel=1e-5)
    assert two[0]["checksum"] == pytest.approx(one[0]["checksum"], rel=1e-5)
    # and it actually trained
    assert two[0]["loss"] < 0.8 * two[0]["first_loss"]


def test_launcher_arg_validation():
    from paddle_tpu.scripts import launch_cluster
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "2", "--hosts", "a,b", "--", "true"])
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "2"])
    # zero/negative rank counts must error, not silently launch nothing
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "0", "--", "true"])
    with pytest.raises(SystemExit):
        launch_cluster.main(["--local", "-2", "--", "true"])


def test_rendezvous_env_contract():
    from paddle_tpu.scripts.launch_cluster import rendezvous_env
    env = rendezvous_env("h0", 8476, 4, 3)
    assert env == {"PADDLE_TPU_COORDINATOR": "h0:8476",
                   "PADDLE_TPU_NUM_PROCESSES": "4",
                   "PADDLE_TPU_PROCESS_ID": "3"}
