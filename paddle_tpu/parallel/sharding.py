"""Parameter/activation sharding rules.

This is the TPU-native replacement for the reference's entire distributed
parameter plane: ParameterServer2 block sharding (pserver/ParameterServer2.h:
115-120 blockOffsetMap_), ParameterClient2 block routing (block i -> server
i mod N), and MultiGradientMachine's replicate-params/ring-reduce-grads
(MultiGradientMachine.h:57-74).  Here the rules are declarative PartitionSpecs
handed to jit; XLA inserts the psum/all-gather/reduce-scatter collectives
that the reference hand-built with sockets and threads.

Default policy (overridable per-param by regex rules):
  - embeddings [vocab, dim]       -> shard vocab over 'model' (the reference's
                                     sparse pserver ports / SparseRowMatrix)
  - large fc kernels [in, out]    -> shard out over 'model' (megatron column)
    paired projections back       -> shard in  over 'model' (megatron row)
  - everything else               -> replicated (psum'd grads = the pserver
                                     dense path)
Optimizer state inherits its parameter's spec via the same path matching.
"""

import re
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL

# version-compat shard_map: jax >= 0.5 promotes it to jax.shard_map, jax
# 0.4.x keeps it in the experimental namespace.  Call sites here use the
# NEW kwarg name (check_vma); whether the resolved function takes it is a
# separate axis from where it lives (the promotion and the check_rep ->
# check_vma rename were different releases), so translate by signature.
try:
    _shard_map_impl = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_impl

try:
    import inspect
    _SM_TAKES_VMA = ("check_vma"
                     in inspect.signature(_shard_map_impl).parameters)
except (TypeError, ValueError):         # uninspectable wrapper: assume new
    _SM_TAKES_VMA = True

if _SM_TAKES_VMA:
    shard_map = _shard_map_impl
else:
    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_impl(f, **kwargs)


def _path_str(path):
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


class ShardingRules:
    """Ordered (regex -> PartitionSpec) rules matched against the pytree path
    'layer_name/param_name'."""

    def __init__(self, rules=None, default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return self.default


def megatron_rules(extra=()):
    """Column-parallel in-projections, row-parallel out-projections, sharded
    embeddings (tensor parallelism over the 'model' axis)."""
    rules = list(extra) + [
        (r"emb|embedding|table", P(AXIS_MODEL, None)),
        # attention: q/k/v in-projections column-parallel (head sharding),
        # out-projection row-parallel — megatron's attention split
        (r"(^|/)(w[qkv]|wqkv)$", P(None, AXIS_MODEL)),
        (r"(^|/)wo$", P(AXIS_MODEL, None)),
        (r"(w_out|proj_out|o_proj|fc2|down)(/|$)", P(AXIS_MODEL, None)),
        (r"(^|/)(w|w\d+|kernel)$", P(None, AXIS_MODEL)),
    ]
    return ShardingRules(rules)


def valid_spec(spec: P, shape, mesh: Mesh, path: str = None) -> P:
    """Drop axis assignments that don't evenly divide the dim (that dim
    falls back to replication) — keeps tiny/odd params replicated instead of
    erroring, like the reference's block-size threshold in
    ParameterClient2::calcParameterBlockSize.

    Every fallback on a non-trivial dim is logged: a fat embedding silently
    replicated onto every chip is exactly the OOM you want a warning for."""
    from paddle_tpu.utils.logging import logger
    ndim = len(shape)
    entries = list(tuple(spec)) + [None] * (ndim - len(tuple(spec)))
    out = []
    for i, axis in enumerate(entries[:ndim]):
        if axis is None:
            out.append(None)
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        ok = shape[i] % size == 0 and shape[i] >= size
        if not ok and int(np.prod(shape)) >= 65536:
            logger.warning(
                "sharding: %sdim %d of shape %s not divisible by %s=%d -> "
                "REPLICATED (%.1f MB per device)",
                f"{path}: " if path else "", i, tuple(shape), axes, size,
                np.prod(shape) * 4 / 2 ** 20)
        out.append(axis if ok else None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def param_shardings(params, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """NamedSharding pytree for jit in_shardings/out_shardings/device_put."""
    rules = rules or ShardingRules()
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, valid_spec(rules.spec_for(_path_str(path)),
                             np.shape(leaf), mesh, path=_path_str(path))),
        params)


def shard_params(params, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """Place a params pytree onto the mesh (the pserver 'scatter parameters
    to shards' moment, minus the sockets)."""
    shardings = param_shardings(params, mesh, rules)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def batch_shardings(feed, mesh: Mesh):
    """Shard every array's leading (batch) dim over 'data'; scalars
    replicated.  SequenceBatch lengths shard over 'data' too.  Leaves may
    be jax.ShapeDtypeStructs (the SGD.precompile AOT path lowers against
    abstract feeds)."""
    def spec_for_leaf(x):
        shape = getattr(x, "shape", None)
        nd = len(shape) if shape is not None else np.ndim(x)
        if nd == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(*([AXIS_DATA] + [None] * (nd - 1))))
    return jax.tree_util.tree_map(spec_for_leaf, feed)


def replicated_shardings(tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), tree)


def globalize_pytree(tree, shardings, gather=None):
    """Host pytree -> global jax.Arrays on a process-spanning mesh.
    Every process holds the same host value (SPMD discipline:
    deterministic init / identical batch streams); each device takes its
    addressable shard via the callback.  The single implementation behind
    both the trainer's synchronous path (SGD._globalize) and the prefetch
    producer thread (data.prefetch.device_placer) — the multi-process
    assembly is subtle enough that two copies would drift.

    gather: optional fn pulling an already-global (non-fully-addressable)
    jax.Array back to a host value first; leaves are assumed host-side
    when omitted."""
    def conv(x, sh):
        if gather is not None and isinstance(x, jax.Array) \
                and not x.is_fully_addressable:
            x = gather(x)
        a = np.asarray(x)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])
    return jax.tree_util.tree_map(conv, tree, shardings)
