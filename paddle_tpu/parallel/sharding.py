"""Parameter/activation sharding rules.

This is the TPU-native replacement for the reference's entire distributed
parameter plane: ParameterServer2 block sharding (pserver/ParameterServer2.h:
115-120 blockOffsetMap_), ParameterClient2 block routing (block i -> server
i mod N), and MultiGradientMachine's replicate-params/ring-reduce-grads
(MultiGradientMachine.h:57-74).  Here the rules are declarative PartitionSpecs
handed to jit; XLA inserts the psum/all-gather/reduce-scatter collectives
that the reference hand-built with sockets and threads.

Default policy (overridable per-param by regex rules):
  - embeddings [vocab, dim]       -> shard vocab over 'model' (the reference's
                                     sparse pserver ports / SparseRowMatrix)
  - large fc kernels [in, out]    -> shard out over 'model' (megatron column)
    paired projections back       -> shard in  over 'model' (megatron row)
  - everything else               -> replicated (psum'd grads = the pserver
                                     dense path)
Optimizer state inherits its parameter's spec via the same path matching.
"""

import re
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL

# version-compat shard_map: jax >= 0.5 promotes it to jax.shard_map, jax
# 0.4.x keeps it in the experimental namespace.  Call sites here use the
# NEW kwarg name (check_vma); whether the resolved function takes it is a
# separate axis from where it lives (the promotion and the check_rep ->
# check_vma rename were different releases), so translate by signature.
try:
    _shard_map_impl = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_impl

try:
    import inspect
    _SM_TAKES_VMA = ("check_vma"
                     in inspect.signature(_shard_map_impl).parameters)
except (TypeError, ValueError):         # uninspectable wrapper: assume new
    _SM_TAKES_VMA = True

if _SM_TAKES_VMA:
    shard_map = _shard_map_impl
else:
    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_impl(f, **kwargs)


def _path_str(path):
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


class ShardingRules:
    """Ordered (regex -> PartitionSpec) rules matched against the pytree path
    'layer_name/param_name'."""

    def __init__(self, rules=None, default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return self.default


def megatron_rules(extra=()):
    """Column-parallel in-projections, row-parallel out-projections, sharded
    embeddings (tensor parallelism over the 'model' axis)."""
    rules = list(extra) + [
        (r"emb|embedding|table", P(AXIS_MODEL, None)),
        # attention: q/k/v in-projections column-parallel (head sharding),
        # out-projection row-parallel — megatron's attention split
        (r"(^|/)(w[qkv]|wqkv)$", P(None, AXIS_MODEL)),
        (r"(^|/)wo$", P(AXIS_MODEL, None)),
        (r"(w_out|proj_out|o_proj|fc2|down)(/|$)", P(AXIS_MODEL, None)),
        (r"(^|/)(w|w\d+|kernel)$", P(None, AXIS_MODEL)),
    ]
    return ShardingRules(rules)


def valid_spec(spec: P, shape, mesh: Mesh, path: str = None) -> P:
    """Drop axis assignments that don't evenly divide the dim (that dim
    falls back to replication) — keeps tiny/odd params replicated instead of
    erroring, like the reference's block-size threshold in
    ParameterClient2::calcParameterBlockSize.

    Every fallback on a non-trivial dim is logged: a fat embedding silently
    replicated onto every chip is exactly the OOM you want a warning for."""
    from paddle_tpu.utils.logging import logger
    ndim = len(shape)
    entries = list(tuple(spec)) + [None] * (ndim - len(tuple(spec)))
    out = []
    for i, axis in enumerate(entries[:ndim]):
        if axis is None:
            out.append(None)
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        ok = shape[i] % size == 0 and shape[i] >= size
        if not ok and int(np.prod(shape)) >= 65536:
            logger.warning(
                "sharding: %sdim %d of shape %s not divisible by %s=%d -> "
                "REPLICATED (%.1f MB per device)",
                f"{path}: " if path else "", i, tuple(shape), axes, size,
                np.prod(shape) * 4 / 2 ** 20)
        out.append(axis if ok else None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def param_shardings(params, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """NamedSharding pytree for jit in_shardings/out_shardings/device_put."""
    rules = rules or ShardingRules()
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, valid_spec(rules.spec_for(_path_str(path)),
                             np.shape(leaf), mesh, path=_path_str(path))),
        params)


def shard_params(params, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """Place a params pytree onto the mesh (the pserver 'scatter parameters
    to shards' moment, minus the sockets)."""
    shardings = param_shardings(params, mesh, rules)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def batch_shardings(feed, mesh: Mesh):
    """Shard every array's leading (batch) dim over 'data'; scalars
    replicated.  SequenceBatch lengths shard over 'data' too.  Leaves may
    be jax.ShapeDtypeStructs (the SGD.precompile AOT path lowers against
    abstract feeds)."""
    def spec_for_leaf(x):
        shape = getattr(x, "shape", None)
        nd = len(shape) if shape is not None else np.ndim(x)
        if nd == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(*([AXIS_DATA] + [None] * (nd - 1))))
    return jax.tree_util.tree_map(spec_for_leaf, feed)


def replicated_shardings(tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), tree)


# ------------------------------------------------- sharded serving decode
#
# The serving-side tensor-parallel policy (docs/serving.md "Sharded
# decode").  Unlike the training rules above, serving carries a HARD
# bit-identity guarantee against the single-chip twin, which rules out
# megatron row-parallel entirely: a psum of partial contractions reorders
# a float sum and therefore changes bits.  Only tensors whose sharded
# compute is a pure COLUMN SLICE of the replicated compute are split —
# the per-column numerics are untouched and a tiled all-gather
# reassembles the columns in device order, i.e. the original order:
#
#   - wq/wk/wv shard their out-feature (head) axis: each chip computes a
#     contiguous stripe of heads exactly as the single chip would.
#   - the KV cache (slab rows or pool blocks, float or int8 + scale
#     sidecars) shards its trailing head axis the same way — each chip
#     holds its Hkv/n stripe of EVERY row/block, so block tables,
#     allocator, prefix index and CoW stay replicated host data.
#   - src_emb shards its vocab axis: the input lookup is a local gather
#     whose misses are exact zeros (psum-of-zeros seam), and the tied
#     logits projection is a local vocab stripe re-gathered tiled.
#   - EVERYTHING else (wo, the FFN, biases, LNs, pos) is replicated —
#     their contractions run whole on every chip, bit-identically.
#
# The two all-gather seams (attention output, logits) plus the embedding
# psum are the ONLY collectives in the step.

_RX_EMB_SCALE = re.compile(r"(^|/)src_emb/(s|__scale__)$")
_RX_EMB = re.compile(r"(^|/)src_emb(/(q|__int8__))?$")
_RX_QKV = re.compile(r"/attn/w[qkv](/(q|s|__int8__|__scale__))?$")


def lm_decode_param_specs(params, axis=AXIS_MODEL):
    """PartitionSpec pytree for the decoder-only LM trunk under the
    bit-exact serving policy above.  Quantized ``{"q","s"}`` leaves
    shard together: a per-out-channel scale ``[1, dout]`` rides its out
    axis with the int8 payload; src_emb's scale is per-COLUMN ``[1, d]``
    (the vocab axis is the one reduced over) and stays replicated."""
    def spec(path, leaf):
        p = _path_str(path)
        if _RX_EMB_SCALE.search(p):
            return P()
        if _RX_EMB.search(p):
            return P(axis, None)
        if _RX_QKV.search(p):
            return P(None, axis)
        return P()
    return jax.tree_util.tree_map_with_path(spec, params)


def lm_cache_specs(cache, axis=AXIS_MODEL):
    """Trailing-axis (head-stripe) specs for a slab or paged KV cache
    tree: every buffer — K/V and the int8 scale sidecars — is
    ``[lead..., Hkv*dh or Hkv]``, so each chip holds its ``Hkv/n``
    stripe of every slot row / pool block."""
    return jax.tree_util.tree_map(
        lambda l: P(*([None] * (np.ndim(l) - 1) + [axis])), cache)


def lm_shard_problems(params, num_heads, shards):
    """Why this LM trunk CANNOT split ``shards`` ways under the
    bit-exact policy (empty list = it can): every sharded axis must
    divide evenly — query heads (wq stripes), KV heads (a contiguous
    ``Hkv/n`` stripe only lines up with its query stripe's GQA groups
    when ``n | Hkv``) and vocab (embedding stripes)."""
    shards = int(shards)
    if shards <= 1:
        return []
    from paddle_tpu.quant.weights import weight_shape
    probs = []
    vocab = int(weight_shape(params["src_emb"])[0])
    if num_heads % shards:
        probs.append(f"num_heads={num_heads} not divisible by "
                     f"shards={shards}")
    if vocab % shards:
        probs.append(f"vocab={vocab} not divisible by shards={shards}")
    enc = params.get("enc") or []
    if enc and num_heads and num_heads % shards == 0:
        d_q = int(weight_shape(enc[0]["attn"]["wq"])[1])
        dkv = int(weight_shape(enc[0]["attn"]["wk"])[1])
        dh = d_q // num_heads
        hkv = dkv // dh if dh and dkv % dh == 0 else 0
        if not hkv or hkv % shards:
            probs.append(f"kv heads={hkv or f'?(dkv={dkv})'} not "
                         f"divisible by shards={shards}")
    return probs


def decode_mesh(shards, devices=None):
    """A 1-axis ``('model',)`` mesh over the first ``shards`` local
    devices — the serving mesh (no data axis: continuous batching IS
    the batch plane, and its slots axis must stay whole for the
    per-row scatter writes)."""
    devices = list(jax.devices() if devices is None else devices)
    shards = int(shards)
    if shards < 1 or shards > len(devices):
        raise ValueError(
            f"decode_mesh: shards={shards} outside [1, "
            f"{len(devices)} visible devices]")
    return Mesh(np.asarray(devices[:shards]), (AXIS_MODEL,))


def globalize_pytree(tree, shardings, gather=None):
    """Host pytree -> global jax.Arrays on a process-spanning mesh.
    Every process holds the same host value (SPMD discipline:
    deterministic init / identical batch streams); each device takes its
    addressable shard via the callback.  The single implementation behind
    both the trainer's synchronous path (SGD._globalize) and the prefetch
    producer thread (data.prefetch.device_placer) — the multi-process
    assembly is subtle enough that two copies would drift.

    gather: optional fn pulling an already-global (non-fully-addressable)
    jax.Array back to a host value first; leaves are assumed host-side
    when omitted."""
    def conv(x, sh):
        if gather is not None and isinstance(x, jax.Array) \
                and not x.is_fully_addressable:
            x = gather(x)
        a = np.asarray(x)
        return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])
    return jax.tree_util.tree_map(conv, tree, shardings)
