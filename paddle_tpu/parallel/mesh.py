"""Device mesh abstraction.

Replaces the reference's process/thread topology knobs — trainer_count
(MultiGradientMachine.h:37-115), pserver host lists (--pservers, --port,
--ports_num), --parallel_nn device= placement — with one declarative object:
a jax.sharding.Mesh over named axes

  data    — batch (data parallelism; the MultiGradientMachine/pserver path)
  model   — tensor/layer sharding (the parallel_nn path)
  seq     — sequence/context parallelism (new capability; SURVEY.md §5)
  expert  — MoE expert parallelism (new capability)

ICI/DCN placement: axes are ordered so the innermost (fastest-varying,
adjacent devices) axis carries the heaviest collectives — put 'model'
innermost so tensor-parallel allreduces ride ICI; 'data' outermost so its
allreduce can cross DCN between slices (scaling-book recipe).
"""

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_STAGE = "stage"
# 'stage' sits between data and the intra-layer axes: its ppermute
# traffic is one activation per microbatch tick — lighter than model/seq
# collectives (keep those innermost on ICI) but heavier than the data
# allreduce (which may cross DCN)
ALL_AXES = (AXIS_DATA, AXIS_STAGE, AXIS_SEQ, AXIS_EXPERT, AXIS_MODEL)


@dataclasses.dataclass
class MeshConfig:
    data: int = 0        # 0 = fill with remaining devices
    model: int = 1
    seq: int = 1
    expert: int = 1
    stage: int = 1       # pipeline stages (parallel/pipeline.py)

    def resolve(self, n_devices):
        fixed = self.model * self.seq * self.expert * self.stage
        data = self.data or max(1, n_devices // fixed)
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{self.stage}x{self.seq}x{self.expert}x"
                f"{self.model} != {n_devices} devices")
        return (data, self.stage, self.seq, self.expert, self.model)


def make_mesh(config: Optional[MeshConfig] = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    shape = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, ALL_AXES)


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(
        (1,) * len(ALL_AXES)), ALL_AXES)


def batch_spec(seq_sharded=False) -> P:
    """Inputs: batch dim over 'data'; optionally time dim over 'seq'."""
    if seq_sharded:
        return P(AXIS_DATA, AXIS_SEQ)
    return P(AXIS_DATA)


def replicated() -> P:
    return P()


def sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
