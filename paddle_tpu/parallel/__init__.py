"""SPMD parallelism: mesh, sharding rules, collectives, sequence parallelism.

Replaces reference §2.6 (pserver) + MultiGradientMachine + ParallelNeuralNetwork
with jax.sharding over a named Mesh (SURVEY.md §2.6 'TPU-native equivalent').
"""

from paddle_tpu.parallel.mesh import (
    Mesh, MeshConfig, make_mesh, single_device_mesh, AXIS_DATA, AXIS_MODEL,
    AXIS_SEQ, AXIS_EXPERT, AXIS_STAGE, ALL_AXES,
)
from paddle_tpu.parallel.pipeline import (
    gpipe, stack_stages, unstack_stages, stage_spec, microbatch,
    unmicrobatch,
)
from paddle_tpu.parallel.sharding import (
    ShardingRules, megatron_rules, param_shardings, shard_params,
    batch_shardings, replicated_shardings, valid_spec,
)
from paddle_tpu.parallel.distributed import (
    init_distributed, is_coordinator, global_mesh, barrier,
    check_equal_progress,
)

__all__ = [
    "Mesh", "MeshConfig", "make_mesh", "single_device_mesh",
    "AXIS_DATA", "AXIS_MODEL", "AXIS_SEQ", "AXIS_EXPERT", "AXIS_STAGE",
    "ALL_AXES",
    "gpipe", "stack_stages", "unstack_stages", "stage_spec", "microbatch",
    "unmicrobatch",
    "ShardingRules", "megatron_rules", "param_shardings", "shard_params",
    "batch_shardings", "replicated_shardings", "valid_spec",
    "init_distributed", "is_coordinator", "global_mesh", "barrier",
    "check_equal_progress",
]
