"""Pipeline parallelism: GPipe-style microbatch pipelining over the
'stage' mesh axis.

The reference's closest ancestor is ParallelNeuralNetwork's `device=N`
layer placement (gserver/gradientmachines/ParallelNeuralNetwork.cpp:15-60:
per-device worker threads execute layers as dependencies become ready,
synchronized by per-Argument condition variables).  The TPU-native redesign
replaces ready-queues and condvars with a *static* schedule compiled into
one SPMD program: each device owns one stage's parameters (pytree leading
axis sharded over 'stage'), microbatches tick through a `lax.scan`, and the
stage-to-stage activation handoff is a `lax.ppermute` ring shift on ICI.

Backward needs no code: `ppermute` and `scan` are differentiable, so
`jax.grad` of a pipelined forward IS the reverse pipeline schedule,
bubbles and all (the transpose of a forward rotation is the backward
rotation).  Use `remat=True` to rematerialize each stage block instead of
saving every tick's activations.

Schedule: plain GPipe fill-and-drain — T = M + S - 1 ticks for M
microbatches over S stages; bubble fraction (S-1)/T shrinks as M grows.
Stage 0 feeds microbatch t at tick t; the last stage emits microbatch m at
tick m + S - 1; outputs are collected from the stacked per-stage scan
output outside the shard_map.

Constraint (inherent to homogeneous pipelining): every stage maps
activations of one shape to the same shape.  Wrap unequal first/last
blocks (embedding in, logits out) outside the pipelined middle.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.mesh import AXIS_STAGE
from paddle_tpu.parallel.sharding import shard_map


def stack_stages(params_list):
    """Stack S per-stage parameter pytrees into one pytree with a leading
    stage axis (shard it over 'stage' via `stage_spec`)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params_list)


def unstack_stages(stacked):
    """Inverse of stack_stages (host-side convenience)."""
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return [jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
            for i in range(n)]


def stage_spec(stacked_params):
    """PartitionSpec pytree: leading axis over 'stage', rest replicated."""
    return jax.tree_util.tree_map(lambda _: P(AXIS_STAGE), stacked_params)


def gpipe(stage_fn, stacked_params, x_mb, *, mesh: Mesh,
          axis_name: str = AXIS_STAGE, data_axis: str = None,
          remat: bool = False, param_specs=None):
    """Run `stage_fn` as a pipeline over `axis_name`.

    stage_fn: (stage_params, x) -> y with y.shape == x.shape (pytrees of
        arrays allowed for x/y as long as shapes match across stages).
    stacked_params: pytree with leading stage axis [S, ...], sharded over
        `axis_name` (see `stage_spec`).
    x_mb: [M, mb, ...] microbatched input, replicated over `axis_name`
        (shard the mb dim over `data_axis` for pp x dp).
    param_specs: optional PartitionSpec pytree for stacked_params whose
        leading dim is `axis_name` — use to tensor-shard each stage's
        weights over further axes (pp x tp); stage_fn is then responsible
        for the matching collectives (e.g. a megatron psum over 'model').
    Returns [M, mb, ...] last-stage outputs, sharded like x_mb.
    """
    s = mesh.shape[axis_name]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != s:
        raise ValueError(
            f"{n_stages} stacked stages but mesh '{axis_name}' axis has "
            f"size {s}; one device must own exactly one stage")
    m = jax.tree_util.tree_leaves(x_mb)[0].shape[0]
    nticks = m + s - 1
    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    def local_fn(p_l, x_l):
        # p_l: [1, ...] stage slice; x_l: [M, mb, ...] (stage-replicated)
        p_my = jax.tree_util.tree_map(lambda a: a[0], p_l)
        stage_id = jax.lax.axis_index(axis_name)
        zero = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape[1:], a.dtype), x_l)
        perm = [(j, (j + 1) % s) for j in range(s)]

        def tick(carry, t):
            # carry: my previous tick's output, about to move one stage up
            recv = jax.lax.ppermute(carry, axis_name, perm)
            # drain ticks (t >= m) re-feed the clamped last microbatch;
            # the duplicates are discarded by the caller's output slice.
            # Deliberately NOT a zero feed: a stage_fn that is non-finite
            # at zero input (eps-free normalization, division by a norm)
            # would produce NaN drain activations, and NaN * 0-cotangent
            # = NaN poisons the summed parameter gradients under grad.
            # A real microbatch keeps every tick finite, and its zero
            # cotangent then contributes an exact 0.  (No FLOPs are
            # wasted relative to any alternative — the scan body runs
            # every tick regardless.)
            feed = jax.tree_util.tree_map(
                lambda a: a[jnp.minimum(t, m - 1)], x_l)
            x_in = jax.tree_util.tree_map(
                lambda f, r: jnp.where(stage_id == 0, f, r), feed, recv)
            out = fn(p_my, x_in)
            return out, out

        _, outs = jax.lax.scan(tick, zero, jnp.arange(nticks))
        # emit every tick's output with a leading singleton stage axis;
        # stacked over 'stage' outside, the caller slices the last stage's
        # drain ticks — no cross-stage collective needed
        return jax.tree_util.tree_map(lambda a: a[None], outs)

    if param_specs is None:
        pspec = jax.tree_util.tree_map(lambda _: P(axis_name),
                                       stacked_params)
    else:
        pspec = param_specs
        for p in jax.tree_util.tree_leaves(
                pspec, is_leaf=lambda x: isinstance(x, P)):
            if not p or p[0] != axis_name:
                raise ValueError(
                    f"param_specs leading dim must be {axis_name!r}, got {p}")
    xspec = jax.tree_util.tree_map(
        lambda _: P(None, data_axis) if data_axis else P(), x_mb)
    ospec = jax.tree_util.tree_map(
        lambda _: (P(axis_name, None, data_axis) if data_axis
                   else P(axis_name)), x_mb)
    run = shard_map(local_fn, mesh=mesh, in_specs=(pspec, xspec),
                        out_specs=ospec, check_vma=False)
    stacked = run(stacked_params, x_mb)     # [S, T, mb, ...]
    # last stage (index S-1) drains microbatch i at tick i + S - 1
    return jax.tree_util.tree_map(
        lambda a: a[s - 1, s - 1:s - 1 + m], stacked)


def microbatch(x, num_microbatches):
    """[B, ...] -> [M, B/M, ...] (B % M == 0)."""
    def split(a):
        b = a.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {num_microbatches} microbatches")
        return a.reshape((num_microbatches, b // num_microbatches)
                         + a.shape[1:])
    return jax.tree_util.tree_map(split, x)


def unmicrobatch(x_mb):
    """[M, mb, ...] -> [M*mb, ...]."""
    return jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), x_mb)
