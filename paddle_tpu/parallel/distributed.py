"""Multi-host distributed runtime.

Replaces the reference's distributed backend bring-up — paddle_pserver
processes + --pservers/--trainer_id/--num_gradient_servers wiring
(pserver/ParameterServerController.cpp:65, trainer/TrainerMain.cpp:39-44,
scripts/cluster_train/paddle.py:101-176) — with JAX's multi-controller
SPMD runtime: every host runs the same program, jax.distributed.initialize
connects them, and the global mesh spans all hosts' devices.  Gradient
exchange is the psum XLA inserts from shardings: over ICI within a slice,
over DCN between slices — no parameter server, no sockets to manage.

Env-var contract (also used by the cluster launcher):
  PADDLE_TPU_COORDINATOR   host:port of process 0
  PADDLE_TPU_NUM_PROCESSES world size
  PADDLE_TPU_PROCESS_ID    this process's rank
(standard TPU-pod deployments can omit all three: jax.distributed.
initialize() autodetects from the TPU metadata server.)
"""

import os
from typing import Optional

import numpy as np
import jax

from paddle_tpu.parallel.mesh import ALL_AXES, MeshConfig, Mesh
from paddle_tpu.utils.logging import logger

_initialized = [False]


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None):
    """Connect this host into the multi-host runtime (idempotent).

    With no arguments, reads the PADDLE_TPU_* env vars; with none set on a
    TPU pod, defers to JAX's autodetection."""
    if _initialized[0]:
        return
    coordinator = coordinator or os.environ.get("PADDLE_TPU_COORDINATOR")
    if num_processes is None and "PADDLE_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PADDLE_TPU_NUM_PROCESSES"])
    if process_id is None and "PADDLE_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PADDLE_TPU_PROCESS_ID"])
    kw = {}
    if coordinator:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if local_device_ids is not None:
        kw["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kw)
    _initialized[0] = True
    logger.info("distributed: process %d/%d, %d local + %d global devices",
                jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def is_coordinator() -> bool:
    return jax.process_index() == 0


def global_mesh(config: Optional[MeshConfig] = None,
                dcn_data_parallel: Optional[int] = None) -> Mesh:
    """Mesh over ALL hosts' devices.

    dcn_data_parallel: number of slices connected by DCN (defaults to
    jax.process_count() on multi-slice deployments when set); the 'data'
    axis is laid out so its outer factor crosses DCN and everything else
    stays on ICI (hybrid mesh, scaling-book recipe).
    """
    config = config or MeshConfig()
    if dcn_data_parallel and dcn_data_parallel > 1:
        from jax.experimental import mesh_utils
        n = jax.device_count()
        shape = config.resolve(n)
        ici_shape = (shape[0] // dcn_data_parallel,) + shape[1:]
        dcn_shape = (dcn_data_parallel,) + (1,) * (len(ALL_AXES) - 1)
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape)
        return Mesh(devices, ALL_AXES)
    shape = config.resolve(jax.device_count())
    arr = np.asarray(jax.devices()).reshape(shape)
    return Mesh(arr, ALL_AXES)


def barrier(name: str = "barrier"):
    """Host-level sync point (the reference's waitPassStart/Finish RPCs,
    ParameterService.proto:90-114)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


# check_equal_progress call ordinal: every rank executes the same sequence
# of pass ends (num_passes comes from the shared config), so a local
# counter stays in lockstep across processes and makes each call's
# coordination-service keys/barrier ids unique — stale keys from an
# earlier train() call can never be read
_progress_seq = [0]
_warned_no_client = []      # one-shot fallback warning state


def check_equal_progress(n_batches, name="pass", timeout_s=600.0,
                         skip=False):
    """Equal-progress guard for multi-process training.

    Gathers each rank's batch count and raises ConfigError on mismatch:
    SPMD training requires every rank's reader to yield the same number
    of batches — a rank with MORE batches has already enqueued step
    executables whose cross-process collectives (the grad psum) no other
    rank will join, so its DEVICE queue is wedged the moment the counts
    diverge.  A device-side collective (process_allgather) would wedge
    right behind it; the gather therefore goes over the coordination
    service's host-side KV store (jax.distributed client), which needs no
    device participation — the mismatch surfaces as this error on every
    rank's HOST even while the device queues hang, and tearing the
    process down aborts the orphaned device work.  A rank that never
    arrives (crashed) turns into a barrier timeout error after
    ``timeout_s`` instead of an infinite hang.

    The trainer calls this at PASS END — a point every rank reaches
    unconditionally, however many batches its reader produced.  Without a
    coordination-service client (multi-process runtime brought up outside
    ``jax.distributed``) it falls back to a device allgather, which still
    catches skew a pass late (counts equal this pass, unequal the next)
    but can itself hang in the wedged case — prefer init_distributed.

    skip=True (a rank stopping early on purpose — SIGTERM preemption)
    still PARTICIPATES in the gather but marks its count preempted (the
    encoding is ``-(n+1)``, so the actual count survives): signal
    delivery is not synchronized across ranks, so unequal counts are
    expected then, and a rank that silently skipped the collective would
    strand every other rank at the barrier for ``timeout_s``.  When any
    rank is preempted the mismatch check never raises; instead the
    equality of the DECODED counts tells every rank — consistently —
    whether the device queues are still sound (equal: all dispatched
    steps' collectives are matched, host syncs and a final checkpoint
    are safe) or wedged (unequal: a rank dispatched steps whose psums
    will never complete).

    Returns ``(common, preempted)``: ``common`` is the shared batch
    count, or None when counts diverged (only possible preempted —
    otherwise it raises); ``preempted`` is True when any rank stopped on
    a signal, which callers must treat as job-wide stop (a preempted
    peer will not join the next pass's collectives).  Single-process:
    no collective, ``(n_batches, skip)``.
    """
    n = -(int(n_batches) + 1) if skip else int(n_batches)
    nproc = jax.process_count()
    if nproc == 1:
        return int(n_batches), bool(skip)
    from paddle_tpu.utils.error import ConfigError

    seq = _progress_seq[0]
    _progress_seq[0] += 1
    try:
        # private namespace: the only handle on the coordination-service
        # KV client; a jax relocation degrades to the device fallback
        # below rather than crashing the pass end
        from jax._src import distributed as _dist
        client = getattr(_dist.global_state, "client", None)
    except ImportError:
        client = None
    if client is None:
        if not _warned_no_client:
            _warned_no_client.append(True)      # once per process, not
            logger.warning(                     # once per pass end
                "check_equal_progress: no coordination-service client; "
                "falling back to a device allgather (cannot interrupt an "
                "already-wedged device queue)")
        from jax.experimental import multihost_utils
        counts = [int(c) for c in np.asarray(multihost_utils.
                  process_allgather(np.asarray([n], np.int64))).reshape(-1)]
    else:
        rank = jax.process_index()
        key = f"paddle_tpu/eqprog/{seq}"
        t_ms = max(1000, int(timeout_s * 1000))
        client.key_value_set(f"{key}/r{rank}", str(n))
        # all ranks' keys are visible once everyone arrives; a missing
        # rank fails this barrier after timeout_s instead of hanging
        client.wait_at_barrier(f"{key}/barrier", t_ms)
        counts = [int(client.blocking_key_value_get(f"{key}/r{i}", t_ms))
                  for i in range(nproc)]
        # second barrier before cleanup so no rank deletes a key a
        # straggler is still reading
        client.wait_at_barrier(f"{key}/done", t_ms)
        client.key_value_delete(f"{key}/r{rank}")
    preempted = any(c < 0 for c in counts)
    decoded = [-c - 1 if c < 0 else c for c in counts]
    if len(set(decoded)) > 1:
        if preempted:       # expected when signal delivery raced the
            return None, True           # stop-check; not a config error
        per_rank = " ".join(f"r{i}={c}" for i, c in enumerate(decoded))
        raise ConfigError(
            f"unequal per-rank batch counts in {name}: {per_rank} — "
            "multi-process train() requires every rank's reader to yield "
            "the same number of batches per pass (shard the data evenly, "
            "or drop the remainder with batch(..., drop_last=True))")
    return decoded[0], preempted


def step_skew_report(durations, name="train_step"):
    """Cross-rank straggler/skew report — the SPMD successor to the
    reference's per-trainer BarrierStat arrival profiling
    (utils/BarrierStat.h:196-273, logged per --log_period_server).

    In synchronous SPMD the collectives themselves equalize device time,
    so the straggler signal lives in each rank's HOST-side step wall
    time (input pipeline, Python dispatch, H2D feeds): a rank that
    arrives late at its next collective stalls every other rank.  Each
    rank passes its recent per-step wall durations (seconds); the stats
    are all-gathered (so this is a COLLECTIVE — every rank must call it
    at the same step, even with an empty window: the gather always runs,
    so ranks can't deadlock on divergent emptiness) and every rank
    returns the same report string; the coordinator also logs it.
    Returns None when every rank's window was empty."""
    durations = np.asarray(durations, np.float64).reshape(-1)
    if durations.size:
        local = np.asarray([
            float(np.percentile(durations, 50)),
            float(np.percentile(durations, 99)),
            float(np.mean(durations)),
            float(durations.size)], np.float32)
    else:
        local = np.zeros((4,), np.float32)
    if jax.process_count() == 1:
        all_stats = local[None]
    else:
        from jax.experimental import multihost_utils
        all_stats = np.asarray(multihost_utils.process_allgather(local))
    have = all_stats[:, 3] > 0
    if not have.any():
        return None
    p50s, p99s = all_stats[:, 0], all_stats[:, 1]
    # ranks with an empty window are reported but excluded from the
    # min/argmax/spread stats (their zeros would poison all three)
    slowest = int(np.argmax(np.where(have, p50s, -np.inf)))
    lo = max(float(p50s[have].min()), 1e-9)
    spread_pct = (float(p50s[have].max()) - float(p50s[have].min())) \
        / lo * 100.0
    per_rank = " ".join(
        f"r{i}[p50={p * 1e3:.1f}ms p99={q * 1e3:.1f}ms]" if h else f"r{i}[--]"
        for i, (p, q, h) in enumerate(zip(p50s, p99s, have)))
    report = (f"{name} skew ({int(all_stats[:, 3].max())} steps/rank): "
              f"{per_rank} | slowest=r{slowest} p50-spread={spread_pct:.0f}%")
    if is_coordinator():
        logger.info(report)
    return report
