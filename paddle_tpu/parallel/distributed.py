"""Multi-host distributed runtime.

Replaces the reference's distributed backend bring-up — paddle_pserver
processes + --pservers/--trainer_id/--num_gradient_servers wiring
(pserver/ParameterServerController.cpp:65, trainer/TrainerMain.cpp:39-44,
scripts/cluster_train/paddle.py:101-176) — with JAX's multi-controller
SPMD runtime: every host runs the same program, jax.distributed.initialize
connects them, and the global mesh spans all hosts' devices.  Gradient
exchange is the psum XLA inserts from shardings: over ICI within a slice,
over DCN between slices — no parameter server, no sockets to manage.

Env-var contract (also used by the cluster launcher):
  PADDLE_TPU_COORDINATOR   host:port of process 0
  PADDLE_TPU_NUM_PROCESSES world size
  PADDLE_TPU_PROCESS_ID    this process's rank
(standard TPU-pod deployments can omit all three: jax.distributed.
initialize() autodetects from the TPU metadata server.)
"""

import os
from typing import Optional

import numpy as np
import jax

from paddle_tpu.parallel.mesh import ALL_AXES, MeshConfig, Mesh
from paddle_tpu.utils.logging import logger

_initialized = [False]


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None):
    """Connect this host into the multi-host runtime (idempotent).

    With no arguments, reads the PADDLE_TPU_* env vars; with none set on a
    TPU pod, defers to JAX's autodetection."""
    if _initialized[0]:
        return
    coordinator = coordinator or os.environ.get("PADDLE_TPU_COORDINATOR")
    if num_processes is None and "PADDLE_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PADDLE_TPU_NUM_PROCESSES"])
    if process_id is None and "PADDLE_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PADDLE_TPU_PROCESS_ID"])
    kw = {}
    if coordinator:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if local_device_ids is not None:
        kw["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kw)
    _initialized[0] = True
    logger.info("distributed: process %d/%d, %d local + %d global devices",
                jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def is_coordinator() -> bool:
    return jax.process_index() == 0


def global_mesh(config: Optional[MeshConfig] = None,
                dcn_data_parallel: Optional[int] = None) -> Mesh:
    """Mesh over ALL hosts' devices.

    dcn_data_parallel: number of slices connected by DCN (defaults to
    jax.process_count() on multi-slice deployments when set); the 'data'
    axis is laid out so its outer factor crosses DCN and everything else
    stays on ICI (hybrid mesh, scaling-book recipe).
    """
    config = config or MeshConfig()
    if dcn_data_parallel and dcn_data_parallel > 1:
        from jax.experimental import mesh_utils
        n = jax.device_count()
        shape = config.resolve(n)
        ici_shape = (shape[0] // dcn_data_parallel,) + shape[1:]
        dcn_shape = (dcn_data_parallel,) + (1,) * (len(ALL_AXES) - 1)
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape)
        return Mesh(devices, ALL_AXES)
    shape = config.resolve(jax.device_count())
    arr = np.asarray(jax.devices()).reshape(shape)
    return Mesh(arr, ALL_AXES)


def barrier(name: str = "barrier"):
    """Host-level sync point (the reference's waitPassStart/Finish RPCs,
    ParameterService.proto:90-114)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)
