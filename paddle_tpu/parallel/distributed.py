"""Multi-host distributed runtime.

Replaces the reference's distributed backend bring-up — paddle_pserver
processes + --pservers/--trainer_id/--num_gradient_servers wiring
(pserver/ParameterServerController.cpp:65, trainer/TrainerMain.cpp:39-44,
scripts/cluster_train/paddle.py:101-176) — with JAX's multi-controller
SPMD runtime: every host runs the same program, jax.distributed.initialize
connects them, and the global mesh spans all hosts' devices.  Gradient
exchange is the psum XLA inserts from shardings: over ICI within a slice,
over DCN between slices — no parameter server, no sockets to manage.

Env-var contract (also used by the cluster launcher):
  PADDLE_TPU_COORDINATOR   host:port of process 0
  PADDLE_TPU_NUM_PROCESSES world size
  PADDLE_TPU_PROCESS_ID    this process's rank
(standard TPU-pod deployments can omit all three: jax.distributed.
initialize() autodetects from the TPU metadata server.)
"""

import os
from typing import Optional

import numpy as np
import jax

from paddle_tpu.parallel.mesh import ALL_AXES, MeshConfig, Mesh
from paddle_tpu.utils.logging import logger

_initialized = [False]


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None):
    """Connect this host into the multi-host runtime (idempotent).

    With no arguments, reads the PADDLE_TPU_* env vars; with none set on a
    TPU pod, defers to JAX's autodetection."""
    if _initialized[0]:
        return
    coordinator = coordinator or os.environ.get("PADDLE_TPU_COORDINATOR")
    if num_processes is None and "PADDLE_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PADDLE_TPU_NUM_PROCESSES"])
    if process_id is None and "PADDLE_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PADDLE_TPU_PROCESS_ID"])
    kw = {}
    if coordinator:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if local_device_ids is not None:
        kw["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kw)
    _initialized[0] = True
    logger.info("distributed: process %d/%d, %d local + %d global devices",
                jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def is_coordinator() -> bool:
    return jax.process_index() == 0


def global_mesh(config: Optional[MeshConfig] = None,
                dcn_data_parallel: Optional[int] = None) -> Mesh:
    """Mesh over ALL hosts' devices.

    dcn_data_parallel: number of slices connected by DCN (defaults to
    jax.process_count() on multi-slice deployments when set); the 'data'
    axis is laid out so its outer factor crosses DCN and everything else
    stays on ICI (hybrid mesh, scaling-book recipe).
    """
    config = config or MeshConfig()
    if dcn_data_parallel and dcn_data_parallel > 1:
        from jax.experimental import mesh_utils
        n = jax.device_count()
        shape = config.resolve(n)
        ici_shape = (shape[0] // dcn_data_parallel,) + shape[1:]
        dcn_shape = (dcn_data_parallel,) + (1,) * (len(ALL_AXES) - 1)
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape)
        return Mesh(devices, ALL_AXES)
    shape = config.resolve(jax.device_count())
    arr = np.asarray(jax.devices()).reshape(shape)
    return Mesh(arr, ALL_AXES)


def barrier(name: str = "barrier"):
    """Host-level sync point (the reference's waitPassStart/Finish RPCs,
    ParameterService.proto:90-114)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def step_skew_report(durations, name="train_step"):
    """Cross-rank straggler/skew report — the SPMD successor to the
    reference's per-trainer BarrierStat arrival profiling
    (utils/BarrierStat.h:196-273, logged per --log_period_server).

    In synchronous SPMD the collectives themselves equalize device time,
    so the straggler signal lives in each rank's HOST-side step wall
    time (input pipeline, Python dispatch, H2D feeds): a rank that
    arrives late at its next collective stalls every other rank.  Each
    rank passes its recent per-step wall durations (seconds); the stats
    are all-gathered (so this is a COLLECTIVE — every rank must call it
    at the same step, even with an empty window: the gather always runs,
    so ranks can't deadlock on divergent emptiness) and every rank
    returns the same report string; the coordinator also logs it.
    Returns None when every rank's window was empty."""
    durations = np.asarray(durations, np.float64).reshape(-1)
    if durations.size:
        local = np.asarray([
            float(np.percentile(durations, 50)),
            float(np.percentile(durations, 99)),
            float(np.mean(durations)),
            float(durations.size)], np.float32)
    else:
        local = np.zeros((4,), np.float32)
    if jax.process_count() == 1:
        all_stats = local[None]
    else:
        from jax.experimental import multihost_utils
        all_stats = np.asarray(multihost_utils.process_allgather(local))
    have = all_stats[:, 3] > 0
    if not have.any():
        return None
    p50s, p99s = all_stats[:, 0], all_stats[:, 1]
    # ranks with an empty window are reported but excluded from the
    # min/argmax/spread stats (their zeros would poison all three)
    slowest = int(np.argmax(np.where(have, p50s, -np.inf)))
    lo = max(float(p50s[have].min()), 1e-9)
    spread_pct = (float(p50s[have].max()) - float(p50s[have].min())) \
        / lo * 100.0
    per_rank = " ".join(
        f"r{i}[p50={p * 1e3:.1f}ms p99={q * 1e3:.1f}ms]" if h else f"r{i}[--]"
        for i, (p, q, h) in enumerate(zip(p50s, p99s, have)))
    report = (f"{name} skew ({int(all_stats[:, 3].max())} steps/rank): "
              f"{per_rank} | slowest=r{slowest} p50-spread={spread_pct:.0f}%")
    if is_coordinator():
        logger.info(report)
    return report
