"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

New capability relative to the reference (SURVEY.md §5 "Long-context ...
the reference has no equivalent, so this is green-field").  Design follows
the ring-attention pattern: shard the sequence across devices, keep Q local,
rotate K/V blocks around the ring with `lax.ppermute` while maintaining a
numerically-stable running softmax (flash-style m/l accumulators), so peak
memory is O(T/n) per device and comm overlaps compute around the ICI ring.

Also provides all_to_all sequence<->head resharding (DeepSpeed-Ulysses
style) as an alternative strategy for models whose head count divides the
mesh axis.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30


def _block_attn(q, k, v, m_prev, l_prev, acc, mask=None, scale=1.0):
    """One K/V block of flash-style attention — delegates to the shared
    accumulation in ops.attention so the delicate m/l/acc math lives in
    exactly one place."""
    from paddle_tpu.ops.attention import online_softmax_block
    return online_softmax_block(q, k, v, m_prev, l_prev, acc, mask=mask,
                                scale=scale)


def ring_attention(q, k, v, mesh: Mesh, axis_name="seq", causal=False,
                   q_mask=None, kv_mask=None, scale=None):
    """Sequence-parallel attention under shard_map.

    q/k/v: [B, H, T, D] GLOBAL shapes, sharded over T on `axis_name`
    (caller annotates; this function builds its own shard_map).
    q_mask/kv_mask: [B, T] validity (global, sharded the same way).
    Returns [B, H, T, D] sharded like q.
    """
    n = mesh.shape[axis_name]
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)

    def local_fn(q_l, k_l, v_l, qm_l, kvm_l):
        # local shapes: [B, H, T/n, D]
        b, h, tq, d = q_l.shape
        my = jax.lax.axis_index(axis_name)

        def body(i, carry):
            m, l, acc, k_blk, v_blk, kvm_blk = carry
            # block owner index: blocks travel forward, so at step i we hold
            # the block originally on device (my - i) mod n
            src = (my - i) % n

            def attend(carry):
                m, l, acc = carry
                # mask built INSIDE the branch: a skipped block must not
                # pay for its [tq, tq] causal mask either
                mask = None
                if kvm_blk is not None:
                    mask = kvm_blk[:, None, None, :] > 0
                if causal:
                    # global positions: q = my*tq + iq ; k = src*tq + ik
                    qpos = my * tq + jnp.arange(tq)
                    kpos = src * tq + jnp.arange(tq)
                    cm = (qpos[:, None] >= kpos[None, :])[None, None]
                    mask = cm if mask is None else (mask & cm)
                return _block_attn(q_l, k_blk, v_blk, m, l, acc, mask,
                                   scale)
            if causal:
                # skip blocks entirely above the diagonal.  NOTE: with the
                # contiguous T sharding used here this saves FLOPs/energy
                # on the idle devices, NOT wall-clock — the ring is
                # synchronous, so each step runs at the speed of its
                # busiest device (balanced zigzag/striped sharding would
                # convert the skip into ~2x throughput; future work).  The
                # ppermute below still runs so the ring stays in step.
                needed = (my * tq + tq - 1) >= (src * tq)
                m, l, acc = jax.lax.cond(needed, attend,
                                         lambda c: c, (m, l, acc))
            else:
                m, l, acc = attend((m, l, acc))
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if kvm_blk is not None:
                kvm_blk = jax.lax.ppermute(kvm_blk, axis_name, perm)
            return m, l, acc, k_blk, v_blk, kvm_blk

        m0 = jnp.full((b, h, tq), _NEG, jnp.float32)
        l0 = jnp.zeros((b, h, tq), jnp.float32)
        acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
        m, l, acc, _, _, _ = jax.lax.fori_loop(
            0, n, body, (m0, l0, acc0, k_l, v_l, kvm_l))
        out = acc / jnp.maximum(l[..., None], 1e-20)
        if qm_l is not None:
            out = out * (qm_l[:, None, :, None] > 0)
        return out.astype(q_l.dtype)

    spec = P(None, None, axis_name, None)
    mspec = P(None, axis_name)
    qm = q_mask if q_mask is not None else jnp.ones(
        (q.shape[0], q.shape[2]), jnp.float32)
    kvm = kv_mask if kv_mask is not None else jnp.ones(
        (k.shape[0], k.shape[2]), jnp.float32)
    fn = jax.shard_map(local_fn, mesh=mesh,
                       in_specs=(spec, spec, spec, mspec, mspec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, qm, kvm)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name="seq", causal=False,
                      mask=None):
    """All-to-all sequence parallelism (Ulysses): reshard [B,H,T/n,D] ->
    [B,H/n,T,D] with all_to_all, run full attention over local heads, then
    reshard back.  Requires H % n == 0."""
    n = mesh.shape[axis_name]
    assert q.shape[1] % n == 0, "heads must divide the seq axis"

    def local_fn(q_l, k_l, v_l):
        # local [B, H, T/n, D] -> [B, H/n, T, D]
        def reshard_fwd(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        def reshard_bwd(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        from paddle_tpu.ops.attention import dot_product_attention
        qh, kh, vh = reshard_fwd(q_l), reshard_fwd(k_l), reshard_fwd(v_l)
        out = dot_product_attention(qh, kh, vh, causal=causal)
        return reshard_bwd(out)

    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
