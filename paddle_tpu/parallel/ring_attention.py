"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

New capability relative to the reference (SURVEY.md §5 "Long-context ...
the reference has no equivalent, so this is green-field").  Design follows
the ring-attention pattern: shard the sequence across devices, keep Q local,
rotate K/V blocks around the ring with `lax.ppermute` while maintaining a
numerically-stable running softmax (flash-style m/l accumulators), so peak
memory is O(T/n) per device and comm overlaps compute around the ICI ring.

Also provides all_to_all sequence<->head resharding (DeepSpeed-Ulysses
style) as an alternative strategy for models whose head count divides the
mesh axis.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.ops.attention import repeat_kv_heads
from paddle_tpu.parallel.sharding import shard_map

_NEG = -1e30


def _block_attn(q, k, v, m_prev, l_prev, acc, mask=None, scale=1.0):
    """One K/V block of flash-style attention — delegates to the shared
    accumulation in ops.attention so the delicate m/l/acc math lives in
    exactly one place."""
    from paddle_tpu.ops.attention import online_softmax_block
    return online_softmax_block(q, k, v, m_prev, l_prev, acc, mask=mask,
                                scale=scale)


def _kv_group(q, k):
    """Query heads per KV head (GQA): the ring carries k/v GROUPED —
    [B, Hkv, T/n, D] travels each ppermute hop, shrinking ring traffic
    by H/Hkv vs repeating to full width before dispatch — and each hop
    expands the received stripe in registers via the shared
    ``ops.attention.repeat_kv_heads`` right before its
    block-attention.  Fail-fast validation only; the expansion itself
    has ONE implementation."""
    h, hkv = q.shape[1], k.shape[1]
    if hkv < 1 or h % hkv:
        raise ValueError(f"query heads {h} not a multiple of KV heads "
                         f"{hkv} — not a grouped-KV layout")
    return h // hkv


def _resolve_segments(q, k, q_segment_ids, kv_segment_ids):
    """Shared validation/defaulting for the segment-packed ring paths.

    Returns (segmented, q_seg, kv_seg) where the seg arrays are int32
    [B, T] (zeros when unsegmented, so shard_map specs stay static).
    Semantics match ops.attention.chunked_attention: q attends k iff
    labels are equal — padding (label 0) only ever matches padding, so
    real queries never see padded keys and padded query rows produce
    garbage that masked losses drop."""
    if kv_segment_ids is not None and q_segment_ids is None:
        raise ValueError(
            "kv_segment_ids without q_segment_ids: label the query side "
            "too (a lone KV labeling would be silently dropped)")
    segmented = q_segment_ids is not None
    if not segmented:
        return (False, jnp.zeros((q.shape[0], q.shape[2]), jnp.int32),
                jnp.zeros((k.shape[0], k.shape[2]), jnp.int32))
    if kv_segment_ids is None and k.shape[2] != q.shape[2]:
        raise ValueError(
            "q_segment_ids with Tq != Tk needs explicit kv_segment_ids")
    return (True, q_segment_ids.astype(jnp.int32),
            (q_segment_ids if kv_segment_ids is None
             else kv_segment_ids).astype(jnp.int32))


def ring_attention(q, k, v, mesh: Mesh, axis_name="seq", causal=False,
                   q_mask=None, kv_mask=None, scale=None,
                   q_segment_ids=None, kv_segment_ids=None):
    """Sequence-parallel attention under shard_map.

    q: [B, H, T, D]; k/v: [B, Hkv, T, D] GLOBAL shapes, sharded over T
    on `axis_name` (caller annotates; this function builds its own
    shard_map).  Hkv may be a DIVISOR of H (grouped-query attention):
    the grouped stripes travel the ppermute ring as-is — H/Hkv less
    ring traffic than pre-repeating — and expand per hop in registers.
    q_mask/kv_mask: [B, T] validity (global, sharded the same way).
    q_segment_ids/kv_segment_ids: [B, T] int labels for PACKED rows
    (core.sequence.pack_sequences) — the KV labels rotate around the
    ring with K/V and attention stays block-diagonal per segment, so
    long-context sharding composes with padding-free packing.
    Returns [B, H, T, D] sharded like q.
    """
    n = mesh.shape[axis_name]
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    _kv_group(q, k)
    segmented, q_seg, kv_seg = _resolve_segments(
        q, k, q_segment_ids, kv_segment_ids)

    def local_fn(q_l, k_l, v_l, qm_l, kvm_l, qseg_l, kvseg_l):
        # local shapes: q [B, H, T/n, D]; k/v [B, Hkv, T/n, D] (grouped
        # KV rides the ring; expanded per hop at the attend below)
        b, h, tq, d = q_l.shape
        my = jax.lax.axis_index(axis_name)

        def body(i, carry):
            m, l, acc, k_blk, v_blk, kvm_blk, kvseg_blk = carry
            # block owner index: blocks travel forward, so at step i we hold
            # the block originally on device (my - i) mod n
            src = (my - i) % n

            def attend(carry):
                m, l, acc = carry
                # mask built INSIDE the branch: a skipped block must not
                # pay for its [tq, tq] causal mask either
                mask = None
                if kvm_blk is not None:
                    mask = kvm_blk[:, None, None, :] > 0
                if segmented:
                    sm = (qseg_l[:, :, None]
                          == kvseg_blk[:, None, :])[:, None]
                    mask = sm if mask is None else (mask & sm)
                if causal:
                    # global positions: q = my*tq + iq ; k = src*tq + ik
                    qpos = my * tq + jnp.arange(tq)
                    kpos = src * tq + jnp.arange(tq)
                    cm = (qpos[:, None] >= kpos[None, :])[None, None]
                    mask = cm if mask is None else (mask & cm)
                return _block_attn(q_l, repeat_kv_heads(k_blk, h),
                                   repeat_kv_heads(v_blk, h), m, l, acc,
                                   mask, scale)
            if causal:
                # skip blocks entirely above the diagonal.  NOTE: with the
                # contiguous T sharding used here this saves FLOPs/energy
                # on the idle devices, NOT wall-clock — the ring is
                # synchronous, so each step runs at the speed of its
                # busiest device.  ring_attention_zigzag below converts
                # the skip into real ~2x throughput via balanced
                # sharding; this plain variant stays for non-causal and
                # layout-constrained callers.  The ppermute below still
                # runs so the ring stays in step.
                needed = (my * tq + tq - 1) >= (src * tq)
                m, l, acc = jax.lax.cond(needed, attend,
                                         lambda c: c, (m, l, acc))
            else:
                m, l, acc = attend((m, l, acc))
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if kvm_blk is not None:
                kvm_blk = jax.lax.ppermute(kvm_blk, axis_name, perm)
            if segmented:
                # KV labels travel with their K/V block (unsegmented runs
                # keep the dummy carry but skip the rotation)
                kvseg_blk = jax.lax.ppermute(kvseg_blk, axis_name, perm)
            return m, l, acc, k_blk, v_blk, kvm_blk, kvseg_blk

        m0 = jnp.full((b, h, tq), _NEG, jnp.float32)
        l0 = jnp.zeros((b, h, tq), jnp.float32)
        acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(
            0, n, body, (m0, l0, acc0, k_l, v_l, kvm_l, kvseg_l))[:3]
        out = acc / jnp.maximum(l[..., None], 1e-20)
        if qm_l is not None:
            out = out * (qm_l[:, None, :, None] > 0)
        return out.astype(q_l.dtype)

    spec = P(None, None, axis_name, None)
    mspec = P(None, axis_name)
    qm = q_mask if q_mask is not None else jnp.ones(
        (q.shape[0], q.shape[2]), jnp.float32)
    kvm = kv_mask if kv_mask is not None else jnp.ones(
        (k.shape[0], k.shape[2]), jnp.float32)
    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(spec, spec, spec, mspec, mspec,
                                 mspec, mspec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, qm, kvm, q_seg, kv_seg)


def zigzag_order(t_global, n):
    """Permutation original->zigzag storage: device d's contiguous shard
    holds original chunks (d, 2n-1-d), each of length T/(2n).  Under this
    layout every device owns one early and one late chunk, so causal ring
    attention does the SAME work per device per step (see
    ring_attention_zigzag) — the load balance contiguous sharding lacks."""
    import numpy as np
    if t_global % (2 * n):
        raise ValueError(f"zigzag needs T % {2 * n} == 0, got {t_global}")
    chunk = t_global // (2 * n)
    idx = []
    for d in range(n):
        idx.extend(range(d * chunk, (d + 1) * chunk))
        idx.extend(range((2 * n - 1 - d) * chunk, (2 * n - d) * chunk))
    return np.asarray(idx)


def zigzag_permute(x, n, axis=2):
    """Reorder the global T axis into zigzag storage layout."""
    return jnp.take(x, jnp.asarray(zigzag_order(x.shape[axis], n)),
                    axis=axis)


def zigzag_unpermute(x, n, axis=2):
    import numpy as np
    order = zigzag_order(x.shape[axis], n)
    return jnp.take(x, jnp.asarray(np.argsort(order)), axis=axis)


def ring_attention_zigzag(q, k, v, mesh: Mesh, axis_name="seq",
                          q_mask=None, kv_mask=None, scale=None,
                          q_segment_ids=None, kv_segment_ids=None):
    """CAUSAL ring attention over zigzag-ordered sequences: the balanced
    long-context training plane.

    Contiguous sharding makes causal ring steps degenerate — device 0
    skips n-1 of n blocks while device n-1 computes all of them, so the
    block skip saves FLOPs but no wall-clock.  Zigzag gives device d
    original chunks (d, 2n-1-d): per ring step each device attends
    exactly ~2 half-blocks (qhi x klo always; qlo x klo when my >= src;
    qhi x khi when src >= my — one of the two, both triangular at
    my == src), halving causal attention cost AND balancing it, so the
    saving is real throughput.

    q: [B, H, T, D]; k/v: [B, Hkv, T, D] (Hkv | H — grouped KV travels
    the ring, expanded per hop like ring_attention) GLOBAL, already
    zigzag_permute'd and sharded over T on `axis_name`; q_mask/kv_mask
    [B, T] likewise (q_mask zeroes padded query rows, matching
    ring_attention).
    q_segment_ids/kv_segment_ids: [B, T] PACKED-row labels, zigzag-
    permuted like everything else — the segment-equality mask depends
    only on label pairs, so it composes with any storage order, and the
    causal comparison uses original global positions (pos()), which stay
    correct for contiguous packed segments.  Returns zigzag-ordered
    output sharded like q (zigzag_unpermute to restore order)."""
    n = mesh.shape[axis_name]
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    _kv_group(q, k)
    segmented, q_seg, kv_seg = _resolve_segments(
        q, k, q_segment_ids, kv_segment_ids)

    def local_fn(q_l, k_l, v_l, qm_l, kvm_l, qseg_l, kvseg_l):
        # q [B, H, T/n, D]; k/v [B, Hkv, T/n, D] — grouped KV rides the
        # ring, expanded per half-block at the attends below
        b, h, tq, d = q_l.shape
        half = tq // 2
        my = jax.lax.axis_index(axis_name)

        def pos(chunk_id):
            return chunk_id * half + jnp.arange(half)

        def split(t, ax):
            lo = jax.lax.slice_in_dim(t, 0, half, axis=ax)
            hi = jax.lax.slice_in_dim(t, half, tq, axis=ax)
            return lo, hi

        def body(i, carry):
            (mlo, llo, alo, mhi, lhi, ahi,
             k_blk, v_blk, kvm_blk, kvseg_blk) = carry
            src = (my - i) % n
            klo, khi = split(k_blk, 2)
            vlo, vhi = split(v_blk, 2)
            kmlo, kmhi = split(kvm_blk, 1)
            kslo, kshi = split(kvseg_blk, 1)
            qlo, qhi = split(q_l, 2)
            qslo, qshi = split(qseg_l, 1)
            q_chunk = (my, 2 * n - 1 - my)
            k_chunk = (src, 2 * n - 1 - src)

            def attend(qc, kc, q_, k_, v_, km_, qs_, ks_, carry,
                       need_causal=True):
                m, l, acc = carry
                mask = km_[:, None, None, :] > 0
                if segmented:
                    mask = mask & (qs_[:, :, None]
                                   == ks_[:, None, :])[:, None]
                if need_causal:
                    cm = pos(qc)[:, None] >= pos(kc)[None, :]
                    mask = mask & cm[None, None]
                return _block_attn(q_, repeat_kv_heads(k_, h),
                                   repeat_kv_heads(v_, h), m, l, acc,
                                   mask, scale)

            # qhi x klo: always fully below the diagonal — padding mask
            # only, no causal comparison to build
            mhi, lhi, ahi = attend(q_chunk[1], k_chunk[0], qhi, klo, vlo,
                                   kmlo, qshi, kslo, (mhi, lhi, ahi),
                                   need_causal=False)
            # qlo x klo: needed iff my >= src
            mlo, llo, alo = jax.lax.cond(
                my >= src,
                lambda c: attend(q_chunk[0], k_chunk[0], qlo, klo, vlo,
                                 kmlo, qslo, kslo, c),
                lambda c: c, (mlo, llo, alo))
            # qhi x khi: needed iff src >= my
            mhi, lhi, ahi = jax.lax.cond(
                src >= my,
                lambda c: attend(q_chunk[1], k_chunk[1], qhi, khi, vhi,
                                 kmhi, qshi, kshi, c),
                lambda c: c, (mhi, lhi, ahi))

            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            kvm_blk = jax.lax.ppermute(kvm_blk, axis_name, perm)
            if segmented:
                kvseg_blk = jax.lax.ppermute(kvseg_blk, axis_name, perm)
            return (mlo, llo, alo, mhi, lhi, ahi, k_blk, v_blk, kvm_blk,
                    kvseg_blk)

        def init(hl):
            return (jnp.full((b, h, hl), _NEG, jnp.float32),
                    jnp.zeros((b, h, hl), jnp.float32),
                    jnp.zeros((b, h, hl, d), jnp.float32))

        (mlo, llo, alo), (mhi, lhi, ahi) = init(half), init(half)
        out = jax.lax.fori_loop(
            0, n, body,
            (mlo, llo, alo, mhi, lhi, ahi, k_l, v_l, kvm_l, kvseg_l))
        mlo, llo, alo, mhi, lhi, ahi = out[:6]
        olo = alo / jnp.maximum(llo[..., None], 1e-20)
        ohi = ahi / jnp.maximum(lhi[..., None], 1e-20)
        o = jnp.concatenate([olo, ohi], axis=2)
        # padded query rows come back zeroed, matching ring_attention
        return (o * (qm_l[:, None, :, None] > 0)).astype(q_l.dtype)

    spec = P(None, None, axis_name, None)
    mspec = P(None, axis_name)
    qm = q_mask if q_mask is not None else jnp.ones(
        (q.shape[0], q.shape[2]), jnp.float32)
    kvm = kv_mask if kv_mask is not None else jnp.ones(
        (k.shape[0], k.shape[2]), jnp.float32)
    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(spec, spec, spec, mspec, mspec,
                                 mspec, mspec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v, qm, kvm, q_seg, kv_seg)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name="seq", causal=False,
                      mask=None):
    """All-to-all sequence parallelism (Ulysses): reshard [B,H,T/n,D] ->
    [B,H/n,T,D] with all_to_all, run full attention over local heads, then
    reshard back.  Requires H % n == 0."""
    n = mesh.shape[axis_name]
    assert q.shape[1] % n == 0, "heads must divide the seq axis"

    def local_fn(q_l, k_l, v_l):
        # local [B, H, T/n, D] -> [B, H/n, T, D]
        def reshard_fwd(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        def reshard_bwd(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        from paddle_tpu.ops.attention import dot_product_attention
        qh, kh, vh = reshard_fwd(q_l), reshard_fwd(k_l), reshard_fwd(v_l)
        out = dot_product_attention(qh, kh, vh, causal=causal)
        return reshard_bwd(out)

    spec = P(None, None, axis_name, None)
    fn = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
