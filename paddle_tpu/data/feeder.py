"""DataFeeder: host samples -> device-ready arrays / SequenceBatches.

Reference: python/paddle/v2/data_feeder.py + py_paddle
dataprovider_converter.py (numpy -> Arguments with sequenceStartPositions).
TPU design: pack ragged samples into padded SequenceBatch with
bucketed max_len (static shapes for XLA; see core.sequence.bucket_boundaries)
and densify sparse vectors (sparse input becomes dense rows or id lists —
the embedding path takes ids, the MXU path takes dense).
"""

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.sequence import (
    SequenceBatch, pad_sequences, pad_nested_sequences, bucket_for)
from paddle_tpu.data.provider import InputType, SeqType
from paddle_tpu import native


def _pad_int_seqs(seqs, max_len):
    """Native fast path for the hot ragged-int packing loop."""
    if native.is_available():
        out, lens = native.pack_i32(seqs, max_len=max_len)
        return SequenceBatch(data=jnp.asarray(out), lengths=jnp.asarray(lens))
    return pad_sequences(seqs, max_len=max_len)


def _pad_f32_seqs(seqs, max_len):
    if native.is_available() and seqs and seqs[0].ndim == 2:
        out, lens = native.pack_f32(seqs, max_len=max_len)
        return SequenceBatch(data=jnp.asarray(out), lengths=jnp.asarray(lens))
    return pad_sequences(seqs, max_len=max_len)


class DataFeeder:
    def __init__(self, feeding, bucket_bounds=None, pad_batch_to=None):
        """feeding: {name: InputType} or {name: index} paired with types.

        bucket_bounds: optional list of allowed padded lengths (per name or
        shared) to bound XLA recompilation.  Stored sorted; sequences
        LONGER than the largest bound are truncated to it — warned once.
        pad_batch_to: optional fixed batch size (pads short final batches).
        """
        self.feeding = feeding
        self.bucket_bounds = sorted(bucket_bounds) if bucket_bounds else None
        self.pad_batch_to = pad_batch_to
        self._warned_truncate = set()   # slot names already warned

    def _convert_one(self, name, itype: InputType, columns):
        # py2-era providers yield lazy iterables (map objects etc.)
        columns = [list(c) if not isinstance(c, (list, tuple, np.ndarray,
                                                 int, float, np.integer))
                   and hasattr(c, "__iter__") else c for c in columns]
        if itype.seq_type == SeqType.NO_SEQUENCE:
            if itype.kind == "index":
                return np.asarray(columns, dtype=np.int32).reshape(len(columns))
            if itype.kind == "dense":
                return np.asarray(columns, dtype=np.float32)
            if itype.kind in ("sparse_binary", "sparse_float"):
                out = np.zeros((len(columns), itype.dim), np.float32)
                for i, ids in enumerate(columns):
                    if itype.kind == "sparse_binary":
                        out[i, np.asarray(ids, np.int64)] = 1.0
                    else:
                        for j, v in ids:
                            out[i, j] = v
                return out
        elif itype.seq_type == SeqType.SEQUENCE:
            if itype.kind == "index":
                seqs = [np.asarray(s, np.int32) for s in columns]
            elif itype.kind == "dense":
                seqs = [np.asarray(s, np.float32) for s in columns]
            elif itype.kind == "sparse_binary":
                seqs = []
                for s in columns:
                    rows = np.zeros((len(s), itype.dim), np.float32)
                    for t, ids in enumerate(s):
                        rows[t, np.asarray(ids, np.int64)] = 1.0
                    seqs.append(rows)
            else:
                seqs = []
                for s in columns:
                    rows = np.zeros((len(s), itype.dim), np.float32)
                    for t, pairs in enumerate(s):
                        for j, v in pairs:
                            rows[t, j] = v
                    seqs.append(rows)
            max_len = max(len(s) for s in seqs)
            if self.bucket_bounds:
                if max_len > self.bucket_bounds[-1] \
                        and name not in self._warned_truncate:
                    self._warned_truncate.add(name)
                    from paddle_tpu.utils.logging import logger
                    logger.warning(
                        "DataFeeder: %r sequences of length %d exceed the "
                        "largest bucket (%d) and are TRUNCATED to it; raise "
                        "the bucket bounds if this is not intended",
                        name, max_len, self.bucket_bounds[-1])
                max_len = bucket_for(max_len, self.bucket_bounds)
            if itype.kind == "index":
                return _pad_int_seqs(seqs, max_len)
            return _pad_f32_seqs(seqs, max_len)
        else:  # SUB_SEQUENCE
            nested = [[np.asarray(sub, np.int32 if itype.kind == "index"
                                  else np.float32) for sub in s]
                      for s in columns]
            return pad_nested_sequences(nested)
        raise ValueError(f"unsupported input type {itype}")

    def feed_specs(self, batch_size, bucket_bounds=None):
        """Abstract feed shapes for AOT warm-up (``SGD.precompile``).

        Returns one feed dict of ``jax.ShapeDtypeStruct`` leaves per
        combination of padded sequence lengths from ``bucket_bounds``
        (default: this feeder's own bounds; pick them with
        ``core.sequence.bucket_boundaries``), mirroring exactly the
        shapes/dtypes ``__call__`` produces for a full batch of
        ``batch_size`` padded to those buckets.  ``__call__`` buckets
        every SEQUENCE slot independently, so with S sequence slots and K
        bounds this is the full K**S cross-product — a seq2seq batch with
        short sources and long targets still hits a precompiled shape.
        With no sequence slots the result is a single spec (shapes don't
        depend on the bucket).
        """
        from itertools import product

        import jax
        from paddle_tpu.core.sequence import SequenceBatch as _SB

        bounds = bucket_bounds if bucket_bounds is not None \
            else self.bucket_bounds
        seq_names = [n for n, t in self.feeding.items()
                     if t.seq_type != SeqType.NO_SEQUENCE]
        if seq_names and not bounds:
            raise ValueError(
                "feed_specs: sequence slots need bucket_bounds (the "
                "padded lengths to precompile for; see "
                "core.sequence.bucket_boundaries)")
        b = int(self.pad_batch_to or batch_size)

        def one(lens):
            feed = {}
            for name, itype in self.feeding.items():
                if itype.seq_type == SeqType.NO_SEQUENCE:
                    if itype.kind == "index":
                        feed[name] = jax.ShapeDtypeStruct((b,), np.int32)
                    else:       # dense / densified sparse -> [B, dim] f32
                        feed[name] = jax.ShapeDtypeStruct(
                            (b, itype.dim), np.float32)
                elif itype.seq_type == SeqType.SEQUENCE:
                    max_len = lens[name]
                    if itype.kind == "index":
                        data = jax.ShapeDtypeStruct((b, max_len), np.int32)
                    else:
                        data = jax.ShapeDtypeStruct((b, max_len, itype.dim),
                                                    np.float32)
                    feed[name] = _SB(
                        data=data,
                        lengths=jax.ShapeDtypeStruct((b,), np.int32))
                else:
                    raise ValueError(
                        f"feed_specs: SUB_SEQUENCE slot {name!r} has no "
                        "static bucket shape (nested max lengths are "
                        "data-dependent); precompile with a concrete "
                        "example feed instead")
            return feed

        if not seq_names:
            return [one({})]
        return [one(dict(zip(seq_names, combo)))
                for combo in product(sorted(int(m) for m in bounds),
                                     repeat=len(seq_names))]

    def __call__(self, batch):
        """batch: list of dicts {name: sample} or tuples in feeding order."""
        names = list(self.feeding)
        if self.pad_batch_to and len(batch) < self.pad_batch_to:
            batch = list(batch) + [batch[-1]] * (self.pad_batch_to - len(batch))
        feed = {}
        for idx, name in enumerate(names):
            itype = self.feeding[name]
            if isinstance(batch[0], dict):
                columns = [b[name] for b in batch]
            else:
                columns = [b[idx] for b in batch]
            feed[name] = self._convert_one(name, itype, columns)
        return feed
