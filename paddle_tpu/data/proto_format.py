"""Reader/writer for the reference's binary proto data format.

Reference: proto/DataFormat.proto:1 (DataHeader / DataSample / VectorSlot /
SubseqSlot) framed as varint32-length-delimited proto2 messages
(gserver/dataproviders/ProtoReader.h:95-102: ReadVarint32 then a
PushLimit'd ParseFromCodedStream), gzip-wrapped when the filename ends in
.gz (ProtoDataProvider.cpp:213).  First message is the DataHeader; every
following message is one DataSample.

Implemented directly on the proto2 wire format (the three messages use
only varint, fixed32-packed and length-delimited fields), so reference
data files are readable without protoc or generated bindings.

Slot payloads per SlotType (DataFormat.proto:44-55):
  VECTOR_DENSE            -> float32[dim]
  VECTOR_SPARSE_NON_VALUE -> uint32 id list
  VECTOR_SPARSE_VALUE     -> (ids, values) pair of equal-length lists
  INDEX                   -> int
  VAR_MDIM_DENSE          -> float32 array reshaped to dims (if given)
  VAR_MDIM_INDEX          -> uint32 id list (from var_id_slots)
  STRING                  -> str
"""

import gzip
import struct

import numpy as np

from paddle_tpu.utils.error import ConfigError

# SlotDef.SlotType (DataFormat.proto:45-53)
VECTOR_DENSE = 0
VECTOR_SPARSE_NON_VALUE = 1
VECTOR_SPARSE_VALUE = 2
INDEX = 3
VAR_MDIM_DENSE = 4
VAR_MDIM_INDEX = 5
STRING = 6

_WIRE_VARINT = 0
_WIRE_F64 = 1
_WIRE_LEN = 2
_WIRE_F32 = 5


# --------------------------------------------------------------- wire level

def _read_varint(buf, pos):
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise ConfigError("proto data: truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ConfigError("proto data: varint too long")


def _write_varint(out, value):
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fields(buf):
    """Iterate (field_number, wire_type, value) over a message buffer.
    LEN fields yield memoryview payloads; varints yield ints; F32 raw."""
    pos = 0
    mv = memoryview(buf)
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            val, pos = _read_varint(buf, pos)
        elif wire == _WIRE_LEN:
            n, pos = _read_varint(buf, pos)
            if pos + n > len(buf):
                raise ConfigError("proto data: truncated field payload")
            val = mv[pos:pos + n]
            pos += n
        elif wire == _WIRE_F32:
            if pos + 4 > len(buf):
                raise ConfigError("proto data: truncated fixed32 field")
            val = mv[pos:pos + 4]
            pos += 4
        elif wire == _WIRE_F64:
            if pos + 8 > len(buf):
                raise ConfigError("proto data: truncated fixed64 field")
            val = mv[pos:pos + 8]
            pos += 8
        else:
            raise ConfigError(f"proto data: unsupported wire type {wire}")
        yield field, wire, val


def _packed_varints(payload):
    out, pos = [], 0
    buf = bytes(payload)
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        out.append(v)
    return out


def _packed_floats(payload):
    buf = bytes(payload)
    if len(buf) % 4:
        raise ConfigError(
            f"proto data: packed float payload of {len(buf)} bytes is not "
            "a multiple of 4")
    return np.frombuffer(buf, "<f4").copy()


# ------------------------------------------------------------ message level

def _parse_vector_slot(buf):
    values, ids, dims, strs = [], [], [], []
    for field, wire, val in _fields(buf):
        if field == 1:      # values: packed float (or unpacked f32)
            values.extend(_packed_floats(val) if wire == _WIRE_LEN
                          else [struct.unpack("<f", bytes(val))[0]])
        elif field == 2:    # ids: packed uint32
            ids.extend(_packed_varints(val) if wire == _WIRE_LEN else [val])
        elif field == 3:    # dims
            dims.extend(_packed_varints(val) if wire == _WIRE_LEN else [val])
        elif field == 4:    # strs
            strs.append(bytes(val).decode("utf-8", errors="replace"))
    return {"values": np.asarray(values, np.float32), "ids": ids,
            "dims": dims, "strs": strs}


def _parse_subseq_slot(buf):
    slot_id, lens = None, []
    for field, wire, val in _fields(buf):
        if field == 1:
            slot_id = val
        elif field == 2:
            lens.extend(_packed_varints(val) if wire == _WIRE_LEN else [val])
    return {"slot_id": slot_id, "lens": lens}


def parse_header(buf):
    """DataHeader -> [(type, dim), ...]."""
    slot_defs = []
    for field, _wire, val in _fields(buf):
        if field == 1:
            t = d = None
            for f2, _w2, v2 in _fields(val):
                if f2 == 1:
                    t = v2
                elif f2 == 2:
                    d = v2
            if t is None or d is None:
                raise ConfigError("proto data: SlotDef missing type/dim")
            slot_defs.append((t, d))
    if not slot_defs:
        raise ConfigError("proto data: header defines no slots")
    return slot_defs


def parse_sample(buf):
    sample = {"is_beginning": True, "vector_slots": [], "id_slots": [],
              "var_id_slots": [], "subseq_slots": []}
    for field, wire, val in _fields(buf):
        if field == 1:
            sample["is_beginning"] = bool(val)
        elif field == 2:
            sample["vector_slots"].append(_parse_vector_slot(val))
        elif field == 3:
            sample["id_slots"].extend(
                _packed_varints(val) if wire == _WIRE_LEN else [val])
        elif field == 4:
            sample["var_id_slots"].append(_parse_vector_slot(val))
        elif field == 5:
            sample["subseq_slots"].append(_parse_subseq_slot(val))
    return sample


# --------------------------------------------------------------- file level

def _open(path, mode="rb"):
    return gzip.open(path, mode) if str(path).endswith(".gz") \
        else open(path, mode)


def _read_messages(f):
    """Yield varint32-delimited message buffers (ProtoReader framing)."""
    while True:
        # read the varint byte-by-byte: the stream has no lookahead
        size = shift = 0
        first = f.read(1)
        if not first:
            return
        b = first[0]
        while True:
            size |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            nxt = f.read(1)
            if not nxt:
                raise ConfigError("proto data: truncated message size")
            b = nxt[0]
        buf = f.read(size)
        if len(buf) < size:
            raise ConfigError(
                f"proto data: truncated message ({len(buf)}/{size} bytes)")
        yield buf


def _slot_value(slot_type, dim, vec):
    if slot_type == VECTOR_DENSE:
        v = vec["values"]
        if len(v) != dim:
            raise ConfigError(
                f"proto data: dense slot expects {dim} values, got {len(v)}")
        return v
    if slot_type == VECTOR_SPARSE_NON_VALUE:
        return list(vec["ids"])
    if slot_type == VECTOR_SPARSE_VALUE:
        return list(vec["ids"]), list(np.asarray(vec["values"]))
    if slot_type == STRING:
        return vec["strs"][0] if vec["strs"] else ""
    if slot_type == VAR_MDIM_DENSE:
        v = vec["values"]
        return v.reshape(vec["dims"]) if vec["dims"] else v
    raise ConfigError(f"proto data: unhandled slot type {slot_type}")


class ProtoDataFile:
    """One reference data file: .slot_defs [(type, dim)], iter -> samples.

    Iteration yields (values, is_beginning) where values is a tuple with
    one entry per header slot, decoded per the table in the module
    docstring — the shape PyDataProvider2-style readers expect."""

    def __init__(self, path):
        self.path = path
        with _open(path) as f:
            msgs = _read_messages(f)
            try:
                header_buf = next(msgs)
            except StopIteration:
                raise ConfigError(f"proto data {path!r}: empty file")
            self.slot_defs = parse_header(header_buf)

    def __iter__(self):
        n_vec = sum(1 for t, _ in self.slot_defs
                    if t in (VECTOR_DENSE, VECTOR_SPARSE_NON_VALUE,
                             VECTOR_SPARSE_VALUE, VAR_MDIM_DENSE, STRING))
        with _open(self.path) as f:
            msgs = _read_messages(f)
            next(msgs)                      # header
            for buf in msgs:
                s = parse_sample(buf)
                if len(s["vector_slots"]) != n_vec:
                    raise ConfigError(
                        f"proto data {self.path!r}: sample has "
                        f"{len(s['vector_slots'])} vector slots, header "
                        f"declares {n_vec}")
                n_idx = sum(1 for t, _ in self.slot_defs if t == INDEX)
                n_var = sum(1 for t, _ in self.slot_defs
                            if t == VAR_MDIM_INDEX)
                if len(s["id_slots"]) < n_idx \
                        or len(s["var_id_slots"]) < n_var:
                    raise ConfigError(
                        f"proto data {self.path!r}: sample has "
                        f"{len(s['id_slots'])} id / "
                        f"{len(s['var_id_slots'])} var-id slots, header "
                        f"declares {n_idx} INDEX / {n_var} VAR_MDIM_INDEX")
                values = []
                vec_i = idx_i = var_i = 0
                for t, dim in self.slot_defs:
                    if t == INDEX:
                        values.append(int(s["id_slots"][idx_i]))
                        idx_i += 1
                    elif t == VAR_MDIM_INDEX:
                        values.append(list(s["var_id_slots"][var_i]["ids"]))
                        var_i += 1
                    else:
                        values.append(_slot_value(
                            t, dim, s["vector_slots"][vec_i]))
                        vec_i += 1
                yield tuple(values), s["is_beginning"]


def reader_creator(paths):
    """PyDataProvider2-style reader over reference proto data files: yields
    one tuple per SAMPLE (callers needing sequence grouping use
    is_beginning via ProtoDataFile directly)."""
    if isinstance(paths, str):
        paths = [paths]

    def reader():
        for p in paths:
            for values, _beg in ProtoDataFile(p):
                yield values
    return reader


# ------------------------------------------------------------------ writer

def _tag(field, wire):
    return (field << 3) | wire


def _emit_len_field(out, field, payload):
    _write_varint(out, _tag(field, _WIRE_LEN))
    _write_varint(out, len(payload))
    out.extend(payload)


def _emit_vector_slot(values=(), ids=(), dims=(), strs=()):
    out = bytearray()
    if len(values):
        _emit_len_field(out, 1, np.asarray(values, "<f4").tobytes())
    if len(ids):
        pk = bytearray()
        for i in ids:
            _write_varint(pk, int(i))
        _emit_len_field(out, 2, pk)
    if len(dims):
        pk = bytearray()
        for d in dims:
            _write_varint(pk, int(d))
        _emit_len_field(out, 3, pk)
    for s in strs:
        _emit_len_field(out, 4, s.encode("utf-8"))
    return out


def _encode_sample(slot_defs, values, is_beginning):
    msg = bytearray()
    if not is_beginning:
        _write_varint(msg, _tag(1, _WIRE_VARINT))
        _write_varint(msg, 0)
    id_slots = []
    for (t, dim), v in zip(slot_defs, values):
        if t == INDEX:
            id_slots.append(int(v))
        elif t == VAR_MDIM_INDEX:
            _emit_len_field(msg, 4, _emit_vector_slot(ids=v))
        elif t == VECTOR_DENSE:
            _emit_len_field(msg, 2, _emit_vector_slot(values=v))
        elif t == VECTOR_SPARSE_NON_VALUE:
            _emit_len_field(msg, 2, _emit_vector_slot(ids=v))
        elif t == VECTOR_SPARSE_VALUE:
            ids, vals = v
            _emit_len_field(msg, 2, _emit_vector_slot(values=vals, ids=ids))
        elif t == VAR_MDIM_DENSE:
            arr = np.asarray(v, np.float32)
            _emit_len_field(msg, 2, _emit_vector_slot(
                values=arr.reshape(-1), dims=arr.shape))
        elif t == STRING:
            _emit_len_field(msg, 2, _emit_vector_slot(strs=[v]))
        else:
            raise ConfigError(f"write_proto_data: bad slot type {t}")
    if id_slots:
        pk = bytearray()
        for i in id_slots:
            _write_varint(pk, i)
        _emit_len_field(msg, 3, pk)
    return msg


def write_proto_data(path, slot_defs, samples):
    """Write a reference-format data file (for tests and for migrating data
    INTO the reference toolchain).  slot_defs: [(type, dim)]; samples:
    iterable of (values_tuple, is_beginning) shaped like ProtoDataFile
    iteration output.  Samples stream to disk one message at a time, so
    memory stays bounded by a single sample regardless of dataset size."""
    header = bytearray()
    for t, dim in slot_defs:
        sd = bytearray()
        _write_varint(sd, _tag(1, _WIRE_VARINT))
        _write_varint(sd, t)
        _write_varint(sd, _tag(2, _WIRE_VARINT))
        _write_varint(sd, dim)
        _emit_len_field(header, 1, sd)

    with _open(path, "wb") as f:
        def emit(msg):
            size = bytearray()
            _write_varint(size, len(msg))
            f.write(bytes(size))
            f.write(bytes(msg))
        emit(header)
        for values, is_beginning in samples:
            emit(_encode_sample(slot_defs, values, is_beginning))
