"""CoNLL-05 SRL (reference v2/dataset/conll05.py: word/predicate/ctx features
+ IOB label sequence)."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

WORD_DICT = 4000
PRED_DICT = 300
LABEL_KINDS = 19   # span types
NUM_LABELS = 2 * LABEL_KINDS + 1


def get_dict():
    return ({f"w{i}": i for i in range(WORD_DICT)},
            {f"v{i}": i for i in range(PRED_DICT)},
            {f"l{i}": i for i in range(NUM_LABELS)})


def _reader(split, n):
    def reader():
        rng = rng_for("conll05", split)
        for _ in range(n):
            length = int(rng.randint(5, 40))
            words = list(rng.randint(0, WORD_DICT, size=length))
            pred = int(rng.randint(0, PRED_DICT))
            labels = []
            t = 0
            while t < length:
                span = min(int(rng.randint(1, 4)), length - t)
                kind = int(rng.randint(0, LABEL_KINDS))
                labels.extend([2 * kind] + [2 * kind + 1] * (span - 1))
                t += span
            yield words, [pred] * length, labels
    return reader


def train():
    return _reader("train", 1024)


def test():
    return _reader("test", 128)
