"""CoNLL-05 SRL (reference v2/dataset/conll05.py: word/predicate features +
IOB label sequence).

Real data: PADDLE_TPU_DATA_DIR/conll05/ holding the reference layout —
`test.wsj.words.gz` (one token per line, blank line between sentences) and
`test.wsj.props.gz` (star-bracket proposition columns, one per predicate) —
plus optional wordDict.txt / verbDict.txt / targetDict.txt (one entry per
line; built from the data when absent).  Without the files, a synthetic
fallback keeps air-gapped runs working.

Yields (word_ids, [pred_id] * len, label_ids) per (sentence, predicate)
pair, labels in B-X/I-X/O encoding (2*kind / 2*kind+1 / 2*KINDS)."""

import gzip
import os

import numpy as np

from paddle_tpu.data.datasets._synth import local_path, rng_for

WORD_DICT = 4000
PRED_DICT = 300
LABEL_KINDS = 19   # span types
NUM_LABELS = 2 * LABEL_KINDS + 1


def _dir():
    return local_path("conll05")


def _open(name):
    p = os.path.join(_dir(), name)
    return gzip.open(p, "rt") if p.endswith(".gz") else open(p)


def _sentences(words_file, props_file):
    """Parse the words/props pair into (tokens, [(pred_lemma, tags)])."""
    with _open(words_file) as wf, _open(props_file) as pf:
        toks, prop_rows = [], []
        for wline, pline in zip(wf, pf):
            wline, pline = wline.strip(), pline.rstrip("\n").strip()
            if not wline:
                if toks:
                    yield toks, prop_rows
                toks, prop_rows = [], []
                continue
            toks.append(wline.split()[0])
            prop_rows.append(pline.split())
        if toks:
            yield toks, prop_rows


def _props_to_iob(prop_rows, col):
    """Star-bracket column -> per-token span labels [(kind|None, is_begin)]."""
    labels, current = [], None
    for row in prop_rows:
        tag = row[col + 1] if col + 1 < len(row) else "*"
        begin = False
        if "(" in tag:
            current = tag[tag.index("(") + 1:].split("*")[0].rstrip(")")
            begin = True
        labels.append((current, begin))
        if ")" in tag:
            current = None
    return labels


_dict_cache = {}


def _load_or_build_dicts():
    # building the dicts scans the whole corpus — cache per data dir
    # (movielens._meta pattern)
    key = _dir()
    if key in _dict_cache:
        return _dict_cache[key]

    def load(fname):
        p = os.path.join(_dir(), fname)
        if os.path.exists(p):
            with open(p) as f:
                return {w.strip(): i for i, w in enumerate(f) if w.strip()}
        return None

    wd, vd, td = (load(f) for f in
                  ("wordDict.txt", "verbDict.txt", "targetDict.txt"))
    if wd is not None and vd is not None and td is not None:
        _dict_cache[key] = (wd, vd, td)
        return wd, vd, td
    # build from the data
    words, verbs, kinds = {}, {}, {}
    for toks, rows in _sentences("test.wsj.words.gz", "test.wsj.props.gz"):
        for t in toks:
            words.setdefault(t, len(words))
        for row in rows:
            if row and row[0] != "-":
                verbs.setdefault(row[0], len(verbs))
        ncols = max((len(r) - 1 for r in rows), default=0)
        for c in range(ncols):
            for kind, _ in _props_to_iob(rows, c):
                if kind is not None:
                    kinds.setdefault(kind, len(kinds))
    targets = {}
    for kind in kinds:
        targets.setdefault(f"B-{kind}", len(targets))
        targets.setdefault(f"I-{kind}", len(targets))
    targets["O"] = len(targets)
    result = ((wd or words), (vd or verbs), (td or targets))
    _dict_cache[key] = result
    return result


def get_dict():
    if os.path.exists(os.path.join(_dir(), "test.wsj.words.gz")):
        return _load_or_build_dicts()
    return ({f"w{i}": i for i in range(WORD_DICT)},
            {f"v{i}": i for i in range(PRED_DICT)},
            {f"l{i}": i for i in range(NUM_LABELS)})


def _real_reader(word_dict, verb_dict, target_dict):
    o_id = target_dict.get("O", len(target_dict) - 1)

    def reader():
        for toks, rows in _sentences("test.wsj.words.gz",
                                     "test.wsj.props.gz"):
            word_ids = [word_dict.get(t, len(word_dict) - 1) for t in toks]
            preds = [i for i, r in enumerate(rows) if r and r[0] != "-"]
            for col, pi in enumerate(preds):
                pred_id = verb_dict.get(rows[pi][0], len(verb_dict) - 1)
                labels = []
                for kind, begin in _props_to_iob(rows, col):
                    if kind is None:
                        labels.append(o_id)
                    else:
                        tag = f"{'B' if begin else 'I'}-{kind}"
                        labels.append(target_dict.get(tag, o_id))
                yield word_ids, [pred_id] * len(toks), labels
    return reader


def _synth_reader(split, n):
    def reader():
        rng = rng_for("conll05", split)
        for _ in range(n):
            length = int(rng.randint(5, 40))
            words = list(rng.randint(0, WORD_DICT, size=length))
            pred = int(rng.randint(0, PRED_DICT))
            labels = []
            t = 0
            while t < length:
                span = min(int(rng.randint(1, 4)), length - t)
                kind = int(rng.randint(0, LABEL_KINDS))
                labels.extend([2 * kind] + [2 * kind + 1] * (span - 1))
                t += span
            yield words, [pred] * length, labels
    return reader


def _reader(split, n):
    if os.path.exists(os.path.join(_dir(), "test.wsj.words.gz")):
        # the reference's own quirk (v2/dataset/conll05.py:202): the CoNLL05
        # train set is not freely distributable, so the TEST set serves for
        # both train() and test()
        return _real_reader(*_load_or_build_dicts())
    return _synth_reader(split, n)


def train():
    return _reader("train", 1024)


def test():
    return _reader("test", 128)
