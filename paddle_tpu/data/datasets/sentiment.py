"""NLTK movie_reviews sentiment set (reference v2/dataset/sentiment.py:1).

Reference call shapes preserved: `train()` / `test()` return ITERATORS of
(word_id_list, 0/1 label) — unlike the other datasets' reader creators,
sentiment.train() in the reference yields directly (sentiment.py:104-117) —
plus `get_word_dict()` -> [(word, id), ...] frequency-sorted, and the
NUM_TRAINING_INSTANCES=1600 / NUM_TOTAL_INSTANCES=2000 split constants.

Real data: the NLTK corpus layout `corpora/movie_reviews/{neg,pos}/*.txt`
under PADDLE_TPU_DATA_DIR (no nltk import needed — the corpus is plain
text files).  Without it, a deterministic synthetic corpus with the same
schema keeps air-gapped runs working.
"""

import os

from paddle_tpu.data.datasets._synth import local_path, rng_for, tokenize

__all__ = ["train", "test", "get_word_dict"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_SYNTH_VOCAB = 512
_SYNTH_LEN = 40


def _corpus_dir():
    return local_path("corpora", "movie_reviews")


def _category_files(category):
    d = os.path.join(_corpus_dir(), category)
    if not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".txt"))


def _words(path):
    with open(path, encoding="utf-8", errors="ignore") as f:
        return tokenize(f.read())


def _have_real():
    return bool(_category_files("neg") and _category_files("pos"))


def _synth_corpus():
    """Deterministic two-distribution corpus: negative reviews skew to low
    token ids, positive to high — learnable, like the real set."""
    rng = rng_for("sentiment", "all")
    docs = {"neg": [], "pos": []}
    for cat in ("neg", "pos"):
        lo, hi = (0, _SYNTH_VOCAB // 2) if cat == "neg" \
            else (_SYNTH_VOCAB // 2, _SYNTH_VOCAB)
        for _ in range(NUM_TOTAL_INSTANCES // 2):
            n = int(rng.randint(10, _SYNTH_LEN))
            main = rng.randint(lo, hi, (n,))
            noise = rng.randint(0, _SYNTH_VOCAB, (max(1, n // 4),))
            docs[cat].append([f"w{i}" for i in
                              list(main) + list(noise)])
    return docs


_docs_cache = {}


def _all_docs():
    """{category: [word list per doc]} from real corpus or synthetic —
    memoized per corpus dir, so get_word_dict() + train() + test() read and
    tokenize the 2000 documents once, not three times."""
    key = _corpus_dir() if _have_real() else "<synthetic>"
    if key not in _docs_cache:
        if key == "<synthetic>":
            _docs_cache[key] = _synth_corpus()
        else:
            _docs_cache[key] = {
                cat: [_words(p) for p in _category_files(cat)]
                for cat in ("neg", "pos")}
    return _docs_cache[key]


def _word_dict_for(docs):
    freq = {}
    for cat in ("neg", "pos"):
        for words in docs[cat]:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
    ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    return [(w, i) for i, (w, _) in enumerate(ordered)]


def get_word_dict():
    """Frequency-sorted [(word, id), ...] over the whole corpus (reference
    sentiment.py:51-70)."""
    return _word_dict_for(_all_docs())


def _interleave(neg, pos):
    """neg/pos cross-read for balanced batches (reference sort_files());
    unlike the reference's zip, an uneven corpus keeps its tail instead of
    silently dropping the longer category's extra documents."""
    out = []
    for i in range(max(len(neg), len(pos))):
        if i < len(neg):
            out.append((neg[i], 0))
        if i < len(pos):
            out.append((pos[i], 1))
    return out


def load_sentiment_data():
    """[(word_id_list, label), ...] with neg/pos interleaved for balanced
    cross-reading (reference sort_files(), sentiment.py:73-100).  The
    corpus is read ONCE: the word dict derives from the same docs."""
    docs = _all_docs()
    ids = dict(_word_dict_for(docs))
    return [([ids[w] for w in words], label)
            for words, label in _interleave(docs["neg"], docs["pos"])]


def _reader(data):
    for words, label in data:
        yield words, label


def train():
    """Iterator over the first 1600 samples (reference semantics: returns
    the generator itself, not a creator)."""
    return _reader(load_sentiment_data()[:NUM_TRAINING_INSTANCES])


def test():
    """Iterator over the remaining samples."""
    return _reader(load_sentiment_data()[NUM_TRAINING_INSTANCES:])


def fetch():
    """The reference downloads the NLTK corpus here; this build has no
    egress — place the corpus at
    $PADDLE_TPU_DATA_DIR/corpora/movie_reviews/ instead."""
    return _corpus_dir()
