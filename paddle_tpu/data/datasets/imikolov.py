"""PTB n-gram LM (reference v2/dataset/imikolov.py: N-gram word ids)."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

WORD_DIM = 2073


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(WORD_DIM)}


def _reader(split, n, ngram):
    def reader():
        rng = rng_for("imikolov", split)
        for _ in range(n):
            # markov-ish synthetic stream
            start = int(rng.randint(0, WORD_DIM))
            ids = [(start + k * 7) % WORD_DIM for k in range(ngram)]
            yield tuple(ids)
    return reader


def train(word_idx=None, n=5):
    return _reader("train", 4096, n)


def test(word_idx=None, n=5):
    return _reader("test", 512, n)
