"""WMT14 fr-en NMT (reference v2/dataset/wmt14.py: (src_ids, trg_ids,
trg_next_ids) triples with <s>/<e>/<unk>)."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

SRC_DICT_SIZE = 3000
TRG_DICT_SIZE = 3000
START, END, UNK = 0, 1, 2


def _reader(split, n):
    def reader():
        rng = rng_for("wmt14", split)
        for _ in range(n):
            slen = int(rng.randint(3, 30))
            src = list(rng.randint(3, SRC_DICT_SIZE, size=slen))
            # synthetic "translation": reversed + offset, teaches copying
            trg = [(t + 7) % (TRG_DICT_SIZE - 3) + 3 for t in src[::-1]]
            trg_in = [START] + trg
            trg_next = trg + [END]
            yield src, trg_in, trg_next
    return reader


def train(dict_size=SRC_DICT_SIZE):
    return _reader("train", 2048)


def test(dict_size=SRC_DICT_SIZE):
    return _reader("test", 256)
