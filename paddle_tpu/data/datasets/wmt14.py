"""WMT14 fr-en NMT (reference v2/dataset/wmt14.py: (src_ids, trg_ids,
trg_next_ids) triples with <s>/<e>/<unk>).

This module is the small-vocab API-parity surface (zero-egress synthetic
corpus, same triple format).  The REFERENCE-SCALE run — 30k vocab, the
reference's demo/seqToseq preprocess.py pipeline role — lives in
scripts/nmt_scale.py, which builds the full-size config and drives the
flagship attention-NMT model through the trainer (see docs/perf.md for
its on-chip milestones)."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

SRC_DICT_SIZE = 3000
TRG_DICT_SIZE = 3000
START, END, UNK = 0, 1, 2


def _reader(split, n):
    def reader():
        rng = rng_for("wmt14", split)
        for _ in range(n):
            slen = int(rng.randint(3, 30))
            src = list(rng.randint(3, SRC_DICT_SIZE, size=slen))
            # synthetic "translation": reversed + offset, teaches copying
            trg = [(t + 7) % (TRG_DICT_SIZE - 3) + 3 for t in src[::-1]]
            trg_in = [START] + trg
            trg_next = trg + [END]
            yield src, trg_in, trg_next
    return reader


def train(dict_size=SRC_DICT_SIZE):
    return _reader("train", 2048)


def test(dict_size=SRC_DICT_SIZE):
    return _reader("test", 256)
