"""MNIST (reference python/paddle/v2/dataset/mnist.py: 28x28 grays in [-1,1],
labels 0-9).  Loads IDX files from PADDLE_TPU_DATA_DIR/mnist if present,
else synthesizes class-dependent digit-like blobs (learnable, deterministic)."""

import gzip
import os
import struct

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for, local_path

IMG_SIZE = 784
NUM_CLASSES = 10


def _load_idx(img_path, lab_path):
    with gzip.open(img_path, "rb") as f:
        _, n, h, w = struct.unpack(">IIII", f.read(16))
        imgs = np.frombuffer(f.read(), np.uint8).reshape(n, h * w)
    with gzip.open(lab_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labs = np.frombuffer(f.read(), np.uint8)
    return imgs.astype(np.float32) / 127.5 - 1.0, labs.astype(np.int32)


def _synth(split, n):
    rng = rng_for("mnist", split)
    labs = rng.randint(0, NUM_CLASSES, size=n).astype(np.int32)
    protos = rng_for("mnist", "protos").randn(NUM_CLASSES, IMG_SIZE).astype(np.float32)
    imgs = np.tanh(protos[labs] + 0.3 * rng.randn(n, IMG_SIZE).astype(np.float32))
    return imgs, labs


def _reader(split, n_synth):
    files = {
        "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }[split]
    ip, lp = (local_path("mnist", f) for f in files)

    def reader():
        if os.path.exists(ip) and os.path.exists(lp):
            imgs, labs = _load_idx(ip, lp)
        else:
            imgs, labs = _synth(split, n_synth)
        for x, y in zip(imgs, labs):
            yield x, int(y)
    return reader


def train():
    return _reader("train", 4096)


def test():
    return _reader("test", 512)
