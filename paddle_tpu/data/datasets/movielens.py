"""MovieLens (reference v2/dataset/movielens.py: user/movie categorical
features -> rating).

Real data: PADDLE_TPU_DATA_DIR/ml-1m/ with the GroupLens 1M layout —
users.dat (UserID::Gender::Age::Occupation::Zip), movies.dat
(MovieID::Title::Genres), ratings.dat (UserID::MovieID::Rating::Ts), all
'::'-separated.  Without it, a deterministic synthetic fallback.

Yields (uid, gender01, age_idx, job, mid, category_ids, title_word_ids,
score) — the 8 slots the recommendation demo feeds."""

import os

import numpy as np

from paddle_tpu.data.datasets._synth import local_path, rng_for

MAX_USER = 6040
MAX_MOVIE = 3952
AGES = 7
JOBS = 21
CATEGORIES = 18
TITLE_DIM = 5174

_AGE_BUCKETS = [1, 18, 25, 35, 45, 50, 56]


def _dir():
    return local_path("ml-1m")


def _have_real():
    return all(os.path.exists(os.path.join(_dir(), f))
               for f in ("users.dat", "movies.dat", "ratings.dat"))


def _load_meta():
    users, movies, genres, title_vocab = {}, {}, {}, {}
    with open(os.path.join(_dir(), "users.dat"),
              encoding="latin-1") as f:
        for line in f:
            uid, gender, age, job, _zip = line.strip().split("::")
            users[int(uid)] = (0 if gender == "F" else 1,
                               _AGE_BUCKETS.index(int(age))
                               if int(age) in _AGE_BUCKETS else 0,
                               int(job))
    with open(os.path.join(_dir(), "movies.dat"),
              encoding="latin-1") as f:
        for line in f:
            mid, title, genre_s = line.strip().split("::")
            gids = []
            for g in genre_s.split("|"):
                gids.append(genres.setdefault(g, len(genres)))
            tids = []
            for w in title.lower().split():
                tids.append(title_vocab.setdefault(w, len(title_vocab)))
            movies[int(mid)] = (gids, tids)
    return users, movies, genres, title_vocab


_meta_cache = {}


def _meta():
    key = _dir()
    if key not in _meta_cache:
        _meta_cache[key] = _load_meta()
    return _meta_cache[key]


def max_user_id():
    return MAX_USER


def max_movie_id():
    return MAX_MOVIE


def max_job_id():
    return JOBS - 1


def _real_reader(split):
    def reader():
        users, movies, _, _ = _meta()
        with open(os.path.join(_dir(), "ratings.dat"),
                  encoding="latin-1") as f:
            for i, line in enumerate(f):
                # deterministic 9:1 train/test split on line index
                # (the reference splits on a random hash)
                if (i % 10 == 9) != (split == "test"):
                    continue
                uid, mid, rating, _ts = line.strip().split("::")
                uid, mid = int(uid), int(mid)
                if uid not in users or mid not in movies:
                    continue
                gender, age, job = users[uid]
                cats, title = movies[mid]
                yield (uid, gender, age, job, mid, list(cats), list(title),
                       float(rating))
    return reader


def _synth_reader(split, n):
    def reader():
        rng = rng_for("movielens", split)
        for _ in range(n):
            uid = int(rng.randint(0, MAX_USER))
            mid = int(rng.randint(0, MAX_MOVIE))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, AGES))
            job = int(rng.randint(0, JOBS))
            category = list(rng.choice(CATEGORIES,
                                       size=rng.randint(1, 4), replace=False))
            title = list(rng.randint(0, TITLE_DIM, size=rng.randint(2, 8)))
            score = float((uid * 31 + mid * 17) % 5 + 1)
            yield uid, gender, age, job, mid, category, title, score
    return reader


def _reader(split, n):
    if _have_real():
        return _real_reader(split)
    return _synth_reader(split, n)


def train():
    return _reader("train", 4096)


def test():
    return _reader("test", 512)
