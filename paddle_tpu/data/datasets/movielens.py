"""MovieLens CTR (reference v2/dataset/movielens.py: user/movie categorical
features -> rating)."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

MAX_USER = 6040
MAX_MOVIE = 3952
AGES = 7
JOBS = 21
CATEGORIES = 18
TITLE_DIM = 5174


def max_user_id():
    return MAX_USER


def max_movie_id():
    return MAX_MOVIE


def max_job_id():
    return JOBS - 1


def _reader(split, n):
    def reader():
        rng = rng_for("movielens", split)
        for _ in range(n):
            uid = int(rng.randint(0, MAX_USER))
            mid = int(rng.randint(0, MAX_MOVIE))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, AGES))
            job = int(rng.randint(0, JOBS))
            category = list(rng.choice(CATEGORIES,
                                       size=rng.randint(1, 4), replace=False))
            title = list(rng.randint(0, TITLE_DIM, size=rng.randint(2, 8)))
            score = float((uid * 31 + mid * 17) % 5 + 1)
            yield uid, gender, age, job, mid, category, title, score
    return reader


def train():
    return _reader("train", 4096)


def test():
    return _reader("test", 512)
