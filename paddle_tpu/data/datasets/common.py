"""Dataset download + cache (reference python/paddle/v2/dataset/common.py:
DATA_HOME, download(url, module, md5), md5file).

DATA_HOME here is PADDLE_TPU_DATA_DIR (the same root every loader reads
local files from), so a successful download drops files exactly where the
real parsers look.  In an air-gapped environment download() raises a clear
DownloadError naming the file to place manually — the loaders themselves
then fall back to deterministic synthetic data."""

import hashlib
import os

from paddle_tpu.data.datasets._synth import data_dir
from paddle_tpu.utils.logging import logger

__all__ = ["DATA_HOME", "data_home", "download", "md5file", "DownloadError"]


def data_home():
    d = data_dir()
    os.makedirs(d, exist_ok=True)
    return d


def __getattr__(name):
    # DATA_HOME resolves lazily: no import-time mkdir (a read-only HOME
    # must not break the synthetic-fallback path), and PADDLE_TPU_DATA_DIR
    # set after import is honored (same contract as _synth.data_dir)
    if name == "DATA_HOME":
        return data_home()
    raise AttributeError(name)


class DownloadError(RuntimeError):
    pass


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, timeout=60):
    """Fetch url into DATA_HOME/module_name (cached by md5).  Returns the
    local path; raises DownloadError when the network is unreachable, with
    instructions for manual placement."""
    dirname = os.path.join(data_home(), module_name)
    os.makedirs(dirname, exist_ok=True)
    filename = os.path.join(dirname, url.split("/")[-1])
    if os.path.exists(filename) and (md5sum is None
                                     or md5file(filename) == md5sum):
        return filename
    logger.info("downloading %s -> %s", url, filename)
    try:
        import urllib.request
        tmp = filename + ".part"
        with urllib.request.urlopen(url, timeout=timeout) as r, \
                open(tmp, "wb") as f:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
        os.replace(tmp, filename)
    except Exception as e:
        raise DownloadError(
            f"cannot download {url} ({e}); place the file manually at "
            f"{filename} (PADDLE_TPU_DATA_DIR={data_home()})") from e
    if md5sum is not None and md5file(filename) != md5sum:
        raise DownloadError(f"{filename}: md5 mismatch after download")
    return filename
