"""Synthetic data helpers shared by the dataset modules."""

import os

import numpy as np

DATA_DIR = os.environ.get("PADDLE_TPU_DATA_DIR",
                          os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def rng_for(name, split):
    return np.random.RandomState(abs(hash((name, split))) % (2 ** 31))


def local_path(*parts):
    return os.path.join(DATA_DIR, *parts)
