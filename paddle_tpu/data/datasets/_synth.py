"""Synthetic data helpers shared by the dataset modules."""

import os
import zlib

import numpy as np


def data_dir():
    """Resolved at call time so tests (and late exports) can set
    PADDLE_TPU_DATA_DIR after import."""
    return os.environ.get("PADDLE_TPU_DATA_DIR",
                          os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def rng_for(name, split):
    # crc32, not hash(): str hash is salted per process and synthetic
    # datasets must be reproducible across runs
    key = f"{name}:{split!r}".encode()
    return np.random.RandomState(zlib.crc32(key) % (2 ** 31))


def local_path(*parts):
    return os.path.join(data_dir(), *parts)


_TOKEN = None


def tokenize(text):
    """Lowercased word tokens (shared by the text datasets)."""
    global _TOKEN
    if _TOKEN is None:
        import re
        _TOKEN = re.compile(r"[A-Za-z0-9']+")
    return [t.lower() for t in _TOKEN.findall(text)]
