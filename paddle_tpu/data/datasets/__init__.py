"""Dataset zoo mirroring paddle.v2.dataset (mnist, cifar, imdb, imikolov,
movielens, conll05, sentiment, uci_housing, wmt14 — reference
python/paddle/v2/dataset/).

This environment has no network egress, so each dataset loads from a local
path when present (PADDLE_TPU_DATA_DIR) and otherwise falls back to a
deterministic synthetic generator with the same sample schema — keeping the
training pipelines runnable end-to-end anywhere.
"""

from paddle_tpu.data.datasets import mnist, cifar, imdb, uci_housing, \
    movielens, imikolov, wmt14, conll05, sentiment

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "movielens", "imikolov",
           "wmt14", "conll05", "sentiment"]
