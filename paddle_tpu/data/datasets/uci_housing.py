"""UCI housing regression (reference v2/dataset/uci_housing.py: 13 features,
scalar price)."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]
DIM = 13
_W = rng_for("uci", "w").randn(DIM).astype(np.float32)


def _reader(split, n):
    def reader():
        rng = rng_for("uci_housing", split)
        for _ in range(n):
            x = rng.randn(DIM).astype(np.float32)
            y = float(x @ _W + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)
    return reader


def train():
    return _reader("train", 404)


def test():
    return _reader("test", 102)
