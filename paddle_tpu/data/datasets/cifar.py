"""CIFAR-10/100 (reference v2/dataset/cifar.py: 3x32x32 float rows + label)."""

import os
import pickle

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for, local_path

DIM = 3 * 32 * 32


def _synth(split, n, num_classes):
    rng = rng_for("cifar", (split, num_classes))
    labs = rng.randint(0, num_classes, size=n).astype(np.int32)
    protos = rng_for("cifar", ("protos", num_classes)).randn(
        num_classes, DIM).astype(np.float32)
    imgs = np.tanh(protos[labs] * 0.5 + 0.5 * rng.randn(n, DIM).astype(np.float32))
    return imgs, labs


def _reader(split, num_classes, n_synth):
    batch_dir = local_path("cifar", "cifar-10-batches-py")

    def reader():
        if num_classes == 10 and os.path.isdir(batch_dir):
            names = [f"data_batch_{i}" for i in range(1, 6)] if split == "train" \
                else ["test_batch"]
            for nm in names:
                with open(os.path.join(batch_dir, nm), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                for x, y in zip(d[b"data"], d[b"labels"]):
                    yield x.astype(np.float32) / 255.0, int(y)
        else:
            imgs, labs = _synth(split, n_synth, num_classes)
            for x, y in zip(imgs, labs):
                yield x, int(y)
    return reader


def train10():
    return _reader("train", 10, 4096)


def test10():
    return _reader("test", 10, 512)


def train100():
    return _reader("train", 100, 4096)


def test100():
    return _reader("test", 100, 512)
