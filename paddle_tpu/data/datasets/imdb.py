"""IMDB sentiment (reference v2/dataset/imdb.py: word-id sequence + 0/1
label).  Synthetic fallback: two token distributions."""

import numpy as np

from paddle_tpu.data.datasets._synth import rng_for

WORD_DIM = 5147  # compact synthetic vocab


def word_dict():
    return {f"w{i}": i for i in range(WORD_DIM)}


def _reader(split, n):
    def reader():
        rng = rng_for("imdb", split)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 120))
            # positive reviews skew to low ids, negative to high
            center = WORD_DIM // 4 if label else 3 * WORD_DIM // 4
            ids = np.clip(rng.normal(center, WORD_DIM // 6, size=length),
                          0, WORD_DIM - 1).astype(np.int64)
            yield list(ids), label
    return reader


def train(word_idx=None):
    return _reader("train", 2048)


def test(word_idx=None):
    return _reader("test", 256)
