"""IMDB sentiment (reference v2/dataset/imdb.py: word-id sequence + 0/1
label, built from the aclImdb tarball's train/{pos,neg}/*.txt reviews).

Real data: point PADDLE_TPU_DATA_DIR at a directory containing `aclImdb/`
(the extracted Stanford tarball).  Without it, a synthetic fallback keeps
air-gapped runs working: two token distributions, learnable and
deterministic."""

import os

import numpy as np

from paddle_tpu.data.datasets._synth import local_path, rng_for, \
    tokenize as _tokenize

WORD_DIM = 5147  # compact synthetic vocab


def _acl_dir():
    return local_path("aclImdb")


def _review_files(split, polarity):
    d = os.path.join(_acl_dir(), split, polarity)
    if not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".txt"))


_dict_cache = {}


def word_dict(cutoff=1):
    """Frequency-ordered word dict over the train split (reference
    imdb.word_dict(): ids ordered by descending frequency).  Synthetic
    fallback: identity vocab.  Built once per data dir (full corpus scan)."""
    if not os.path.isdir(_acl_dir()):
        return {f"w{i}": i for i in range(WORD_DIM)}
    key = (_acl_dir(), cutoff)
    if key in _dict_cache:
        return _dict_cache[key]
    freq = {}
    for pol in ("pos", "neg"):
        for path in _review_files("train", pol):
            with open(path, encoding="utf-8", errors="ignore") as f:
                for tok in _tokenize(f.read()):
                    freq[tok] = freq.get(tok, 0) + 1
    words = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
             if c >= cutoff]
    d = {w: i for i, w in enumerate(words)}
    d["<unk>"] = len(d)
    _dict_cache[key] = d
    return d


def _real_reader(split, word_idx):
    unk = word_idx.get("<unk>", len(word_idx) - 1)

    def read_one(path):
        with open(path, encoding="utf-8", errors="ignore") as f:
            return [word_idx.get(t, unk) for t in _tokenize(f.read())]

    def reader():
        # interleave pos/neg deterministically (the reference shuffles the
        # tarball walk; interleaving keeps batches label-balanced)
        pos = _review_files(split, "pos")
        neg = _review_files(split, "neg")
        for i in range(max(len(pos), len(neg))):
            if i < len(pos):
                yield read_one(pos[i]), 0
            if i < len(neg):
                yield read_one(neg[i]), 1
    return reader


def _synth_reader(split, n):
    def reader():
        rng = rng_for("imdb", split)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 120))
            # label polarity matches the real reader (and the reference):
            # 0 = positive (low-id skew), 1 = negative (high-id skew)
            center = WORD_DIM // 4 if label == 0 else 3 * WORD_DIM // 4
            ids = np.clip(rng.normal(center, WORD_DIM // 6, size=length),
                          0, WORD_DIM - 1).astype(np.int64)
            yield list(ids), label
    return reader


def _reader(split, n, word_idx):
    if os.path.isdir(os.path.join(_acl_dir(), split)):
        return _real_reader(split, word_idx if word_idx is not None
                            else word_dict())
    return _synth_reader(split, n)


def train(word_idx=None):
    return _reader("train", 2048, word_idx)


def test(word_idx=None):
    return _reader("test", 256, word_idx)
