"""PyDataProvider2-compatible @provider decorator + input type declarations.

Reference: python/paddle/trainer/PyDataProvider2.py:25-210 — input types
dense_vector, sparse_binary_vector, sparse_float_vector, integer_value, each
x (no_sequence | sequence | sub_sequence), cache types, and the @provider
decorator turning a Python generator into a framework data source.  The C++
consumer (gserver/dataproviders/PyDataProvider2.cpp) becomes the DataFeeder
(feeder.py) which packs samples into device arrays.
"""

import dataclasses
import functools
from enum import Enum


class SeqType(Enum):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


@dataclasses.dataclass(frozen=True)
class InputType:
    dim: int
    seq_type: SeqType
    kind: str  # dense | sparse_binary | sparse_float | index


def dense_vector(dim, seq_type=SeqType.NO_SEQUENCE):
    return InputType(dim, seq_type, "dense")


def sparse_binary_vector(dim, seq_type=SeqType.NO_SEQUENCE):
    return InputType(dim, seq_type, "sparse_binary")


def sparse_float_vector(dim, seq_type=SeqType.NO_SEQUENCE):
    return InputType(dim, seq_type, "sparse_float")


def integer_value(value_range, seq_type=SeqType.NO_SEQUENCE):
    return InputType(value_range, seq_type, "index")


def dense_vector_sequence(dim):
    return dense_vector(dim, SeqType.SEQUENCE)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, SeqType.SEQUENCE)


def sparse_float_vector_sequence(dim):
    return sparse_float_vector(dim, SeqType.SEQUENCE)


def integer_value_sequence(value_range):
    return integer_value(value_range, SeqType.SEQUENCE)


def integer_value_sub_sequence(value_range):
    return integer_value(value_range, SeqType.SUB_SEQUENCE)


class CacheType(Enum):
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


def provider(input_types=None, cache=CacheType.NO_CACHE, should_shuffle=None,
             init_hook=None, min_pool_size=-1, pool_size=-1,
             calc_batch_size=None, check=False, check_fail_continue=False,
             **outer_kwargs):
    """@provider(input_types={'word': integer_value_sequence(dict_len), ...})

    The wrapped generator has signature gen(settings, filename) and yields
    dicts keyed by input name (or tuples in declaration order).  Returns a
    reader factory: fn(filenames) -> reader compatible with trainer.SGD.

    init_hook (reference PyDataProvider2.py provider(init_hook=...)): called
    as init_hook(settings, **args) before reading, and may fill
    settings.input_types itself (the quick_start dataprovider_bow pattern).
    pool_size/calc_batch_size/check* are accepted for config compatibility;
    shuffling/pooling is the reader pipeline's job here.
    """
    def deco(gen):
        @functools.wraps(gen)
        def make_reader(file_list, **kw):
            files = [file_list] if isinstance(file_list, str) else list(file_list)

            class Settings:
                pass

            settings = Settings()
            settings.input_types = input_types
            settings.logger = __import__("logging").getLogger("provider")
            if init_hook is not None:
                # reference PyDataProvider2 passes file_list to the hook
                init_hook(settings, file_list=files,
                          **{**outer_kwargs, **kw})
            else:
                for k, v in {**outer_kwargs, **kw}.items():
                    setattr(settings, k, v)

            cached = []

            def reader():
                if cache == CacheType.CACHE_PASS_IN_MEM and cached:
                    yield from cached
                    return
                for f in files:
                    for sample in gen(settings, f):
                        if cache == CacheType.CACHE_PASS_IN_MEM:
                            cached.append(sample)
                        yield sample
            # init_hook may have replaced settings.input_types ('slots' is
            # the reference's legacy alias for the same field)
            reader.input_types = (getattr(settings, "input_types", None)
                                  or getattr(settings, "slots", None))
            return reader
        make_reader.input_types = input_types
        return make_reader
    return deco
