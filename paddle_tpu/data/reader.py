"""Reader decorators.

Reference: python/paddle/v2/reader/decorator.py — map_readers, buffered,
shuffle, batched(+minibatch.py), compose, chain, firstn — and the creator
helpers.  A reader is a zero-arg callable returning an iterator of samples.
"""

import itertools
import random
import threading
import queue as _queue


def map_readers(func, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def shuffle(reader, buf_size, seed=None):
    def new_reader():
        rng = random.Random(seed)
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf
    return new_reader


def buffered(reader, size):
    """Async prefetch thread (reference DoubleBuffer, DataProvider.h:251)."""
    _end = object()

    def new_reader():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(_end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _end:
                break
            yield item
    return new_reader


def batch(reader, batch_size, drop_last=False):
    def new_reader():
        it = reader()
        while True:
            chunk = list(itertools.islice(it, batch_size))
            if not chunk:
                return
            if len(chunk) < batch_size and drop_last:
                return
            yield chunk
    return new_reader


batched = batch


def compose(*readers):
    def new_reader():
        for items in zip(*[r() for r in readers]):
            out = []
            for x in items:
                if isinstance(x, tuple):
                    out.extend(x)
                else:
                    out.append(x)
            yield tuple(out)
    return new_reader


def chain(*readers):
    def new_reader():
        for r in readers:
            yield from r()
    return new_reader


def firstn(reader, n):
    def new_reader():
        yield from itertools.islice(reader(), n)
    return new_reader


def cache(reader):
    data = []
    filled = []

    def new_reader():
        if not filled:
            data.extend(reader())
            filled.append(True)
        yield from data
    return new_reader


def mix(readers_and_ratios, seed=0):
    """Interleave readers with given sampling ratios (reference
    MultiDataProvider, gserver/dataproviders/MultiDataProvider.cpp: mixes
    sub-providers by config ratio).  readers_and_ratios: [(reader, ratio)].
    Exhausted readers drop out; stops when all are exhausted."""
    import numpy as np

    def new_reader():
        rng = np.random.RandomState(seed)
        iters = [iter(r()) for r, _ in readers_and_ratios]
        weights = np.asarray([float(w) for _, w in readers_and_ratios])
        alive = [True] * len(iters)
        while any(alive):
            w = np.where(alive, weights, 0.0)
            total = w.sum()
            if total <= 0:
                break
            i = int(rng.choice(len(iters), p=w / total))
            try:
                yield next(iters[i])
            except StopIteration:
                alive[i] = False
    return new_reader


def packed(reader, max_len, buffer_size=256, pad_value=0):
    """Pack a reader of ragged token sequences into (data, segment_ids,
    positions) rows of width max_len (core.sequence.pack_sequences):
    several short sequences share a row, attention stays block-diagonal
    per segment (ops.attention q_segment_ids / transformer.encode
    segment_ids=...).  Buffers `buffer_size` sequences per packing round
    so first-fit has material to work with; yields one packed ROW per
    item (compose with batch() for [B, max_len] feeds).  Sequences longer
    than max_len are TRUNCATED to it (warned once per stream — split long
    documents upstream if the tail matters)."""
    from paddle_tpu.core.sequence import pack_sequences
    from paddle_tpu.utils.logging import logger

    def new_reader():
        buf = []
        warned = [False]

        def flush():
            data, seg, pos = pack_sequences(buf, max_len,
                                            pad_value=pad_value)
            # clear BEFORE yielding: a consumer that abandons the stream
            # mid-flush (zip with a shorter iterator) must not leave the
            # buffer populated in the suspended frame
            buf.clear()
            for i in range(data.shape[0]):
                yield data[i], seg[i], pos[i]

        for s in reader():
            if len(s) > max_len and not warned[0]:
                warned[0] = True
                logger.warning(
                    "packed(): sequence of %d tokens truncated to "
                    "max_len=%d (further truncations not logged)",
                    len(s), max_len)
            buf.append(s)
            if len(buf) >= buffer_size:
                yield from flush()
        if buf:
            yield from flush()
    return new_reader
