"""Data path: reader decorators, PyDataProvider2-compatible @provider,
DataFeeder, dataset zoo (reference §2.2 DataProviders + v2 readers/datasets)."""

from paddle_tpu.data import reader
from paddle_tpu.data.provider import (
    provider, dense_vector, sparse_binary_vector, sparse_float_vector,
    integer_value, dense_vector_sequence, sparse_binary_vector_sequence,
    sparse_float_vector_sequence, integer_value_sequence,
    integer_value_sub_sequence, CacheType, SeqType, InputType,
)
from paddle_tpu.data.feeder import DataFeeder
from paddle_tpu.data.prefetch import ShardedPrefetcher, device_placer
from paddle_tpu.data import datasets

__all__ = [
    "reader", "provider", "DataFeeder", "datasets",
    "ShardedPrefetcher", "device_placer",
    "dense_vector", "sparse_binary_vector", "sparse_float_vector",
    "integer_value", "dense_vector_sequence", "sparse_binary_vector_sequence",
    "sparse_float_vector_sequence", "integer_value_sequence",
    "integer_value_sub_sequence", "CacheType", "SeqType", "InputType",
]
