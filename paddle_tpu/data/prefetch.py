"""Overlapped input pipeline: host batches -> device-resident feeds, N ahead.

Reference: the DoubleBuffer thread (gserver/dataproviders/DataProvider.h:251)
hid host-side data latency behind GPU compute; ``reader.buffered()`` carries
that over for HOST batches.  This module completes the device half of the
story: a bounded background thread runs DataFeeder conversion AND the H2D
transfer (``jax.device_put`` under the mesh's batch ``NamedSharding``, or
``jax.make_array_from_callback`` on a process-spanning mesh), so the trainer
hot loop dequeues batches that are already device-resident and sharded —
step wall time excludes input time entirely.

Donation safety: every batch is freshly ``device_put`` — the prefetcher
never pools or reuses device buffers, and the producer drops its own
reference the moment a batch enters the queue, so even a jitted consumer
that DONATES its feed can never alias a buffer still held here (see
``test_donation_safety``).  Note the trainer step itself does not donate
feeds (its ``donate_argnums`` covers params/opt state only); the
fresh-buffer discipline is what keeps third-party donating consumers
safe, and is one reason ``SGD.train(prefetch=N)`` is bit-identical to
``prefetch=0``.

Exceptions raised by the source reader, the convert fn, or device placement
surface in the CONSUMER thread at the next ``__next__``; ``close()`` (or
exhausting the stream) joins the producer thread.
"""

import queue as _queue
import threading
import time
import weakref

import jax


_END = object()


def _release(stop, q):
    """Stop the producer and drop queued (device-resident) batches.
    Module-level so a weakref.finalize can run it after the owning
    prefetcher is garbage collected (no strong ref to self)."""
    stop.set()
    try:
        while True:
            q.get_nowait()
    except _queue.Empty:
        pass


def _bounded_put(q, stop, item):
    """Bounded put that aborts promptly on stop instead of blocking
    forever against a consumer that went away."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except _queue.Full:
            continue
    return False


def _fill(source, convert, place, stop, q):
    """Producer body.  Module-level (not a bound method) on purpose: a
    RUNNING Thread strongly references its target, so a method target
    would keep the prefetcher alive and its GC finalizer from ever
    firing."""
    from paddle_tpu.obs import trace as obstrace
    from paddle_tpu.resilience import faults
    try:
        for batch in source():
            if stop.is_set():
                return
            feed = convert(batch) if convert else batch
            # fault point at the H2D boundary (resilience/faults.py): an
            # injected failure crosses to the consumer like any real
            # placement error — surfaced at its next __next__
            faults.hit("data.prefetch.h2d")
            # tracing (obs/trace.py): the producer-side H2D transfer as
            # a span, so a Chrome trace shows whether the pipeline hides
            # it behind the train steps; strict no-op when disabled
            with obstrace.span("data.h2d", root=False):
                feed = place(feed)
            if not _bounded_put(q, stop, feed):
                return
            # the queue now holds the ONLY producer-side reference: once
            # dequeued, the consumer (and its donating step) owns the
            # buffers outright
            del feed
    except BaseException as e:  # noqa: BLE001 — must cross threads
        _bounded_put(q, stop, _Failure(e))
    else:
        _bounded_put(q, stop, _END)


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def _run_jobs(jobs, done, stop):
    """TransferWorker body.  Module-level for the same GC reason as
    ``_fill``: a running Thread strongly references its target, so a
    bound method would pin the worker object and defeat its finalizer."""
    while not stop.is_set():
        try:
            item = jobs.get(timeout=0.1)
        except _queue.Empty:
            continue
        if item is _END:
            return
        tag, fn = item
        try:
            out = fn()
        except BaseException as e:  # noqa: BLE001 — must cross threads
            out = _Failure(e)
        if not _bounded_put(done, stop, (tag, out)):
            return
        del out


class TransferWorker:
    """The transfer core of ``ShardedPrefetcher``, generalized: ONE
    bounded daemon thread running submitted zero-arg jobs in order, with
    the same lifecycle discipline (stop event, ``_bounded_put`` against
    an absent consumer, ``_Failure`` exception crossing, GC finalizer).

    The input pipeline above specializes this shape to an iterator of
    batches; the serving host tier (``serving/kv_pool.HostTier``) reuses
    it for asynchronous KV-chain restores (deserialize + ``device_put``
    off the decode thread).  Jobs run on the worker thread; their
    results — or a ``_Failure`` wrapping what they raised — arrive via
    ``poll()`` tagged with the token the submitter chose, so the
    consumer matches completions to requests without ordering
    assumptions."""

    def __init__(self, name="paddle-tpu-transfer", depth=8):
        self._jobs = _queue.Queue()
        self._done = _queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_run_jobs, args=(self._jobs, self._done, self._stop),
            daemon=True, name=name)
        self._finalizer = weakref.finalize(self, _release,
                                           self._stop, self._jobs)
        self._thread.start()

    def submit(self, tag, fn):
        """Queue ``fn`` (zero-arg) for the worker thread; its result
        comes back from ``poll()`` as ``(tag, result)``."""
        self._jobs.put((tag, fn))

    def poll(self, timeout=0.0):
        """Next completed job as ``(tag, result)`` — ``result`` is a
        ``_Failure`` if the job raised (the caller decides per-job
        fate) — or None when nothing completed within ``timeout``."""
        try:
            if timeout:
                return self._done.get(timeout=timeout)
            return self._done.get_nowait()
        except _queue.Empty:
            return None

    def close(self):
        """Stop the worker and join it; queued jobs and undelivered
        results are dropped.  Safe to call more than once."""
        _release(self._stop, self._jobs)
        _release(self._stop, self._done)
        self._finalizer.detach()
        self._jobs.put(_END)
        self._thread.join(timeout=30.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def device_placer(mesh=None, multiprocess=False):
    """Return a fn placing a host feed pytree onto device(s).

    mesh=None: plain ``jax.device_put`` (default device).
    mesh: ``device_put`` under ``batch_shardings`` (leading dim over the
    'data' axis — the NamedSharding shard_map-era placement).
    multiprocess: the mesh spans devices owned by other processes;
    ``device_put`` cannot target non-addressable devices, so global arrays
    are assembled from the (identical-per-process) host values by
    ``parallel.sharding.globalize_pytree`` — the same helper behind
    ``SGD._globalize``.
    """
    if mesh is None:
        return jax.device_put
    from paddle_tpu.parallel import batch_shardings
    if not multiprocess:
        def place(feed):
            return jax.device_put(feed, batch_shardings(feed, mesh))
        return place

    from paddle_tpu.parallel.sharding import globalize_pytree

    def place_global(feed):
        return globalize_pytree(feed, batch_shardings(feed, mesh))
    return place_global


class ShardedPrefetcher:
    """Bounded background producer of device-resident feeds.

    source: zero-arg callable returning an iterator of host batches (the
    reader contract).
    convert: host batch -> feed pytree (feeder conversion + normalization);
    runs on the producer thread.  None = identity.
    place: feed pytree -> device-resident feed; runs on the producer
    thread.  None = ``jax.device_put`` (see ``device_placer`` for mesh /
    multi-process placement).
    depth: max batches resident ahead of the consumer (queue bound; HBM
    cost is ~depth+1 extra batches).

    Iterate to consume; ``wait_s`` accumulates the consumer-side blocked
    time (the trainer's ``h2d_wait`` counter: ~0 when the pipeline keeps
    up, ~input latency when input-bound).  Context manager: ``close()`` on
    exit stops the producer and joins it.
    """

    def __init__(self, source, depth=2, convert=None, place=None,
                 start=True):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self._source = source
        self._convert = convert
        self._place = place if place is not None else jax.device_put
        self._q = _queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._started = False
        self.wait_s = 0.0       # cumulative consumer-side input wait
        self.batches = 0        # batches handed to the consumer
        self._thread = threading.Thread(
            target=_fill,
            args=(source, convert, self._place, self._stop, self._q),
            daemon=True, name="paddle-tpu-prefetch")
        # a consumer that abandons the iterator without close() (break
        # out of the loop, exception) must not leave the producer
        # spinning with ~depth+1 batches of HBM pinned: GC of the
        # prefetcher stops and drains it
        self._finalizer = weakref.finalize(self, _release,
                                           self._stop, self._q)
        if start:
            self.start()

    # ------------------------------------------------------------ consumer

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if not self._started:   # start=False consumer iterating directly:
            self.start()        # a forever-empty queue would deadlock here
        t0 = time.perf_counter()
        item = self._q.get()
        self.wait_s += time.perf_counter() - t0
        if item is _END:
            self._done = True
            self._thread.join()
            raise StopIteration
        if isinstance(item, _Failure):
            self._done = True
            self._thread.join()
            raise item.exc
        self.batches += 1
        return item

    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def close(self):
        """Stop the producer and join it; safe to call more than once.
        Queued (undelivered) batches are dropped."""
        self._done = True
        # stop + drain (unblocks a producer waiting on a full queue);
        # also disarms the GC finalizer
        _release(self._stop, self._q)
        self._finalizer.detach()
        if self._started:
            self._thread.join(timeout=30.0)
            if self._thread.is_alive():
                from paddle_tpu.utils.logging import logger
                logger.warning(
                    "ShardedPrefetcher.close(): producer thread still "
                    "alive after 30s (reader or device placement is "
                    "blocked); it is a daemon and ~depth batches of "
                    "device memory stay pinned until it unblocks")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
